# Convenience targets (the CI-role entry points — SURVEY §3.4).

.PHONY: test gate gate-fast bench bench-compile bench-import native native-test lint lint-baseline shape-lint life-lint check check-baseline obs-smoke serve-smoke tune-smoke tune chaos-smoke train-chaos-smoke cluster-chaos-smoke slo-smoke prefix-smoke spec-smoke aot-smoke locktrace-smoke shapetrace-smoke lifetrace-smoke

# graftlint: JAX-footgun static analysis (docs/LINT.md). Fails only on
# findings NOT grandfathered in lint_baseline.json. JAX_PLATFORMS=cpu so
# the registry-consistency rules can never hang on an unreachable TPU.
lint:
	JAX_PLATFORMS=cpu python tools/graftlint.py

# regenerate the baseline (after FIXING findings — the baseline only shrinks)
lint-baseline:
	JAX_PLATFORMS=cpu python tools/graftlint.py --write-baseline

# graftshape tier alone (docs/LINT.md § graftshape): jit-signature &
# recompile-discipline rules GS001-GS005. Already part of `make lint` —
# this target is the fast loop while working on shape discipline.
shape-lint:
	JAX_PLATFORMS=cpu python tools/graftlint.py --rules GS001,GS002,GS003,GS004,GS005

# graftlife tier alone (docs/LINT.md § graftlife): resource-lifecycle &
# exactly-once rules GR001-GR005. Already part of `make lint` — this
# target is the fast loop while working on ownership discipline.
life-lint:
	JAX_PLATFORMS=cpu python tools/graftlint.py --rules GR001,GR002,GR003,GR004,GR005

# graftcheck: abstract shape/dtype verification of the SameDiff fixture
# zoo (docs/ANALYSIS.md). Build-only — no jit, no device. Fails only on
# findings NOT grandfathered in check_baseline.json (committed empty:
# the fixtures must stay clean).
check:
	JAX_PLATFORMS=cpu python tools/graftcheck.py

check-baseline:
	JAX_PLATFORMS=cpu python tools/graftcheck.py --write-baseline

# observability smoke (docs/OBSERVABILITY.md): run the obsreport demo
# workload on CPU and emit ONE JSON line — fails unless train steps,
# recompile-ledger events, and serving percentiles all came out nonzero.
obs-smoke:
	JAX_PLATFORMS=cpu python tools/obsreport.py --json

# kernel-autotuner smoke (docs/KERNELS.md): tiny-shape tune on CPU — must
# exit 0 anywhere, produce a valid tuning table in the (throwaway by
# default) cache dir, and PROVE via the dispatch counters that resolve
# honors the tuned flash_min_t (XLA below, Pallas above). ONE JSON line
# like lint/check. The throwaway dir matters: smoke thresholds are
# interpret-mode noise and must never clobber a real measured table in
# ~/.cache (set DL4J_TPU_TUNING_DIR yourself to keep the smoke table).
tune-smoke:
	JAX_PLATFORMS=cpu \
	DL4J_TPU_TUNING_DIR=$${DL4J_TPU_TUNING_DIR:-$$(mktemp -d -t dl4j_tune_smoke.XXXXXX)} \
	python tools/tune.py --smoke --json

# full-ladder autotune — run ON THE TARGET CHIP; writes the measured table
# for this device kind to DL4J_TPU_TUNING_DIR (commit a copy under
# deeplearning4j_tpu/ops/tuning_tables/<kind>.json to ship it as default)
tune:
	python tools/tune.py

# chaos smoke (docs/ROBUSTNESS.md): generative serving + checkpoints under
# an injected fault schedule (page OOM, decode crash, worker death, torn
# checkpoint write) — every request must reach a terminal finish reason,
# the supervisor must restart within its cap with ZERO new_shape ledger
# events, and restore() must fall back to the last intact checkpoint.
# ONE JSON line like lint/check/obs.
chaos-smoke:
	JAX_PLATFORMS=cpu python tools/chaos.py --json

# preemption-proof-training smoke (docs/ROBUSTNESS.md § Preemption-proof
# training): a supervised MLN fit under torn checkpoint writes, an
# async-writer death, and hard preemption kills — fails unless the
# resumed loss/param trajectory is BIT-EXACT vs the uninterrupted
# oracle with zero new_shape recompiles, >=1 intact checkpoint, and
# every-step ASYNC checkpointing costs < 10% of the synchronous-save
# baseline per step. ONE JSON line like lint/check/obs/chaos.
train-chaos-smoke:
	JAX_PLATFORMS=cpu python tools/chaos.py --json --leg training

# cluster-failure-domain smoke (docs/ROBUSTNESS.md § Cluster failure
# domains): three engines behind the ClusterRouter under a past-capacity
# burst, one hard-killed mid-flight by engine_death — fails unless every
# request reaches a terminal state, >= 1 in-flight request migrates with
# its greedy output token-for-token identical to the single-engine
# oracle, goodput degrades no worse than proportionally to the capacity
# lost, and survivors show zero new_shape ledger events. ONE JSON line
# like lint/check/obs/chaos.
cluster-chaos-smoke:
	JAX_PLATFORMS=cpu python tools/chaos.py --json --leg cluster

# SLO smoke (docs/SERVING.md § SLO admission frontend): the goodput-
# under-overload ramp, frontend on vs off with an identical offered
# schedule — fails unless frontend-on goodput >= frontend-off, every
# request reaches a terminal state on both legs, the degradation ladder
# actually engaged, and zero new_shape ledger events were paid for it.
# ONE JSON line like lint/check/obs/chaos.
slo-smoke:
	JAX_PLATFORMS=cpu python tools/slo.py --json

# locktrace smoke (docs/LINT.md § graftlock): runtime shadow-lock
# cross-validation of the static lock-order graph — fails unless the
# static graph is acyclic, every lock-order edge observed under the
# threaded serving + checkpoint workload is inside its transitive
# closure, and the combined graph stays acyclic.
# ONE JSON line like lint/check/obs/chaos/slo.
locktrace-smoke:
	JAX_PLATFORMS=cpu python tools/locktrace.py

# shapetrace smoke (docs/LINT.md § graftshape): runtime cross-validation
# of the static jit-site inventory against the RecompileLedger — drives a
# randomized-shape serving replay (prefix cache + speculation on) plus a
# checkpoint-resumed training leg, then fails unless every recompile
# event attributes to a statically ledgered callsite and every new_shape
# event lands in a statically flagged hazard module.
# ONE JSON line like lint/check/obs/chaos/slo/locktrace.
shapetrace-smoke:
	JAX_PLATFORMS=cpu python tools/shapetrace.py

# lifetrace smoke (docs/LINT.md § graftlife): runtime cross-validation of
# the static ownership inventory against live allocators — wraps the real
# paged-KV caches of a 3-engine prefix cluster in recording proxies,
# drives a faults-armed workload (page_oom mid-prefix-admission, decode
# crashes, one engine death) plus an async-checkpoint training leg with a
# worker death MID-WRITE, then fails unless pages end rc-clean, every
# request terminal counted exactly once, no thread leaked, and every
# observed acquire/release callsite lies inside the static inventory.
# ONE JSON line like lint/check/obs/chaos/slo/locktrace/shapetrace.
lifetrace-smoke:
	JAX_PLATFORMS=cpu python tools/lifetrace.py

# prefix-cache smoke (docs/SERVING.md § Radix prefix cache): the shared-
# prompt replay, cache on vs off with an identical request plan — fails
# unless prefix hit tokens > 0, TTFT p50 is >= 30% better than cache-off
# (median of paired trials), greedy outputs are bit-identical on both
# legs, and zero new_shape ledger events were paid for it.
# ONE JSON line like lint/check/obs/chaos/slo.
prefix-smoke:
	JAX_PLATFORMS=cpu python tools/prefix.py --json

# speculative-decoding smoke (docs/SERVING.md § Speculative decoding):
# the greedy replay, spec on vs off with an identical request plan under
# the deterministic slow_decode target-step floor — fails unless draft
# tokens were accepted, tokens/sec >= spec-off (median of paired
# trials), greedy outputs are bit-identical on both legs, the ledger
# shows exactly the expected first_compile events (draft decode +
# verify join the family), and zero new_shape events were paid for it.
# ONE JSON line like lint/check/obs/chaos/slo/prefix.
spec-smoke:
	JAX_PLATFORMS=cpu python tools/spec.py --json

# AOT warm-boot smoke (docs/SERVING.md § AOT warm boot): three fresh
# processes replay the identical randomized-shape request mix with the
# persistent export cache off, populating, and warm — fails unless the
# warm restart pays ZERO serving first_compile ledger events (every
# dispatched fn arrives as cache_hit), its greedy outputs are
# bit-identical to the cache-off leg, zero new_shape events were paid,
# and cold-start TTFT (process boot + first token) stays within 2x the
# cache-off leg. ONE JSON line like lint/check/obs/chaos/slo/prefix.
aot-smoke:
	JAX_PLATFORMS=cpu python tools/aot.py --json

# generative-serving smoke (docs/SERVING.md): continuous-batching
# generation, smoke-sized, CPU-pinned — ONE JSON line with tokens/sec,
# TTFT/inter-token percentiles and the observe generate section.
serve-smoke:
	JAX_PLATFORMS=cpu BENCH_MODEL=generate BENCH_RECORD=0 BENCH_QPS=5 \
	BENCH_REQUESTS=8 BENCH_GEN_TOKENS=8 BENCH_SLOTS=4 BENCH_GPT=tiny \
	python bench.py

# DL4J_TPU_REQUIRE_NATIVE=1: a missing native lib FAILS the ctypes tests
# instead of silently exercising the numpy fallback (SURVEY §5.3)
test: native-test
	DL4J_TPU_REQUIRE_NATIVE=1 python -m pytest tests/ -q

native-test: native
	ctest --test-dir native/build --output-on-failure

# full pre-snapshot gate: pytest + on-chip consistency + bench smoke +
# multichip dryrun (tools/gate.py). Run before any round-end commit.
gate:
	python tools/gate.py

gate-fast:
	python tools/gate.py --fast

bench:
	python bench.py

# graph-compile metric (docs/OPTIMIZER.md): trace+XLA-compile speedup from
# the pre-trace SameDiff optimizer, CPU-pinned (pure compile-time
# measurement — no device loop), one gate-friendly JSON line on stdout.
# Also asserts the fusion tier: a 2-layer imported BERT must report >= 1
# attention fusion, so a matcher regression fails this target.
bench-compile:
	JAX_PLATFORMS=cpu BENCH_MODEL=graph_compile BENCH_RECORD=0 python bench.py

# imported-BERT forward throughput, fusion on vs off (docs/OPTIMIZER.md
# § Fusion tier): one JSON line with tokens/sec + fused_attention_count/
# fused_epilogue_count. Smoke-sized here; unpinned `BENCH_MODEL=bert_import
# python bench.py` measures the real chip.
bench-import:
	JAX_PLATFORMS=cpu BENCH_MODEL=bert_import BENCH_RECORD=0 \
	BENCH_ITERS=3 BENCH_IMPORT_LAYERS=2 BENCH_SEQ=16 BENCH_IMPORT_D=128 \
	BENCH_IMPORT_HEADS=2 BENCH_IMPORT_FF=256 python bench.py

native:
	cmake -S native -B native/build && cmake --build native/build -j
