# Convenience targets (the CI-role entry points — SURVEY §3.4).

.PHONY: test gate gate-fast bench native

test:
	python -m pytest tests/ -q

# full pre-snapshot gate: pytest + on-chip consistency + bench smoke +
# multichip dryrun (tools/gate.py). Run before any round-end commit.
gate:
	python tools/gate.py

gate-fast:
	python tools/gate.py --fast

bench:
	python bench.py

native:
	cmake -S native -B native/build -G Ninja && cmake --build native/build
