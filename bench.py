"""Benchmark harness — prints ONE JSON line for the driver.

Measures training throughput (images/sec) of the flagship model on the
default JAX backend (the real TPU chip under the driver; XLA-CPU locally).
The baseline reference (BASELINE.json) published no numbers
(``published == {}``), so ``vs_baseline`` ratchets against the last recorded
value in BENCH_HISTORY.json (1.0 on first run).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import nn
    from deeplearning4j_tpu.datasets.mnist import synthetic_mnist

    BATCH = 256
    net = nn.MultiLayerNetwork(
        nn.builder().seed(123)
        .updater(nn.Adam(learning_rate=1e-3)).weight_init("xavier").list()
        .layer(nn.ConvolutionLayer(n_out=20, kernel=(5, 5), activation="relu"))
        .layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
        .layer(nn.ConvolutionLayer(n_out=50, kernel=(5, 5), activation="relu"))
        .layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
        .layer(nn.DenseLayer(n_out=500, activation="relu"))
        .layer(nn.OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
        .set_input_type(nn.InputType.convolutional_flat(28, 28, 1))
        .build()
    ).init()

    feats, labels = synthetic_mnist(BATCH)
    y = np.zeros((BATCH, 10), np.float32)
    y[np.arange(BATCH), labels] = 1.0
    x = jnp.asarray(feats)
    yj = jnp.asarray(y)

    step_fn = net._make_train_step()
    params, opt_state, net_state = net.params, net.opt_state, net.net_state
    key = jax.random.key(0)

    def one(i, params, opt_state, net_state):
        return step_fn(params, opt_state, net_state,
                       jnp.asarray(i, jnp.int32), key, x, yj, None, None)

    # warmup/compile
    params, opt_state, net_state, loss = one(0, params, opt_state, net_state)
    loss.block_until_ready()

    iters = 50
    t0 = time.perf_counter()
    for i in range(1, iters + 1):
        params, opt_state, net_state, loss = one(i, params, opt_state, net_state)
    loss.block_until_ready()
    dt = time.perf_counter() - t0
    imgs_per_sec = BATCH * iters / dt

    hist_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_HISTORY.json")
    prev = None
    if os.path.exists(hist_path):
        try:
            prev = json.load(open(hist_path)).get("value")
        except Exception:
            prev = None
    vs_baseline = imgs_per_sec / prev if prev else 1.0
    try:
        json.dump({"value": imgs_per_sec}, open(hist_path, "w"))
    except Exception:
        pass

    print(json.dumps({
        "metric": "lenet5_mnist_train_images_per_sec",
        "value": round(imgs_per_sec, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs_baseline, 3),
    }))


if __name__ == "__main__":
    main()
