"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): ResNet-50 ImageNet-shaped training
throughput, images/sec/chip, on the default JAX backend (the real TPU chip
under the driver). The reference published no numbers
(``BASELINE.json.published == {}``), so ``vs_baseline`` ratchets against the
last recorded value in BENCH_HISTORY.json (1.0 on first run).

Env knobs: BENCH_BATCH (default 64), BENCH_ITERS (default 20),
BENCH_MODEL (resnet50 | lenet), BENCH_IMAGE (default 224; resnet50 only —
LeNet is fixed 28×28 MNIST).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def _bench_resnet50(batch: int, iters: int, image: int):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import models, nn
    from deeplearning4j_tpu.datasets.image import synthetic_image_batch

    net = models.ResNet50(num_classes=1000, input_shape=(image, image, 3),
                          updater=nn.Nesterovs(learning_rate=0.1, momentum=0.9)).init()
    imgs, labels = synthetic_image_batch(batch, image, image, 3, 1000, seed=0)
    y = np.zeros((batch, 1000), np.float32)
    y[np.arange(batch), labels] = 1.0
    x = jnp.asarray(imgs)
    yj = jnp.asarray(y)
    in_name = net.conf.network_inputs[0]
    out_name = net.conf.network_outputs[0]

    step_fn = net._make_train_step()
    params, opt_state, net_state = net.params, net.opt_state, net.net_state
    key = jax.random.key(0)

    def one(i, p, o, s):
        return step_fn(p, o, s, jnp.asarray(i, jnp.int32), key,
                       {in_name: x}, {out_name: yj}, None, None)

    params, opt_state, net_state, loss = one(0, params, opt_state, net_state)
    loss.block_until_ready()  # compile + warmup
    t0 = time.perf_counter()
    for i in range(1, iters + 1):
        params, opt_state, net_state, loss = one(i, params, opt_state, net_state)
    loss.block_until_ready()
    dt = time.perf_counter() - t0
    return batch * iters / dt, "resnet50_imagenet_train_images_per_sec"


def _bench_lenet(batch: int, iters: int):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import models
    from deeplearning4j_tpu.datasets.mnist import synthetic_mnist

    net = models.LeNet(num_classes=10).init()
    feats, labels = synthetic_mnist(batch)
    y = np.zeros((batch, 10), np.float32)
    y[np.arange(batch), labels] = 1.0
    x = jnp.asarray(feats)
    yj = jnp.asarray(y)
    step_fn = net._make_train_step()
    params, opt_state, net_state = net.params, net.opt_state, net.net_state
    key = jax.random.key(0)

    def one(i, p, o, s):
        return step_fn(p, o, s, jnp.asarray(i, jnp.int32), key, x, yj, None, None)

    params, opt_state, net_state, loss = one(0, params, opt_state, net_state)
    loss.block_until_ready()
    t0 = time.perf_counter()
    for i in range(1, iters + 1):
        params, opt_state, net_state, loss = one(i, params, opt_state, net_state)
    loss.block_until_ready()
    dt = time.perf_counter() - t0
    return batch * iters / dt, "lenet5_mnist_train_images_per_sec"


def main() -> None:
    batch = int(os.environ.get("BENCH_BATCH", "64"))
    iters = int(os.environ.get("BENCH_ITERS", "20"))
    image = int(os.environ.get("BENCH_IMAGE", "224"))
    model = os.environ.get("BENCH_MODEL", "resnet50")

    if model == "lenet":
        value, metric = _bench_lenet(batch, iters)
    else:
        value, metric = _bench_resnet50(batch, iters, image)

    hist_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_HISTORY.json")
    hist = {}
    if os.path.exists(hist_path):
        try:
            hist = json.load(open(hist_path))
        except Exception:
            hist = {}
    prev = hist.get(metric)
    vs_baseline = value / prev if prev else 1.0
    try:
        hist[metric] = value
        json.dump(hist, open(hist_path, "w"))
    except Exception:
        pass

    print(json.dumps({
        "metric": metric,
        "value": round(value, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs_baseline, 3),
    }))


if __name__ == "__main__":
    main()
