"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): ResNet-50 ImageNet-shaped training
throughput, images/sec/chip, on the default JAX backend (the real TPU chip
under the driver). The reference published no numbers
(``BASELINE.json.published == {}``), so ``vs_baseline`` ratchets against the
last recorded value in BENCH_HISTORY.json (1.0 on first run).

Env knobs: BENCH_BATCH (default 128), BENCH_ITERS (default 20),
BENCH_MODEL (resnet50 | lenet), BENCH_IMAGE (default 224; resnet50 only —
LeNet is fixed 28×28 MNIST), BENCH_DTYPE (default "mixed": bf16 compute /
f32 params — the TPU-native policy; "float32" for the f32 baseline).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def _bench_resnet50(batch: int, iters: int, image: int, dtype: str):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import models, nn
    from deeplearning4j_tpu.datasets.image import synthetic_image_batch

    net = models.ResNet50(num_classes=1000, input_shape=(image, image, 3),
                          updater=nn.Nesterovs(learning_rate=0.1, momentum=0.9),
                          dtype=dtype).init()
    imgs, labels = synthetic_image_batch(batch, image, image, 3, 1000, seed=0)
    y = np.zeros((batch, 1000), np.float32)
    y[np.arange(batch), labels] = 1.0
    x = jnp.asarray(imgs)
    yj = jnp.asarray(y)

    # fused multi-step loop: lax.scan over the whole train step — zero host
    # dispatch between iterations (fit_scanned). Warm up with the SAME step
    # count so the timed call reuses the compiled executable.
    losses = net.fit_scanned(x, yj, steps=iters)
    assert np.isfinite(losses[-1])
    t0 = time.perf_counter()
    losses = net.fit_scanned(x, yj, steps=iters)
    dt = time.perf_counter() - t0
    assert np.isfinite(losses[-1])
    return batch * iters / dt, "resnet50_imagenet_train_images_per_sec"


def _bench_bert(batch: int, iters: int, dtype: str):
    """BERT-base MLM train step, seq 512 — the attention-bound workload where
    the Pallas flash platform helper carries the win (BENCH_MODEL=bert)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.bert import BertConfig, BertModel

    seq = int(os.environ.get("BENCH_SEQ", "512"))
    # default dropout=0.1 — the production fine-tune config; the Pallas flash
    # helper handles attention-prob dropout IN-KERNEL since round 3, so the
    # fast path no longer needs dropout disabled
    cfg = BertConfig.base()
    model = BertModel(cfg, seed=0,
                      dtype=jnp.bfloat16 if dtype != "float32" else jnp.float32)
    rng = np.random.RandomState(0)
    batch_data = {
        "ids": rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32),
        "segments": np.zeros((batch, seq), np.int32),
        "mask": (rng.rand(batch, seq) > 0.1).astype(np.int32),
        "mlm_labels": rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32),
        "mlm_mask": (rng.rand(batch, seq) < 0.15).astype(np.float32),
    }
    losses = model.fit_mlm_scanned(batch_data, iters)  # compile + warmup
    assert np.isfinite(losses[-1])
    t0 = time.perf_counter()
    losses = model.fit_mlm_scanned(batch_data, iters)
    dt = time.perf_counter() - t0
    assert np.isfinite(losses[-1])
    return batch * seq * iters / dt, "bert_base_mlm_train_tokens_per_sec"


def _bench_lenet(batch: int, iters: int):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import models
    from deeplearning4j_tpu.datasets.mnist import synthetic_mnist

    net = models.LeNet(num_classes=10).init()
    feats, labels = synthetic_mnist(batch)
    y = np.zeros((batch, 10), np.float32)
    y[np.arange(batch), labels] = 1.0
    x = jnp.asarray(feats)
    yj = jnp.asarray(y)
    losses = net.fit_scanned(x, yj, steps=iters)
    assert np.isfinite(losses[-1])
    t0 = time.perf_counter()
    losses = net.fit_scanned(x, yj, steps=iters)
    dt = time.perf_counter() - t0
    assert np.isfinite(losses[-1])
    return batch * iters / dt, "lenet5_mnist_train_images_per_sec"


def _bench_attention(iters: int):
    """Flash-vs-generic attention at T=8192 d=64 bf16 fwd+bwd (the Pallas
    platform-helper headline; recorded as the BENCH_HISTORY 'attention'
    entry the kernel docstring points at). Device-side lax.scan loop — wall
    timing through the axon tunnel is unreliable for single dispatches."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops.pallas_attention import (
        flash_attention, _reference_attention)

    bh, t, d = 8, 8192, 64
    r = np.random.RandomState(0)
    q = jnp.asarray(r.randn(bh, t, d).astype(np.float32)).astype(jnp.bfloat16)
    k = jnp.asarray(r.randn(bh, t, d).astype(np.float32)).astype(jnp.bfloat16)
    v = jnp.asarray(r.randn(bh, t, d).astype(np.float32)).astype(jnp.bfloat16)

    def make(loss_fn):
        grad = jax.grad(loss_fn, argnums=(0, 1, 2))

        @jax.jit
        def bench(q, k, v):
            def body(carry, _):
                dq, dk, dv = grad(carry, k, v)
                z = jnp.asarray(0.0, carry.dtype)
                return carry + z * dq + z * dk + z * dv, jnp.float32(0)

            qf, _ = jax.lax.scan(body, q, None, length=iters)
            return jnp.sum(qf.astype(jnp.float32))

        return bench

    def flash_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, None, None, None, True,
                                       None, None, None, 0.0)
                       .astype(jnp.float32) ** 2)

    def gen_loss(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, scale=d ** -0.5,
                                            causal=True)
                       .astype(jnp.float32) ** 2)

    def run(bench):
        _ = float(bench(q, k, v))  # compile
        t0 = time.perf_counter()
        _ = float(bench(q, k, v))
        return (time.perf_counter() - t0) / iters

    t_flash = run(make(flash_loss))
    t_gen = run(make(gen_loss))
    return t_gen / t_flash, "flash_attention_t8192_speedup_vs_generic"


def main() -> None:
    batch = int(os.environ.get("BENCH_BATCH", "128"))
    iters = int(os.environ.get("BENCH_ITERS", "20"))
    image = int(os.environ.get("BENCH_IMAGE", "224"))
    model = os.environ.get("BENCH_MODEL", "resnet50")
    dtype = os.environ.get("BENCH_DTYPE", "mixed")

    if model == "lenet":
        value, metric = _bench_lenet(batch, iters)
    elif model == "attention":
        value, metric = _bench_attention(iters)
    elif model == "bert":
        value, metric = _bench_bert(int(os.environ.get("BENCH_BERT_BATCH", "16")),
                                    iters, dtype)
    else:
        value, metric = _bench_resnet50(batch, iters, image, dtype)

    hist_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_HISTORY.json")
    hist = {}
    if os.path.exists(hist_path):
        try:
            hist = json.load(open(hist_path))
        except Exception:
            hist = {}
    # RATCHET against the max-watermark, not the previous run — a regression
    # reports <1.0 on EVERY run until fixed instead of resetting its own
    # baseline (round-2 verdict weak #7)
    entry = hist.get(metric)
    if isinstance(entry, dict):
        watermark = entry.get("watermark", 0.0)
        runs = entry.get("runs", [])
    else:  # legacy scalar entry
        watermark = float(entry) if entry else 0.0
        runs = []
    vs_baseline = value / watermark if watermark else 1.0
    nd = 3 if value < 100 else 1  # keep ratio metrics' ratchet sensitive
    runs = (runs + [round(value, nd)])[-20:]
    try:
        hist[metric] = {"watermark": round(max(watermark, value), nd),
                        "runs": runs}
        json.dump(hist, open(hist_path, "w"), indent=1)
    except Exception:
        pass

    unit = {"resnet50_imagenet_train_images_per_sec": "images/sec/chip",
            "lenet5_mnist_train_images_per_sec": "images/sec/chip",
            "bert_base_mlm_train_tokens_per_sec": "tokens/sec/chip",
            "flash_attention_t8192_speedup_vs_generic": "x vs XLA generic"}[metric]
    print(json.dumps({
        "metric": metric,
        "value": round(value, 3 if value < 100 else 1),
        "unit": unit,
        "vs_baseline": round(vs_baseline, 3),
    }))


if __name__ == "__main__":
    main()
