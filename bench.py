"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): ResNet-50 ImageNet-shaped training
throughput, images/sec/chip, on the default JAX backend (the real TPU chip
under the driver). The reference published no numbers
(``BASELINE.json.published == {}``), so ``vs_baseline`` ratchets against the
last recorded value in BENCH_HISTORY.json (1.0 on first run).

Env knobs: BENCH_BATCH (default per model — 128 for resnet50, 4096 for
lenet), BENCH_ITERS (default 60 — the whole
multi-step loop is ONE device dispatch, and through the remote-chip tunnel a
dispatch costs ~100ms, so a short window under-reports the device rate; 60
steps puts the dispatch under 5% of the measurement),
BENCH_MODEL (resnet50 | lenet), BENCH_IMAGE (default 224; resnet50 only —
LeNet is fixed 28×28 MNIST), BENCH_DTYPE (default "mixed": bf16 compute /
f32 params — the TPU-native policy; "float32" for the f32 baseline).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np


def probe_default_backend(timeout: float = 240.0) -> bool:
    """Probe the default JAX backend in a SUBPROCESS — an unreachable TPU
    can hang or crash the initializer (BENCH_r05 recorded rc=1 crashes;
    MULTICHIP_r05 a 1200s hang), so the probe runs where a hang costs a
    bounded timeout. THE one backend probe: tools/obsreport.py and
    __graft_entry__.py import it rather than growing drifting copies."""
    try:
        proc = subprocess.run([sys.executable, "-c",
                               "import jax; jax.devices()"],
                              capture_output=True, timeout=timeout)
        return proc.returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


def _ensure_backend() -> str:
    """Probe the default backend; on failure pin this process to CPU so the
    run still produces data. Returns "default", "pinned" (caller set
    JAX_PLATFORMS) or "cpu-fallback"."""
    if os.environ.get("JAX_PLATFORMS"):
        return "pinned"
    if probe_default_backend():
        return "default"
    os.environ["JAX_PLATFORMS"] = "cpu"
    return "cpu-fallback"


def _bench_resnet50(batch: int, iters: int, image: int, dtype: str):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import models, nn
    from deeplearning4j_tpu.datasets.image import synthetic_image_batch

    net = models.ResNet50(num_classes=1000, input_shape=(image, image, 3),
                          updater=nn.Nesterovs(learning_rate=0.1, momentum=0.9),
                          dtype=dtype).init()
    imgs, labels = synthetic_image_batch(batch, image, image, 3, 1000, seed=0)
    y = np.zeros((batch, 1000), np.float32)
    y[np.arange(batch), labels] = 1.0
    x = jnp.asarray(imgs)
    yj = jnp.asarray(y)

    # fused multi-step loop: lax.scan over the whole train step — zero host
    # dispatch between iterations (fit_scanned). Warm up with the SAME step
    # count so the timed call reuses the compiled executable.
    losses = net.fit_scanned(x, yj, steps=iters)
    assert np.isfinite(losses[-1])
    t0 = time.perf_counter()
    losses = net.fit_scanned(x, yj, steps=iters)
    dt = time.perf_counter() - t0
    assert np.isfinite(losses[-1])
    return batch * iters / dt, "resnet50_imagenet_train_images_per_sec"


def _bench_bert(batch: int, iters: int, dtype: str, seq: int):
    """BERT-base MLM train step, seq 512 — the attention-bound workload where
    the Pallas flash platform helper carries the win (BENCH_MODEL=bert)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.bert import BertConfig, BertModel
    # default dropout=0.1 — the production fine-tune config; the Pallas flash
    # helper handles attention-prob dropout IN-KERNEL since round 3, so the
    # fast path no longer needs dropout disabled
    cfg = BertConfig.base()
    model = BertModel(cfg, seed=0,
                      dtype=jnp.bfloat16 if dtype != "float32" else jnp.float32)
    rng = np.random.RandomState(0)
    batch_data = {
        "ids": rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32),
        "segments": np.zeros((batch, seq), np.int32),
        "mask": (rng.rand(batch, seq) > 0.1).astype(np.int32),
        "mlm_labels": rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32),
        "mlm_mask": (rng.rand(batch, seq) < 0.15).astype(np.float32),
    }
    losses = model.fit_mlm_scanned(batch_data, iters)  # compile + warmup
    assert np.isfinite(losses[-1])
    t0 = time.perf_counter()
    losses = model.fit_mlm_scanned(batch_data, iters)
    dt = time.perf_counter() - t0
    assert np.isfinite(losses[-1])
    return batch * seq * iters / dt, "bert_base_mlm_train_tokens_per_sec"


def _bench_lenet(batch: int, iters: int):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import models
    from deeplearning4j_tpu.datasets.mnist import synthetic_mnist

    net = models.LeNet(num_classes=10).init()
    feats, labels = synthetic_mnist(batch)
    y = np.zeros((batch, 10), np.float32)
    y[np.arange(batch), labels] = 1.0
    x = jnp.asarray(feats)
    yj = jnp.asarray(y)
    losses = net.fit_scanned(x, yj, steps=iters)
    assert np.isfinite(losses[-1])
    t0 = time.perf_counter()
    losses = net.fit_scanned(x, yj, steps=iters)
    dt = time.perf_counter() - t0
    assert np.isfinite(losses[-1])
    return batch * iters / dt, "lenet5_mnist_train_images_per_sec"


def _bench_attention(iters: int):
    """Flash-vs-generic attention at T=8192 d=64 bf16 fwd+bwd (the Pallas
    platform-helper headline; recorded as the BENCH_HISTORY 'attention'
    entry the kernel docstring points at). Device-side lax.scan loop — wall
    timing through the axon tunnel is unreliable for single dispatches."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops.pallas_attention import (
        flash_attention, _reference_attention)

    bh, t, d = 8, 8192, 64
    r = np.random.RandomState(0)
    q = jnp.asarray(r.randn(bh, t, d).astype(np.float32)).astype(jnp.bfloat16)
    k = jnp.asarray(r.randn(bh, t, d).astype(np.float32)).astype(jnp.bfloat16)
    v = jnp.asarray(r.randn(bh, t, d).astype(np.float32)).astype(jnp.bfloat16)

    def make(loss_fn):
        grad = jax.grad(loss_fn, argnums=(0, 1, 2))

        @jax.jit
        def bench(q, k, v):
            def body(carry, _):
                dq, dk, dv = grad(carry, k, v)
                z = jnp.asarray(0.0, carry.dtype)
                return carry + z * dq + z * dk + z * dv, jnp.float32(0)

            qf, _ = jax.lax.scan(body, q, None, length=iters)
            return jnp.sum(qf.astype(jnp.float32))

        return bench

    def flash_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, None, None, None, True,
                                       None, None, None, 0.0)
                       .astype(jnp.float32) ** 2)

    def gen_loss(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, scale=d ** -0.5,
                                            causal=True)
                       .astype(jnp.float32) ** 2)

    def run(bench):
        _ = float(bench(q, k, v))  # compile
        t0 = time.perf_counter()
        _ = float(bench(q, k, v))
        return (time.perf_counter() - t0) / iters

    t_flash = run(make(flash_loss))
    t_gen = run(make(gen_loss))
    return t_gen / t_flash, "flash_attention_t8192_speedup_vs_generic"


def _pct_ms(sorted_xs, q: float) -> float:
    """Nearest-rank percentile of an ascending latency list, in ms — ONE
    convention for every latency report this file emits."""
    return round(sorted_xs[min(len(sorted_xs) - 1,
                               int(q * len(sorted_xs)))] * 1e3, 3)


def _bench_serving(qps: float, n_requests: int, max_batch: int):
    """Serving-latency benchmark (BENCH_MODEL=serving): a fixed-QPS open
    load of ``ParallelInference.predict`` calls against a small MLP —
    requests are issued on schedule regardless of completions (open-loop,
    the honest way to measure tail latency under load; closed loops hide
    queueing). Value = achieved req/sec; the JSON line carries p50/p99 from
    the measured per-request latencies AND the observe/ snapshot carries
    the registry's serving histogram, so the bench trajectory records
    latency, not just throughput. CPU-smoke sized under the subprocess-
    probe fallback."""
    import threading
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    from deeplearning4j_tpu import nn
    from deeplearning4j_tpu.parallel.mesh import ParallelInference

    n_in, n_out = 32, 10
    conf = (nn.builder().seed(0).updater(nn.Adam(learning_rate=1e-3)).list()
            .layer(nn.DenseLayer(n_out=64, activation="relu"))
            .layer(nn.OutputLayer(n_out=n_out, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(n_in)).build())
    net = nn.MultiLayerNetwork(conf).init()
    pi = ParallelInference(net, max_batch=max_batch, window_ms=2.0).start()
    lat = [None] * n_requests
    try:
        pi.predict(np.zeros(n_in, np.float32))  # compile the serving path
        r = np.random.RandomState(0)
        reqs = r.randn(n_requests, n_in).astype(np.float32)

        def issue(i, t0):
            # t0 is the SUBMIT time: executor queueing counts toward the
            # client-perceived latency — starting the clock at worker
            # pickup would reintroduce coordinated omission exactly when
            # the pool saturates (the overload regime tails matter in)
            pi.predict(reqs[i])
            lat[i] = time.perf_counter() - t0

        futs = []
        with ThreadPoolExecutor(max_workers=32) as ex:
            t_start = time.perf_counter()
            for i in range(n_requests):
                delay = (t_start + i / qps) - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                futs.append(ex.submit(issue, i, time.perf_counter()))
        t_total = time.perf_counter() - t_start
        # a failed request must fail the bench, not silently shrink the
        # sample — survivors-only percentiles would record an inflated
        # watermark from a partially broken serving path
        errs = [f.exception() for f in futs if f.exception() is not None]
        if errs:
            raise RuntimeError(
                f"{len(errs)}/{n_requests} serving requests failed; "
                f"first: {errs[0]!r}")
    finally:
        pi.stop()
    done = sorted(l for l in lat if l is not None)
    assert done, "no serving request completed"
    extra = {"p50_ms": _pct_ms(done, 0.50), "p99_ms": _pct_ms(done, 0.99),
             "offered_qps": qps, "completed": len(done)}
    return len(done) / t_total, "serving_fixed_qps_req_per_sec", extra


def _bench_generate(qps: float, n_requests: int, gen_tokens: int,
                    max_slots: int, preset: str):
    """Generative-serving benchmark (BENCH_MODEL=generate): a fixed-QPS
    open-loop stream of text-generation requests against the continuous-
    batching engine (docs/SERVING.md) — submissions follow the schedule
    regardless of completions, same honesty argument as BENCH_MODEL=serving.
    Value = generated tokens/sec; the JSON line carries p50/p99
    time-to-first-token AND inter-token latency from the per-request
    measurements, plus the observe/ snapshot (admit/evict/generated
    counters, decode-step percentiles). The snapshot is PROCESS-WIDE and
    includes the warmup request's compile-inclusive latencies (same
    semantics as BENCH_MODEL=serving) — the steady-state percentiles are
    the top-level ttft_*/intertoken_* fields, measured post-warmup.
    Smoke-sized under the subprocess-probe CPU fallback."""
    from deeplearning4j_tpu.models.gpt import GptConfig, GptModel
    from deeplearning4j_tpu.serving import GenerativeEngine

    cfg = GptConfig.tiny(vocab_size=512) if preset == "tiny" else \
        GptConfig.base(vocab_size=8192, max_position=512)
    model = GptModel(cfg, seed=0)
    max_prompt = int(os.environ.get("BENCH_MAX_PROMPT", "16"))
    pages_per_seq = -(-(max_prompt + gen_tokens + 1) // 16) + 1
    eng = GenerativeEngine(model, max_slots=max_slots, page_size=16,
                           max_pages_per_seq=pages_per_seq,
                           max_prompt=max_prompt, seed=0).start()
    try:
        r = np.random.RandomState(0)
        prompts = [r.randint(1, cfg.vocab_size,
                             size=r.randint(2, max_prompt)).astype(np.int32)
                   for _ in range(n_requests)]
        # warm both compiled paths so the timed window measures serving,
        # not the first prefill/decode XLA compile
        eng.submit(prompts[0][:2], max_new_tokens=2,
                   eos_token=-1).result(timeout=600)
        futs = []
        t_start = time.perf_counter()
        for i in range(n_requests):
            delay = (t_start + i / qps) - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            futs.append(eng.submit(
                prompts[i], max_new_tokens=gen_tokens, temperature=0.8,
                top_k=40, top_p=0.95, eos_token=-1))
        results = [f.result(timeout=600) for f in futs]
        t_total = time.perf_counter() - t_start
    finally:
        eng.stop()
    n_tokens = sum(len(res.tokens) for res in results)
    assert n_tokens > 0, "no tokens generated"
    ttfts = sorted(res.ttft_s for res in results)
    itls = sorted(g for res in results for g in res.intertoken_s)
    extra = {"generated_tokens": n_tokens,
             "ttft_p50_ms": _pct_ms(ttfts, 0.50),
             "ttft_p99_ms": _pct_ms(ttfts, 0.99),
             "intertoken_p50_ms": _pct_ms(itls, 0.50) if itls else None,
             "intertoken_p99_ms": _pct_ms(itls, 0.99) if itls else None,
             "offered_qps": qps, "completed": len(results)}
    return n_tokens / t_total, "generate_open_loop_tokens_per_sec", extra


def _bench_generate_overload(n_requests: int, gen_tokens: int,
                             max_slots: int, factor: float,
                             slow_decode: bool):
    """Goodput-under-overload benchmark (BENCH_MODEL=generate +
    BENCH_OVERLOAD=1): the shared open-loop overload ramp
    (serving/overload.py, docs/SERVING.md § SLO admission frontend) run
    twice past measured capacity — SLOFrontend on, then raw engine.submit
    with the IDENTICAL offered schedule. Value = frontend-on goodput
    (completed-within-deadline tokens/sec, the ROADMAP 2(d) metric); the
    JSON line carries the frontend-off goodput, the ratio, shed/reason
    accounting and the ladder states visited, so "the frontend beats the
    baseline under overload" is a recorded number, not a claim. This is a
    POLICY benchmark, not a kernel benchmark: by default both legs arm
    the deterministic 50ms slow_decode service floor so the comparison
    measures admission policy rather than host scheduling jitter
    (BENCH_SLOW_DECODE=0 opts out for a raw-hardware ramp)."""
    from deeplearning4j_tpu.serving.overload import run_overload_ramp

    # throwaway warm-up: the first ramp in a process absorbs the slow
    # early XLA steps into its latency signal
    run_overload_ramp(frontend_on=False, n_requests=3,
                      gen_tokens=gen_tokens, max_slots=max_slots,
                      overload_factor=factor)
    on = run_overload_ramp(
        frontend_on=True, n_requests=n_requests, gen_tokens=gen_tokens,
        max_slots=max_slots, overload_factor=factor,
        slow_decode=slow_decode)
    off = run_overload_ramp(
        frontend_on=False, n_requests=n_requests, gen_tokens=gen_tokens,
        max_slots=max_slots, overload_factor=factor,
        slow_decode=slow_decode,
        capacity_tokens_per_sec=on["capacity_tokens_per_sec"])
    assert on["all_terminal"] and off["all_terminal"], \
        "overload ramp left non-terminal requests"
    g_on, g_off = on["goodput_tokens_per_sec"], off["goodput_tokens_per_sec"]
    extra = {
        "goodput_off": g_off,
        "goodput_ratio": round(g_on / g_off, 3) if g_off else None,
        "overload_factor": factor,
        "capacity_tokens_per_sec": on["capacity_tokens_per_sec"],
        "states_visited": on.get("states_visited"),
        "reasons_on": on["reasons"], "reasons_off": off["reasons"],
        "degraded_results": on["degraded_results"],
        "interactive_ttft_p99_ms_on": on.get("interactive_ttft_p99_ms"),
        "interactive_ttft_p99_ms_off": off.get("interactive_ttft_p99_ms"),
        "new_shape_events": on["new_shape_events"] + off["new_shape_events"],
    }
    return g_on, "generate_overload_goodput_tokens_per_sec", extra


def _bench_generate_prefix(n_requests: int, n_prefixes: int, sys_len: int,
                           gen_tokens: int):
    """Shared-prompt replay benchmark (BENCH_MODEL=generate +
    BENCH_PREFIX=1): the radix-prefix-cache acceptance harness
    (serving/replay.py, docs/SERVING.md § Radix prefix cache) run twice —
    cache on, then cache off with the IDENTICAL request plan. Value = the
    TTFT p50 speedup the cache buys (off/on); the JSON line carries both
    legs' TTFT percentiles, the hit accounting, and the bit-identical
    check, so "shared prompts admit in O(suffix)" is a recorded number.
    Both legs greedy: outputs MUST match token-for-token — a numerics
    regression in the suffix-prefill path fails the bench, not just a
    test."""
    from deeplearning4j_tpu.serving.replay import run_prefix_replay

    on = run_prefix_replay(prefix_on=True, n_requests=n_requests,
                           n_prefixes=n_prefixes, sys_len=sys_len,
                           gen_tokens=gen_tokens)
    off = run_prefix_replay(prefix_on=False, n_requests=n_requests,
                            n_prefixes=n_prefixes, sys_len=sys_len,
                            gen_tokens=gen_tokens)
    identical = on["outputs"] == off["outputs"]
    assert identical, (
        "prefix-cache outputs diverged from the cache-off oracle — the "
        "suffix-prefill path is numerically wrong")
    assert on["prefix_hit_tokens"] > 0, "replay produced zero prefix hits"
    speedup = (off["ttft_p50_ms"] / on["ttft_p50_ms"]
               if on["ttft_p50_ms"] else 0.0)
    extra = {
        "ttft_p50_ms_on": on["ttft_p50_ms"],
        "ttft_p50_ms_off": off["ttft_p50_ms"],
        "ttft_p99_ms_on": on["ttft_p99_ms"],
        "ttft_p99_ms_off": off["ttft_p99_ms"],
        "ttft_improvement_pct": round(100.0 * (1.0 - 1.0 / speedup), 1)
        if speedup else None,
        "prefix_hit_tokens": on["prefix_hit_tokens"],
        "hit_requests": on["hit_requests"],
        "requests": on["requests"],
        "outputs_identical": identical,
        "tree_pages": on.get("tree_pages"),
        "new_shape_events": on["new_shape_events"] + off["new_shape_events"],
    }
    return speedup, "generate_prefix_ttft_p50_speedup", extra


def _bench_generate_spec(n_requests: int, gen_tokens: int, spec_k: int):
    """Speculative-decoding benchmark (BENCH_MODEL=generate +
    BENCH_SPEC=1): the replay harness (serving/replay.py, docs/SERVING.md
    § Speculative decoding) run twice — spec on, then spec off with the
    IDENTICAL greedy request plan, both under the deterministic 50ms
    slow_decode target-step floor (the slo-gate measurement model). Value
    = the decode tokens/sec speedup speculation buys (on/off); the JSON
    line carries both legs' rates, the proposal/acceptance accounting,
    and the bit-identical check — losslessness fails the bench, not just
    a test."""
    from deeplearning4j_tpu.serving.replay import run_spec_replay

    on = run_spec_replay(spec_on=True, n_requests=n_requests,
                         gen_tokens=gen_tokens, spec_k=spec_k)
    off = run_spec_replay(spec_on=False, n_requests=n_requests,
                          gen_tokens=gen_tokens, spec_k=spec_k)
    identical = on["outputs"] == off["outputs"]
    assert identical, (
        "speculative outputs diverged from the spec-off oracle — the "
        "verify/rollback path is numerically wrong")
    assert on["accepted_tokens"] > 0, "replay accepted zero draft tokens"
    speedup = (on["tokens_per_sec"] / off["tokens_per_sec"]
               if off["tokens_per_sec"] else 0.0)
    extra = {
        "tokens_per_sec_on": on["tokens_per_sec"],
        "tokens_per_sec_off": off["tokens_per_sec"],
        "spec_k": on["spec_k"],
        "proposed_tokens": on["proposed_tokens"],
        "accepted_tokens": on["accepted_tokens"],
        "acceptance_rate": on["acceptance_rate"],
        "requests": on["requests"],
        "outputs_identical": identical,
        "first_compile_keys_on": on["first_compile_keys"],
        "new_shape_events": on["new_shape_events"] + off["new_shape_events"],
    }
    return speedup, "generate_spec_tokens_per_sec_speedup", extra


def _bench_generate_random_shapes(n_requests: int, gen_max: int,
                                  spec_k: int):
    """Shape-diversity benchmark (BENCH_MODEL=generate +
    BENCH_RANDOM_SHAPES=1): the graftshape cross-validation workload
    (serving/replay.py, docs/LINT.md § graftshape) — prompt lengths
    drawn across the whole 1..max_prompt range, varied generation
    lengths, shared-prefix mixes, prefix cache AND speculation armed.
    Value = distinct prompt lengths served; the assertions are the
    point: every request terminal, ZERO serving new_shape events — the
    bucketing contract absorbs arbitrary request geometry without a
    single recompile."""
    from deeplearning4j_tpu.serving.replay import run_randomized_replay

    out = run_randomized_replay(n_requests=n_requests, gen_max=gen_max,
                                spec_k=spec_k)
    assert out["all_terminal"], (
        "randomized-shape replay left non-terminal requests: "
        f"{out['reasons']}")
    assert out["new_shape_events"] == 0, (
        "randomized request shapes leaked into a jit signature — "
        f"{out['new_shape_events']} serving new_shape event(s)")
    extra = {
        "requests": out["requests"],
        "prompt_lens": out["prompt_lens"],
        "gen_lens": out["gen_lens"],
        "generated_tokens": out["generated_tokens"],
        "prefix_hit_tokens": out["prefix_hit_tokens"],
        "first_compile_keys": out["first_compile_keys"],
        "new_shape_events": out["new_shape_events"],
    }
    return (float(len(out["prompt_lens"])),
            "generate_random_shapes_distinct_prompt_lens", extra)


def _bench_generate_cold_restart(n_requests: int, seed: int):
    """Cold-process restart benchmark (BENCH_MODEL=generate
    BENCH_COLD_RESTART=1): the AOT warm-boot workload (tools/aot.py,
    docs/SERVING.md § AOT warm boot) — three FRESH processes replay the
    identical randomized-shape request mix with the compile cache off,
    populating, and warm. Value = cold-restart TTFT ratio (cache-off
    process boot + first token over warm ditto); the assertions are the
    acceptance criteria: the warm leg pays ZERO serving first_compile
    events, its outputs are bit-identical to the cache-off leg, and its
    cold-start TTFT stays within 2x."""
    import subprocess

    cmd = [sys.executable,
           os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "aot.py"),
           "--json", "--requests", str(n_requests), "--seed", str(seed)]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=1800)
    rec = None
    for ln in proc.stdout.splitlines():
        ln = ln.strip()
        if ln.startswith("{") and '"tool"' in ln:
            rec = json.loads(ln)
            break
    assert rec is not None, (
        f"tools/aot.py emitted no summary line (rc={proc.returncode}): "
        f"{proc.stderr[-500:]}")
    assert rec["ok"], f"AOT warm-boot gate failed: {rec}"
    extra = {
        "ttft_cold_off_ms": rec["ttft_cold_off_ms"],
        "ttft_warm_ms": rec["ttft_warm_ms"],
        "boot_cold_s": rec["boot_cold_s"],
        "boot_warm_s": rec["boot_warm_s"],
        "warm_cache_hit_keys": rec["warm_cache_hit_keys"],
        "warm_first_compile_keys": rec["warm_first_compile_keys"],
        "outputs_identical": rec["outputs_identical"],
        "new_shape_events": rec["new_shape_events"],
        "requests_per_leg": rec["requests_per_leg"],
    }
    return float(rec["cold_restart_ttft_ratio"]), \
        "generate_cold_restart_ttft_ratio", extra


def _bench_bert_import(layers: int, seq: int, d: int, heads: int, ff: int,
                       iters: int):
    """Imported-BERT forward throughput (BENCH_MODEL=bert_import): the
    SAME ONNX bytes imported twice — fusion off vs on (docs/OPTIMIZER.md
    § Fusion tier) — timed end-to-end on repeated forward passes. Value =
    tokens/sec WITH fusion; the JSON line carries the unfused rate, the
    speedup, and the fused_attention_count/fused_epilogue_count hit
    counters from OptimizeStats, so the import-path fast-kernel routing is
    a number, not a claim. CPU-smoke sized under the subprocess-probe
    fallback."""
    from deeplearning4j_tpu.imports.onnx_import import import_onnx
    from deeplearning4j_tpu.testing.onnx_builder import bert_onnx_model

    batch = 1
    model = bert_onnx_model(layers=layers, batch=batch, seq=seq, d=d,
                            heads=heads, ff=ff)
    r = np.random.RandomState(1)
    feeds = {"ids": r.randint(0, 512, (batch, seq)).astype(np.float32),
             "mask": (r.rand(batch, seq) > 0.1).astype(np.float32)}

    def run(sd):
        sd.output(feeds, ["y"])  # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            out = sd.output(feeds, ["y"])["y"]
        dt = time.perf_counter() - t0
        assert np.isfinite(out).all()
        return batch * seq * iters / dt

    # pin BOTH legs explicitly — an ambient DL4J_TPU_FUSION=0 (the
    # documented opt-out) must not silently turn the "fused" leg into a
    # second unfused measurement (and a false regression assert)
    prev = os.environ.get("DL4J_TPU_FUSION")
    try:
        os.environ["DL4J_TPU_FUSION"] = "0"
        unfused_tps = run(import_onnx(model))
        os.environ["DL4J_TPU_FUSION"] = "1"
        sd = import_onnx(model)
        fused_tps = run(sd)
    finally:
        if prev is None:
            os.environ.pop("DL4J_TPU_FUSION", None)
        else:
            os.environ["DL4J_TPU_FUSION"] = prev
    st = sd.last_compile_stats
    att = st.fusions.get("attention", 0)
    epi = st.fusions.get("epilogue", 0)
    assert att >= layers, (
        f"attention fusion regressed: {att} < {layers} blocks matched "
        f"on a {layers}-layer imported BERT")
    extra = {"fused_attention_count": att, "fused_epilogue_count": epi,
             "tokens_per_sec_unfused": round(unfused_tps, 1),
             "fusion_speedup": round(fused_tps / unfused_tps, 3),
             "nodes_before": st.nodes_before, "nodes_after": st.nodes_after}
    return fused_tps, "bert_import_forward_tokens_per_sec", extra


def _bench_graph_compile(layers: int, width: int):
    """Graph-compile metric (docs/OPTIMIZER.md, `make bench-compile`): a
    redundant SameDiff graph — per-layer duplicated subexpressions, foldable
    constant chains, identity/transpose no-ops, dead branches, i.e. the
    shapes importers actually emit — is traced+compiled twice, optimizer off
    vs on. Value = wall speedup of trace+XLA-compile; the JSON line also
    carries the node counts so the win is a number, not a claim. CPU-safe
    (pure compile-time measurement, no training loop)."""
    from deeplearning4j_tpu.autodiff.samediff import SameDiff

    batch = 4

    def build(optimize: bool) -> SameDiff:
        r = np.random.RandomState(0)
        sd = SameDiff(optimize=optimize)
        h = sd.placeholder("x", (batch, width))
        for i in range(layers):
            w = sd.var(f"w{i}", r.randn(width, width).astype(np.float32) * 0.05)
            b = sd.var(f"b{i}", np.zeros(width, np.float32))
            c = sd.constant(f"c{i}", np.float32(width))
            scale = sd.math.sqrt(c)                   # foldable const chain
            pre = (h @ w + b) / scale
            t1 = sd.math.tanh(pre)
            t2 = sd.math.tanh(pre)                    # CSE duplicate
            g = sd.nn.sigmoid(t1 + t2)
            # no-op chain: the identity node and transpose pair are
            # stripped; the *1+0 arithmetic survives (placeholder-rooted,
            # so its dtype is unprovable — see docs/OPTIMIZER.md) exactly
            # as it would in an imported graph
            g = sd.op("identity", g) * 1.0 + 0.0
            g = g.transpose(1, 0).transpose(1, 0)
            _dead = sd.math.exp(pre) @ w              # dead branch
            h = g
        h.sum().rename("out")
        return sd

    feeds = {"x": np.random.RandomState(1).randn(batch, width)
             .astype(np.float32)}
    wall, outs, stats = {}, {}, {}
    for mode in (False, True):
        sd = build(mode)
        t0 = time.perf_counter()
        outs[mode] = sd.output(feeds, ["out"])["out"]
        wall[mode] = time.perf_counter() - t0
        stats[mode] = sd.last_compile_stats
    np.testing.assert_allclose(outs[False], outs[True], rtol=1e-5, atol=1e-5)

    # fusion gate (docs/OPTIMIZER.md § Fusion tier): a mini imported BERT
    # must report attention fusions — a matcher regression fails
    # `make bench-compile` (a gate-adjacent target), not just the separate
    # BENCH_MODEL=bert_import benchmark
    from deeplearning4j_tpu.imports.onnx_import import import_onnx
    from deeplearning4j_tpu.testing.onnx_builder import bert_onnx_model

    prev = os.environ.get("DL4J_TPU_FUSION")
    os.environ["DL4J_TPU_FUSION"] = "1"  # the gate must test the matcher
    try:                                 # even under an ambient opt-out
        mini = import_onnx(bert_onnx_model(layers=2, seq=8, d=64, heads=2,
                                           ff=128, vocab=64))
        r = np.random.RandomState(2)
        mini.output({"ids": r.randint(0, 64, (1, 8)).astype(np.float32),
                     "mask": np.ones((1, 8), np.float32)}, ["y"])
    finally:
        if prev is None:
            os.environ.pop("DL4J_TPU_FUSION", None)
        else:
            os.environ["DL4J_TPU_FUSION"] = prev
    att = mini.last_compile_stats.fusions.get("attention", 0)
    assert att >= 1, (
        f"fusion regression: imported 2-layer BERT reports {att} attention "
        f"fusions (expected >= 1)")

    extra = {"nodes_before": stats[True].nodes_before,
             "nodes_after": stats[True].nodes_after,
             "compile_s_unoptimized": round(wall[False], 3),
             "compile_s_optimized": round(wall[True], 3),
             "fused_attention_count": att}
    return wall[False] / wall[True], "graph_compile_optimizer_speedup", extra


# bf16 peak matmul TFLOP/s by device kind substring (public spec sheets);
# MFU = achieved model FLOP/s over this peak — the honest utilization
# number the reference's img/s headline hides (round-3 verdict weak #1)
_PEAK_TFLOPS = (("v5 lite", 197.0), ("v5litepod", 197.0), ("v5p", 459.0),
                ("v6e", 918.0), ("v4", 275.0))


def _device_peak_tflops() -> float:
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for sub, peak in _PEAK_TFLOPS:
        if sub in kind:
            return peak
    return 0.0  # unknown device (CPU test runs): suppress MFU


def _model_flops_per_unit(metric: str, image: int) -> float:
    """Analytic training FLOPs per metric unit (image or token)."""
    if metric.startswith("resnet50"):
        # 4.1 GFLOP fwd @224 (standard count), train ~= 3x fwd
        return 4.1e9 * 3 * (image / 224.0) ** 2
    if metric.startswith("bert_base"):
        # 6 * params per token (fwd+bwd), BERT-base N=110M; attention terms
        # add a few % at seq 512 — the 6N convention is the scaling-book one
        return 6.0 * 110e6
    if metric.startswith("lenet5"):
        return 11e6 * 3  # ~11 MFLOP fwd per 28x28 image
    return 0.0


def _mfu(metric: str, value: float, image: int):
    peak = _device_peak_tflops()
    per_unit = _model_flops_per_unit(metric, image)
    if not peak or not per_unit:
        return None
    return round(value * per_unit / (peak * 1e12), 4)


# unit by metric — module-level so the failure path can still label the line
_UNITS = {"resnet50_imagenet_train_images_per_sec": "images/sec/chip",
          "lenet5_mnist_train_images_per_sec": "images/sec/chip",
          "bert_base_mlm_train_tokens_per_sec": "tokens/sec/chip",
          "flash_attention_t8192_speedup_vs_generic": "x vs XLA generic",
          "graph_compile_optimizer_speedup": "x trace+compile speedup",
          "bert_import_forward_tokens_per_sec": "tokens/sec",
          "serving_fixed_qps_req_per_sec": "req/sec",
          "generate_open_loop_tokens_per_sec": "tokens/sec",
          "generate_overload_goodput_tokens_per_sec":
              "deadline-met tokens/sec",
          "generate_prefix_ttft_p50_speedup": "x TTFT p50 vs cache-off",
          "generate_spec_tokens_per_sec_speedup": "x tokens/sec vs spec-off",
          "generate_random_shapes_distinct_prompt_lens":
              "distinct prompt lens, 0 recompiles",
          "generate_cold_restart_ttft_ratio":
              "x cold-restart TTFT, cache-off vs warm"}

_MODEL_METRIC = {"resnet50": "resnet50_imagenet_train_images_per_sec",
                 "lenet": "lenet5_mnist_train_images_per_sec",
                 "bert": "bert_base_mlm_train_tokens_per_sec",
                 "attention": "flash_attention_t8192_speedup_vs_generic",
                 "graph_compile": "graph_compile_optimizer_speedup",
                 "bert_import": "bert_import_forward_tokens_per_sec",
                 "serving": "serving_fixed_qps_req_per_sec",
                 "generate": "generate_open_loop_tokens_per_sec",
                 "generate_overload":
                     "generate_overload_goodput_tokens_per_sec",
                 "generate_prefix": "generate_prefix_ttft_p50_speedup",
                 "generate_spec": "generate_spec_tokens_per_sec_speedup",
                 "generate_random_shapes":
                     "generate_random_shapes_distinct_prompt_lens",
                 "generate_cold_restart":
                     "generate_cold_restart_ttft_ratio"}


def main() -> None:
    backend = _ensure_backend()
    model = os.environ.get("BENCH_MODEL", "resnet50")
    # the documented spellings are BENCH_MODEL=generate BENCH_OVERLOAD=1
    # (goodput ramp) and BENCH_MODEL=generate BENCH_PREFIX=1 (shared-
    # prompt replay); the canonical metric keys apply either way
    if model == "generate" and os.environ.get("BENCH_OVERLOAD") == "1":
        model = "generate_overload"
    elif model == "generate" and os.environ.get("BENCH_PREFIX") == "1":
        model = "generate_prefix"
    elif model == "generate" and os.environ.get("BENCH_SPEC") == "1":
        model = "generate_spec"
    elif model == "generate" and os.environ.get("BENCH_RANDOM_SHAPES") == "1":
        model = "generate_random_shapes"
    elif model == "generate" and os.environ.get("BENCH_COLD_RESTART") == "1":
        model = "generate_cold_restart"
    dtype = os.environ.get("BENCH_DTYPE", "mixed")
    smoke = backend == "cpu-fallback"
    # On cpu-fallback, headline workloads at device sizes would run for
    # hours on the host — shrink to smoke sizes (explicit env still wins)
    # so the run exits 0 with a labeled, parsable line instead of rc=1.
    iters = int(os.environ.get("BENCH_ITERS", "2" if smoke else "60"))
    image = int(os.environ.get("BENCH_IMAGE", "64" if smoke else "224"))

    # Per-model default batch: the timed window must dwarf the ~100ms tunnel
    # dispatch or the number measures jitter, not the device (LeNet at
    # batch 128 × 60 steps is ~80ms of device work — pure noise). 4096 puts
    # LeNet's window at ~2.5s; ResNet's 128×60 is already ~2.8s.
    default_batch = {"lenet": 4096}.get(model, 128)
    if smoke:
        default_batch = {"lenet": 256}.get(model, 8)
    batch = int(os.environ.get("BENCH_BATCH", str(default_batch)))

    extra = {}
    try:
        if model == "lenet":
            value, metric = _bench_lenet(batch, iters)
            method = f"b{batch}i{iters}"
        elif model == "attention":
            value, metric = _bench_attention(iters)
            method = f"i{iters}"
        elif model == "bert":
            bb = int(os.environ.get("BENCH_BERT_BATCH", "2" if smoke else "16"))
            seq = int(os.environ.get("BENCH_SEQ", "128" if smoke else "512"))
            value, metric = _bench_bert(bb, iters, dtype, seq)
            method = f"b{bb}s{seq}i{iters}{'' if dtype == 'mixed' else dtype}"
        elif model == "graph_compile":
            layers = int(os.environ.get("BENCH_GRAPH_LAYERS", "6"))
            width = int(os.environ.get("BENCH_GRAPH_WIDTH", "192"))
            value, metric, extra = _bench_graph_compile(layers, width)
            method = f"L{layers}w{width}"
        elif model == "bert_import":
            bl = int(os.environ.get("BENCH_IMPORT_LAYERS",
                                    "2" if smoke else "12"))
            seq = int(os.environ.get("BENCH_SEQ", "16" if smoke else "128"))
            bd = int(os.environ.get("BENCH_IMPORT_D",
                                    "128" if smoke else "768"))
            bh = int(os.environ.get("BENCH_IMPORT_HEADS",
                                    "2" if smoke else "12"))
            bff = int(os.environ.get("BENCH_IMPORT_FF",
                                     "256" if smoke else "3072"))
            value, metric, extra = _bench_bert_import(bl, seq, bd, bh, bff,
                                                      iters)
            method = f"L{bl}s{seq}d{bd}i{iters}"
        elif model == "serving":
            qps = float(os.environ.get("BENCH_QPS", "25" if smoke else "200"))
            nreq = int(os.environ.get("BENCH_REQUESTS",
                                      "50" if smoke else "1000"))
            mb = int(os.environ.get("BENCH_MAX_BATCH",
                                    "8" if smoke else "32"))
            value, metric, extra = _bench_serving(qps, nreq, mb)
            method = f"q{qps:g}n{nreq}b{mb}"
        elif model == "generate":
            qps = float(os.environ.get("BENCH_QPS", "4" if smoke else "16"))
            nreq = int(os.environ.get("BENCH_REQUESTS",
                                      "8" if smoke else "64"))
            gen = int(os.environ.get("BENCH_GEN_TOKENS",
                                     "8" if smoke else "64"))
            slots = int(os.environ.get("BENCH_SLOTS", "4" if smoke else "16"))
            preset = os.environ.get("BENCH_GPT",
                                    "tiny" if smoke else "base")
            value, metric, extra = _bench_generate(qps, nreq, gen, slots,
                                                   preset)
            method = f"q{qps:g}n{nreq}g{gen}s{slots}{preset}"
        elif model == "generate_prefix":
            nreq = int(os.environ.get("BENCH_REQUESTS",
                                      "12" if smoke else "32"))
            npfx = int(os.environ.get("BENCH_PREFIX_COUNT", "3"))
            slen = int(os.environ.get("BENCH_PREFIX_SYS", "88"))
            gen = int(os.environ.get("BENCH_GEN_TOKENS", "4"))
            value, metric, extra = _bench_generate_prefix(nreq, npfx, slen,
                                                          gen)
            method = f"n{nreq}p{npfx}s{slen}g{gen}"
        elif model == "generate_spec":
            nreq = int(os.environ.get("BENCH_REQUESTS",
                                      "6" if smoke else "16"))
            gen = int(os.environ.get("BENCH_GEN_TOKENS", "12"))
            k = int(os.environ.get("BENCH_SPEC_K", "4"))
            value, metric, extra = _bench_generate_spec(nreq, gen, k)
            method = f"n{nreq}g{gen}k{k}"
        elif model == "generate_random_shapes":
            nreq = int(os.environ.get("BENCH_REQUESTS",
                                      "16" if smoke else "48"))
            gen = int(os.environ.get("BENCH_GEN_TOKENS", "6"))
            k = int(os.environ.get("BENCH_SPEC_K", "3"))
            value, metric, extra = _bench_generate_random_shapes(nreq, gen,
                                                                 k)
            method = f"n{nreq}g{gen}k{k}"
        elif model == "generate_cold_restart":
            nreq = int(os.environ.get("BENCH_REQUESTS", "6"))
            seed = int(os.environ.get("BENCH_SEED", "3"))
            value, metric, extra = _bench_generate_cold_restart(nreq, seed)
            method = f"n{nreq}s{seed}"
        elif model == "generate_overload":
            nreq = int(os.environ.get("BENCH_REQUESTS",
                                      "24" if smoke else "64"))
            gen = int(os.environ.get("BENCH_GEN_TOKENS",
                                     "12" if smoke else "32"))
            slots = int(os.environ.get("BENCH_SLOTS", "2" if smoke else "8"))
            factor = float(os.environ.get("BENCH_OVERLOAD_FACTOR", "2.5"))
            slow = os.environ.get("BENCH_SLOW_DECODE", "1") != "0"
            value, metric, extra = _bench_generate_overload(
                nreq, gen, slots, factor, slow_decode=slow)
            method = f"n{nreq}g{gen}s{slots}x{factor:g}" + \
                ("" if slow else "raw")
        else:
            value, metric = _bench_resnet50(batch, iters, image, dtype)
            method = f"b{batch}x{image}i{iters}{'' if dtype == 'mixed' else dtype}"
    except Exception as e:  # noqa: BLE001 — the one-JSON-line contract:
        # an individual benchmark failure must still emit the final
        # machine-parsable line (every BENCH round so far recorded
        # `parsed: null` because the crash pre-empted it)
        metric = _MODEL_METRIC.get(model, model)
        line = {"metric": metric, "value": None,
                "unit": _UNITS.get(metric, ""), "vs_baseline": None,
                "backend": backend,
                "error": f"{type(e).__name__}: {e}"[:500]}
        print(json.dumps(line))
        raise SystemExit(2)

    # a cpu-fallback smoke number must never ratchet (or reset the method
    # of) the real device series in BENCH_HISTORY.json
    record = (os.environ.get("BENCH_RECORD", "1") != "0") and not smoke
    hist_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_HISTORY.json")
    hist = {}
    if os.path.exists(hist_path):
        try:
            hist = json.load(open(hist_path))
        except Exception:
            hist = {}
    # RATCHET against the max-watermark, not the previous run — a regression
    # reports <1.0 on EVERY run until fixed instead of resetting its own
    # baseline (round-2 verdict weak #7)
    entry = hist.get(metric)
    if isinstance(entry, dict):
        watermark = entry.get("watermark", 0.0)
        runs = entry.get("runs", [])
        # A watermark is only comparable within one measurement methodology
        # (batch/seq/iters/dtype). When the method changes, the old series
        # would report nonsense ratios (e.g. a window-size change once read
        # as a 60× "speedup"), so start a fresh series — the old one stays
        # in git history.
        if entry.get("method") != method:
            watermark, runs = 0.0, []
    else:  # legacy scalar entry
        watermark = float(entry) if entry else 0.0
        runs = []
    vs_baseline = value / watermark if watermark else 1.0
    nd = 3 if value < 100 else 1  # keep ratio metrics' ratchet sensitive
    if record:
        runs = (runs + [round(value, nd)])[-20:]
        try:
            hist[metric] = {"watermark": round(max(watermark, value), nd),
                            "runs": runs, "method": method}
            json.dump(hist, open(hist_path, "w"), indent=1)
        except Exception:
            pass

    line = {
        "metric": metric,
        "value": round(value, 3 if value < 100 else 1),
        "unit": _UNITS[metric],
        "vs_baseline": round(vs_baseline, 3),
    }
    if backend != "default":
        line["backend"] = backend
    if smoke:
        line["smoke"] = True
    line.update(extra)
    mfu = _mfu(metric, value, image)
    if mfu is not None:
        line["mfu"] = mfu
    # embed the observe/ snapshot (recompiles, step + serving latency
    # percentiles) so the bench trajectory carries latency, not just
    # throughput — docs/OBSERVABILITY.md
    from deeplearning4j_tpu import observe

    obs = observe.summary()
    if obs:
        line["observe"] = obs
    print(json.dumps(line))


if __name__ == "__main__":
    main()
