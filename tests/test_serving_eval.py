"""Round-5 verdict items 4+5: serving-grade ParallelInference (request
queue + dynamic batching window), distributed evaluation with cross-process
Evaluation merge, file-level ETL sharding, and the double-buffered
device-transfer path in ParallelWrapper.fit."""

import os
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.parallel.launch import ShardedDataSetIterator, distributed_evaluate
from deeplearning4j_tpu.parallel.mesh import ParallelInference, ParallelWrapper, make_mesh

from tests._helpers import _mln, _rng


def _small_net(d=12, classes=4):
    return _mln([
        nn.DenseLayer(n_out=32, activation="relu"),
        nn.OutputLayer(n_out=classes, activation="softmax", loss="mcxent"),
    ], nn.InputType.feed_forward(d))


class TestServingParallelInference:
    def test_predict_matches_output(self):
        net = _small_net()
        pi = ParallelInference(net, max_batch=8, window_ms=2.0).start()
        try:
            r = _rng(0)
            x = r.randn(5, 12).astype(np.float32)
            got = pi.predict(x)
            want = pi.output(x)
            np.testing.assert_allclose(got, want, atol=1e-5)
            # single-example request (no batch dim)
            one = pi.predict(x[0])
            np.testing.assert_allclose(one[0], want[0], atol=1e-5)
        finally:
            pi.stop()

    def test_concurrent_clients_get_their_own_rows(self):
        net = _small_net()
        pi = ParallelInference(net, max_batch=16, window_ms=5.0).start()
        try:
            r = _rng(1)
            xs = [r.randn(12).astype(np.float32) for _ in range(24)]
            direct = pi.output(np.stack(xs))
            results = [None] * len(xs)

            def client(i):
                results[i] = pi.predict(xs[i])[0]

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(len(xs))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            for i, res in enumerate(results):
                assert res is not None, f"client {i} got no reply"
                np.testing.assert_allclose(res, direct[i], atol=1e-5)
        finally:
            pi.stop()

    def test_batching_beats_per_request(self):
        """The reference's dynamic-batching claim: many tiny concurrent
        requests through the batching window must beat one forward PER
        request by >=3x (each per-request call pays a full padded forward;
        the queue amortizes it)."""
        # a model big enough that one forward dominates threading overhead
        net = _mln([
            nn.DenseLayer(n_out=2048, activation="relu"),
            nn.DenseLayer(n_out=2048, activation="relu"),
            nn.OutputLayer(n_out=64, activation="softmax", loss="mcxent"),
        ], nn.InputType.feed_forward(512))
        pi = ParallelInference(net, max_batch=32, window_ms=20.0).start()
        try:
            r = _rng(2)
            xs = [r.randn(512).astype(np.float32) for _ in range(64)]
            pi.predict(xs[0])       # warm the compiled shape
            _ = pi.output(xs[0][None])

            t0 = time.perf_counter()
            for x in xs:
                _ = pi.output(x[None])  # per-request: one forward each
            per_request = time.perf_counter() - t0

            t0 = time.perf_counter()
            threads = [threading.Thread(
                target=lambda x=x: pi.predict(x)) for x in xs]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            batched = time.perf_counter() - t0
            assert batched * 3 < per_request, (
                f"batched {batched:.3f}s vs per-request {per_request:.3f}s")
        finally:
            pi.stop()


class TestDistributedEvaluate:
    def test_single_process_passthrough(self):
        net = _small_net()
        r = _rng(3)
        x = r.randn(40, 12).astype(np.float32)
        y = np.eye(4)[r.randint(0, 4, 40)].astype(np.float32)
        it = ListDataSetIterator(DataSet(x, y), batch_size=10)
        ev = distributed_evaluate(net, it)
        it.reset()
        ev2 = net.evaluate(it)
        assert np.array_equal(ev.confusion, ev2.confusion)

    def test_two_process_merge_equals_single(self, tmp_path):
        """2-process jax.distributed run: each rank evaluates its shard of
        the same dataset; the merged Evaluation must equal a single-process
        evaluation over the full data (verdict item 4 'Done' gate)."""
        worker = tmp_path / "worker.py"
        worker.write_text(
            """
import jax
jax.config.update("jax_platforms", "cpu")
import sys, json, numpy as np
sys.path.insert(0, %r)
sys.path.insert(0, %r)
from deeplearning4j_tpu.parallel.launch import (
    initialize_distributed, ShardedDataSetIterator, distributed_evaluate)
initialize_distributed()
from deeplearning4j_tpu import nn
from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from tests._helpers import _mln, _rng
net = _mln([
    nn.DenseLayer(n_out=32, activation="relu"),
    nn.OutputLayer(n_out=4, activation="softmax", loss="mcxent"),
], nn.InputType.feed_forward(12))
r = _rng(3)
x = r.randn(40, 12).astype(np.float32)
y = np.eye(4)[r.randint(0, 4, 40)].astype(np.float32)
base = ListDataSetIterator(DataSet(x, y), batch_size=10)
ev = distributed_evaluate(net, ShardedDataSetIterator(base))
if jax.process_index() == 0:
    np.save(%r, ev.confusion)
""" % ("/root/repo", "/root/repo", str(tmp_path / "conf.npy")))
        from deeplearning4j_tpu.parallel.launch import launch
        rc = launch(2, [str(worker)], timeout=240.0)
        assert rc == 0
        merged = np.load(tmp_path / "conf.npy")

        net = _small_net()
        r = _rng(3)
        x = r.randn(40, 12).astype(np.float32)
        y = np.eye(4)[r.randint(0, 4, 40)].astype(np.float32)
        single = net.evaluate(ListDataSetIterator(DataSet(x, y),
                                                  batch_size=10))
        assert np.array_equal(merged, single.confusion)


class TestFileShardedETL:
    def _image_tree(self, tmp, n_per_class=6):
        from PIL import Image
        for lab in ("cat", "dog"):
            d = os.path.join(tmp, lab)
            os.makedirs(d, exist_ok=True)
            for i in range(n_per_class):
                arr = (np.random.RandomState(hash(lab) % 1000 + i)
                       .rand(8, 8, 3) * 255).astype(np.uint8)
                Image.fromarray(arr).save(os.path.join(d, f"{i}.png"))

    def test_shard_files_partitions_work(self, tmp_path):
        self._image_tree(str(tmp_path))
        from deeplearning4j_tpu.datasets.image import ImageRecordReader
        r0 = ImageRecordReader(str(tmp_path), 8, 8, batch_size=4)
        total = len(r0.files)
        r0.shard_files(0, 2)
        r1 = ImageRecordReader(str(tmp_path), 8, 8, batch_size=4)
        r1.shard_files(1, 2)
        assert len(r0.files) + len(r1.files) == total
        assert not (set(f for f, _ in r0.files)
                    & set(f for f, _ in r1.files))

    def test_sharded_iterator_uses_file_sharding(self, tmp_path):
        self._image_tree(str(tmp_path))
        from deeplearning4j_tpu.datasets.image import ImageRecordReader
        reader = ImageRecordReader(str(tmp_path), 8, 8, batch_size=4)
        total = len(reader.files)
        it = ShardedDataSetIterator(reader, process_id=1, num_processes=3)
        assert it._file_sharded
        assert len(reader.files) == len(list(range(total))[1::3])
        seen = sum(ds.num_examples() for ds in it)
        assert seen == len(reader.files)

    def test_round_robin_fallback_warns(self):
        r = _rng(4)
        x = r.randn(12, 4).astype(np.float32)
        y = np.eye(2)[r.randint(0, 2, 12)].astype(np.float32)
        base = ListDataSetIterator(DataSet(x, y), batch_size=4)
        with pytest.warns(UserWarning, match="full ETL"):
            it = ShardedDataSetIterator(base, process_id=0, num_processes=2)
        assert not it._file_sharded
        assert len(list(it)) == 2  # batches 0 and 2 of 3


class TestDoubleBufferedFit:
    def test_fit_correctness_unchanged(self):
        # the lookahead placement must not change results vs plain fit
        net_a = _small_net()
        net_b = _small_net()
        net_b.params = jax.tree.map(jnp.array, net_a.params)
        net_b.opt_state = jax.tree.map(jnp.array, net_a.opt_state)
        r = _rng(5)
        x = r.randn(32, 12).astype(np.float32)
        y = np.eye(4)[r.randint(0, 4, 32)].astype(np.float32)
        it = ListDataSetIterator(DataSet(x, y), batch_size=8)
        pw = ParallelWrapper(net_b, mesh=make_mesh({"data": 2}, devices=jax.devices()[:2]))
        pw.fit(it, epochs=2)
        it.reset()
        for _ in range(2):
            for ds in it:
                net_a.fit(ds.features, ds.labels)
            it.reset()
        da = jax.tree.map(lambda p, q: float(jnp.max(jnp.abs(p - q))),
                          net_a.params, net_b.params)
        assert jax.tree.reduce(max, da) < 2e-4
