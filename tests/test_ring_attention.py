"""Ring attention tests on the 8-device CPU mesh — distributed blockwise
attention vs the single-device oracle (the first-class long-context path)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.parallel import make_mesh
from deeplearning4j_tpu.parallel.ring_attention import (
    ring_attention, RingSelfAttention,
)
from deeplearning4j_tpu.ops.pallas_attention import _reference_attention


def rand_qkv(bh=2, t=64, d=16, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(bh, t, d).astype(np.float32)),
            jnp.asarray(rng.randn(bh, t, d).astype(np.float32)),
            jnp.asarray(rng.randn(bh, t, d).astype(np.float32)))


class TestRingAttention:
    def test_matches_full_attention(self):
        mesh = make_mesh({"seq": 8})
        q, k, v = rand_qkv(t=64)
        out = ring_attention(q, k, v, mesh=mesh, axis="seq")
        ref = _reference_attention(q, k, v, scale=1.0 / np.sqrt(16), causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_causal_matches(self):
        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = rand_qkv(t=32, seed=1)
        out = ring_attention(q, k, v, mesh=mesh, axis="seq", causal=True)
        ref = _reference_attention(q, k, v, scale=1.0 / np.sqrt(16), causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_gradients_match(self):
        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = rand_qkv(t=16, seed=2)

        def ring_loss(q, k, v):
            return jnp.sum(ring_attention(q, k, v, mesh=mesh, axis="seq") ** 2)

        def ref_loss(q, k, v):
            return jnp.sum(_reference_attention(
                q, k, v, scale=1.0 / np.sqrt(16), causal=False) ** 2)

        g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)

    def test_sharded_inputs_stay_sharded(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = make_mesh({"seq": 8})
        q, k, v = rand_qkv(t=64)
        sh = NamedSharding(mesh, P(None, "seq", None))
        qs = jax.device_put(q, sh)
        out = ring_attention(qs, jax.device_put(k, sh), jax.device_put(v, sh),
                             mesh=mesh, axis="seq")
        assert out.shape == q.shape

    def test_self_attention_wrapper(self):
        mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(2, 32, 16).astype(np.float32))
        w = lambda: jnp.asarray(rng.randn(16, 16).astype(np.float32) * 0.1)
        attn = RingSelfAttention(mesh, num_heads=4)
        out = attn(x, w(), w(), w(), w())
        assert out.shape == (2, 32, 16)
