"""TF import golden-file tests — the reference's TFGraphTestAllSameDiff
pattern (SURVEY §5.4): build a TF graph in-env, freeze it, import to
SameDiff, and compare outputs elementwise to TF's own."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.imports import TensorflowImporter, import_frozen_graph


def freeze(fn, *specs, lower_control_flow=True):
    """Concrete function → frozen GraphDef (variables inlined as Consts).

    lower_control_flow=True (TF's default) lowers functional While/If into
    TF1 frames (Enter/Exit/Merge/Switch); False keeps the functional nodes
    + library — both forms appear in real frozen graphs and both import."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    cf = tf.function(fn).get_concrete_function(*specs)
    frozen = convert_variables_to_constants_v2(
        cf, lower_control_flow=lower_control_flow)
    return frozen.graph.as_graph_def(), [t.name.split(":")[0] for t in frozen.inputs], \
        [t.name.split(":")[0] for t in frozen.outputs]


class TestTfImport:
    def test_mlp_golden(self):
        rng = np.random.RandomState(0)
        w0 = tf.Variable(rng.randn(4, 8).astype(np.float32))
        b0 = tf.Variable(np.zeros(8, np.float32))
        w1 = tf.Variable(rng.randn(8, 3).astype(np.float32))

        def model(x):
            h = tf.nn.relu(tf.matmul(x, w0) + b0)
            return tf.nn.softmax(tf.matmul(h, w1))

        gd, ins, outs = freeze(model, tf.TensorSpec([None, 4], tf.float32))
        x = rng.randn(5, 4).astype(np.float32)
        golden = model(tf.constant(x)).numpy()

        sd = TensorflowImporter().run_import(gd)
        got = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)

    def test_elementwise_chain_golden(self):
        def model(x):
            y = tf.sqrt(tf.abs(x) + 1.0) * tf.tanh(x) - tf.sigmoid(x)
            return tf.reduce_mean(y, axis=1)

        gd, ins, outs = freeze(model, tf.TensorSpec([3, 6], tf.float32))
        x = np.random.RandomState(1).randn(3, 6).astype(np.float32)
        golden = model(tf.constant(x)).numpy()
        sd = import_frozen_graph(gd.SerializeToString())
        got = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)

    def test_reshape_transpose_golden(self):
        def model(x):
            y = tf.transpose(tf.reshape(x, [2, 3, 4]), perm=[0, 2, 1])
            return tf.reduce_sum(y, axis=[1], keepdims=True)

        gd, ins, outs = freeze(model, tf.TensorSpec([2, 12], tf.float32))
        x = np.arange(24, dtype=np.float32).reshape(2, 12)
        golden = model(tf.constant(x)).numpy()
        got = import_frozen_graph(gd)._exec_fn  # importer returns SameDiff
        sd = import_frozen_graph(gd)
        out = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(out, golden, rtol=1e-6)

    def test_conv_pool_golden(self):
        rng = np.random.RandomState(2)
        k = tf.Variable(rng.randn(3, 3, 2, 4).astype(np.float32) * 0.1)

        def model(x):
            y = tf.nn.conv2d(x, k, strides=[1, 1, 1, 1], padding="SAME")
            y = tf.nn.relu(y)
            return tf.nn.max_pool2d(y, ksize=2, strides=2, padding="VALID")

        gd, ins, outs = freeze(model, tf.TensorSpec([1, 8, 8, 2], tf.float32))
        x = rng.randn(1, 8, 8, 2).astype(np.float32)
        golden = model(tf.constant(x)).numpy()
        sd = import_frozen_graph(gd)
        got = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-4, atol=1e-5)

    def test_imported_variables_are_trainable(self):
        w = tf.Variable(np.ones((2, 2), np.float32))

        def model(x):
            return tf.matmul(x, w)

        gd, ins, outs = freeze(model, tf.TensorSpec([1, 2], tf.float32))
        sd = TensorflowImporter().run_import(gd)
        trainables = [n for n, v in sd._vars.items() if v.vtype == "VARIABLE"]
        assert len(trainables) == 1
        sd.get_variable(outs[0]).sum().rename("loss")  # scalarize for grad
        g = sd.calculate_gradients({ins[0]: np.ones((1, 2), np.float32)},
                                   "loss", wrt=trainables)
        assert list(g.values())[0].shape == (2, 2)
        np.testing.assert_allclose(list(g.values())[0], np.ones((2, 2)))

    def test_unsupported_op_raises_clearly(self):
        # Betainc gained a mapper in round 5 — use a genuinely unmapped op
        def model(x):
            return tf.raw_ops.Angle(input=tf.complex(x, x))

        gd, ins, outs = freeze(model, tf.TensorSpec([2], tf.float32))
        with pytest.raises(NotImplementedError, match="Angle|Complex"):
            TensorflowImporter().run_import(gd)

    def test_gelu_composite_golden(self):
        """The BERT-critical GELU-from-erf composite imports op-by-op."""

        def model(x):
            return 0.5 * x * (1.0 + tf.math.erf(x / tf.sqrt(2.0)))

        gd, ins, outs = freeze(model, tf.TensorSpec([4], tf.float32))
        x = np.linspace(-2, 2, 4).astype(np.float32)
        golden = model(tf.constant(x)).numpy()
        sd = import_frozen_graph(gd)
        got = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)


class TestTfImportWidened:
    """Round-3 widened dialect: conv/bn/pad/slice ops via the shared IR layer."""

    def test_cnn_bn_golden(self):
        rng = np.random.RandomState(7)
        w = tf.Variable((rng.randn(3, 3, 3, 8) * 0.3).astype(np.float32))
        dw = tf.Variable((rng.randn(3, 3, 8, 1) * 0.3).astype(np.float32))
        gamma = tf.Variable((np.abs(rng.randn(8)) + 0.5).astype(np.float32))
        beta = tf.Variable(rng.randn(8).astype(np.float32))
        mean = tf.Variable(rng.randn(8).astype(np.float32))
        var = tf.Variable((np.abs(rng.randn(8)) + 0.5).astype(np.float32))

        def model(x):
            y = tf.nn.conv2d(x, w, strides=1, padding="SAME")
            y, _, _ = tf.compat.v1.nn.fused_batch_norm(
                y, gamma, beta, mean=mean, variance=var, is_training=False)
            y = tf.nn.leaky_relu(y, alpha=0.1)
            y = tf.nn.depthwise_conv2d(y, dw, strides=[1, 1, 1, 1],
                                       padding="VALID")
            y = tf.pad(y, [[0, 0], [1, 1], [1, 1], [0, 0]])
            return tf.reduce_mean(y, axis=[1, 2])

        gd, ins, outs = freeze(model, tf.TensorSpec([2, 8, 8, 3], tf.float32))
        x = rng.randn(2, 8, 8, 3).astype(np.float32)
        golden = model(tf.constant(x)).numpy()
        sd = TensorflowImporter().run_import(gd)
        got = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-4, atol=1e-5)

    def test_strided_slice_clip_cumsum_golden(self):
        def model(x):
            y = tf.strided_slice(x, [0, 1], [3, 7], [1, 2])
            y = tf.clip_by_value(y, -0.5, 0.5)
            return tf.cumsum(y, axis=1)

        gd, ins, outs = freeze(model, tf.TensorSpec([3, 8], tf.float32))
        x = np.random.RandomState(8).randn(3, 8).astype(np.float32)
        golden = model(tf.constant(x)).numpy()
        sd = import_frozen_graph(gd.SerializeToString())
        got = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)


class TestTfControlFlow:
    """TF2 function-graph control flow → lax.while_loop/cond
    (TFGraphMapper + AbstractSession frames, SURVEY §4.3)."""

    def test_while_loop_golden(self):
        def model(x):
            i = tf.constant(0)

            def cond(i, x):
                return i < 5

            def body(i, x):
                return i + 1, x * 1.5 + 1.0

            _, out = tf.while_loop(cond, body, [i, x])
            return out

        gd, ins, outs = freeze(model, tf.TensorSpec([4], tf.float32),
                               lower_control_flow=False)
        assert any(n.op in ("While", "StatelessWhile") for n in gd.node)
        x = np.array([1.0, -2.0, 0.5, 3.0], np.float32)
        golden = model(tf.constant(x)).numpy()
        sd = TensorflowImporter().run_import(gd)
        got = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)

    def test_while_loop_data_dependent_trip_count(self):
        def model(x):
            def cond(x):
                return tf.reduce_sum(x) < 100.0

            def body(x):
                return (x * 2.0,)

            return tf.while_loop(cond, body, [x])[0]

        gd, ins, outs = freeze(model, tf.TensorSpec([3], tf.float32),
                               lower_control_flow=False)
        sd = TensorflowImporter().run_import(gd)
        for scale in (1.0, 7.0):  # different trip counts, same import
            x = scale * np.array([1.0, 2.0, 3.0], np.float32)
            golden = model(tf.constant(x)).numpy()
            got = sd.output({ins[0]: x}, outs[0])[outs[0]]
            np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)

    def test_cond_golden_both_branches(self):
        def model(x):
            return tf.cond(tf.reduce_sum(x) > 0.0,
                           lambda: x * 2.0 + 1.0,
                           lambda: -x)

        gd, ins, outs = freeze(model, tf.TensorSpec([4], tf.float32),
                               lower_control_flow=False)
        assert any(n.op in ("If", "StatelessIf") for n in gd.node)
        sd = TensorflowImporter().run_import(gd)
        for sign in (1.0, -1.0):  # exercise BOTH branches
            x = sign * np.arange(1.0, 5.0, dtype=np.float32)
            golden = model(tf.constant(x)).numpy()
            got = sd.output({ins[0]: x}, outs[0])[outs[0]]
            np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)

    def test_nested_while_in_cond(self):
        def model(x):
            def loop():
                return tf.while_loop(lambda i, v: i < 3,
                                     lambda i, v: (i + 1, v + v),
                                     [tf.constant(0), x])[1]

            return tf.cond(tf.reduce_sum(x) > 0.0, loop, lambda: x)

        gd, ins, outs = freeze(model, tf.TensorSpec([2], tf.float32),
                               lower_control_flow=False)
        sd = TensorflowImporter().run_import(gd)
        for sign in (1.0, -1.0):
            x = sign * np.array([1.0, 2.0], np.float32)
            golden = model(tf.constant(x)).numpy()
            got = sd.output({ins[0]: x}, outs[0])[outs[0]]
            np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)

    def test_while_multi_output_slots(self):
        """Both loop vars of a While consumed downstream (slot addressing)."""
        def model(x):
            i, y = tf.while_loop(lambda i, v: i < 4,
                                 lambda i, v: (i + 1, v * 1.1),
                                 [tf.constant(0), x])
            return tf.cast(i, tf.float32) + tf.reduce_sum(y)

        gd, ins, outs = freeze(model, tf.TensorSpec([3], tf.float32),
                               lower_control_flow=False)
        sd = TensorflowImporter().run_import(gd)
        x = np.array([1.0, 2.0, 3.0], np.float32)
        golden = model(tf.constant(x)).numpy()
        got = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)


class TestTf1FrameControlFlow:
    """Default freezing (lower_control_flow=True) lowers While/If into TF1
    frames — Enter/Merge/Switch/Exit/NextIteration/LoopCond — the form every
    legacy frozen .pb carries. The importer collapses each frame back onto
    lax.while_loop, and frameless Switch/Merge conds onto pred-selects
    (AbstractSession frame interpretation, SURVEY §4.3)."""

    def test_lowered_while_golden(self):
        def model(x):
            def cond(i, x):
                return i < 5

            def body(i, x):
                return i + 1, x * 1.5 + 1.0

            _, out = tf.while_loop(cond, body, [tf.constant(0), x])
            return out

        gd, ins, outs = freeze(model, tf.TensorSpec([4], tf.float32))
        assert any(n.op == "Enter" for n in gd.node)  # really lowered
        x = np.array([1.0, -2.0, 0.5, 3.0], np.float32)
        golden = model(tf.constant(x)).numpy()
        sd = TensorflowImporter().run_import(gd)
        got = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)

    def test_lowered_while_data_dependent(self):
        def model(x):
            def cond(x):
                return tf.reduce_sum(x) < 100.0

            def body(x):
                return (x * 2.0,)

            return tf.while_loop(cond, body, [x])[0]

        gd, ins, outs = freeze(model, tf.TensorSpec([3], tf.float32))
        sd = TensorflowImporter().run_import(gd)
        for scale in (1.0, 7.0):
            x = scale * np.array([1.0, 2.0, 3.0], np.float32)
            golden = model(tf.constant(x)).numpy()
            got = sd.output({ins[0]: x}, outs[0])[outs[0]]
            np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)

    def test_lowered_cond_golden_both_branches(self):
        def model(x):
            return tf.cond(tf.reduce_sum(x) > 0.0,
                           lambda: x * 2.0 + 1.0,
                           lambda: -x)

        gd, ins, outs = freeze(model, tf.TensorSpec([4], tf.float32))
        assert any(n.op == "Switch" for n in gd.node)
        assert not any(n.op in ("If", "StatelessIf") for n in gd.node)
        sd = TensorflowImporter().run_import(gd)
        for sign in (1.0, -1.0):
            x = sign * np.arange(1.0, 5.0, dtype=np.float32)
            golden = model(tf.constant(x)).numpy()
            got = sd.output({ins[0]: x}, outs[0])[outs[0]]
            np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)

    def test_lowered_cond_multi_capture(self):
        def model(x, y):
            return tf.cond(tf.reduce_mean(x) > tf.reduce_mean(y),
                           lambda: x - y,
                           lambda: x * y + 3.0)

        gd, ins, outs = freeze(model, tf.TensorSpec([3], tf.float32),
                               tf.TensorSpec([3], tf.float32))
        sd = TensorflowImporter().run_import(gd)
        r = np.random.RandomState(0)
        for _ in range(3):
            x = r.randn(3).astype(np.float32)
            y = r.randn(3).astype(np.float32)
            golden = model(tf.constant(x), tf.constant(y)).numpy()
            got = sd.output({ins[0]: x, ins[1]: y}, outs[0])[outs[0]]
            np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)

    def test_lowered_while_matmul_body(self):
        """Loop body with a matmul on a carried state (power iteration)."""
        def model(x):
            m = tf.constant(np.array([[0.9, 0.1], [0.2, 0.7]], np.float32))

            def cond(i, v):
                return i < 4

            def body(i, v):
                return i + 1, tf.linalg.matvec(m, v)

            return tf.while_loop(cond, body, [tf.constant(0), x])[1]

        gd, ins, outs = freeze(model, tf.TensorSpec([2], tf.float32))
        sd = TensorflowImporter().run_import(gd)
        x = np.array([1.0, 2.0], np.float32)
        golden = model(tf.constant(x)).numpy()
        got = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)

    def test_lowered_nested_cond(self):
        """A cond nested inside a branch: the outer Merge must select on the
        OUTER predicate (slot-crossing analysis), not the nearest Switch."""
        def model(x):
            return tf.cond(
                tf.reduce_sum(x) > 0.0,
                lambda: tf.cond(tf.reduce_max(x) > 5.0,
                                lambda: x + 100.0,
                                lambda: x + 1.0),
                lambda: -x)

        gd, ins, outs = freeze(model, tf.TensorSpec([2], tf.float32))
        sd = TensorflowImporter().run_import(gd)
        for x in (np.array([1.0, 2.0], np.float32),      # outer T, inner F
                  np.array([1.0, 9.0], np.float32),      # outer T, inner T
                  np.array([-1.0, -2.0], np.float32)):   # outer F
            golden = model(tf.constant(x)).numpy()
            got = sd.output({ins[0]: x}, outs[0])[outs[0]]
            np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6,
                                       err_msg=str(x))

    def test_single_var_while_keeps_shape(self):
        """One-loop-variable While: result must keep the carried shape, not
        grow lax.while_loop's 1-tuple into a leading dimension."""
        def model(x):
            return tf.while_loop(lambda v: tf.reduce_sum(v) < 10.0,
                                 lambda v: (v * 2.0,), [x])[0]

        for lcf in (True, False):
            gd, ins, outs = freeze(model, tf.TensorSpec([3], tf.float32),
                                   lower_control_flow=lcf)
            sd = TensorflowImporter().run_import(gd)
            x = np.array([1.0, 0.5, 0.25], np.float32)
            golden = model(tf.constant(x)).numpy()
            got = sd.output({ins[0]: x}, outs[0])[outs[0]]
            assert got.shape == golden.shape == (3,), (lcf, got.shape)
            np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)


class TestSavedModelImport:
    """SavedModel dir → SameDiff with checkpoint variables restored as
    VARIABLE-role SDVariables (TFGraphMapper restore, SURVEY §4.3 step 1)."""

    def _save_model(self, tmp_path):
        rng = np.random.RandomState(7)

        class M(tf.Module):
            def __init__(self):
                super().__init__()
                self.w = tf.Variable(rng.randn(6, 3).astype(np.float32),
                                     name="w")
                self.b = tf.Variable(rng.randn(3).astype(np.float32),
                                     name="b")

            @tf.function(input_signature=[tf.TensorSpec([None, 6], tf.float32)])
            def __call__(self, x):
                return tf.nn.softmax(tf.tanh(x @ self.w) + self.b)

        m = M()
        path = str(tmp_path / "sm")
        tf.saved_model.save(m, path)
        return m, path

    def test_saved_model_golden(self, tmp_path):
        from deeplearning4j_tpu.imports.tf_import import import_saved_model

        m, path = self._save_model(tmp_path)
        sd = import_saved_model(path)
        x = np.random.RandomState(0).randn(5, 6).astype(np.float32)
        golden = m(tf.constant(x)).numpy()
        got = sd.output({sd.graph_inputs[0]: x},
                        sd.graph_outputs[0])[sd.graph_outputs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)

    def test_variables_restored_as_trainable(self, tmp_path):
        from deeplearning4j_tpu.imports.tf_import import import_saved_model
        from deeplearning4j_tpu.autodiff.samediff import TrainingConfig
        from deeplearning4j_tpu import nn
        from deeplearning4j_tpu.datasets.dataset import (
            DataSet, ListDataSetIterator)

        m, path = self._save_model(tmp_path)
        sd = import_saved_model(path)
        var_names = [n for n, v in sd._vars.items() if v.vtype == "VARIABLE"]
        assert len(var_names) == 2, var_names
        # the restored values ARE the trained weights
        restored = sorted((np.asarray(sd.get_arr(n)).shape, n)
                          for n in var_names)
        assert restored[0][0] == (3,) and restored[1][0] == (6, 3)
        w_name = restored[1][1]
        np.testing.assert_allclose(sd.get_arr(w_name), m.w.numpy(),
                                   rtol=1e-6)

        # fine-tune: one step moves weights FROM the restored point
        rng = np.random.RandomState(1)
        x = rng.randn(32, 6).astype(np.float32)
        y = np.eye(3)[rng.randint(0, 3, 32)].astype(np.float32)
        labels = sd.placeholder("labels", shape=(None, 3))
        out_var = sd._vars[sd.graph_outputs[0]]
        sd.loss.mean_squared_error(out_var, labels).rename("ft_loss")
        sd.set_training_config(TrainingConfig(
            updater=nn.Sgd(learning_rate=0.5),
            data_set_feature_mapping=[sd.graph_inputs[0]],
            data_set_label_mapping=["labels"],
            loss_variables=["ft_loss"]))
        before = np.asarray(sd.get_arr(w_name)).copy()
        hist = sd.fit(ListDataSetIterator(DataSet(x, y), batch_size=32),
                      epochs=3)
        after = np.asarray(sd.get_arr(w_name))
        assert not np.allclose(before, after)  # training moved the weights
        assert np.isfinite(hist[-1])

    def test_keras_saved_model_with_optimizer_slots(self, tmp_path):
        """A trained Keras SavedModel: object paths differ from variable
        names, optimizer slot variables (Adam m/v) duplicate every weight's
        shape, and two same-shaped Dense layers break shape-uniqueness —
        the object-graph full_name table must resolve all of it."""
        from deeplearning4j_tpu.imports.tf_import import import_saved_model

        rng = np.random.RandomState(3)
        model = tf.keras.Sequential([
            tf.keras.layers.Input((8,)),
            tf.keras.layers.Dense(8, activation="tanh", name="d1"),
            tf.keras.layers.Dense(8, activation="tanh", name="d2"),  # same shape as d1
            tf.keras.layers.Dense(2, name="out"),
        ])
        model.compile(optimizer="adam", loss="mse")
        x = rng.randn(64, 8).astype(np.float32)
        y = rng.randn(64, 2).astype(np.float32)
        model.fit(x, y, epochs=1, verbose=0)  # creates Adam m/v slots
        path = str(tmp_path / "keras_sm")
        tf.saved_model.save(model, path)

        sd = import_saved_model(path)
        golden = model(tf.constant(x[:5])).numpy()
        got = sd.output({sd.graph_inputs[0]: x[:5]},
                        sd.graph_outputs[0])[sd.graph_outputs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-4, atol=1e-5)
        n_vars = sum(1 for v in sd._vars.values() if v.vtype == "VARIABLE")
        assert n_vars == 6, n_vars  # 3 kernels + 3 biases, NO optimizer slots

    def test_multi_output_signature_slots(self, tmp_path):
        """Signature outputs on slots >0 must fetch their own values, not
        silently collapse to slot 0."""
        from deeplearning4j_tpu.imports.tf_import import import_saved_model

        class M(tf.Module):
            @tf.function(input_signature=[tf.TensorSpec([4], tf.float32)])
            def __call__(self, x):
                return {"double": x * 2.0, "neg": -x}

        m = M()
        path = str(tmp_path / "multi_sm")
        tf.saved_model.save(m, path)
        sd = import_saved_model(path)
        assert len(set(sd.graph_outputs)) == 2, sd.graph_outputs
        x = np.array([1.0, -2.0, 3.0, -4.0], np.float32)
        res = sd.output({sd.graph_inputs[0]: x}, sd.graph_outputs)
        vals = sorted(np.asarray(v).tolist() for v in res.values())
        want = sorted([(x * 2.0).tolist(), (-x).tolist()])
        assert vals == want, (vals, want)


class TestRound4OpBreadth:
    """Round-4 widened op set, golden vs in-env TF."""

    def _golden(self, model, specs, feeds, rtol=1e-5, atol=1e-6):
        gd, ins, outs = freeze(model, *specs)
        golden = model(*[tf.constant(f) for f in feeds]).numpy()
        sd = TensorflowImporter().run_import(gd)
        got = sd.output(dict(zip(ins, feeds)), outs[0])[outs[0]]
        np.testing.assert_allclose(got, golden, rtol=rtol, atol=atol)

    def test_einsum(self):
        def model(a, b):
            return tf.einsum("bij,bjk->bik", a, b)

        r = np.random.RandomState(0)
        self._golden(model,
                     [tf.TensorSpec([2, 3, 4], tf.float32),
                      tf.TensorSpec([2, 4, 5], tf.float32)],
                     [r.randn(2, 3, 4).astype(np.float32),
                      r.randn(2, 4, 5).astype(np.float32)], rtol=1e-4)

    def test_gather_nd_addn_cumprod(self):
        def model(x):
            idx = tf.constant([[0, 1], [1, 0]])
            g = tf.gather_nd(x, idx)           # (2,)
            s = tf.add_n([x, x * 2.0, x + 1.0])
            c = tf.math.cumprod(x, axis=1)
            return tf.reduce_sum(s) + tf.reduce_sum(c) + tf.reduce_sum(g)

        x = np.random.RandomState(1).rand(2, 3).astype(np.float32) + 0.5
        self._golden(model, [tf.TensorSpec([2, 3], tf.float32)], [x],
                     rtol=1e-4)

    def test_mirror_pad_and_logicals(self):
        def model(x):
            p = tf.pad(x, [[1, 1], [2, 2]], mode="REFLECT")
            m = tf.logical_and(x > 0.3, tf.logical_not(x > 0.7))
            return p * 1.0 + tf.reduce_sum(tf.cast(m, tf.float32))

        x = np.random.RandomState(2).rand(3, 4).astype(np.float32)
        self._golden(model, [tf.TensorSpec([3, 4], tf.float32)], [x])

    def test_xdivy_and_select(self):
        def model(x, y):
            return tf.math.xdivy(x, y) + tf.where(x > 0.5, x, -y)

        r = np.random.RandomState(3)
        x = r.rand(3, 4).astype(np.float32)
        x[0, 0] = 0.0  # xdivy special case
        y = np.zeros((3, 4), np.float32)
        y[0, 0] = 0.0  # 0/0 must be 0, not nan
        y += r.rand(3, 4).astype(np.float32) * (x != 0)
        y[y == 0] = 1.0
        y[0, 0] = 0.0
        self._golden(model, [tf.TensorSpec([3, 4], tf.float32),
                             tf.TensorSpec([3, 4], tf.float32)], [x, y])

    def test_reduce_all_any(self):
        def model(x):
            a = tf.reduce_all(x > 0.2, axis=1)
            b = tf.reduce_any(x > 0.8, axis=0)
            return tf.cast(a, tf.float32)[None, :] + \
                tf.cast(b, tf.float32)[:, None] * 0.5

        x = np.random.RandomState(4).rand(3, 3).astype(np.float32)
        self._golden(model, [tf.TensorSpec([3, 3], tf.float32)], [x])

    def test_conv2d_transpose(self):
        w = np.random.RandomState(5).randn(3, 3, 5, 2).astype(np.float32)

        def model(x):
            return tf.nn.conv2d_transpose(
                x, tf.constant(w), output_shape=[2, 8, 8, 5],
                strides=[1, 2, 2, 1], padding="SAME")

        x = np.random.RandomState(6).randn(2, 4, 4, 2).astype(np.float32)
        self._golden(model, [tf.TensorSpec([2, 4, 4, 2], tf.float32)], [x],
                     rtol=1e-4, atol=1e-4)

    def test_inverse_hyperbolics(self):
        def model(x):
            return tf.asinh(x) + tf.math.expm1(x) + tf.math.erfc(x) + \
                tf.acosh(x + 2.0) + tf.atanh(x * 0.5)

        x = np.random.RandomState(7).rand(8).astype(np.float32)
        self._golden(model, [tf.TensorSpec([8], tf.float32)], [x],
                     rtol=1e-4, atol=1e-5)

    def test_newaxis_and_ellipsis_slicing(self):
        def model(x):
            a = x[None]               # new_axis at front
            b = x[..., None]          # ellipsis + trailing new_axis
            c = x[:, None, 1:, 0]     # mixed: new_axis + slice + shrink
            return tf.reduce_sum(a) + tf.reduce_sum(b * 2.0) + \
                tf.reduce_sum(c * 3.0)

        x = np.random.RandomState(8).rand(3, 4, 5).astype(np.float32)
        self._golden(model, [tf.TensorSpec([3, 4, 5], tf.float32)], [x],
                     rtol=1e-4)


class TestBertSavedModelFinetune:
    """BASELINE config[3] gate: a transformer (embeddings + self-attention
    via Einsum + LayerNorm + GELU FFN + residuals, built and trained-shape
    in TF) imports from a SavedModel with its weights restored, matches TF
    elementwise, compiles whole-graph (StableHLO exportable), and a
    SameDiff fine-tune CONVERGES from the restored point."""

    D, HEADS, FF, T, VOCAB = 32, 4, 64, 12, 50

    def _build_tf_model(self):
        d, heads, ff, T, vocab = (self.D, self.HEADS, self.FF, self.T,
                                  self.VOCAB)

        class MiniBert(tf.Module):
            def __init__(self):
                super().__init__()
                r = np.random.RandomState(0)

                def g(name, *s):
                    return tf.Variable(
                        r.randn(*s).astype(np.float32) * 0.08, name=name)

                self.emb = g("emb", vocab, d)
                self.pos = g("pos", T, d)
                self.wq, self.wk = g("wq", d, d), g("wk", d, d)
                self.wv, self.wo = g("wv", d, d), g("wo", d, d)
                self.ln1_g = tf.Variable(np.ones(d, np.float32), name="ln1_g")
                self.ln1_b = tf.Variable(np.zeros(d, np.float32), name="ln1_b")
                self.w1, self.b1 = g("w1", d, ff), tf.Variable(
                    np.zeros(ff, np.float32), name="b1")
                self.w2, self.b2 = g("w2", ff, d), tf.Variable(
                    np.zeros(d, np.float32), name="b2")
                self.ln2_g = tf.Variable(np.ones(d, np.float32), name="ln2_g")
                self.ln2_b = tf.Variable(np.zeros(d, np.float32), name="ln2_b")
                self.cls_w = g("cls_w", d, 2)
                self.cls_b = tf.Variable(np.zeros(2, np.float32), name="cls_b")

            def ln(self, x, gv, bv):
                m = tf.reduce_mean(x, axis=-1, keepdims=True)
                v = tf.reduce_mean(tf.square(x - m), axis=-1, keepdims=True)
                return (x - m) * tf.math.rsqrt(v + 1e-6) * gv + bv

            @tf.function(input_signature=[
                tf.TensorSpec([None, T], tf.int32)])
            def __call__(self, ids):
                x = tf.gather(self.emb, ids) + self.pos
                hd = d // heads

                def split(t):
                    s = tf.shape(t)
                    return tf.transpose(
                        tf.reshape(t, [s[0], T, heads, hd]), [0, 2, 1, 3])

                q, k, v = split(x @ self.wq), split(x @ self.wk), \
                    split(x @ self.wv)
                scores = tf.einsum("bhqd,bhkd->bhqk", q, k) / \
                    np.sqrt(hd).astype(np.float32)
                att = tf.einsum("bhqk,bhkd->bhqd",
                                tf.nn.softmax(scores, axis=-1), v)
                att = tf.reshape(tf.transpose(att, [0, 2, 1, 3]),
                                 [tf.shape(x)[0], T, d])
                x = self.ln(x + att @ self.wo, self.ln1_g, self.ln1_b)
                h = tf.nn.gelu(x @ self.w1 + self.b1)
                x = self.ln(x + h @ self.w2 + self.b2, self.ln2_g, self.ln2_b)
                return tf.nn.softmax(x[:, 0] @ self.cls_w + self.cls_b)

        return MiniBert()

    def test_import_matches_and_finetune_converges(self, tmp_path):
        from deeplearning4j_tpu import nn
        from deeplearning4j_tpu.autodiff.samediff import TrainingConfig
        from deeplearning4j_tpu.datasets.dataset import (
            DataSet, ListDataSetIterator)
        from deeplearning4j_tpu.imports.tf_import import import_saved_model

        m = self._build_tf_model()
        path = str(tmp_path / "minibert")
        tf.saved_model.save(m, path)
        sd = import_saved_model(path)

        rng = np.random.RandomState(1)
        ids = rng.randint(0, self.VOCAB, (4, self.T)).astype(np.int32)
        golden = m(tf.constant(ids)).numpy()
        got = sd.output({sd.graph_inputs[0]: ids},
                        sd.graph_outputs[0])[sd.graph_outputs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-3, atol=1e-5)

        # whole-graph compile artifact (StableHLO text) exists
        hlo = sd.as_stablehlo({sd.graph_inputs[0]: ids},
                              [sd.graph_outputs[0]])
        assert "stablehlo" in hlo or "func.func" in hlo

        # fine-tune: a learnable synthetic task — class = token-0 parity
        n = 128
        xs = rng.randint(0, self.VOCAB, (n, self.T)).astype(np.int32)
        ys = np.eye(2, dtype=np.float32)[xs[:, 0] % 2]
        labels = sd.placeholder("labels", shape=(None, 2))
        out_var = sd._vars[sd.graph_outputs[0]]
        sd.loss.mean_squared_error(out_var, labels).rename("ft_loss")
        sd.set_training_config(TrainingConfig(
            updater=nn.Adam(learning_rate=3e-3),
            data_set_feature_mapping=[sd.graph_inputs[0]],
            data_set_label_mapping=["labels"],
            loss_variables=["ft_loss"]))
        hist = sd.fit(ListDataSetIterator(DataSet(xs, ys), batch_size=32),
                      epochs=30)
        assert hist[-1] < hist[0] * 0.5, (hist[0], hist[-1])

        # accuracy on the training task beats chance decisively
        pred = sd.output({sd.graph_inputs[0]: xs},
                         sd.graph_outputs[0])[sd.graph_outputs[0]]
        acc = (pred.argmax(1) == ys.argmax(1)).mean()
        assert acc > 0.8, acc


class TestSpaceBatchOps:
    def test_atrous_conv_via_space_to_batch(self):
        """tf.nn.atrous_conv2d lowers to SpaceToBatchND → Conv2D →
        BatchToSpaceND in frozen graphs — the dilated-conv import path."""
        w = np.random.RandomState(0).randn(3, 3, 2, 4).astype(np.float32)

        def model(x):
            return tf.nn.atrous_conv2d(x, tf.constant(w), rate=2,
                                       padding="SAME")

        gd, ins, outs = freeze(model, tf.TensorSpec([1, 8, 8, 2], tf.float32))
        assert "SpaceToBatchND" in {n.op for n in gd.node}  # real lowering
        x = np.random.RandomState(1).rand(1, 8, 8, 2).astype(np.float32)
        golden = model(tf.constant(x)).numpy()
        sd = TensorflowImporter().run_import(gd)
        got = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-4, atol=1e-5)

    def test_space_batch_round_trip(self):
        def model(x):
            y = tf.space_to_batch(x, paddings=[[0, 0], [0, 0]],
                                  block_shape=[2, 2])
            return tf.batch_to_space(y, crops=[[0, 0], [0, 0]],
                                     block_shape=[2, 2])

        gd, ins, outs = freeze(model, tf.TensorSpec([2, 4, 4, 3], tf.float32))
        x = np.random.RandomState(2).rand(2, 4, 4, 3).astype(np.float32)
        sd = TensorflowImporter().run_import(gd)
        got = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(got, x, rtol=1e-6)
