"""TF import golden-file tests — the reference's TFGraphTestAllSameDiff
pattern (SURVEY §5.4): build a TF graph in-env, freeze it, import to
SameDiff, and compare outputs elementwise to TF's own."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.imports import TensorflowImporter, import_frozen_graph


def freeze(fn, *specs):
    """Concrete function → frozen GraphDef (variables inlined as Consts)."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    cf = tf.function(fn).get_concrete_function(*specs)
    frozen = convert_variables_to_constants_v2(cf)
    return frozen.graph.as_graph_def(), [t.name.split(":")[0] for t in frozen.inputs], \
        [t.name.split(":")[0] for t in frozen.outputs]


class TestTfImport:
    def test_mlp_golden(self):
        rng = np.random.RandomState(0)
        w0 = tf.Variable(rng.randn(4, 8).astype(np.float32))
        b0 = tf.Variable(np.zeros(8, np.float32))
        w1 = tf.Variable(rng.randn(8, 3).astype(np.float32))

        def model(x):
            h = tf.nn.relu(tf.matmul(x, w0) + b0)
            return tf.nn.softmax(tf.matmul(h, w1))

        gd, ins, outs = freeze(model, tf.TensorSpec([None, 4], tf.float32))
        x = rng.randn(5, 4).astype(np.float32)
        golden = model(tf.constant(x)).numpy()

        sd = TensorflowImporter().run_import(gd)
        got = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)

    def test_elementwise_chain_golden(self):
        def model(x):
            y = tf.sqrt(tf.abs(x) + 1.0) * tf.tanh(x) - tf.sigmoid(x)
            return tf.reduce_mean(y, axis=1)

        gd, ins, outs = freeze(model, tf.TensorSpec([3, 6], tf.float32))
        x = np.random.RandomState(1).randn(3, 6).astype(np.float32)
        golden = model(tf.constant(x)).numpy()
        sd = import_frozen_graph(gd.SerializeToString())
        got = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)

    def test_reshape_transpose_golden(self):
        def model(x):
            y = tf.transpose(tf.reshape(x, [2, 3, 4]), perm=[0, 2, 1])
            return tf.reduce_sum(y, axis=[1], keepdims=True)

        gd, ins, outs = freeze(model, tf.TensorSpec([2, 12], tf.float32))
        x = np.arange(24, dtype=np.float32).reshape(2, 12)
        golden = model(tf.constant(x)).numpy()
        got = import_frozen_graph(gd)._exec_fn  # importer returns SameDiff
        sd = import_frozen_graph(gd)
        out = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(out, golden, rtol=1e-6)

    def test_conv_pool_golden(self):
        rng = np.random.RandomState(2)
        k = tf.Variable(rng.randn(3, 3, 2, 4).astype(np.float32) * 0.1)

        def model(x):
            y = tf.nn.conv2d(x, k, strides=[1, 1, 1, 1], padding="SAME")
            y = tf.nn.relu(y)
            return tf.nn.max_pool2d(y, ksize=2, strides=2, padding="VALID")

        gd, ins, outs = freeze(model, tf.TensorSpec([1, 8, 8, 2], tf.float32))
        x = rng.randn(1, 8, 8, 2).astype(np.float32)
        golden = model(tf.constant(x)).numpy()
        sd = import_frozen_graph(gd)
        got = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-4, atol=1e-5)

    def test_imported_variables_are_trainable(self):
        w = tf.Variable(np.ones((2, 2), np.float32))

        def model(x):
            return tf.matmul(x, w)

        gd, ins, outs = freeze(model, tf.TensorSpec([1, 2], tf.float32))
        sd = TensorflowImporter().run_import(gd)
        trainables = [n for n, v in sd._vars.items() if v.vtype == "VARIABLE"]
        assert len(trainables) == 1
        sd.get_variable(outs[0]).sum().rename("loss")  # scalarize for grad
        g = sd.calculate_gradients({ins[0]: np.ones((1, 2), np.float32)},
                                   "loss", wrt=trainables)
        assert list(g.values())[0].shape == (2, 2)
        np.testing.assert_allclose(list(g.values())[0], np.ones((2, 2)))

    def test_unsupported_op_raises_clearly(self):
        def model(x):
            return tf.raw_ops.Betainc(a=x, b=x, x=x)

        gd, ins, outs = freeze(model, tf.TensorSpec([2], tf.float32))
        with pytest.raises(NotImplementedError, match="Betainc"):
            TensorflowImporter().run_import(gd)

    def test_gelu_composite_golden(self):
        """The BERT-critical GELU-from-erf composite imports op-by-op."""

        def model(x):
            return 0.5 * x * (1.0 + tf.math.erf(x / tf.sqrt(2.0)))

        gd, ins, outs = freeze(model, tf.TensorSpec([4], tf.float32))
        x = np.linspace(-2, 2, 4).astype(np.float32)
        golden = model(tf.constant(x)).numpy()
        sd = import_frozen_graph(gd)
        got = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)


class TestTfImportWidened:
    """Round-3 widened dialect: conv/bn/pad/slice ops via the shared IR layer."""

    def test_cnn_bn_golden(self):
        rng = np.random.RandomState(7)
        w = tf.Variable((rng.randn(3, 3, 3, 8) * 0.3).astype(np.float32))
        dw = tf.Variable((rng.randn(3, 3, 8, 1) * 0.3).astype(np.float32))
        gamma = tf.Variable((np.abs(rng.randn(8)) + 0.5).astype(np.float32))
        beta = tf.Variable(rng.randn(8).astype(np.float32))
        mean = tf.Variable(rng.randn(8).astype(np.float32))
        var = tf.Variable((np.abs(rng.randn(8)) + 0.5).astype(np.float32))

        def model(x):
            y = tf.nn.conv2d(x, w, strides=1, padding="SAME")
            y, _, _ = tf.compat.v1.nn.fused_batch_norm(
                y, gamma, beta, mean=mean, variance=var, is_training=False)
            y = tf.nn.leaky_relu(y, alpha=0.1)
            y = tf.nn.depthwise_conv2d(y, dw, strides=[1, 1, 1, 1],
                                       padding="VALID")
            y = tf.pad(y, [[0, 0], [1, 1], [1, 1], [0, 0]])
            return tf.reduce_mean(y, axis=[1, 2])

        gd, ins, outs = freeze(model, tf.TensorSpec([2, 8, 8, 3], tf.float32))
        x = rng.randn(2, 8, 8, 3).astype(np.float32)
        golden = model(tf.constant(x)).numpy()
        sd = TensorflowImporter().run_import(gd)
        got = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-4, atol=1e-5)

    def test_strided_slice_clip_cumsum_golden(self):
        def model(x):
            y = tf.strided_slice(x, [0, 1], [3, 7], [1, 2])
            y = tf.clip_by_value(y, -0.5, 0.5)
            return tf.cumsum(y, axis=1)

        gd, ins, outs = freeze(model, tf.TensorSpec([3, 8], tf.float32))
        x = np.random.RandomState(8).randn(3, 8).astype(np.float32)
        golden = model(tf.constant(x)).numpy()
        sd = import_frozen_graph(gd.SerializeToString())
        got = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)
