"""Image pipeline tests (datavec-image role)."""

import numpy as np

from deeplearning4j_tpu import nn

from deeplearning4j_tpu.datasets.image import (
    ColorJitterTransform, FlipImageTransform, PipelineImageTransform,
    RandomCropTransform, RotateImageTransform, SyntheticImageNetIterator,
    synthetic_image_batch,
)


class TestTransforms:
    def test_flip(self):
        rng = np.random.RandomState(0)
        img = np.arange(12, dtype=np.float32).reshape(2, 2, 3)
        out = FlipImageTransform()(img, rng)
        np.testing.assert_allclose(out[:, 0], img[:, 1])

    def test_random_crop(self):
        rng = np.random.RandomState(0)
        img = np.random.rand(10, 10, 3).astype(np.float32)
        out = RandomCropTransform(6, 6)(img, rng)
        assert out.shape == (6, 6, 3)

    def test_crop_pads_small_images(self):
        rng = np.random.RandomState(0)
        out = RandomCropTransform(8, 8)(np.ones((4, 4, 1), np.float32), rng)
        assert out.shape == (8, 8, 1)

    def test_rotate(self):
        rng = np.random.RandomState(0)
        img = np.random.rand(5, 7, 1).astype(np.float32)
        out = RotateImageTransform(quarters=[1])(img, rng)
        assert out.shape == (7, 5, 1)

    def test_jitter_clips(self):
        rng = np.random.RandomState(0)
        out = ColorJitterTransform(0.5, 0.5)(np.random.rand(4, 4, 3).astype(np.float32), rng)
        assert out.min() >= 0.0 and out.max() <= 1.0

    def test_pipeline_probabilities(self):
        rng = np.random.RandomState(0)
        img = np.random.rand(6, 6, 1).astype(np.float32)
        p = PipelineImageTransform([(FlipImageTransform(), 0.0)])
        np.testing.assert_allclose(p(img, rng), img)  # prob 0 → never applied


class TestSyntheticImageNet:
    def test_deterministic(self):
        a, la = synthetic_image_batch(4, 16, 16, 3, 10, seed=1)
        b, lb = synthetic_image_batch(4, 16, 16, 3, 10, seed=1)
        np.testing.assert_allclose(a, b)
        np.testing.assert_array_equal(la, lb)

    def test_iterator_shapes(self):
        it = SyntheticImageNetIterator(batch_size=4, height=32, width=32,
                                       num_classes=10, batches_per_epoch=2)
        batches = list(it)
        assert len(batches) == 2
        assert batches[0].features.shape == (4, 32, 32, 3)
        assert batches[0].labels.shape == (4, 10)
        np.testing.assert_allclose(batches[0].labels.sum(-1), 1.0)

    def test_classes_distinguishable(self):
        """Per-class frequency signatures are learnable: nearest-centroid on
        downsampled images beats chance by a wide margin."""
        x, y = synthetic_image_batch(200, 16, 16, 1, 4, seed=0)
        feats = x.reshape(200, -1)
        cents = np.stack([feats[y == c].mean(0) for c in range(4)])
        pred = np.argmin(
            ((feats[:, None, :] - cents[None]) ** 2).sum(-1), axis=1)
        assert (pred == y).mean() > 0.5


class TestCifarEmnistIterators:
    """Round-3 fetcher fill: CIFAR-10 + EMNIST iterators (deeplearning4j-
    datasets role) — local files when present, deterministic synthetic
    fallback otherwise (no egress in this environment)."""

    def test_cifar10_iterator_shapes_and_determinism(self):
        from deeplearning4j_tpu.datasets import Cifar10DataSetIterator

        it = Cifar10DataSetIterator(batch_size=16, train=True,
                                    num_examples=64, seed=5,
                                    root="/nonexistent")  # force synthetic
        assert it.synthetic
        ds = next(iter(it))
        assert ds.features.shape == (16, 32, 32, 3)
        assert ds.labels.shape == (16, 10)
        assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0
        it2 = Cifar10DataSetIterator(batch_size=16, train=True,
                                     num_examples=64, seed=5,
                                     root="/nonexistent")
        np.testing.assert_array_equal(ds.features,
                                      next(iter(it2)).features)

    def test_cifar10_is_learnable(self):
        from deeplearning4j_tpu.datasets import Cifar10DataSetIterator

        it = Cifar10DataSetIterator(batch_size=64, train=True,
                                    num_examples=256, seed=1,
                                    root="/nonexistent")
        b = nn.builder().seed(3).updater(nn.Adam(learning_rate=1e-3)).list()
        b.layer(nn.ConvolutionLayer(n_out=8, kernel=(3, 3),
                                    convolution_mode="same",
                                    activation="relu"))
        b.layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
        b.layer(nn.OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
        net = nn.MultiLayerNetwork(
            b.set_input_type(nn.InputType.convolutional(32, 32, 3)).build()).init()
        net.fit(it, epochs=6)
        ev = net.evaluate(Cifar10DataSetIterator(batch_size=64, train=True,
                                                 num_examples=256, seed=1,
                                                 root="/nonexistent"))
        assert ev.accuracy() > 0.3  # well above 10% chance

    def test_emnist_sets(self):
        from deeplearning4j_tpu.datasets import (
            EMNIST_SETS, EmnistDataSetIterator)

        it = EmnistDataSetIterator(batch_size=8, emnist_set="letters",
                                   num_examples=32, root="/nonexistent")
        ds = next(iter(it))
        assert ds.features.shape == (8, 784)
        assert ds.labels.shape == (8, 26)
        assert EMNIST_SETS["balanced"] == 47
        import pytest as _pytest

        with _pytest.raises(ValueError):
            EmnistDataSetIterator(batch_size=8, emnist_set="nope")
