"""observe/ — unified runtime telemetry (docs/OBSERVABILITY.md).

Covers the metric model (counters/gauges/histograms + streaming
percentiles, thread safety, Prometheus rendering), the span tracer (ONE
trace format shared with utils/profiling.py), the recompile ledger through
real SameDiff / MultiLayerNetwork jit caches (same-shape refit → no event;
new batch shape → new_shape; constant rebind → constant_rebind), the
ParallelInference serving metrics under multithreaded client load, and the
JSONL event log."""

import json
import threading

import numpy as np
import pytest

from deeplearning4j_tpu import observe


@pytest.fixture(autouse=True)
def fresh_observe():
    """Isolate every test from telemetry recorded by earlier tests (and by
    the fixture-owning test itself from later ones)."""
    observe.reset()
    yield
    observe.reset()


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


class TestMetricsRegistry:
    def test_counter_and_gauge(self):
        m = observe.metrics()
        c = m.counter("t_total")
        c.inc()
        c.inc(4)
        assert c.value == 5
        assert m.counter("t_total") is c  # create-or-get
        with pytest.raises(ValueError):
            c.inc(-1)
        g = m.gauge("t_depth")
        g.set(7)
        g.dec(2)
        assert g.value == 5

    def test_labels_are_distinct_instruments(self):
        m = observe.metrics()
        m.counter("t_steps", model="mln").inc(3)
        m.counter("t_steps", model="graph").inc(4)
        assert m.counter("t_steps", model="mln").value == 3
        assert m.family_total("t_steps") == 7

    def test_kind_conflict_raises(self):
        m = observe.metrics()
        m.counter("t_thing")
        with pytest.raises(TypeError):
            m.histogram("t_thing")

    def test_histogram_percentiles(self):
        h = observe.metrics().histogram("t_lat")
        for v in [0.001] * 98 + [0.5, 1.0]:
            h.observe(v)
        assert h.count == 100
        # p50 lands in the bucket containing 1ms; p99 near the 0.5-1.0 tail
        assert h.quantile(0.50) < 0.01
        assert h.quantile(0.99) > 0.1
        assert h.min == 0.001 and h.max == 1.0
        pct = h.percentiles()
        assert set(pct) == {"p50", "p95", "p99"}

    def test_histogram_empty(self):
        h = observe.metrics().histogram("t_empty")
        assert h.quantile(0.5) is None and h.mean is None

    def test_merged_histogram_across_labels(self):
        m = observe.metrics()
        m.histogram("t_step", model="a").observe(0.01)
        m.histogram("t_step", model="b").observe(0.01)
        merged = m.merged_histogram("t_step")
        assert merged.count == 2

    def test_thread_safety_exact_counts(self):
        m = observe.metrics()
        c = m.counter("t_conc_total")
        h = m.histogram("t_conc_lat")

        def worker(seed):
            r = np.random.RandomState(seed)
            for _ in range(1000):
                c.inc()
                h.observe(float(r.rand()) * 0.01)

        threads = [threading.Thread(target=worker, args=(s,))
                   for s in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 8000
        assert h.count == 8000

    def test_prometheus_rendering(self):
        m = observe.metrics()
        m.counter("t_req_total", model="mln").inc(2)
        h = m.histogram("t_req_seconds")
        h.observe(0.003)
        text = m.render_prometheus()
        assert "# TYPE t_req_total counter" in text
        assert 't_req_total{model="mln"} 2' in text
        assert "# TYPE t_req_seconds histogram" in text
        assert "t_req_seconds_count 1" in text
        assert "t_req_seconds_sum 0.003" in text
        assert 'le="+Inf"} 1' in text
        # cumulative buckets are monotonically non-decreasing
        cums = [int(l.rsplit(" ", 1)[1]) for l in text.splitlines()
                if l.startswith("t_req_seconds_bucket")]
        assert cums == sorted(cums) and cums[-1] == 1
        # the eagerly registered core catalog is always present
        assert "dl4j_tpu_recompiles_total" in text
        assert "dl4j_tpu_serving_request_seconds" in text


# ---------------------------------------------------------------------------
# span tracer — one trace format
# ---------------------------------------------------------------------------


class TestSpanTracer:
    def test_nested_spans_and_export(self, tmp_path):
        tr = observe.tracer()
        with tr.span("outer", category="test", k=1):
            with tr.span("inner", category="test"):
                pass
            tr.instant("mark", note="x")
        names = [e["name"] for e in tr.events]
        assert names == ["inner", "mark", "outer"]  # inner completes first
        ev = {e["name"]: e for e in tr.events}
        assert ev["outer"]["ph"] == "X" and ev["outer"]["dur"] >= 0
        assert ev["outer"]["args"] == {"k": 1}
        p = str(tmp_path / "trace.json")
        tr.write(p)
        data = json.load(open(p))
        assert data["displayTimeUnit"] == "ms"
        assert len(data["traceEvents"]) == 3

    def test_complete_between_perf_counter(self):
        import time

        tr = observe.tracer()
        t0 = time.perf_counter()
        t1 = t0 + 0.25
        tr.complete_between("window", t0, t1, category="test")
        ev = tr.events[-1]
        assert abs(ev["dur"] - 0.25e6) < 1.0  # microseconds

    def test_chrome_trace_writer_is_the_same_format(self, tmp_path):
        """utils/profiling.ChromeTraceWriter IS a SpanTracer now — the
        profiling artifact and the telemetry spans share one format."""
        from deeplearning4j_tpu.observe.tracing import SpanTracer
        from deeplearning4j_tpu.utils.profiling import (ChromeTraceWriter,
                                                        ProfileAnalyzer)

        w = ChromeTraceWriter()
        assert isinstance(w, SpanTracer)
        with w.span("step", category="train_step"):
            pass
        p = str(tmp_path / "t.json")
        w.write(p)
        agg = ProfileAnalyzer.load(p)
        assert "train_step" in agg

    def test_profiling_listener_still_writes(self, tmp_path):
        from deeplearning4j_tpu.utils.profiling import ProfilingListener

        p = str(tmp_path / "prof.json")
        pl = ProfilingListener(p)
        pl.on_epoch_start(model=None)
        pl.iteration_done(None, 1, 0, 0.5)
        pl.iteration_done(None, 2, 0, 0.4)
        pl.on_epoch_end(model=None)
        data = json.load(open(p))
        assert any(e.get("cat") == "train_step"
                   for e in data["traceEvents"])


# ---------------------------------------------------------------------------
# recompile ledger — SameDiff jit cache
# ---------------------------------------------------------------------------


def _linreg_sd(with_const=False):
    from deeplearning4j_tpu import nn
    from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig

    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 4))
    labels = sd.placeholder("labels", shape=(None, 1))
    w = sd.var("w", np.zeros((4, 1), np.float32))
    pred = x.mmul(w)
    if with_const:
        scale = sd.constant("scale", np.float32(1.0))
        pred = pred * scale
    sd.loss.mean_squared_error(pred, labels).rename("loss")
    sd.set_training_config(TrainingConfig(
        updater=nn.Sgd(learning_rate=0.01),
        data_set_feature_mapping=["x"], data_set_label_mapping=["labels"],
        loss_variables=["loss"]))
    return sd


def _fit(sd, n=32, epochs=1):
    from deeplearning4j_tpu.datasets.dataset import (DataSet,
                                                     ListDataSetIterator)

    r = np.random.RandomState(0)
    xs = r.randn(n, 4).astype(np.float32)
    ys = (xs @ np.array([[1.0], [2.0], [0.5], [-1.0]], np.float32))
    sd.fit(ListDataSetIterator(DataSet(xs, ys), batch_size=n), epochs=epochs)


def _events(graph=None, key=None):
    evs = observe.ledger().events()
    return [e for e in evs
            if (graph is None or e.graph == graph)
            and (key is None or e.key == key)]


class TestRecompileLedgerSameDiff:
    def test_same_shape_refit_exactly_one_compile_event(self):
        sd = _linreg_sd()
        _fit(sd, n=32, epochs=2)
        _fit(sd, n=32, epochs=3)   # same shapes: cached step fn, no event
        evs = _events("samediff", "train")
        assert len(evs) == 1
        assert evs[0].cause == "first_compile"
        assert "[32,4]" in evs[0].signature

    def test_new_batch_shape_one_new_event(self):
        sd = _linreg_sd()
        _fit(sd, n=32)
        _fit(sd, n=48)             # new feed signature on the cached fn
        evs = _events("samediff", "train")
        assert [e.cause for e in evs] == ["first_compile", "new_shape"]
        assert "[48,4]" in evs[1].signature

    def test_constant_rebind_cause(self):
        sd = _linreg_sd(with_const=True)
        _fit(sd, n=32)
        sd.set_arr("scale", np.float32(2.0))   # CONSTANT rebind: cache wiped
        _fit(sd, n=32)
        evs = _events("samediff", "train")
        assert [e.cause for e in evs] == ["first_compile", "constant_rebind"]

    def test_output_path_new_shape(self):
        from deeplearning4j_tpu.autodiff import SameDiff

        sd2 = SameDiff.create()
        x = sd2.placeholder("x", shape=(None, 3))
        w = sd2.var("w", np.ones((3, 2), np.float32))
        x.mmul(w).rename("out")
        sd2.output({"x": np.zeros((4, 3), np.float32)}, "out")
        sd2.output({"x": np.zeros((4, 3), np.float32)}, "out")  # cache hit
        sd2.output({"x": np.zeros((6, 3), np.float32)}, "out")  # retrace
        evs = _events("samediff", "exec")
        assert [e.cause for e in evs] == ["first_compile", "new_shape"]
        # the exec path's stats carry the measured trace/compile split
        assert evs[0].stats is not None
        assert evs[0].stats.trace_seconds is not None

    def test_graph_mutation_cause(self):
        from deeplearning4j_tpu.autodiff import SameDiff

        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 3))
        w = sd.var("w", np.ones((3, 3), np.float32))
        h = x.mmul(w)
        h.rename("out")
        feeds = {"x": np.zeros((2, 3), np.float32)}
        sd.output(feeds, "out")
        sd.math.tanh(h).rename("out2")   # mutation AFTER a compile
        # the PREVIOUSLY-compiled key rebuilt → graph_mutation; a key never
        # compiled before ("out2") is a first_compile even post-mutation
        sd.output(feeds, "out")
        sd.output(feeds, "out2")
        evs = _events("samediff", "exec")
        assert [e.cause for e in evs] == [
            "first_compile", "graph_mutation", "first_compile"]

    def test_recompile_counters(self):
        sd = _linreg_sd()
        _fit(sd, n=32)
        _fit(sd, n=16)
        m = observe.metrics()
        assert m.counter("dl4j_tpu_recompiles_total").value >= 2
        assert m.counter("dl4j_tpu_recompile_cause_total",
                         cause="new_shape").value >= 1


class TestRecompileLedgerNetworks:
    def test_mln_fit_first_compile_then_new_shape(self):
        from deeplearning4j_tpu import nn

        net = nn.MultiLayerNetwork(
            nn.builder().seed(0).updater(nn.Sgd(learning_rate=0.1)).list()
            .layer(nn.DenseLayer(n_out=4, activation="tanh"))
            .layer(nn.OutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(3)).build()).init()
        r = np.random.RandomState(0)
        x = r.randn(16, 3).astype(np.float32)
        y = np.eye(2)[r.randint(0, 2, 16)].astype(np.float32)
        net.fit(x, y, batch_size=16)
        net.fit(x, y, batch_size=16)   # same shape: no new event
        net.fit(x[:8], y[:8], batch_size=8)
        evs = _events("mln", "train_step")
        assert [e.cause for e in evs] == ["first_compile", "new_shape"]
        m = observe.metrics()
        assert m.counter("dl4j_tpu_train_steps_total", model="mln").value == 3
        assert m.counter("dl4j_tpu_train_examples_total",
                         model="mln").value == 40
        assert m.merged_histogram("dl4j_tpu_train_step_seconds").count == 3


# ---------------------------------------------------------------------------
# ParallelInference serving metrics under concurrent clients
# ---------------------------------------------------------------------------


class TestServingMetrics:
    def test_multithreaded_clients_counters_and_percentiles(self):
        from deeplearning4j_tpu import nn
        from deeplearning4j_tpu.parallel.mesh import ParallelInference

        net = nn.MultiLayerNetwork(
            nn.builder().seed(0).updater(nn.Sgd(learning_rate=0.1)).list()
            .layer(nn.DenseLayer(n_out=8, activation="relu"))
            .layer(nn.OutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(5)).build()).init()
        # max_batch=8: divisible by the 8-device virtual CPU mesh
        pi = ParallelInference(net, max_batch=8, window_ms=2.0).start()
        errors = []
        try:
            def client(seed):
                r = np.random.RandomState(seed)
                try:
                    for _ in range(10):
                        out = pi.predict(r.randn(5).astype(np.float32))
                        assert out.shape == (1, 3)
                except Exception as e:  # surfaced below
                    errors.append(e)

            threads = [threading.Thread(target=client, args=(s,))
                       for s in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            pi.stop()
        assert not errors, errors
        m = observe.metrics()
        # counters consistent: every request counted once, each row served
        assert m.counter("dl4j_tpu_serving_requests_total").value == 40
        assert m.counter("dl4j_tpu_serving_rows_total").value == 40
        batches = m.counter("dl4j_tpu_serving_batches_total").value
        assert 5 <= batches <= 40  # batched (>=5 at max_batch=8) but every
        #                            request still individually served
        lat = m.histogram("dl4j_tpu_serving_request_seconds")
        assert lat.count == 40
        pct = lat.percentiles()
        assert pct["p50"] is not None and pct["p99"] is not None
        assert 0 < pct["p50"] <= pct["p99"]
        wait = m.histogram("dl4j_tpu_serving_queue_wait_seconds")
        assert wait.count == 40
        occ = m.histogram("dl4j_tpu_serving_batch_occupancy")
        assert occ.count == batches
        assert 0 < occ.mean <= 1.0
        # summary() carries the serving section bench.py embeds
        s = observe.summary()
        assert s["serving"]["requests"] == 40
        assert s["serving"]["p99_ms"] is not None


# ---------------------------------------------------------------------------
# JSONL event log
# ---------------------------------------------------------------------------


class TestJsonlEventLog:
    def test_events_append_when_env_set(self, tmp_path, monkeypatch):
        path = str(tmp_path / "obs.jsonl")
        monkeypatch.setenv(observe.OBS_LOG_ENV, path)
        observe.ledger().record(graph="samediff", key="train",
                                signature="x:f32[4,2]", cause="new_shape")
        observe.log_event("train_epoch", model="mln", epoch=1, steps=7)
        lines = [json.loads(l) for l in open(path).read().splitlines()]
        assert [l["kind"] for l in lines] == ["recompile", "train_epoch"]
        assert lines[0]["cause"] == "new_shape"
        assert lines[1]["steps"] == 7
        assert all("ts" in l for l in lines)

    def test_noop_without_env(self, tmp_path, monkeypatch):
        monkeypatch.delenv(observe.OBS_LOG_ENV, raising=False)
        observe.log_event("train_epoch", steps=1)  # must not raise

    def test_obsreport_log_mode(self, tmp_path, monkeypatch, capsys):
        import sys

        from deeplearning4j_tpu.autodiff.optimize import OptimizeStats

        path = str(tmp_path / "obs.jsonl")
        monkeypatch.setenv(observe.OBS_LOG_ENV, path)
        st = OptimizeStats()
        st.record_fusion("attention", 12)
        st.record_fusion("epilogue", 72)
        observe.ledger().record(graph="mln", key="train_step",
                                signature="s", cause="first_compile",
                                stats=st)
        observe.log_event("serving_batch", rows=6, requests=3,
                          batch_seconds=0.004)
        monkeypatch.delenv(observe.OBS_LOG_ENV)

        sys.path.insert(0, "tools")
        try:
            import obsreport
        finally:
            sys.path.pop(0)
        rc = obsreport._summarize_log(path, json_mode=True)
        assert rc == 0
        out = json.loads(capsys.readouterr().out.strip())
        assert out["by_kind"] == {"recompile": 1, "serving_batch": 1}
        assert out["recompile_causes"] == {"first_compile": 1}
        assert out["serving_rows"] == 6
        # fusion hits ride the recompile event into the post-hoc summary
        assert out["fusion_hits"] == {"attention": 12, "epilogue": 72}


# ---------------------------------------------------------------------------
# ledger unit behavior
# ---------------------------------------------------------------------------


class TestLedgerUnit:
    def test_unknown_cause_rejected(self):
        with pytest.raises(ValueError):
            observe.ledger().record(graph="g", key="k", signature="s",
                                    cause="cosmic_rays")

    def test_bounded(self):
        led = observe.RecompileLedger(max_events=5)
        for i in range(9):
            led.record(graph="g", key="k", signature=f"s{i}",
                       cause="new_shape")
        assert len(led) == 5
        assert led.events()[0].signature == "s4"  # oldest dropped

    def test_summary_by_cause(self):
        led = observe.ledger()
        led.record(graph="g", key="k", signature="a", cause="first_compile")
        led.record(graph="g", key="k", signature="b", cause="new_shape")
        s = led.summary()
        assert s["total"] == 2
        assert s["by_cause"] == {"first_compile": 1, "new_shape": 1}
