"""SLO admission frontend tests (docs/SERVING.md § SLO admission
frontend).

The properties under test mirror the ``slo`` gate stage:
  * admission control is a POLICY, not an accident: token buckets,
    concurrency caps, per-class queue bounds and predictive early shed
    each deny for their own counted reason, and every denial is a
    TERMINAL result — never an exception, never a hang;
  * the pending queue is priority-ordered and shed-lowest-first, and
    supervisor retries preserve class/priority/submit time;
  * the degradation ladder escalates immediately, de-escalates with
    hysteresis, trims only degradable classes, and records the trim on
    the result;
  * the circuit breaker fast-fails admissions while the engine thrashes
    and re-admits after the cooldown;
  * every terminal path — engine retires, queue fails, frontend sheds —
    increments the ONE ``dl4j_tpu_serving_evicted_total{reason}`` family
    exactly once with a reason from ``FINISH_REASONS``;
  * a threaded mixed-class overload run ends with every request
    terminal, interactive p99 TTFT inside its SLO while batch sheds, and
    ZERO ``new_shape`` ledger events across all ladder transitions.
"""

import threading
import time
import types

import numpy as np
import pytest

from deeplearning4j_tpu import faults, observe
from deeplearning4j_tpu.serving import (
    ClassPolicy, GenerationRequest, LadderThresholds, SLOFrontend,
)
from deeplearning4j_tpu.serving.scheduler import (
    FINISH_REASONS, SlotScheduler, count_terminal,
)

PROMPT = np.array([3, 5, 7], np.int32)


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


class StubEngine:
    """The engine surface the frontend touches, minus the device: a real
    SlotScheduler (pure host-side), a restarts attr, and a
    submit_request that queues without serving."""

    def __init__(self, max_slots: int = 2):
        self.scheduler = SlotScheduler(max_slots)
        self.restarts = 0
        self.cfg = types.SimpleNamespace(eos_token=-1, vocab_size=64)
        self.default_deadline_s = None
        self.submitted = []

    def validate_request(self, req):
        pass  # the real engine's prompt-bucket/vocab checks

    def submit_request(self, req):
        self.submitted.append(req)
        return self.scheduler.submit(req)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv(faults.FAULTS_ENV, raising=False)
    faults.reset()
    observe.reset()
    yield
    faults.reset()
    observe.reset()


def evicted_counts():
    out = {}
    for inst in observe.metrics().instruments():
        if inst.name == "dl4j_tpu_serving_evicted_total" and inst.labels:
            out[dict(inst.labels)["reason"]] = int(inst.value)
    return out


def slo_shed_counts():
    out = {}
    for inst in observe.metrics().instruments():
        if inst.name == "dl4j_tpu_slo_shed_total" and inst.labels:
            lbl = dict(inst.labels)
            out[(lbl["class"], lbl["reason"])] = int(inst.value)
    return out


# ---------------------------------------------------------------------------
# admission control: buckets, caps, predictive shed
# ---------------------------------------------------------------------------


class TestAdmissionControl:
    def test_unknown_class_raises(self):
        fe = SLOFrontend(StubEngine())
        with pytest.raises(ValueError, match="unknown SLO class"):
            fe.submit(PROMPT, slo_class="platinum")

    def test_token_bucket_rate_limit(self):
        clock = FakeClock()
        classes = {"standard": ClassPolicy("standard", priority=1,
                                           rate=1.0, burst=2)}
        fe = SLOFrontend(StubEngine(), classes=classes, clock=clock)
        futs = [fe.submit(PROMPT) for _ in range(3)]
        # burst of 2 admitted, the third shed terminally as rate_limit
        assert not futs[0].done() and not futs[1].done()
        res = futs[2].result(timeout=0)
        assert res.finish_reason == "shed"
        assert slo_shed_counts()[("standard", "rate_limit")] == 1
        # the bucket refills with (fake) time — one token per second
        clock.t += 1.0
        assert not fe.submit(PROMPT).done()
        res = fe.submit(PROMPT).result(timeout=0)
        assert res.finish_reason == "shed"

    def test_concurrency_cap(self):
        classes = {"batch": ClassPolicy("batch", priority=2,
                                        max_concurrent=2)}
        eng = StubEngine()
        fe = SLOFrontend(eng, classes=classes)
        f1 = fe.submit(PROMPT, slo_class="batch")
        fe.submit(PROMPT, slo_class="batch")
        shed = fe.submit(PROMPT, slo_class="batch")
        assert shed.result(timeout=0).finish_reason == "shed"
        assert slo_shed_counts()[("batch", "concurrency")] == 1
        # completing one in-flight request frees a slot in the cap
        eng.scheduler.fail_pending(RuntimeError("drain"), reason="error")
        assert f1.done()
        assert not fe.submit(PROMPT, slo_class="batch").done()

    def test_predictive_shed_on_hopeless_deadline(self):
        eng = StubEngine(max_slots=2)
        fe = SLOFrontend(eng, est_tokens_per_request=16.0)
        fe._rolling.p50 = 0.1  # 100ms/step signal
        # queue 10 deep ahead of us -> ~8 waves x 16 tokens x 100ms >> 0.5s
        for _ in range(10):
            eng.scheduler.submit(GenerationRequest(prompt=PROMPT))
        fut = fe.submit(PROMPT, deadline_s=0.5)
        assert fut.result(timeout=0).finish_reason == "shed"
        assert slo_shed_counts()[("standard", "predicted_deadline")] == 1

    def test_no_predictive_shed_without_latency_signal(self):
        """Cold start: no decode histogram samples and no prior — the
        frontend must never early-shed blind."""
        eng = StubEngine(max_slots=1)
        fe = SLOFrontend(eng)
        for _ in range(50):
            eng.scheduler.submit(GenerationRequest(prompt=PROMPT))
        assert fe.estimate_ttft_s() is None
        assert not fe.submit(PROMPT, deadline_s=0.001).done()

    def test_priority_aware_estimate(self):
        """An interactive arrival jumps the queue — its TTFT estimate
        counts only same-or-better-priority work ahead."""
        eng = StubEngine(max_slots=2)
        fe = SLOFrontend(eng)
        fe._rolling.p50 = 0.1
        for _ in range(10):
            eng.scheduler.submit(
                GenerationRequest(prompt=PROMPT, priority=2))
        est_batch = fe.estimate_ttft_s(priority=2)
        est_interactive = fe.estimate_ttft_s(priority=0)
        assert est_interactive < est_batch


# ---------------------------------------------------------------------------
# priority ordering + shed-lowest-first
# ---------------------------------------------------------------------------


class TestPriorityQueue:
    def test_best_pending_orders_by_priority_then_fifo(self):
        sched = SlotScheduler(2)
        batch = GenerationRequest(prompt=PROMPT, priority=2)
        std1 = GenerationRequest(prompt=PROMPT, priority=1)
        std2 = GenerationRequest(prompt=PROMPT, priority=1)
        sched.submit(batch)
        sched.submit(std1)
        sched.submit(std2)
        item = sched.peek_best_pending()
        assert item[0] is std1  # best priority, earliest submit
        assert sched.remove_pending(item)
        assert sched.peek_best_pending()[0] is std2
        assert not sched.remove_pending(item)  # already gone

    def test_steal_lowest_pending(self):
        sched = SlotScheduler(2)
        b1 = GenerationRequest(prompt=PROMPT, priority=2)
        b2 = GenerationRequest(prompt=PROMPT, priority=2)
        s1 = GenerationRequest(prompt=PROMPT, priority=1)
        for r in (b1, s1, b2):
            sched.submit(r)
        # nothing strictly lower-priority than batch itself
        assert sched.steal_lowest_pending(2) is None
        # an interactive arrival displaces the NEWEST worst-class item
        victim = sched.steal_lowest_pending(0)
        assert victim[0] is b2
        assert len(sched.pending) == 2

    def test_queue_bound_sheds_lowest_class_first(self):
        eng = StubEngine()
        fe = SLOFrontend(eng, max_queue_total=2)
        batch_fut = fe.submit(PROMPT, slo_class="batch")
        fe.submit(PROMPT, slo_class="standard")
        # the queue is full; an interactive arrival displaces batch
        inter_fut = fe.submit(PROMPT, slo_class="interactive")
        assert batch_fut.result(timeout=0).finish_reason == "shed"
        assert batch_fut.result(timeout=0).slo_class == "batch"
        assert not inter_fut.done()
        assert slo_shed_counts()[("batch", "queue_full")] == 1
        # a batch arrival with nothing worse queued sheds ITSELF
        fut = fe.submit(PROMPT, slo_class="batch")
        assert fut.result(timeout=0).finish_reason == "shed"

    def test_per_class_queue_bound(self):
        classes = {"batch": ClassPolicy("batch", priority=2, max_queued=2)}
        fe = SLOFrontend(StubEngine(), classes=classes)
        fe.submit(PROMPT, slo_class="batch")
        fe.submit(PROMPT, slo_class="batch")
        fut = fe.submit(PROMPT, slo_class="batch")
        assert fut.result(timeout=0).finish_reason == "shed"
        assert slo_shed_counts()[("batch", "queue_full")] == 1


# ---------------------------------------------------------------------------
# the degradation ladder
# ---------------------------------------------------------------------------


class TestLadder:
    def _fe(self, q_p99):
        fe = SLOFrontend(StubEngine(), thresholds=LadderThresholds(
            degraded_queue=8, shedding_queue=16,
            degraded_p99_s=0.5, shedding_p99_s=2.0))
        fe._signals = lambda: q_p99[0]  # noqa: test hook
        return fe

    def test_escalation_and_hysteresis(self):
        sig = [(0, None)]
        fe = self._fe(sig)
        fe._update_state(0.0)
        assert fe.state == "ok"
        sig[0] = (20, None)  # past the shedding enter threshold
        fe._update_state(0.0)
        assert fe.state == "shedding"
        # inside the hysteresis band: 9 < 16 but > 0.5 * 16 — stays
        sig[0] = (9, None)
        fe._update_state(0.0)
        assert fe.state == "shedding"
        # below the exit band: one level at a time
        sig[0] = (7, None)
        fe._update_state(0.0)
        assert fe.state == "degraded"
        sig[0] = (7, None)  # above degraded exit (4) — stays degraded
        fe._update_state(0.0)
        assert fe.state == "degraded"
        sig[0] = (2, None)
        fe._update_state(0.0)
        assert fe.state == "ok"
        assert fe.states_visited == {"ok", "degraded", "shedding"}
        # transitions were counted and the gauge tracks the level
        trans = {dict(i.labels).get("to"): int(i.value)
                 for i in observe.metrics().instruments()
                 if i.name == "dl4j_tpu_slo_transitions_total" and i.labels}
        assert trans == {"shedding": 1, "degraded": 1, "ok": 1}
        assert observe.metrics().gauge("dl4j_tpu_slo_state").value == 0.0

    def test_p99_signal_escalates(self):
        sig = [(0, 3.0)]  # rolling decode p99 of 3s
        fe = self._fe(sig)
        fe._update_state(0.0)
        assert fe.state == "shedding"

    def test_degraded_trims_low_classes_only(self):
        eng = StubEngine()
        fe = SLOFrontend(eng, degraded_max_new_tokens=4)
        fe._signals = lambda: (100, None)  # force shedding-level pressure
        fe.submit(PROMPT, slo_class="standard", max_new_tokens=32,
                  top_k=40, top_p=0.9)
        req = eng.submitted[-1]
        assert req.degraded and req.max_new_tokens == 4
        assert req.top_k == 0 and req.top_p == 1.0
        # interactive is not degradable in the default ladder
        fe.submit(PROMPT, slo_class="interactive", max_new_tokens=32,
                  top_k=40, top_p=0.9)
        req = eng.submitted[-1]
        assert not req.degraded and req.max_new_tokens == 32
        assert req.top_k == 40
        deg = int(observe.metrics().family_total(
            "dl4j_tpu_slo_degraded_total"))
        assert deg == 1

    def test_shedding_rejects_batch_outright(self):
        eng = StubEngine()
        fe = SLOFrontend(eng)
        fe._signals = lambda: (100, None)
        fut = fe.submit(PROMPT, slo_class="batch")
        assert fut.result(timeout=0).finish_reason == "shed"
        assert slo_shed_counts()[("batch", "shedding_state")] == 1
        # interactive still admits in shedding
        assert not fe.submit(PROMPT, slo_class="interactive").done()

    def test_degraded_flag_propagates_to_result(self):
        eng = StubEngine()
        fe = SLOFrontend(eng)
        fe._signals = lambda: (100, None)
        fut = fe.submit(PROMPT, slo_class="standard")
        item = eng.scheduler.peek_best_pending()
        eng.scheduler.remove_pending(item)
        eng.scheduler.admit(0, item[0], item[1], item[2], first_token=1,
                            now=item[2])
        res = eng.scheduler.retire(0, "length")
        assert res.degraded and res.slo_class == "standard"
        assert fut.result(timeout=0).degraded


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def test_opens_on_restart_rate_and_cools_down(self):
        clock = FakeClock()
        eng = StubEngine()
        fe = SLOFrontend(eng, breaker_window_s=60.0, breaker_restarts=3,
                         breaker_cooldown_s=5.0, clock=clock)
        assert not fe.submit(PROMPT).done()
        eng.restarts = 3  # supervisor thrash
        fut = fe.submit(PROMPT)
        res = fut.result(timeout=0)
        assert res.finish_reason == "error"  # fast-fail, not shed
        assert fe.breaker_open and fe.breaker_opens == 1
        assert slo_shed_counts()[("standard", "circuit_open")] == 1
        assert observe.metrics().gauge(
            "dl4j_tpu_slo_breaker_open").value == 1.0
        # still open inside the cooldown
        clock.t += 4.0
        assert fe.submit(PROMPT).done()
        # past the cooldown with no NEW restarts: admissions resume
        clock.t += 2.0
        assert not fe.submit(PROMPT).done()
        assert observe.metrics().gauge(
            "dl4j_tpu_slo_breaker_open").value == 0.0

    def test_reopens_only_on_new_restarts(self):
        clock = FakeClock()
        eng = StubEngine()
        fe = SLOFrontend(eng, breaker_restarts=2, breaker_cooldown_s=1.0,
                         clock=clock)
        eng.restarts = 2
        assert fe.submit(PROMPT).done()
        clock.t += 2.0
        assert not fe.submit(PROMPT).done()  # old thrash burst consumed
        eng.restarts = 4
        assert fe.submit(PROMPT).done()
        assert fe.breaker_opens == 2


# ---------------------------------------------------------------------------
# one terminal taxonomy (satellite)
# ---------------------------------------------------------------------------


class TestTerminalTaxonomy:
    def test_count_terminal_rejects_unknown_reasons(self):
        with pytest.raises(ValueError, match="unknown finish reason"):
            count_terminal("vibes")

    def test_frontend_sheds_count_exactly_once(self):
        fe = SLOFrontend(StubEngine(), max_queue_total=0)
        before = evicted_counts()
        fut = fe.submit(PROMPT, slo_class="batch")
        assert fut.result(timeout=0).finish_reason == "shed"
        after = evicted_counts()
        assert after.get("shed", 0) - before.get("shed", 0) == 1
        assert sum(after.values()) - sum(before.values()) == 1

    def test_breaker_error_counts_exactly_once(self):
        eng = StubEngine()
        fe = SLOFrontend(eng, breaker_restarts=1)
        eng.restarts = 1
        before = evicted_counts()
        fe.submit(PROMPT)
        after = evicted_counts()
        assert after.get("error", 0) - before.get("error", 0) == 1
        assert sum(after.values()) - sum(before.values()) == 1

    def test_fail_pending_and_fail_all_label_reasons(self):
        sched = SlotScheduler(2)
        sched.submit(GenerationRequest(prompt=PROMPT))
        before = evicted_counts()
        sched.fail_pending(RuntimeError("stop hung"), reason="stopped")
        after = evicted_counts()
        assert after.get("stopped", 0) - before.get("stopped", 0) == 1
        from concurrent.futures import Future
        fut: "Future" = Future()
        sched.admit(0, GenerationRequest(prompt=PROMPT), fut, 0.0, 1, 0.0)
        sched.submit(GenerationRequest(prompt=PROMPT))
        before = evicted_counts()
        sched.fail_all(RuntimeError("died"))
        after = evicted_counts()
        assert after.get("error", 0) - before.get("error", 0) == 2

    def test_already_done_futures_not_double_counted(self):
        sched = SlotScheduler(2)
        fut = sched.submit(GenerationRequest(prompt=PROMPT))
        item = sched.peek_best_pending()
        # frontend-style displacement completes the future first...
        stolen = sched.steal_lowest_pending(0)
        assert stolen is item
        from deeplearning4j_tpu.serving.scheduler import GenerationResult
        fut.set_result(GenerationResult(
            tokens=np.zeros((0,), np.int32), finish_reason="shed",
            prompt_len=0, ttft_s=None, intertoken_s=[]))
        before = evicted_counts()
        sched.fail_pending(RuntimeError("x"))  # queue already empty
        assert evicted_counts() == before

    def test_all_reason_labels_are_in_finish_reasons(self):
        """Every reason label the counter family has ever seen must come
        from the shared taxonomy."""
        fe = SLOFrontend(StubEngine(), max_queue_total=0)
        fe.submit(PROMPT, slo_class="batch")
        sched = SlotScheduler(1)
        sched.submit(GenerationRequest(prompt=PROMPT))
        sched.fail_pending(RuntimeError("x"), reason="stopped")
        for reason in evicted_counts():
            assert reason in FINISH_REASONS


# ---------------------------------------------------------------------------
# burst_arrival fault hook
# ---------------------------------------------------------------------------


class TestBurstArrival:
    def test_burst_injects_tracked_lowest_class_arrivals(self):
        eng = StubEngine(max_slots=2)
        fe = SLOFrontend(eng, burst_size=3)
        faults.arm("burst_arrival", prob=1.0, max_fires=1)
        fe.submit(PROMPT, slo_class="interactive")
        assert len(fe.burst_futures) == 3
        # injected arrivals are LOWEST class and pass through admission
        # (here: queued on the stub, ready to shed/serve like any other)
        burst_reqs = [r for r in eng.submitted if r.slo_class == "batch"]
        assert len(burst_reqs) == 3
        fired = int(observe.metrics().counter(
            "dl4j_tpu_faults_injected_total", point="burst_arrival").value)
        assert fired == 1
        # one fire only — the next submit injects nothing more
        fe.submit(PROMPT, slo_class="interactive")
        assert len(fe.burst_futures) == 3

    def test_burst_point_is_registered(self):
        assert "burst_arrival" in faults.FAULT_POINTS


# ---------------------------------------------------------------------------
# observability surface
# ---------------------------------------------------------------------------


class TestSummary:
    def test_slo_section_in_summary(self):
        fe = SLOFrontend(StubEngine(), max_queue_total=0)
        fe.submit(PROMPT, slo_class="standard")  # sheds (queue bound 0)
        s = observe.summary()
        assert "slo" in s
        assert s["slo"]["state"] in (0, 1, 2)
        assert s["slo"]["shed"].get("standard/queue_full") == 1
        assert "breaker_open" in s["slo"]

    def test_eagerly_registered_metric_names(self):
        rendered = observe.metrics().render_prometheus()
        for name in ("dl4j_tpu_slo_state", "dl4j_tpu_slo_breaker_open",
                     "dl4j_tpu_slo_admitted_total", "dl4j_tpu_slo_shed_total",
                     "dl4j_tpu_slo_degraded_total",
                     "dl4j_tpu_slo_transitions_total"):
            assert name in rendered


# ---------------------------------------------------------------------------
# GL010 hygiene (satellite): serving timing is monotonic-only
# ---------------------------------------------------------------------------


class TestWallClockHygiene:
    def test_serving_sources_never_call_wall_clock(self):
        """``time.time()`` anywhere in serving/ would let a wall-clock
        jump expire deadlines or corrupt TTFT — the timing contract is
        perf_counter only (scheduler docstring, graftlint GL010)."""
        import deeplearning4j_tpu.serving as serving_pkg
        import glob
        import os
        pkg_dir = os.path.dirname(serving_pkg.__file__)
        for path in sorted(glob.glob(os.path.join(pkg_dir, "*.py"))):
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
            assert "time.time(" not in src, (
                f"{os.path.basename(path)} uses wall-clock time.time(); "
                f"serving timing must be time.perf_counter (GL010)")

    def test_serving_is_gl010_lint_clean(self):
        """The real linter, rule GL010 only, over the serving package —
        a regression reintroducing wall-clock durations fails here
        without waiting for the repo-wide lint gate."""
        from deeplearning4j_tpu.lint.core import lint_paths
        import deeplearning4j_tpu
        import os
        repo_root = os.path.dirname(
            os.path.dirname(os.path.abspath(deeplearning4j_tpu.__file__)))
        findings = lint_paths(["deeplearning4j_tpu/serving"], repo_root,
                              rules=["GL010"])
        assert not findings, [f"{f.path}:{f.line} {f.message}"
                              for f in findings]


# ---------------------------------------------------------------------------
# integration: real engine behind the frontend
# ---------------------------------------------------------------------------


class TestFrontendEngineIntegration:
    @staticmethod
    def _engine(**kw):
        from deeplearning4j_tpu.models.gpt import GptConfig, GptModel
        from deeplearning4j_tpu.serving import GenerativeEngine
        model = GptModel(GptConfig.tiny(), seed=1)
        kw.setdefault("max_slots", 2)
        kw.setdefault("page_size", 8)
        kw.setdefault("max_pages_per_seq", 6)
        kw.setdefault("max_prompt", 16)
        kw.setdefault("seed", 3)
        kw.setdefault("restart_backoff_s", 0.0)
        return GenerativeEngine(model, **kw)

    def test_priority_admission_order(self):
        """With one slot, a later-submitted interactive request admits
        BEFORE earlier batch requests — the pending queue is
        priority-ordered, not FIFO."""
        eng = self._engine(max_slots=1)
        fe = SLOFrontend(eng)
        done_order = []
        futs = []
        for i, cls in enumerate(["batch", "batch", "interactive"]):
            fut = fe.submit(PROMPT, slo_class=cls, max_new_tokens=2,
                            eos_token=-1)
            fut.add_done_callback(
                lambda _f, i=i: done_order.append(i))
            futs.append(fut)
        while eng.scheduler.has_work():
            eng.step()
        assert all(f.result(timeout=0).finish_reason == "length"
                   for f in futs)
        assert done_order[0] == 2  # interactive finished first

    def test_retry_preserves_class_and_submit_time(self):
        """A supervisor crash-retry re-queues the SAME request object:
        class, priority and submit time survive, so recovery re-admits
        it AHEAD of younger work and the result still carries its
        class."""
        eng = self._engine()
        fe = SLOFrontend(eng)
        # warm the compiled paths so the armed crash hits generation
        eng.generate([PROMPT[:2]], max_new_tokens=2, eos_token=-1)
        faults.arm("decode_step_error", prob=1.0, after_n=1, max_fires=1)
        eng.start()
        try:
            fut = fe.submit(PROMPT, slo_class="interactive",
                            max_new_tokens=6, eos_token=-1, max_retries=2)
            res = fut.result(timeout=600)
        finally:
            eng.stop()
        assert res.finish_reason == "length"
        assert res.slo_class == "interactive"
        assert eng.restarts == 1

    def test_threaded_overload_mixed_classes(self):
        """Satellite: saturate a tiny engine with mixed-class traffic.
        (a) every request reaches a terminal state; (b) interactive p99
        TTFT stays under its SLO while batch sheds; (c) ZERO new_shape
        recompiles across all degradation transitions."""
        eng = self._engine(max_slots=2)
        fe = SLOFrontend(
            eng,
            thresholds=LadderThresholds(degraded_queue=3, shedding_queue=8),
            max_queue_total=8,
            degraded_max_new_tokens=2,
            classes={
                "interactive": ClassPolicy("interactive", priority=0,
                                           degradable=False),
                "batch": ClassPolicy("batch", priority=2, max_queued=4,
                                     reject_in_shedding=True),
            })
        eng.generate([PROMPT[:2]], max_new_tokens=2, eos_token=-1)  # warm
        new_shape_before = sum(
            1 for e in observe.ledger().events()
            if e.graph == "serving" and e.cause == "new_shape")
        eng.start()
        inter_futs, batch_futs = [], []
        stop_flood = threading.Event()

        def flood_batch():
            r = np.random.RandomState(7)
            while not stop_flood.is_set():
                p = r.randint(1, 50, size=3).astype(np.int32)
                batch_futs.append(
                    fe.submit(p, slo_class="batch", max_new_tokens=8,
                              eos_token=-1))
                time.sleep(0.002)

        flooder = threading.Thread(target=flood_batch, daemon=True)
        try:
            flooder.start()
            r = np.random.RandomState(11)
            for _ in range(12):
                p = r.randint(1, 50, size=3).astype(np.int32)
                inter_futs.append(
                    fe.submit(p, slo_class="interactive", max_new_tokens=4,
                              eos_token=-1))
                time.sleep(0.05)
            stop_flood.set()
            flooder.join(timeout=30)
            inter_res = [f.result(timeout=600) for f in inter_futs]
            batch_res = [f.result(timeout=600) for f in batch_futs]
        finally:
            stop_flood.set()
            eng.stop()
        # (a) every request terminal
        assert all(f.done() for f in inter_futs + batch_futs)
        assert all(r.finish_reason in FINISH_REASONS
                   for r in inter_res + batch_res)
        # (b) interactive served within SLO while batch shed under
        # pressure; interactive is never degraded
        ttfts = sorted(r.ttft_s for r in inter_res if r.ttft_s is not None)
        assert len(ttfts) == len(inter_res), \
            "an interactive request was shed"
        p99 = ttfts[min(len(ttfts) - 1, int(0.99 * len(ttfts)))]
        assert p99 < 2.0, f"interactive p99 TTFT {p99:.3f}s blew the SLO"
        assert not any(r.degraded for r in inter_res)
        shed_batch = sum(1 for r in batch_res if r.finish_reason == "shed")
        assert shed_batch > 0, "batch flood never shed — not overloaded"
        # the ladder actually moved
        assert "degraded" in fe.states_visited
        # (c) zero new_shape across every transition the run produced
        new_shape_after = sum(
            1 for e in observe.ledger().events()
            if e.graph == "serving" and e.cause == "new_shape")
        assert new_shape_after - new_shape_before == 0

    def test_invalid_arrival_never_displaces_a_victim(self):
        """Validation runs BEFORE the shed-lowest-first steal: an
        over-long prompt raises to its caller without destroying the
        queued batch request it would have displaced."""
        eng = self._engine(max_prompt=16)
        fe = SLOFrontend(eng, max_queue_total=1)
        batch_fut = fe.submit(PROMPT, slo_class="batch", eos_token=-1)
        with pytest.raises(ValueError, match="max_prompt"):
            fe.submit(np.arange(1, 30, dtype=np.int32),
                      slo_class="interactive", eos_token=-1)
        assert not batch_fut.done()  # the victim survived
        assert len(eng.scheduler.pending) == 1

    def test_breaker_threshold_scales_to_engine_restart_budget(self):
        """A fixed threshold above engine.max_restarts would be dead code
        — the supervisor fail_alls before the breaker could ever open."""
        eng = self._engine(max_restarts=3)
        fe = SLOFrontend(eng)
        assert fe.breaker_restarts == 3

    def test_engine_submit_accepts_class_kwargs(self):
        """Plain engine.submit carries class labels through to results
        (the frontend-free path keeps the taxonomy)."""
        eng = self._engine()
        res = eng.generate([PROMPT], max_new_tokens=2, eos_token=-1,
                           slo_class="batch", priority=2)
        assert res[0].slo_class == "batch"
        assert res[0].finish_reason == "length"
