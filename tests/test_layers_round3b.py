"""Layer-catalog breadth, continued: padding/cropping/upsampling families,
1-D pooling, Deconvolution3D, CNN/RNN loss layers, masking utilities,
RepeatVector, ElementWiseMultiplication, FrozenLayerWithBackprop,
CenterLossOutputLayer, Yolo2OutputLayer, and the CapsNet trio.

Pattern per SURVEY §5.2: every parameterized layer gets a gradient check;
shape/semantics tests for the rest; JSON round-trip for every new conf."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.autodiff.gradcheck import check_gradients, check_gradients_fn
from deeplearning4j_tpu.nn import conf as C
from deeplearning4j_tpu.ops.losses import get_loss


from tests._helpers import _mln, _rng


class TestPadCropUpsample:
    def test_zero_padding_1d(self):
        net = _mln([nn.ZeroPadding1DLayer(padding=(2, 1))],
                   nn.InputType.recurrent(3, 5))
        x = _rng(0).randn(2, 5, 3).astype(np.float32)
        out = net.output(x)
        assert out.shape == (2, 8, 3)
        np.testing.assert_allclose(out[:, 2:7], x)
        assert np.all(out[:, :2] == 0) and np.all(out[:, 7:] == 0)

    def test_zero_padding_2d_and_crop(self):
        net = _mln([
            nn.ZeroPaddingLayer(padding=(1, 2, 3, 4)),
            nn.Cropping2D(cropping=(1, 2, 3, 4)),
        ], nn.InputType.convolutional(5, 6, 2))
        x = _rng(1).randn(2, 5, 6, 2).astype(np.float32)
        out = net.output(x)
        np.testing.assert_allclose(out, x)  # pad then crop = identity

    def test_zero_padding_3d_and_crop(self):
        net = _mln([
            nn.ZeroPadding3DLayer(padding=(1, 1, 2, 0, 0, 2)),
            nn.Cropping3D(cropping=(1, 1, 2, 0, 0, 2)),
        ], nn.InputType.convolutional3d(3, 4, 5, 2))
        x = _rng(2).randn(2, 3, 4, 5, 2).astype(np.float32)
        np.testing.assert_allclose(net.output(x), x)

    def test_cropping_1d(self):
        net = _mln([nn.Cropping1D(cropping=(1, 2))],
                   nn.InputType.recurrent(3, 7))
        x = _rng(3).randn(2, 7, 3).astype(np.float32)
        np.testing.assert_allclose(net.output(x), x[:, 1:5])

    def test_upsampling_1d(self):
        net = _mln([nn.Upsampling1D(size=3)], nn.InputType.recurrent(2, 4))
        x = _rng(4).randn(1, 4, 2).astype(np.float32)
        out = net.output(x)
        assert out.shape == (1, 12, 2)
        np.testing.assert_allclose(out[0, :3], np.repeat(x[0, :1], 3, axis=0))

    def test_upsampling_3d(self):
        net = _mln([nn.Upsampling3D(size=(2, 1, 2))],
                   nn.InputType.convolutional3d(2, 3, 2, 1))
        x = _rng(5).randn(1, 2, 3, 2, 1).astype(np.float32)
        out = net.output(x)
        assert out.shape == (1, 4, 3, 4, 1)
        np.testing.assert_allclose(out[0, 0], out[0, 1])


class TestPool1dDeconv3d:
    def test_subsampling_1d_max(self):
        net = _mln([nn.Subsampling1DLayer(kernel=2, stride=2,
                                          pooling_type="max")],
                   nn.InputType.recurrent(2, 6))
        x = _rng(0).randn(2, 6, 2).astype(np.float32)
        out = net.output(x)
        assert out.shape == (2, 3, 2)
        np.testing.assert_allclose(out, np.maximum(x[:, ::2], x[:, 1::2]),
                                   rtol=1e-6)

    def test_subsampling_1d_avg_gradcheck(self):
        net = _mln([
            nn.Convolution1D(n_out=4, kernel=3, convolution_mode="same",
                             activation="tanh"),
            nn.Subsampling1DLayer(kernel=2, stride=2, pooling_type="avg"),
            nn.GlobalPoolingLayer(pooling_type="avg"),
            nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], nn.InputType.recurrent(3, 6))
        r = _rng(1)
        x = r.randn(2, 6, 3)
        y = np.eye(2)[r.randint(0, 2, 2)]
        assert check_gradients(net, x, y)

    def test_deconvolution_3d(self):
        net = _mln([
            nn.Deconvolution3D(n_in=2, n_out=3, kernel=(2, 2, 2),
                               stride=(2, 2, 2), activation="tanh"),
            nn.GlobalPoolingLayer(pooling_type="avg"),
            nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], nn.InputType.convolutional3d(2, 2, 2, 2))
        r = _rng(2)
        x = r.randn(2, 2, 2, 2, 2)
        out = net.feed_forward(x.astype(np.float32))[0]
        assert out.shape == (2, 4, 4, 4, 3)
        y = np.eye(2)[r.randint(0, 2, 2)]
        assert check_gradients(net, x, y)


class TestLossLayers:
    def test_cnn_loss_layer(self):
        net = _mln([
            nn.ConvolutionLayer(n_out=3, kernel=(1, 1),
                                convolution_mode="same", activation="identity"),
            nn.CnnLossLayer(activation="softmax", loss="mcxent"),
        ], nn.InputType.convolutional(4, 4, 2))
        r = _rng(0)
        x = r.randn(2, 4, 4, 2)
        y = np.eye(3)[r.randint(0, 3, (2, 4, 4))]
        assert check_gradients(net, x, y)

    def test_rnn_loss_layer(self):
        net = _mln([
            nn.SimpleRnn(n_out=4, activation="tanh"),
            nn.RnnLossLayer(activation="softmax", loss="mcxent"),
        ], nn.InputType.recurrent(3, 5))
        r = _rng(1)
        x = r.randn(2, 5, 3)
        y = np.eye(4)[r.randint(0, 4, (2, 5))]
        assert check_gradients(net, x, y)


class TestMaskingUtility:
    def test_mask_layer(self):
        net = _mln([nn.MaskLayer()], nn.InputType.recurrent(2, 4))
        x = np.ones((1, 4, 2), np.float32)
        # un-masked: passthrough
        np.testing.assert_allclose(net.output(x), x)

    def test_mask_zero_layer(self):
        inner = nn.SimpleRnn(n_in=2, n_out=3, activation="tanh")
        net = _mln([nn.MaskZeroLayer(underlying=inner, mask_value=0.0)],
                   nn.InputType.recurrent(2, 4))
        x = _rng(0).randn(1, 4, 2).astype(np.float32)
        x[0, 2:] = 0.0  # steps 2,3 are all-mask_value -> masked out
        out = net.output(x)
        assert out.shape == (1, 4, 3)

    def test_repeat_vector(self):
        net = _mln([nn.RepeatVector(n=5)], nn.InputType.feed_forward(3))
        x = _rng(1).randn(2, 3).astype(np.float32)
        out = net.output(x)
        assert out.shape == (2, 5, 3)
        for t in range(5):
            np.testing.assert_allclose(out[:, t], x)

    def test_elementwise_multiplication_gradcheck(self):
        net = _mln([
            nn.ElementWiseMultiplicationLayer(n_in=4, n_out=4, activation="tanh"),
            nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], nn.InputType.feed_forward(4))
        r = _rng(2)
        x = r.randn(3, 4)
        y = np.eye(2)[r.randint(0, 2, 3)]
        assert check_gradients(net, x, y)


class TestFrozenWithBackprop:
    def test_frozen_params_fixed_but_gradient_flows(self):
        inner = nn.DenseLayer(n_in=3, n_out=4, activation="tanh")
        net = _mln([
            nn.DenseLayer(n_out=3, activation="tanh"),
            nn.FrozenLayerWithBackprop(underlying=inner),
            nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], nn.InputType.feed_forward(3))
        r = _rng(0)
        x = r.randn(8, 3).astype(np.float32)
        y = np.eye(2)[r.randint(0, 2, 8)].astype(np.float32)
        frozen_before = np.asarray(net.params[1]["inner"]["W"]).copy()
        first_before = np.asarray(net.params[0]["W"]).copy()
        net.fit(x, y, epochs=2, batch_size=4)
        frozen_after = np.asarray(net.params[1]["inner"]["W"])
        first_after = np.asarray(net.params[0]["W"])
        np.testing.assert_allclose(frozen_before, frozen_after)  # frozen
        assert np.abs(first_before - first_after).max() > 1e-6   # still learns


class TestCenterLoss:
    def test_center_loss_trains_centers_and_features(self):
        net = _mln([
            nn.DenseLayer(n_out=4, activation="tanh"),
            nn.CenterLossOutputLayer(n_out=3, activation="softmax",
                                     loss="mcxent", lambda_=0.5),
        ], nn.InputType.feed_forward(5))
        r = _rng(0)
        x = r.randn(9, 5).astype(np.float32)
        y = np.eye(3)[r.randint(0, 3, 9)].astype(np.float32)
        centers_before = np.asarray(net.params[-1]["centers"]).copy()
        net.fit(x, y, epochs=3, batch_size=3)
        centers_after = np.asarray(net.params[-1]["centers"])
        # the center term's gradient λ(c_y − f) must move the centers
        assert np.abs(centers_after - centers_before).max() > 1e-6

    def test_center_loss_alpha_lambda_semantics(self):
        """The decoupled objective's gradients: centers feel α(c_y − f̄)
        exactly (closed form), and α=0 freezes the centers entirely."""
        def build(alpha, lam):
            net = _mln([
                nn.DenseLayer(n_out=4, activation="tanh"),
                nn.CenterLossOutputLayer(n_out=3, activation="softmax",
                                         loss="mcxent", alpha=alpha,
                                         lambda_=lam),
            ], nn.InputType.feed_forward(5))
            r = _rng(1)
            net.params[-1]["centers"] = jnp.asarray(r.randn(3, 4) * 0.1)
            return net, r

        net, r = build(alpha=0.2, lam=0.3)
        x = r.randn(6, 5).astype(np.float32)
        y = np.eye(3)[r.randint(0, 3, 6)].astype(np.float32)
        lc = net.conf.layers[-1]

        # mirror of the train-step objective (see _make_train_step)
        def loss_fn(params):
            out, _, feats = net._forward(params, net.net_state,
                                         jnp.asarray(x), None, train=False,
                                         rng=None,
                                         tap_input_of=len(net.layers) - 1)
            base = net._loss_from_out(out, jnp.asarray(y), None)
            sg = jax.lax.stop_gradient
            c = params[-1]["centers"]
            idx = jnp.argmax(jnp.asarray(y), axis=-1)
            d_feat = feats - sg(c[idx])
            d_ctr = sg(feats) - c[idx]
            return (base
                    + 0.5 * lc.lambda_ * jnp.mean(jnp.sum(d_feat ** 2, -1))
                    + 0.5 * lc.alpha * jnp.mean(jnp.sum(d_ctr ** 2, -1)))

        g = jax.grad(loss_fn)(net.params)
        # closed form: ∂/∂c_k = α/N · Σ_{i: y_i=k} (c_k − f_i)
        feats = np.asarray(net.feed_forward(x)[0])
        centers = np.asarray(net.params[-1]["centers"])
        idx = y.argmax(-1)
        want = np.zeros_like(centers)
        for i, k in enumerate(idx):
            want[k] += lc.alpha / len(idx) * (centers[k] - feats[i])
        np.testing.assert_allclose(np.asarray(g[-1]["centers"]), want,
                                   rtol=1e-5, atol=1e-6)

        # α=0 freezes the centers through a real fit
        net0, r0 = build(alpha=0.0, lam=0.3)
        c_before = np.asarray(net0.params[-1]["centers"]).copy()
        net0.fit(x, y, epochs=2, batch_size=3)
        np.testing.assert_allclose(np.asarray(net0.params[-1]["centers"]),
                                   c_before)


class TestYolo2Output:
    def test_yolo2_loss_decreases(self):
        b, cls = 2, 3  # 2 anchor boxes, 3 classes
        builder = (nn.builder().seed(7)
                   .updater(nn.Adam(learning_rate=1e-3)).list())
        for lc in [
            nn.ConvolutionLayer(n_out=8, kernel=(3, 3),
                                convolution_mode="same", activation="relu"),
            nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)),
            nn.ConvolutionLayer(n_out=b * (5 + cls), kernel=(1, 1),
                                convolution_mode="same",
                                activation="identity"),
            nn.Yolo2OutputLayer(anchors=((1.0, 1.0), (2.0, 2.0))),
        ]:
            builder.layer(lc)
        net = nn.MultiLayerNetwork(
            builder.set_input_type(nn.InputType.convolutional(8, 8, 3))
            .build()).init()
        r = _rng(0)
        x = r.randn(2, 8, 8, 3).astype(np.float32)
        t = np.zeros((2, 4, 4, b, 5 + cls), np.float32)
        t[0, 1, 1, 0] = [0.5, 0.5, 0.3, 0.3, 1.0, 1, 0, 0]
        t[1, 2, 3, 1] = [0.2, 0.7, 0.5, 0.2, 1.0, 0, 0, 1]
        scores = []
        for _ in range(12):
            net.fit(x, t, batch_size=2)
            scores.append(net.score())
        assert np.isfinite(scores[-1]) and scores[-1] < scores[0]

    def test_yolo2_loss_fn_direct(self):
        fn = get_loss("yolo2")
        r = _rng(1)
        pred = jnp.asarray(r.randn(2, 4, 4, 2 * 8).astype(np.float32))
        target = jnp.asarray(np.zeros((2, 4, 4, 2, 8), np.float32))
        val = float(fn(pred, target, None))
        assert np.isfinite(val) and val > 0  # no-object penalty is positive


class TestCapsules:
    def test_capsnet_forward_and_squash_norm(self):
        net = _mln([
            nn.PrimaryCapsules(capsules=4, capsule_dim=6, kernel=(3, 3),
                               stride=(2, 2)),
            nn.CapsuleLayer(capsules=3, capsule_dim=4, routings=3),
            nn.CapsuleStrengthLayer(),
        ], nn.InputType.convolutional(9, 9, 2))
        x = _rng(0).randn(2, 9, 9, 2).astype(np.float32)
        out = net.output(x)
        assert out.shape == (2, 3)
        # capsule strengths are squash norms: bounded to [0, 1)
        assert np.all(out >= 0) and np.all(out < 1.0)

    def test_capsnet_gradcheck(self):
        # routings=1: no routing-agreement update, so the analytic gradient
        # is exact. (With routings>1 the coupling logits are detached —
        # standard CapsNet practice — and finite differences legitimately
        # see the extra path the analytic gradient intentionally ignores.)
        net = _mln([
            nn.PrimaryCapsules(capsules=2, capsule_dim=4, kernel=(3, 3),
                               stride=(2, 2)),
            nn.CapsuleLayer(capsules=2, capsule_dim=3, routings=1),
            nn.CapsuleStrengthLayer(),
            nn.LossLayer(activation="identity", loss="mse"),
        ], nn.InputType.convolutional(5, 5, 1))
        r = _rng(1)
        x = r.randn(2, 5, 5, 1)
        y = r.rand(2, 2)
        assert check_gradients(net, x, y, max_per_param=10)


class TestSerdeRoundTrip:
    def test_all_new_confs_round_trip(self):
        confs = [
            nn.ZeroPadding1DLayer(padding=(2, 1)),
            nn.ZeroPaddingLayer(padding=(1, 2, 3, 4)),
            nn.ZeroPadding3DLayer(padding=(1, 1, 2, 0, 0, 2)),
            nn.Cropping1D(cropping=(1, 2)),
            nn.Cropping2D(cropping=(1, 2, 3, 4)),
            nn.Cropping3D(cropping=(1, 1, 0, 0, 2, 2)),
            nn.Upsampling1D(size=3),
            nn.Upsampling3D(size=(2, 1, 2)),
            nn.Subsampling1DLayer(kernel=3, stride=2, pooling_type="avg"),
            nn.Deconvolution3D(n_in=2, n_out=3, kernel=(2, 2, 2)),
            nn.CnnLossLayer(loss="mse"),
            nn.RnnLossLayer(loss="mcxent"),
            nn.MaskLayer(),
            nn.MaskZeroLayer(underlying=nn.SimpleRnn(n_in=2, n_out=3),
                             mask_value=0.0),
            nn.RepeatVector(n=4),
            nn.ElementWiseMultiplicationLayer(n_in=3, n_out=3),
            nn.FrozenLayerWithBackprop(
                underlying=nn.DenseLayer(n_in=3, n_out=4)),
            nn.CenterLossOutputLayer(n_in=4, n_out=3, alpha=0.1, lambda_=0.1),
            nn.Yolo2OutputLayer(anchors=((1.0, 2.0), (3.0, 4.0))),
            nn.PrimaryCapsules(capsules=4, capsule_dim=6),
            nn.CapsuleLayer(capsules=3, capsule_dim=4, routings=3),
            nn.CapsuleStrengthLayer(),
        ]
        import json
        for lc in confs:
            d = json.loads(json.dumps(lc.to_dict()))
            back = C.LayerConf.from_dict(d)
            assert type(back) is type(lc)
            d2 = back.to_dict()
            assert json.loads(json.dumps(d2)) == json.loads(json.dumps(d)) or \
                type(C.LayerConf.from_dict(d2)) is type(lc)

    def test_yolo2_conf_lambdas_are_wired(self):
        """The conf's lambda_coord/lambda_noobj/anchors must reach the loss
        (round-3b review finding): different lambdas ⇒ different score."""
        from deeplearning4j_tpu.ops.losses import yolo2
        r = _rng(3)
        pred = jnp.asarray(r.randn(1, 2, 2, 2 * 7).astype(np.float32))
        target = np.zeros((1, 2, 2, 2, 7), np.float32)
        target[0, 0, 0, 0] = [0.5, 0.5, 0.2, 0.2, 1.0, 1, 0]
        t = jnp.asarray(target)
        base = float(yolo2(pred, t, None))
        heavy = float(yolo2(pred, t, None, lambda_coord=50.0))
        assert heavy != base
        anchored = float(yolo2(pred, t, None, anchors=[[1.0, 1.0], [2.0, 2.0]]))
        assert anchored != base

        lc = nn.Yolo2OutputLayer(anchors=((1.0, 1.0), (2.0, 2.0)),
                                 lambda_coord=50.0)
        via_conf = float(lc.loss_fn()(pred, t, None))
        want = float(yolo2(pred, t, None, lambda_coord=50.0,
                           anchors=[[1.0, 1.0], [2.0, 2.0]]))
        assert abs(via_conf - want) < 1e-6
