"""Keras import golden-file tests — the reference modelimport pattern
(SURVEY §5.4): build with in-env keras, import, compare outputs elementwise."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
from tensorflow import keras

from deeplearning4j_tpu.imports.keras_import import (
    import_keras_model, import_keras_sequential_model_and_weights,
)


def assert_outputs_match(model, net, x, rtol=1e-4, atol=1e-5):
    golden = model(x, training=False).numpy()
    got = net.output(x)
    np.testing.assert_allclose(got, golden, rtol=rtol, atol=atol)


class TestKerasImport:
    def test_mlp(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((6,)),
            tf.keras.layers.Dense(12, activation="relu"),
            tf.keras.layers.Dense(3, activation="softmax"),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(0).randn(4, 6).astype(np.float32)
        assert_outputs_match(model, net, x)

    def test_cnn_with_flatten(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((12, 12, 3)),
            tf.keras.layers.Conv2D(8, 3, activation="relu", padding="same"),
            tf.keras.layers.MaxPooling2D(2),
            tf.keras.layers.Conv2D(4, 3, activation="relu"),
            tf.keras.layers.Flatten(),
            tf.keras.layers.Dense(5, activation="softmax"),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(1).rand(2, 12, 12, 3).astype(np.float32)
        assert_outputs_match(model, net, x)

    def test_batchnorm_inference(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((5,)),
            tf.keras.layers.Dense(8),
            tf.keras.layers.BatchNormalization(),
            tf.keras.layers.Activation("relu"),
            tf.keras.layers.Dense(2, activation="softmax"),
        ])
        # train briefly so BN stats are non-trivial
        model.compile(optimizer="sgd", loss="categorical_crossentropy")
        rng = np.random.RandomState(2)
        model.fit(rng.randn(64, 5).astype(np.float32),
                  np.eye(2, dtype=np.float32)[rng.randint(0, 2, 64)],
                  epochs=1, verbose=0)
        net = import_keras_model(model)
        x = rng.randn(4, 5).astype(np.float32)
        assert_outputs_match(model, net, x, rtol=1e-3, atol=1e-4)

    def test_dropout_imported_as_eval_identity(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((4,)),
            tf.keras.layers.Dense(6, activation="tanh"),
            tf.keras.layers.Dropout(0.5),
            tf.keras.layers.Dense(2, activation="softmax"),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(3).randn(3, 4).astype(np.float32)
        assert_outputs_match(model, net, x)

    def test_lstm(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((7, 4)),
            tf.keras.layers.LSTM(6, return_sequences=True),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(4).randn(2, 7, 4).astype(np.float32)
        assert_outputs_match(model, net, x, rtol=1e-3, atol=1e-4)

    def test_global_average_pooling(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((8, 8, 2)),
            tf.keras.layers.Conv2D(4, 3, padding="same", activation="relu"),
            tf.keras.layers.GlobalAveragePooling2D(),
            tf.keras.layers.Dense(3, activation="softmax"),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(5).rand(2, 8, 8, 2).astype(np.float32)
        assert_outputs_match(model, net, x)

    def test_h5_file_round_trip(self, tmp_path):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((6,)),
            tf.keras.layers.Dense(4, activation="relu"),
            tf.keras.layers.Dense(2, activation="softmax"),
        ])
        path = str(tmp_path / "model.keras")
        model.save(path)
        net = import_keras_sequential_model_and_weights(path)
        x = np.random.RandomState(6).randn(3, 6).astype(np.float32)
        assert_outputs_match(model, net, x)

    def test_unsupported_layer_raises(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((4, 4)),
            tf.keras.layers.LocallyConnected1D(2, 2)
            if hasattr(tf.keras.layers, "LocallyConnected1D")
            else tf.keras.layers.Lambda(lambda t: t),
        ])
        with pytest.raises(NotImplementedError,
                           match="LocallyConnected1D|Lambda"):
            import_keras_model(model)


class TestKerasOwnH5:
    """Round 3: own HDF5 parsing (no tf.keras deserialization) + functional
    API → ComputationGraph — KerasModelImport.importKerasModelAndWeights."""

    def test_sequential_own_h5_golden(self, tmp_path):
        from deeplearning4j_tpu.imports.keras_import import (
            import_keras_model_and_weights, read_keras_h5)

        rng = np.random.RandomState(0)
        model = keras.Sequential([
            keras.layers.Input((12,)),
            keras.layers.Dense(8, activation="relu"),
            keras.layers.Dense(4, activation="softmax"),
        ])
        path = str(tmp_path / "seq.h5")
        model.save(path)
        config, weights = read_keras_h5(path)
        assert config["class_name"] == "Sequential"
        net = import_keras_model_and_weights(path)
        x = rng.randn(6, 12).astype(np.float32)
        golden = model.predict(x, verbose=0)
        np.testing.assert_allclose(net.output(x), golden, rtol=1e-4, atol=1e-5)

    def test_functional_resnet_ish_golden(self, tmp_path):
        """Functional graph with a residual Add and a Concatenate — the
        'functional ResNet-ish golden import' from the round-2 verdict."""
        from deeplearning4j_tpu.imports.keras_import import (
            import_keras_model_and_weights)

        rng = np.random.RandomState(1)
        inp = keras.Input((16, 16, 3), name="img")
        c1 = keras.layers.Conv2D(8, 3, padding="same", activation="relu",
                                 name="c1")(inp)
        c2 = keras.layers.Conv2D(8, 3, padding="same", name="c2")(c1)
        add = keras.layers.Add(name="res_add")([c1, c2])
        act = keras.layers.ReLU(name="res_act")(add)
        p = keras.layers.MaxPooling2D(2, name="pool")(act)
        br1 = keras.layers.Conv2D(4, 1, activation="relu", name="br1")(p)
        br2 = keras.layers.DepthwiseConv2D(3, padding="same", name="br2")(p)
        cat = keras.layers.Concatenate(name="cat")([br1, br2])
        gap = keras.layers.GlobalAveragePooling2D(name="gap")(cat)
        out = keras.layers.Dense(5, activation="softmax", name="logits")(gap)
        model = keras.Model(inp, out)

        path = str(tmp_path / "func.h5")
        model.save(path)
        net = import_keras_model_and_weights(path)
        x = rng.rand(2, 16, 16, 3).astype(np.float32)
        golden = model.predict(x, verbose=0)
        got = net.output(x)[0]
        np.testing.assert_allclose(got, golden, rtol=1e-4, atol=1e-5)

    def test_functional_flatten_dense_golden(self, tmp_path):
        from deeplearning4j_tpu.imports.keras_import import (
            import_keras_model_and_weights)

        rng = np.random.RandomState(2)
        inp = keras.Input((6, 6, 2), name="x")
        c = keras.layers.Conv2D(3, 3, activation="tanh", name="conv")(inp)
        f = keras.layers.Flatten(name="flat")(c)
        out = keras.layers.Dense(4, name="fc")(f)
        model = keras.Model(inp, out)
        path = str(tmp_path / "flat.h5")
        model.save(path)
        net = import_keras_model_and_weights(path)
        x = rng.rand(3, 6, 6, 2).astype(np.float32)
        golden = model.predict(x, verbose=0)
        np.testing.assert_allclose(net.output(x)[0], golden, rtol=1e-4,
                                   atol=1e-5)

    def test_widened_sequential_layers_golden(self, tmp_path):
        from deeplearning4j_tpu.imports.keras_import import (
            import_keras_model_and_weights)

        rng = np.random.RandomState(3)
        model = keras.Sequential([
            keras.layers.Input((10, 10, 3)),
            keras.layers.SeparableConv2D(6, 3, activation="relu"),
            keras.layers.UpSampling2D(2),
            keras.layers.DepthwiseConv2D(3),
            keras.layers.LeakyReLU(negative_slope=0.3),
            keras.layers.GlobalMaxPooling2D(),
            keras.layers.Dense(4, activation="softmax"),
        ])
        path = str(tmp_path / "widened.h5")
        model.save(path)
        net = import_keras_model_and_weights(path)
        x = rng.rand(2, 10, 10, 3).astype(np.float32)
        golden = model.predict(x, verbose=0)
        np.testing.assert_allclose(net.output(x), golden, rtol=1e-4, atol=1e-5)

    def test_conv1d_prelu_pool_golden(self, tmp_path):
        from deeplearning4j_tpu.imports.keras_import import (
            import_keras_model_and_weights)

        rng = np.random.RandomState(4)
        model = keras.Sequential([
            keras.layers.Input((12, 3)),
            keras.layers.Conv1D(6, 3, activation="tanh", padding="same"),
            keras.layers.PReLU(shared_axes=[1]),
            keras.layers.GlobalAveragePooling1D(),
            keras.layers.Dense(4, activation="softmax"),
        ])
        # nudge PReLU alphas off their init so the import actually carries them
        ws = model.layers[1].get_weights()
        model.layers[1].set_weights([np.abs(rng.rand(*ws[0].shape)) * 0.5])
        path = str(tmp_path / "c1d.h5")
        model.save(path)
        net = import_keras_model_and_weights(path)
        x = rng.rand(3, 12, 3).astype(np.float32)
        golden = model.predict(x, verbose=0)
        np.testing.assert_allclose(net.output(x), golden, rtol=1e-4, atol=1e-5)

    def test_conv3d_pool3d_golden(self, tmp_path):
        from deeplearning4j_tpu.imports.keras_import import (
            import_keras_model_and_weights)

        rng = np.random.RandomState(5)
        model = keras.Sequential([
            keras.layers.Input((6, 8, 8, 2)),
            keras.layers.Conv3D(4, 3, activation="relu", padding="valid"),
            keras.layers.MaxPooling3D(2),
            keras.layers.Flatten(),
            keras.layers.Dense(3, activation="softmax"),
        ])
        path = str(tmp_path / "c3d.h5")
        model.save(path)
        net = import_keras_model_and_weights(path)
        x = rng.rand(2, 6, 8, 8, 2).astype(np.float32)
        golden = model.predict(x, verbose=0)
        np.testing.assert_allclose(net.output(x), golden, rtol=1e-4, atol=1e-5)


class TestKerasImportRound3b:
    """Golden tests for the pad/crop/upsample/1-D-pool/transpose/utility
    mappers added with the round-3b layer catalog."""

    def test_pad_crop_upsample_2d_golden(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((8, 8, 2)),
            tf.keras.layers.ZeroPadding2D(((1, 2), (3, 4))),
            tf.keras.layers.Cropping2D(((1, 2), (3, 4))),
            tf.keras.layers.UpSampling2D(2),
            tf.keras.layers.Conv2D(3, 3, activation="relu"),
            tf.keras.layers.GlobalAveragePooling2D(),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(0).rand(2, 8, 8, 2).astype(np.float32)
        assert_outputs_match(model, net, x)

    def test_temporal_pipeline_golden(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((10, 4)),
            tf.keras.layers.ZeroPadding1D((1, 2)),
            tf.keras.layers.Conv1D(6, 3, activation="tanh"),
            tf.keras.layers.MaxPooling1D(2),
            tf.keras.layers.Cropping1D((0, 1)),
            tf.keras.layers.UpSampling1D(2),
            tf.keras.layers.AveragePooling1D(2),
            tf.keras.layers.GlobalMaxPooling1D(),
            tf.keras.layers.Dense(3, activation="softmax"),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(1).randn(3, 10, 4).astype(np.float32)
        assert_outputs_match(model, net, x)

    def test_pad_crop_3d_golden(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((4, 4, 4, 2)),
            tf.keras.layers.ZeroPadding3D(((1, 1), (0, 2), (2, 0))),
            tf.keras.layers.Cropping3D(((1, 1), (0, 2), (2, 0))),
            tf.keras.layers.Conv3D(3, 2, activation="relu"),
            tf.keras.layers.GlobalAveragePooling3D(),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(2).rand(2, 4, 4, 4, 2).astype(np.float32)
        assert_outputs_match(model, net, x)

    def test_conv3d_transpose_golden(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((3, 3, 3, 2)),
            tf.keras.layers.Conv3DTranspose(4, 2, strides=2,
                                            activation="tanh"),
            tf.keras.layers.GlobalMaxPooling3D(),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(3).randn(2, 3, 3, 3, 2).astype(np.float32)
        assert_outputs_match(model, net, x)

    def test_repeat_vector_timedistributed_golden(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((5,)),
            tf.keras.layers.Dense(4, activation="relu"),
            tf.keras.layers.RepeatVector(6),
            tf.keras.layers.TimeDistributed(
                tf.keras.layers.Dense(3, activation="softmax")),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(4).randn(3, 5).astype(np.float32)
        assert_outputs_match(model, net, x)

    def test_masking_and_noise_inference_golden(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((6, 3)),
            tf.keras.layers.Masking(mask_value=0.0),
            tf.keras.layers.GaussianNoise(0.5),
            tf.keras.layers.SimpleRNN(5, activation="tanh",
                                      return_sequences=False),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(5).randn(2, 6, 3).astype(np.float32)
        x[:, 4:, :] = 0.0  # masked tail
        golden = model(x, training=False).numpy()
        # our SimpleRnn returns the full sequence; keras returns last step.
        got = net.output(x)
        if got.ndim == 3:
            got = got[:, -1]  # but masked: the LAST VALID step
        # keras masking makes the RNN skip masked steps, carrying the state
        # from step 3 — our masked scan does the same, so last-step state
        # must match
        np.testing.assert_allclose(got[:, :], golden, rtol=1e-4, atol=1e-5)

    def test_spatial_dropout_variants_identity_at_inference(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((8, 4)),
            tf.keras.layers.SpatialDropout1D(0.4),
            tf.keras.layers.Conv1D(3, 3, padding="same"),
            tf.keras.layers.GlobalAveragePooling1D(),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(6).randn(2, 8, 4).astype(np.float32)
        assert_outputs_match(model, net, x)
