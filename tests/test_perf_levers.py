"""Round-5 perf levers: s2d stem exactness, fused conv+BN Pallas kernel.

The levers must be *mathematically exact* rewrites — every test here checks
the optimized path against the canonical one, not against golden numbers.
"""

import numpy as np

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import nn

from tests._helpers import _mln, _rng


class TestS2DStem:
    """ConvolutionLayer(s2d_stem=True): 7×7/2 'same' conv lowered over a 2×2
    space-to-depth input (MLPerf ResNet stem trick) must match the plain
    lowering bit-for-bit up to fp reassociation."""

    def _nets(self, h=32, w=32):
        def mk(s2d):
            return _mln([
                nn.ConvolutionLayer(n_out=16, kernel=(7, 7), stride=(2, 2),
                                    convolution_mode="same", has_bias=False,
                                    activation="identity", s2d_stem=s2d),
                nn.GlobalPoolingLayer(pooling_type="avg"),
                nn.OutputLayer(n_out=5, activation="softmax", loss="mcxent"),
            ], nn.InputType.convolutional(h, w, 3))
        a, b = mk(False), mk(True)
        b.params = jax.tree.map(jnp.array, a.params)  # copy (donation-safe)
        return a, b

    def test_forward_matches_plain_conv(self):
        a, b = self._nets()
        x = _rng(0).randn(4, 32, 32, 3).astype(np.float32)
        np.testing.assert_allclose(a.output(x), b.output(x), atol=1e-5)

    def test_train_step_matches_plain_conv(self):
        a, b = self._nets()
        r = _rng(1)
        x = r.randn(4, 32, 32, 3).astype(np.float32)
        y = np.eye(5)[r.randint(0, 5, 4)].astype(np.float32)
        a.fit(x, y)
        b.fit(x, y)
        diffs = jax.tree.map(
            lambda p, q: float(jnp.max(jnp.abs(p - q))), a.params, b.params)
        assert jax.tree.reduce(max, diffs) < 1e-5

    def test_odd_input_falls_back(self):
        # odd spatial dims can't space-to-depth; the layer must fall back to
        # the plain conv path rather than mis-shape
        a, b = self._nets(h=31, w=31)
        x = _rng(2).randn(2, 31, 31, 3).astype(np.float32)
        np.testing.assert_allclose(a.output(x), b.output(x), atol=1e-5)

    def test_json_roundtrip(self):
        lc = nn.ConvolutionLayer(n_out=8, kernel=(7, 7), stride=(2, 2),
                                 convolution_mode="same", s2d_stem=True)
        from deeplearning4j_tpu.nn import conf as C
        d = lc.to_dict()
        back = C.LayerConf.from_dict(d)
        assert back.s2d_stem is True


class TestFusedBnMatmulStats:
    """Pallas fused BN-apply → matmul → shifted-stats kernel (interpret mode
    on the CPU mesh; the real-chip timing lives in
    tools/bench_convbn_fusion.py)."""

    def test_matches_reference_chain(self):
        from deeplearning4j_tpu.ops.pallas_convbn import (
            fused_bn_matmul_stats, reference_bn_matmul_stats)
        r = _rng(0)
        m, k, n = 512, 128, 64
        x = jnp.asarray(r.randn(m, k).astype(np.float32)).astype(jnp.bfloat16)
        sc = jnp.asarray(r.rand(k).astype(np.float32) + 0.5)
        sh = jnp.asarray(r.randn(k).astype(np.float32) * 0.1)
        w = jnp.asarray((r.randn(k, n) * k ** -0.5).astype(np.float32)
                        ).astype(jnp.bfloat16)
        ss = jnp.asarray(r.randn(n).astype(np.float32) * 0.1)
        z1, m1, v1 = fused_bn_matmul_stats(x, sc, sh, w, ss, interpret=True)
        z2, m2, v2 = reference_bn_matmul_stats(x, sc, sh, w, ss)
        np.testing.assert_allclose(np.asarray(z1, np.float32),
                                   np.asarray(z2, np.float32), atol=1e-2)
        np.testing.assert_allclose(np.asarray(m1), np.asarray(m2), atol=1e-3)
        np.testing.assert_allclose(np.asarray(v1), np.asarray(v2), rtol=1e-2,
                                   atol=1e-3)

    def test_no_prologue_no_relu(self):
        from deeplearning4j_tpu.ops.pallas_convbn import (
            fused_bn_matmul_stats, reference_bn_matmul_stats)
        r = _rng(1)
        m, k, n = 256, 64, 128
        x = jnp.asarray(r.randn(m, k).astype(np.float32)).astype(jnp.bfloat16)
        sc = jnp.ones((k,), jnp.float32)
        sh = jnp.zeros((k,), jnp.float32)
        w = jnp.asarray((r.randn(k, n) * k ** -0.5).astype(np.float32)
                        ).astype(jnp.bfloat16)
        ss = jnp.zeros((n,), jnp.float32)
        z1, m1, v1 = fused_bn_matmul_stats(
            x, sc, sh, w, ss, relu=False, fuse_prologue=False, interpret=True)
        z2, m2, v2 = reference_bn_matmul_stats(
            x, sc, sh, w, ss, relu=False, fuse_prologue=False)
        np.testing.assert_allclose(np.asarray(z1, np.float32),
                                   np.asarray(z2, np.float32), atol=1e-2)
        np.testing.assert_allclose(np.asarray(v1), np.asarray(v2), rtol=1e-2,
                                   atol=1e-3)
