"""Pallas fused matmul epilogue (ops/pallas_matmul.py): interpret-mode
kernel vs the XLA generic and a numpy oracle, the platform-helper usable()
gate, the custom-vjp backward, and the registry wiring.

No TPU in CI: the kernel runs in interpret mode (same code path, Mosaic
lowering unverified here — covered by the on-chip consistency suite)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deeplearning4j_tpu  # noqa: F401 — registry + platform registration
from deeplearning4j_tpu.environment import environment
from deeplearning4j_tpu.ops.nn_ops import fused_matmul_bias_act
from deeplearning4j_tpu.ops.pallas_matmul import (
    _usable, fused_matmul_bias_act_pallas, fused_matmul_helper)
from deeplearning4j_tpu.ops.registry import registry

M, K, N = 16, 128, 128


def _data(seed=0):
    r = np.random.RandomState(seed)
    return (r.randn(M, K).astype(np.float32),
            (r.randn(K, N) * 0.1).astype(np.float32),
            r.randn(N).astype(np.float32))


class TestKernelEquivalence:
    @pytest.mark.parametrize("act", ["none", "relu", "tanh", "gelu",
                                     "gelu_exact"])
    def test_interpret_matches_generic(self, act):
        x, w, b = _data()
        want = np.asarray(fused_matmul_bias_act(
            jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), activation=act))
        got = np.asarray(fused_matmul_bias_act_pallas(
            jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), activation=act,
            interpret=True))
        np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-5)

    def test_3d_batch_fold(self):
        r = np.random.RandomState(1)
        x = r.randn(2, 8, K).astype(np.float32)
        _, w, b = _data()
        want = np.asarray(fused_matmul_bias_act(
            jnp.asarray(x), jnp.asarray(w), jnp.asarray(b),
            activation="relu"))
        got = np.asarray(fused_matmul_bias_act_pallas(
            jnp.asarray(x), jnp.asarray(w), jnp.asarray(b),
            activation="relu", interpret=True))
        assert got.shape == (2, 8, N)
        np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-5)

    def test_no_bias(self):
        x, w, _ = _data()
        want = x @ w
        got = np.asarray(fused_matmul_bias_act_pallas(
            jnp.asarray(x), jnp.asarray(w), None, interpret=True))
        np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-4)

    def test_f32_accumulation_bf16_inputs(self):
        # bf16 operands, f32 accumulator: the kernel's dot must not lose
        # more than bf16-input precision over a K=128 reduction
        x, w, b = _data(2)
        xb, wb = jnp.asarray(x, jnp.bfloat16), jnp.asarray(w, jnp.bfloat16)
        want = np.asarray(
            jnp.matmul(xb, wb, preferred_element_type=jnp.float32)
            + jnp.asarray(b))
        got = np.asarray(fused_matmul_bias_act_pallas(
            xb, wb, jnp.asarray(b), interpret=True)).astype(np.float32)
        np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2)


class TestBackward:
    def test_custom_vjp_matches_generic_grads(self):
        x, w, b = _data(3)

        def loss_fused(x_, w_, b_):
            return jnp.sum(fused_matmul_helper(
                x_, w_, b_, activation="gelu_exact") ** 2)

        def loss_ref(x_, w_, b_):
            return jnp.sum(fused_matmul_bias_act(
                x_, w_, b_, activation="gelu_exact") ** 2)

        args = (jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
        g_f = jax.grad(loss_fused, argnums=(0, 1, 2))(*args)
        g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(*args)
        for gf, gr in zip(g_f, g_r):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                       rtol=1e-2, atol=1e-3)


class TestDispatch:
    def test_usable_gate(self):
        x, w, b = (jnp.zeros((M, K)), jnp.zeros((K, N)), jnp.zeros(N))
        assert _usable(x, w, b)
        assert not _usable(x, w, b, transpose_b=True)
        assert not _usable(jnp.zeros((7, K)), w, b)          # M % 8
        assert not _usable(jnp.zeros((M, 100)), jnp.zeros((100, N)), b)
        assert not _usable(x, w, jnp.zeros((1, N)))           # bias rank
        assert not _usable(x, w, b, activation="swish")

    def test_registered_as_tpu_platform_helper(self):
        desc = registry().get("fused_matmul_bias_act")
        assert "tpu" in desc.platform_impls

    def test_forced_pallas_resolves_to_kernel_on_cpu(self):
        desc = registry().get("fused_matmul_bias_act")
        x, w, b = _data(4)
        env = environment()
        prev = env.helper_mode
        env.helper_mode = "pallas"
        try:
            impl = desc.resolve(jnp.asarray(x), jnp.asarray(w),
                                jnp.asarray(b))
        finally:
            env.helper_mode = prev
        assert impl is fused_matmul_helper

    def test_generic_on_cpu_by_default(self):
        desc = registry().get("fused_matmul_bias_act")
        x, w, b = _data(5)
        impl = desc.resolve(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
        assert impl is desc.fn
