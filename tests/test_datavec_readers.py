"""Record readers (line/regex/json/svmlight/sequence), the columnar
(Arrow-role) converter, and the parallel transform executor."""

import numpy as np
import pytest

from deeplearning4j_tpu.datavec import (
    Schema, TransformProcess, LineRecordReader, RegexLineRecordReader,
    JacksonLineRecordReader, SVMLightRecordReader, CSVSequenceRecordReader,
    ParallelTransformExecutor, ColumnarBatch, to_columnar, save_columnar,
    load_columnar,
)


class TestReaders:
    def test_line_reader(self):
        recs = LineRecordReader(skip_lines=1).read("header\nfoo\nbar\n")
        assert recs == [["foo"], ["bar"]]

    def test_regex_reader(self):
        text = "2024-01-01 INFO start\n2024-01-02 WARN slow\n"
        recs = RegexLineRecordReader(r"(\S+) (\S+) (.*)").read(text)
        assert recs == [["2024-01-01", "INFO", "start"],
                        ["2024-01-02", "WARN", "slow"]]

    def test_regex_reader_mismatch_raises(self):
        with pytest.raises(ValueError, match="does not match"):
            RegexLineRecordReader(r"(\d+)").read("abc\n")

    def test_jackson_reader(self):
        text = '{"a": 1, "b": "x"}\n{"a": 2, "c": true}\n'
        recs = JacksonLineRecordReader(["a", "b"]).read(text)
        assert recs == [[1, "x"], [2, None]]

    def test_svmlight_reader(self):
        text = "1 1:0.5 3:2.0\n-1 2:1.5 # comment\n"
        feats, labels = SVMLightRecordReader(num_features=3).read_dataset(text)
        np.testing.assert_allclose(feats, [[0.5, 0, 2.0], [0, 1.5, 0]])
        np.testing.assert_allclose(labels, [1, -1])

    def test_svmlight_out_of_range(self):
        with pytest.raises(ValueError, match="out of range"):
            SVMLightRecordReader(num_features=2).read("1 3:1.0\n")

    def test_csv_sequence_reader_blocks(self):
        text = "1,2\n3,4\n\n5,6\n7,8\n9,10\n"
        seqs = CSVSequenceRecordReader().read(text)
        assert len(seqs) == 2
        assert seqs[0] == [["1", "2"], ["3", "4"]]
        assert len(seqs[1]) == 3


class TestColumnar:
    def _schema(self):
        return (Schema.Builder()
                .add_column_integer("id")
                .add_column_double("score")
                .add_column_string("tag")
                .build())

    def test_round_trip_records(self):
        schema = self._schema()
        records = [[1, 0.5, "a"], [2, 1.5, "b"], [3, -1.0, "c"]]
        batch = to_columnar(records, schema)
        assert batch.num_rows == 3
        np.testing.assert_array_equal(batch.column("id"), [1, 2, 3])
        assert batch.to_records() == records

    def test_save_load(self, tmp_path):
        schema = self._schema()
        records = [[1, 0.5, "a"], [2, 1.5, "b"]]
        batch = to_columnar(records, schema)
        p = str(tmp_path / "batch.npz")
        save_columnar(batch, p)
        back = load_columnar(p)
        assert back.to_records() == records
        assert back.schema.names == schema.names

    def test_to_matrix(self):
        schema = (Schema.Builder().add_column_double("x")
                  .add_column_double("y").build())
        batch = to_columnar([[1.0, 2.0], [3.0, 4.0]], schema)
        np.testing.assert_allclose(batch.to_matrix(),
                                   [[1, 2], [3, 4]])

    def test_ragged_raises(self):
        schema = self._schema()
        with pytest.raises(ValueError, match="ragged"):
            ColumnarBatch(schema, {"id": np.arange(3), "score": np.arange(2),
                                   "tag": np.asarray(["a", "b", "c"])})


class TestParallelExecutor:
    def _tp(self):
        schema = (Schema.Builder().add_column_integer("v").build())
        return (TransformProcess.builder(schema)
                .math_op("v", "Add", 10)
                .build())

    def test_matches_serial(self):
        records = [[i] for i in range(2000)]
        tp = self._tp()
        serial = tp.execute([list(r) for r in records])
        par = ParallelTransformExecutor(workers=4).execute(
            [list(r) for r in records], tp)
        assert par == serial

    def test_small_input_runs_inline(self):
        records = [[i] for i in range(10)]
        tp = self._tp()
        out = ParallelTransformExecutor(workers=4).execute(records, tp)
        assert out == [[i + 10] for i in range(10)]

    def test_order_preserved_with_filter(self):
        schema = Schema.Builder().add_column_integer("v").build()
        from deeplearning4j_tpu.datavec import ColumnCondition
        # filter REMOVES matching records (ConditionFilter semantics)
        tp = (TransformProcess.builder(schema)
              .filter(ColumnCondition("v", "LessThan", 1000))
              .build())
        records = [[i] for i in range(3000)]
        out = ParallelTransformExecutor(workers=3).execute(
            [list(r) for r in records], tp)
        assert out == [[i] for i in range(1000, 3000)]
