"""Record readers (line/regex/json/svmlight/sequence), the columnar
(Arrow-role) converter, and the parallel transform executor."""

import numpy as np
import pytest

from deeplearning4j_tpu.datavec import (
    Schema, TransformProcess, LineRecordReader, RegexLineRecordReader,
    JacksonLineRecordReader, SVMLightRecordReader, CSVSequenceRecordReader,
    ParallelTransformExecutor, ColumnarBatch, to_columnar, save_columnar,
    load_columnar,
)


class TestReaders:
    def test_line_reader(self):
        recs = LineRecordReader(skip_lines=1).read("header\nfoo\nbar\n")
        assert recs == [["foo"], ["bar"]]

    def test_regex_reader(self):
        text = "2024-01-01 INFO start\n2024-01-02 WARN slow\n"
        recs = RegexLineRecordReader(r"(\S+) (\S+) (.*)").read(text)
        assert recs == [["2024-01-01", "INFO", "start"],
                        ["2024-01-02", "WARN", "slow"]]

    def test_regex_reader_mismatch_raises(self):
        with pytest.raises(ValueError, match="does not match"):
            RegexLineRecordReader(r"(\d+)").read("abc\n")

    def test_jackson_reader(self):
        text = '{"a": 1, "b": "x"}\n{"a": 2, "c": true}\n'
        recs = JacksonLineRecordReader(["a", "b"]).read(text)
        assert recs == [[1, "x"], [2, None]]

    def test_svmlight_reader(self):
        text = "1 1:0.5 3:2.0\n-1 2:1.5 # comment\n"
        feats, labels = SVMLightRecordReader(num_features=3).read_dataset(text)
        np.testing.assert_allclose(feats, [[0.5, 0, 2.0], [0, 1.5, 0]])
        np.testing.assert_allclose(labels, [1, -1])

    def test_svmlight_out_of_range(self):
        with pytest.raises(ValueError, match="out of range"):
            SVMLightRecordReader(num_features=2).read("1 3:1.0\n")

    def test_csv_sequence_reader_blocks(self):
        text = "1,2\n3,4\n\n5,6\n7,8\n9,10\n"
        seqs = CSVSequenceRecordReader().read(text)
        assert len(seqs) == 2
        assert seqs[0] == [["1", "2"], ["3", "4"]]
        assert len(seqs[1]) == 3


class TestColumnar:
    def _schema(self):
        return (Schema.Builder()
                .add_column_integer("id")
                .add_column_double("score")
                .add_column_string("tag")
                .build())

    def test_round_trip_records(self):
        schema = self._schema()
        records = [[1, 0.5, "a"], [2, 1.5, "b"], [3, -1.0, "c"]]
        batch = to_columnar(records, schema)
        assert batch.num_rows == 3
        np.testing.assert_array_equal(batch.column("id"), [1, 2, 3])
        assert batch.to_records() == records

    def test_save_load(self, tmp_path):
        schema = self._schema()
        records = [[1, 0.5, "a"], [2, 1.5, "b"]]
        batch = to_columnar(records, schema)
        p = str(tmp_path / "batch.npz")
        save_columnar(batch, p)
        back = load_columnar(p)
        assert back.to_records() == records
        assert back.schema.names == schema.names

    def test_to_matrix(self):
        schema = (Schema.Builder().add_column_double("x")
                  .add_column_double("y").build())
        batch = to_columnar([[1.0, 2.0], [3.0, 4.0]], schema)
        np.testing.assert_allclose(batch.to_matrix(),
                                   [[1, 2], [3, 4]])

    def test_ragged_raises(self):
        schema = self._schema()
        with pytest.raises(ValueError, match="ragged"):
            ColumnarBatch(schema, {"id": np.arange(3), "score": np.arange(2),
                                   "tag": np.asarray(["a", "b", "c"])})


class TestParallelExecutor:
    def _tp(self):
        schema = (Schema.Builder().add_column_integer("v").build())
        return (TransformProcess.builder(schema)
                .math_op("v", "Add", 10)
                .build())

    def test_matches_serial(self):
        records = [[i] for i in range(2000)]
        tp = self._tp()
        serial = tp.execute([list(r) for r in records])
        par = ParallelTransformExecutor(workers=4).execute(
            [list(r) for r in records], tp)
        assert par == serial

    def test_small_input_runs_inline(self):
        records = [[i] for i in range(10)]
        tp = self._tp()
        out = ParallelTransformExecutor(workers=4).execute(records, tp)
        assert out == [[i + 10] for i in range(10)]

    def test_order_preserved_with_filter(self):
        schema = Schema.Builder().add_column_integer("v").build()
        from deeplearning4j_tpu.datavec import ColumnCondition
        # filter REMOVES matching records (ConditionFilter semantics)
        tp = (TransformProcess.builder(schema)
              .filter(ColumnCondition("v", "LessThan", 1000))
              .build())
        records = [[i] for i in range(3000)]
        out = ParallelTransformExecutor(workers=3).execute(
            [list(r) for r in records], tp)
        assert out == [[i] for i in range(1000, 3000)]


class TestAudio:
    """datavec-data-audio role: WAV decode + spectrogram features."""

    def _tone(self, freq=440.0, rate=8000, secs=0.25):
        t = np.arange(int(rate * secs)) / rate
        return np.sin(2 * np.pi * freq * t).astype(np.float32), rate

    def test_wav_round_trip(self, tmp_path):
        from deeplearning4j_tpu.datavec import read_wav, write_wav
        samples, rate = self._tone()
        p = str(tmp_path / "tone.wav")
        write_wav(p, samples.reshape(-1, 1), rate)
        back, r2 = read_wav(p)
        assert r2 == rate and back.shape == (len(samples), 1)
        np.testing.assert_allclose(back[:, 0], samples, atol=2e-4)

    def test_spectrogram_peak_at_tone_frequency(self):
        from deeplearning4j_tpu.datavec import spectrogram
        samples, rate = self._tone(freq=1000.0)
        spec = spectrogram(samples, frame_size=256, log_scale=False)
        # bin of 1kHz at 8kHz rate, 256-pt fft: 1000/8000*256 = 32
        peak_bins = spec.argmax(axis=1)
        assert np.abs(np.median(peak_bins) - 32) <= 1

    def test_wav_record_reader(self, tmp_path):
        from deeplearning4j_tpu.datavec import (WavFileRecordReader,
                                                write_wav)
        for i, f in enumerate([300.0, 600.0]):
            s, rate = self._tone(freq=f)
            write_wav(str(tmp_path / f"t{i}.wav"), s.reshape(-1, 1), rate)
        reader = WavFileRecordReader(features="spectrogram", frame_size=128)
        recs = reader.read(str(tmp_path))
        assert len(recs) == 2
        assert recs[0].ndim == 2 and recs[0].shape[1] == 65
        raw = WavFileRecordReader().read(str(tmp_path))
        assert raw[0].ndim == 1


class TestModelHub:
    """Omnihub-role local registry: publish/load with checksum verify."""

    def _net(self):
        from deeplearning4j_tpu import nn
        conf = (nn.builder().seed(3).updater(nn.Sgd(learning_rate=0.1)).list()
                .layer(nn.DenseLayer(n_out=4, activation="tanh"))
                .layer(nn.OutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(nn.InputType.feed_forward(3)).build())
        return nn.MultiLayerNetwork(conf).init()

    def test_publish_load_round_trip(self, tmp_path):
        from deeplearning4j_tpu.models.hub import ModelHub
        hub = ModelHub(root=str(tmp_path))
        net = self._net()
        hub.publish("tiny-mlp", net, metadata={"task": "demo"})
        assert hub.list_models() == ["tiny-mlp"]
        assert hub.manifest("tiny-mlp")["metadata"]["task"] == "demo"
        back = hub.load("tiny-mlp")
        x = np.random.RandomState(0).randn(2, 3).astype(np.float32)
        np.testing.assert_allclose(back.output(x), net.output(x),
                                   rtol=1e-6, atol=1e-6)

    def test_checksum_verification(self, tmp_path):
        from deeplearning4j_tpu.models.hub import ModelHub
        hub = ModelHub(root=str(tmp_path))
        hub.publish("m", self._net())
        # corrupt the artifact
        p = str(tmp_path / "m" / "model.zip")
        with open(p, "r+b") as f:
            f.seek(30)
            f.write(b"\xff\xff")
        with pytest.raises(IOError, match="checksum mismatch"):
            hub.load("m")

    def test_unknown_model_raises(self, tmp_path):
        from deeplearning4j_tpu.models.hub import ModelHub
        with pytest.raises(KeyError, match="no model"):
            ModelHub(root=str(tmp_path)).manifest("ghost")

    def test_bad_name_rejected(self, tmp_path):
        from deeplearning4j_tpu.models.hub import ModelHub
        with pytest.raises(ValueError, match="invalid model name"):
            ModelHub(root=str(tmp_path)).publish("../evil", self._net())

    def test_dotted_name_and_stray_files(self, tmp_path):
        from deeplearning4j_tpu.models.hub import ModelHub
        hub = ModelHub(root=str(tmp_path))
        hub.publish("resnet50-v1.5", self._net())  # dots are legal
        (tmp_path / ".DS_Store").write_text("junk")
        (tmp_path / "README.md").write_text("notes")
        assert hub.list_models() == ["resnet50-v1.5"]
        with pytest.raises(KeyError):
            hub.manifest("missing")  # KeyError, not ValueError from strays

    def test_single_file_read_and_exact_channel_layout(self, tmp_path):
        from deeplearning4j_tpu.datavec import (WavFileRecordReader,
                                                read_wav, write_wav)
        rate = 8000
        t = np.arange(int(rate * 0.1)) / rate
        s = np.sin(2 * np.pi * 440.0 * t).astype(np.float32)
        p = str(tmp_path / "one.wav")
        write_wav(p, s, rate)  # 1-D input
        recs = WavFileRecordReader().read(p)  # single path, not a dir
        assert len(recs) == 1 and recs[0].shape == (len(s),)
        # (1, C): one frame of 4 channels, NOT 4 mono frames
        p2 = str(tmp_path / "frame.wav")
        write_wav(p2, np.zeros((1, 4), np.float32), rate)
        back, _ = read_wav(p2)
        assert back.shape == (1, 4)


class TestExcelSqlGeo:
    """Round-5 DataVec residue (verdict missing #5): excel/jdbc/geo."""

    def _write_xlsx(self, path):
        # hand-rolled minimal xlsx (zip of xml) — no writer library in env
        import zipfile
        sheet = (
            '<?xml version="1.0"?>'
            '<worksheet xmlns="http://schemas.openxmlformats.org/'
            'spreadsheetml/2006/main"><sheetData>'
            '<row r="1"><c t="s"><v>0</v></c><c t="s"><v>1</v></c></row>'
            '<row r="2"><c><v>1.5</v></c><c t="s"><v>2</v></c></row>'
            '<row r="3"><c><v>2</v></c><c t="inlineStr"><is><t>inline</t>'
            '</is></c></row>'
            '</sheetData></worksheet>')
        shared = (
            '<?xml version="1.0"?>'
            '<sst xmlns="http://schemas.openxmlformats.org/spreadsheetml/'
            '2006/main"><si><t>value</t></si><si><t>name</t></si>'
            '<si><t>abc</t></si></sst>')
        with zipfile.ZipFile(path, "w") as z:
            z.writestr("xl/worksheets/sheet1.xml", sheet)
            z.writestr("xl/sharedStrings.xml", shared)
            z.writestr("[Content_Types].xml", "<Types/>")

    def test_excel_reader(self, tmp_path):
        from deeplearning4j_tpu.datavec.readers import ExcelRecordReader
        p = str(tmp_path / "t.xlsx")
        self._write_xlsx(p)
        rows = ExcelRecordReader(skip_rows=1).read(p)
        assert rows == [[1.5, "abc"], [2.0, "inline"]]

    def test_sql_reader_with_schema(self):
        import sqlite3
        from deeplearning4j_tpu.datavec.readers import SQLRecordReader
        conn = sqlite3.connect(":memory:")
        conn.execute("create table t (age integer, score real, name text)")
        conn.executemany("insert into t values (?,?,?)",
                         [(30, 1.5, "a"), (40, 2.5, "b")])
        rr = SQLRecordReader(conn, "select * from t order by age")
        assert rr.read() == [[30, 1.5, "a"], [40, 2.5, "b"]]
        schema = rr.schema()
        kinds = [c["type"] for c in schema.columns]
        assert kinds == ["long", "double", "string"]

    def test_haversine(self):
        from deeplearning4j_tpu.datavec.readers import haversine_km
        # Paris -> London ~= 344 km
        d = haversine_km(48.8566, 2.3522, 51.5074, -0.1278)
        assert 335 < d < 355
        assert haversine_km(10.0, 20.0, 10.0, 20.0) == 0.0

    def test_excel_sparse_cells_align_by_reference(self, tmp_path):
        # writers omit empty cells; alignment must come from the r= attr
        import zipfile
        from deeplearning4j_tpu.datavec.readers import ExcelRecordReader
        sheet = (
            '<?xml version="1.0"?>'
            '<worksheet xmlns="http://schemas.openxmlformats.org/'
            'spreadsheetml/2006/main"><sheetData>'
            '<row r="1"><c r="B1"><v>5</v></c><c r="D1"><v>7</v></c></row>'
            '</sheetData></worksheet>')
        p = str(tmp_path / "sparse.xlsx")
        with zipfile.ZipFile(p, "w") as z:
            z.writestr("xl/worksheets/sheet1.xml", sheet)
        rows = ExcelRecordReader().read(p)
        assert rows == [[None, 5.0, None, 7.0]]
