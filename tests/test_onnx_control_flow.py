"""ONNX control-flow import: Loop / If / Scan → lax.while_loop / cond / scan
(round-5 verdict item 3). Each graph is hand-assembled with the same
protowire helpers the parser tests use, imported, and checked against a
hand-built numpy oracle. Subgraph outer-scope captures are exercised in
every case (ONNX subgraphs capture by name, unlike TF function bodies).

Reference: onnx/defs/controlflow op definitions as imported by the
reference's samediff-import-onnx registry (SURVEY §3.2)."""

import numpy as np

from deeplearning4j_tpu.imports import protowire as pw
from deeplearning4j_tpu.imports.onnx_import import import_onnx

from tests.test_onnx_import import (attr_proto, build_model, node_proto,
                                    tensor_proto, value_info)


def graph_proto(nodes, inputs, outputs, initializers=None, name="sub"):
    g = b"".join(pw.field_bytes(1, n) for n in nodes)
    g += pw.field_string(2, name)
    for n, a in (initializers or {}).items():
        g += pw.field_bytes(5, tensor_proto(n, a))
    g += b"".join(pw.field_bytes(11, value_info(n, s)) for n, s in inputs)
    g += b"".join(pw.field_bytes(12, value_info(n, s)) for n, s in outputs)
    return g


def graph_attr(name, graph_bytes):
    return pw.field_string(1, name) + pw.field_bytes(6, graph_bytes) \
        + pw.field_varint(20, 5)


def node_with_graph_attrs(op_type, inputs, outputs, graph_attrs,
                          name="", **attrs):
    out = b"".join(pw.field_string(1, i) for i in inputs)
    out += b"".join(pw.field_string(2, o) for o in outputs)
    out += pw.field_string(3, name or outputs[0] + "_node")
    out += pw.field_string(4, op_type)
    out += b"".join(pw.field_bytes(5, attr_proto(k, v))
                    for k, v in attrs.items())
    out += b"".join(pw.field_bytes(5, graph_attr(k, g))
                    for k, g in graph_attrs.items())
    return out


class TestOnnxLoop:
    def test_for_loop_with_capture(self):
        # x_{i+1} = x_i + step   (step captured from the outer scope)
        body = graph_proto(
            nodes=[
                node_proto("Identity", ["cond_in"], ["cond_out"]),
                node_proto("Add", ["x_in", "step"], ["x_out"]),
            ],
            inputs=[("iter", ()), ("cond_in", ()), ("x_in", (2,))],
            outputs=[("cond_out", ()), ("x_out", (2,))])
        nodes = [
            node_proto("Add", ["s0", "s0"], ["step"]),  # outer tensor
            node_with_graph_attrs("Loop", ["M", "", "x0"], ["x_final"],
                                  {"body": body}),
        ]
        model = build_model(
            nodes, [("x0", (2,))], [("x_final", (2,))],
            {"M": np.asarray(5, np.int64), "s0": np.asarray([0.5, 1.0],
                                                            np.float32)})
        sd = import_onnx(model)
        x0 = np.asarray([1.0, 2.0], np.float32)
        out = sd.output({"x0": x0}, "x_final")["x_final"]
        np.testing.assert_allclose(out, x0 + 5 * np.asarray([1.0, 2.0]),
                                   atol=1e-6)

    def test_while_loop_runtime_cond(self):
        # run until x[0] >= 10 (cond computed in the body)
        body = graph_proto(
            nodes=[
                node_proto("Add", ["x_in", "one"], ["x_out"]),
                node_proto("Less", ["x_out", "ten"], ["cond_out"]),
            ],
            inputs=[("iter", ()), ("cond_in", ()), ("x_in", ())],
            outputs=[("cond_out", ()), ("x_out", ())],
            initializers={"one": np.asarray(1.0, np.float32),
                          "ten": np.asarray(10.0, np.float32)})
        nodes = [
            node_proto("Less", ["x0", "c10"], ["cond0"]),
            node_with_graph_attrs("Loop", ["", "cond0", "x0"], ["x_final"],
                                  {"body": body}),
        ]
        model = build_model(nodes, [("x0", ())], [("x_final", ())],
                            {"c10": np.asarray(10.0, np.float32)})
        sd = import_onnx(model)
        out = sd.output({"x0": np.asarray(3.0, np.float32)},
                        "x_final")["x_final"]
        assert float(out) == 10.0

    def test_loop_scan_outputs_static_m(self):
        # accumulate x_i and also emit each intermediate (scan output)
        body = graph_proto(
            nodes=[
                node_proto("Identity", ["cond_in"], ["cond_out"]),
                node_proto("Add", ["x_in", "one"], ["x_out"]),
                node_proto("Identity", ["x_out"], ["emit"]),
            ],
            inputs=[("iter", ()), ("cond_in", ()), ("x_in", (3,))],
            outputs=[("cond_out", ()), ("x_out", (3,)), ("emit", (3,))],
            initializers={"one": np.asarray([1.0, 1.0, 1.0], np.float32)})
        nodes = [node_with_graph_attrs("Loop", ["M", "", "x0"],
                                       ["x_final", "trace"], {"body": body})]
        model = build_model(nodes, [("x0", (3,))],
                            [("x_final", (3,)), ("trace", (4, 3))],
                            {"M": np.asarray(4, np.int64)})
        sd = import_onnx(model)
        x0 = np.zeros(3, np.float32)
        res = sd.output({"x0": x0}, ["x_final", "trace"])
        np.testing.assert_allclose(res["x_final"], x0 + 4)
        want = np.stack([x0 + i for i in range(1, 5)])
        np.testing.assert_allclose(res["trace"], want)


class TestOnnxIf:
    def _model(self):
        then_g = graph_proto(
            nodes=[node_proto("Add", ["a", "b"], ["z_then"])],
            inputs=[], outputs=[("z_then", (2,))], name="then")
        else_g = graph_proto(
            nodes=[node_proto("Sub", ["a", "b"], ["z_else"])],
            inputs=[], outputs=[("z_else", (2,))], name="else")
        nodes = [
            node_proto("Add", ["x", "x"], ["a"]),
            node_proto("Mul", ["x", "x"], ["b"]),
            node_proto("ReduceSum", ["x"], ["s"], keepdims=0),
            node_proto("Greater", ["s", "zero"], ["pred"]),
            node_with_graph_attrs("If", ["pred"], ["y"],
                                  {"then_branch": then_g,
                                   "else_branch": else_g}),
        ]
        return build_model(nodes, [("x", (2,))], [("y", (2,))],
                           {"zero": np.asarray(0.0, np.float32)})

    def test_then_branch(self):
        sd = import_onnx(self._model())
        x = np.asarray([1.0, 2.0], np.float32)
        out = sd.output({"x": x}, "y")["y"]
        np.testing.assert_allclose(out, 2 * x + x * x, atol=1e-6)

    def test_else_branch(self):
        sd = import_onnx(self._model())
        x = np.asarray([-1.0, -2.0], np.float32)
        out = sd.output({"x": x}, "y")["y"]
        np.testing.assert_allclose(out, 2 * x - x * x, atol=1e-6)


class TestOnnxScan:
    def test_cumsum_scan(self):
        body = graph_proto(
            nodes=[node_proto("Add", ["s_in", "x_el"], ["s_out"]),
                   node_proto("Identity", ["s_out"], ["y_el"])],
            inputs=[("s_in", (2,)), ("x_el", (2,))],
            outputs=[("s_out", (2,)), ("y_el", (2,))])
        nodes = [node_with_graph_attrs(
            "Scan", ["s0", "xs"], ["s_final", "ys"], {"body": body},
            num_scan_inputs=1)]
        model = build_model(nodes, [("s0", (2,)), ("xs", (5, 2))],
                            [("s_final", (2,)), ("ys", (5, 2))], {})
        sd = import_onnx(model)
        r = np.random.RandomState(0)
        xs = r.randn(5, 2).astype(np.float32)
        s0 = np.zeros(2, np.float32)
        res = sd.output({"s0": s0, "xs": xs}, ["s_final", "ys"])
        np.testing.assert_allclose(res["s_final"], xs.sum(0), atol=1e-5)
        np.testing.assert_allclose(res["ys"], np.cumsum(xs, 0), atol=1e-5)

    def test_reverse_direction(self):
        body = graph_proto(
            nodes=[node_proto("Add", ["s_in", "x_el"], ["s_out"]),
                   node_proto("Identity", ["s_out"], ["y_el"])],
            inputs=[("s_in", (2,)), ("x_el", (2,))],
            outputs=[("s_out", (2,)), ("y_el", (2,))])
        nodes = [node_with_graph_attrs(
            "Scan", ["s0", "xs"], ["s_final", "ys"], {"body": body},
            num_scan_inputs=1, scan_input_directions=[1],
            scan_output_directions=[1])]
        model = build_model(nodes, [("s0", (2,)), ("xs", (4, 2))],
                            [("s_final", (2,)), ("ys", (4, 2))], {})
        sd = import_onnx(model)
        xs = np.arange(8, dtype=np.float32).reshape(4, 2)
        s0 = np.zeros(2, np.float32)
        res = sd.output({"s0": s0, "xs": xs}, ["s_final", "ys"])
        np.testing.assert_allclose(res["s_final"], xs.sum(0))
        # reverse in, reverse out: ys[i] = suffix sum from the end up to i
        want = np.cumsum(xs[::-1], 0)[::-1]
        np.testing.assert_allclose(res["ys"], want)


class TestOnnxIfDifferingCaptures:
    def test_branches_capture_different_outer_tensors(self):
        # then reads outer `a` only, else reads outer `b` only — the
        # capture-union binding must route each branch the right tensor
        then_g = graph_proto(nodes=[node_proto("Identity", ["a"], ["z_t"])],
                             inputs=[], outputs=[("z_t", (2,))], name="t")
        else_g = graph_proto(nodes=[node_proto("Identity", ["b"], ["z_e"])],
                             inputs=[], outputs=[("z_e", (2,))], name="e")
        nodes = [
            node_proto("Add", ["x", "x"], ["a"]),
            node_proto("Mul", ["x", "x"], ["b"]),
            node_proto("ReduceSum", ["x"], ["s"], keepdims=0),
            node_proto("Greater", ["s", "zero"], ["pred"]),
            node_with_graph_attrs("If", ["pred"], ["y"],
                                  {"then_branch": then_g,
                                   "else_branch": else_g}),
        ]
        model = build_model(nodes, [("x", (2,))], [("y", (2,))],
                            {"zero": np.asarray(0.0, np.float32)})
        sd = import_onnx(model)
        xp = np.asarray([1.0, 2.0], np.float32)
        np.testing.assert_allclose(sd.output({"x": xp}, "y")["y"], 2 * xp)
        xn = np.asarray([-1.0, -2.0], np.float32)
        np.testing.assert_allclose(sd.output({"x": xn}, "y")["y"], xn * xn)


class TestOnnxBreadthRound5:
    def test_scatter_nd(self):
        nodes = [node_proto("ScatterND", ["data", "idx", "upd"], ["y"])]
        model = build_model(nodes, [("data", (4, 2))], [("y", (4, 2))],
                            {"idx": np.asarray([[0], [2]], np.int64),
                             "upd": np.asarray([[9., 9.], [7., 7.]],
                                               np.float32)})
        sd = import_onnx(model)
        d = np.zeros((4, 2), np.float32)
        out = sd.output({"data": d}, "y")["y"]
        want = d.copy(); want[0] = 9; want[2] = 7
        np.testing.assert_allclose(out, want)

    def test_gather_elements(self):
        nodes = [node_proto("GatherElements", ["x", "i"], ["y"], axis=1)]
        model = build_model(nodes, [("x", (2, 3))], [("y", (2, 2))],
                            {"i": np.asarray([[0, 2], [1, 0]], np.int64)})
        sd = import_onnx(model)
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        out = sd.output({"x": x}, "y")["y"]
        np.testing.assert_allclose(out, np.take_along_axis(
            x, np.asarray([[0, 2], [1, 0]]), axis=1))

    def test_nms_padded_indices(self):
        boxes = np.asarray([[[0, 0, 1, 1], [0, 0, 1.05, 1.05],
                             [2, 2, 3, 3]]], np.float32)
        scores = np.asarray([[[0.9, 0.8, 0.7]]], np.float32)
        nodes = [node_proto("NonMaxSuppression",
                            ["boxes", "scores", "mo", "iou", "st"], ["sel"])]
        model = build_model(
            nodes, [("boxes", boxes.shape), ("scores", scores.shape)],
            [("sel", (2, 3))],
            {"mo": np.asarray(2, np.int64),
             "iou": np.asarray(0.5, np.float32),
             "st": np.asarray(0.0, np.float32)})
        sd = import_onnx(model)
        out = np.asarray(sd.output({"boxes": boxes, "scores": scores},
                                   "sel")["sel"])
        # box 1 suppressed by IoU with box 0; boxes 0 and 2 selected
        assert out[0].tolist() == [0, 0, 0]
        assert out[1].tolist() == [0, 0, 2]

    def test_roi_align_avg(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        rois = np.asarray([[0.0, 0.0, 3.0, 3.0]], np.float32)
        nodes = [node_proto("RoiAlign", ["x", "rois", "bi"], ["y"],
                            output_height=2, output_width=2,
                            sampling_ratio=2, spatial_scale=1.0,
                            coordinate_transformation_mode="output_half_pixel")]
        model = build_model(nodes, [("x", x.shape), ("rois", rois.shape)],
                            [("y", (1, 1, 2, 2))],
                            {"bi": np.asarray([0], np.int64)})
        sd = import_onnx(model)
        out = np.asarray(sd.output({"x": x, "rois": rois}, "y")["y"])
        assert out.shape == (1, 1, 2, 2)
        # average pooling over an aligned roi of a linear ramp: monotone
        assert out[0, 0, 0, 0] < out[0, 0, 1, 1]

    def test_bitshift_left(self):
        nodes = [node_proto("BitShift", ["x", "s"], ["y"], direction="LEFT")]
        model = build_model(nodes, [("x", (3,))], [("y", (3,))],
                            {"s": np.asarray([1, 2, 3], np.int32)})
        sd = import_onnx(model)
        x = np.asarray([1, 1, 1], np.int32)
        np.testing.assert_array_equal(
            np.asarray(sd.output({"x": x}, "y")["y"]), [2, 4, 8])

    def test_quantize_uint8_roundtrip(self):
        nodes = [node_proto("QuantizeLinear", ["x", "sc", "zp"], ["q"]),
                 node_proto("DequantizeLinear", ["q", "sc", "zp"], ["y"])]
        model = build_model(nodes, [("x", (4,))], [("y", (4,))],
                            {"sc": np.asarray(0.1, np.float32),
                             "zp": np.asarray(128, np.uint8)})
        sd = import_onnx(model)
        x = np.asarray([-1.0, 0.0, 0.54, 5.0], np.float32)
        out = np.asarray(sd.output({"x": x}, "y")["y"])
        # 0.54/0.1 -> round-half-even(5.4) = 5 -> 0.5 (ONNX round semantics)
        np.testing.assert_allclose(out, [-1.0, 0.0, 0.5, 5.0], atol=0.01)

    def test_constant_of_shape_and_range(self):
        nodes = [node_proto("ConstantOfShape", ["shp"], ["z"]),
                 node_proto("Range", ["st", "li", "de"], ["r"]),
                 node_proto("Add", ["z", "r"], ["y"])]
        model = build_model(nodes, [], [("y", (4,))],
                            {"shp": np.asarray([4], np.int64),
                             "st": np.asarray(0.0, np.float32),
                             "li": np.asarray(4.0, np.float32),
                             "de": np.asarray(1.0, np.float32)})
        sd = import_onnx(model)
        np.testing.assert_allclose(np.asarray(sd.output({}, "y")["y"]),
                                   [0, 1, 2, 3])

    def test_documented_reject_message(self):
        nodes = [node_proto("NonZero", ["x"], ["y"])]
        model = build_model(nodes, [("x", (3,))], [("y", (1, 3))], {})
        with pytest.raises(NotImplementedError, match="dynamic-length"):
            import_onnx(model)


import pytest  # noqa: E402  (used by the reject test)


class TestOnnxRandomStreams:
    def test_unseeded_ops_get_distinct_stable_streams(self):
        nodes = [
            node_proto("RandomNormal", [], ["r1"], shape=[4], name="rn1"),
            node_proto("RandomNormal", [], ["r2"], shape=[4], name="rn2"),
            node_proto("Sub", ["r1", "r2"], ["y"]),
        ]
        model = build_model(nodes, [], [("y", (4,))], {})
        sd = import_onnx(model)
        out = np.asarray(sd.output({}, "y")["y"])
        # distinct per-name streams: difference must not vanish
        assert np.abs(out).max() > 1e-3
        # and deterministic across executions
        out2 = np.asarray(sd.output({}, "y")["y"])
        np.testing.assert_array_equal(out, out2)


class TestGridSample:
    @pytest.mark.parametrize("mode,align", [("bilinear", 0), ("bilinear", 1),
                                            ("nearest", 0)])
    def test_matches_torch(self, mode, align):
        torch = pytest.importorskip("torch")
        import torch.nn.functional as F
        r = np.random.RandomState(0)
        x = r.randn(2, 3, 5, 6).astype(np.float32)
        grid = (r.rand(2, 4, 4, 2).astype(np.float32) * 2.2 - 1.1)
        golden = F.grid_sample(torch.tensor(x), torch.tensor(grid),
                               mode=mode, padding_mode="zeros",
                               align_corners=bool(align)).numpy()
        nodes = [node_proto("GridSample", ["x", "grid"], ["y"],
                            mode="bilinear" if mode == "bilinear" else "nearest",
                            padding_mode="zeros", align_corners=align)]
        model = build_model(nodes, [("x", x.shape), ("grid", grid.shape)],
                            [("y", golden.shape)], {})
        sd = import_onnx(model)
        got = np.asarray(sd.output({"x": x, "grid": grid}, "y")["y"])
        np.testing.assert_allclose(got, golden, atol=1e-5)
