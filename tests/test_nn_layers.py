"""Layer config + runtime tests.

Reference test strategy parity (SURVEY §5.1): layer behavior tests akin to
deeplearning4j-core layer tests — shape inference, JSON config round-trip,
forward shapes, and numerics vs numpy oracles.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.nn import conf as C
from deeplearning4j_tpu.nn.layers import build_layer


def make_net(*layers, input_type, **kw):
    b = nn.builder().seed(42)
    for k, v in kw.items():
        getattr(b, k)(v)
    b = b.list()
    for l in layers:
        b.layer(l)
    return nn.MultiLayerNetwork(b.set_input_type(input_type).build()).init()


class TestShapeInference:
    def test_dense_chain_n_in_inferred(self):
        net = make_net(
            nn.DenseLayer(n_out=32, activation="relu"),
            nn.OutputLayer(n_out=10, activation="softmax", loss="mcxent"),
            input_type=nn.InputType.feed_forward(20),
        )
        assert net.conf.layers[0].n_in == 20
        assert net.conf.layers[1].n_in == 32

    def test_conv_stack_shapes(self):
        net = make_net(
            nn.ConvolutionLayer(n_out=8, kernel=(5, 5), activation="relu"),
            nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)),
            nn.ConvolutionLayer(n_out=16, kernel=(5, 5), activation="relu"),
            nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)),
            nn.DenseLayer(n_out=64, activation="relu"),
            nn.OutputLayer(n_out=10, activation="softmax"),
            input_type=nn.InputType.convolutional_flat(28, 28, 1),
        )
        # 28 -conv5-> 24 -pool-> 12 -conv5-> 8 -pool-> 4; 4*4*16 = 256
        assert net.conf.layers[4].n_in == 256
        x = np.random.RandomState(0).rand(3, 784).astype(np.float32)
        out = net.output(x)
        assert out.shape == (3, 10)
        np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)

    def test_same_mode_conv(self):
        lc = nn.ConvolutionLayer(n_in=3, n_out=4, kernel=(3, 3), stride=(2, 2),
                                 convolution_mode="same")
        ot = lc.output_type(nn.InputType.convolutional(9, 9, 3))
        assert (ot.height, ot.width, ot.channels) == (5, 5, 4)


class TestJsonRoundTrip:
    def test_full_conf_round_trip(self):
        conf = (
            nn.builder().seed(7).updater(nn.Adam(learning_rate=1e-3))
            .l2(1e-4).weight_init("relu").activation("relu")
            .list()
            .layer(nn.ConvolutionLayer(n_out=6, kernel=(5, 5)))
            .layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2), pooling_type="max"))
            .layer(nn.BatchNormalization())
            .layer(nn.DenseLayer(n_out=32, dropout=0.5))
            .layer(nn.OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.convolutional_flat(28, 28, 1))
            .build()
        )
        js = conf.to_json()
        conf2 = C.MultiLayerConfiguration.from_json(js)
        assert conf2.to_json() == js
        assert [type(l) for l in conf2.layers] == [type(l) for l in conf.layers]
        assert conf2.layers[3].dropout == 0.5
        assert isinstance(conf2.updater, nn.Adam)

    def test_schedule_round_trip(self):
        u = nn.Adam(learning_rate=nn.StepSchedule(value=0.1, decay_rate=0.5, step=100))
        d = u.to_dict()
        u2 = nn.get_updater(d)
        assert isinstance(u2.learning_rate, nn.StepSchedule)
        assert float(u2.lr(250)) == pytest.approx(0.1 * 0.25)

    def test_bidirectional_round_trip(self):
        lc = nn.Bidirectional.wrap(nn.LSTM(n_in=8, n_out=16), mode="concat")
        lc2 = C.LayerConf.from_dict(lc.to_dict())
        assert isinstance(lc2.inner(), nn.LSTM)
        assert lc2.output_type(nn.InputType.recurrent(8)).size == 32


class TestLayerForward:
    def test_dense_oracle(self):
        net = make_net(nn.DenseLayer(n_out=4, activation="identity"),
                       input_type=nn.InputType.feed_forward(3))
        x = np.random.RandomState(1).randn(5, 3).astype(np.float32)
        W = np.asarray(net.params[0]["W"])
        b = np.asarray(net.params[0]["b"])
        np.testing.assert_allclose(net.output(x), x @ W + b, rtol=1e-5, atol=1e-6)

    def test_batchnorm_train_vs_eval(self):
        net = make_net(nn.BatchNormalization(),
                       input_type=nn.InputType.feed_forward(4))
        x = np.random.RandomState(2).randn(64, 4).astype(np.float32) * 3 + 1
        acts = net.feed_forward(x, train=True)
        # train-mode output is standardized
        np.testing.assert_allclose(acts[0].mean(0), 0.0, atol=1e-4)
        np.testing.assert_allclose(acts[0].std(0), 1.0, atol=1e-2)

    def test_embedding(self):
        net = make_net(nn.EmbeddingLayer(n_in=10, n_out=5),
                       input_type=nn.InputType.feed_forward(1))
        ids = np.array([[1], [3], [7]])
        out = net.output(ids)
        W = np.asarray(net.params[0]["W"])
        np.testing.assert_allclose(out, W[[1, 3, 7]], rtol=1e-6)

    def test_dropout_train_only(self):
        net = make_net(nn.DropoutLayer(rate=0.5),
                       input_type=nn.InputType.feed_forward(50))
        x = np.ones((4, 50), np.float32)
        np.testing.assert_allclose(net.output(x), x)  # eval: identity
        acts = net.feed_forward(x, train=True)
        assert (acts[0] == 0).sum() > 0  # train: some dropped

    def test_lstm_shapes_and_mask(self):
        net = make_net(nn.LSTM(n_out=6, activation="tanh"),
                       nn.RnnOutputLayer(n_out=3, activation="softmax"),
                       input_type=nn.InputType.recurrent(4))
        x = np.random.RandomState(3).randn(2, 7, 4).astype(np.float32)
        out = net.output(x)
        assert out.shape == (2, 7, 3)
        # mask freezes state: fully-masked suffix must not change outputs
        mask = np.ones((2, 7), np.float32)
        mask[:, 5:] = 0
        out_m = net.output(x, mask)
        assert out_m.shape == (2, 7, 3)

    def test_bidirectional_concat(self):
        net = make_net(nn.Bidirectional.wrap(nn.LSTM(n_out=5, activation="tanh")),
                       input_type=nn.InputType.recurrent(3))
        x = np.random.RandomState(4).randn(2, 6, 3).astype(np.float32)
        acts = net.feed_forward(x)
        assert acts[0].shape == (2, 6, 10)

    def test_last_time_step_masked(self):
        net = make_net(nn.LastTimeStep.wrap(nn.SimpleRnn(n_out=4, activation="tanh")),
                       input_type=nn.InputType.recurrent(3))
        x = np.random.RandomState(5).randn(2, 6, 3).astype(np.float32)
        mask = np.array([[1, 1, 1, 0, 0, 0], [1, 1, 1, 1, 1, 1]], np.float32)
        out = net.output(x, mask)
        assert out.shape == (2, 4)
        # row 0's output must equal the full output at t=2
        acts = net.feed_forward(x)  # unmasked inner
        # can't compare directly (mask changes scan); just check finite
        assert np.isfinite(out).all()

    def test_self_attention(self):
        net = make_net(nn.SelfAttentionLayer(n_out=8, n_heads=2),
                       input_type=nn.InputType.recurrent(8))
        x = np.random.RandomState(6).randn(2, 5, 8).astype(np.float32)
        out = net.output(x)
        assert out.shape == (2, 5, 8)

    def test_global_pooling_masked_avg(self):
        net = make_net(nn.GlobalPoolingLayer(pooling_type="avg"),
                       input_type=nn.InputType.recurrent(3))
        x = np.ones((1, 4, 3), np.float32)
        x[0, 2:] = 100.0  # masked-out steps
        mask = np.array([[1, 1, 0, 0]], np.float32)
        out = net.output(x, mask)
        np.testing.assert_allclose(out, np.ones((1, 3)), rtol=1e-5)

    def test_depthwise_separable_upsampling(self):
        net = make_net(
            nn.DepthwiseConvolution2D(kernel=(3, 3), depth_multiplier=2, convolution_mode="same"),
            nn.SeparableConvolution2D(n_out=8, kernel=(3, 3), convolution_mode="same"),
            nn.Upsampling2D(size=(2, 2)),
            input_type=nn.InputType.convolutional(8, 8, 3),
        )
        x = np.random.RandomState(7).rand(2, 8, 8, 3).astype(np.float32)
        acts = net.feed_forward(x)
        assert acts[0].shape == (2, 8, 8, 6)
        assert acts[1].shape == (2, 8, 8, 8)
        assert acts[2].shape == (2, 16, 16, 8)
