"""graftcheck acceptance at scale (docs/ANALYSIS.md):

* the clean BERT-base ONNX import (12 layers, D=768 — the
  test_optimizer_bert_onnx wire model) checks with ZERO findings, at
  import (the auto-validation path) and on demand;
* a symbolic-batch BERT-style encoder — ``placeholder(shape=(None, 128))``
  — flows through ``check()`` with zero findings and a named batch Dim
  surviving to the logits.
"""

import numpy as np

from tests.test_optimizer_bert_onnx import _bert_base_model

from deeplearning4j_tpu.analysis import Dim, check_samediff, fixtures
from deeplearning4j_tpu.imports.onnx_import import import_onnx


class TestBertOnnxClean:
    def test_import_time_check_is_clean(self):
        sd = import_onnx(_bert_base_model())
        # the importer ran graftcheck (validate defaults on) and attached
        # the report; BERT-base must carry zero findings of ANY severity
        report = sd.last_check_report
        assert report is not None
        assert report.findings == [], "\n".join(
            f.render() for f in report.findings[:20])

    def test_on_demand_check_derives_logit_shape(self):
        sd = import_onnx(_bert_base_model())
        report = sd.check(name="onnx:bert_base")
        assert report.ok
        # B=1, T=16, 2-class head — the abstract output of 1000+ nodes
        assert report.avals["y"].shape == (1, 16, 2)
        assert report.avals["y"].dtype == np.dtype(np.float32)


class TestBertSymbolicBatch:
    def test_none_batch_checks_clean(self):
        sd = fixtures.bert_encoder_sym_batch(layers=2, seq=128)
        report = check_samediff(sd, graph_name="zoo/bert_sym")
        assert report.findings == [], "\n".join(
            f.render() for f in report.findings)

    def test_logit_shape_tracks_symbolic_batch(self):
        sd = fixtures.bert_encoder_sym_batch(layers=1, seq=128)
        report = sd.check(name="zoo/bert_sym")
        aval = report.avals["y"]
        # ids and mask carry INDEPENDENT batch symbols; where they meet
        # (the mask add) the checker soundly degrades the batch entry to
        # unknown rather than asserting the two Nones are equal — but the
        # rank and every concrete dim must survive all 1000+ edges
        assert aval.shape is not None and len(aval.shape) == 3
        assert aval.shape[0] in (Dim("ids.0"), None)
        assert aval.shape[1:] == (128, 2), aval
        assert aval.dtype == np.dtype(np.float32)

    def test_single_placeholder_dim_survives_end_to_end(self):
        # one placeholder → its named dim reaches the output intact
        sd = fixtures.mlp_sym_batch()
        report = sd.check(name="zoo/mlp_sym")
        assert report.avals["logits"].shape == (Dim("x.0"), 3)
        assert report.avals["loss"].shape == ()
