"""BERT path tests — BASELINE config[3] gate: wordpiece, BertIterator,
fine-tune convergence, MLM step (reference BertIterator + SameDiff-BERT
workload, SURVEY §4.3)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import BertWordPieceTokenizer, BertIterator, build_vocab
from deeplearning4j_tpu.models.bert import (
    BertConfig, BertModel, bert_encoder, init_bert_params,
    classification_logits, mlm_logits,
)
from deeplearning4j_tpu import nn

import jax


CORPUS = [
    "the good movie was great and fun",
    "a terrible film bad and boring",
    "great acting wonderful story good",
    "awful plot bad acting boring waste",
    "fun and wonderful a great time",
    "boring terrible waste of time bad",
] * 8


def corpus_labels():
    return [1, 0, 1, 0, 1, 0] * 8


class TestWordPiece:
    def test_build_vocab_has_specials(self):
        v = build_vocab(CORPUS)
        for sp in ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]:
            assert sp in v

    def test_tokenize_known_words(self):
        v = build_vocab(CORPUS)
        t = BertWordPieceTokenizer(v)
        assert t.tokenize("good movie") == ["good", "movie"]

    def test_wordpiece_fallback_to_chars(self):
        v = build_vocab(CORPUS)
        t = BertWordPieceTokenizer(v)
        pieces = t.tokenize("goodmovie")  # unseen compound → greedy pieces
        assert len(pieces) >= 2
        # round trip through ids
        ids = t.encode("good movie")
        assert t.decode(ids) == "good movie"

    def test_greedy_longest_match(self):
        v = {"[PAD]": 0, "[UNK]": 1, "[CLS]": 2, "[SEP]": 3, "[MASK]": 4,
             "un": 5, "##able": 6, "##a": 7, "##b": 8, "##l": 9, "##e": 10,
             "u": 11, "##n": 12}
        t = BertWordPieceTokenizer(v)
        assert t.tokenize("unable") == ["un", "##able"]


class TestBertIterator:
    def test_classification_batches(self):
        v = build_vocab(CORPUS)
        it = BertIterator(BertWordPieceTokenizer(v), CORPUS, corpus_labels(),
                          num_classes=2, max_len=16, batch_size=8)
        b = next(iter(it))
        assert b["ids"].shape == (8, 16)
        assert b["labels"].shape == (8, 2)
        assert b["mask"].max() == 1
        # CLS at position 0 everywhere
        assert (b["ids"][:, 0] == v["[CLS]"]).all()

    def test_mlm_batches(self):
        v = build_vocab(CORPUS)
        it = BertIterator(BertWordPieceTokenizer(v), CORPUS, task="unsupervised",
                          max_len=16, batch_size=8, seed=3)
        b = next(iter(it))
        assert b["mlm_mask"].sum() > 0  # some positions masked
        sel = b["mlm_mask"] > 0
        # labels hold the ORIGINAL ids at masked positions
        assert (b["mlm_labels"][sel] > 0).all()


class TestBertModel:
    def test_encoder_shapes(self):
        cfg = BertConfig.tiny()
        params = init_bert_params(jax.random.key(0), cfg)
        ids = np.zeros((2, 10), np.int32)
        seq, pooled = bert_encoder(params, ids, np.zeros_like(ids),
                                   np.ones_like(ids), cfg)
        assert seq.shape == (2, 10, cfg.hidden)
        assert pooled.shape == (2, cfg.hidden)

    def test_mask_blocks_attention(self):
        """Padding must not change unmasked outputs (attention mask works)."""
        cfg = BertConfig.tiny(dropout=0.0)
        params = init_bert_params(jax.random.key(0), cfg)
        rng = np.random.RandomState(0)
        ids8 = rng.randint(5, 50, (1, 8)).astype(np.int32)
        mask8 = np.ones((1, 8), np.int32)
        ids12 = np.concatenate([ids8, np.zeros((1, 4), np.int32)], axis=1)
        mask12 = np.concatenate([mask8, np.zeros((1, 4), np.int32)], axis=1)
        seq8, _ = bert_encoder(params, ids8, np.zeros_like(ids8), mask8, cfg)
        seq12, _ = bert_encoder(params, ids12, np.zeros_like(ids12), mask12, cfg)
        np.testing.assert_allclose(np.asarray(seq12)[:, :8], np.asarray(seq8),
                                   rtol=1e-4, atol=1e-5)

    def test_fine_tune_converges(self):
        """config[3] gate (tiny scale): sentiment fine-tune reaches high
        train accuracy."""
        v = build_vocab(CORPUS)
        tok = BertWordPieceTokenizer(v)
        cfg = BertConfig.tiny(vocab_size=len(v), num_labels=2, dropout=0.0)
        model = BertModel(cfg, seed=1, updater=nn.Adam(learning_rate=1e-3))
        it = BertIterator(tok, CORPUS, corpus_labels(), num_classes=2,
                          max_len=16, batch_size=16, seed=1)
        hist = model.fit_classifier(it, epochs=12)
        assert hist[-1] < hist[0] * 0.3, hist
        # accuracy on the training sentences
        b = next(iter(BertIterator(tok, CORPUS, corpus_labels(), num_classes=2,
                                   max_len=16, batch_size=48, seed=9)))
        pred = model.predict(b["ids"], b["segments"], b["mask"]).argmax(-1)
        acc = (pred == b["labels"].argmax(-1)).mean()
        assert acc > 0.9, acc

    def test_mlm_step_runs(self):
        v = build_vocab(CORPUS)
        tok = BertWordPieceTokenizer(v)
        cfg = BertConfig.tiny(vocab_size=len(v), dropout=0.0)
        model = BertModel(cfg, seed=2, updater=nn.Adam(learning_rate=1e-3))
        it = BertIterator(tok, CORPUS, task="unsupervised", max_len=16,
                          batch_size=16, seed=2)
        hist = model.fit_mlm(it, epochs=3)
        assert np.isfinite(hist).all()
        assert hist[-1] < hist[0], hist

    def test_param_count_base_is_bertbase_sized(self):
        cfg = BertConfig.base()
        params = init_bert_params(jax.random.key(0), cfg)
        n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))
        # BERT-base ≈ 110M (+MLM head)
        assert 100_000_000 < n < 135_000_000, n
