"""Recurrent path tests — BASELINE config[2] (BiLSTM sequence tagging),
tBPTT semantics, and stateful rnnTimeStep (reference
MultiLayerNetwork.rnnTimeStep / doTruncatedBPTT, SURVEY §6.7)."""

import numpy as np
import pytest

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.datasets import DataSet, ListDataSetIterator


def tagging_data(n=64, t=12, f=6, classes=3, seed=0):
    """Learnable sequence tagging: label depends on a sliding window sign."""
    rng = np.random.RandomState(seed)
    x = rng.randn(n, t, f).astype(np.float32)
    w = rng.randn(f).astype(np.float32)
    proj = x @ w
    cum = np.cumsum(proj, axis=1)
    y_id = np.clip((cum > 0).astype(int) + (proj > 0.5).astype(int), 0, classes - 1)
    y = np.eye(classes, dtype=np.float32)[y_id]
    return x, y, y_id


class TestBiLSTMTagger:
    """BASELINE config[2] exit gate."""

    def test_bilstm_tagger_converges(self):
        x, y, y_id = tagging_data(n=128, t=10)
        net = nn.MultiLayerNetwork(
            nn.builder().seed(42).updater(nn.Adam(learning_rate=5e-3))
            .weight_init("xavier").list()
            .layer(nn.Bidirectional.wrap(nn.LSTM(n_out=24, activation="tanh")))
            .layer(nn.RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.recurrent(6)).build()
        ).init()
        net.fit(x, y, epochs=60, batch_size=64)
        pred = net.output(x).argmax(-1)
        acc = (pred == y_id).mean()
        assert acc > 0.85, acc


class TestTbptt:
    def test_tbptt_trains(self):
        x, y, y_id = tagging_data(n=64, t=20)
        net = nn.MultiLayerNetwork(
            nn.builder().seed(7).updater(nn.Adam(learning_rate=5e-3))
            .tbptt(5).list()
            .layer(nn.LSTM(n_out=16, activation="tanh"))
            .layer(nn.RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.recurrent(6)).build()
        ).init()
        assert net.conf.backprop_type == "tbptt"
        net.fit(x, y, epochs=30, batch_size=64)
        acc = (net.output(x).argmax(-1) == y_id).mean()
        assert acc > 0.7, acc

    def test_tbptt_state_carries_across_segments(self):
        """With state carry, segment 2 sees segment 1's history: a tBPTT
        forward over [0:4]+[4:8] must equal the full forward at t>=4 — for a
        stateless-equivalent net it wouldn't."""
        net = nn.MultiLayerNetwork(
            nn.builder().seed(3).tbptt(4).list()
            .layer(nn.LSTM(n_out=5, activation="tanh"))
            .layer(nn.RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.recurrent(3)).build()
        ).init()
        x = np.random.RandomState(0).randn(2, 8, 3).astype(np.float32)
        full = net.output(x)
        # stateful two-segment forward
        net.rnn_clear_previous_state()
        seg1 = net.rnn_time_step(x[:, :4])
        seg2 = net.rnn_time_step(x[:, 4:])
        np.testing.assert_allclose(seg2, full[:, 4:], rtol=1e-4, atol=1e-5)


class TestRnnTimeStep:
    def test_streaming_equals_full(self):
        net = nn.MultiLayerNetwork(
            nn.builder().seed(11).list()
            .layer(nn.LSTM(n_out=8, activation="tanh"))
            .layer(nn.LSTM(n_out=6, activation="tanh"))
            .layer(nn.RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.recurrent(4)).build()
        ).init()
        x = np.random.RandomState(1).randn(3, 6, 4).astype(np.float32)
        full = net.output(x)
        net.rnn_clear_previous_state()
        outs = [net.rnn_time_step(x[:, [t]]) for t in range(6)]
        stream = np.concatenate(outs, axis=1)
        np.testing.assert_allclose(stream, full, rtol=1e-4, atol=1e-5)

    def test_single_step_2d_input(self):
        net = nn.MultiLayerNetwork(
            nn.builder().seed(2).list()
            .layer(nn.SimpleRnn(n_out=4, activation="tanh"))
            .layer(nn.RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.recurrent(3)).build()
        ).init()
        out = net.rnn_time_step(np.ones((2, 3), np.float32))
        assert out.shape == (2, 2)
        # second call uses carried state → different output
        out2 = net.rnn_time_step(np.ones((2, 3), np.float32))
        assert not np.allclose(out, out2)

    def test_clear_state_resets(self):
        net = nn.MultiLayerNetwork(
            nn.builder().seed(2).list()
            .layer(nn.SimpleRnn(n_out=4, activation="tanh"))
            .layer(nn.RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.recurrent(3)).build()
        ).init()
        a = net.rnn_time_step(np.ones((1, 3), np.float32))
        net.rnn_clear_previous_state()
        b = net.rnn_time_step(np.ones((1, 3), np.float32))
        np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_get_previous_state(self):
        net = nn.MultiLayerNetwork(
            nn.builder().seed(2).list()
            .layer(nn.LSTM(n_out=4, activation="tanh"))
            .layer(nn.RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.recurrent(3)).build()
        ).init()
        net.rnn_time_step(np.ones((1, 3), np.float32))
        h, c = net.rnn_get_previous_state(0)
        assert h.shape == (1, 4) and c.shape == (1, 4)
        assert np.abs(np.asarray(h)).sum() > 0


class TestMaskedTraining:
    def test_variable_length_sequences(self):
        x, y, y_id = tagging_data(n=64, t=10)
        mask = np.ones((64, 10), np.float32)
        lengths = np.random.RandomState(5).randint(4, 11, 64)
        for i, L in enumerate(lengths):
            mask[i, L:] = 0
        ds = DataSet(x, y, features_mask=mask, labels_mask=mask)
        net = nn.MultiLayerNetwork(
            nn.builder().seed(9).updater(nn.Adam(learning_rate=5e-3)).list()
            .layer(nn.LSTM(n_out=16, activation="tanh"))
            .layer(nn.RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.recurrent(6)).build()
        ).init()
        net.fit(ListDataSetIterator(ds, batch_size=64), epochs=20)
        assert np.isfinite(net.score())
        # masked positions don't affect evaluation
        e = net.evaluate(ListDataSetIterator(ds, batch_size=64))
        assert e.confusion.sum() == int(mask.sum())
