"""End-to-end training tests — the reference's MultiLayerTest /
gradient-descent convergence tests + ModelSerializer round-trip
(SURVEY §5.1, §6.4). Includes the BASELINE config[0] LeNet-MNIST smoke gate.
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.datasets import DataSet, ListDataSetIterator, NormalizerStandardize
from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.eval import Evaluation


def xor_data(n=512, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 2).astype(np.float32)
    y = ((x[:, 0] > 0.5) ^ (x[:, 1] > 0.5)).astype(np.int64)
    labels = np.zeros((n, 2), np.float32)
    labels[np.arange(n), y] = 1.0
    return x, labels


class TestTrainingLoop:
    def test_xor_converges(self):
        x, y = xor_data()
        net = nn.MultiLayerNetwork(
            nn.builder().seed(12).updater(nn.Adam(learning_rate=0.02))
            .weight_init("xavier").list()
            .layer(nn.DenseLayer(n_out=32, activation="tanh"))
            .layer(nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(2)).build()
        ).init()
        net.fit(x, y, epochs=150, batch_size=128)
        acc = (net.predict(x) == y.argmax(-1)).mean()
        assert acc > 0.95, f"XOR accuracy {acc}"
        assert net.score() < 0.25

    def test_regression_mse(self):
        rng = np.random.RandomState(3)
        x = rng.randn(256, 4).astype(np.float32)
        w = rng.randn(4, 1).astype(np.float32)
        y = x @ w + 0.7
        net = nn.MultiLayerNetwork(
            nn.builder().seed(5).updater(nn.Adam(learning_rate=0.05)).list()
            .layer(nn.OutputLayer(n_out=1, activation="identity", loss="mse"))
            .set_input_type(nn.InputType.feed_forward(4)).build()
        ).init()
        net.fit(x, y, epochs=100, batch_size=256)
        learned_w = np.asarray(net.params[0]["W"])
        np.testing.assert_allclose(learned_w, w, atol=0.05)

    def test_listeners_called(self):
        x, y = xor_data(128)
        net = nn.MultiLayerNetwork(
            nn.builder().list()
            .layer(nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(2)).build()
        ).init()
        collect = nn.CollectScoresIterationListener()
        net.set_listeners(collect, nn.ScoreIterationListener(5))
        net.fit(x, y, epochs=2, batch_size=32)
        assert len(collect.scores) == 8  # 4 batches × 2 epochs

    def test_l2_regularization_shrinks_weights(self):
        x, y = xor_data(256)
        def build(l2):
            return nn.MultiLayerNetwork(
                nn.builder().seed(9).updater(nn.Sgd(learning_rate=0.1)).l2(l2).list()
                .layer(nn.DenseLayer(n_out=32, activation="tanh"))
                .layer(nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
                .set_input_type(nn.InputType.feed_forward(2)).build()
            ).init()
        a, b = build(0.0), build(0.1)
        a.fit(x, y, epochs=20, batch_size=64)
        b.fit(x, y, epochs=20, batch_size=64)
        na = np.abs(np.asarray(a.params[0]["W"])).mean()
        nb = np.abs(np.asarray(b.params[0]["W"])).mean()
        assert nb < na

    def test_gradient_clipping_runs(self):
        x, y = xor_data(64)
        net = nn.MultiLayerNetwork(
            nn.builder().gradient_normalization("clip_l2_per_layer", 1.0).list()
            .layer(nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(2)).build()
        ).init()
        net.fit(x, y, epochs=1, batch_size=32)
        assert np.isfinite(net.score())

    def test_batchnorm_network_trains(self):
        x, y = xor_data(256)
        net = nn.MultiLayerNetwork(
            nn.builder().seed(2).updater(nn.Adam(learning_rate=0.02)).list()
            .layer(nn.DenseLayer(n_out=16, activation="identity"))
            .layer(nn.BatchNormalization())
            .layer(nn.ActivationLayer(activation="relu"))
            .layer(nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(2)).build()
        ).init()
        net.fit(x, y, epochs=40, batch_size=64)
        acc = (net.predict(x) == y.argmax(-1)).mean()
        assert acc > 0.9
        # running stats were updated away from init
        assert np.abs(np.asarray(net.net_state[1]["mean"])).sum() > 0


class TestLeNetMnist:
    """BASELINE config[0]: LeNet-5 MNIST single-chip smoke gate."""

    @staticmethod
    def lenet():
        return nn.MultiLayerNetwork(
            nn.builder().seed(123).updater(nn.Adam(learning_rate=1e-3))
            .weight_init("xavier").list()
            .layer(nn.ConvolutionLayer(n_out=20, kernel=(5, 5), activation="relu"))
            .layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(nn.ConvolutionLayer(n_out=50, kernel=(5, 5), activation="relu"))
            .layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(nn.DenseLayer(n_out=500, activation="relu"))
            .layer(nn.OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.convolutional_flat(28, 28, 1)).build()
        ).init()

    def test_lenet_mnist_converges(self):
        train = MnistDataSetIterator(batch_size=128, train=True, num_examples=2048)
        test = MnistDataSetIterator(batch_size=256, train=False, num_examples=512)
        net = self.lenet()
        net.fit(train, epochs=3)
        e: Evaluation = net.evaluate(test)
        assert e.accuracy() > 0.90, f"LeNet MNIST accuracy {e.accuracy()}\n{e.stats()}"


class TestSerde:
    def test_save_restore_round_trip(self, tmp_path):
        x, y = xor_data(128)
        net = nn.MultiLayerNetwork(
            nn.builder().seed(11).updater(nn.Adam(learning_rate=0.01)).list()
            .layer(nn.DenseLayer(n_out=8, activation="relu"))
            .layer(nn.BatchNormalization())
            .layer(nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(2)).build()
        ).init()
        net.fit(x, y, epochs=3, batch_size=32)
        path = str(tmp_path / "model.zip")
        nn.save_model(net, path)
        net2 = nn.restore_model(path)
        np.testing.assert_allclose(net2.output(x), net.output(x), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(net2.params_flat(), net.params_flat(), rtol=1e-6)
        # exact resume: continue training both, trajectories must match
        net.fit(x, y, epochs=1, batch_size=32)
        net2.fit(x, y, epochs=1, batch_size=32)
        np.testing.assert_allclose(net2.params_flat(), net.params_flat(), rtol=1e-4, atol=1e-5)

    def test_normalizer_round_trip(self, tmp_path):
        x, y = xor_data(64)
        ds = DataSet(x, y)
        norm = NormalizerStandardize()
        norm.fit(ds)
        net = nn.MultiLayerNetwork(
            nn.builder().list()
            .layer(nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(2)).build()
        ).init()
        path = str(tmp_path / "m.zip")
        nn.save_model(net, path, normalizer=norm)
        norm2 = nn.restore_normalizer(path)
        np.testing.assert_allclose(norm2.mean, norm.mean)
        np.testing.assert_allclose(norm2.std, norm.std)

    def test_params_flat_set_round_trip(self):
        net = TestLeNetMnist.lenet()
        flat = net.params_flat()
        flat2 = flat + 0.25
        net.set_params_flat(flat2)
        np.testing.assert_allclose(net.params_flat(), flat2, rtol=1e-6)


class TestEvaluation:
    def test_evaluation_counts(self):
        e = Evaluation()
        labels = np.eye(3)[[0, 1, 2, 2]]
        preds = np.eye(3)[[0, 1, 1, 2]]
        e.eval(labels, preds)
        assert e.accuracy() == pytest.approx(0.75)
        assert e.confusion[2, 1] == 1
        assert "Accuracy" in e.stats()

    def test_merge(self):
        a, b = Evaluation(), Evaluation()
        a.eval(np.eye(2)[[0]], np.eye(2)[[0]])
        b.eval(np.eye(2)[[1]], np.eye(2)[[0]])
        a.merge(b)
        assert a.accuracy() == pytest.approx(0.5)

    def test_roc_auc_perfect(self):
        from deeplearning4j_tpu.eval import ROC

        r = ROC()
        r.eval(np.array([1, 1, 0, 0]), np.array([0.9, 0.8, 0.2, 0.1]))
        assert r.calculate_auc() == pytest.approx(1.0)

    def test_regression_eval(self):
        from deeplearning4j_tpu.eval import RegressionEvaluation

        r = RegressionEvaluation()
        r.eval(np.array([[1.0], [2.0]]), np.array([[1.1], [1.9]]))
        assert r.mean_squared_error(0) == pytest.approx(0.01, rel=1e-3)
        assert r.r_squared(0) > 0.9
