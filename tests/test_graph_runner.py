"""GraphRunner interop tests — nd4j-tensorflow GraphRunner /
nd4j-onnxruntime parity: load a foreign graph (file or bytes), run it with
named feeds/fetches, match the source framework's own output elementwise."""

import numpy as np
import pytest

from deeplearning4j_tpu.imports import GraphRunner
from deeplearning4j_tpu.imports.graph_runner import _sniff_framework

tf = pytest.importorskip("tensorflow")

from tests.test_tf_import import freeze
from tests.test_onnx_import import build_model, node_proto


def _tf_mlp():
    rng = np.random.RandomState(0)
    w0 = tf.Variable(rng.randn(4, 8).astype(np.float32))
    b0 = tf.Variable(np.zeros(8, np.float32))
    w1 = tf.Variable(rng.randn(8, 3).astype(np.float32))

    def model(x):
        h = tf.nn.relu(tf.matmul(x, w0) + b0)
        return tf.nn.softmax(tf.matmul(h, w1))

    gd, ins, outs = freeze(model, tf.TensorSpec([None, 4], tf.float32))
    return model, gd, ins, outs


class TestGraphRunnerTF:
    def test_tf_bytes_sniffed(self):
        model, gd, ins, outs = _tf_mlp()
        data = gd.SerializeToString()
        assert _sniff_framework(data) == "tensorflow"
        runner = GraphRunner(data)
        x = np.random.RandomState(1).randn(5, 4).astype(np.float32)
        res = runner.run({ins[0]: x})
        np.testing.assert_allclose(res[outs[0]],
                                   model(tf.constant(x)).numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_tf_file_by_extension(self, tmp_path):
        model, gd, ins, outs = _tf_mlp()
        p = tmp_path / "frozen.pb"
        p.write_bytes(gd.SerializeToString())
        runner = GraphRunner(str(p))
        assert runner.framework == "tensorflow"
        assert ins[0] in runner.input_names
        x = np.random.RandomState(2).randn(3, 4).astype(np.float32)
        res = runner({ins[0]: x})  # __call__ alias
        np.testing.assert_allclose(res[outs[0]],
                                   model(tf.constant(x)).numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_explicit_fetches(self):
        model, gd, ins, outs = _tf_mlp()
        runner = GraphRunner(gd.SerializeToString(), outputs=outs)
        assert runner.output_names == list(outs)


class TestGraphRunnerOnnx:
    def _onnx_mlp(self):
        r = np.random.RandomState(0)
        w = r.randn(4, 6).astype(np.float32)
        nodes = [node_proto("MatMul", ["x", "w"], ["h"]),
                 node_proto("Relu", ["h"], ["y"])]
        model = build_model(nodes, [("x", (2, 4))], [("y", (2, 6))],
                            {"w": w})
        return bytes(model), w

    def test_onnx_bytes_sniffed(self):
        data, w = self._onnx_mlp()
        assert _sniff_framework(data) == "onnx"
        runner = GraphRunner(data)
        assert runner.framework == "onnx"
        x = np.random.RandomState(1).randn(2, 4).astype(np.float32)
        res = runner.run({"x": x})
        np.testing.assert_allclose(res["y"], np.maximum(x @ w, 0),
                                   rtol=1e-5, atol=1e-6)

    def test_onnx_file_by_extension(self, tmp_path):
        data, w = self._onnx_mlp()
        p = tmp_path / "model.onnx"
        p.write_bytes(data)
        runner = GraphRunner(str(p))
        assert runner.framework == "onnx"
        assert runner.output_names == ["y"]
        x = np.zeros((2, 4), np.float32)
        res = runner.run({"x": x})
        np.testing.assert_allclose(res["y"], np.zeros((2, 6), np.float32))

    def test_empty_bytes_raise(self):
        with pytest.raises(ValueError, match="empty"):
            _sniff_framework(b"")
