"""Kernel autotuner + measured dispatch (ops/tuning.py, docs/KERNELS.md).

Covers the tuning-table serde/merge/fallback contract, the tuned() read
path every dispatch site uses, the dispatch-counter family, and —
per-tuned-op — that resolve picks XLA below and Pallas above the measured
threshold (the ISSUE 9 acceptance criterion, asserted via the
dl4j_tpu_helper_dispatch_total counters)."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

import deeplearning4j_tpu.ops  # noqa: F401 - registers catalog + helpers
from deeplearning4j_tpu import observe
from deeplearning4j_tpu.environment import environment
from deeplearning4j_tpu.ops import tuning
from deeplearning4j_tpu.ops.registry import registry


@pytest.fixture
def tuning_sandbox(tmp_path, monkeypatch):
    """Point the tuning cache at a per-test dir; restore memoized tables on
    exit so a test-written table never leaks into other tests."""
    monkeypatch.setenv(tuning.ENV_DIR, str(tmp_path))
    tuning.reset_tables()
    yield tmp_path
    monkeypatch.undo()
    tuning.reset_tables()


def _write_table(tmp_path, entries, kind="cpu"):
    t = tuning.TuningTable(device_kind=kind, entries=entries)
    t.save(os.path.join(str(tmp_path), f"{kind}.json"))
    tuning.reset_tables()
    return t


@pytest.fixture
def pallas_mode():
    env = environment()
    old = env.helper_mode
    env.helper_mode = "pallas"  # platform-table resolution on CPU
    yield env
    env.helper_mode = old


def _dispatch_delta(fn):
    before = observe.dispatch_summary()
    out = fn()
    after = observe.dispatch_summary()
    return out, {k: after.get(k, 0) - before.get(k, 0)
                 for k in after if after.get(k, 0) != before.get(k, 0)}


class TestTableSerde:
    def test_round_trip(self, tmp_path):
        t = tuning.TuningTable(device_kind="cpu")
        t.set("dot_product_attention", "flash_min_t", 256)
        t.set_block("matmul_int8", "m256_k512_n512", "block_m", 128)
        path = t.save(str(tmp_path / "cpu.json"))
        back = tuning.TuningTable.load(path)
        assert back.device_kind == "cpu"
        assert back.get("dot_product_attention", "flash_min_t") == 256
        assert back.get_block("matmul_int8", "m256_k512_n512",
                              "block_m") == 128

    def test_merge_deep(self):
        a = tuning.TuningTable("cpu", {
            "op": {"thresh": 1, "blocks": {"t64": {"block_q": 8}}}})
        b = tuning.TuningTable("cpu", {
            "op": {"thresh": 2, "blocks": {"t64": {"block_k": 16},
                                           "t128": {"block_q": 32}}}})
        a.merge(b)
        assert a.get("op", "thresh") == 2  # other wins
        assert a.get_block("op", "t64", "block_q") == 8   # preserved
        assert a.get_block("op", "t64", "block_k") == 16  # merged in
        assert a.get_block("op", "t128", "block_q") == 32

    def test_rejects_wrong_schema(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text(json.dumps({"schema": "nope", "entries": {}}))
        with pytest.raises(ValueError):
            tuning.TuningTable.load(str(p))

    def test_corrupt_cache_falls_back_to_defaults(self, tuning_sandbox):
        # three corruption flavors: unparsable, wrong schema, bad entries
        (tuning_sandbox / "cpu.json").write_text("{not json")
        t = tuning.active_table("cpu")
        assert t.get("dot_product_attention", "flash_min_t") == 4096
        tuning.reset_tables()
        (tuning_sandbox / "cpu.json").write_text(
            json.dumps({"schema": "v0", "entries": {}}))
        assert tuning.active_table("cpu").get(
            "dot_product_attention", "flash_min_t") == 4096
        tuning.reset_tables()
        (tuning_sandbox / "cpu.json").write_text(
            json.dumps({"schema": tuning.SCHEMA, "entries": {"x": 3}}))
        assert tuning.active_table("cpu").get(
            "dot_product_attention", "flash_min_t") == 4096

    def test_malformed_blocks_falls_back_not_crashes(self, tuning_sandbox):
        # schema-valid but malformed: "blocks": null (a hand-merge typo)
        # must land in the warn-once fallback, not crash every tuned() read
        (tuning_sandbox / "cpu.json").write_text(json.dumps({
            "schema": tuning.SCHEMA, "device_kind": "cpu",
            "entries": {"fused_layer_norm": {"blocks": None}}}))
        tuning.reset_tables()
        assert tuning.tuned("dot_product_attention", "flash_min_t") == 4096
        (tuning_sandbox / "cpu.json").write_text(json.dumps({
            "schema": tuning.SCHEMA, "device_kind": "cpu",
            "entries": {"op": {"blocks": {"t64": 512}}}}))  # bucket->scalar
        tuning.reset_tables()
        assert tuning.tuned("dot_product_attention", "flash_min_t") == 4096

    def test_cache_overlays_default(self, tuning_sandbox):
        _write_table(tuning_sandbox,
                     {"dot_product_attention": {"flash_min_t": 99}})
        assert tuning.tuned("dot_product_attention", "flash_min_t") == 99
        # untouched defaults still visible through the overlay
        assert tuning.tuned("fused_updater_step", "min_size") == 65536

    def test_bucket_beats_op_level(self, tuning_sandbox):
        _write_table(tuning_sandbox, {"op": {
            "block_q": 1, "blocks": {"t64": {"block_q": 7}}}})
        assert tuning.tuned("op", "block_q", bucket="t64") == 7
        assert tuning.tuned("op", "block_q", bucket="t128") == 1
        assert tuning.tuned("op", "missing", 5, bucket="t64") == 5


class TestBuckets:
    def test_pow2(self):
        assert [tuning.pow2_bucket(n) for n in (1, 2, 3, 63, 64, 65)] == \
            [1, 2, 4, 64, 64, 128]

    def test_labels(self):
        assert tuning.bucket_t(4097) == "t8192"
        assert tuning.bucket_mkn(100, 512, 513) == "m128_k512_n1024"
        assert tuning.bucket_rows(9) == "r16"

    def test_tuned_block_divisibility_fallback(self, tuning_sandbox):
        _write_table(tuning_sandbox, {"op": {
            "blocks": {"t64": {"block_q": 48}}}})
        # 48 does not divide 64 -> fallback runs
        assert tuning.tuned_block("op", "block_q", 64, "t64",
                                  lambda s: 32) == 32
        # 48 divides 96 -> tuned value wins
        assert tuning.tuned_block("op", "block_q", 96, "t64",
                                  lambda s: 32) == 48


class TestAutotune:
    def test_smoke_subset_saves_and_is_live(self, tuning_sandbox):
        table, report = tuning.autotune(ops=["fused_updater_step"],
                                        smoke=True)
        assert report.ops == ["fused_updater_step"]
        assert report.measurements > 0
        assert os.path.exists(report.table_path)
        loaded = tuning.TuningTable.load(report.table_path)
        assert loaded.get("fused_updater_step", "min_size") is not None
        # autotune(save=True) reset the memoized readers: live immediately
        assert tuning.tuned("fused_updater_step", "min_size") == \
            loaded.get("fused_updater_step", "min_size")

    def test_subset_tune_preserves_other_ops_entries(self, tuning_sandbox):
        """A --ops subset re-tune must refresh only what it measured — not
        clobber previously measured entries for other ops."""
        _write_table(tuning_sandbox,
                     {"fused_layer_norm": {"min_rows": 123}})
        tuning.autotune(ops=["fused_updater_step"], smoke=True)
        saved = tuning.TuningTable.load(str(tuning_sandbox / "cpu.json"))
        assert saved.get("fused_layer_norm", "min_rows") == 123  # kept
        assert saved.get("fused_updater_step", "min_size") is not None

    def test_aot_time_measures(self):
        sec = tuning.aot_time(lambda x: x * 2.0,
                              (jnp.ones((8, 8), jnp.float32),), iters=2,
                              reps=1)
        assert sec > 0.0

    def test_tuning_telemetry(self, tuning_sandbox):
        c = observe.metrics().counter("dl4j_tpu_tuning_runs_total",
                                     op="fused_updater_step")
        before = c.value
        tuning.autotune(ops=["fused_updater_step"], smoke=True, save=False)
        assert c.value == before + 1


class TestFlashMinTCache:
    """Round-9 bugfix: flash_min_t parses once per distinct env value and
    logs the invalid-value warning once, not per resolve call."""

    def test_env_changes_stay_live(self, monkeypatch):
        from deeplearning4j_tpu.ops.pallas_attention import (
            flash_min_t, reset_flash_min_t_cache)

        reset_flash_min_t_cache()
        monkeypatch.delenv("DL4J_TPU_FLASH_MIN_T", raising=False)
        assert flash_min_t() == 4096
        monkeypatch.setenv("DL4J_TPU_FLASH_MIN_T", "123")
        assert flash_min_t() == 123

    def test_invalid_value_warns_once(self, monkeypatch, caplog):
        import logging

        from deeplearning4j_tpu.ops import pallas_attention as pa

        pa.reset_flash_min_t_cache()
        monkeypatch.setenv("DL4J_TPU_FLASH_MIN_T", "junk")
        with caplog.at_level(logging.WARNING,
                             logger="deeplearning4j_tpu.ops.pallas_attention"):
            for _ in range(5):
                assert pa.flash_min_t() == 4096
        warns = [r for r in caplog.records
                 if "DL4J_TPU_FLASH_MIN_T" in r.getMessage()]
        assert len(warns) == 1

    def test_tuned_table_feeds_threshold(self, tuning_sandbox, monkeypatch):
        from deeplearning4j_tpu.ops import pallas_attention as pa

        monkeypatch.delenv("DL4J_TPU_FLASH_MIN_T", raising=False)
        _write_table(tuning_sandbox,
                     {"dot_product_attention": {"flash_min_t": 48}})
        assert pa.flash_min_t() == 48
        # env override still wins over the measured table
        monkeypatch.setenv("DL4J_TPU_FLASH_MIN_T", "96")
        assert pa.flash_min_t() == 96


class TestMeasuredDispatch:
    """Both sides of the tuned threshold for EVERY tuned op, asserted via
    impl identity AND the dispatch-counter family."""

    def test_attention_flash_min_t(self, tuning_sandbox, pallas_mode,
                                   monkeypatch):
        monkeypatch.delenv("DL4J_TPU_FLASH_MIN_T", raising=False)
        _write_table(tuning_sandbox,
                     {"dot_product_attention": {"flash_min_t": 64}})
        desc = registry().get("dot_product_attention")
        lo = jnp.zeros((2, 32, 16), jnp.float32)
        hi = jnp.zeros((2, 128, 16), jnp.float32)
        below, d1 = _dispatch_delta(lambda: desc.resolve(lo, lo, lo))
        above, d2 = _dispatch_delta(lambda: desc.resolve(hi, hi, hi))
        assert below is desc.fn
        assert above is desc.platform_impls["tpu"]
        assert d1.get("dot_product_attention/generic/not_usable") == 1
        assert d2.get("dot_product_attention/tpu/usable") == 1

    def test_fused_matmul_pallas_min_m(self, tuning_sandbox, pallas_mode):
        _write_table(tuning_sandbox,
                     {"fused_matmul_bias_act": {"pallas_min_m": 64}})
        desc = registry().get("fused_matmul_bias_act")
        w = jnp.zeros((128, 128), jnp.float32)
        below, d1 = _dispatch_delta(
            lambda: desc.resolve(jnp.zeros((32, 128), jnp.float32), w))
        above, d2 = _dispatch_delta(
            lambda: desc.resolve(jnp.zeros((64, 128), jnp.float32), w))
        assert below is desc.fn
        assert above is not desc.fn
        assert d1.get("fused_matmul_bias_act/generic/not_usable") == 1
        assert d2.get("fused_matmul_bias_act/tpu/usable") == 1

    def test_layernorm_min_rows(self, tuning_sandbox, pallas_mode):
        _write_table(tuning_sandbox,
                     {"fused_layer_norm": {"min_rows": 32}})
        desc = registry().get("fused_layer_norm")
        g = jnp.ones((128,), jnp.float32)
        below, d1 = _dispatch_delta(
            lambda: desc.resolve(jnp.zeros((16, 128), jnp.float32), g))
        above, d2 = _dispatch_delta(
            lambda: desc.resolve(jnp.zeros((32, 128), jnp.float32), g))
        assert below is desc.fn
        assert above is not desc.fn
        assert d1.get("fused_layer_norm/generic/not_usable") == 1
        assert d2.get("fused_layer_norm/tpu/usable") == 1

    def test_updater_min_size(self, tuning_sandbox, pallas_mode):
        _write_table(tuning_sandbox,
                     {"fused_updater_step": {"min_size": 1024}})
        desc = registry().get("fused_updater_step")
        lr = jnp.float32(1e-2)
        step = jnp.float32(0.0)

        def args(n):
            z = jnp.zeros((n,), jnp.float32)
            return (z, z, lr, step, z)  # Nesterovs: one state leaf (v)

        below, d1 = _dispatch_delta(
            lambda: desc.resolve(*args(512), kind="Nesterovs"))
        above, d2 = _dispatch_delta(
            lambda: desc.resolve(*args(1024), kind="Nesterovs"))
        assert below is desc.fn
        assert above is not desc.fn
        assert d1.get("fused_updater_step/generic/not_usable") == 1
        assert d2.get("fused_updater_step/tpu/usable") == 1

    def test_int8_pallas_min_m(self, tuning_sandbox, pallas_mode):
        _write_table(tuning_sandbox,
                     {"matmul_int8": {"pallas_min_m": 64}})
        desc = registry().get("matmul_int8")
        wq = jnp.zeros((128, 128), jnp.int8)
        ws = jnp.ones((128,), jnp.float32)
        below, d1 = _dispatch_delta(
            lambda: desc.resolve(jnp.zeros((32, 128), jnp.float32), wq, ws))
        above, d2 = _dispatch_delta(
            lambda: desc.resolve(jnp.zeros((64, 128), jnp.float32), wq, ws))
        assert below is desc.fn
        assert above is not desc.fn
        assert d1.get("matmul_int8/generic/not_usable") == 1
        assert d2.get("matmul_int8/tpu/usable") == 1

    def test_paged_decode_min_pages(self, tuning_sandbox, pallas_mode):
        _write_table(tuning_sandbox,
                     {"paged_decode_attention": {"min_pages": 4}})
        desc = registry().get("paged_decode_attention")
        q = jnp.zeros((2, 2, 8), jnp.float32)
        kp = jnp.zeros((8, 8, 2, 8), jnp.float32)
        sl = jnp.zeros((2,), jnp.int32)

        def pt(pages):
            return jnp.zeros((2, pages), jnp.int32)

        below, d1 = _dispatch_delta(
            lambda: desc.resolve(q, kp, kp, pt(2), sl))
        above, d2 = _dispatch_delta(
            lambda: desc.resolve(q, kp, kp, pt(4), sl))
        assert below is desc.fn
        assert above is not desc.fn
        assert d1.get("paged_decode_attention/generic/not_usable") == 1
        assert d2.get("paged_decode_attention/tpu/usable") == 1

    def test_helperless_ops_not_counted(self):
        desc = registry().get("layer_norm")  # no platform impls
        _, delta = _dispatch_delta(
            lambda: desc.resolve(jnp.zeros((4, 8)), jnp.ones((8,))))
        assert not any(k.startswith("layer_norm/") for k in delta)

    def test_forced_xla_counted(self, tuning_sandbox):
        env = environment()
        old = env.helper_mode
        env.helper_mode = "xla"
        try:
            desc = registry().get("fused_layer_norm")
            impl, delta = _dispatch_delta(
                lambda: desc.resolve(jnp.zeros((32, 128), jnp.float32),
                                     jnp.ones((128,), jnp.float32)))
        finally:
            env.helper_mode = old
        assert impl is desc.fn
        assert delta.get("fused_layer_norm/generic/forced_xla") == 1


class TestObserveSurface:
    def test_dispatch_in_summary(self, tuning_sandbox, pallas_mode):
        _write_table(tuning_sandbox,
                     {"fused_layer_norm": {"min_rows": 8}})
        desc = registry().get("fused_layer_norm")
        desc.resolve(jnp.zeros((32, 128), jnp.float32),
                     jnp.ones((128,), jnp.float32))
        s = observe.summary()
        assert "dispatch" in s
        assert any(k.startswith("fused_layer_norm/") for k in s["dispatch"])


class TestSweepFragments:
    """tools/bench_* sweep tools emit mergeable dl4j_tpu_tuning_v1
    fragments (the schema contract; the sweeps themselves need a chip)."""

    def test_fragment_merges_into_default(self, tuning_sandbox):
        frag = tuning.TuningTable(device_kind="cpu")
        frag.set("dot_product_attention", "flash_min_t", 2048)
        frag.set_block("fused_bn_matmul_stats", "m4096_k256_n256",
                       "block_m", 512)
        path = frag.save(str(tuning_sandbox / "fragment.json"))
        base = tuning.active_table("cpu")
        merged = tuning.TuningTable(base.device_kind,
                                    json.loads(json.dumps(base.entries)))
        merged.merge(tuning.TuningTable.load(path))
        assert merged.get("dot_product_attention", "flash_min_t") == 2048
        assert merged.get_block("fused_bn_matmul_stats", "m4096_k256_n256",
                                "block_m") == 512
        # untouched entries survive the merge
        assert merged.get("fused_updater_step", "min_size") == 65536
