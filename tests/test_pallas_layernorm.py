"""Fused LayerNorm(+activation) (ops/pallas_layernorm.py) and the
optimizer's layernorm fusion rule (autodiff/optimize.py).

Interpret-mode Pallas vs the XLA generic at f32 1e-5, gradient equivalence
through the custom_vjp, the tuned usable() gate, and the graph rewrite:
layer_norm→gelu (node and decomposed-erf forms) → ONE fused_layer_norm
node, with negative fixtures left verbatim."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deeplearning4j_tpu.ops  # noqa: F401 - registers catalog + helpers
from deeplearning4j_tpu.autodiff.samediff import SameDiff
from deeplearning4j_tpu.ops.pallas_layernorm import (
    fused_layer_norm, fused_layer_norm_helper, fused_layer_norm_pallas)


def _data(rows=16, d=128, seed=0):
    r = np.random.RandomState(seed)
    x = jnp.asarray(r.randn(rows, d).astype(np.float32))
    g = jnp.asarray((r.rand(d) + 0.5).astype(np.float32))
    b = jnp.asarray(r.randn(d).astype(np.float32))
    return x, g, b


class TestKernelEquivalence:
    @pytest.mark.parametrize("act", ["none", "relu", "gelu", "gelu_exact"])
    def test_interpret_matches_generic(self, act):
        x, g, b = _data()
        want = fused_layer_norm.fn(x, g, b, activation=act)
        got = fused_layer_norm_pallas(x, g, b, activation=act,
                                      block_rows=8, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_no_bias_and_3d(self):
        r = np.random.RandomState(1)
        x = jnp.asarray(r.randn(2, 8, 128).astype(np.float32))
        g = jnp.asarray((r.rand(128) + 0.5).astype(np.float32))
        want = fused_layer_norm.fn(x, g, activation="gelu")
        got = fused_layer_norm_pallas(x, g, activation="gelu",
                                      block_rows=8, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_matches_catalog_layer_norm_plus_gelu(self):
        from deeplearning4j_tpu.ops.nn_ops import layer_norm

        x, g, b = _data(seed=2)
        want = jax.nn.gelu(layer_norm.fn(x, g, b))
        got = fused_layer_norm.fn(x, g, b, activation="gelu")
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)

    def test_non_trailing_axis_rejected(self):
        """gain/bias broadcast along the last axis, so a non-trailing axis
        would silently scale the wrong dim — must raise, not mis-normalize."""
        x, g, b = _data()
        with pytest.raises(ValueError, match="trailing axis"):
            fused_layer_norm.fn(x, g, b, axis=0)
        # trailing axis spelled positively is fine
        got = fused_layer_norm.fn(x, g, b, axis=x.ndim - 1)
        want = fused_layer_norm.fn(x, g, b)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.parametrize("act", ["none", "gelu"])
    def test_gradients_match(self, act):
        x, g, b = _data(rows=8, seed=3)

        def loss(fn):
            return lambda x, g, b: jnp.sum(
                fn(x, g, b, activation=act) ** 2)

        want = jax.grad(loss(fused_layer_norm.fn), argnums=(0, 1, 2))(
            x, g, b)
        got = jax.grad(loss(fused_layer_norm_helper), argnums=(0, 1, 2))(
            x, g, b)
        for w, a in zip(want, got):
            np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                       rtol=1e-4, atol=1e-4)


class TestUsableGate:
    def _usable(self, *args, **kw):
        from deeplearning4j_tpu.ops.pallas_layernorm import _usable

        return _usable(*args, **kw)

    def test_alignment_and_axis(self):
        g128 = jnp.ones((128,), jnp.float32)
        assert self._usable(jnp.zeros((16, 128)), g128)
        assert not self._usable(jnp.zeros((16, 64)), jnp.ones((64,)))
        assert not self._usable(jnp.zeros((15, 128)), g128)  # rows % 8
        assert not self._usable(jnp.zeros((16, 128)), g128, axis=0)
        assert not self._usable(jnp.zeros((128,)), g128)  # rank 1
        assert not self._usable(jnp.zeros((16, 128)), g128,
                                activation="exp")
        assert not self._usable(jnp.zeros((16, 128), jnp.int32), g128)


class TestLayerNormFusionPass:
    def _ln_gelu_graph(self, form="node", optimize=True, axis=-1,
                       extra_consumer=False):
        r = np.random.RandomState(0)
        sd = SameDiff(optimize=optimize)
        x = sd.placeholder("x", (8, 128))
        g = sd.var("g", (r.rand(128).astype(np.float32) + 0.5))
        b = sd.var("b", r.randn(128).astype(np.float32))
        h = sd.nn.layer_norm(x, g, b, axis=axis)
        if form == "node":
            out = sd._record("gelu", [h])
        elif form == "erf":
            sqrt2 = sd.constant("sqrt2", np.float32(np.sqrt(2.0)))
            onec = sd.constant("onec", np.float32(1.0))
            halfc = sd.constant("halfc", np.float32(0.5))
            e = sd._record("erf", [h / sqrt2])
            out = h * (e + onec) * halfc
        else:
            raise AssertionError(form)
        if extra_consumer:
            (h + out).rename("out")
        else:
            out.rename("out")
        return sd

    def _plan_ops(self, sd):
        key = ("plan", ("out",), sd._effective_passes())
        return [n.op for n in sd._jit_cache[key].nodes]

    def _feed(self):
        return {"x": np.random.RandomState(5).randn(8, 128)
                .astype(np.float32)}

    def test_gelu_node_fuses_and_matches(self):
        sd = self._ln_gelu_graph("node")
        feed = self._feed()
        got = sd.exec(feed, "out")["out"]
        ops = self._plan_ops(sd)
        assert "fused_layer_norm" in ops
        assert "layer_norm_graph" not in ops and "gelu" not in ops
        assert sd.last_compile_stats.fusions.get("layernorm") == 1
        want = self._ln_gelu_graph("node", optimize=False).exec(
            feed, "out")["out"]
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_erf_gelu_chain_fuses_exact(self):
        sd = self._ln_gelu_graph("erf")
        feed = self._feed()
        got = sd.exec(feed, "out")["out"]
        ops = self._plan_ops(sd)
        assert "fused_layer_norm" in ops
        assert "erf" not in ops
        plan_nodes = [n for n in sd._jit_cache[
            ("plan", ("out",), sd._effective_passes())].nodes
            if n.op == "fused_layer_norm"]
        assert plan_nodes[0].kwargs["activation"] == "gelu_exact"
        want = self._ln_gelu_graph("erf", optimize=False).exec(
            feed, "out")["out"]
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_shared_ln_output_not_fused(self):
        sd = self._ln_gelu_graph("node", extra_consumer=True)
        sd.exec(self._feed(), "out")
        ops = self._plan_ops(sd)
        assert "fused_layer_norm" not in ops
        assert not sd.last_compile_stats.fusions.get("layernorm")

    def test_plain_layer_norm_left_verbatim(self):
        r = np.random.RandomState(0)
        sd = SameDiff(optimize=True)
        x = sd.placeholder("x", (8, 128))
        g = sd.var("g", (r.rand(128).astype(np.float32) + 0.5))
        sd.nn.layer_norm(x, g).rename("out")
        sd.exec(self._feed(), "out")
        assert "fused_layer_norm" not in self._plan_ops(sd)

    def _with_loss(self, form="node", optimize=True):
        sd = self._ln_gelu_graph(form, optimize=optimize)
        out = sd._vars["out"]
        (out * out).sum().rename("loss")
        return sd

    def test_gradients_flow_through_fused_node(self):
        feed = self._feed()
        grads = self._with_loss().calculate_gradients(
            feed, "loss", ["g", "b"])
        want = self._with_loss(optimize=False).calculate_gradients(
            feed, "loss", ["g", "b"])
        for k in ("g", "b"):
            np.testing.assert_allclose(np.asarray(grads[k]),
                                       np.asarray(want[k]),
                                       rtol=1e-4, atol=1e-4)

    def test_pallas_helper_under_forced_mode(self):
        """The fused node dispatches onto the Pallas interpret kernel under
        helper_mode=pallas on CPU and stays numerically equivalent."""
        from deeplearning4j_tpu.environment import environment

        env = environment()
        old = env.helper_mode
        feed = self._feed()
        want = self._ln_gelu_graph("node", optimize=False).exec(
            feed, "out")["out"]
        env.helper_mode = "pallas"
        try:
            got = self._ln_gelu_graph("node").exec(feed, "out")["out"]
        finally:
            env.helper_mode = old
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)
