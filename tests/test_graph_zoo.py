"""ComputationGraph + zoo tests — reference ComputationGraph tests +
TestInstantiation-style zoo smoke tests (SURVEY §3.3, §5.1)."""

import numpy as np
import pytest

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.nn.graph import (
    ComputationGraph, ComputationGraphConfiguration, ElementWiseVertex,
    L2NormalizeVertex, MergeVertex, ScaleVertex, ShiftVertex, SubsetVertex,
    graph_builder, save_graph, restore_graph,
)
from deeplearning4j_tpu.datasets import DataSet, ListDataSetIterator
from deeplearning4j_tpu import models


def xor_data(n=256, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 2).astype(np.float32)
    y = ((x[:, 0] > 0.5) ^ (x[:, 1] > 0.5)).astype(np.int64)
    labels = np.zeros((n, 2), np.float32)
    labels[np.arange(n), y] = 1.0
    return x, labels


class TestGraphBasics:
    def test_linear_graph_matches_mln(self):
        """Same arch as MLN → same class of results (two-API parity)."""
        x, y = xor_data()
        g = ComputationGraph(
            graph_builder().seed(12).updater(nn.Adam(learning_rate=0.02))
            .weight_init("xavier")
            .add_inputs("in")
            .set_input_types(**{"in": nn.InputType.feed_forward(2)})
            .add_layer("h", nn.DenseLayer(n_out=32, activation="tanh"), "in")
            .add_layer("out", nn.OutputLayer(n_out=2, activation="softmax",
                                             loss="mcxent"), "h")
            .set_outputs("out").build()
        ).init()
        g.fit(x, y, epochs=150, batch_size=128)
        acc = (g.output_single(x).argmax(-1) == y.argmax(-1)).mean()
        assert acc > 0.95, acc

    def test_multi_branch_merge(self):
        g = ComputationGraph(
            graph_builder().seed(1)
            .add_inputs("in")
            .set_input_types(**{"in": nn.InputType.feed_forward(4)})
            .add_layer("a", nn.DenseLayer(n_out=3, activation="relu"), "in")
            .add_layer("b", nn.DenseLayer(n_out=5, activation="tanh"), "in")
            .add_vertex("m", MergeVertex(), "a", "b")
            .add_layer("out", nn.OutputLayer(n_out=2, activation="softmax",
                                             loss="mcxent"), "m")
            .set_outputs("out").build()
        ).init()
        out = g.output_single(np.ones((3, 4), np.float32))
        assert out.shape == (3, 2)
        assert g.conf.nodes[-1].layer.n_in == 8  # 3 + 5 merged

    def test_residual_add(self):
        g = ComputationGraph(
            graph_builder().seed(2)
            .add_inputs("in")
            .set_input_types(**{"in": nn.InputType.feed_forward(6)})
            .add_layer("d", nn.DenseLayer(n_out=6, activation="relu"), "in")
            .add_vertex("add", ElementWiseVertex(op="add"), "d", "in")
            .add_layer("out", nn.OutputLayer(n_out=2, activation="softmax",
                                             loss="mcxent"), "add")
            .set_outputs("out").build()
        ).init()
        assert g.output_single(np.ones((2, 6), np.float32)).shape == (2, 2)

    def test_vertices(self):
        x = np.array([[3.0, 4.0]], np.float32)
        assert np.allclose(ScaleVertex(scale=2.0).apply([x]), [[6, 8]])
        assert np.allclose(ShiftVertex(shift=1.0).apply([x]), [[4, 5]])
        n = L2NormalizeVertex().apply([x])
        assert np.allclose(np.linalg.norm(n), 1.0, atol=1e-5)
        s = SubsetVertex(from_idx=0, to_idx=0).apply([x])
        assert s.shape == (1, 1)
        m = ElementWiseVertex(op="max").apply([x, 2 * x])
        assert np.allclose(m, 2 * x)
        avg = ElementWiseVertex(op="average").apply([x, 3 * x])
        assert np.allclose(avg, 2 * x)

    def test_graph_json_round_trip(self):
        conf = (
            graph_builder().seed(3).updater(nn.Adam(learning_rate=1e-3))
            .add_inputs("in")
            .set_input_types(**{"in": nn.InputType.feed_forward(4)})
            .add_layer("h", nn.DenseLayer(n_out=8, activation="relu"), "in")
            .add_vertex("sc", ScaleVertex(scale=0.5), "h")
            .add_layer("out", nn.OutputLayer(n_out=2, activation="softmax",
                                             loss="mcxent"), "sc")
            .set_outputs("out").build()
        )
        # build once so shape inference fills n_in
        ComputationGraph(conf)
        js = conf.to_json()
        conf2 = ComputationGraphConfiguration.from_json(js)
        g2 = ComputationGraph(conf2).init()
        assert g2.output_single(np.ones((1, 4), np.float32)).shape == (1, 2)

    def test_graph_serde_round_trip(self, tmp_path):
        x, y = xor_data(64)
        g = ComputationGraph(
            graph_builder().seed(4).updater(nn.Adam(learning_rate=0.01))
            .add_inputs("in")
            .set_input_types(**{"in": nn.InputType.feed_forward(2)})
            .add_layer("h", nn.DenseLayer(n_out=8, activation="relu"), "in")
            .add_layer("out", nn.OutputLayer(n_out=2, activation="softmax",
                                             loss="mcxent"), "h")
            .set_outputs("out").build()
        ).init()
        g.fit(x, y, epochs=2, batch_size=32)
        p = str(tmp_path / "g.zip")
        save_graph(g, p)
        g2 = restore_graph(p)
        np.testing.assert_allclose(g2.output_single(x), g.output_single(x),
                                   rtol=1e-5, atol=1e-6)


class TestZoo:
    """Zoo instantiation smoke tests (reference TestInstantiation pattern) —
    small input shapes to keep compile times sane on CPU."""

    def test_lenet(self):
        net = models.LeNet(num_classes=10).init()
        out = net.output(np.zeros((2, 784), np.float32))
        assert out.shape == (2, 10)

    def test_simple_cnn(self):
        net = models.SimpleCNN(num_classes=5, input_shape=(32, 32, 3)).init()
        out = net.output(np.zeros((2, 32, 32, 3), np.float32))
        assert out.shape == (2, 5)

    def test_vgg16_tiny(self):
        net = models.VGG16(num_classes=10, input_shape=(32, 32, 3)).init()
        out = net.output(np.zeros((1, 32, 32, 3), np.float32))
        assert out.shape == (1, 10)

    def test_resnet50_structure(self):
        net = models.ResNet50(num_classes=10, input_shape=(64, 64, 3)).init()
        # 53 conv layers incl. projections; ~23.6M params at 1000 classes
        out = net.output_single(np.zeros((1, 64, 64, 3), np.float32))
        assert out.shape == (1, 10)
        n_convs = sum(1 for n in net.conf.nodes
                      if n.kind == "layer" and isinstance(n.layer, nn.ConvolutionLayer))
        assert n_convs == 53

    def test_resnet50_param_count_imagenet(self):
        net = models.ResNet50(num_classes=1000, input_shape=(32, 32, 3)).init()
        n = net.num_params()
        # reference ResNet-50: ~25.6M with BN params
        assert 23_000_000 < n < 28_000_000, n

    def test_unet(self):
        net = models.UNet(input_shape=(32, 32, 1), base=4).init()
        out = net.output_single(np.zeros((1, 32, 32, 1), np.float32))
        assert out.shape == (1, 32, 32, 1)
        assert (out >= 0).all() and (out <= 1).all()

    def test_darknet19_tiny(self):
        net = models.Darknet19(num_classes=10, input_shape=(32, 32, 3)).init()
        out = net.output(np.zeros((1, 32, 32, 3), np.float32))
        assert out.shape == (1, 10)

    def test_text_generation_lstm(self):
        net = models.TextGenerationLSTM(vocab_size=20, hidden=16).init()
        out = net.output(np.zeros((2, 5, 20), np.float32))
        assert out.shape == (2, 5, 20)

    def test_resnet_trains(self):
        """A tiny ResNet-50 graph takes a gradient step without error."""
        net = models.ResNet50(num_classes=4, input_shape=(32, 32, 3),
                              updater=nn.Sgd(learning_rate=0.01)).init()
        x = np.random.RandomState(0).rand(4, 32, 32, 3).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[[0, 1, 2, 3]]
        net.fit(x, y, epochs=2, batch_size=4)
        assert np.isfinite(net.score())
