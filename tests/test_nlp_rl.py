"""Word2Vec + RL tests (reference word2vec tests + rl4j QLearning tests)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.rl import (
    MDP, EpsGreedy, BoltzmannPolicy, ExpReplay, QLearningConfiguration,
    QLearningDiscrete, ActorCritic,
)
from deeplearning4j_tpu import nn


def toy_corpus():
    """Two topic clusters: numbers co-occur with numbers, animals with animals."""
    rng = np.random.RandomState(0)
    numbers = ["one", "two", "three", "four", "five"]
    animals = ["cat", "dog", "bird", "fish", "horse"]
    sents = []
    for _ in range(300):
        pool = numbers if rng.rand() < 0.5 else animals
        sents.append([pool[rng.randint(5)] for _ in range(8)])
    return sents


class TestWord2Vec:
    def test_vocab_and_vectors(self):
        w2v = Word2Vec(layer_size=16, epochs=1, seed=1)
        w2v.fit(toy_corpus())
        assert w2v.vocab_size() == 10
        assert w2v.get_word_vector("cat").shape == (16,)
        assert w2v.get_word_vector("unknown-word") is None

    def test_topic_clusters_learned(self):
        w2v = Word2Vec(layer_size=32, epochs=5, learning_rate=0.05, seed=2,
                       negative_samples=5)
        hist = w2v.fit(toy_corpus())
        assert hist[-1] < hist[0]
        # within-cluster similarity beats cross-cluster
        within = w2v.similarity("cat", "dog")
        across = w2v.similarity("cat", "two")
        assert within > across, (within, across)

    def test_words_nearest(self):
        w2v = Word2Vec(layer_size=32, epochs=5, learning_rate=0.05, seed=3)
        w2v.fit(toy_corpus())
        nearest = w2v.words_nearest("one", n=4)
        animals = {"cat", "dog", "bird", "fish", "horse"}
        # majority of nearest neighbours of a number are numbers
        hits = sum(1 for w in nearest if w not in animals)
        assert hits >= 3, nearest

    def test_serde(self, tmp_path):
        w2v = Word2Vec(layer_size=8, epochs=1, seed=4)
        w2v.fit(toy_corpus())
        p = str(tmp_path / "w2v.npz")
        w2v.save(p)
        w2 = Word2Vec.load(p)
        np.testing.assert_allclose(w2.get_word_vector("cat"),
                                   w2v.get_word_vector("cat"))


class ChainMDP(MDP):
    """5-state chain: action 1 moves right (+1 at the end), action 0 resets.
    Optimal return from start = 1.0 reaching the end."""

    def __init__(self, length=5):
        self.length = length
        self.pos = 0

    def reset(self):
        self.pos = 0
        return self._obs()

    def _obs(self):
        o = np.zeros(self.length, np.float32)
        o[self.pos] = 1.0
        return o

    def step(self, action):
        if action == 1:
            self.pos += 1
            if self.pos >= self.length - 1:
                return self._obs(), 1.0, True
            return self._obs(), 0.0, False
        self.pos = 0
        return self._obs(), 0.01, False  # small distractor reward

    @property
    def num_actions(self):
        return 2

    @property
    def obs_size(self):
        return self.length


def q_net(obs_size, n_actions, seed=0):
    return nn.MultiLayerNetwork(
        nn.builder().seed(seed).updater(nn.Adam(learning_rate=5e-3)).list()
        .layer(nn.DenseLayer(n_out=32, activation="relu"))
        .layer(nn.OutputLayer(n_out=n_actions, activation="identity", loss="mse"))
        .set_input_type(nn.InputType.feed_forward(obs_size)).build()
    ).init()


class TestPolicies:
    def test_eps_greedy_anneals(self):
        p = EpsGreedy(eps_start=1.0, eps_min=0.1, anneal_steps=10)
        assert p.epsilon() == 1.0
        for _ in range(20):
            p.next_action(np.array([0.0, 1.0]))
        assert p.epsilon() == pytest.approx(0.1)

    def test_boltzmann_prefers_high_q(self):
        p = BoltzmannPolicy(temperature=0.1, seed=0)
        picks = [p.next_action(np.array([0.0, 2.0])) for _ in range(100)]
        assert np.mean(picks) > 0.9

    def test_replay_buffer(self):
        r = ExpReplay(max_size=5, batch_size=3, seed=0)
        for i in range(10):
            r.store((np.zeros(2), 0, float(i), np.zeros(2), False))
        assert len(r) == 5
        s, a, rew, s2, d = r.sample()
        assert s.shape == (3, 2)


class TestDQN:
    def test_dqn_learns_chain(self):
        mdp = ChainMDP()
        net = q_net(mdp.obs_size, mdp.num_actions, seed=7)
        dqn = QLearningDiscrete(mdp, net, QLearningConfiguration(
            gamma=0.95, batch_size=32, target_update_freq=50, start_size=32,
            eps_anneal_steps=300, seed=7))
        dqn.train(episodes=60, max_steps=30)
        score = dqn.play(max_steps=30)
        assert score == pytest.approx(1.0), score  # reaches the goal greedily

    def test_double_dqn_flag(self):
        mdp = ChainMDP()
        net = q_net(mdp.obs_size, mdp.num_actions)
        dqn = QLearningDiscrete(mdp, net, QLearningConfiguration(
            double_dqn=False, start_size=8, batch_size=8))
        dqn.train(episodes=3, max_steps=10)
        assert len(dqn.episode_rewards) == 3


class TestActorCritic:
    def test_ac_learns_chain(self):
        mdp = ChainMDP()
        pnet = nn.MultiLayerNetwork(
            nn.builder().seed(3).updater(nn.Adam(learning_rate=5e-3)).list()
            .layer(nn.DenseLayer(n_out=32, activation="relu"))
            .layer(nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(mdp.obs_size)).build()).init()
        vnet = nn.MultiLayerNetwork(
            nn.builder().seed(4).updater(nn.Adam(learning_rate=5e-3)).list()
            .layer(nn.DenseLayer(n_out=32, activation="relu"))
            .layer(nn.OutputLayer(n_out=1, activation="identity", loss="mse"))
            .set_input_type(nn.InputType.feed_forward(mdp.obs_size)).build()).init()
        ac = ActorCritic(mdp, pnet, vnet, gamma=0.95, n_steps=16, seed=5)
        ac.train_steps(3000, max_episode_steps=30)
        # policy strongly prefers moving right at the start state
        probs = pnet.output(mdp.reset()[None])[0]
        assert probs[1] > 0.8, probs


def toy_corpus2():
    base = [
        "the cat sat on the mat".split(),
        "the dog sat on the log".split(),
        "cats and dogs are animals".split(),
        "the king rules the kingdom".split(),
        "the queen rules the kingdom".split(),
    ]
    return base * 30


class TestGloVe:
    def test_fit_and_similarity(self):
        from deeplearning4j_tpu.nlp import GloVe

        g = GloVe(layer_size=16, window_size=3, epochs=30,
                  learning_rate=0.1, seed=1)
        losses = g.fit(toy_corpus2())
        assert losses[-1] < losses[0]  # the WLS objective decreases
        assert g.get_word_vector("cat").shape == (16,)
        assert np.isfinite(g.similarity("king", "queen"))
        assert "cat" not in g.words_nearest("cat", 3)

    def test_cooccurrence_weighting(self):
        from deeplearning4j_tpu.nlp import GloVe

        g = GloVe(window_size=2)
        g.build_vocab([["a", "b", "c"]])
        rows, cols, vals = g._cooccurrences([["a", "b", "c"]])
        pairs = {(int(r), int(c)): float(v)
                 for r, c, v in zip(rows, cols, vals)}
        a, b, c = g.vocab["a"], g.vocab["b"], g.vocab["c"]
        assert pairs[(a, b)] == 1.0      # distance 1
        assert pairs[(a, c)] == 0.5      # distance 2 → 1/2
        assert pairs[(b, a)] == 1.0      # symmetric


class TestParagraphVectors:
    def test_fit_infer_and_nearest(self):
        from deeplearning4j_tpu.nlp import LabelledDocument, ParagraphVectors

        cats = "the cat sat on the mat and the cat purred".split()
        dogs = "the dog ran in the park and the dog barked".split()
        docs = [LabelledDocument(cats, "cats"),
                LabelledDocument(dogs, "dogs"),
                LabelledDocument(cats + ["feline"], "cats2"),
                LabelledDocument(dogs + ["canine"], "dogs2")]
        pv = ParagraphVectors(layer_size=16, epochs=60, batch_size=16,
                              learning_rate=0.05, seed=3)
        losses = pv.fit(docs)
        assert losses[-1] < losses[0]
        assert pv.get_doc_vector("cats").shape == (16,)
        # same-topic documents are closer than cross-topic ones
        assert pv.similarity("cats", "cats2") > pv.similarity("cats", "dogs")
        # inference on an unseen doc lands near the same-topic vectors
        near = pv.nearest_labels("the cat sat and purred".split(), n=2)
        assert "cats" in near or "cats2" in near


class TestAsyncRL:
    def test_history_processor(self):
        from deeplearning4j_tpu.rl import HistoryProcessor

        hp = HistoryProcessor(history_length=3, skip_frames=2)
        kept = [hp.record(np.full((2,), i, np.float32)) for i in range(6)]
        assert kept == [True, False, True, False, True, False]
        h = hp.get_history()
        assert h.shape == (3, 2)
        np.testing.assert_allclose(h[:, 0], [0, 2, 4])
        hp.reset()
        hp.record(np.ones((2,)))
        h = hp.get_history()
        np.testing.assert_allclose(h[0], 0)  # zero-padded until warm

    def test_gym_mdp_adapter(self):
        from deeplearning4j_tpu.rl import GymMDP

        class FakeGym:
            class Space:
                n = 3
                shape = (4,)

            action_space = Space()
            observation_space = Space()

            def reset(self):
                return np.zeros(4), {}

            def step(self, a):
                return np.ones(4) * a, 1.0, a == 2, False, {}

        mdp = GymMDP(FakeGym())
        assert mdp.obs_size == 4 and mdp.num_actions == 3
        obs = mdp.reset()
        assert obs.shape == (4,)
        obs, r, done = mdp.step(2)
        assert r == 1.0 and done and obs[0] == 2.0

    def test_a3c_learns_chain(self):
        from deeplearning4j_tpu.rl import A3CDiscrete

        def make_net(n_out, activation):
            b = nn.builder().seed(5).updater(nn.Adam(learning_rate=5e-3)).list()
            b.layer(nn.DenseLayer(n_out=32, activation="tanh"))
            b.layer(nn.OutputLayer(n_out=n_out, activation=activation,
                                   loss="mcxent" if activation == "softmax"
                                   else "mse"))
            conf = b.set_input_type(nn.InputType.feed_forward(5)).build()
            return nn.MultiLayerNetwork(conf).init()

        a3c = A3CDiscrete(lambda: ChainMDP(), make_net(2, "softmax"),
                          make_net(1, "identity"), n_envs=4, n_steps=8,
                          gamma=0.95, seed=7)
        a3c.train(batches=120)
        # the learned policy walks the chain: average recent episode reward
        # approaches the optimal 1.0 (vs 0.0x for the distractor loop)
        recent = a3c.episode_rewards[-20:]
        assert len(recent) >= 5
        assert np.mean(recent) > 0.8


class TestHierarchicalSoftmax:
    def test_huffman_codes_prefix_free_and_frequency_ordered(self):
        w2v = Word2Vec(layer_size=8, use_hierarchic_softmax=True)
        w2v.build_vocab(toy_corpus2())
        paths, codes, mask = w2v._build_huffman()
        lens = mask.sum(axis=1)
        # most frequent word gets one of the SHORTEST codes
        assert lens[0] == lens.min()
        # codes are prefix-free: all (path, code) full sequences distinct
        seqs = {tuple(zip(paths[i][:int(lens[i])], codes[i][:int(lens[i])]))
                for i in range(len(lens))}
        assert len(seqs) == len(lens)

    def test_hs_training_learns(self):
        w2v = Word2Vec(layer_size=16, window_size=3, epochs=8,
                       use_hierarchic_softmax=True, seed=2,
                       learning_rate=0.05)
        losses = w2v.fit(toy_corpus2())
        assert losses[-1] < losses[0]
        assert np.isfinite(w2v.similarity("king", "queen"))


from deeplearning4j_tpu.rl.dqn import dueling_q_net


class TestDuelingDQN:
    def test_aggregation_formula(self):
        # Q must equal V + A - mean(A) exactly (identifiable dueling head)
        net = dueling_q_net(4, 3, hidden=8, seed=1)
        p = net.params[1]
        r = np.random.RandomState(0)
        x = r.randn(5, 4).astype(np.float32)
        h = np.maximum(x @ np.asarray(net.params[0]["W"])
                       + np.asarray(net.params[0]["b"]), 0.0)
        v = h @ np.asarray(p["Wv"]) + np.asarray(p["bv"])
        a = h @ np.asarray(p["Wa"]) + np.asarray(p["ba"])
        want = v + a - a.mean(axis=-1, keepdims=True)
        np.testing.assert_allclose(net.output(x), want, atol=1e-5)

    def test_dueling_dqn_learns_chain(self):
        mdp = ChainMDP()
        net = dueling_q_net(mdp.obs_size, mdp.num_actions, hidden=32, seed=7)
        dqn = QLearningDiscrete(mdp, net, QLearningConfiguration(
            gamma=0.95, batch_size=32, target_update_freq=50, start_size=32,
            eps_anneal_steps=300, seed=7))
        dqn.train(episodes=60, max_steps=30)
        assert dqn.play(max_steps=30) == pytest.approx(1.0)


class TestAsyncNStepQ:
    def test_learns_chain(self):
        from deeplearning4j_tpu.rl.async_rl import AsyncNStepQLearningDiscrete
        net = q_net(5, 2, seed=11)
        alg = AsyncNStepQLearningDiscrete(
            ChainMDP, net, n_envs=8, n_steps=5, gamma=0.95,
            target_update_freq=20, eps_anneal_batches=80, seed=11)
        losses = alg.train(batches=150)
        assert np.isfinite(losses[-1])
        assert alg.play(ChainMDP(), max_steps=30) == pytest.approx(1.0)
