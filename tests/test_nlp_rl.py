"""Word2Vec + RL tests (reference word2vec tests + rl4j QLearning tests)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.rl import (
    MDP, EpsGreedy, BoltzmannPolicy, ExpReplay, QLearningConfiguration,
    QLearningDiscrete, ActorCritic,
)
from deeplearning4j_tpu import nn


def toy_corpus():
    """Two topic clusters: numbers co-occur with numbers, animals with animals."""
    rng = np.random.RandomState(0)
    numbers = ["one", "two", "three", "four", "five"]
    animals = ["cat", "dog", "bird", "fish", "horse"]
    sents = []
    for _ in range(300):
        pool = numbers if rng.rand() < 0.5 else animals
        sents.append([pool[rng.randint(5)] for _ in range(8)])
    return sents


class TestWord2Vec:
    def test_vocab_and_vectors(self):
        w2v = Word2Vec(layer_size=16, epochs=1, seed=1)
        w2v.fit(toy_corpus())
        assert w2v.vocab_size() == 10
        assert w2v.get_word_vector("cat").shape == (16,)
        assert w2v.get_word_vector("unknown-word") is None

    def test_topic_clusters_learned(self):
        w2v = Word2Vec(layer_size=32, epochs=5, learning_rate=0.05, seed=2,
                       negative_samples=5)
        hist = w2v.fit(toy_corpus())
        assert hist[-1] < hist[0]
        # within-cluster similarity beats cross-cluster
        within = w2v.similarity("cat", "dog")
        across = w2v.similarity("cat", "two")
        assert within > across, (within, across)

    def test_words_nearest(self):
        w2v = Word2Vec(layer_size=32, epochs=5, learning_rate=0.05, seed=3)
        w2v.fit(toy_corpus())
        nearest = w2v.words_nearest("one", n=4)
        animals = {"cat", "dog", "bird", "fish", "horse"}
        # majority of nearest neighbours of a number are numbers
        hits = sum(1 for w in nearest if w not in animals)
        assert hits >= 3, nearest

    def test_serde(self, tmp_path):
        w2v = Word2Vec(layer_size=8, epochs=1, seed=4)
        w2v.fit(toy_corpus())
        p = str(tmp_path / "w2v.npz")
        w2v.save(p)
        w2 = Word2Vec.load(p)
        np.testing.assert_allclose(w2.get_word_vector("cat"),
                                   w2v.get_word_vector("cat"))


class ChainMDP(MDP):
    """5-state chain: action 1 moves right (+1 at the end), action 0 resets.
    Optimal return from start = 1.0 reaching the end."""

    def __init__(self, length=5):
        self.length = length
        self.pos = 0

    def reset(self):
        self.pos = 0
        return self._obs()

    def _obs(self):
        o = np.zeros(self.length, np.float32)
        o[self.pos] = 1.0
        return o

    def step(self, action):
        if action == 1:
            self.pos += 1
            if self.pos >= self.length - 1:
                return self._obs(), 1.0, True
            return self._obs(), 0.0, False
        self.pos = 0
        return self._obs(), 0.01, False  # small distractor reward

    @property
    def num_actions(self):
        return 2

    @property
    def obs_size(self):
        return self.length


def q_net(obs_size, n_actions, seed=0):
    return nn.MultiLayerNetwork(
        nn.builder().seed(seed).updater(nn.Adam(learning_rate=5e-3)).list()
        .layer(nn.DenseLayer(n_out=32, activation="relu"))
        .layer(nn.OutputLayer(n_out=n_actions, activation="identity", loss="mse"))
        .set_input_type(nn.InputType.feed_forward(obs_size)).build()
    ).init()


class TestPolicies:
    def test_eps_greedy_anneals(self):
        p = EpsGreedy(eps_start=1.0, eps_min=0.1, anneal_steps=10)
        assert p.epsilon() == 1.0
        for _ in range(20):
            p.next_action(np.array([0.0, 1.0]))
        assert p.epsilon() == pytest.approx(0.1)

    def test_boltzmann_prefers_high_q(self):
        p = BoltzmannPolicy(temperature=0.1, seed=0)
        picks = [p.next_action(np.array([0.0, 2.0])) for _ in range(100)]
        assert np.mean(picks) > 0.9

    def test_replay_buffer(self):
        r = ExpReplay(max_size=5, batch_size=3, seed=0)
        for i in range(10):
            r.store((np.zeros(2), 0, float(i), np.zeros(2), False))
        assert len(r) == 5
        s, a, rew, s2, d = r.sample()
        assert s.shape == (3, 2)


class TestDQN:
    def test_dqn_learns_chain(self):
        mdp = ChainMDP()
        net = q_net(mdp.obs_size, mdp.num_actions, seed=7)
        dqn = QLearningDiscrete(mdp, net, QLearningConfiguration(
            gamma=0.95, batch_size=32, target_update_freq=50, start_size=32,
            eps_anneal_steps=300, seed=7))
        dqn.train(episodes=60, max_steps=30)
        score = dqn.play(max_steps=30)
        assert score == pytest.approx(1.0), score  # reaches the goal greedily

    def test_double_dqn_flag(self):
        mdp = ChainMDP()
        net = q_net(mdp.obs_size, mdp.num_actions)
        dqn = QLearningDiscrete(mdp, net, QLearningConfiguration(
            double_dqn=False, start_size=8, batch_size=8))
        dqn.train(episodes=3, max_steps=10)
        assert len(dqn.episode_rewards) == 3


class TestActorCritic:
    def test_ac_learns_chain(self):
        mdp = ChainMDP()
        pnet = nn.MultiLayerNetwork(
            nn.builder().seed(3).updater(nn.Adam(learning_rate=5e-3)).list()
            .layer(nn.DenseLayer(n_out=32, activation="relu"))
            .layer(nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(mdp.obs_size)).build()).init()
        vnet = nn.MultiLayerNetwork(
            nn.builder().seed(4).updater(nn.Adam(learning_rate=5e-3)).list()
            .layer(nn.DenseLayer(n_out=32, activation="relu"))
            .layer(nn.OutputLayer(n_out=1, activation="identity", loss="mse"))
            .set_input_type(nn.InputType.feed_forward(mdp.obs_size)).build()).init()
        ac = ActorCritic(mdp, pnet, vnet, gamma=0.95, n_steps=16, seed=5)
        ac.train_steps(3000, max_episode_steps=30)
        # policy strongly prefers moving right at the start state
        probs = pnet.output(mdp.reset()[None])[0]
        assert probs[1] > 0.8, probs
