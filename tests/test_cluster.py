"""Cluster-router tests (serving/cluster.py — docs/ROBUSTNESS.md
§ Cluster failure domains).

The properties under test mirror the ``cluster`` gate stage:
  * routing follows prefix affinity and load/health, deterministically;
  * whole-engine death migrates in-flight retryable work to survivors at
    queue FRONT with the ORIGINAL submit time and priority — deadlines
    and ``peek_best_pending`` ordering never invert across a migration;
  * a migrated greedy generation is bit-identical to the single-engine
    oracle, with zero ``new_shape`` ledger events on survivors;
  * every request reaches exactly one labelled terminal state;
  * the frontend's circuit breaker is per-engine: one dead/thrashing
    engine never fast-fails admissions a healthy sibling could serve.
"""

import time

import numpy as np
import pytest

from deeplearning4j_tpu import faults, observe
from deeplearning4j_tpu.faults import InjectedFault
from deeplearning4j_tpu.models.gpt import (
    GptConfig, GptModel, reference_generate,
)
from deeplearning4j_tpu.serving import (
    ClusterRouter, GenerativeEngine, SLOFrontend,
)
from deeplearning4j_tpu.serving.overload import _serving_new_shape_count

CFG = GptConfig.tiny()
MODEL = GptModel(CFG, seed=1)

PROMPTS = [np.array([3, 5, 7, 9], np.int32),
           np.array([11, 2], np.int32),
           np.array([42, 43, 44, 45, 46, 47], np.int32)]


def make_engine(**kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_pages_per_seq", 6)
    kw.setdefault("max_prompt", 16)
    kw.setdefault("seed", 3)
    kw.setdefault("restart_backoff_s", 0.0)
    return GenerativeEngine(MODEL, **kw)


def make_router(n=2, router_kw=None, **ekw):
    engines = [make_engine(**ekw) for _ in range(n)]
    return ClusterRouter(engines, **(router_kw or {}))


def evicted_counts():
    out = {}
    for inst in observe.metrics().instruments():
        if inst.name == "dl4j_tpu_serving_evicted_total" and inst.labels:
            out[dict(inst.labels)["reason"]] = int(inst.value)
    return out


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv(faults.FAULTS_ENV, raising=False)
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# routing: load, affinity, health
# ---------------------------------------------------------------------------


class TestRouting:
    def test_engines_renumbered_and_least_loaded_wins(self):
        r = make_router(2)
        assert [e.engine_id for e in r.engines] == [0, 1]
        # two queued requests make engine 0 the loaded one
        r.engines[0].submit(PROMPTS[0], max_new_tokens=4)
        r.engines[0].submit(PROMPTS[1], max_new_tokens=4)
        r.submit(PROMPTS[2], max_new_tokens=4)
        assert len(r.engines[1].scheduler.pending) == 1
        assert len(r.engines[0].scheduler.pending) == 2

    def test_prefix_affinity_routes_to_cached_engine(self):
        r = make_router(2, prefix_pages=8)
        shared = np.arange(1, 13, dtype=np.int32)          # 12 tokens
        # warm ONLY engine 1's radix tree; loads stay equal (drained)
        r.engines[1].generate([shared], max_new_tokens=1, eos_token=-1)
        m = observe.metrics()
        before = m.counter("dl4j_tpu_cluster_routed_total",
                           engine="1", reason="affinity").value
        prompt = np.concatenate([shared, [99, 100]]).astype(np.int32)
        r.submit(prompt, max_new_tokens=4)
        assert len(r.engines[1].scheduler.pending) == 1
        assert len(r.engines[0].scheduler.pending) == 0
        assert m.counter("dl4j_tpu_cluster_routed_total",
                         engine="1", reason="affinity").value == before + 1

    def test_affinity_yields_to_load_imbalance(self):
        """Cache locality must not pile work onto a drowning engine: past
        ``affinity_max_imbalance`` waves of extra load the cached engine
        loses to the idle one."""
        r = make_router(2, prefix_pages=8,
                        router_kw=dict(affinity_max_imbalance=2.0))
        shared = np.arange(1, 13, dtype=np.int32)
        r.engines[1].generate([shared], max_new_tokens=1, eos_token=-1)
        for _ in range(5):  # 5 queued / 2 slots = 2.5 waves > 2.0
            r.engines[1].submit(PROMPTS[0], max_new_tokens=4)
        prompt = np.concatenate([shared, [99, 100]]).astype(np.int32)
        r.submit(prompt, max_new_tokens=4)
        assert len(r.engines[0].scheduler.pending) == 1

    def test_dead_engines_excluded_until_none_left(self):
        r = make_router(2)
        r._on_engine_death(r.engines[0], RuntimeError("boom"))
        assert [e.engine_id for e in r.live_engines()] == [1]
        r.submit(PROMPTS[0], max_new_tokens=4)
        assert len(r.engines[1].scheduler.pending) == 1
        assert len(r.engines[0].scheduler.pending) == 0
        r._on_engine_death(r.engines[1], RuntimeError("boom"))
        with pytest.raises(RuntimeError, match="no live engine"):
            r.submit(PROMPTS[0], max_new_tokens=4)

    def test_restart_thrash_quarantines_but_never_strands(self):
        r = make_router(2, router_kw=dict(quarantine_restarts=2,
                                          quarantine_window_s=60.0,
                                          quarantine_cooldown_s=30.0))
        q0 = observe.metrics().counter(
            "dl4j_tpu_cluster_quarantined_total").value
        r.engines[0].restarts = 2       # a thrash burst inside the window
        r.submit(PROMPTS[0], max_new_tokens=4)
        assert len(r.engines[1].scheduler.pending) == 1
        assert observe.metrics().counter(
            "dl4j_tpu_cluster_quarantined_total").value == q0 + 1
        # quarantine deprioritises, never strands: with the healthy
        # sibling dead, the quarantined engine still serves
        r._on_engine_death(r.engines[1], RuntimeError("boom"))
        r.submit(PROMPTS[1], max_new_tokens=4)
        assert len(r.engines[0].scheduler.pending) >= 1


# ---------------------------------------------------------------------------
# the engine_death fault point (satellite)
# ---------------------------------------------------------------------------


class TestEngineDeathFault:
    def test_engine_death_is_hard_and_unrestartable(self):
        """Without a router, engine_death spends the restart budget and
        fails every submitted future — loudly, not as a hang."""
        faults.arm("engine_death", prob=1.0, max_fires=1)
        eng = make_engine()
        eng.start()
        futs = [eng.submit(p, max_new_tokens=4) for p in PROMPTS]
        with pytest.raises(InjectedFault, match="engine_death"):
            for f in futs:
                f.result(timeout=60)
        assert eng.restarts == eng.max_restarts
        assert isinstance(eng._error, InjectedFault)
        assert eng._error.point == "engine_death"
        assert observe.metrics().counter(
            "dl4j_tpu_faults_injected_total", point="engine_death").value >= 1
        eng.stop()


# ---------------------------------------------------------------------------
# cross-engine migration
# ---------------------------------------------------------------------------


class TestMigration:
    def test_kill_one_engine_mid_flight_bit_identical_to_oracle(self):
        """The tentpole property, end to end: one engine hard-killed
        mid-flight, every request terminal, >= 1 in-flight migration, the
        greedy outputs token-for-token the single-engine oracle's, and
        zero ``new_shape`` on the survivor."""
        r = make_router(2)
        prompts = [np.arange(1, 9, dtype=np.int32) + i for i in range(6)]
        for e in r.engines:  # compile before the clock starts
            e.generate([prompts[0]], max_new_tokens=2, eos_token=-1)
        before_terminal = sum(evicted_counts().values())
        new_shape0 = _serving_new_shape_count()
        r.start()
        faults.arm("slow_decode", prob=1.0)       # keep work in flight
        faults.arm("engine_death", prob=1.0, after_n=4, max_fires=1)
        futs = [r.submit(p, max_new_tokens=6, eos_token=-1, max_retries=3)
                for p in prompts]
        res = [f.result(timeout=120) for f in futs]
        faults.reset()
        assert r.deaths == 1 and len(r.live_engines()) == 1
        assert r.migrations >= 1
        assert all(x.finish_reason == "length" for x in res)
        for p, x in zip(prompts, res):
            np.testing.assert_array_equal(
                x.tokens, reference_generate(MODEL.params, CFG, p, 6))
        assert _serving_new_shape_count() == new_shape0
        # terminal taxonomy: every request exactly one labelled counter
        assert (sum(evicted_counts().values())
                == before_terminal + len(prompts))
        r.check_invariants()
        r.stop()

    def test_migrated_request_keeps_submit_time_and_priority(self):
        """The bugfix satellite: migration re-admits with the ORIGINAL
        submit time and priority, so the request expires at the same wall
        deadline it would have on its first engine and the pending order
        never inverts."""
        r = make_router(2)
        e0, e1 = r.engines
        fut = e0.submit(PROMPTS[0], max_new_tokens=64, eos_token=-1,
                        deadline_s=0.25, max_retries=2, priority=2)
        e0.step()                       # admit + first token: IN FLIGHT
        (slot,) = e0.scheduler.active_slots()
        st = e0.scheduler.slots[slot]
        orig_submit_t = st.submit_t
        r._on_engine_death(e0, RuntimeError("boom"))
        (item,) = e1.scheduler.pending_snapshot()
        req, mig_fut, submit_t = item
        assert mig_fut is fut               # the SAME future, not a chain
        assert submit_t == orig_submit_t    # deadline keeps counting
        assert req.priority == 2            # ordering never inverts
        assert req.retries_used == 1        # migration charged one retry
        # the deadline is measured from the ORIGINAL submit: once that
        # wall instant passes, the survivor's sweep retires it
        while time.perf_counter() - orig_submit_t <= 0.25:
            time.sleep(0.01)
        e1.step()
        assert fut.result(timeout=10).finish_reason == "deadline"

    def test_in_flight_without_retry_budget_fails_terminally(self):
        r = make_router(2)
        e0, e1 = r.engines
        before = evicted_counts().get("error", 0)
        fut = e0.submit(PROMPTS[0], max_new_tokens=64, eos_token=-1,
                        max_retries=0)
        e0.step()
        r._on_engine_death(e0, RuntimeError("boom"))
        assert fut.result(timeout=10).finish_reason == "error"
        assert e1.scheduler.pending_snapshot() == []
        assert evicted_counts().get("error", 0) == before + 1

    def test_pending_migrates_in_order_without_retry_charge(self):
        r = make_router(2)
        e0, e1 = r.engines
        futs = [e0.submit(p, max_new_tokens=4) for p in PROMPTS]
        r._on_engine_death(e0, RuntimeError("boom"))
        items = e1.scheduler.pending_snapshot()
        assert [it[1] for it in items] == futs     # order preserved
        assert all(it[0].retries_used == 0 for it in items)

    def test_no_survivors_every_request_terminal_error(self):
        r = make_router(1)
        futs = [r.engines[0].submit(p, max_new_tokens=4) for p in PROMPTS]
        r._on_engine_death(r.engines[0], RuntimeError("boom"))
        assert all(f.result(timeout=10).finish_reason == "error"
                   for f in futs)

    def test_pinned_prefix_rewarms_on_destination(self):
        """A pinned per-class prefix lost with the dead engine is carried
        back onto the destination behind the migrated work."""
        r = make_router(2, prefix_pages=8)
        e0, e1 = r.engines
        shared = np.arange(1, 13, dtype=np.int32)
        r.prewarm_prefix(shared, pin=True)
        e1.prefix.clear()               # cold destination (intents survive)
        fut = e0.submit(PROMPTS[0], max_new_tokens=4, eos_token=-1,
                        max_retries=2)
        e0.step()
        r._on_engine_death(e0, RuntimeError("boom"))
        while e1.scheduler.has_work():  # drain migrated + re-warm work
            e1.step()
        assert fut.result(timeout=10).finish_reason == "length"
        probe = np.concatenate([shared, [99, 100]]).astype(np.int32)
        m = e1.prefix.match(probe)
        assert m is not None and m.matched >= 8
        assert e1.prefix.pinned_pages >= 1


# ---------------------------------------------------------------------------
# lifecycle + SLO frontend composition
# ---------------------------------------------------------------------------


class TestLifecycleAndFrontend:
    def test_threaded_lifecycle(self):
        r = make_router(2).start()
        r.start()                                        # idempotent
        fut = r.submit(PROMPTS[0], max_new_tokens=3, eos_token=-1)
        assert fut.result(timeout=60).finish_reason == "length"
        r.stop()
        r.stop()                                         # idempotent
        assert all(e.stopped_cleanly for e in r.engines)
        with pytest.raises(RuntimeError):
            r.submit(PROMPTS[0], max_new_tokens=3)

    def test_frontend_breaker_is_per_engine(self):
        """One thrashing engine must not fast-fail admissions the healthy
        sibling could serve; only ALL engines open fast-fails."""
        r = make_router(2)
        fe = SLOFrontend(r, breaker_restarts=2, breaker_window_s=60.0,
                         breaker_cooldown_s=600.0)
        r.engines[0].restarts = 2
        fut = fe.submit(PROMPTS[0], max_new_tokens=4)
        assert not fut.done()            # admitted (queued), NOT fast-failed
        assert fe.breaker_opens == 1 and not fe.breaker_open
        assert observe.metrics().gauge(
            "dl4j_tpu_slo_breaker_open").value == 0.5
        r.engines[1].restarts = 2
        fut2 = fe.submit(PROMPTS[1], max_new_tokens=4)
        assert fut2.result(timeout=10).finish_reason == "error"
        assert fe.breaker_open and fe.breaker_opens == 2
        assert observe.metrics().gauge(
            "dl4j_tpu_slo_breaker_open").value == 1.0

    def test_frontend_single_engine_breaker_regression(self):
        """The pre-cluster path: a single engine's open breaker still
        fast-fails with the historical gauge values."""
        eng = make_engine()
        fe = SLOFrontend(eng, breaker_restarts=2, breaker_window_s=60.0,
                         breaker_cooldown_s=600.0)
        eng.restarts = 2
        fut = fe.submit(PROMPTS[0], max_new_tokens=4)
        assert fut.result(timeout=10).finish_reason == "error"
        assert fe.breaker_open and fe.breaker_opens == 1
        assert observe.metrics().gauge(
            "dl4j_tpu_slo_breaker_open").value == 1.0

    def test_frontend_capacity_tracks_live_engines(self):
        """The frontend's wave estimates see the cluster's LIVE capacity:
        a death shrinks ``max_slots`` so the ladder degrades
        proportionally instead of pretending dead slots exist."""
        r = make_router(2)
        assert r.scheduler.max_slots == 4
        r._on_engine_death(r.engines[0], RuntimeError("boom"))
        assert r.scheduler.max_slots == 2
