"""Round-3 layer/vertex/zoo breadth — each new layer type gets a gradient
check (the reference's GradientCheckUtil per-layer pattern, SURVEY §5.2)
plus a forward-shape test; new vertices get forward-semantics tests; new
zoo models build and run at reduced input sizes."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.autodiff.gradcheck import check_gradients
from deeplearning4j_tpu.nn import conf as C
from deeplearning4j_tpu.nn import graph as G


from tests._helpers import _mln, _rng


class TestNewLayerGradchecks:
    def test_conv1d(self):
        net = _mln([
            nn.Convolution1D(n_out=5, kernel=3, convolution_mode="same",
                             activation="tanh"),
            nn.GlobalPoolingLayer(pooling_type="avg"),
            nn.OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
        ], nn.InputType.recurrent(4, 6))
        r = _rng(0)
        x = r.randn(3, 6, 4)
        y = np.eye(3)[r.randint(0, 3, 3)]
        assert check_gradients(net, x, y)

    def test_conv3d_and_pool3d(self):
        net = _mln([
            nn.Convolution3D(n_out=4, kernel=(2, 2, 2),
                             convolution_mode="valid", activation="tanh"),
            nn.Subsampling3DLayer(kernel=(2, 2, 2), stride=(2, 2, 2)),
            nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], nn.InputType.convolutional3d(5, 5, 5, 2))
        r = _rng(1)
        x = r.randn(2, 5, 5, 5, 2)
        y = np.eye(2)[r.randint(0, 2, 2)]
        out = net.output(x.astype(np.float32))
        assert out.shape == (2, 2)
        assert check_gradients(net, x, y)

    def test_locally_connected_2d(self):
        net = _mln([
            nn.LocallyConnected2D(n_out=3, kernel=(2, 2), activation="tanh"),
            nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], nn.InputType.convolutional(5, 5, 2))
        r = _rng(2)
        x = r.randn(2, 5, 5, 2)
        y = np.eye(2)[r.randint(0, 2, 2)]
        assert check_gradients(net, x, y)

    def test_locally_connected_2d_unshared(self):
        """Same input patch at two positions must produce DIFFERENT outputs
        (the defining unshared-weights property vs ConvolutionLayer)."""
        net = _mln([
            nn.LocallyConnected2D(n_out=1, kernel=(1, 1),
                                  activation="identity"),
            nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], nn.InputType.convolutional(3, 3, 1))
        feats = net.feed_forward(np.ones((1, 3, 3, 1), np.float32))
        lc_out = feats[0]
        assert np.std(lc_out) > 1e-4  # per-position weights differ

    def test_locally_connected_1d(self):
        net = _mln([
            nn.LocallyConnected1D(n_out=4, kernel=2, activation="tanh"),
            nn.GlobalPoolingLayer(pooling_type="max"),
            nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], nn.InputType.recurrent(3, 5))
        r = _rng(3)
        x = r.randn(2, 5, 3)
        y = np.eye(2)[r.randint(0, 2, 2)]
        assert check_gradients(net, x, y)

    def test_prelu(self):
        net = _mln([
            nn.DenseLayer(n_out=6, activation="identity"),
            nn.PReLULayer(),
            nn.OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
        ], nn.InputType.feed_forward(4))
        r = _rng(4)
        x = r.randn(5, 4)
        y = np.eye(3)[r.randint(0, 3, 5)]
        assert check_gradients(net, x, y)
        # alpha actually used: negative inputs scale by alpha
        alpha = np.asarray(net.params[1]["alpha"])
        np.testing.assert_allclose(alpha, 0.25)

    def test_learned_self_attention(self):
        net = _mln([
            nn.LearnedSelfAttentionLayer(n_out=8, n_heads=2, n_queries=3),
            nn.GlobalPoolingLayer(pooling_type="avg"),
            nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], nn.InputType.recurrent(4, 7))
        r = _rng(5)
        x = r.randn(2, 7, 4)
        y = np.eye(2)[r.randint(0, 2, 2)]
        out = net.output(x.astype(np.float32))
        assert out.shape == (2, 2)
        assert check_gradients(net, x, y)

    def test_recurrent_attention(self):
        net = _mln([
            nn.RecurrentAttentionLayer(n_out=5, activation="tanh"),
            nn.GlobalPoolingLayer(pooling_type="avg"),
            nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], nn.InputType.recurrent(3, 6))
        r = _rng(6)
        x = r.randn(2, 6, 3)
        y = np.eye(2)[r.randint(0, 2, 2)]
        assert check_gradients(net, x, y)

    def test_vae_forward_and_elbo(self):
        net = _mln([
            nn.VariationalAutoencoder(n_out=4, encoder_layer_sizes=(8,),
                                      decoder_layer_sizes=(8,),
                                      activation="tanh"),
            nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], nn.InputType.feed_forward(6))
        r = _rng(7)
        x = r.randn(3, 6)
        y = np.eye(2)[r.randint(0, 2, 3)]
        assert check_gradients(net, x, y)
        # pretrain objective: ELBO is finite and differentiable
        vae_impl = net.layers[0]
        loss = vae_impl.elbo_loss(net.params[0], jnp.asarray(x, jnp.float32),
                                  jax.random.key(0))
        g = jax.grad(lambda p: vae_impl.elbo_loss(
            p, jnp.asarray(x, jnp.float32), jax.random.key(0)))(net.params[0])
        assert np.isfinite(float(loss))
        assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))


class TestNewVertices:
    def test_attention_vertex_graph(self):
        b = (G.graph_builder().seed(3).updater(nn.Sgd(learning_rate=0.1))
             .add_inputs("q", "kv")
             .set_input_types(q=nn.InputType.recurrent(4, 5),
                              kv=nn.InputType.recurrent(4, 9)))
        b.add_vertex("attn", C.AttentionVertex(n_out=8, n_heads=2,
                                               n_in_queries=4, n_in_keys=4,
                                               n_in_values=4), "q", "kv")
        b.add_layer("gap", nn.GlobalPoolingLayer(pooling_type="avg"), "attn")
        b.add_layer("out", nn.OutputLayer(n_out=2, activation="softmax",
                                          loss="mcxent"), "gap")
        b.set_outputs("out")
        net = G.ComputationGraph(b.build()).init()
        r = _rng(8)
        q = r.randn(2, 5, 4).astype(np.float32)
        kv = r.randn(2, 9, 4).astype(np.float32)
        out = net.output(q, kv)[0]
        assert out.shape == (2, 2)
        # trains: loss decreases over a few steps
        y = np.eye(2)[r.randint(0, 2, 2)].astype(np.float32)
        first = last = None
        for i in range(30):
            s = net.fit_multi([q, kv], [y])
            first = s if first is None else first
            last = s
        assert last < first

    def test_unstack_vertex(self):
        b = (G.graph_builder().add_inputs("a", "b")
             .set_input_types(a=nn.InputType.feed_forward(3),
                              b=nn.InputType.feed_forward(3)))
        b.add_vertex("stack", G.StackVertex(), "a", "b")
        b.add_vertex("u0", G.UnstackVertex(from_idx=0, stack_size=2), "stack")
        b.add_vertex("u1", G.UnstackVertex(from_idx=1, stack_size=2), "stack")
        b.add_vertex("diff", G.ElementWiseVertex(op="subtract"), "u1", "u0")
        b.add_layer("out", nn.LossLayer(loss="mse"), "diff")
        b.set_outputs("out")
        net = G.ComputationGraph(b.build()).init()
        a = np.ones((2, 3), np.float32)
        bb = 3 * np.ones((2, 3), np.float32)
        np.testing.assert_allclose(net.output(a, bb)[0], 2 * np.ones((2, 3)))

    def test_duplicate_to_time_series_vertex(self):
        b = (G.graph_builder().add_inputs("vec", "seq")
             .set_input_types(vec=nn.InputType.feed_forward(3),
                              seq=nn.InputType.recurrent(2, 4)))
        b.add_vertex("dup", G.DuplicateToTimeSeriesVertex(), "vec", "seq")
        b.add_vertex("cat", G.MergeVertex(), "dup", "seq")
        b.add_layer("out", nn.LossLayer(loss="mse"), "cat")
        b.set_outputs("out")
        net = G.ComputationGraph(b.build()).init()
        vec = np.arange(6, dtype=np.float32).reshape(2, 3)
        seq = np.zeros((2, 4, 2), np.float32)
        out = net.output(vec, seq)[0]
        assert out.shape == (2, 4, 5)
        for t in range(4):
            np.testing.assert_allclose(out[:, t, :3], vec)

    def test_last_time_step_vertex(self):
        b = (G.graph_builder().add_inputs("seq")
             .set_input_types(seq=nn.InputType.recurrent(3, 5)))
        b.add_vertex("last", G.LastTimeStepVertex(), "seq")
        b.add_layer("out", nn.LossLayer(loss="mse"), "last")
        b.set_outputs("out")
        net = G.ComputationGraph(b.build()).init()
        x = _rng(9).randn(2, 5, 3).astype(np.float32)
        np.testing.assert_allclose(net.output(x)[0], x[:, -1])


class TestNewZooModels:
    def test_vgg19_builds_and_runs(self):
        net = __import__("deeplearning4j_tpu.models", fromlist=["VGG19"]) \
            .VGG19(num_classes=5, input_shape=(64, 64, 3)).init()
        out = net.output(np.random.rand(1, 64, 64, 3).astype(np.float32))
        assert out.shape == (1, 5)
        np.testing.assert_allclose(out.sum(), 1.0, rtol=1e-4)

    def test_squeezenet_builds_and_runs(self):
        from deeplearning4j_tpu.models import SqueezeNet

        net = SqueezeNet(num_classes=4, input_shape=(67, 67, 3)).init()
        out = net.output(np.random.rand(1, 67, 67, 3).astype(np.float32))[0]
        assert out.shape == (1, 4)
        np.testing.assert_allclose(out.sum(), 1.0, rtol=1e-4)

    def test_xception_builds_and_runs(self):
        from deeplearning4j_tpu.models import Xception

        net = Xception(num_classes=3, input_shape=(71, 71, 3),
                       middle_repeats=1).init()
        out = net.output(np.random.rand(1, 71, 71, 3).astype(np.float32))[0]
        assert out.shape == (1, 3)
        np.testing.assert_allclose(out.sum(), 1.0, rtol=1e-4)

    def test_tiny_yolo_builds_and_loss(self):
        from deeplearning4j_tpu.models import TinyYOLO

        zoo = TinyYOLO(num_classes=4, num_boxes=2, input_shape=(64, 64, 3))
        net = zoo.init()
        x = np.random.rand(1, 64, 64, 3).astype(np.float32)
        pred = net.output(x)
        assert pred.shape == (1, 2, 2, 2 * (5 + 4))
        target = np.zeros((1, 2, 2, 2, 9), np.float32)
        target[0, 1, 1, 0, :] = [0.5, 0.5, 0.1, 0.1, 1, 1, 0, 0, 0]
        loss = float(zoo.yolo_loss(jnp.asarray(pred), jnp.asarray(target)))
        assert np.isfinite(loss) and loss > 0
        g = jax.grad(lambda p: zoo.yolo_loss(p, jnp.asarray(target)))(
            jnp.asarray(pred))
        assert np.isfinite(np.asarray(g)).all()


class TestReviewRegressions:
    def test_attention_vertex_distinct_dims(self):
        """queries and keys/values with DIFFERENT widths — catches the
        dropped-second-input bug where q self-attended silently."""
        b = (G.graph_builder().seed(3).updater(nn.Sgd(learning_rate=0.1))
             .add_inputs("q", "kv")
             .set_input_types(q=nn.InputType.recurrent(6, 4),
                              kv=nn.InputType.recurrent(10, 7)))
        b.add_vertex("attn", C.AttentionVertex(n_out=8, n_heads=2,
                                               n_in_queries=6, n_in_keys=10,
                                               n_in_values=10), "q", "kv")
        b.add_layer("gap", nn.GlobalPoolingLayer(pooling_type="avg"), "attn")
        b.add_layer("out", nn.OutputLayer(n_out=2, activation="softmax",
                                          loss="mcxent"), "gap")
        b.set_outputs("out")
        net = G.ComputationGraph(b.build()).init()
        r = _rng(11)
        q = r.randn(2, 4, 6).astype(np.float32)
        kv = r.randn(2, 7, 10).astype(np.float32)
        out = net.output(q, kv)[0]
        assert out.shape == (2, 2)
        # output must actually DEPEND on kv (the dropped-input bug didn't)
        kv2 = kv + 1.0
        out2 = net.output(q, kv2)[0]
        assert not np.allclose(out, out2)

    def test_conv3d_dense_graph(self):
        """Conv3D → Dense inside a ComputationGraph (5-D flatten path)."""
        b = (G.graph_builder().seed(1).updater(nn.Sgd(learning_rate=0.1))
             .add_inputs("vol")
             .set_input_types(vol=nn.InputType.convolutional3d(4, 6, 6, 2)))
        b.add_layer("c3", nn.Convolution3D(n_out=3, kernel=(2, 2, 2),
                                           convolution_mode="valid",
                                           activation="tanh"), "vol")
        b.add_layer("fc", nn.DenseLayer(n_out=5, activation="relu"), "c3")
        b.add_layer("out", nn.OutputLayer(n_out=2, activation="softmax",
                                          loss="mcxent"), "fc")
        b.set_outputs("out")
        net = G.ComputationGraph(b.build()).init()
        x = _rng(12).randn(2, 4, 6, 6, 2).astype(np.float32)
        out = net.output(x)[0]
        assert out.shape == (2, 2)

    def test_conv1d_mask_subsampled(self):
        net = _mln([
            nn.Convolution1D(n_out=4, kernel=3, stride=2,
                             convolution_mode="same", activation="tanh"),
            nn.RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], nn.InputType.recurrent(3, 8))
        x = _rng(13).randn(2, 8, 3).astype(np.float32)
        mask = np.asarray([[1] * 8, [1] * 5 + [0] * 3], np.float32)
        out = net.output(x, mask=mask)
        assert out.shape[1] == 4  # T=8 stride 2 → 4 steps, mask followed

    def test_conv3d_network_serde_roundtrip(self):
        net = _mln([
            nn.Convolution3D(n_out=3, kernel=(2, 2, 2),
                             convolution_mode="valid", activation="tanh"),
            nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], nn.InputType.convolutional3d(4, 5, 5, 2))
        from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration

        js = net.conf.to_json()
        conf2 = MultiLayerConfiguration.from_json(js)
        net2 = nn.MultiLayerNetwork(conf2).init(net.params)
        x = _rng(14).randn(2, 4, 5, 5, 2).astype(np.float32)
        np.testing.assert_allclose(net.output(x), net2.output(x), rtol=1e-6)


    def test_inception_resnet_v1_builds_and_runs(self):
        from deeplearning4j_tpu.models import InceptionResNetV1

        net = InceptionResNetV1(num_classes=4, input_shape=(96, 96, 3),
                                blocks=(1, 1, 1)).init()
        out = net.output(np.random.rand(1, 96, 96, 3).astype(np.float32))[0]
        assert out.shape == (1, 4)
        np.testing.assert_allclose(out.sum(), 1.0, rtol=1e-4)

    def test_yolo2_builds_with_passthrough(self):
        """YOLO2: Darknet19 backbone + SpaceToDepth passthrough concat —
        zoo/model/YOLO2.java parity (round-4; the reorg halves the route's
        spatial dims and 4x its channels before the merge)."""
        from deeplearning4j_tpu.models import YOLO2

        zoo = YOLO2(num_classes=3, num_boxes=2, input_shape=(64, 64, 3))
        net = zoo.init()
        x = np.random.rand(1, 64, 64, 3).astype(np.float32)
        pred = net.output(x)[0]
        assert pred.shape == (1, 2, 2, 2 * (5 + 3)), pred.shape
        assert np.isfinite(pred).all()
        # the passthrough reorg layer exists in the DAG
        assert "reorg" in net.layers
