"""Flash-attention kernel tests — the two-backends-one-answer pattern
(SURVEY §5.2): Pallas kernel vs the generic XLA attention oracle."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.pallas_attention import (
    flash_attention, flash_mha, _reference_attention, register_platform_attention,
)


def rand_qkv(bh=4, t=64, d=32, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(bh, t, d).astype(np.float32)),
            jnp.asarray(rng.randn(bh, t, d).astype(np.float32)),
            jnp.asarray(rng.randn(bh, t, d).astype(np.float32)))


class TestFlashAttention:
    def test_matches_reference(self):
        q, k, v = rand_qkv()
        out = flash_attention(q, k, v, None, None, False, 16, 16, True)
        ref = _reference_attention(q, k, v, scale=1.0 / np.sqrt(32), causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_causal_matches_reference(self):
        q, k, v = rand_qkv(t=32)
        out = flash_attention(q, k, v, None, None, True, 16, 16, True)
        ref = _reference_attention(q, k, v, scale=1.0 / np.sqrt(32), causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_non_divisible_seq_len(self):
        q, k, v = rand_qkv(t=50)  # not a multiple of block
        out = flash_attention(q, k, v, None, None, False, 16, 16, True)
        ref = _reference_attention(q, k, v, scale=1.0 / np.sqrt(32), causal=False)
        # zero-padded keys contribute exp(s) mass — guard: compare unpadded
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-3, atol=1e-4)

    def test_gradients_flow(self):
        q, k, v = rand_qkv(bh=2, t=16, d=16)

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, None, None, False, 8, 8, True) ** 2)

        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        def ref_loss(q, k, v):
            return jnp.sum(_reference_attention(
                q, k, v, scale=1.0 / np.sqrt(16), causal=False) ** 2)

        rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), rtol=1e-3, atol=1e-4)

    def test_flash_mha_wrapper(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(2, 24, 32).astype(np.float32))
        out = flash_mha(x, x, x, num_heads=4, interpret=True)
        assert out.shape == (2, 24, 32)

    def test_long_sequence_blocks(self):
        q, k, v = rand_qkv(bh=1, t=256, d=16, seed=3)
        out = flash_attention(q, k, v, None, None, False, 64, 64, True)
        ref = _reference_attention(q, k, v, scale=1.0 / np.sqrt(16), causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_platform_registration(self):
        from deeplearning4j_tpu.ops.registry import registry

        register_platform_attention()
        desc = registry().get("dot_product_attention")
        assert "tpu" in desc.platform_impls


    def test_key_padding_mask_matches_reference(self):
        q, k, v = rand_qkv(bh=3, t=40, d=16, seed=5)
        rng = np.random.RandomState(7)
        mask = jnp.asarray((rng.rand(3, 40) > 0.3).astype(np.float32))
        out = flash_attention(q, k, v, mask, None, False, 16, 16, True)
        ref = _reference_attention(q, k, v, scale=1.0 / np.sqrt(16),
                                   causal=False, kv_mask=mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-3, atol=1e-4)

    def test_masked_gradients_match_reference(self):
        q, k, v = rand_qkv(bh=2, t=24, d=16, seed=9)
        mask = jnp.asarray((np.arange(24)[None, :] < np.array([[20], [16]]))
                           .astype(np.float32))

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, mask, None, False, 8, 8,
                                           True) ** 2)

        def ref_loss(q, k, v):
            return jnp.sum(_reference_attention(
                q, k, v, scale=1.0 / np.sqrt(16), causal=False,
                kv_mask=mask) ** 2)

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        r = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)
