"""Flash-attention kernel tests — the two-backends-one-answer pattern
(SURVEY §5.2): Pallas kernel vs the generic XLA attention oracle."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.pallas_attention import (
    flash_attention, flash_mha, _reference_attention, register_platform_attention,
)


def rand_qkv(bh=4, t=64, d=32, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(bh, t, d).astype(np.float32)),
            jnp.asarray(rng.randn(bh, t, d).astype(np.float32)),
            jnp.asarray(rng.randn(bh, t, d).astype(np.float32)))


class TestFlashAttention:
    def test_matches_reference(self):
        q, k, v = rand_qkv()
        out = flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)
        ref = _reference_attention(q, k, v, scale=1.0 / np.sqrt(32), causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_causal_matches_reference(self):
        q, k, v = rand_qkv(t=32)
        out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16, interpret=True)
        ref = _reference_attention(q, k, v, scale=1.0 / np.sqrt(32), causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_non_divisible_seq_len(self):
        q, k, v = rand_qkv(t=50)  # not a multiple of block
        out = flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)
        ref = _reference_attention(q, k, v, scale=1.0 / np.sqrt(32), causal=False)
        # zero-padded keys contribute exp(s) mass — guard: compare unpadded
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-3, atol=1e-4)

    def test_gradients_flow(self):
        q, k, v = rand_qkv(bh=2, t=16, d=16)

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, block_q=8, block_k=8, interpret=True) ** 2)

        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        def ref_loss(q, k, v):
            return jnp.sum(_reference_attention(
                q, k, v, scale=1.0 / np.sqrt(16), causal=False) ** 2)

        rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), rtol=1e-3, atol=1e-4)

    def test_flash_mha_wrapper(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(2, 24, 32).astype(np.float32))
        out = flash_mha(x, x, x, num_heads=4, interpret=True)
        assert out.shape == (2, 24, 32)

    def test_long_sequence_blocks(self):
        q, k, v = rand_qkv(bh=1, t=256, d=16, seed=3)
        out = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
        ref = _reference_attention(q, k, v, scale=1.0 / np.sqrt(16), causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_platform_registration(self):
        from deeplearning4j_tpu.ops.registry import registry

        register_platform_attention()
        desc = registry().get("dot_product_attention")
        assert "tpu" in desc.platform_impls


    def test_key_padding_mask_matches_reference(self):
        q, k, v = rand_qkv(bh=3, t=40, d=16, seed=5)
        rng = np.random.RandomState(7)
        mask = jnp.asarray((rng.rand(3, 40) > 0.3).astype(np.float32))
        out = flash_attention(q, k, v, mask, block_q=16, block_k=16, interpret=True)
        ref = _reference_attention(q, k, v, scale=1.0 / np.sqrt(16),
                                   causal=False, kv_mask=mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-3, atol=1e-4)

    def test_dropout_zero_rate_matches_reference(self):
        q, k, v = rand_qkv(bh=2, t=32, d=16, seed=11)
        seed = jnp.asarray([[5]], jnp.int32)
        out = flash_attention(q, k, v, None, seed, block_q=16, block_k=16,
                              interpret=True, dropout_rate=0.0)
        ref = _reference_attention(q, k, v, scale=1.0 / np.sqrt(16),
                                   causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_dropout_deterministic_and_unbiased(self):
        q, k, v = rand_qkv(bh=2, t=32, d=16, seed=13)
        seed = jnp.asarray([[42]], jnp.int32)
        kw = dict(block_q=16, block_k=16, interpret=True, dropout_rate=0.3)
        a = flash_attention(q, k, v, None, seed, **kw)
        b = flash_attention(q, k, v, None, seed, **kw)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        c = flash_attention(q, k, v, None, jnp.asarray([[43]], jnp.int32), **kw)
        assert not np.allclose(np.asarray(a), np.asarray(c))
        # E[dropout(attn)] over seeds ≈ no-dropout output
        ref = _reference_attention(q, k, v, scale=1.0 / np.sqrt(16),
                                   causal=False)
        outs = [np.asarray(flash_attention(
            q, k, v, None, jnp.asarray([[s]], jnp.int32), **kw))
            for s in range(64)]
        err = np.abs(np.mean(outs, axis=0) - np.asarray(ref)).max()
        assert err < 0.15, f"dropout mean deviates from expectation: {err}"

    def test_dropout_gradients_flow_and_match_forward_mask(self):
        # gradient of sum(out) wrt v for a fixed seed equals the jacobian of
        # the (linear-in-v) dropped attention — check against numeric diff
        q, k, v = rand_qkv(bh=1, t=16, d=8, seed=17)
        seed = jnp.asarray([[7]], jnp.int32)
        kw = dict(block_q=8, block_k=8, interpret=True, dropout_rate=0.25)

        def loss(v):
            return jnp.sum(flash_attention(q, k, v, None, seed, **kw))

        g = np.asarray(jax.grad(loss)(v))
        eps = 1e-3
        v_np = np.asarray(v)
        for idx in [(0, 3, 2), (0, 9, 5)]:
            dv = v_np.copy(); dv[idx] += eps
            up = float(loss(jnp.asarray(dv)))
            dv[idx] -= 2 * eps
            dn = float(loss(jnp.asarray(dv)))
            num = (up - dn) / (2 * eps)
            np.testing.assert_allclose(g[idx], num, rtol=2e-2, atol=1e-3)

    def test_dropout_requires_seed(self):
        q, k, v = rand_qkv(bh=1, t=16, d=8)
        with pytest.raises(ValueError):
            flash_attention(q, k, v, interpret=True, dropout_rate=0.1)

    def test_masked_gradients_match_reference(self):
        q, k, v = rand_qkv(bh=2, t=24, d=16, seed=9)
        mask = jnp.asarray((np.arange(24)[None, :] < np.array([[20], [16]]))
                           .astype(np.float32))

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, mask, block_q=8, block_k=8,
                                           interpret=True) ** 2)

        def ref_loss(q, k, v):
            return jnp.sum(_reference_attention(
                q, k, v, scale=1.0 / np.sqrt(16), causal=False,
                kv_mask=mask) ** 2)

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        r = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)


class TestShapeAwareDispatch:
    """The registry must route dot_product_attention by kv length: XLA below
    the measured crossover (flash loses to the fused path at small T —
    BENCH_HISTORY attention_sweep), the Pallas helper at/above it. The
    threshold is DL4J_TPU_FLASH_MIN_T (default 4096), read at resolve time."""

    def _desc(self):
        from deeplearning4j_tpu.ops.registry import registry

        register_platform_attention()  # idempotent under `in reg` guard
        return registry().get("dot_product_attention")

    def _qkv(self, t, d=16):
        x = jnp.zeros((2, t, d), jnp.float32)
        return x, x, x

    def test_default_threshold(self, monkeypatch):
        from deeplearning4j_tpu.ops.pallas_attention import flash_min_t

        monkeypatch.delenv("DL4J_TPU_FLASH_MIN_T", raising=False)
        assert flash_min_t() == 4096
        monkeypatch.setenv("DL4J_TPU_FLASH_MIN_T", "512")
        assert flash_min_t() == 512
        monkeypatch.setenv("DL4J_TPU_FLASH_MIN_T", "junk")
        assert flash_min_t() == 4096

    def test_dispatch_both_sides_of_boundary(self, monkeypatch):
        from deeplearning4j_tpu.environment import environment

        desc = self._desc()
        env = environment()
        old = env.helper_mode
        env.helper_mode = "pallas"  # force platform-table resolution on CPU
        try:
            monkeypatch.setenv("DL4J_TPU_FLASH_MIN_T", "64")
            below = desc.resolve(*self._qkv(t=63))
            at = desc.resolve(*self._qkv(t=64))
            above = desc.resolve(*self._qkv(t=128))
            assert below is desc.fn, "below threshold must fall back to XLA"
            assert at is desc.platform_impls["tpu"]
            assert above is desc.platform_impls["tpu"]
        finally:
            env.helper_mode = old

    def test_dropout_overrides_threshold(self, monkeypatch):
        """In-kernel dropout flips the crossover (the generic path pays a
        (T, T) HBM mask) — flash stays selected below the threshold."""
        from deeplearning4j_tpu.environment import environment

        desc = self._desc()
        env = environment()
        old = env.helper_mode
        env.helper_mode = "pallas"
        try:
            monkeypatch.setenv("DL4J_TPU_FLASH_MIN_T", "4096")
            q, k, v = self._qkv(t=32)
            got = desc.resolve(q, k, v, dropout_rate=0.1,
                               dropout_rng=jax.random.key(0))
            assert got is desc.platform_impls["tpu"]
        finally:
            env.helper_mode = old

    def test_causal_prefill_equivalence_across_dispatch(self):
        """The serving prefill calls the op with causal=True: both resolved
        impls must agree (1e-2/1e-5) so the dispatch threshold can never
        change generated text."""
        r = np.random.RandomState(4)
        q = jnp.asarray(r.randn(1, 2, 24, 16).astype(np.float32))
        k = jnp.asarray(r.randn(1, 2, 24, 16).astype(np.float32))
        v = jnp.asarray(r.randn(1, 2, 24, 16).astype(np.float32))
        mask = jnp.asarray((np.arange(24) < 20).astype(np.float32)
                           .reshape(1, 1, 1, 24))
        from deeplearning4j_tpu.ops.registry import registry

        desc = registry().get("dot_product_attention")
        generic = desc.fn(q, k, v, mask > 0.5, scaled=True, causal=True)
        flash = desc.platform_impls["tpu"](q, k, v, mask, scaled=True,
                                           causal=True)
        np.testing.assert_allclose(np.asarray(flash)[:, :, :20],
                                   np.asarray(generic)[:, :, :20],
                                   rtol=1e-2, atol=1e-5)
