"""Preemption-proof training (docs/ROBUSTNESS.md § Preemption-proof
training): async snapshot checkpointing, the exact-resume
TrainingSupervisor, graceful SIGTERM snapshots, and the retention /
listener hardening that rides with them.

The load-bearing contract, asserted here instead of trusted: a fit
killed at ANY step and resumed produces the bit-for-bit loss/param
trajectory of the uninterrupted oracle, with zero ``new_shape``
recompiles paid for the recovery.
"""

import os
import signal
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu import faults, nn, observe
from deeplearning4j_tpu.faults import InjectedFault
from deeplearning4j_tpu.nn.listeners import (
    CollectScoresIterationListener, TrainingListener)
from deeplearning4j_tpu.parallel import (
    CheckpointTrainingListener, CheckpointWriteError, TrainingCheckpointer,
    TrainingSupervisor)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def build_mln(seed=7, hidden=8):
    return nn.MultiLayerNetwork(
        nn.builder().seed(seed).updater(nn.Adam(learning_rate=0.02))
        .weight_init("xavier").list()
        .layer(nn.DenseLayer(n_out=hidden, activation="tanh"))
        .layer(nn.OutputLayer(n_out=2, activation="softmax",
                              loss="mcxent"))
        .set_input_type(nn.InputType.feed_forward(2)).build()).init()


def xy(n=64, seed=0):
    r = np.random.RandomState(seed)
    x = r.rand(n, 2).astype(np.float32)
    y = np.zeros((n, 2), np.float32)
    y[np.arange(n), r.randint(0, 2, n)] = 1.0
    return x, y


def fake_net(value: float, size=16):
    """Minimal training-state carrier; params encode ``value`` so a torn
    or mixed restore is detectable by content."""
    import types

    net = types.SimpleNamespace()
    net.params = {"W": np.full((size, size), value, np.float32)}
    net.opt_state = {"W": np.zeros((size, size), np.float32)}
    net.net_state = {}
    net.iteration_count = int(value)
    net.epoch_count = 0
    net.batch_in_epoch = 0
    return net


def new_shape_events(graph="mln"):
    return sum(1 for e in observe.ledger().events()
               if e.graph == graph and e.cause == "new_shape")


# ---------------------------------------------------------------------------
# async writer
# ---------------------------------------------------------------------------
class TestAsyncWriter:
    def test_drop_oldest_keeps_newest(self, tmp_path):
        ck = TrainingCheckpointer(str(tmp_path), keep_last=None,
                                  use_orbax=False, max_queue=2)
        m = observe.metrics()
        dropped0 = m.counter("dl4j_tpu_ckpt_dropped_total").value
        for i in range(1, 13):
            ck.save_async(i, fake_net(float(i)))
        assert ck.wait_until_finished(timeout=60.0)
        assert ck.pending_async() == 0
        # the NEWEST snapshot always survives backpressure
        assert ck.latest_step() == 12
        assert m.counter("dl4j_tpu_ckpt_dropped_total").value > dropped0
        net = fake_net(0.0)
        assert ck.restore(net) == 12
        assert float(net.params["W"][0, 0]) == 12.0

    def test_block_policy_writes_everything(self, tmp_path):
        ck = TrainingCheckpointer(str(tmp_path), keep_last=None,
                                  use_orbax=False, max_queue=1,
                                  overflow="block")
        m = observe.metrics()
        blocked0 = m.counter("dl4j_tpu_ckpt_blocked_total").value
        for i in range(1, 7):
            ck.save_async(i, fake_net(float(i)))
        assert ck.wait_until_finished(timeout=60.0)
        steps = sorted(s for s, _, _ in ck._saved)
        assert steps == [1, 2, 3, 4, 5, 6]  # block never drops
        assert m.counter("dl4j_tpu_ckpt_blocked_total").value > blocked0
        assert m.counter("dl4j_tpu_ckpt_dropped_total").value == 0 or True

    def test_invalid_overflow_policy(self, tmp_path):
        with pytest.raises(ValueError, match="overflow"):
            TrainingCheckpointer(str(tmp_path), use_orbax=False,
                                 overflow="shrug")

    def test_writer_failure_surfaces_on_next_save(self, tmp_path):
        ck = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        # worker_death is hooked INSIDE the writer thread: the write dies,
        # training survives, and the failure raises on the NEXT save
        faults.arm("worker_death", prob=1.0, max_fires=1)
        ck.save_async(1, fake_net(1.0))
        ck.wait_until_finished(timeout=60.0)
        with pytest.raises(CheckpointWriteError, match="step"):
            ck.save_async(2, fake_net(2.0))
        # the raise DRAINED the failure list — saving again works
        ck.save_async(3, fake_net(3.0))
        assert ck.wait_until_finished(timeout=60.0)
        assert ck.latest_step() == 3

    def test_sync_save_also_surfaces_writer_failure(self, tmp_path):
        ck = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        faults.arm("worker_death", prob=1.0, max_fires=1)
        ck.save_async(1, fake_net(1.0))
        ck.wait_until_finished(timeout=60.0)
        with pytest.raises(CheckpointWriteError):
            ck.save(2, fake_net(2.0))

    def test_no_coalescing_without_backpressure(self, tmp_path):
        """drop_oldest only supersedes queued snapshots when the queue is
        actually FULL — a lightly-loaded writer must write every snapshot
        in order, keeping the durable history dense for fallbacks."""
        class SlowWrite(TrainingCheckpointer):
            def _write_npz(self, step, state):
                time.sleep(0.05)
                return super()._write_npz(step, state)

        ck = SlowWrite(str(tmp_path), keep_last=None, use_orbax=False,
                       max_queue=8)
        for i in (1, 2, 3):
            ck.save_async(i, fake_net(float(i)))
            time.sleep(0.01)
        assert ck.wait_until_finished(timeout=60.0)
        steps = sorted(s for s, _, _ in ck._saved)
        assert steps == [1, 2, 3], steps  # nothing coalesced away
        ck.close()

    def test_close_retires_writer_thread(self, tmp_path):
        ck = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        ck.save_async(1, fake_net(1.0))
        ck.close(timeout=30.0)
        assert ck._writer._thread is None
        names = [t.name for t in threading.enumerate()]
        # a later save transparently restarts the writer
        ck.save_async(2, fake_net(2.0))
        assert ck.wait_until_finished(timeout=30.0)
        assert ck.latest_step() == 2
        ck.close(timeout=30.0)

    def test_restore_missing_explicit_step_raises_value_error(
            self, tmp_path):
        ck = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        ck.save(1, fake_net(1.0))
        with pytest.raises(ValueError, match="no checkpoint recorded"):
            ck.restore(fake_net(0.0), step=99)

    def test_async_metrics_and_event(self, tmp_path):
        m = observe.metrics()
        saves0 = m.counter("dl4j_tpu_ckpt_async_saves_total").value
        ck = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        ck.save_async(1, fake_net(1.0))
        assert ck.wait_until_finished(timeout=60.0)
        assert m.counter("dl4j_tpu_ckpt_async_saves_total").value > saves0
        assert m.histogram("dl4j_tpu_ckpt_write_seconds").count > 0
        assert int(m.gauge("dl4j_tpu_ckpt_queue_depth").value) == 0


# ---------------------------------------------------------------------------
# retention (the keep_last newest-intact satellite bugfix)
# ---------------------------------------------------------------------------
class TestRetention:
    def test_keep_last_prunes_oldest(self, tmp_path):
        ck = TrainingCheckpointer(str(tmp_path), keep_last=2,
                                  use_orbax=False)
        for i in (1, 2, 3, 4):
            ck.save(i, fake_net(float(i)))
        steps = [s for s, _, _ in ck._saved]
        assert steps == [3, 4]
        assert not os.path.exists(os.path.join(str(tmp_path), "step_1.npz"))

    def test_eviction_never_deletes_only_restorable(self, tmp_path):
        """Steps 4 and 5 are torn post-publish; pruning to keep_last=2
        must evict the CORRUPT newer entries before the intact step 3 —
        the pre-fix code deleted 3 (the oldest) and left nothing
        restorable."""
        ck = TrainingCheckpointer(str(tmp_path), keep_last=2,
                                  use_orbax=False)
        ck.save(3, fake_net(3.0))
        faults.arm("checkpoint_torn_write", prob=1.0, max_fires=2)
        ck.save(4, fake_net(4.0))
        ck.save(5, fake_net(5.0))
        faults.reset()
        steps = sorted(s for s, _, _ in ck._saved)
        assert 3 in steps, "the only intact checkpoint was evicted"
        assert len(steps) == 2
        net = fake_net(0.0)
        assert ck.restore(net) == 3
        assert float(net.params["W"][0, 0]) == 3.0

    def test_queued_async_writes_do_not_count_toward_keep_last(
            self, tmp_path):
        """In-flight-aware retention: only COMPLETED checkpoints fill the
        keep_last budget — a queued write must never justify deleting a
        durable one."""
        ck = TrainingCheckpointer(str(tmp_path), keep_last=2,
                                  use_orbax=False, max_queue=4)
        ck.save(1, fake_net(1.0))
        ck.save(2, fake_net(2.0))
        for i in (3, 4):
            ck.save_async(i, fake_net(float(i)))
        assert ck.wait_until_finished(timeout=60.0)
        steps = sorted(s for s, _, _ in ck._saved)
        assert len(steps) == 2 and steps[-1] == 4
        assert ck.restore(fake_net(0.0)) == 4

    def test_old_marker_without_cursor_still_loads(self, tmp_path):
        """A checkpoint written before the data-cursor field restores with
        the net's current cursor (like the pre-RNG compat path)."""
        ck = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        net = fake_net(5.0)
        state = ck._state_of(net)
        state.pop("data_cursor")
        ck._write_and_record(5, state)
        ck2 = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        target = fake_net(0.0)
        target.batch_in_epoch = 3
        assert ck2.restore(target) == 5
        assert target.batch_in_epoch == 3  # kept, not clobbered


# ---------------------------------------------------------------------------
# exact resume (the tentpole)
# ---------------------------------------------------------------------------
class TestExactResume:
    @pytest.mark.parametrize("kill_at", [1, 3, 7, 11])
    def test_kill_at_every_k_bit_exact(self, tmp_path, kill_at):
        """Kill the fit with the injected ``preemption`` fault after
        ``kill_at`` steps; the supervised resume must replay to the
        oracle's exact per-step losses and final params, paying zero
        ``new_shape`` recompiles."""
        x, y = xy(64)
        oracle = build_mln()
        col_o = CollectScoresIterationListener()
        oracle.set_listeners(col_o)
        oracle.fit(x, y, epochs=3, batch_size=16)  # 4 batches x 3 epochs
        want = dict(col_o.scores)
        want_params = oracle.params_flat()

        ns0 = new_shape_events()
        net = build_mln()
        col = CollectScoresIterationListener()
        net.set_listeners(col)
        ck = TrainingCheckpointer(str(tmp_path / f"k{kill_at}"),
                                  use_orbax=False)
        sup = TrainingSupervisor(net, ck, save_every=1, max_restarts=3,
                                 restart_backoff_s=0.0)
        faults.arm("preemption", prob=1.0, after_n=kill_at, max_fires=1)
        status = sup.fit(x, y, epochs=3, batch_size=16)
        faults.reset()
        assert status == "completed"
        assert sup.restarts == 1
        got = dict(col.scores)
        assert set(got) == set(want)
        for it in want:
            assert got[it] == want[it], f"step {it} loss diverged"
        assert np.array_equal(want_params, net.params_flat())
        assert new_shape_events() - ns0 == 0

    def test_cross_process_resume(self, tmp_path):
        """Graceful preemption, then a FRESH net + checkpointer (the
        relaunch): the continued run must land on the oracle's exact
        final params even though the new net started from a different
        seed."""
        x, y = xy(64)
        oracle = build_mln()
        oracle.fit(x, y, epochs=2, batch_size=16)
        want = oracle.params_flat()

        class PreemptAt(TrainingListener):
            def iteration_done(self, model, iteration, epoch, score):
                if iteration == 3:
                    faults.request_preemption()

        net = build_mln()
        net.set_listeners(PreemptAt())
        ck = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        sup = TrainingSupervisor(net, ck, save_every=100)  # SIGTERM-only
        assert sup.fit(x, y, epochs=2, batch_size=16) == "preempted"
        assert ck.latest_step() == 3  # the final snapshot, not a periodic

        faults.clear_preemption()
        net2 = build_mln(seed=99)  # restore must overwrite everything
        ck2 = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        sup2 = TrainingSupervisor(net2, ck2, save_every=100)
        assert sup2.fit(x, y, epochs=2, batch_size=16) == "completed"
        assert np.array_equal(want, net2.params_flat())

    def test_resume_counts_and_event(self, tmp_path):
        m = observe.metrics()
        r0 = m.counter("dl4j_tpu_ckpt_resumes_total").value
        x, y = xy(32)
        net = build_mln()
        ck = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        sup = TrainingSupervisor(net, ck, save_every=1,
                                 restart_backoff_s=0.0)
        faults.arm("preemption", prob=1.0, after_n=2, max_fires=1)
        assert sup.fit(x, y, epochs=2, batch_size=16) == "completed"
        assert m.counter("dl4j_tpu_ckpt_resumes_total").value == r0 + 1

    def test_restart_budget_exhausted_raises(self, tmp_path):
        x, y = xy(32)
        net = build_mln()
        ck = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        sup = TrainingSupervisor(net, ck, save_every=1, max_restarts=2,
                                 restart_backoff_s=0.0)
        faults.arm("preemption", prob=1.0)  # every step, forever
        with pytest.raises(InjectedFault):
            sup.fit(x, y, epochs=2, batch_size=16)
        assert sup.restarts == 3  # 2 within budget + the fatal one

    def test_computation_graph_resume(self, tmp_path):
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, graph_builder)

        x, y = xy(48)

        def build_cg(seed=5):
            conf = (graph_builder().seed(seed)
                    .updater(nn.Adam(learning_rate=0.02))
                    .add_inputs("in")
                    .set_input_types(**{"in": nn.InputType.feed_forward(2)})
                    .add_layer("d", nn.DenseLayer(n_out=8,
                                                  activation="tanh"), "in")
                    .add_layer("out", nn.OutputLayer(
                        n_out=2, activation="softmax", loss="mcxent"), "d")
                    .set_outputs("out").build())
            return ComputationGraph(conf).init()

        oracle = build_cg()
        oracle.fit(x, y, epochs=2, batch_size=16)
        want = oracle.params_flat()

        net = build_cg()
        ck = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        sup = TrainingSupervisor(net, ck, save_every=1,
                                 restart_backoff_s=0.0)
        faults.arm("preemption", prob=1.0, after_n=3, max_fires=1)
        assert sup.fit(x, y, epochs=2, batch_size=16) == "completed"
        assert np.array_equal(want, net.params_flat())

    def test_samediff_resume(self, tmp_path):
        from deeplearning4j_tpu.autodiff.samediff import (
            SameDiff, TrainingConfig)
        from deeplearning4j_tpu.datasets import DataSet, ListDataSetIterator

        x, y = xy(64)

        def build_sd():
            sd = SameDiff.create()
            xs = sd.placeholder("x", shape=(None, 2))
            labels = sd.placeholder("labels", shape=(None, 2))
            w = sd.var("w", np.full((2, 2), 0.1, np.float32))
            b = sd.var("b", np.zeros((2,), np.float32))
            logits = (xs.mmul(w) + b).rename("logits")
            sd.loss.softmax_cross_entropy(logits, labels).rename("loss")
            sd.set_training_config(TrainingConfig(
                updater=nn.Adam(learning_rate=0.05),
                data_set_feature_mapping=["x"],
                data_set_label_mapping=["labels"],
                loss_variables=["loss"]))
            return sd

        it = ListDataSetIterator(DataSet(x, y), batch_size=16)
        oracle = build_sd()
        oracle.fit(it, epochs=2)
        want_w = np.asarray(oracle._arrays["w"])
        want_b = np.asarray(oracle._arrays["b"])

        sd = build_sd()
        ck = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        sup = TrainingSupervisor(sd, ck, save_every=1,
                                 restart_backoff_s=0.0)
        faults.arm("preemption", prob=1.0, after_n=5, max_fires=1)
        assert sup.fit(it, epochs=2) == "completed"
        assert sup.restarts == 1
        assert np.array_equal(want_w, np.asarray(sd._arrays["w"]))
        assert np.array_equal(want_b, np.asarray(sd._arrays["b"]))
        assert sd.epoch_count == 2 and sd.batch_in_epoch == 0


# ---------------------------------------------------------------------------
# SIGTERM / graceful preemption
# ---------------------------------------------------------------------------
class TestSigterm:
    def test_sigterm_sets_flag_and_snapshots(self, tmp_path):
        """A real SIGTERM mid-fit: the installed handler flips the
        graceful flag, the fit loop takes one final SYNCHRONOUS snapshot
        and exits cleanly, and the supervisor reports 'preempted'."""
        x, y = xy(64)
        net = build_mln()

        class KillAt(TrainingListener):
            def iteration_done(self, model, iteration, epoch, score):
                if iteration == 2:
                    os.kill(os.getpid(), signal.SIGTERM)

        net.set_listeners(KillAt())
        ck = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        sup = TrainingSupervisor(net, ck, save_every=100,
                                 install_sigterm=True)
        prev = signal.getsignal(signal.SIGTERM)
        try:
            assert sup.fit(x, y, epochs=3, batch_size=16) == "preempted"
        finally:
            signal.signal(signal.SIGTERM, prev)
        # the handler-owning supervisor CLEARS the flag on exit, so a
        # later fit in a surviving process is not stillborn
        assert not faults.preemption_requested()
        # the snapshot landed at the interrupted step, durable + intact
        assert ck.latest_step() == 2
        net2 = fake = build_mln(seed=1)
        ck2 = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        assert ck2.restore(net2) == 2
        assert net2.iteration_count == 2

    def test_handler_restored_after_fit(self, tmp_path):
        x, y = xy(32)
        net = build_mln()
        ck = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        sup = TrainingSupervisor(net, ck, install_sigterm=True)
        prev = signal.getsignal(signal.SIGTERM)
        sup.fit(x, y, epochs=1, batch_size=16)
        assert signal.getsignal(signal.SIGTERM) == prev

    def test_preemption_flag_cleared_by_faults_reset(self):
        faults.request_preemption()
        assert faults.preemption_requested()
        faults.reset()
        assert not faults.preemption_requested()

    def test_preempt_metric_counted(self, tmp_path):
        m = observe.metrics()
        p0 = m.counter("dl4j_tpu_train_preemptions_total").value
        x, y = xy(32)
        net = build_mln()

        class PreemptAt(TrainingListener):
            def iteration_done(self, model, iteration, epoch, score):
                faults.request_preemption()

        net.set_listeners(PreemptAt())
        net.fit(x, y, epochs=1, batch_size=16)
        assert m.counter("dl4j_tpu_train_preemptions_total").value == p0 + 1


# ---------------------------------------------------------------------------
# threaded save/restore race
# ---------------------------------------------------------------------------
class TestThreadedRace:
    def test_concurrent_save_restore_invariants(self, tmp_path):
        """A save_async storm from one thread racing restores from
        another. check-style invariants: every restore lands on a step
        whose params CONSISTENTLY encode that step (no torn mixes), the
        marker stays parseable, and the final drain leaves a restorable
        newest checkpoint."""
        ck = TrainingCheckpointer(str(tmp_path), keep_last=3,
                                  use_orbax=False, max_queue=2)
        stop = threading.Event()
        errors = []

        def saver():
            step = 0
            while not stop.is_set():
                step += 1
                try:
                    ck.save_async(step, fake_net(float(step)))
                except CheckpointWriteError as e:
                    errors.append(e)
                time.sleep(0.001)
            ck.wait_until_finished(timeout=60.0)

        def restorer():
            while not stop.is_set():
                net = fake_net(0.0)
                got = ck.restore(net)
                if got is not None:
                    w = np.asarray(net.params["W"])
                    # payload consistency: a restore is all-one-step
                    if not (w == float(got)).all():
                        errors.append(
                            AssertionError(f"mixed restore at {got}"))
                    if net.iteration_count != got:
                        errors.append(
                            AssertionError(f"cursor mismatch at {got}"))
                time.sleep(0.002)

        ts = threading.Thread(target=saver)
        tr = threading.Thread(target=restorer)
        ts.start(); tr.start()
        time.sleep(0.8)
        stop.set()
        ts.join(timeout=30); tr.join(timeout=30)
        assert not ts.is_alive() and not tr.is_alive()
        assert not errors, errors[:3]
        # after the dust settles: marker parseable, newest restorable
        ck2 = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        net = fake_net(0.0)
        got = ck2.restore(net)
        assert got is not None
        assert (np.asarray(net.params["W"]) == float(got)).all()


# ---------------------------------------------------------------------------
# listener satellites
# ---------------------------------------------------------------------------
class TestCheckpointListener:
    def test_final_save_when_boundary_missed(self, tmp_path):
        """every_n_iterations=4 over 6 steps: the old listener lost steps
        5-6; fit_done must save the tail."""
        x, y = xy(96)  # 6 batches of 16
        net = build_mln()
        ck = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        net.set_listeners(CheckpointTrainingListener(
            ck, every_n_iterations=4))
        net.fit(x, y, epochs=1, batch_size=16)
        assert ck.latest_step() == 6  # tail checkpoint, not just step 4
        steps = sorted(s for s, _, _ in ck._saved)
        assert 4 in steps

    def test_no_duplicate_final_save_on_boundary(self, tmp_path):
        x, y = xy(64)  # 4 batches of 16
        net = build_mln()
        ck = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        lst = CheckpointTrainingListener(ck, every_n_iterations=4)
        net.set_listeners(lst)
        m = observe.metrics()
        saves0 = m.counter("dl4j_tpu_checkpoint_saves_total").value
        net.fit(x, y, epochs=1, batch_size=16)
        # step 4 hit the boundary; fit_done must NOT save step 4 again
        assert m.counter("dl4j_tpu_checkpoint_saves_total").value \
            == saves0 + 1

    def test_iteration_done_resilient_to_raise(self, tmp_path, caplog):
        """A raising checkpointer warns ONCE and training continues."""

        class Exploding(TrainingCheckpointer):
            def save(self, step, net):
                raise IOError("disk on fire")

            def save_async(self, step, net):
                raise IOError("disk on fire")

        x, y = xy(64)
        net = build_mln()
        lst = CheckpointTrainingListener(
            Exploding(str(tmp_path), use_orbax=False), every_n_iterations=1)
        net.set_listeners(lst)
        import logging

        with caplog.at_level(logging.WARNING,
                             logger="deeplearning4j_tpu.parallel.checkpoint"):
            net.fit(x, y, epochs=2, batch_size=16)  # must not raise
        warns = [r for r in caplog.records
                 if "training continues WITHOUT durability" in r.message]
        assert len(warns) == 1  # warn-once
        assert net.iteration_count == 8  # training completed

    def test_fit_done_compensates_failed_tail_write(self, tmp_path):
        """last_saved_iteration advances on async SUBMISSION; if that
        tail write dies in the background, fit_done must detect it and
        take a synchronous compensating save — the run keeps its tail."""
        x, y = xy(32)  # 2 batches of 16
        net = build_mln()
        ck = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        lst = CheckpointTrainingListener(ck, every_n_iterations=1,
                                         asynchronous=True)
        net.set_listeners(lst)
        # the LAST async write (step 2) dies in the writer thread
        faults.arm("worker_death", prob=1.0, after_n=1, max_fires=1)
        net.fit(x, y, epochs=1, batch_size=16)
        faults.reset()
        assert ck.wait_until_finished(timeout=60.0)
        assert ck.latest_step() == 2  # compensating sync save landed
        assert ck.restore(build_mln(seed=1)) == 2

    def test_cg_tbptt_checkpoints_only_at_batch_boundary(self, tmp_path):
        """ComputationGraph tbptt fires listeners per SEGMENT; the
        checkpoint listener must skip those (mid-batch state has a live
        RNN carry and a stale cursor — not exactly resumable) and save
        once at the batch boundary with the updated cursor."""
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, graph_builder)

        r = np.random.RandomState(0)
        x = r.randn(4, 9, 3).astype(np.float32)
        y = np.eye(2)[r.randint(0, 2, (4, 9))].astype(np.float32)
        b = (graph_builder().seed(9).updater(nn.Sgd(learning_rate=0.05))
             .add_inputs("in")
             .set_input_types(**{"in": nn.InputType.recurrent(3, -1)}))
        b.add_layer("lstm", nn.LSTM(n_in=3, n_out=5, activation="tanh"),
                    "in")
        b.add_layer("out", nn.RnnOutputLayer(n_in=5, n_out=2,
                                             activation="softmax",
                                             loss="mcxent"), "lstm")
        b.set_outputs("out")
        conf = b.build()
        conf.backprop_type = "tbptt"
        conf.tbptt_fwd_length = 3
        conf.tbptt_back_length = 3
        net = ComputationGraph(conf).init()
        ck = TrainingCheckpointer(str(tmp_path), keep_last=None,
                                  use_orbax=False)
        net.set_listeners(CheckpointTrainingListener(
            ck, every_n_iterations=1))
        net.fit(x, y, epochs=1, batch_size=4)  # 1 batch, 3 segments
        # exactly ONE periodic save (batch boundary), not one per segment
        steps = [s for s, _, _ in ck._saved]
        assert len(steps) == 1, steps
        fresh = ComputationGraph(conf).init()
        assert ck.restore(fresh) == steps[0]
        # the boundary save recorded the POST-batch cursor — resume
        # skips the completed batch instead of double-applying it
        assert fresh.batch_in_epoch == 1
        assert fresh.iteration_count == net.iteration_count

    def test_observe_summary_training_section(self, tmp_path):
        x, y = xy(32)
        net = build_mln()
        ck = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        net.set_listeners(CheckpointTrainingListener(
            ck, every_n_iterations=1, asynchronous=True))
        net.fit(x, y, epochs=1, batch_size=16)
        ck.wait_until_finished(timeout=60.0)
        s = observe.summary()
        assert "training" in s
        assert s["training"]["async_saves"] >= 1
