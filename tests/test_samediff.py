"""SameDiff graph engine tests — reference OpValidation / SameDiff test
patterns (SURVEY §5.2): forward-value assertions, autodiff checks vs finite
differences, serde round-trip, and the layer-API-vs-graph-API equivalence
gate (M2 exit criterion)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.autodiff import (
    SameDiff, TrainingConfig, check_samediff_gradients, check_gradients,
)
from deeplearning4j_tpu import nn
from deeplearning4j_tpu.datasets import DataSet, ListDataSetIterator


class TestGraphBuild:
    def test_basic_arithmetic(self):
        sd = SameDiff.create()
        a = sd.constant("a", np.array([1.0, 2.0, 3.0], np.float32))
        b = sd.constant("b", np.array([4.0, 5.0, 6.0], np.float32))
        c = (a + b) * 2.0
        out = c.eval()
        np.testing.assert_allclose(out, [10.0, 14.0, 18.0])

    def test_placeholder_feed(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 3))
        w = sd.var("w", np.eye(3, dtype=np.float32) * 2)
        y = x.mmul(w).rename("y")
        feeds = {"x": np.ones((2, 3), np.float32)}
        out = sd.output(feeds, "y")["y"]
        np.testing.assert_allclose(out, 2 * np.ones((2, 3)))

    def test_namespaces(self):
        sd = SameDiff.create()
        x = sd.constant(np.array([-1.0, 0.0, 2.0], np.float32))
        np.testing.assert_allclose(sd.nn.relu(x).eval(), [0, 0, 2])
        np.testing.assert_allclose(sd.math.abs(x).eval(), [1, 0, 2])
        s = sd.nn.softmax(x).eval()
        np.testing.assert_allclose(s.sum(), 1.0, rtol=1e-6)

    def test_reductions_and_shapes(self):
        sd = SameDiff.create()
        x = sd.constant(np.arange(12, dtype=np.float32).reshape(3, 4))
        assert float(x.sum().eval()) == 66.0
        np.testing.assert_allclose(x.mean(0).eval(), [4, 5, 6, 7])
        assert x.reshape(4, 3).eval().shape == (4, 3)
        assert x.transpose().eval().shape == (4, 3)
        assert int(x.argmax(1).eval()[0]) == 3

    def test_conv_graph(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 8, 8, 1))
        w = sd.var("w", shape=(3, 3, 1, 4), initializer="xavier")
        h = sd.cnn.conv2d(x, w, padding="same")
        p = sd.cnn.max_pooling2d(h, kernel=(2, 2), stride=(2, 2)).rename("out")
        out = sd.output({"x": np.ones((2, 8, 8, 1), np.float32)}, "out")["out"]
        assert out.shape == (2, 4, 4, 4)

    def test_whole_graph_is_one_xla_computation(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(4, 4))
        w = sd.var("w", np.ones((4, 4), np.float32))
        y = sd.nn.relu(x.mmul(w) + 1.0).rename("y")
        hlo = sd.as_stablehlo({"x": np.zeros((4, 4), np.float32)}, ["y"])
        assert "stablehlo" in hlo or "mhlo" in hlo or "module" in hlo
        # one module containing dot + max (relu) — fused whole-graph compile
        assert "dot" in hlo

    def test_summary(self):
        sd = SameDiff.create()
        x = sd.constant(1.0)
        (x + 1.0).rename("y")
        s = sd.summary()
        assert "add" in s


class TestAutodiff:
    def test_calculate_gradients_simple(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(3,))
        w = sd.var("w", np.array([2.0, 3.0, 4.0], np.float32))
        loss = (x * w).sum().rename("loss")
        g = sd.calculate_gradients({"x": np.array([1.0, 1.0, 1.0], np.float32)}, "loss")
        np.testing.assert_allclose(g["w"], [1.0, 1.0, 1.0])

    def test_gradcheck_mlp_graph(self):
        sd = SameDiff.create()
        rng = np.random.RandomState(0)
        x = sd.placeholder("x", shape=(4, 5))
        labels = sd.placeholder("labels", shape=(4, 3))
        w0 = sd.var("w0", rng.randn(5, 8).astype(np.float64) * 0.3)
        b0 = sd.var("b0", np.zeros(8))
        w1 = sd.var("w1", rng.randn(8, 3).astype(np.float64) * 0.3)
        b1 = sd.var("b1", np.zeros(3))
        h = sd.nn.tanh((x.mmul(w0) + b0)) if hasattr(sd.nn, "tanh") else sd.math.tanh(x.mmul(w0) + b0)
        logits = h.mmul(w1) + b1
        sd.loss.softmax_cross_entropy(logits, labels).rename("loss")
        feeds = {"x": rng.randn(4, 5), "labels": np.eye(3)[rng.randint(0, 3, 4)]}
        assert check_samediff_gradients(sd, feeds, "loss")

    def test_gradcheck_multilayernetwork(self):
        """GradientCheckUtil semantics on the layer API (SURVEY §5.2)."""
        rng = np.random.RandomState(1)
        net = nn.MultiLayerNetwork(
            nn.builder().seed(3).dtype("float64").list()
            .layer(nn.DenseLayer(n_out=6, activation="tanh"))
            .layer(nn.BatchNormalization())
            .layer(nn.OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(4)).build()
        ).init()
        x = rng.randn(8, 4)
        y = np.eye(3)[rng.randint(0, 3, 8)]
        assert check_gradients(net, x, y)

    def test_gradcheck_cnn(self):
        rng = np.random.RandomState(2)
        net = nn.MultiLayerNetwork(
            nn.builder().seed(4).dtype("float64").list()
            .layer(nn.ConvolutionLayer(n_out=3, kernel=(3, 3), activation="tanh"))
            .layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.convolutional_flat(8, 8, 1)).build()
        ).init()
        x = rng.randn(4, 64)
        y = np.eye(2)[rng.randint(0, 2, 4)]
        assert check_gradients(net, x, y, max_per_param=10)

    def test_gradcheck_lstm(self):
        rng = np.random.RandomState(3)
        net = nn.MultiLayerNetwork(
            nn.builder().seed(5).dtype("float64").list()
            .layer(nn.LSTM(n_out=5, activation="tanh"))
            .layer(nn.RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.recurrent(3)).build()
        ).init()
        x = rng.randn(2, 6, 3)
        y = np.eye(2)[rng.randint(0, 2, (2, 6))]
        assert check_gradients(net, x, y, max_per_param=10)


class TestSameDiffTraining:
    def test_fit_linear_regression(self):
        sd = SameDiff.create()
        rng = np.random.RandomState(0)
        x = sd.placeholder("x", shape=(None, 4))
        labels = sd.placeholder("labels", shape=(None, 1))
        w = sd.var("w", np.zeros((4, 1), np.float32))
        b = sd.var("b", np.zeros((1,), np.float32))
        pred = x.mmul(w) + b
        sd.loss.mean_squared_error(pred, labels).rename("loss")
        sd.set_training_config(TrainingConfig(
            updater=nn.Adam(learning_rate=0.05),
            data_set_feature_mapping=["x"], data_set_label_mapping=["labels"],
            loss_variables=["loss"]))
        xs = rng.randn(256, 4).astype(np.float32)
        true_w = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
        ys = xs @ true_w + 0.25
        it = ListDataSetIterator(DataSet(xs, ys), batch_size=256)
        hist = sd.fit(it, epochs=120)
        assert hist[-1] < 0.01, hist[-1]
        np.testing.assert_allclose(sd.get_arr("w"), true_w, atol=0.05)
        np.testing.assert_allclose(sd.get_arr("b"), [0.25], atol=0.05)

    def test_fit_classifier(self):
        sd = SameDiff.create()
        rng = np.random.RandomState(1)
        x = sd.placeholder("x", shape=(None, 2))
        labels = sd.placeholder("labels", shape=(None, 2))
        w0 = sd.var("w0", shape=(2, 16), initializer="xavier")
        b0 = sd.var("b0", np.zeros(16, np.float32))
        w1 = sd.var("w1", shape=(16, 2), initializer="xavier")
        b1 = sd.var("b1", np.zeros(2, np.float32))
        h = sd.math.tanh(x.mmul(w0) + b0)
        logits = (h.mmul(w1) + b1).rename("logits")
        sd.loss.softmax_cross_entropy(logits, labels).rename("loss")
        sd.set_training_config(TrainingConfig(
            updater=nn.Adam(learning_rate=0.02),
            data_set_feature_mapping=["x"], data_set_label_mapping=["labels"],
            loss_variables=["loss"]))
        xs = rng.rand(512, 2).astype(np.float32)
        yl = ((xs[:, 0] > 0.5) ^ (xs[:, 1] > 0.5)).astype(int)
        ys = np.eye(2, dtype=np.float32)[yl]
        sd.fit(ListDataSetIterator(DataSet(xs, ys), batch_size=128), epochs=150)
        pred = sd.output({"x": xs}, "logits")["logits"].argmax(-1)
        assert (pred == yl).mean() > 0.95


class TestSerde:
    def test_save_load_round_trip(self, tmp_path):
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 3))
        w = sd.var("w", np.random.RandomState(0).randn(3, 2).astype(np.float32))
        sd.nn.softmax(x.mmul(w)).rename("out")
        feeds = {"x": np.ones((2, 3), np.float32)}
        expected = sd.output(feeds, "out")["out"]
        p = str(tmp_path / "graph.sdz")
        sd.save(p)
        sd2 = SameDiff.load(p)
        np.testing.assert_allclose(sd2.output(feeds, "out")["out"], expected, rtol=1e-6)

    def test_save_load_with_updater_state(self, tmp_path):
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 2))
        labels = sd.placeholder("labels", shape=(None, 1))
        w = sd.var("w", np.zeros((2, 1), np.float32))
        sd.loss.mean_squared_error(x.mmul(w), labels).rename("loss")
        sd.set_training_config(TrainingConfig(
            updater=nn.Adam(learning_rate=0.1),
            data_set_feature_mapping=["x"], data_set_label_mapping=["labels"],
            loss_variables=["loss"]))
        xs = np.random.RandomState(0).randn(32, 2).astype(np.float32)
        ys = xs @ np.array([[1.0], [2.0]], np.float32)
        sd.fit(ListDataSetIterator(DataSet(xs, ys), batch_size=32), epochs=2)
        p = str(tmp_path / "g.sdz")
        sd.save(p, save_updater_state=True)
        sd2 = SameDiff.load(p)
        assert sd2._updater_state is not None
        np.testing.assert_allclose(
            np.asarray(sd2._updater_state["w"]["m"]),
            np.asarray(sd._updater_state["w"]["m"]), rtol=1e-6)


class TestLayerGraphEquivalence:
    """M2 exit gate: the same model built via layer API and graph API
    produces identical outputs (the reference's cuDNN-vs-builtin
    two-paths-one-answer pattern, SURVEY §5.2)."""

    def test_mlp_equivalence(self):
        rng = np.random.RandomState(7)
        net = nn.MultiLayerNetwork(
            nn.builder().seed(9).list()
            .layer(nn.DenseLayer(n_out=8, activation="relu"))
            .layer(nn.OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(5)).build()
        ).init()
        # build the same function as a SameDiff graph using the SAME params
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 5))
        w0 = sd.var("w0", net.params[0]["W"])
        b0 = sd.var("b0", net.params[0]["b"])
        w1 = sd.var("w1", net.params[1]["W"])
        b1 = sd.var("b1", net.params[1]["b"])
        h = sd.nn.relu(x.mmul(w0) + b0)
        sd.nn.softmax(h.mmul(w1) + b1).rename("out")
        xs = rng.randn(6, 5).astype(np.float32)
        np.testing.assert_allclose(
            sd.output({"x": xs}, "out")["out"], net.output(xs), rtol=1e-5, atol=1e-6)


class TestSameDiffListeners:
    """Round-3 listener-family completion: HistoryListener + UIListener
    (autodiff/listeners/records/History + UIListener roles)."""

    def _train_sd(self, listeners, epochs=2):
        from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
        from deeplearning4j_tpu.nn.updater import Sgd

        sd = SameDiff.create()
        rng = np.random.RandomState(0)
        x = sd.placeholder("x", shape=(None, 4))
        y = sd.placeholder("y", shape=(None, 2))
        w = sd.var("w", rng.randn(4, 2).astype(np.float32) * 0.1)
        b = sd.var("b", np.zeros(2, np.float32))
        out = sd.nn.softmax(x @ w + b)
        loss = sd.loss.log_loss(out, y).rename("loss")
        sd.set_training_config(TrainingConfig(
            updater=Sgd(learning_rate=0.05),
            data_set_feature_mapping=["x"],
            data_set_label_mapping=["y"],
            loss_variables=["loss"]))
        sd.set_listeners(*listeners)
        from deeplearning4j_tpu.datasets.dataset import DataSet

        feats = rng.rand(64, 4).astype(np.float32)
        labels = np.eye(2)[rng.randint(0, 2, 64)].astype(np.float32)
        sd.fit(DataSet(feats, labels), epochs=epochs)
        return sd

    def test_history_listener(self):
        from deeplearning4j_tpu.autodiff import HistoryListener

        hl = HistoryListener()
        self._train_sd([hl], epochs=3)
        h = hl.finalize()
        assert len(h.epoch_losses) == 3
        assert len(h.loss_curve) == 3 * 2  # 64/32 batches per epoch
        assert np.isfinite(h.final_train_loss())
        assert h.epoch_losses[-1] <= h.epoch_losses[0]
        assert h.training_time_millis > 0

    def test_ui_listener_feeds_dashboard(self):
        import json
        import urllib.request

        from deeplearning4j_tpu.autodiff import UIListener
        from deeplearning4j_tpu.ui import UIServer
        from deeplearning4j_tpu.utils.stats import StatsStorage

        server = UIServer(port=0).start()
        try:
            storage = StatsStorage()
            server.attach(storage)
            self._train_sd([UIListener(storage)], epochs=2)
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/train/overview",
                    timeout=5) as r:
                ov = json.loads(r.read())
            assert len(ov["score"]) == 4
            assert all(np.isfinite(p[1]) for p in ov["score"])
        finally:
            server.stop()


class TestGenericOpFacade:
    """sd.op(name, ...) — Nd4j.exec(DynamicCustomOp) parity over the full
    254-op declarable catalog."""

    def test_catalog_op_records_and_executes(self):
        from deeplearning4j_tpu.autodiff import SameDiff

        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 8))
        vals_v, idx_v = sd.op("top_k", x, k=3, n_out=2)
        feats = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        vals = sd.output({"x": feats}, vals_v.name)[vals_v.name]
        np.testing.assert_allclose(vals, np.sort(feats, axis=1)[:, ::-1][:, :3],
                                   rtol=1e-6)

    def test_unknown_op_fails_at_build(self):
        from deeplearning4j_tpu.autodiff import SameDiff

        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(2, 2))
        with pytest.raises(Exception):
            sd.op("definitely_not_an_op", x)


class TestSameDiffLayerAdapter:
    """conf/layers/samediff/SameDiffLayer.java — a SameDiff block inside a
    MultiLayerNetwork, differentiated by the OUTER network's jax.grad."""

    def _net(self):
        def define(sd, x, p):
            h = x.mmul(p["W"]) + p["b"]
            return sd.math.tanh(h) if hasattr(sd, "math") else h.tanh()

        return nn.MultiLayerNetwork(
            nn.builder().seed(4).updater(nn.Sgd(learning_rate=0.1)).list()
            .layer(nn.conf.SameDiffLayer(
                define=define, param_shapes={"W": (5, 7), "b": (7,)},
                n_out=7))
            .layer(nn.OutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(5)).build()).init()

    def test_forward_matches_manual(self):
        net = self._net()
        x = np.random.RandomState(0).randn(4, 5).astype(np.float32)
        W = np.asarray(net.params[0]["W"])
        b = np.asarray(net.params[0]["b"])
        h = np.tanh(x @ W + b)
        out = net.feed_forward(x)[0]
        np.testing.assert_allclose(np.asarray(out), h, rtol=1e-5, atol=1e-6)

    def test_trains_through_the_block(self):
        net = self._net()
        rng = np.random.RandomState(1)
        x = rng.randn(32, 5).astype(np.float32)
        y = np.eye(3)[rng.randint(0, 3, 32)].astype(np.float32)
        before = np.asarray(net.params[0]["W"]).copy()
        net.fit(x, y)
        first = float(net.score())
        for _ in range(20):
            net.fit(x, y)
        assert float(net.score()) < first
        assert not np.allclose(before, np.asarray(net.params[0]["W"]))

    def test_gradcheck_through_block(self):
        from deeplearning4j_tpu.autodiff.gradcheck import check_gradients

        net = self._net()
        rng = np.random.RandomState(2)
        x = rng.randn(4, 5)
        y = np.eye(3)[rng.randint(0, 3, 4)]
        assert check_gradients(net, x, y, max_per_param=10)

    def test_no_double_activation_with_net_default(self):
        """A net-wide default activation must NOT re-activate the block's
        output (reference SameDiffLayer semantics)."""
        def define(sd, x, p):
            return sd.math.tanh(x.mmul(p["W"]))

        net = nn.MultiLayerNetwork(
            nn.builder().seed(4).activation("tanh").list()
            .layer(nn.conf.SameDiffLayer(define=define,
                                         param_shapes={"W": (5, 7)},
                                         n_out=7))
            .layer(nn.OutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(5)).build()).init()
        x = np.random.RandomState(0).randn(4, 5).astype(np.float32)
        W = np.asarray(net.params[0]["W"])
        want = np.tanh(x @ W)  # applied ONCE
        got = np.asarray(net.feed_forward(x)[0])
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


class TestConstantBaking:
    def test_set_arr_on_constant_invalidates_caches(self):
        """Constants are baked into cached traces — changing one must not
        serve stale results (round-4 const-baking regression guard)."""
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(3,))
        c = sd.constant("c", np.asarray(2.0, np.float32))
        out = sd._record("mul", [x, c])
        xv = np.array([1.0, 2.0, 3.0], np.float32)
        np.testing.assert_allclose(sd.output({"x": xv}, out.name)[out.name],
                                   2.0 * xv)
        sd.set_arr("c", np.asarray(5.0, np.float32))
        np.testing.assert_allclose(sd.output({"x": xv}, out.name)[out.name],
                                   5.0 * xv)

    def test_stack_keeps_device_arrays_on_device(self):
        import jax

        from deeplearning4j_tpu.ops import exec_op

        a = jnp.ones((4,))
        out = exec_op("stack", a, a * 2)
        assert isinstance(out, jax.Array)  # no silent host round-trip
        # host-only inputs stay numpy (shape-chain concreteness)
        out2 = exec_op("stack", np.int32(3), np.int32(4))
        assert isinstance(out2, np.ndarray)
