"""Distributed tests on the virtual 8-device CPU mesh — the reference's
Spark-local[N] + Aeron-loopback test translation (SURVEY §5.5)."""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.datasets import DataSet, ListDataSetIterator
from deeplearning4j_tpu.parallel import (
    make_mesh, shard_params, ParallelWrapper, ParallelInference,
    TrainingCheckpointer, CheckpointTrainingListener, host_shard,
    ShardedDataSetIterator, DEFAULT_TP_RULES,
)


def xor_data(n=256, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 2).astype(np.float32)
    y = ((x[:, 0] > 0.5) ^ (x[:, 1] > 0.5)).astype(np.int64)
    labels = np.zeros((n, 2), np.float32)
    labels[np.arange(n), y] = 1.0
    return x, labels, y


def small_net(seed=12, lr=0.02):
    return nn.MultiLayerNetwork(
        nn.builder().seed(seed).updater(nn.Adam(learning_rate=lr))
        .weight_init("xavier").list()
        .layer(nn.DenseLayer(n_out=32, activation="tanh"))
        .layer(nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(nn.InputType.feed_forward(2)).build()
    ).init()


class TestMesh:
    def test_make_mesh_8(self):
        mesh = make_mesh()
        assert mesh.shape["data"] == 8

    def test_make_mesh_2d(self):
        mesh = make_mesh({"data": 4, "model": 2})
        assert mesh.shape == {"data": 4, "model": 2}

    def test_mesh_wrong_count_raises(self):
        with pytest.raises(ValueError):
            make_mesh({"data": 3})


class TestDataParallel:
    def test_dp_training_converges(self):
        x, y, y_id = xor_data(512)
        net = small_net()
        pw = ParallelWrapper(net, mesh=make_mesh({"data": 8}))
        pw.fit(ListDataSetIterator(DataSet(x, y), batch_size=256), epochs=200)
        acc = (net.predict(x) == y_id).mean()
        assert acc > 0.95, acc

    def test_dp_matches_single_device(self):
        """DP over N devices with the same global batch = single-device math
        (sync all-reduce DP is exact, unlike the reference's async mode)."""
        x, y, _ = xor_data(128)
        a, b = small_net(seed=5), small_net(seed=5)
        it = lambda: ListDataSetIterator(DataSet(x, y), batch_size=128)
        a.fit(it(), epochs=3)
        pw = ParallelWrapper(b, mesh=make_mesh({"data": 8}))
        pw.fit(it(), epochs=3)
        np.testing.assert_allclose(a.params_flat(), b.params_flat(), rtol=2e-4, atol=1e-5)

    def test_parallel_inference(self):
        x, y, _ = xor_data(100)  # 100 % 8 != 0 → exercises padding
        net = small_net()
        pi = ParallelInference(net, mesh=make_mesh({"data": 8}))
        out = pi.output(x)
        np.testing.assert_allclose(out, net.output(x), rtol=1e-5, atol=1e-6)


class TestTensorParallel:
    def test_tp_sharded_params_match_replicated(self):
        x, y, _ = xor_data(64)
        a, b = small_net(seed=8), small_net(seed=8)
        it = lambda: ListDataSetIterator(DataSet(x, y), batch_size=64)
        a.fit(it(), epochs=2)
        mesh = make_mesh({"data": 4, "model": 2})
        pw = ParallelWrapper(b, mesh=mesh, tp_rules=DEFAULT_TP_RULES)
        pw.fit(it(), epochs=2)
        np.testing.assert_allclose(a.params_flat(), b.params_flat(), rtol=2e-4, atol=1e-5)

    def test_shard_params_specs(self):
        mesh = make_mesh({"data": 4, "model": 2})
        net = small_net()
        sharded = shard_params(net.params, mesh, DEFAULT_TP_RULES)
        w = sharded[0]["W"]  # (2, 32): out axis divisible by 2
        spec = w.sharding.spec
        assert tuple(spec) == (None, "model")
        b = sharded[0]["b"]
        assert tuple(b.sharding.spec) in ((), (None,))

    def test_indivisible_falls_back_replicated(self):
        mesh = make_mesh({"data": 4, "model": 2})
        net = nn.MultiLayerNetwork(
            nn.builder().list()
            .layer(nn.OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(2)).build()
        ).init()
        sharded = shard_params(net.params, mesh, DEFAULT_TP_RULES)
        # n_out=3 not divisible by 2 → replicated
        assert tuple(sharded[0]["W"].sharding.spec) in ((), (None, None))


class TestCheckpointResume:
    def test_save_restore_exact_resume(self, tmp_path):
        x, y, _ = xor_data(128)
        net = small_net(seed=3)
        it = lambda: ListDataSetIterator(DataSet(x, y), batch_size=64)
        net.fit(it(), epochs=2)
        ck = TrainingCheckpointer(str(tmp_path / "ckpt"), keep_last=2)
        ck.save(net.iteration_count, net)
        # train further, then restore and replay — must match exactly
        snapshot = net.params_flat().copy()
        net.fit(it(), epochs=1)
        after_more = net.params_flat().copy()
        assert not np.allclose(snapshot, after_more)
        ck2 = TrainingCheckpointer(str(tmp_path / "ckpt"))
        net2 = small_net(seed=3)
        step = ck2.restore(net2)
        assert step == net2.iteration_count
        np.testing.assert_allclose(net2.params_flat(), snapshot, rtol=1e-6)
        net2.fit(it(), epochs=1)
        np.testing.assert_allclose(net2.params_flat(), after_more, rtol=1e-4, atol=1e-6)

    def test_retention(self, tmp_path):
        net = small_net()
        ck = TrainingCheckpointer(str(tmp_path / "c"), keep_last=2)
        for s in [1, 2, 3, 4]:
            ck.save(s, net)
        assert len(ck._saved) == 2
        assert ck.latest_step() == 4

    def test_checkpoint_listener(self, tmp_path):
        x, y, _ = xor_data(64)
        net = small_net()
        ck = TrainingCheckpointer(str(tmp_path / "cl"), keep_last=None)
        net.set_listeners(CheckpointTrainingListener(ck, every_n_iterations=1))
        net.fit(ListDataSetIterator(DataSet(x, y), batch_size=32), epochs=1)
        assert len(ck._saved) == 2  # 2 batches


class TestHostSharding:
    def test_host_shard_single_process(self):
        # single-process: takes everything
        assert host_shard([1, 2, 3]) == [1, 2, 3]

    def test_host_shard_explicit(self):
        assert host_shard(list(range(10)), process_id=1, num_processes=3) == [1, 4, 7]

    def test_sharded_iterator(self):
        x, y, _ = xor_data(64)
        base = ListDataSetIterator(DataSet(x, y), batch_size=16)  # 4 batches
        it = ShardedDataSetIterator(base, process_id=1, num_processes=2)
        batches = list(it)
        assert len(batches) == 2


class TestTransformerTP:
    """Round-3 weak-#6 fix: REAL-transformer tensor parallelism — BERT
    attention + MLP blocks sharded Megatron-style (column Wq/Wk/Wv/W1, row
    Wo/W2) over the virtual mesh, numerics matching the replicated run and
    the TP all-reduce present in the compiled HLO."""

    def test_bert_tp_matches_replicated(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh

        from deeplearning4j_tpu.models.bert import (
            BertConfig, bert_encoder, init_bert_params)
        from deeplearning4j_tpu.parallel.mesh import (
            DEFAULT_TP_RULES, shard_params)

        cfg = BertConfig(vocab_size=211, hidden=64, layers=2, heads=4,
                         intermediate=128, max_position=32, dropout=0.0)
        params = init_bert_params(jax.random.key(0), cfg)
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, 211, (4, 16)).astype(np.int32))
        seg = jnp.zeros_like(ids)
        mask = jnp.ones_like(ids)

        def fwd(p):
            seq, pooled = bert_encoder(p, ids, seg, mask, cfg, train=False)
            return seq

        want = np.asarray(jax.jit(fwd)(params))

        devs = jax.devices()[:4]
        mesh = Mesh(np.array(devs).reshape(1, 4), ("data", "model"))
        sharded = shard_params(params, mesh, DEFAULT_TP_RULES)
        # the attention projections must actually BE sharded (not silently
        # replicated) for this to test anything
        wq = sharded["encoder"][0]["attn"]["Wq"]
        assert not wq.sharding.is_fully_replicated
        wo = sharded["encoder"][0]["attn"]["Wo"]
        assert not wo.sharding.is_fully_replicated

        jit_fwd = jax.jit(fwd)
        got = np.asarray(jit_fwd(sharded))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
        # row-parallel Wo/W2 force a psum: all-reduce must appear in the HLO
        hlo = jit_fwd.lower(sharded).compile().as_text()
        assert "all-reduce" in hlo or "all_reduce" in hlo


def test_ragged_batch_fallback_warns(caplog):
    """Round-2 weak #6: the replicated fallback for a ragged batch must be
    LOUD, not silent."""
    import logging

    conf = (nn.builder().seed(1).updater(nn.Sgd(learning_rate=0.1)).list()
            .layer(nn.DenseLayer(n_out=4, activation="tanh"))
            .layer(nn.OutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(3)).build())
    net = nn.MultiLayerNetwork(conf).init()
    mesh = make_mesh({"data": 8})
    pw = ParallelWrapper(net, mesh=mesh)
    r = np.random.RandomState(0)
    x = r.randn(11, 3).astype(np.float32)  # 11 % 8 != 0 → ragged
    y = np.eye(2)[r.randint(0, 2, 11)].astype(np.float32)
    with caplog.at_level(logging.WARNING,
                         logger="deeplearning4j_tpu.parallel.mesh"):
        pw.fit(DataSet(x, y), epochs=1, batch_size=11)
    assert any("REPLICATED" in rec.message for rec in caplog.records)
