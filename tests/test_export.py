"""AOT export + persistent executable cache (docs/SERVING.md § AOT warm
boot): the compile-once/serve-every-shape/restart-warm contract,
asserted instead of trusted.

* Round trips are BIT-EXACT: a computation exported through
  ``autodiff/export.py``, serialized to disk, and restored must produce
  outputs identical to the in-process jit — for an MLN fused train
  step, a SameDiff whole-graph exec, and the serving engine fns.
* Symbolic batch dims mean ONE artifact serves every batch size: fresh
  signatures on a restored fn record ``cache_hit``, never ``new_shape``.
* Every non-hit degrades to a fresh compile: corrupt entries, stale jax
  versions, and wrong device kinds warn once and miss — they can never
  restore the wrong toolchain's binary.

The cross-process legs (a genuinely fresh interpreter restoring from a
populated cache) live in tools/aot.py / the gate's ``aot`` stage; these
tests exercise the same machinery in-process where the ledger is
inspectable.
"""

import base64
import json
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import nn, observe
from deeplearning4j_tpu.autodiff import export as aot
from deeplearning4j_tpu.autodiff.samediff import SameDiff

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv(aot.ENV_DIR, raising=False)
    observe.reset()
    aot.reset_export_cache()
    yield
    observe.reset()
    aot.reset_export_cache()


def build_mln(seed=7, hidden=8):
    return nn.MultiLayerNetwork(
        nn.builder().seed(seed).updater(nn.Adam(learning_rate=0.02))
        .weight_init("xavier").list()
        .layer(nn.DenseLayer(n_out=hidden, activation="tanh"))
        .layer(nn.OutputLayer(n_out=2, activation="softmax",
                              loss="mcxent"))
        .set_input_type(nn.InputType.feed_forward(2)).build()).init()


def xy(n=32, seed=0):
    r = np.random.RandomState(seed)
    x = r.rand(n, 2).astype(np.float32)
    y = np.zeros((n, 2), np.float32)
    y[np.arange(n), r.randint(0, 2, n)] = 1.0
    return x, y


def build_sd(seed=0):
    r = np.random.RandomState(seed)
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 4))
    w = sd.var("w", r.randn(4, 3).astype(np.float32))
    b = sd.var("b", np.zeros(3, np.float32))
    out = sd.nn.softmax(sd.math.tanh(sd.nn.linear(x, w, b)))
    return sd, out.name


def params_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


def ledger_window(start):
    return observe.ledger().events()[start:]


# ---------------------------------------------------------------------------
# ExportCache: store/load discipline (the ops/tuning.py table contract)
# ---------------------------------------------------------------------------


def _tiny_exported():
    jitted = jax.jit(lambda x: x * 2.0 + 1.0)
    from jax import export as jexport
    return jexport.export(jitted)(
        jax.ShapeDtypeStruct((4,), jnp.float32))


class TestExportCache:
    def test_store_load_roundtrip_and_atomicity(self, tmp_path):
        cache = aot.ExportCache(str(tmp_path))
        exported = _tiny_exported()
        path = cache.store("fp0", "k0", exported, meta={"graph": "t"})
        assert os.path.exists(path)
        # atomic tmp+replace: no torn .tmp left behind
        leftovers = [f for root, _, fs in os.walk(tmp_path)
                     for f in fs if f.endswith(".tmp")]
        assert leftovers == []
        restored = cache.load("fp0", "k0")
        assert restored is not None
        x = jnp.arange(4, dtype=jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(restored.call(x)), np.asarray(x * 2.0 + 1.0))

    def test_miss_on_unknown_key(self, tmp_path):
        cache = aot.ExportCache(str(tmp_path))
        assert cache.load("fp0", "nothing") is None

    def test_corrupt_entry_warns_once_then_fresh_compile(self, tmp_path,
                                                         caplog):
        cache = aot.ExportCache(str(tmp_path))
        path = cache.store("fp0", "k0", _tiny_exported())
        with open(path, "w") as f:
            f.write("{this is not json")
        with caplog.at_level(logging.WARNING):
            assert cache.load("fp0", "k0") is None
        assert any("corrupt" in r.message for r in caplog.records)
        caplog.clear()
        with caplog.at_level(logging.WARNING):  # warn-once: second load
            assert cache.load("fp0", "k0") is None  # is a silent miss
        assert [r for r in caplog.records if "corrupt" in r.message] == []

    def test_undeserializable_payload_degrades_to_miss(self, tmp_path,
                                                       caplog):
        cache = aot.ExportCache(str(tmp_path))
        path = cache.store("fp0", "k0", _tiny_exported())
        doc = json.load(open(path))
        doc["payload"] = base64.b64encode(b"garbage bytes").decode("ascii")
        json.dump(doc, open(path, "w"))
        with caplog.at_level(logging.WARNING):
            assert cache.load("fp0", "k0") is None
        assert any("undeserializable" in r.message for r in caplog.records)

    def test_jax_version_mismatch_invalidates(self, tmp_path, caplog):
        cache = aot.ExportCache(str(tmp_path))
        path = cache.store("fp0", "k0", _tiny_exported())
        doc = json.load(open(path))
        doc["jax_version"] = "0.0.0-stale"
        json.dump(doc, open(path, "w"))
        with caplog.at_level(logging.WARNING):
            assert cache.load("fp0", "k0") is None
        assert any("stale" in r.message for r in caplog.records)
        # entries() also refuses to surface the stale doc
        assert list(cache.entries()) == []

    def test_device_kind_mismatch_invalidates(self, tmp_path):
        cache = aot.ExportCache(str(tmp_path))
        path = cache.store("fp0", "k0", _tiny_exported())
        doc = json.load(open(path))
        doc["device_kind"] = "tpu-v9000"
        json.dump(doc, open(path, "w"))
        assert cache.load("fp0", "k0") is None

    def test_digest_pins_toolchain(self, tmp_path):
        cache = aot.ExportCache(str(tmp_path))
        d = cache.digest("fp0", "k0")
        assert d != cache.digest("fp1", "k0")
        assert d != cache.digest("fp0", "k1")
        assert jax.__version__ in "|".join(
            (aot.SCHEMA, "fp0", "k0", cache.device_kind, jax.__version__))

    def test_from_env_is_inert_without_optin(self, monkeypatch, tmp_path):
        monkeypatch.delenv(aot.ENV_DIR, raising=False)
        assert aot.ExportCache.from_env() is None
        monkeypatch.setenv(aot.ENV_DIR, str(tmp_path))
        cache = aot.ExportCache.from_env()
        assert cache is not None and cache.root == str(tmp_path)


# ---------------------------------------------------------------------------
# restore_callable ledger semantics (the cache_hit cause — satellite of
# docs/OBSERVABILITY.md § Recompile ledger)
# ---------------------------------------------------------------------------


class TestRestoreLedger:
    def test_hit_restore_records_cache_hit(self):
        start = len(observe.ledger().events())
        fn = aot.restore_callable(_tiny_exported(), graph="t", key="k0",
                                  hit=True)
        evs = ledger_window(start)
        assert [(e.graph, e.key, e.cause) for e in evs] == \
            [("t", "k0", "cache_hit")]
        assert fn._aot_restored
        summ = observe.ledger().summary()
        assert summ["by_cause"].get("cache_hit", 0) == 1
        assert any("export.py" in cs for cs in summ["by_callsite"])

    def test_polymorphic_new_signature_is_cache_hit_not_new_shape(self):
        fn = aot.restore_callable(_tiny_exported(), graph="t", key="k0",
                                  hit=True, polymorphic=True)
        start = len(observe.ledger().events())
        observe.note_jit_signature(fn, graph="t", key="k0",
                                   signature="x=f32[8]")
        observe.note_jit_signature(fn, graph="t", key="k0",
                                   signature="x=f32[3]")
        causes = [e.cause for e in ledger_window(start)]
        assert causes == ["cache_hit", "cache_hit"]

    def test_miss_install_leaves_first_compile_to_dispatch(self):
        fn = aot.restore_callable(_tiny_exported(), graph="t", key="k0",
                                  hit=False)
        start = len(observe.ledger().events())
        observe.note_jit_signature(fn, graph="t", key="k0",
                                   signature="x=f32[4]")
        causes = [e.cause for e in ledger_window(start)]
        assert causes == ["first_compile"]


# ---------------------------------------------------------------------------
# MLN train step: export → persist → warm boot, bit-exact vs in-process jit
# ---------------------------------------------------------------------------


class TestMLNRoundTrip:
    def test_populate_and_warm_boot_are_bit_exact(self, tmp_path):
        x, y = xy(n=32)
        oracle = build_mln()
        oracle.fit(x, y, epochs=2, batch_size=16)

        cache = aot.ExportCache(str(tmp_path))
        net = build_mln()
        path = aot.export_train_step(net, x[:16], y[:16], cache=cache)
        assert path is not None and os.path.exists(path)
        net.fit(x, y, epochs=2, batch_size=16)
        assert params_equal(net.params, oracle.params), \
            "populating leg diverged from the in-process jit"

        warm = build_mln()
        start = len(observe.ledger().events())
        assert aot.warm_boot_net(warm, cache=cache) == 1
        warm.fit(x, y, epochs=2, batch_size=16)
        assert params_equal(warm.params, oracle.params), \
            "warm-booted leg diverged from the in-process jit"
        evs = [e for e in ledger_window(start) if e.graph == "mln"]
        assert evs and all(e.cause == "cache_hit" for e in evs), \
            [(e.key, e.cause) for e in evs]

    def test_symbolic_batch_serves_ragged_batches(self, tmp_path):
        x, y = xy(n=12)
        cache = aot.ExportCache(str(tmp_path))
        net = build_mln()
        aot.export_train_step(net, x[:5], y[:5], cache=cache)
        warm = build_mln()
        assert aot.warm_boot_net(warm, cache=cache) == 1
        start = len(observe.ledger().events())
        warm.fit(x, y, epochs=1, batch_size=5)  # batches of 5, 5, 2
        evs = [e for e in ledger_window(start) if e.graph == "mln"]
        assert all(e.cause == "cache_hit" for e in evs), \
            [(e.key, e.cause) for e in evs]
        oracle = build_mln()
        oracle.fit(x, y, epochs=1, batch_size=5)
        assert params_equal(warm.params, oracle.params)

    def test_fingerprint_separates_configs(self):
        assert aot.net_fingerprint(build_mln(hidden=8)) != \
            aot.net_fingerprint(build_mln(hidden=16))
        assert aot.net_fingerprint(build_mln()) == \
            aot.net_fingerprint(build_mln())

    def test_supervisor_resume_warm_boots(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.parallel import (
            TrainingCheckpointer, TrainingSupervisor)

        x, y = xy(n=32)
        cache_dir = tmp_path / "aot"
        monkeypatch.setenv(aot.ENV_DIR, str(cache_dir))
        net = build_mln()
        aot.export_train_step(net, x[:16], y[:16])
        net.fit(x, y, epochs=1, batch_size=16)
        ckpt = TrainingCheckpointer(str(tmp_path / "ckpt"), use_orbax=False)
        ckpt.save(int(net.iteration_count), net)
        ckpt.wait_until_finished()

        # fresh net, as a restarted process would build it
        net2 = build_mln()
        sup = TrainingSupervisor(net2, TrainingCheckpointer(
            str(tmp_path / "ckpt"), use_orbax=False), save_every=100)
        start = len(observe.ledger().events())
        sup.resume()
        assert "train_step" in net2._jit_cache
        evs = [e for e in ledger_window(start) if e.graph == "mln"]
        assert evs and all(e.cause == "cache_hit" for e in evs)


# ---------------------------------------------------------------------------
# SameDiff whole-graph exec: export → warm boot, bit-exact at every batch
# ---------------------------------------------------------------------------


class TestSameDiffRoundTrip:
    def test_populate_and_warm_boot_are_bit_exact(self, tmp_path):
        r = np.random.RandomState(1)
        x8 = r.randn(8, 4).astype(np.float32)
        x3 = r.randn(3, 4).astype(np.float32)

        sd0, out0 = build_sd()
        oracle8 = sd0.output({"x": x8}, out0)[out0]
        oracle3 = sd0.output({"x": x3}, out0)[out0]

        cache = aot.ExportCache(str(tmp_path))
        sd1, out1 = build_sd()
        path = aot.export_exec(sd1, {"x": x8}, out1, cache=cache)
        assert path is not None
        np.testing.assert_array_equal(
            sd1.output({"x": x8}, out1)[out1], oracle8)

        sd2, out2 = build_sd()
        assert aot.warm_boot_samediff(sd2, out2, cache=cache)
        start = len(observe.ledger().events())
        np.testing.assert_array_equal(
            sd2.output({"x": x8}, out2)[out2], oracle8)
        # the symbolic batch dim serves OTHER batch sizes from the same
        # restored artifact — cache_hit, never new_shape
        np.testing.assert_array_equal(
            sd2.output({"x": x3}, out2)[out2], oracle3)
        evs = [e for e in ledger_window(start) if e.graph == "samediff"]
        assert evs and all(e.cause == "cache_hit" for e in evs), \
            [(e.key, e.cause, e.signature) for e in evs]

    def test_warm_boot_misses_on_different_graph(self, tmp_path):
        # weight VALUES deliberately don't key the cache (variables are
        # runtime arguments) — a different STRUCTURE must miss
        cache = aot.ExportCache(str(tmp_path))
        sd1, out1 = build_sd(seed=0)
        aot.export_exec(sd1, {"x": np.zeros((2, 4), np.float32)}, out1,
                        cache=cache)
        r = np.random.RandomState(0)
        sd2 = SameDiff.create()
        x = sd2.placeholder("x", shape=(None, 4))
        w = sd2.var("w", r.randn(4, 5).astype(np.float32))  # 3 → 5 wide
        b = sd2.var("b", np.zeros(5, np.float32))
        out2 = sd2.nn.softmax(sd2.math.tanh(sd2.nn.linear(x, w, b))).name
        assert not aot.warm_boot_samediff(sd2, out2, cache=cache)


# ---------------------------------------------------------------------------
# Serving engine: warm boot in a config-identical engine, replay clean
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestEngineWarmBoot:
    def test_replay_over_restored_engine_is_clean(self, tmp_path,
                                                  monkeypatch):
        from deeplearning4j_tpu.serving.replay import run_randomized_replay
        from deeplearning4j_tpu.testing.shapetrace import ShapeTracer

        monkeypatch.setenv(aot.ENV_DIR, str(tmp_path))
        populate = run_randomized_replay(n_requests=4, seed=3)
        assert populate["all_terminal"]
        files = [f for root, _, fs in os.walk(tmp_path)
                 for f in fs if f.endswith(".json")]
        assert files, "populating replay stored no cache entries"

        tracer = ShapeTracer()
        warm = run_randomized_replay(n_requests=4, seed=3)
        assert warm["all_terminal"]
        assert warm["first_compile_keys"] == [], warm["first_compile_keys"]
        assert warm["cache_hit_keys"], "warm leg restored nothing"
        assert warm["new_shape_events"] == 0
        assert warm["outputs"] == populate["outputs"], \
            "restored executables diverged from the populating leg"
        report = tracer.check(REPO)
        assert report["ok"], report
        assert report["by_cause"].get("new_shape", 0) == 0
        assert report["by_cause"].get("cache_hit", 0) > 0
