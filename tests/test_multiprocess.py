"""Multi-process distributed tests — SURVEY §5.5's translation: two real OS
processes form a jax.distributed cluster over loopback (the Spark-local /
Aeron-loopback pattern), validating the multi-host bootstrap + global-mesh
collectives the pod path relies on."""

import os
import socket
import subprocess
import sys

import pytest

WORKER = r"""
import os, sys
proc_id = int(sys.argv[1]); nprocs = int(sys.argv[2]); port = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
from deeplearning4j_tpu.parallel import initialize_distributed, host_shard
initialize_distributed(coordinator_address=f"127.0.0.1:{port}",
                       num_processes=nprocs, process_id=proc_id)
assert jax.process_count() == nprocs, jax.process_count()
assert len(jax.devices()) == 4 * nprocs, len(jax.devices())

# global-mesh collective: psum over all devices of both processes
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(np.array(jax.devices()).reshape(-1), ("data",))

from jax.experimental.shard_map import shard_map
def allreduce_ones(x):
    return jax.lax.psum(x, "data")
fn = shard_map(allreduce_ones, mesh=mesh, in_specs=P("data"), out_specs=P())

# each process supplies ITS shard of the global array
local = jnp.ones((4, 2))  # 4 local devices x 1 row
from jax import make_array_from_single_device_arrays
global_shape = (4 * nprocs, 2)
sharding = NamedSharding(mesh, P("data"))
arrs = [jax.device_put(local[i:i+1], d)
        for i, d in enumerate(jax.local_devices())]
garr = make_array_from_single_device_arrays(global_shape, sharding, arrs)
out = fn(garr)
total = float(jax.device_get(out.addressable_data(0))[0, 0])
assert total == 4 * nprocs, total

# host_shard partitions deterministically
shard = host_shard(list(range(10)))
assert shard == list(range(10))[proc_id::nprocs]
print(f"WORKER_{proc_id}_OK")
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# (no pytest-timeout in env — the inner communicate(timeout=150) bounds the run)
def test_two_process_cluster():
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, str(i), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            env=env, text=True)
        for i in range(2)
    ]
    outs = []
    for i, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multiprocess worker timed out")
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"WORKER_{i}_OK" in out


import numpy as np  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestDistributedWord2Vec:
    def test_two_process_averaging_matches_vocab_and_trains(self, tmp_path):
        """SparkWord2Vec role: 2-rank corpus-sharded training with
        parameter averaging; rank 0 saves the vectors, and similarity
        structure from the toy corpus must hold (cats cluster together)."""
        worker = tmp_path / "w2v_worker.py"
        worker.write_text("""
import jax
jax.config.update("jax_platforms", "cpu")
import sys, numpy as np
sys.path.insert(0, %r)
from deeplearning4j_tpu.parallel.launch import initialize_distributed
initialize_distributed()
from deeplearning4j_tpu.nlp.word2vec import Word2Vec, distributed_word2vec_fit
corpus = ([["cat", "purrs", "softly"], ["cat", "meows", "softly"],
           ["dog", "barks", "loudly"], ["dog", "growls", "loudly"]] * 40)
w2v = Word2Vec(layer_size=16, window_size=2, negative_samples=3,
               learning_rate=0.05, epochs=1, seed=3)
losses = distributed_word2vec_fit(w2v, corpus, epochs=8)
assert losses and np.isfinite(losses[-1])
if jax.process_index() == 0:
    sim_same = w2v.similarity("cat", "meows")
    sim_diff = w2v.similarity("cat", "barks")
    assert sim_same > sim_diff, (sim_same, sim_diff)
    np.save(%r, np.asarray(w2v.syn0))
""" % (REPO_ROOT, str(tmp_path / "syn0.npy")))
        from deeplearning4j_tpu.parallel.launch import launch
        rc = launch(2, [str(worker)], timeout=300.0)
        assert rc == 0
        syn0 = np.load(tmp_path / "syn0.npy")
        assert syn0.shape[1] == 16 and np.isfinite(syn0).all()
