"""WordVectorSerializer formats, ROCBinary, graph transfer learning, and
the Keras custom-layer registry."""

import numpy as np
import pytest

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.nn import graph as G


class TestWordVectorSerializer:
    def _vectors(self):
        r = np.random.RandomState(0)
        words = ["the", "quick", "brown", "fox", "naïve"]  # incl. non-ascii
        mat = r.randn(5, 8).astype(np.float32)
        return words, mat

    def test_binary_round_trip(self, tmp_path):
        from deeplearning4j_tpu.nlp import (read_word2vec_binary,
                                            write_word2vec_binary)
        words, mat = self._vectors()
        p = str(tmp_path / "vecs.bin")
        write_word2vec_binary((words, mat), p)
        w2, m2 = read_word2vec_binary(p)
        assert w2 == words
        np.testing.assert_array_equal(m2, mat)

    def test_text_round_trip(self, tmp_path):
        from deeplearning4j_tpu.nlp import (read_word2vec_text,
                                            write_word2vec_text)
        words, mat = self._vectors()
        p = str(tmp_path / "vecs.txt")
        write_word2vec_text((words, mat), p)
        w2, m2 = read_word2vec_text(p)
        assert w2 == words
        np.testing.assert_allclose(m2, mat, rtol=0, atol=0)  # repr() is exact

    def test_headerless_glove_style_text(self, tmp_path):
        from deeplearning4j_tpu.nlp import read_word2vec_text
        p = tmp_path / "glove.txt"
        p.write_text("cat 1.0 2.0\ndog 3.0 4.0\n")
        words, mat = read_word2vec_text(str(p))
        assert words == ["cat", "dog"]
        np.testing.assert_allclose(mat, [[1, 2], [3, 4]])

    def test_load_static_model_sniffs_format(self, tmp_path):
        from deeplearning4j_tpu.nlp import (load_static_model,
                                            write_word2vec_binary,
                                            write_word2vec_text)
        words, mat = self._vectors()
        pb = str(tmp_path / "vecs.bin")
        pt = str(tmp_path / "vecs.txt")
        write_word2vec_binary((words, mat), pb)
        write_word2vec_text((words, mat), pt)
        for p in (pb, pt):
            sv = load_static_model(p)
            assert sv.has_word("fox")
            np.testing.assert_allclose(sv.word2vec("fox"), mat[3],
                                       rtol=1e-6, atol=1e-6)
            assert sv.similarity("fox", "fox") == pytest.approx(1.0)
            assert len(sv.words_nearest("the", 3)) == 3

    def test_word2vec_model_export(self, tmp_path):
        from deeplearning4j_tpu.nlp import Word2Vec, load_static_model
        from deeplearning4j_tpu.nlp.serde import write_word2vec_binary
        sents = [["a", "b", "c", "d"]] * 30
        w2v = Word2Vec(layer_size=6, min_word_frequency=1, epochs=1, seed=1)
        w2v.fit(sents)
        p = str(tmp_path / "model.bin")
        write_word2vec_binary(w2v, p)
        sv = load_static_model(p)
        for w in w2v.inv_vocab:
            np.testing.assert_allclose(sv.word2vec(w),
                                       np.asarray(w2v.syn0)[w2v.vocab[w]],
                                       rtol=1e-6, atol=1e-6)


class TestROCBinary:
    def test_per_output_auc(self):
        from deeplearning4j_tpu.eval import ROCBinary
        r = np.random.RandomState(0)
        n = 200
        labels = (r.rand(n, 3) > 0.5).astype(np.float32)
        # output 0: perfect scores; output 1: random; output 2: inverted
        preds = np.stack([
            labels[:, 0] * 0.9 + 0.05,
            r.rand(n),
            1.0 - labels[:, 2],
        ], axis=1)
        roc = ROCBinary()
        roc.eval(labels, preds)
        assert roc.calculate_auc(0) == pytest.approx(1.0)
        assert 0.35 < roc.calculate_auc(1) < 0.65
        assert roc.calculate_auc(2) == pytest.approx(0.0)
        avg = roc.calculate_average_auc()
        assert 0.4 < avg < 0.6


class TestGraphTransferLearning:
    def _base_graph(self):
        b = (G.graph_builder().seed(5)
             .updater(nn.Sgd(learning_rate=0.1))
             .add_inputs("in")
             .set_input_types(**{"in": nn.InputType.feed_forward(4)}))
        b.add_layer("fc1", nn.DenseLayer(n_out=6, activation="tanh"), "in")
        b.add_layer("fc2", nn.DenseLayer(n_out=5, activation="tanh"), "fc1")
        b.add_layer("out", nn.OutputLayer(n_out=3, activation="softmax",
                                          loss="mcxent"), "fc2")
        b.set_outputs("out")
        return G.ComputationGraph(b.build()).init()

    def test_freeze_and_replace_head(self):
        net = self._base_graph()
        r = np.random.RandomState(0)
        x = r.randn(8, 4).astype(np.float32)
        y = np.eye(2)[r.randint(0, 2, 8)].astype(np.float32)

        new = (nn.graph_transfer_builder(net)
               .set_feature_extractor("fc2")
               .remove_vertex_and_connections("out")
               .add_layer("new_out",
                          nn.OutputLayer(n_in=5, n_out=2,
                                         activation="softmax", loss="mcxent"),
                          "fc2")
               .set_outputs("new_out")
               .build())
        # kept params copied
        np.testing.assert_allclose(np.asarray(new.params["fc1"]["W"]),
                                   np.asarray(net.params["fc1"]["W"]))
        fc1_before = np.asarray(new.params["fc1"]["W"]).copy()
        fc2_before = np.asarray(new.params["fc2"]["W"]).copy()
        for _ in range(3):
            new.fit_multi([x], [y])
        # frozen extractor (fc1, fc2 + ancestors) unchanged; head trained
        np.testing.assert_allclose(np.asarray(new.params["fc1"]["W"]), fc1_before)
        np.testing.assert_allclose(np.asarray(new.params["fc2"]["W"]), fc2_before)
        assert np.isfinite(float(new.score()))

    def test_n_out_replace_fixes_consumer(self):
        net = self._base_graph()
        new = (nn.graph_transfer_builder(net)
               .n_out_replace("fc2", 9)
               .build())
        assert new.params["fc2"]["W"].shape == (6, 9)
        assert new.params["out"]["W"].shape == (9, 3)

    def test_dangling_consumer_raises(self):
        net = self._base_graph()
        with pytest.raises(ValueError, match="no longer exists"):
            (nn.graph_transfer_builder(net)
             .remove_vertex_and_connections("fc2")
             .add_layer("head", nn.OutputLayer(n_in=5, n_out=2), "fc2")
             .build())


class TestKerasCustomLayerRegistry:
    def test_register_custom_layer(self):
        tf = pytest.importorskip("tensorflow")
        from deeplearning4j_tpu.imports import (import_keras_model,
                                                register_custom_layer)
        from deeplearning4j_tpu.imports.keras_import import KerasLayerMapper

        @register_custom_layer("MyScale")
        def _my_scale(cfg, weights):
            return nn.ActivationLayer(activation="identity"), {}

        try:
            class MyScale(tf.keras.layers.Layer):
                def call(self, t):
                    return t

            model = tf.keras.Sequential([
                tf.keras.layers.Input((4,)),
                tf.keras.layers.Dense(3, activation="relu"),
                MyScale(),
            ])
            net = import_keras_model(model)
            x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
            np.testing.assert_allclose(net.output(x),
                                       model(x, training=False).numpy(),
                                       rtol=1e-5, atol=1e-6)
        finally:
            KerasLayerMapper.MAPPERS.pop("MyScale", None)


class TestReviewFixRegression:
    """Regressions for the round-3b review findings."""

    def test_remove_then_readd_keeps_downstream(self):
        b = (G.graph_builder().seed(5).updater(nn.Sgd(learning_rate=0.1))
             .add_inputs("in")
             .set_input_types(**{"in": nn.InputType.feed_forward(4)}))
        b.add_layer("fc1", nn.DenseLayer(n_out=6, activation="tanh"), "in")
        b.add_layer("fc2", nn.DenseLayer(n_out=5, activation="tanh"), "fc1")
        b.add_layer("out", nn.OutputLayer(n_out=3, activation="softmax",
                                          loss="mcxent"), "fc2")
        b.set_outputs("out")
        net = G.ComputationGraph(b.build()).init()
        # replace fc1 with a wider layer; fc2/out must SURVIVE (the closure
        # treats re-added names as available)
        new = (nn.graph_transfer_builder(net)
               .remove_vertex_and_connections("fc1")
               .add_layer("fc1", nn.DenseLayer(n_in=4, n_out=6,
                                               activation="relu"), "in")
               .build())
        assert set(new.layers) == {"fc1", "fc2", "out"}
        x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
        assert new.output_single(x).shape == (2, 3)

    def test_stale_output_raises(self):
        b = (G.graph_builder().seed(5).updater(nn.Sgd(learning_rate=0.1))
             .add_inputs("in")
             .set_input_types(**{"in": nn.InputType.feed_forward(4)}))
        b.add_layer("out", nn.OutputLayer(n_out=3, activation="softmax",
                                          loss="mcxent"), "in")
        b.set_outputs("out")
        net = G.ComputationGraph(b.build()).init()
        with pytest.raises(ValueError, match="set_outputs"):
            (nn.graph_transfer_builder(net)
             .remove_vertex_and_connections("out")
             .add_layer("head", nn.OutputLayer(n_in=4, n_out=2,
                                               activation="softmax",
                                               loss="mcxent"), "in")
             .build())

    def test_glove_export(self, tmp_path):
        from deeplearning4j_tpu.nlp import GloVe
        from deeplearning4j_tpu.nlp.serde import (load_static_model,
                                                  write_word2vec_binary)
        g = GloVe(layer_size=4, epochs=2, seed=0)
        g.fit([["red", "green", "blue", "red"]] * 10)
        p = str(tmp_path / "glove.bin")
        write_word2vec_binary(g, p)
        sv = load_static_model(p)
        np.testing.assert_allclose(sv.word2vec("red"),
                                   np.asarray(g.W)[g.vocab["red"]],
                                   rtol=1e-6, atol=1e-6)

    def test_rocbinary_per_output_mask(self):
        from deeplearning4j_tpu.eval import ROCBinary
        r = np.random.RandomState(0)
        labels = (r.rand(32, 4) > 0.5).astype(np.float32)
        preds = r.rand(32, 4).astype(np.float32)
        mask = (r.rand(32, 4) > 0.3).astype(np.float32)
        roc = ROCBinary()
        roc.eval(labels, preds, mask)  # per-output mask must not crash
        assert np.isfinite(roc.calculate_average_auc())

    def test_binary_reader_handles_missing_trailing_newline(self, tmp_path):
        from deeplearning4j_tpu.nlp.serde import read_word2vec_binary
        p = tmp_path / "nosep.bin"
        vec = np.asarray([1.0, 2.0], "<f4")
        # original C tool style: no newline between rows at all
        p.write_bytes(b"2 2\n" + b"aa " + vec.tobytes() + b"bb " + vec.tobytes())
        words, mat = read_word2vec_binary(str(p))
        assert words == ["aa", "bb"]
        np.testing.assert_allclose(mat, [[1, 2], [1, 2]])


class TestSDNamespaces:
    """sd.image()/linalg()/bitwise()/random() op factories (the reference's
    code-generated SDImage/SDLinalg/SDBitwise/SDRandom namespaces)."""

    def _sd(self):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        return SameDiff.create()

    def test_image_resize_and_adjust(self):
        sd = self._sd()
        x = sd.placeholder("x", shape=(1, 4, 4, 3))
        y = sd.image.resize_bilinear(x, 8, 8)
        z = sd.image.adjust_contrast(y, 1.5)
        img = np.random.RandomState(0).rand(1, 4, 4, 3).astype(np.float32)
        out = sd.output({"x": img}, z.name)[z.name]
        assert out.shape == (1, 8, 8, 3)

    def test_linalg_solve_and_det(self):
        sd = self._sd()
        r = np.random.RandomState(1)
        a_np = r.randn(3, 3).astype(np.float32) + 3 * np.eye(3, dtype=np.float32)
        b_np = r.randn(3, 2).astype(np.float32)
        a = sd.constant("a", a_np)
        b = sd.constant("b", b_np)
        x = sd.linalg.solve(a, b)
        d = sd.linalg.matrix_determinant(a)
        res = sd.output({}, [x.name, d.name])
        np.testing.assert_allclose(a_np @ res[x.name], b_np, rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_allclose(res[d.name], np.linalg.det(a_np),
                                   rtol=1e-4)

    def test_linalg_qr_two_outputs(self):
        sd = self._sd()
        a_np = np.random.RandomState(2).randn(4, 3).astype(np.float32)
        a = sd.constant("a", a_np)
        q, r_ = sd.linalg.qr(a)
        res = sd.output({}, [q.name, r_.name])
        np.testing.assert_allclose(res[q.name] @ res[r_.name], a_np,
                                   rtol=1e-4, atol=1e-4)

    def test_bitwise(self):
        sd = self._sd()
        a = sd.constant("a", np.asarray([0b1100, 0b1010], np.int32))
        b = sd.constant("b", np.asarray([0b1010, 0b0110], np.int32))
        res = sd.output({}, [sd.bitwise.and_(a, b).name,
                             sd.bitwise.xor(a, b).name,
                             sd.bitwise.left_shift(a, 2).name])
        vals = list(res.values())
        np.testing.assert_array_equal(vals[0], [0b1000, 0b0010])
        np.testing.assert_array_equal(vals[1], [0b0110, 0b1100])
        np.testing.assert_array_equal(vals[2], [0b110000, 0b101000])

    def test_random_deterministic_by_seed(self):
        sd = self._sd()
        u1 = sd.random.uniform(0.0, 1.0, (16,), seed=7)
        u2 = sd.random.uniform(0.0, 1.0, (16,), seed=7)
        n = sd.random.normal(0.0, 1.0, (64,), seed=3)
        res = sd.output({}, [u1.name, u2.name, n.name])
        np.testing.assert_array_equal(res[u1.name], res[u2.name])
        assert res[u1.name].min() >= 0 and res[u1.name].max() <= 1
        assert abs(float(res[n.name].mean())) < 0.5

    def test_nms_two_outputs(self):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd = SameDiff.create()
        boxes = sd.constant("boxes", np.asarray(
            [[0, 0, 1, 1], [0, 0, 1, 1], [2, 2, 3, 3]], np.float32))
        scores = sd.constant("scores", np.asarray([0.9, 0.8, 0.7], np.float32))
        idx, valid = sd.image.non_max_suppression(boxes, scores, 2,
                                                  iou_threshold=0.5)
        res = sd.output({}, [idx.name, valid.name])
        assert res[idx.name].shape[0] == 2  # indices only, not stacked pair
        assert res[idx.name][0] == 0

    def test_rocbinary_per_timestep_mask_when_T_equals_nout(self):
        from deeplearning4j_tpu.eval import ROCBinary
        r = np.random.RandomState(1)
        labels = (r.rand(4, 3, 3) > 0.5).astype(np.float32)  # T == nOut == 3
        preds = r.rand(4, 3, 3).astype(np.float32)
        mask = np.ones((4, 3), np.float32)  # per-timestep, NOT per-output
        roc = ROCBinary()
        roc.eval(labels, preds, mask)  # must not crash
        assert np.isfinite(roc.calculate_average_auc())


class TestGraphStatefulRnn:
    """ComputationGraph.rnnTimeStep + doTruncatedBPTT analogs."""

    def _rnn_graph(self, tbptt=0):
        b = (G.graph_builder().seed(9).updater(nn.Sgd(learning_rate=0.05))
             .add_inputs("in")
             .set_input_types(**{"in": nn.InputType.recurrent(3, -1)}))
        b.add_layer("lstm", nn.LSTM(n_in=3, n_out=5, activation="tanh"), "in")
        b.add_layer("out", nn.RnnOutputLayer(n_in=5, n_out=2,
                                             activation="softmax",
                                             loss="mcxent"), "lstm")
        b.set_outputs("out")
        conf = b.build()
        if tbptt:
            conf.backprop_type = "tbptt"
            conf.tbptt_fwd_length = tbptt
            conf.tbptt_back_length = tbptt
        return G.ComputationGraph(conf).init()

    def test_rnn_time_step_matches_full_sequence(self):
        net = self._rnn_graph()
        r = np.random.RandomState(0)
        x = r.randn(2, 6, 3).astype(np.float32)
        full = net.output_single(x)  # whole sequence at once
        net.rnn_clear_previous_state()
        chunks = [net.rnn_time_step(x[:, :2]), net.rnn_time_step(x[:, 2:4]),
                  net.rnn_time_step(x[:, 4:])]
        streamed = np.concatenate(chunks, axis=1)
        np.testing.assert_allclose(streamed, full, rtol=1e-5, atol=1e-6)

    def test_single_step_squeeze(self):
        net = self._rnn_graph()
        x = np.random.RandomState(1).randn(2, 3).astype(np.float32)
        out = net.rnn_time_step(x)
        assert out.shape == (2, 2)

    def test_fit_tbptt_trains(self):
        net = self._rnn_graph(tbptt=3)
        r = np.random.RandomState(2)
        x = r.randn(4, 9, 3).astype(np.float32)
        y = np.eye(2)[r.randint(0, 2, (4, 9))].astype(np.float32)
        losses = [net.fit_tbptt(x, y) for _ in range(6)]
        assert np.isfinite(losses[-1])
        assert losses[-1] < losses[0]
        # iteration advances per segment (3 segments each) + final
        assert net.iteration_count == 6 * 3

    def test_fit_tbptt_rejects_2d_labels(self):
        net = self._rnn_graph(tbptt=3)
        with pytest.raises(ValueError, match="3-D time-series"):
            net.fit_tbptt(np.zeros((2, 6, 3), np.float32),
                          np.zeros((2, 2), np.float32))

    def test_fit_dispatches_tbptt_and_fires_listeners(self):
        """graph.fit must honor backprop_type='tbptt' (not silent full
        BPTT), firing listeners per segment; dropout on the recurrent layer
        must survive the tBPTT path (review findings)."""
        from deeplearning4j_tpu.nn.listeners import CollectScoresIterationListener
        net = self._rnn_graph(tbptt=3)
        r = np.random.RandomState(3)
        x = r.randn(4, 9, 3).astype(np.float32)
        y = np.eye(2)[r.randint(0, 2, (4, 9))].astype(np.float32)
        coll = CollectScoresIterationListener()
        net.listeners = [coll]
        net.fit(x, y, epochs=1, batch_size=4)
        # 9 timesteps / fwd 3 = 3 segments -> 3 listener notifications
        assert len(coll.scores) == 3
        assert net.epoch_count == 1

    def test_tbptt_mask_as_plain_array(self):
        net = self._rnn_graph(tbptt=3)
        r = np.random.RandomState(4)
        x = r.randn(2, 6, 3).astype(np.float32)
        y = np.eye(2)[r.randint(0, 2, (2, 6))].astype(np.float32)
        m = np.ones((2, 6), np.float32)
        loss = net.fit_tbptt(x, y, masks=m, lmasks=m)  # plain arrays OK
        assert np.isfinite(loss)


from deeplearning4j_tpu.autodiff.gradcheck import check_gradients
from tests._helpers import _mln, _rng


class TestGRU:
    """nn.GRU over the gru_cell declarable op + Keras import."""

    def test_gru_gradcheck(self):
        net = _mln([
            nn.GRU(n_out=5),
            nn.RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], nn.InputType.recurrent(3, 6))
        r = _rng(0)
        x = r.randn(2, 6, 3)
        y = np.eye(2)[r.randint(0, 2, (2, 6))]
        assert check_gradients(net, x, y)

    def test_gru_streaming_matches_full(self):
        net = _mln([
            nn.GRU(n_out=4),
            nn.RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ], nn.InputType.recurrent(3, 6))
        x = _rng(1).randn(2, 6, 3).astype(np.float32)
        full = net.output(x)
        net.rnn_clear_previous_state()
        streamed = np.concatenate(
            [net.rnn_time_step(x[:, :3]), net.rnn_time_step(x[:, 3:])], axis=1)
        np.testing.assert_allclose(streamed, full, rtol=1e-5, atol=1e-6)

    def test_keras_gru_golden(self):
        tf = pytest.importorskip("tensorflow")
        from deeplearning4j_tpu.imports import import_keras_model
        model = tf.keras.Sequential([
            tf.keras.layers.Input((7, 4)),
            tf.keras.layers.GRU(6, return_sequences=True),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(2).randn(3, 7, 4).astype(np.float32)
        np.testing.assert_allclose(net.output(x),
                                   model(x, training=False).numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_keras_gru_last_step_default(self):
        """keras default return_sequences=False must import as LastTimeStep
        (review finding: previously the full sequence leaked through)."""
        tf = pytest.importorskip("tensorflow")
        from deeplearning4j_tpu.imports import import_keras_model
        model = tf.keras.Sequential([
            tf.keras.layers.Input((6, 3)),
            tf.keras.layers.GRU(5),       # last step only
            tf.keras.layers.Dense(2, activation="softmax"),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(3).randn(2, 6, 3).astype(np.float32)
        np.testing.assert_allclose(net.output(x),
                                   model(x, training=False).numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_keras_lstm_last_step_default(self):
        tf = pytest.importorskip("tensorflow")
        from deeplearning4j_tpu.imports import import_keras_model
        model = tf.keras.Sequential([
            tf.keras.layers.Input((5, 3)),
            tf.keras.layers.LSTM(4),      # last step only
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(4).randn(2, 5, 3).astype(np.float32)
        np.testing.assert_allclose(net.output(x),
                                   model(x, training=False).numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_keras_gru_no_bias(self):
        tf = pytest.importorskip("tensorflow")
        from deeplearning4j_tpu.imports import import_keras_model
        model = tf.keras.Sequential([
            tf.keras.layers.Input((4, 3)),
            tf.keras.layers.GRU(4, use_bias=False, return_sequences=True),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(5).randn(2, 4, 3).astype(np.float32)
        np.testing.assert_allclose(net.output(x),
                                   model(x, training=False).numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_gru_explicit_activation_rejected(self):
        with pytest.raises(ValueError, match="gru_cell"):
            _mln([nn.GRU(n_out=4, activation="relu"),
                  nn.RnnOutputLayer(n_out=2, activation="softmax",
                                    loss="mcxent")],
                 nn.InputType.recurrent(3, 5))
