"""Int8 quantized matmul path (ops/quantized.py).

Scale round-trip bounds, generic-vs-Pallas-interpret equivalence, the
straight-through gradient contract, and the tuned usable() gate."""

import numpy as np

import jax
import jax.numpy as jnp

import deeplearning4j_tpu.ops  # noqa: F401 - registers catalog + helpers
from deeplearning4j_tpu.ops.quantized import (
    dequantize_int8, matmul_int8, matmul_int8_helper, matmul_int8_pallas,
    quantize_int8)


def _wq(k=128, n=128, seed=0):
    r = np.random.RandomState(seed)
    w = (r.randn(k, n) * k ** -0.5).astype(np.float32)
    wq, ws = quantize_int8.fn(jnp.asarray(w), axis=0)
    return w, wq, ws.reshape(-1)


class TestQuantizeRoundTrip:
    def test_per_tensor_and_per_axis(self):
        r = np.random.RandomState(1)
        x = r.randn(16, 32).astype(np.float32)
        q, s = quantize_int8.fn(jnp.asarray(x))
        assert q.dtype == jnp.int8 and np.asarray(s).shape == ()
        back = np.asarray(dequantize_int8.fn(q, s))
        assert np.abs(back - x).max() <= float(s) / 2 + 1e-9

        q, s = quantize_int8.fn(jnp.asarray(x), axis=0)
        assert np.asarray(s).shape == (1, 32)
        back = np.asarray(dequantize_int8.fn(q, s))
        assert (np.abs(back - x) <= np.asarray(s) / 2 + 1e-9).all()

    def test_extremes_map_to_127(self):
        x = jnp.asarray(np.array([[-3.0, 0.0, 3.0]], np.float32))
        q, s = quantize_int8.fn(x)
        assert int(np.asarray(q).max()) == 127
        assert int(np.asarray(q).min()) == -127


class TestMatmulEquivalence:
    def test_generic_close_to_f32_matmul(self):
        r = np.random.RandomState(2)
        x = r.randn(32, 128).astype(np.float32)
        w, wq, ws = _wq(seed=2)
        got = np.asarray(matmul_int8.fn(jnp.asarray(x), wq, ws))
        want = x @ w
        # two symmetric-int8 quantizations: relative error bounded by the
        # scale quanta; tolerance reflects the serving-accuracy contract
        assert np.abs(got - want).max() / np.abs(want).max() < 0.02

    def test_pallas_interpret_matches_generic(self):
        r = np.random.RandomState(3)
        x = jnp.asarray(r.randn(32, 128).astype(np.float32))
        _, wq, ws = _wq(seed=3)
        want = matmul_int8.fn(x, wq, ws)
        got = matmul_int8_pallas(x, wq, ws, block_m=32, block_k=128,
                                 block_n=128, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_3d_batch_dim(self):
        r = np.random.RandomState(4)
        x = jnp.asarray(r.randn(2, 16, 128).astype(np.float32))
        _, wq, ws = _wq(seed=4)
        want = matmul_int8.fn(x, wq, ws)
        assert want.shape == (2, 16, 128)
        got = matmul_int8_pallas(x, wq, ws, block_m=32, block_k=128,
                                 block_n=128, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


class TestGradients:
    def test_straight_through_matches_dequantized_matmul(self):
        """STE contract: d/dx matmul_int8(x, wq, ws) == d/dx (x @ deq(w))
        EXACTLY (the backward is defined as that matmul)."""
        r = np.random.RandomState(5)
        x = jnp.asarray(r.randn(16, 128).astype(np.float32))
        _, wq, ws = _wq(seed=5)
        w_deq = wq.astype(jnp.float32) * ws.reshape(1, -1)

        g_q = jax.grad(lambda x: jnp.sum(matmul_int8.fn(x, wq, ws) ** 2))(x)
        # cotangent differs (quantized vs exact forward), so compare the
        # VJP structure on an identical cotangent instead
        y, vjp = jax.vjp(lambda x: matmul_int8.fn(x, wq, ws), x)
        ct = jnp.ones_like(y)
        np.testing.assert_allclose(
            np.asarray(vjp(ct)[0]),
            np.asarray(ct @ w_deq.T), rtol=1e-6, atol=1e-6)
        assert g_q.shape == x.shape

    def test_helper_backward_matches_generic(self):
        r = np.random.RandomState(6)
        x = jnp.asarray(r.randn(32, 128).astype(np.float32))
        _, wq, ws = _wq(seed=6)
        y1, vjp1 = jax.vjp(lambda x: matmul_int8.fn(x, wq, ws), x)
        y2, vjp2 = jax.vjp(lambda x: matmul_int8_helper(x, wq, ws), x)
        ct = jnp.ones_like(y1)
        np.testing.assert_allclose(np.asarray(vjp2(ct)[0]),
                                   np.asarray(vjp1(ct)[0]),
                                   rtol=1e-6, atol=1e-6)


class TestUsableGate:
    def _usable(self, *args, **kw):
        from deeplearning4j_tpu.ops.quantized import _usable

        return _usable(*args, **kw)

    def test_alignment_dtype_and_rank(self):
        wq = jnp.zeros((128, 128), jnp.int8)
        ws = jnp.ones((128,), jnp.float32)
        assert self._usable(jnp.zeros((32, 128), jnp.float32), wq, ws)
        # float weights are not the quantized path
        assert not self._usable(jnp.zeros((32, 128), jnp.float32),
                                jnp.zeros((128, 128), jnp.float32), ws)
        # int x is not supported (dynamic row quantization needs floats)
        assert not self._usable(jnp.zeros((32, 128), jnp.int32), wq, ws)
        # int8 sublane alignment: m % 32
        assert not self._usable(jnp.zeros((24, 128), jnp.float32), wq, ws)
        assert not self._usable(jnp.zeros((32, 64), jnp.float32),
                                jnp.zeros((64, 128), jnp.int8), ws)
