"""Supervised-recovery tests (docs/ROBUSTNESS.md): the GenerativeEngine
under injected faults, the death paths of the serving stack, and the
ParallelInference crash contract.

The properties under test mirror the chaos gate stage:
  * crash recovery is CORRECT — a retried greedy generation emits exactly
    the oracle tokens, as if the crash never happened;
  * recovery never recompiles — zero ``new_shape`` ledger events across
    restarts (the compile-once property survives the supervisor);
  * every submitted request reaches a terminal state — shed, deadline,
    error and oom are results, not hangs;
  * death paths stay loud — unsupervised engines and exhausted retry
    budgets propagate to blocked callers instead of wedging them.
"""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu import faults, nn, observe
from deeplearning4j_tpu.faults import InjectedFault
from deeplearning4j_tpu.models.gpt import (
    GptConfig, GptModel, reference_generate,
)
from deeplearning4j_tpu.serving import GenerativeEngine
from deeplearning4j_tpu.serving.scheduler import (
    FINISH_REASONS, GenerationRequest, SlotScheduler,
)

CFG = GptConfig.tiny()
MODEL = GptModel(CFG, seed=1)

PROMPTS = [np.array([3, 5, 7, 9], np.int32),
           np.array([11, 2], np.int32),
           np.array([42, 43, 44, 45, 46, 47], np.int32)]


def make_engine(**kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_pages_per_seq", 6)
    kw.setdefault("max_prompt", 16)
    kw.setdefault("seed", 3)
    kw.setdefault("restart_backoff_s", 0.0)  # tests need no pacing
    return GenerativeEngine(MODEL, **kw)


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv(faults.FAULTS_ENV, raising=False)
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# supervised crash recovery
# ---------------------------------------------------------------------------


class TestSupervisedRecovery:
    def test_inline_decode_crash_recovers_to_oracle(self):
        """One injected decode crash mid-generation: the supervisor
        re-admits and the final greedy output is EXACTLY the oracle's —
        recovery is invisible in the tokens."""
        faults.arm("decode_step_error", prob=1.0, after_n=1, max_fires=1)
        eng = make_engine()
        res = eng.generate(PROMPTS, max_new_tokens=5)
        for p, r in zip(PROMPTS, res):
            assert r.finish_reason == "length"
            np.testing.assert_array_equal(
                r.tokens, reference_generate(MODEL.params, CFG, p, 5))
        assert eng.restarts == 1
        eng.cache.check_invariants()
        assert eng.cache.free_pages == eng.cache.num_pages

    def test_recovery_never_recompiles(self):
        """Compile-once survives the supervisor: crash + KV-buffer
        reallocation + re-admission produce ZERO new_shape events."""
        observe.reset()
        faults.arm("decode_step_error", prob=1.0, after_n=2, max_fires=2)
        eng = make_engine()
        eng.generate(PROMPTS, max_new_tokens=4)
        assert eng.restarts == 2
        serving = [e for e in observe.ledger().events()
                   if e.graph == "serving"]
        assert serving, "expected serving compile events"
        assert not any(e.cause == "new_shape" for e in serving)
        by_key = {}
        for ev in serving:
            by_key.setdefault(ev.key, []).append(ev.cause)
        assert by_key["decode"] == ["first_compile"], by_key

    def test_restart_counter_and_metric(self):
        observe.reset()
        faults.arm("decode_step_error", prob=1.0, max_fires=1)
        eng = make_engine()
        eng.generate([PROMPTS[0]], max_new_tokens=3)
        assert eng.restarts == 1
        assert observe.metrics().counter(
            "dl4j_tpu_serving_engine_restarts_total").value >= 1
        assert observe.metrics().counter(
            "dl4j_tpu_serving_retries_total").value >= 1

    def test_retry_budget_exhausted_is_error_result(self):
        """A request whose slot dies more often than max_retries completes
        terminally as 'error' — no exception, no hang."""
        faults.arm("decode_step_error", prob=1.0, max_fires=2)
        eng = make_engine(max_slots=1)
        res = eng.generate([PROMPTS[0]], max_new_tokens=4, max_retries=1)[0]
        assert res.finish_reason == "error"
        eng.cache.check_invariants()
        assert eng.cache.free_pages == eng.cache.num_pages

    def test_restart_budget_exhausted_raises_inline(self):
        """Past max_restarts the supervisor gives up LOUDLY: inline
        generate() re-raises the original fault."""
        faults.arm("decode_step_error", prob=1.0)  # crash every step
        eng = make_engine(max_restarts=2)
        with pytest.raises(InjectedFault, match="decode_step_error"):
            eng.generate([PROMPTS[0]], max_new_tokens=4, max_retries=100)
        assert eng.restarts == 2

    def test_unsupervised_engine_keeps_old_contract(self):
        """supervise=False: the first crash propagates (inline) — the
        pre-robustness behavior stays reachable."""
        faults.arm("decode_step_error", prob=1.0, max_fires=1)
        eng = make_engine(supervise=False)
        with pytest.raises(InjectedFault):
            eng.generate([PROMPTS[0]], max_new_tokens=4)
        assert eng.restarts == 0

    def test_threaded_worker_death_restarts_and_serves(self):
        """worker_death kills the serving thread; a REPLACEMENT thread
        finishes the request correctly and stop() joins cleanly."""
        faults.arm("worker_death", prob=1.0, max_fires=1)
        eng = make_engine().start()
        ident0 = eng._worker.ident
        try:
            fut = eng.submit(PROMPTS[0], max_new_tokens=4)
            res = fut.result(timeout=120)
            np.testing.assert_array_equal(
                res.tokens,
                reference_generate(MODEL.params, CFG, PROMPTS[0], 4))
        finally:
            eng.stop()
        assert eng.restarts == 1
        assert eng._worker is None and eng.stopped_cleanly
        assert ident0 is not None  # the original worker existed and died

    def test_threaded_unsupervised_crash_propagates_to_callers(self):
        """Satellite: engine-thread exception propagation — a blocked
        submit() caller gets the worker's exception, and later submits
        are rejected with the death cause chained."""
        faults.arm("decode_step_error", prob=1.0, max_fires=1)
        eng = make_engine(supervise=False).start()
        fut = eng.submit(PROMPTS[0], max_new_tokens=8)
        with pytest.raises(InjectedFault):
            fut.result(timeout=120)
        # the engine is dead: new submissions refuse loudly
        with pytest.raises(RuntimeError, match="died"):
            for _ in range(100):
                eng.submit(PROMPTS[1])
                time.sleep(0.01)
        eng.stop()


# ---------------------------------------------------------------------------
# deadlines, shedding, injected pool pressure
# ---------------------------------------------------------------------------


class TestDeadlinesAndShedding:
    def test_pending_deadline_expires_without_slot(self):
        eng = make_engine(max_slots=1)
        fut = eng.submit(PROMPTS[0], max_new_tokens=4, deadline_s=0.0)
        time.sleep(0.005)
        eng.step()
        res = fut.result(timeout=0)
        assert res.finish_reason == "deadline"
        assert res.tokens.size == 0

    def test_active_deadline_retires_with_partial_tokens(self):
        faults.arm("slow_decode", prob=1.0)  # +50ms per decode step
        eng = make_engine(max_slots=1)
        fut = eng.submit(PROMPTS[0], max_new_tokens=50, deadline_s=0.12)
        while eng.scheduler.has_work():
            eng.step()
        res = fut.result(timeout=0)
        assert res.finish_reason == "deadline"
        # partial output is the oracle prefix — the deadline lost time,
        # not correctness
        assert res.tokens.size >= 1
        np.testing.assert_array_equal(
            res.tokens,
            reference_generate(MODEL.params, CFG, PROMPTS[0],
                               len(res.tokens)))
        eng.cache.check_invariants()
        assert eng.cache.free_pages == eng.cache.num_pages

    def test_default_deadline_applies_to_submit(self):
        eng = make_engine(default_deadline_s=0.0)
        fut = eng.submit(PROMPTS[0])
        time.sleep(0.005)
        eng.step()
        assert fut.result(timeout=0).finish_reason == "deadline"

    def test_bounded_queue_sheds_with_terminal_reason(self):
        observe.reset()
        eng = make_engine(max_slots=1, max_queue=2)
        futs = [eng.submit(p, max_new_tokens=2) for p in PROMPTS]
        shed = [f for f in futs if f.done()
                and f.result().finish_reason == "shed"]
        assert len(shed) == 1  # queue bound 2, third submission shed
        assert observe.metrics().counter(
            "dl4j_tpu_serving_evicted_total", reason="shed").value == 1
        # the queued ones still complete normally
        while eng.scheduler.has_work():
            eng.step()
        reasons = sorted(f.result(timeout=0).finish_reason for f in futs)
        assert reasons == ["length", "length", "shed"]

    def test_injected_page_oom_is_terminal_oom(self):
        faults.arm("page_oom", prob=1.0, max_fires=1)
        eng = make_engine(max_slots=1)
        res = eng.generate([PROMPTS[0]], max_new_tokens=6)[0]
        assert res.finish_reason == "oom"
        eng.cache.check_invariants()
        assert eng.cache.free_pages == eng.cache.num_pages

    def test_deadline_validation(self):
        with pytest.raises(ValueError, match="deadline_s"):
            GenerationRequest(prompt=PROMPTS[0], deadline_s=-1.0)
        with pytest.raises(ValueError, match="max_retries"):
            GenerationRequest(prompt=PROMPTS[0], max_retries=-1)

    def test_prefill_crash_does_not_strand_request(self, monkeypatch):
        """A crash inside prefill hits AFTER the request left the pending
        queue but BEFORE it owns a slot — recovery must re-queue it (front,
        original submit time) instead of stranding its future forever."""
        eng = make_engine(max_slots=1)
        real = eng._prefill_into
        calls = {"n": 0}

        def flaky(slot, req):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("injected prefill crash")
            return real(slot, req)

        monkeypatch.setattr(eng, "_prefill_into", flaky)
        res = eng.generate([PROMPTS[0]], max_new_tokens=3)[0]
        assert res.finish_reason == "length"
        np.testing.assert_array_equal(
            res.tokens, reference_generate(MODEL.params, CFG, PROMPTS[0], 3))
        assert eng.restarts == 1
        eng.cache.check_invariants()
        assert eng.cache.free_pages == eng.cache.num_pages

    def test_wall_clock_jump_cannot_expire_deadlines(self, monkeypatch):
        """GL010 satellite: deadline bookkeeping runs on perf_counter.
        A wall-clock jump (NTP step, manual reset) mid-generation must
        NOT spuriously expire a request whose monotonic budget is fine —
        here the wall clock leaps a full year and everything still
        finishes as 'length'."""
        real_time = time.time
        monkeypatch.setattr(time, "time",
                            lambda: real_time() + 365 * 24 * 3600.0)
        eng = make_engine(max_slots=1)
        res = eng.generate([PROMPTS[0]], max_new_tokens=4,
                           deadline_s=120.0)[0]
        assert res.finish_reason == "length"
        assert res.tokens.size == 4


# ---------------------------------------------------------------------------
# death paths of the existing stack (satellite)
# ---------------------------------------------------------------------------


class TestDeathPaths:
    def test_fail_all_drains_pending_submits(self):
        """SlotScheduler.fail_all completes EVERY queued future — pending
        submissions cannot hang across an engine death."""
        sched = SlotScheduler(max_slots=2)
        futs = [sched.submit(GenerationRequest(prompt=p)) for p in PROMPTS]
        exc = RuntimeError("engine died")
        sched.fail_all(exc)
        assert not sched.pending and not sched.slots
        for f in futs:
            with pytest.raises(RuntimeError, match="engine died"):
                f.result(timeout=0)

    def test_fail_pending_leaves_active_slots_alone(self):
        sched = SlotScheduler(max_slots=2)
        from concurrent.futures import Future
        active_fut: "Future" = Future()
        sched.admit(0, GenerationRequest(prompt=PROMPTS[0]), active_fut,
                    submit_t=0.0, first_token=1, now=0.0)
        queued = sched.submit(GenerationRequest(prompt=PROMPTS[1]))
        sched.fail_pending(RuntimeError("stop hung"))
        with pytest.raises(RuntimeError):
            queued.result(timeout=0)
        assert not active_fut.done()  # the (possibly stuck) worker owns it
        assert 0 in sched.slots

    def test_stop_detects_hung_worker(self):
        """Satellite: a worker that outlives the join timeout is detected
        — logged, stopped_cleanly False, gauge 0 — and stop() returns
        instead of silently continuing (or raising mid-shutdown)."""
        observe.reset()
        eng = make_engine().start()
        release = threading.Event()

        def stuck_step():
            release.wait(5.0)
            return 0

        eng.step = stuck_step  # the loop picks it up on the next iteration
        fut = eng.submit(PROMPTS[0], max_new_tokens=4)
        time.sleep(0.05)  # let the loop enter the stuck step
        eng.stop(timeout=0.2)
        assert eng.stopped_cleanly is False
        assert observe.metrics().gauge(
            "dl4j_tpu_serving_stopped_cleanly").value == 0.0
        assert eng._worker is not None  # deliberately NOT nulled
        with pytest.raises(RuntimeError, match="stopped"):
            eng.submit(PROMPTS[1])
        # the queued request was failed so nothing hangs...
        with pytest.raises(RuntimeError):
            fut.result(timeout=0)
        release.set()  # ...and the stuck worker is released for teardown
        eng._worker.join(timeout=10)

    def test_clean_stop_sets_gauge_one(self):
        observe.reset()
        eng = make_engine().start()
        eng.stop()
        assert eng.stopped_cleanly is True
        assert observe.metrics().gauge(
            "dl4j_tpu_serving_stopped_cleanly").value == 1.0

    def test_parallel_inference_worker_raise_fails_batch_not_loop(self):
        """Satellite: a backend worker raising mid-batch fails THAT
        batch's futures and the serving loop keeps serving."""
        from tests._helpers import _mln, _rng
        from deeplearning4j_tpu.parallel.mesh import ParallelInference

        net = _mln([
            nn.DenseLayer(n_out=16, activation="relu"),
            nn.OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
        ], nn.InputType.feed_forward(6))
        pi = ParallelInference(net, max_batch=8, window_ms=1.0).start()
        try:
            x = _rng(0).randn(6).astype(np.float32)
            ref = pi.predict(x)  # warm + healthy
            faults.arm("backend_init_fail", prob=1.0, max_fires=1)
            with pytest.raises(InjectedFault, match="backend_init_fail"):
                pi.predict(x)
            # fault exhausted: the SAME loop serves the next request
            np.testing.assert_allclose(pi.predict(x), ref, atol=1e-6)
        finally:
            pi.stop()

    def test_parallel_inference_start_fails_loudly(self):
        from tests._helpers import _mln
        from deeplearning4j_tpu.parallel.mesh import ParallelInference

        net = _mln([
            nn.OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
        ], nn.InputType.feed_forward(6))
        faults.arm("backend_init_fail", prob=1.0, max_fires=1)
        pi = ParallelInference(net, max_batch=4)
        with pytest.raises(InjectedFault):
            pi.start()

    def test_finish_reasons_superset(self):
        """The terminal-state vocabulary the SLO frontend consumes."""
        assert set(FINISH_REASONS) >= {"eos", "length", "overflow", "oom",
                                       "stopped", "shed", "deadline",
                                       "error"}
