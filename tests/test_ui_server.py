"""UI server tests — the VertxUIServer role (SURVEY §6.5): attach a
StatsStorage, train a LeNet, and assert the dashboard + JSON endpoints
serve live score and update:param-ratio series over HTTP."""

import json
import urllib.request

import numpy as np

from deeplearning4j_tpu import models, nn
from deeplearning4j_tpu.ui import UIServer
from deeplearning4j_tpu.utils.stats import StatsListener, StatsStorage


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.status, r.read()


class TestUIServer:
    def test_dashboard_during_lenet_fit(self):
        server = UIServer(port=0).start()  # ephemeral port
        try:
            storage = StatsStorage()
            server.attach(storage)
            net = models.LeNet(num_classes=10).init()
            net.set_listeners(StatsListener(storage, frequency=1))
            rng = np.random.RandomState(0)
            x = rng.rand(64, 784).astype(np.float32)
            y = np.eye(10)[rng.randint(0, 10, 64)].astype(np.float32)
            net.fit(x, y, epochs=3, batch_size=32)

            status, body = _get(server.port, "/")
            assert status == 200 and b"Training UI" in body
            assert b"update" in body.lower()  # the ratio chart is present

            status, body = _get(server.port, "/train/overview")
            ov = json.loads(body)
            assert status == 200 and len(ov["score"]) >= 6
            its = [p[0] for p in ov["score"]]
            assert its == sorted(its)
            assert all(np.isfinite(p[1]) for p in ov["score"])

            status, body = _get(server.port, "/train/model")
            m = json.loads(body)
            assert status == 200
            ratios = m["update_ratio_log10"]
            assert ratios, "update:param ratio series missing"
            # every weight series holds finite log10 ratios (≈ -8 … 0)
            for name, series in ratios.items():
                assert name.endswith("_W")
                for _, v in series:
                    assert -13 < v < 2

            status, body = _get(server.port, "/train/sessions")
            s = json.loads(body)
            assert s["records"] >= 6

            import urllib.error

            try:
                _get(server.port, "/nope")
                raise AssertionError("expected 404")
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            server.stop()

    def test_singleton_attach(self):
        server = UIServer.get_instance(port=0)
        try:
            st = StatsStorage()
            server.attach(st)
            st.put({"iteration": 1, "epoch": 0, "score": 0.5, "layers": {}})
            status, body = _get(server.port, "/train/overview")
            assert json.loads(body)["score"] == [[1, 0.5]]
            server.detach(st)
            _, body = _get(server.port, "/train/overview")
            assert json.loads(body)["score"] == []
        finally:
            server.stop()


def test_histograms_endpoint():
    """/train/histograms serves the latest iteration's parameter histograms
    when StatsListener collects them."""
    import json
    import urllib.request

    import numpy as np

    from deeplearning4j_tpu import nn
    from deeplearning4j_tpu.utils.stats import StatsListener, StatsStorage
    from deeplearning4j_tpu.ui.server import UIServer

    storage = StatsStorage()
    server = UIServer(port=0).start()
    try:
        server.attach(storage)
        conf = (nn.builder().seed(3).updater(nn.Sgd(learning_rate=0.1)).list()
                .layer(nn.DenseLayer(n_out=4, activation="tanh"))
                .layer(nn.OutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(nn.InputType.feed_forward(3)).build())
        net = nn.MultiLayerNetwork(conf).init()
        net.set_listeners(StatsListener(storage, collect_histograms=True))
        r = np.random.RandomState(0)
        net.fit(r.randn(8, 3).astype(np.float32),
                np.eye(2)[r.randint(0, 2, 8)].astype(np.float32),
                batch_size=4)
        port = server._httpd.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/train/histograms", timeout=5) as rsp:
            data = json.loads(rsp.read())
        assert data["iteration"] >= 0
        assert data["histograms"], "no histograms collected"
        first = next(iter(data["histograms"].values()))
        assert len(first["counts"]) == 20
    finally:
        server.stop()


class TestModelGraphPane:
    def test_graph_endpoint_sequential(self):
        import json
        import urllib.request

        import numpy as np

        from deeplearning4j_tpu import nn
        from deeplearning4j_tpu.ui.server import UIServer
        from deeplearning4j_tpu.utils.stats import StatsListener, StatsStorage

        net = nn.MultiLayerNetwork(
            nn.builder().seed(0).list()
            .layer(nn.DenseLayer(n_out=4, activation="tanh"))
            .layer(nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(3)).build()).init()
        storage = StatsStorage()
        net.set_listeners(StatsListener(storage))
        x = np.random.RandomState(0).randn(8, 3).astype(np.float32)
        y = np.eye(2)[np.random.RandomState(1).randint(0, 2, 8)]
        net.fit(x, y)

        ui = UIServer(port=0).start()
        try:
            ui.attach(storage)
            data = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{ui.port}/train/graph", timeout=10).read())
            assert data["kind"] == "sequential"
            names = [n["name"] for n in data["nodes"]]
            assert names[0] == "input" and len(names) == 3
            assert data["edges"] == [[names[0], names[1]],
                                     [names[1], names[2]]]
            assert data["nodes"][1]["params"] == 3 * 4 + 4  # W + b
            # score series still clean despite the static record
            ov = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{ui.port}/train/overview",
                timeout=10).read())
            assert len(ov["score"]) == 1
        finally:
            ui.stop()

    def test_graph_endpoint_dag(self):
        import json
        import urllib.request

        import numpy as np

        from deeplearning4j_tpu import nn
        from deeplearning4j_tpu.nn.graph import (ElementWiseVertex,
                                                 graph_builder,
                                                 ComputationGraph)
        from deeplearning4j_tpu.ui.server import UIServer
        from deeplearning4j_tpu.utils.stats import StatsListener, StatsStorage

        b = (graph_builder().seed(0).updater(nn.Sgd(learning_rate=0.1))
             .add_inputs("in")
             .set_input_types(**{"in": nn.InputType.feed_forward(4)}))
        b.add_layer("d1", nn.DenseLayer(n_out=4, activation="tanh"), "in")
        b.add_vertex("res", ElementWiseVertex(op="add"), "in", "d1")
        b.add_layer("out", nn.OutputLayer(n_out=2, activation="softmax",
                                          loss="mcxent"), "res")
        b.set_outputs("out")
        net = ComputationGraph(b.build()).init()
        storage = StatsStorage()
        net.set_listeners(StatsListener(storage))
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        y = np.eye(2)[np.random.RandomState(1).randint(0, 2, 8)]
        from deeplearning4j_tpu.datasets.dataset import DataSet

        net.fit(DataSet(x, y))

        ui = UIServer(port=0).start()
        try:
            ui.attach(storage)
            data = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{ui.port}/train/graph", timeout=10).read())
            assert data["kind"] == "graph"
            names = {n["name"] for n in data["nodes"]}
            assert {"in", "d1", "res", "out"} <= names
            assert ["in", "res"] in data["edges"]
            assert ["d1", "res"] in data["edges"]
        finally:
            ui.stop()


class TestMetricsEndpoint:
    def test_prometheus_exposition(self):
        """/metrics serves the process-wide observe/ registry in Prometheus
        text format — the acceptance probe asserts the recompile counter
        and the serving latency histogram are present (they are registered
        eagerly, so the endpoint carries them even before traffic)."""
        from deeplearning4j_tpu import observe

        server = UIServer(port=0).start()
        try:
            observe.metrics().counter("dl4j_tpu_recompiles_total").inc()
            observe.metrics().histogram(
                "dl4j_tpu_serving_request_seconds").observe(0.004)
            status, body = _get(server.port, "/metrics")
            assert status == 200
            text = body.decode()
            assert "# TYPE dl4j_tpu_recompiles_total counter" in text
            assert "dl4j_tpu_recompiles_total" in text
            assert ("# TYPE dl4j_tpu_serving_request_seconds histogram"
                    in text)
            assert "dl4j_tpu_serving_request_seconds_bucket" in text
            assert "dl4j_tpu_serving_request_seconds_count" in text
        finally:
            server.stop()


class TestRemoteUIStatsStorageRouter:
    def test_worker_posts_reach_the_dashboard(self):
        """A remote router (the launcher-worker side) posts records over
        HTTP; the UIServer's overview chart must include them (round-4
        missing #4: multi-host runs become observable)."""
        import urllib.request

        from deeplearning4j_tpu.ui.server import UIServer
        from deeplearning4j_tpu.utils.stats import RemoteUIStatsStorageRouter

        srv = UIServer(port=0).start()
        try:
            router = RemoteUIStatsStorageRouter(
                f"http://127.0.0.1:{srv.port}")
            for i in range(5):
                router.put({"iteration": i, "score": 1.0 / (i + 1)})
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/train/overview",
                timeout=5).read()
            ov = json.loads(body)
            assert [it for it, _ in ov["score"]] == list(range(5))
        finally:
            srv.stop()

    def test_buffering_survives_server_outage(self):
        from deeplearning4j_tpu.ui.server import UIServer
        from deeplearning4j_tpu.utils.stats import RemoteUIStatsStorageRouter

        # no server yet: puts buffer without raising
        import socket
        s = socket.socket(); s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]; s.close()
        router = RemoteUIStatsStorageRouter(f"http://127.0.0.1:{port}",
                                            timeout=0.3)
        router.put({"iteration": 0, "score": 3.0})
        assert router._pending  # buffered, not lost
        srv = UIServer(port=port).start()
        try:
            router.put({"iteration": 1, "score": 2.0})  # flushes both
            assert not router._pending
            assert len(srv.remote_storage().records) == 2
        finally:
            srv.stop()


class TestActivationStats:
    def test_per_layer_activation_drilldown(self):
        """StatsListener(collect_activations=True) reports per-layer
        activation mean|a|/std — the reference model view's activation
        charts (round-4 weak #8)."""
        from deeplearning4j_tpu import nn
        from deeplearning4j_tpu.utils.stats import StatsListener, StatsStorage

        net = nn.MultiLayerNetwork(
            nn.builder().seed(0).updater(nn.Sgd(learning_rate=0.1)).list()
            .layer(nn.DenseLayer(n_out=8, activation="relu", name="d1"))
            .layer(nn.OutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent", name="out"))
            .set_input_type(nn.InputType.feed_forward(5)).build()).init()
        storage = StatsStorage()
        net.listeners = [StatsListener(storage, collect_activations=True)]
        r = np.random.RandomState(0)
        x = r.randn(6, 5).astype(np.float32)
        y = np.eye(3)[r.randint(0, 3, 6)].astype(np.float32)
        net.fit(x, y)
        rec = storage.latest()
        assert "activations" in rec
        assert set(rec["activations"]) == {"d1", "out"}
        for st in rec["activations"].values():
            assert st["mean_magnitude"] >= 0 and st["stdev"] >= 0
