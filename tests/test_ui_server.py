"""UI server tests — the VertxUIServer role (SURVEY §6.5): attach a
StatsStorage, train a LeNet, and assert the dashboard + JSON endpoints
serve live score and update:param-ratio series over HTTP."""

import json
import urllib.request

import numpy as np

from deeplearning4j_tpu import models, nn
from deeplearning4j_tpu.ui import UIServer
from deeplearning4j_tpu.utils.stats import StatsListener, StatsStorage


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.status, r.read()


class TestUIServer:
    def test_dashboard_during_lenet_fit(self):
        server = UIServer(port=0).start()  # ephemeral port
        try:
            storage = StatsStorage()
            server.attach(storage)
            net = models.LeNet(num_classes=10).init()
            net.set_listeners(StatsListener(storage, frequency=1))
            rng = np.random.RandomState(0)
            x = rng.rand(64, 784).astype(np.float32)
            y = np.eye(10)[rng.randint(0, 10, 64)].astype(np.float32)
            net.fit(x, y, epochs=3, batch_size=32)

            status, body = _get(server.port, "/")
            assert status == 200 and b"Training UI" in body
            assert b"update" in body.lower()  # the ratio chart is present

            status, body = _get(server.port, "/train/overview")
            ov = json.loads(body)
            assert status == 200 and len(ov["score"]) >= 6
            its = [p[0] for p in ov["score"]]
            assert its == sorted(its)
            assert all(np.isfinite(p[1]) for p in ov["score"])

            status, body = _get(server.port, "/train/model")
            m = json.loads(body)
            assert status == 200
            ratios = m["update_ratio_log10"]
            assert ratios, "update:param ratio series missing"
            # every weight series holds finite log10 ratios (≈ -8 … 0)
            for name, series in ratios.items():
                assert name.endswith("_W")
                for _, v in series:
                    assert -13 < v < 2

            status, body = _get(server.port, "/train/sessions")
            s = json.loads(body)
            assert s["records"] >= 6

            import urllib.error

            try:
                _get(server.port, "/nope")
                raise AssertionError("expected 404")
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            server.stop()

    def test_singleton_attach(self):
        server = UIServer.get_instance(port=0)
        try:
            st = StatsStorage()
            server.attach(st)
            st.put({"iteration": 1, "epoch": 0, "score": 0.5, "layers": {}})
            status, body = _get(server.port, "/train/overview")
            assert json.loads(body)["score"] == [[1, 0.5]]
            server.detach(st)
            _, body = _get(server.port, "/train/overview")
            assert json.loads(body)["score"] == []
        finally:
            server.stop()


def test_histograms_endpoint():
    """/train/histograms serves the latest iteration's parameter histograms
    when StatsListener collects them."""
    import json
    import urllib.request

    import numpy as np

    from deeplearning4j_tpu import nn
    from deeplearning4j_tpu.utils.stats import StatsListener, StatsStorage
    from deeplearning4j_tpu.ui.server import UIServer

    storage = StatsStorage()
    server = UIServer(port=0).start()
    try:
        server.attach(storage)
        conf = (nn.builder().seed(3).updater(nn.Sgd(learning_rate=0.1)).list()
                .layer(nn.DenseLayer(n_out=4, activation="tanh"))
                .layer(nn.OutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(nn.InputType.feed_forward(3)).build())
        net = nn.MultiLayerNetwork(conf).init()
        net.set_listeners(StatsListener(storage, collect_histograms=True))
        r = np.random.RandomState(0)
        net.fit(r.randn(8, 3).astype(np.float32),
                np.eye(2)[r.randint(0, 2, 8)].astype(np.float32),
                batch_size=4)
        port = server._httpd.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/train/histograms", timeout=5) as rsp:
            data = json.loads(rsp.read())
        assert data["iteration"] >= 0
        assert data["histograms"], "no histograms collected"
        first = next(iter(data["histograms"].values()))
        assert len(first["counts"]) == 20
    finally:
        server.stop()
