"""SameDiff pre-trace graph optimizer (autodiff/optimize.py).

Per-pass equivalence (optimized vs unoptimized outputs AND grads on a mixed
graph), pipeline idempotence, the stale-cache invalidation contract
(constant rebind + graph mutation), per-pass opt-out, the
last_compile_stats instrumentation surface, and the graftcheck
pass-invariance contract (docs/ANALYSIS.md).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.optimize import (
    PASS_ORDER, OptimizeStats, _canon_kwargs, optimize_graph)
from deeplearning4j_tpu.autodiff.samediff import SameDiff


def _mixed_graph(optimize=True, optimize_passes=None):
    """A graph exercising every pass: dead branch (dce), constant chain
    (fold), duplicated subexpression (cse), identity/transpose/no-op
    arithmetic (algebraic) — on top of placeholder + VARIABLE inputs."""
    r = np.random.RandomState(0)
    sd = SameDiff(optimize=optimize, optimize_passes=optimize_passes)
    x = sd.placeholder("x", (4, 8))
    w = sd.var("w", r.randn(8, 8).astype(np.float32) * 0.3)
    b = sd.var("b", r.randn(8).astype(np.float32) * 0.1)
    c = sd.constant("c", np.float32(64.0))
    scale = sd.math.sqrt(c * c) / c          # foldable chain -> 1 node gone
    pre = (x @ w + b) / scale
    t1 = sd.math.tanh(pre)
    t2 = sd.math.tanh(pre)                   # CSE duplicate
    g = sd.nn.sigmoid(t1 + t2)
    g = sd.op("identity", g)                 # identity chain
    g = g * 1.0                              # mul-by-one
    g = g + 0.0                              # add-zero
    g = g.transpose(1, 0).transpose(1, 0)    # cancelling transposes
    g = g.reshape(4, 8)                      # reshape-to-same shape
    _dead = sd.math.exp(pre) @ w             # dead branch
    loss = (g * g).mean()
    loss.rename("loss")
    feeds = {"x": r.randn(4, 8).astype(np.float32)}
    return sd, feeds


def _reference():
    sd, feeds = _mixed_graph(optimize=False)
    out = sd.output(feeds, ["loss"])["loss"]
    grads = sd.calculate_gradients(feeds, "loss")
    return out, grads, feeds


class TestPassEquivalence:
    @pytest.mark.parametrize("passes", [None] + [(p,) for p in PASS_ORDER])
    def test_outputs_and_grads_match(self, passes):
        ref_out, ref_grads, feeds = _reference()
        sd, _ = _mixed_graph(optimize=True, optimize_passes=passes)
        out = sd.output(feeds, ["loss"])["loss"]
        np.testing.assert_allclose(out, ref_out, rtol=1e-6, atol=1e-6)
        grads = sd.calculate_gradients(feeds, "loss")
        assert set(grads) == set(ref_grads)
        for k in ref_grads:
            np.testing.assert_allclose(grads[k], ref_grads[k],
                                       rtol=1e-6, atol=1e-6)

    def test_each_pass_fires_on_mixed_graph(self):
        sd, feeds = _mixed_graph()
        sd.output(feeds, ["loss"])
        st = sd.last_compile_stats
        for p in PASS_ORDER:
            assert st.passes[p]["removed"] > 0, f"pass '{p}' removed nothing"

    def test_output_aliased_to_placeholder(self):
        # ir.py records identity nodes to alias graph outputs; the optimizer
        # must keep the requested name fetchable after removing them
        sd = SameDiff()
        x = sd.placeholder("x", (3,))
        sd._record("identity", [x]).rename("y")
        v = np.asarray([1.0, 2.0, 3.0], np.float32)
        np.testing.assert_array_equal(sd.output({"x": v}, ["y"])["y"], v)

    def test_fully_folded_output(self):
        sd = SameDiff()
        c = sd.constant("c", np.float32(3.0))
        (c * c + c).rename("out")
        assert float(sd.output({}, ["out"])["out"]) == pytest.approx(12.0)
        assert sd.last_compile_stats.nodes_after == 0

    def test_cse_multi_output(self):
        sd = SameDiff()
        x = sd.placeholder("x", (6,))
        v1, i1 = sd.op("top_k", x, k=2, n_out=2)
        v2, i2 = sd.op("top_k", x, k=2, n_out=2)
        out = v1 + v2
        out.rename("out")
        (i1.sum() + i2.sum()).rename("idx")
        feeds = {"x": np.asarray([3, 1, 4, 1, 5, 9], np.float32)}
        res = sd.output(feeds, ["out", "idx"])
        np.testing.assert_allclose(res["out"], [18.0, 10.0])
        assert float(res["idx"]) == 18.0  # top-2 indices 5 and 4, twice
        assert sd.last_compile_stats.passes["cse"]["removed"] >= 1

    def test_dce_opt_out_never_executes_dead_nodes(self):
        # plan seeding uses the reachable subgraph — the node set the
        # unoptimized trace executes — so opting 'dce' out must not run
        # (or fold) dead nodes, even ones that would error
        sd = SameDiff(optimize_passes=("fold", "cse", "algebraic"))
        x = sd.placeholder("x", (2, 2))
        (x * 2.0).sum().rename("out")
        x.reshape(999)  # dead AND impossible: must never execute
        r = sd.output({"x": np.ones((2, 2), np.float32)}, ["out"])["out"]
        assert float(r) == 8.0

    def test_variable_rooted_strip_fires(self):
        # dtype evidence from an actual bound array licenses the x*1/x+0
        # strips (placeholder-rooted chains stay un-stripped: declared
        # placeholder metadata is not enforced at feed time)
        sd = SameDiff()
        w = sd.var("w", np.asarray([1.0, 2.0], np.float32))
        (w * 1.0 + 0.0).sum().rename("out")
        np.testing.assert_allclose(sd.output({}, ["out"])["out"], 3.0)
        assert sd.last_compile_stats.passes["algebraic"]["removed"] >= 2

    def test_placeholder_reshape_not_stripped_for_polymorphic_feed(self):
        # feeds are shape-polymorphic under jit: a reshape matching the
        # DECLARED placeholder shape must survive, so a same-size feed of a
        # different shape still gets reshaped (review-round regression)
        sd = SameDiff()
        x = sd.placeholder("x", (4, 3))
        x.reshape(4, 3).rename("y")
        out = sd.output({"x": np.ones((3, 4), np.float32)}, ["y"])["y"]
        assert out.shape == (4, 3)

    def test_var_reshape_after_shape_changing_set_arr(self):
        # set_arr with a new shape refreshes the declared metadata AND
        # clears plans, so a previously-stripped reshape re-materializes
        # (review-round regression)
        sd = SameDiff()
        w = sd.var("w", np.ones((4, 3), np.float32))
        w.reshape(4, 3).rename("y")
        assert sd.output({}, ["y"])["y"].shape == (4, 3)
        sd.set_arr("w", np.ones((3, 4), np.float32))
        assert sd.output({}, ["y"])["y"].shape == (4, 3)

    def test_bf16_add_zero_not_stripped(self):
        # x(bf16) + 0.0(f32) promotes to f32; stripping would change the
        # result dtype/precision — the dtype guard must keep the node
        import jax.numpy as jnp

        sd = SameDiff()
        w = sd.var("w", jnp.asarray([1.0, 2.0], jnp.bfloat16))
        (w + np.float32(0.0)).rename("out")
        sd.output({}, ["out"])
        # graph is bf16-policy; the add-zero survives (only fold may claim
        # it — as a constant expression — never the algebraic strip)
        assert sd.last_compile_stats.passes["algebraic"]["removed"] == 0


class TestPassInvariance:
    """Every pass is shape/dtype-preserving on the requested outputs,
    verified through the graftcheck abstract interpreter
    (docs/OPTIMIZER.md § Pass invariance)."""

    def _interface(self, sd, nodes, extra_consts, name):
        """Abstract aval of `name` after executing `nodes` (interpreter)."""
        from deeplearning4j_tpu.analysis import infer_nodes, seed_avals

        avals, known = seed_avals(sd)
        for k, v in extra_consts.items():
            from deeplearning4j_tpu.analysis import AVal

            avals[k] = AVal.of_array(v, keep_value=True)
            known.add(k)
        infer_nodes(list(enumerate(nodes)), avals, sd._local_ops,
                    findings=[], known_names=known)
        return avals.get(name)

    @pytest.mark.parametrize("passes", [(p,) for p in PASS_ORDER])
    def test_each_pass_preserves_interface_avals(self, passes):
        # the satellite contract: for EVERY pass, the interpreter-derived
        # shape/dtype of the surviving output matches the unoptimized graph
        sd, _ = _mixed_graph()
        seed_dtypes = {n: np.dtype(a.dtype) for n, a in sd._arrays.items()}
        before = self._interface(sd, sd._nodes, {}, "loss")
        plan = optimize_graph(sd._nodes, ["loss"],
                              const_env=sd._const_env(),
                              seed_dtypes=seed_dtypes,
                              var_shapes={n: tuple(np.shape(a))
                                          for n, a in sd._arrays.items()},
                              local_ops=sd._local_ops,
                              passes=passes,
                              input_avals=sd._input_avals())
        after = self._interface(sd, plan.nodes, plan.extra_consts,
                                plan.resolve("loss"))
        assert before.shape == after.shape == ()  # scalar loss, both known
        assert before.dtype == after.dtype == np.dtype(np.float32)

    def test_invariant_checks_run_by_default(self):
        sd, feeds = _mixed_graph()
        sd.output(feeds, ["loss"])
        st = sd.last_compile_stats
        assert st.invariant_checks > 0
        assert st.to_dict()["invariant_checks"] == st.invariant_checks

    def test_env_opt_out(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_CHECK_PASSES", "0")
        sd, feeds = _mixed_graph()
        sd.output(feeds, ["loss"])
        assert sd.last_compile_stats.invariant_checks == 0

    def test_interface_change_raises_naming_the_pass(self):
        # drive the checker directly with a tampered "pass" result: the
        # transpose that produced the output vanished, so the interface
        # shape flips from (3, 2) to (2, 3) — the checker must name the
        # offending pass
        from deeplearning4j_tpu.analysis import PassInvariantError
        from deeplearning4j_tpu.autodiff.optimize import (
            OptimizeStats as _Stats, _InvariantChecker)

        sd = SameDiff()
        x = sd.placeholder("x", (2, 3))
        t = sd._record("transpose", [x], {"axes": (1, 0)})
        t.rename("out")
        stats = _Stats()
        checker = _InvariantChecker(["out"], sd._input_avals(), {}, {},
                                    sd._local_ops, stats)
        checker.snapshot(sd._nodes, {}, {})
        tampered_alias = {"out": "x"}  # a broken pass aliased through
        with pytest.raises(PassInvariantError, match="'algebraic'"):
            checker.verify("algebraic", [], {}, tampered_alias)
        assert stats.invariant_checks == 1


class TestCanonKwargsHardening:
    """_canon_kwargs must exclude un-canonicalizable nodes from CSE, never
    abort the pass pipeline (satellite regression)."""

    def test_mixed_type_dict_keys_canonicalize(self):
        # int-vs-str dict keys are unorderable; repr-sort handles them
        k1 = _canon_kwargs({"cfg": {1: "a", "b": 2}})
        k2 = _canon_kwargs({"cfg": {"b": 2, 1: "a"}})
        assert k1 is not None and k1 == k2

    def test_raising_repr_excluded_not_fatal(self):
        class Unrepresentable:
            def __repr__(self):
                raise ValueError("no repr")

            __hash__ = object.__hash__

        assert _canon_kwargs(
            {"cfg": {Unrepresentable(): 1, "b": 2}}) is None

    def test_nested_ndarray_kwargs_canonicalize(self):
        a = np.asarray([1, 2])
        k1 = _canon_kwargs({"paddings": [a, np.asarray([3, 4])]})
        k2 = _canon_kwargs({"paddings": [a.copy(), np.asarray([3, 4])]})
        assert k1 is not None and k1 == k2

    def test_pipeline_survives_weird_kwargs_end_to_end(self, monkeypatch):
        from deeplearning4j_tpu.autodiff import samediff as sdmod

        monkeypatch.setitem(sdmod.GRAPH_OPS, "kwargs_probe",
                            lambda a, **kw: a * 2.0)
        sd = SameDiff()
        x = sd.placeholder("x", (3,))
        bad_kw = {"cfg": {1: "a", "b": [np.asarray([1.0])]}}
        y1 = sd._record("kwargs_probe", [x], dict(bad_kw))
        y2 = sd._record("kwargs_probe", [x], dict(bad_kw))
        (y1 + y2).rename("out")
        v = np.asarray([1.0, 2.0, 3.0], np.float32)
        res = sd.output({"x": v}, ["out"])["out"]
        np.testing.assert_allclose(res, v * 4)


class TestIdempotence:
    def test_pipeline_twice_changes_nothing(self):
        sd, _ = _mixed_graph()
        seed_dtypes = {n: np.dtype(a.dtype) for n, a in sd._arrays.items()}
        kw = dict(seed_dtypes=seed_dtypes, local_ops=sd._local_ops)
        p1 = optimize_graph(sd._nodes, ["loss"],
                            const_env=sd._const_env(), **kw)
        assert p1.stats.nodes_after < p1.stats.nodes_before
        p2 = optimize_graph(p1.nodes, [p1.resolve("loss")],
                            const_env={**sd._const_env(), **p1.extra_consts},
                            **kw)
        assert len(p2.nodes) == len(p1.nodes)
        assert [n.op for n in p2.nodes] == [n.op for n in p1.nodes]
        assert [n.inputs for n in p2.nodes] == [n.inputs for n in p1.nodes]
        assert not p2.alias
        assert not p2.extra_consts

    def test_unknown_pass_rejected(self):
        sd, _ = _mixed_graph()
        with pytest.raises(ValueError, match="unknown optimizer pass"):
            optimize_graph(sd._nodes, ["loss"], const_env=sd._const_env(),
                           passes=("dce", "nope"))


class TestStaleCacheInvalidation:
    def test_constant_rebind_after_optimized_compile(self):
        # fold bakes c*c into the plan; set_arr on the constant goes through
        # the same _jit_cache.clear() that invalidates compiled traces, so
        # the next output() must re-fold against the new value
        sd = SameDiff()
        x = sd.placeholder("x", (3,))
        c = sd.constant("c", np.float32(2.0))
        (x + c * c).rename("out")
        v = np.asarray([1.0, 2.0, 3.0], np.float32)
        np.testing.assert_allclose(sd.output({"x": v}, ["out"])["out"], v + 4)
        assert sd.last_compile_stats.passes["fold"]["removed"] >= 1
        sd.set_arr("c", np.float32(3.0))
        np.testing.assert_allclose(sd.output({"x": v}, ["out"])["out"], v + 9)

    def test_graph_mutation_after_optimized_compile(self):
        sd = SameDiff()
        x = sd.placeholder("x", (3,))
        c = sd.constant("c", np.float32(2.0))
        y = x * (c + c)
        y.rename("out")
        v = np.asarray([1.0, 2.0, 3.0], np.float32)
        np.testing.assert_allclose(sd.output({"x": v}, ["out"])["out"], v * 4)
        (y + c).rename("out2")  # mutation clears _jit_cache incl. plans
        res = sd.output({"x": v}, ["out", "out2"])
        np.testing.assert_allclose(res["out"], v * 4)
        np.testing.assert_allclose(res["out2"], v * 4 + 2)

    def test_rename_after_optimized_compile(self):
        # rename rewrites node names in place; cached plans hold frozen
        # name snapshots, so _rename must invalidate like any mutation
        # (review-round regression)
        sd = SameDiff()
        x = sd.placeholder("x", (2,))
        (x * 2.0).rename("y")
        v = np.asarray([1.0, 2.0], np.float32)
        np.testing.assert_allclose(sd.output({"x": v}, ["y"])["y"], v * 2)
        x.rename("inp")
        np.testing.assert_allclose(sd.output({"inp": v}, ["y"])["y"], v * 2)

    def test_variable_update_never_stale(self):
        # VARIABLEs are jit arguments, never folded — updating one must be
        # picked up WITHOUT a recompile-triggering invalidation
        sd = SameDiff()
        x = sd.placeholder("x", (3,))
        w = sd.var("w", np.asarray([1.0, 1.0, 1.0], np.float32))
        (x * w).rename("out")
        v = np.asarray([1.0, 2.0, 3.0], np.float32)
        np.testing.assert_allclose(sd.output({"x": v}, ["out"])["out"], v)
        before = len(sd._jit_cache)
        sd.set_arr("w", np.asarray([2.0, 2.0, 2.0], np.float32))
        assert len(sd._jit_cache) == before  # same dtype/shape: no clear
        np.testing.assert_allclose(sd.output({"x": v}, ["out"])["out"], v * 2)


class TestTrainingPath:
    def test_fit_matches_unoptimized(self):
        from deeplearning4j_tpu import nn
        from deeplearning4j_tpu.autodiff.samediff import TrainingConfig
        from deeplearning4j_tpu.datasets.dataset import (
            DataSet, ListDataSetIterator)

        r = np.random.RandomState(3)
        feats = r.randn(8, 4).astype(np.float32)
        labs = r.randn(8, 2).astype(np.float32)

        def run(optimize):
            sd = SameDiff(optimize=optimize)
            x = sd.placeholder("x", (None, 4))
            y = sd.placeholder("y", (None, 2))
            w = sd.var("w", r2.randn(4, 2).astype(np.float32))
            c = sd.constant("c", np.float32(4.0))
            pred = (x @ w) / sd.math.sqrt(c * c / c)  # foldable scale chain
            pred = sd.op("identity", pred) * 1.0
            sd.loss.mean_squared_error(pred, y).rename("l")
            sd.set_training_config(TrainingConfig(
                updater=nn.Sgd(learning_rate=0.1),
                data_set_feature_mapping=["x"], data_set_label_mapping=["y"],
                loss_variables=["l"]))
            hist = sd.fit(ListDataSetIterator(DataSet(feats, labs),
                                              batch_size=8), epochs=3)
            return hist, sd.get_arr("w")

        r2 = np.random.RandomState(7)
        h0, w0 = run(False)
        r2 = np.random.RandomState(7)
        h1, w1 = run(True)
        np.testing.assert_allclose(h0, h1, rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(w0, w1, rtol=1e-6, atol=1e-6)


class TestStatsSurface:
    def test_last_compile_stats_fields(self):
        sd, feeds = _mixed_graph()
        assert sd.last_compile_stats is None
        sd.output(feeds, ["loss"])
        st = sd.last_compile_stats
        assert isinstance(st, OptimizeStats)
        assert st.nodes_before > st.nodes_after > 0
        assert st.removed == st.nodes_before - st.nodes_after
        assert st.trace_seconds is not None and st.trace_seconds >= 0
        assert st.compile_seconds is not None and st.compile_seconds >= 0
        assert st.optimize_seconds > 0
        d = st.to_dict()
        assert set(d["passes"]) <= set(PASS_ORDER)
        for entry in d["passes"].values():
            assert {"before", "after", "removed"} <= set(entry)

    def test_opt_out_runs_only_selected_passes(self):
        sd, feeds = _mixed_graph(optimize_passes=("dce", "cse"))
        sd.output(feeds, ["loss"])
        st = sd.last_compile_stats
        assert set(st.passes) == {"dce", "cse"}

    def test_optimize_off_still_reports_compile_times(self):
        sd, feeds = _mixed_graph(optimize=False)
        sd.output(feeds, ["loss"])
        st = sd.last_compile_stats
        assert st.passes == {}
        assert st.trace_seconds is not None
        assert st.compile_seconds is not None

    def test_graph_runner_exposes_stats(self):
        from deeplearning4j_tpu.imports.graph_runner import GraphRunner

        sd, feeds = _mixed_graph()
        sd.graph_inputs, sd.graph_outputs = ["x"], ["loss"]
        gr = GraphRunner(sd)
        assert gr.compile_stats is None
        gr.run(feeds)
        assert gr.compile_stats.nodes_after < gr.compile_stats.nodes_before

    def test_graph_runner_optimize_flag_on_samediff_instance(self):
        # optimize= must also apply when wrapping an already-built SameDiff
        # (the debug path: compare optimized vs unoptimized execution)
        from deeplearning4j_tpu.imports.graph_runner import GraphRunner

        sd, feeds = _mixed_graph()
        sd.graph_inputs, sd.graph_outputs = ["x"], ["loss"]
        gr = GraphRunner(sd, optimize=False)
        assert sd.optimize is False
        gr.run(feeds)
        assert gr.compile_stats.passes == {}
        assert GraphRunner(sd).sd.optimize is False  # None leaves it alone
