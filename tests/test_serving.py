"""Continuous-batching generative serving tests (docs/SERVING.md).

Covers the four properties the subsystem is built around:
  * allocator soundness — the paged KV cache's free-list/page-table
    invariants across alloc/free/fragmentation and mid-flight eviction;
  * numerical equivalence — the Pallas paged decode path reproduces the
    XLA gather fallback (1e-2/1e-5) AND greedy engine output reproduces a
    full-attention autoregressive oracle token-for-token;
  * compile-once — admits/evicts never change the decode jit signature
    (asserted through the PR-6 RecompileLedger);
  * PRNG hygiene — no key value is ever consumed twice across the
    scheduler loop (the graftlint GL004 property, asserted at runtime).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import models, observe
from deeplearning4j_tpu.models.gpt import (
    GptConfig, GptModel, reference_generate,
)
from deeplearning4j_tpu.ops.pallas_attention import (
    _paged_decode_call, paged_decode_attention_xla,
)
from deeplearning4j_tpu.serving import GenerativeEngine, PagedKVCache
from deeplearning4j_tpu.serving.sampling import sample_tokens

CFG = GptConfig.tiny()
MODEL = GptModel(CFG, seed=1)


def make_engine(**kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_pages_per_seq", 6)
    kw.setdefault("max_prompt", 16)
    kw.setdefault("seed", 3)
    return GenerativeEngine(MODEL, **kw)


PROMPTS = [np.array([3, 5, 7, 9], np.int32),
           np.array([11, 2], np.int32),
           np.array([42, 43, 44, 45, 46, 47], np.int32),
           np.array([8, 8, 8], np.int32),
           np.array([17, 23, 31], np.int32)]


# ---------------------------------------------------------------------------
# paged KV cache — allocator invariants
# ---------------------------------------------------------------------------


class TestPagedKVCache:
    def make_cache(self, **kw):
        kw.setdefault("layers", 2)
        kw.setdefault("heads", 2)
        kw.setdefault("head_dim", 8)
        kw.setdefault("page_size", 4)
        kw.setdefault("num_pages", 8)
        kw.setdefault("max_slots", 3)
        kw.setdefault("max_pages_per_seq", 4)
        return PagedKVCache(**kw)

    def test_alloc_grow_free_invariants(self):
        c = self.make_cache()
        assert c.free_pages == 8
        assert c.ensure_capacity(0, 5) == "ok"   # 2 pages
        c.check_invariants()
        assert c.free_pages == 6 and len(c.owned[0]) == 2
        assert c.ensure_capacity(0, 6) == "ok"   # still 2 pages
        assert len(c.owned[0]) == 2
        assert c.ensure_capacity(1, 9) == "ok"   # 3 pages
        c.check_invariants()
        assert c.free_pages == 3
        released = c.free_slot(0)
        assert released == 2 and c.free_pages == 5
        c.check_invariants()
        # the freed slot's table row points wholly at the trash page
        assert all(int(p) == c.trash_page for p in c.page_table[0])

    def test_fragmented_reuse(self):
        """Pages freed by a middle slot are reusable by a later alloc — the
        free list doesn't care about contiguity (that's the point of
        paging)."""
        c = self.make_cache()
        for slot, toks in ((0, 8), (1, 8), (2, 8)):
            assert c.ensure_capacity(slot, toks) == "ok"
        assert c.free_pages == 2
        freed = set(c.owned[1])
        c.free_slot(1)
        assert c.ensure_capacity(1, 16) == "ok"  # 4 pages from a torn pool
        c.check_invariants()
        assert freed & set(c.owned[1]), "freed pages were not reused"

    def test_overflow_no_partial_alloc(self):
        c = self.make_cache()
        assert c.ensure_capacity(0, 17) == "overflow"  # 5 pages > 4/seq
        assert c.owned[0] == [] and c.free_pages == 8
        c.check_invariants()

    def test_oom_no_partial_alloc(self):
        c = self.make_cache()
        assert c.ensure_capacity(0, 16) == "ok"
        assert c.ensure_capacity(1, 16) == "ok"
        assert c.ensure_capacity(2, 4) == "oom"  # 0 pages left
        assert c.owned[2] == [] and c.free_pages == 0
        c.check_invariants()
        c.free_slot(0)
        assert c.ensure_capacity(2, 4) == "ok"
        c.check_invariants()


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------


class TestSampling:
    def logits(self, s=4, v=32, seed=0):
        return jnp.asarray(np.random.RandomState(seed).randn(s, v)
                           .astype(np.float32))

    def test_greedy_when_temperature_zero(self):
        lg = self.logits()
        toks = sample_tokens(lg, jax.random.key(0),
                             jnp.zeros(4), jnp.zeros(4, jnp.int32),
                             jnp.ones(4))
        np.testing.assert_array_equal(np.asarray(toks),
                                      np.asarray(jnp.argmax(lg, -1)))

    def test_top_k_one_is_greedy(self):
        lg = self.logits()
        toks = sample_tokens(lg, jax.random.key(1),
                             jnp.full(4, 2.0), jnp.ones(4, jnp.int32),
                             jnp.ones(4))
        np.testing.assert_array_equal(np.asarray(toks),
                                      np.asarray(jnp.argmax(lg, -1)))

    def test_top_p_tiny_is_greedy(self):
        lg = self.logits()
        toks = sample_tokens(lg, jax.random.key(2),
                             jnp.full(4, 2.0), jnp.zeros(4, jnp.int32),
                             jnp.full(4, 1e-6))
        np.testing.assert_array_equal(np.asarray(toks),
                                      np.asarray(jnp.argmax(lg, -1)))

    def test_top_k_restricts_support(self):
        lg = self.logits(s=2, v=16)
        top3 = np.asarray(jnp.argsort(lg, axis=-1)[:, -3:])
        for seed in range(20):
            toks = np.asarray(sample_tokens(
                lg, jax.random.key(seed), jnp.full(2, 1.5),
                jnp.full(2, 3, jnp.int32), jnp.ones(2)))
            for row in range(2):
                assert toks[row] in top3[row]

    def test_slots_sample_independently(self):
        """Identical logits rows must NOT force identical samples — each
        slot consumes its own split of the step key."""
        lg = jnp.zeros((8, 64))  # uniform
        toks = np.asarray(sample_tokens(
            lg, jax.random.key(5), jnp.ones(8), jnp.zeros(8, jnp.int32),
            jnp.ones(8)))
        assert len(set(toks.tolist())) > 1

    def test_mixed_greedy_and_sampled_slots(self):
        lg = self.logits()
        temp = jnp.asarray([0.0, 1.0, 0.0, 1.0])
        toks = np.asarray(sample_tokens(lg, jax.random.key(3), temp,
                                        jnp.zeros(4, jnp.int32),
                                        jnp.ones(4)))
        greedy = np.asarray(jnp.argmax(lg, -1))
        assert toks[0] == greedy[0] and toks[2] == greedy[2]


# ---------------------------------------------------------------------------
# paged decode numerics: Pallas vs XLA gather fallback
# ---------------------------------------------------------------------------


class TestPagedDecodeEquivalence:
    def test_kernel_matches_fallback(self):
        r = np.random.RandomState(3)
        s_n, h, d, page, n_pages, max_pages = 4, 4, 16, 8, 12, 4
        q = jnp.asarray(r.randn(s_n, h, d).astype(np.float32))
        kp = jnp.asarray(r.randn(n_pages, page, h, d).astype(np.float32))
        vp = jnp.asarray(r.randn(n_pages, page, h, d).astype(np.float32))
        pt = jnp.asarray(np.stack(
            [r.choice(n_pages, max_pages, replace=False)
             for _ in range(s_n)]).astype(np.int32))
        sl = jnp.asarray(np.array([1, 9, 25, 32], np.int32))
        want = paged_decode_attention_xla(q, kp, vp, pt, sl)
        got = _paged_decode_call(q, kp, vp, pt, sl, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-2, atol=1e-5)

    def test_greedy_engine_equivalence_pallas_vs_xla(self):
        """Whole-loop equivalence: greedy generation with the registry
        resolving the Pallas paged path (forced helper_mode, interpret on
        CPU) must emit the SAME tokens as the XLA gather fallback."""
        from deeplearning4j_tpu.environment import environment

        def run():
            eng = make_engine(max_slots=2)
            return [r.tokens for r in
                    eng.generate(PROMPTS[:3], max_new_tokens=6)]

        env = environment()
        old = env.helper_mode
        try:
            env.helper_mode = "xla"
            xla_toks = run()
            env.helper_mode = "pallas"
            pallas_toks = run()
        finally:
            env.helper_mode = old
        for a, b in zip(xla_toks, pallas_toks):
            np.testing.assert_array_equal(a, b)

    def test_greedy_matches_full_attention_oracle(self):
        """Paged decode vs an O(T²) full-prefill autoregressive oracle —
        token-for-token, across slot counts and mid-flight admits."""
        eng = make_engine(max_slots=2)
        results = eng.generate(PROMPTS, max_new_tokens=5)
        for prompt, res in zip(PROMPTS, results):
            assert res.finish_reason == "length"
            want = reference_generate(MODEL.params, CFG, prompt, 5)
            np.testing.assert_array_equal(res.tokens, want)
        eng.cache.check_invariants()
        assert eng.cache.free_pages == eng.cache.num_pages


# ---------------------------------------------------------------------------
# continuous batching: admit/evict mid-flight
# ---------------------------------------------------------------------------


class TestContinuousBatching:
    def test_admit_evict_midflight(self):
        """5 requests through 2 slots with different budgets: slots must
        turn over mid-flight, every result must still match the oracle,
        and every page must come home."""
        observe.reset()
        eng = make_engine(max_slots=2)
        budgets = [3, 8, 2, 6, 4]
        futs = [eng.submit(p, max_new_tokens=b)
                for p, b in zip(PROMPTS, budgets)]
        while eng.scheduler.has_work():
            eng.step()
        for p, b, f in zip(PROMPTS, budgets, futs):
            res = f.result(timeout=0)
            assert res.finish_reason == "length"
            np.testing.assert_array_equal(
                res.tokens, reference_generate(MODEL.params, CFG, p, b))
        m = observe.metrics()
        assert m.counter("dl4j_tpu_serving_admitted_total").value == 5
        assert m.family_total("dl4j_tpu_serving_evicted_total") == 5
        assert m.counter(
            "dl4j_tpu_serving_generated_tokens_total").value == sum(budgets)
        eng.cache.check_invariants()
        assert eng.cache.free_pages == eng.cache.num_pages

    def test_eos_finishes_early(self):
        """Whatever greedy decode emits first becomes the eos token of a
        second run — which must then stop immediately after it."""
        probe = make_engine().generate([PROMPTS[0]], max_new_tokens=3)[0]
        eos = int(probe.tokens[0])
        res = make_engine().generate([PROMPTS[0]], max_new_tokens=10,
                                     eos_token=eos)[0]
        assert res.finish_reason == "eos"
        assert res.tokens.size == 0  # eos was the first token; excluded

    def test_overflow_eviction(self):
        """A sequence that outgrows its page-table row is evicted with its
        partial output — which must equal the oracle prefix."""
        eng = make_engine(max_slots=1, page_size=4, max_pages_per_seq=3,
                          max_prompt=8)  # context cap: 12 tokens
        prompt = PROMPTS[0]  # 4 tokens
        res = eng.generate([prompt], max_new_tokens=50)[0]
        assert res.finish_reason == "overflow"
        # capacity 12: 4 prompt + 8 cached tokens; the 9th token was
        # sampled but its K/V had nowhere to land
        assert res.tokens.size == 9
        np.testing.assert_array_equal(
            res.tokens, reference_generate(MODEL.params, CFG, prompt, 9))
        eng.cache.check_invariants()
        assert eng.cache.free_pages == eng.cache.num_pages

    def test_oom_eviction_returns_pages(self):
        """An oversubscribed pool (2 slots × 4 pages/seq, 5 pages total)
        must evict under pressure, return the pages, and keep serving."""
        observe.reset()
        eng = make_engine(max_slots=2, page_size=4, max_pages_per_seq=4,
                          num_pages=5, max_prompt=8)
        res = eng.generate([PROMPTS[0], PROMPTS[3]], max_new_tokens=12)
        reasons = sorted(r.finish_reason for r in res)
        assert "oom" in reasons, reasons
        # the survivor must have completed its full budget
        assert "length" in reasons, reasons
        for prompt, r in zip([PROMPTS[0], PROMPTS[3]], res):
            np.testing.assert_array_equal(
                r.tokens,
                reference_generate(MODEL.params, CFG, prompt,
                                   len(r.tokens)))
        assert observe.metrics().counter(
            "dl4j_tpu_serving_evicted_total", reason="oom").value >= 1
        eng.cache.check_invariants()
        assert eng.cache.free_pages == eng.cache.num_pages

    def test_threaded_serving_loop(self):
        """start()/submit()/stop() — the ParallelInference lifecycle."""
        eng = make_engine(max_slots=2).start()
        try:
            futs = [eng.submit(p, max_new_tokens=4) for p in PROMPTS[:4]]
            for p, f in zip(PROMPTS, futs):
                res = f.result(timeout=120)
                np.testing.assert_array_equal(
                    res.tokens, reference_generate(MODEL.params, CFG, p, 4))
        finally:
            eng.stop()

    def test_parallel_inference_facade(self):
        from deeplearning4j_tpu.parallel.mesh import ParallelInference

        eng = ParallelInference.generative(MODEL, max_slots=2, page_size=8,
                                           max_pages_per_seq=6,
                                           max_prompt=16)
        assert isinstance(eng, GenerativeEngine)
        res = eng.generate([PROMPTS[1]], max_new_tokens=3)[0]
        np.testing.assert_array_equal(
            res.tokens, reference_generate(MODEL.params, CFG, PROMPTS[1], 3))

    def test_oversized_prompt_rejected(self):
        eng = make_engine(max_prompt=8)
        with pytest.raises(ValueError, match="prefill bucket"):
            eng.submit(np.arange(9, dtype=np.int32))

    def test_max_prompt_beyond_positions_rejected(self):
        with pytest.raises(ValueError, match="max_position"):
            make_engine(max_prompt=CFG.max_position + 1,
                        max_pages_per_seq=64)

    def test_submit_after_stop_rejected(self):
        eng = make_engine().start()
        eng.stop()
        with pytest.raises(RuntimeError, match="stopped"):
            eng.submit(PROMPTS[0])

    def test_out_of_vocab_prompt_rejected(self):
        eng = make_engine()
        with pytest.raises(ValueError, match="token ids"):
            eng.submit(np.array([CFG.vocab_size], np.int32))
        with pytest.raises(ValueError, match="token ids"):
            eng.submit(np.array([-1], np.int32))

    def test_stop_delivers_partial_results_as_stopped(self):
        """stop() mid-generation retires in-flight slots with reason
        'stopped' and their partial tokens — not a bare exception."""
        eng = make_engine(max_slots=1)
        fut = eng.submit(PROMPTS[0], max_new_tokens=50)
        eng.step()  # admit + first decode: at least 2 tokens exist
        eng.stop()
        res = fut.result(timeout=0)
        assert res.finish_reason == "stopped"
        assert res.tokens.size >= 1
        np.testing.assert_array_equal(
            res.tokens,
            reference_generate(MODEL.params, CFG, PROMPTS[0],
                               len(res.tokens)))
        eng.cache.check_invariants()
        assert eng.cache.free_pages == eng.cache.num_pages

    def test_bad_sampling_knobs_rejected(self):
        eng = make_engine()
        with pytest.raises(ValueError, match="top_p"):
            eng.submit(PROMPTS[0], top_p=0.0)  # would degenerate to id 0
        with pytest.raises(ValueError, match="top_k"):
            eng.submit(PROMPTS[0], top_k=-1)

    def test_eos_at_page_boundary_retires_as_eos(self):
        """A slot whose LAST decode emitted eos while sitting at a page
        boundary must retire as 'eos' (trimmed), not grab a capacity page
        or get mis-retired as oom/overflow."""
        probe = make_engine(max_slots=1).generate(
            [np.arange(1, 8, dtype=np.int32)], max_new_tokens=3)[0]
        eos = int(probe.tokens[1])  # second generated token
        eng = make_engine(max_slots=1, page_size=8)
        # prompt 7 tokens: after first decode seq_len=8 == page boundary;
        # the eos arrives exactly there
        res = eng.generate([np.arange(1, 8, dtype=np.int32)],
                           max_new_tokens=10, eos_token=eos)[0]
        assert res.finish_reason == "eos"  # not oom/overflow at the boundary
        assert eos not in res.tokens.tolist()  # trimmed
        assert res.tokens.size < probe.tokens.size + 1
        eng.cache.check_invariants()
        assert eng.cache.free_pages == eng.cache.num_pages

    def test_page_aligned_prompt(self):
        """Regression: admission must allocate pages for prompt + 1 — with
        a page-aligned prompt the SAME iteration's decode writes the first
        generated token's K/V at position p_len, which otherwise lands on
        the trash page and is permanently lost (later steps attend to a
        zeroed page at that position). Asserted white-box: after the first
        decode the next page must be real and hold nonzero K/V."""
        eng = make_engine(max_slots=1, page_size=8)
        prompt = np.arange(1, 9, dtype=np.int32)  # 8 == page_size exactly
        fut = eng.submit(prompt, max_new_tokens=4)
        eng.step()  # admit + prefill + first decode (writes position 8)
        slot = eng.scheduler.active_slots()[0]
        page1 = int(eng.cache.page_table[slot, 1])
        assert page1 != eng.cache.trash_page, (
            "admission did not allocate the page the first decode writes")
        pos8_kv = np.asarray(eng.cache.kv[:, :, page1, 0])
        assert np.abs(pos8_kv).max() > 0, (
            "first generated token's K/V was lost to the trash page")
        while eng.scheduler.has_work():
            eng.step()
        res = fut.result(timeout=0)
        np.testing.assert_array_equal(
            res.tokens, reference_generate(MODEL.params, CFG, prompt, 4))
        eng.cache.check_invariants()


# ---------------------------------------------------------------------------
# compile-once: jit-signature stability across admits/evicts
# ---------------------------------------------------------------------------


class TestDecodeJitStability:
    def test_one_compile_across_admits_and_evicts(self):
        observe.reset()
        eng = make_engine(max_slots=2)
        eng.generate(PROMPTS, max_new_tokens=4)  # 5 reqs > 2 slots: turnover
        serving = [e for e in observe.ledger().events()
                   if e.graph == "serving"]
        by_key = {}
        for ev in serving:
            by_key.setdefault(ev.key, []).append(ev.cause)
        assert by_key["decode"] == ["first_compile"], by_key
        assert by_key["prefill"] == ["first_compile"], by_key
        assert not any("new_shape" in causes for causes in by_key.values())


# ---------------------------------------------------------------------------
# PRNG hygiene: no key reuse across the scheduler loop (GL004 at runtime)
# ---------------------------------------------------------------------------


class TestPrngHygiene:
    def test_no_key_reuse_across_loop(self):
        eng = make_engine(max_slots=2, seed=11)
        eng.generate(PROMPTS, max_new_tokens=5)
        trail = list(eng.key_trail)
        # every prefill and every decode step consumed exactly one fresh key
        assert len(trail) >= len(PROMPTS) + 5
        assert len(set(trail)) == len(trail), (
            "a PRNG key value was issued twice across the scheduler loop")

    def test_sampling_differs_across_steps(self):
        """Same slot, same logits landscape, successive steps: sampled
        continuations must not be locked to one token by key reuse."""
        eng = make_engine(max_slots=1, seed=12)
        res = eng.generate([PROMPTS[2]], max_new_tokens=24,
                           temperature=1.5, top_k=0, top_p=1.0)[0]
        assert len(set(res.tokens.tolist())) > 1


# ---------------------------------------------------------------------------
# zoo / hub / serde registration
# ---------------------------------------------------------------------------


class TestGptRegistration:
    def test_zoo_listing(self):
        assert hasattr(models, "GPT")
        m = models.GPT("tiny", seed=2).init()
        assert isinstance(m, GptModel)
        with pytest.raises(ValueError, match="preset"):
            models.GPT("huge")

    def test_config_round_trip(self):
        cfg = GptConfig.tiny(vocab_size=300, eos_token=7)
        assert GptConfig.from_json(cfg.to_json()) == cfg

    def test_hub_round_trip(self, tmp_path):
        hub = models.ModelHub(root=str(tmp_path))
        hub.publish("gpt-t", MODEL, metadata={"purpose": "test"})
        assert "gpt-t" in hub.list_models()
        assert hub.manifest("gpt-t")["kind"] == "GptModel"
        loaded = hub.load("gpt-t")
        assert isinstance(loaded, GptModel) and loaded.cfg == CFG
        ids = np.array([[3, 1, 4]], np.int32)
        np.testing.assert_allclose(loaded.logits(ids), MODEL.logits(ids),
                                   rtol=1e-6, atol=1e-6)

    def test_serde_preserves_dtype(self, tmp_path):
        import jax.numpy as jnp

        from deeplearning4j_tpu.models.gpt import restore_gpt, save_gpt

        m = GptModel(CFG, seed=4, dtype=jnp.bfloat16)
        p = str(tmp_path / "bf16.zip")
        save_gpt(m, p)
        loaded = restore_gpt(p)
        leaf = jax.tree.leaves(loaded.params)[0]
        assert leaf.dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(jax.tree.leaves(m.params)[0], np.float32),
            np.asarray(jax.tree.leaves(loaded.params)[0], np.float32))

    def test_serde_detects_mismatch(self, tmp_path):
        import zipfile

        from deeplearning4j_tpu.models.gpt import restore_gpt, save_gpt

        p = str(tmp_path / "m.zip")
        save_gpt(MODEL, p)
        with zipfile.ZipFile(p) as z:
            cfg_json = z.read("configuration.json").decode()
            coeff = z.read("coefficients.bin")
        with zipfile.ZipFile(p, "w") as z:  # truncate the buffer
            z.writestr("configuration.json", cfg_json)
            z.writestr("coefficients.bin", coeff[:-8])
        with pytest.raises(ValueError, match="mismatch"):
            restore_gpt(p)
