"""Op-catalog tests — per-op numeric cases vs numpy oracles.

Reference analog: libnd4j DeclarableOpsTests*.cpp (hand-computed expectations)
and ND4J OpValidation per-op forward checks.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.ops import registry, exec_op
from deeplearning4j_tpu.ops import nn_ops, activations, losses
from deeplearning4j_tpu.ops.weight_init import init_weights


class TestRegistry:
    def test_catalog_populated(self):
        names = registry().names()
        for required in ["conv2d", "maxpool2d", "batchnorm", "lstm_cell",
                         "dot_product_attention", "matmul", "encode_threshold"]:
            assert required in names

    def test_exec_by_name(self):
        a = jnp.ones((2, 3))
        b = jnp.ones((3, 4))
        out = exec_op("matmul", a, b)
        np.testing.assert_allclose(out, 3 * np.ones((2, 4)))

    def test_shape_calculation(self):
        shape = registry().calculate_output_shape(
            "conv2d", jax.ShapeDtypeStruct((2, 8, 8, 3), jnp.float32),
            jax.ShapeDtypeStruct((3, 3, 3, 16), jnp.float32),
            stride=1, padding="same")
        assert shape.shape == (2, 8, 8, 16)

    def test_unknown_op_raises(self):
        with pytest.raises(KeyError):
            registry().get("nonexistent_op_xyz")


class TestConv:
    def test_conv2d_identity_kernel(self):
        x = jnp.arange(16.0).reshape(1, 4, 4, 1)
        w = jnp.zeros((3, 3, 1, 1)).at[1, 1, 0, 0].set(1.0)
        out = nn_ops.conv2d(x, w, padding="same")
        np.testing.assert_allclose(out, x)

    def test_conv2d_vs_manual(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 5, 5, 3).astype(np.float32)
        w = rng.randn(3, 3, 3, 4).astype(np.float32)
        out = np.asarray(nn_ops.conv2d(jnp.array(x), jnp.array(w), padding="valid"))
        # manual valid conv at position (0,0), batch 0, out-channel 1
        patch = x[0, 0:3, 0:3, :]
        expected = np.sum(patch * w[:, :, :, 1])
        np.testing.assert_allclose(out[0, 0, 0, 1], expected, rtol=1e-4)

    def test_depthwise(self):
        x = jnp.ones((1, 4, 4, 2))
        w = jnp.ones((3, 3, 2, 1))
        out = nn_ops.depthwise_conv2d(x, w, padding="valid")
        assert out.shape == (1, 2, 2, 2)
        np.testing.assert_allclose(out, 9.0 * np.ones((1, 2, 2, 2)))

    def test_deconv_shape(self):
        x = jnp.ones((1, 4, 4, 3))
        w = jnp.ones((2, 2, 3, 8))
        out = nn_ops.deconv2d(x, w, stride=2, padding="valid")
        assert out.shape == (1, 8, 8, 8)


class TestPooling:
    def test_maxpool(self):
        x = jnp.arange(16.0).reshape(1, 4, 4, 1)
        out = nn_ops.maxpool2d(x, kernel=2, stride=2)
        np.testing.assert_allclose(np.asarray(out).reshape(2, 2),
                                   [[5.0, 7.0], [13.0, 15.0]])

    def test_avgpool(self):
        x = jnp.arange(16.0).reshape(1, 4, 4, 1)
        out = nn_ops.avgpool2d(x, kernel=2, stride=2)
        np.testing.assert_allclose(np.asarray(out).reshape(2, 2),
                                   [[2.5, 4.5], [10.5, 12.5]])

    def test_pnorm(self):
        x = jnp.ones((1, 2, 2, 1)) * 2.0
        out = nn_ops.pnormpool2d(x, kernel=2, stride=2, p=2.0)
        np.testing.assert_allclose(np.asarray(out).ravel(), [4.0])


class TestNorm:
    def test_batchnorm_inference(self):
        x = jnp.array([[1.0, 2.0], [3.0, 4.0]])
        mean = jnp.array([2.0, 3.0])
        var = jnp.array([1.0, 1.0])
        out = nn_ops.batchnorm(x, mean, var, eps=0.0)
        np.testing.assert_allclose(out, [[-1.0, -1.0], [1.0, 1.0]], atol=1e-6)

    def test_batchnorm_train_normalizes(self):
        rng = np.random.RandomState(1)
        x = jnp.array(rng.randn(64, 8).astype(np.float32) * 3 + 5)
        out, nm, nv = nn_ops.batch_norm_train(
            x, jnp.ones(8), jnp.zeros(8), jnp.zeros(8), jnp.ones(8), axis=(0,))
        np.testing.assert_allclose(np.asarray(out).mean(0), np.zeros(8), atol=1e-4)
        np.testing.assert_allclose(np.asarray(out).std(0), np.ones(8), atol=1e-2)

    def test_layer_norm(self):
        x = jnp.array([[1.0, 2.0, 3.0]])
        out = nn_ops.layer_norm(x, jnp.ones(3), eps=0.0)
        np.testing.assert_allclose(np.asarray(out).mean(), 0.0, atol=1e-6)


class TestAttention:
    def test_attention_uniform(self):
        # identical keys -> uniform weights -> mean of values
        q = jnp.ones((1, 2, 4))
        k = jnp.ones((1, 3, 4))
        v = jnp.arange(6.0).reshape(1, 3, 2)
        out = nn_ops.dot_product_attention(q, k, v)
        np.testing.assert_allclose(out[0, 0], np.asarray(v[0]).mean(0), rtol=1e-5)

    def test_attention_mask(self):
        q = jnp.ones((1, 1, 4))
        k = jnp.ones((1, 3, 4))
        v = jnp.array([[[1.0], [2.0], [100.0]]])
        mask = jnp.array([[[True, True, False]]])
        out = nn_ops.dot_product_attention(q, k, v, mask)
        np.testing.assert_allclose(out[0, 0, 0], 1.5, rtol=1e-4)

    def test_mha_shape(self):
        B, L, D = 2, 5, 8
        rng = np.random.RandomState(0)
        q = jnp.array(rng.randn(B, L, D).astype(np.float32))
        w = [jnp.array(rng.randn(D, D).astype(np.float32) * 0.1) for _ in range(4)]
        out = nn_ops.multi_head_dot_product_attention(q, q, q, *w, num_heads=2)
        assert out.shape == (B, L, D)


class TestRecurrentCells:
    def test_lstm_cell_shapes_and_bounds(self):
        B, I, H = 3, 4, 5
        rng = np.random.RandomState(0)
        h, c = nn_ops.lstm_cell(
            jnp.array(rng.randn(B, I).astype(np.float32)),
            jnp.zeros((B, H)), jnp.zeros((B, H)),
            jnp.array(rng.randn(I, 4 * H).astype(np.float32)),
            jnp.array(rng.randn(H, 4 * H).astype(np.float32)),
            jnp.zeros(4 * H))
        assert h.shape == (B, H) and c.shape == (B, H)
        assert np.all(np.abs(np.asarray(h)) <= 1.0)

    def test_gru_cell(self):
        B, I, H = 2, 3, 4
        rng = np.random.RandomState(0)
        h = nn_ops.gru_cell(
            jnp.array(rng.randn(B, I).astype(np.float32)), jnp.zeros((B, H)),
            jnp.array(rng.randn(I, 3 * H).astype(np.float32)),
            jnp.array(rng.randn(H, 3 * H).astype(np.float32)),
            jnp.zeros(3 * H), jnp.zeros(3 * H))
        assert h.shape == (B, H)


class TestActivations:
    @pytest.mark.parametrize("name", sorted(activations.ACTIVATIONS))
    def test_finite(self, name):
        fn = activations.get_activation(name)
        x = jnp.linspace(-3, 3, 7)
        out = fn(x)
        assert np.all(np.isfinite(np.asarray(out)))

    def test_known_values(self):
        np.testing.assert_allclose(activations.relu(jnp.array([-1.0, 2.0])), [0.0, 2.0])
        np.testing.assert_allclose(activations.sigmoid(jnp.array([0.0])), [0.5])
        np.testing.assert_allclose(
            np.asarray(activations.softmax(jnp.array([1.0, 1.0]))), [0.5, 0.5])
        np.testing.assert_allclose(activations.hardsigmoid(jnp.array([-10.0, 0.0, 10.0])),
                                   [0.0, 0.5, 1.0])


class TestLosses:
    def test_mcxent_perfect(self):
        probs = jnp.array([[1.0, 0.0], [0.0, 1.0]])
        labels = jnp.array([[1.0, 0.0], [0.0, 1.0]])
        assert float(losses.mcxent(probs, labels)) < 1e-6

    def test_softmax_ce_matches_mcxent(self):
        rng = np.random.RandomState(0)
        logits = jnp.array(rng.randn(4, 5).astype(np.float32))
        labels = jax.nn.one_hot(jnp.array([0, 1, 2, 3]), 5)
        fused = losses.softmax_cross_entropy_with_logits(logits, labels)
        unfused = losses.mcxent(jax.nn.softmax(logits), labels)
        np.testing.assert_allclose(float(fused), float(unfused), rtol=1e-5)

    def test_sparse_matches_dense(self):
        rng = np.random.RandomState(0)
        logits = jnp.array(rng.randn(4, 5).astype(np.float32))
        ids = jnp.array([0, 1, 2, 3])
        dense = losses.softmax_cross_entropy_with_logits(logits, jax.nn.one_hot(ids, 5))
        sparse = losses.sparse_mcxent(logits, ids)
        np.testing.assert_allclose(float(dense), float(sparse), rtol=1e-5)

    def test_mse(self):
        preds = jnp.array([[1.0, 2.0]])
        labels = jnp.array([[0.0, 0.0]])
        np.testing.assert_allclose(float(losses.mse(preds, labels)), 2.5)

    def test_mask(self):
        preds = jnp.array([[1.0], [100.0]])
        labels = jnp.array([[0.0], [0.0]])
        mask = jnp.array([1.0, 0.0])
        np.testing.assert_allclose(float(losses.mse(preds, labels, mask)), 1.0)


class TestWeightInit:
    @pytest.mark.parametrize("scheme", ["xavier", "relu", "uniform", "normal",
                                        "lecun_normal", "xavier_uniform"])
    def test_variance(self, scheme, jax_key):
        w = init_weights(jax_key, (256, 128), scheme)
        assert w.shape == (256, 128)
        assert float(jnp.std(w)) > 0.0

    def test_zero_ones_identity(self, jax_key):
        assert float(jnp.sum(init_weights(jax_key, (3, 3), "zero"))) == 0.0
        assert float(jnp.sum(init_weights(jax_key, (3, 3), "ones"))) == 9.0
        np.testing.assert_allclose(init_weights(jax_key, (3, 3), "identity"), np.eye(3))


class TestCompression:
    def test_roundtrip(self):
        from deeplearning4j_tpu.ops import compression

        g = jnp.array([0.5, -0.01, 0.02, -2.0, 0.001])
        enc, residual = compression.encode_threshold(g, threshold=0.1, capacity=4)
        dec = compression.decode_threshold(enc, shape=(5,))
        # decoded + residual == original
        np.testing.assert_allclose(np.asarray(dec) + np.asarray(residual),
                                   np.asarray(g), atol=1e-6)
        assert int(enc.count) == 2

    def test_bitmap(self):
        from deeplearning4j_tpu.ops import compression

        g = jnp.array([0.5, -0.5, 0.0])
        code, residual = compression.encode_bitmap(g, threshold=0.1)
        dec = compression.decode_bitmap(code, threshold=0.1)
        np.testing.assert_allclose(np.asarray(dec) + np.asarray(residual),
                                   np.asarray(g), atol=1e-6)
