"""Regression tests for the round-1 advisor findings (ADVICE.md):
control-flow op isolation between SameDiff instances, training-step
persistence in SameDiff.save/load, per-segment tBPTT iteration advance,
2-D evaluation masks, and the dropout semantics converter."""

import numpy as np
import pytest

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.autodiff.samediff import SameDiff, TrainingConfig
from deeplearning4j_tpu.eval.evaluation import Evaluation


class TestControlFlowIsolation:
    def test_two_instances_same_counter_do_not_collide(self):
        """Two SameDiff graphs generate the same 'scan_1_impl' counter name;
        each must keep its own body closure (ADVICE finding 1)."""
        def build(mult):
            sd = SameDiff()
            xs = sd.placeholder("xs", (4,))
            out = sd.scan(lambda c, x: (c, mult * x), 0.0, xs)
            return sd, out

        sd_a, out_a = build(2.0)
        sd_b, out_b = build(10.0)
        xs = np.arange(4, dtype=np.float32)
        # re-execute A AFTER B registered its own scan_1_impl
        res_a = sd_a.output({"xs": xs}, out_a.name)[out_a.name]
        res_b = sd_b.output({"xs": xs}, out_b.name)[out_b.name]
        np.testing.assert_allclose(res_a, 2.0 * xs)
        np.testing.assert_allclose(res_b, 10.0 * xs)

    def test_save_refuses_control_flow_graphs(self, tmp_path):
        sd = SameDiff()
        xs = sd.placeholder("xs", (4,))
        sd.scan(lambda c, x: (c, x + 1.0), 0.0, xs)
        with pytest.raises(ValueError, match="control-flow"):
            sd.save(str(tmp_path / "g.sd"))


class TestSaveStepPersistence:
    def test_step_round_trips(self, tmp_path):
        sd = SameDiff()
        x = sd.placeholder("x", (None, 2))
        y = sd.placeholder("y", (None, 1))
        w = sd.var("w", np.zeros((2, 1), np.float32))
        pred = x.mmul(w)
        loss = sd.loss.mean_squared_error(pred, y).rename("loss")
        sd.set_training_config(TrainingConfig(
            updater=nn.Adam(learning_rate=0.05),
            data_set_feature_mapping=["x"], data_set_label_mapping=["y"],
            loss_variables=["loss"]))
        rng = np.random.RandomState(0)
        xa = rng.randn(16, 2).astype(np.float32)
        ya = (xa @ np.array([[1.0], [-2.0]], np.float32))
        from deeplearning4j_tpu.datasets import DataSet

        sd.fit(DataSet(xa, ya), epochs=3)
        assert sd._step > 0
        p = str(tmp_path / "m.sd")
        sd.save(p, save_updater_state=True)
        sd2 = SameDiff.load(p)
        assert sd2._step == sd._step


class TestTbpttIterationAdvance:
    def test_iteration_advances_per_segment(self):
        net = nn.MultiLayerNetwork(
            nn.builder().seed(3).tbptt(5).list()
            .layer(nn.LSTM(n_out=4, activation="tanh"))
            .layer(nn.RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.recurrent(3)).build()
        ).init()
        x = np.random.RandomState(0).randn(4, 20, 3).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[np.zeros((4, 20), int)]
        net.fit(x, y, epochs=1, batch_size=4)
        # 20 timesteps / fwd 5 = 4 segments = 4 optimize calls (reference
        # increments the iteration per optimize call)
        assert net.iteration_count == 4


class TestEval2DMask:
    def test_2d_mask_rows_excluded(self):
        ev = Evaluation()
        labels = np.eye(2, dtype=np.float32)[[0, 1, 0, 1]]
        preds = np.eye(2, dtype=np.float32)[[0, 1, 1, 0]]  # last two wrong
        ev.eval(labels, preds, mask=np.array([1, 1, 0, 0]))
        assert ev.confusion.sum() == 2
        assert ev.accuracy() == 1.0


class TestDropoutConverter:
    def test_retain_prob_conversion(self):
        assert nn.dl4j_drop_out(0.8) == pytest.approx(0.2)
        # dropOut(0.0) is the reference's 'disabled' sentinel
        assert nn.dl4j_drop_out(0.0) == 0.0
        with pytest.raises(ValueError):
            nn.dl4j_drop_out(-0.5)

    def test_per_output_mask_rejected(self):
        ev = Evaluation()
        labels = np.eye(2, dtype=np.float32)[[0, 1]]
        with pytest.raises(ValueError, match="per-output"):
            ev.eval(labels, labels, mask=np.ones((2, 2)))
