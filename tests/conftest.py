"""Test harness.

Reference test strategy parity (SURVEY §5): tests run on the CPU backend as
the de-facto reference implementation; distributed logic is exercised on a
virtual multi-device mesh (the analog of DL4J's Spark local[N] + Aeron
loopback tests). We force an 8-device CPU platform BEFORE jax import.
"""

import os

# Unit tests run on the CPU reference backend; the real chip is exercised by
# bench.py and the driver's compile checks. The ambient environment pins
# JAX_PLATFORMS=axon via a sitecustomize that also updates jax.config at
# interpreter startup, so overriding the env var alone is not enough — we must
# update the config after import, before any backend is touched.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# Tests read tuning from a throwaway cache dir: a measured table left in the
# user cache by `make tune-smoke` must not change dispatch thresholds under
# test (the checked-in default table keeps untuned hosts deterministic).
import tempfile

os.environ["DL4J_TPU_TUNING_DIR"] = tempfile.mkdtemp(prefix="dl4j_tuning_test_")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.RandomState(12345)


@pytest.fixture
def jax_key():
    import jax

    return jax.random.key(0)


def assert_allclose(a, b, rtol=1e-5, atol=1e-6):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol)
