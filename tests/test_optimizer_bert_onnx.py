"""Optimizer acceptance at scale: the BERT-base ONNX import path.

Assembles a 12-layer BERT-base (D=768, H=12, FF=3072) ModelProto at the
protobuf wire level — with the redundancy real per-module tracing exporters
emit (the attention-mask expansion chain re-inlined in every layer, Dropout
and Identity no-op nodes, per-layer scale chains computed from constants) —
imports it twice (optimizer off/on) and asserts:

  * the optimizer removes >= 15% of the imported SameDiff nodes,
  * optimized and unoptimized outputs match within 1e-5,
  * SameDiff.last_compile_stats reports per-pass node deltas.

Sequence length and batch are kept small: node count (what the optimizer
attacks) is structural, and this keeps the double compile CI-sane.
"""

import numpy as np

from deeplearning4j_tpu.imports.onnx_import import import_onnx
from deeplearning4j_tpu.testing.onnx_builder import bert_onnx_model

B, T, D, HEADS, FF, LAYERS, VOCAB = 1, 16, 768, 12, 3072, 12, 512
HD = D // HEADS


def _bert_base_model():
    return bert_onnx_model(layers=LAYERS, batch=B, seq=T, d=D, heads=HEADS,
                           ff=FF, vocab=VOCAB)


class TestBertBaseOnnxOptimizer:
    def test_node_reduction_and_equivalence(self):
        from deeplearning4j_tpu.environment import environment

        model = _bert_base_model()
        r = np.random.RandomState(1)
        feeds = {
            "ids": r.randint(0, VOCAB, (B, T)).astype(np.float32),
            "mask": (r.rand(B, T) > 0.1).astype(np.float32),
        }

        sd_ref = import_onnx(model, optimize=False)
        ref = sd_ref.output(feeds, ["y"])["y"]

        # helper_mode="xla" pins BOTH runs to the generic registry impls:
        # the fused-vs-unfused comparison isolates the REWRITE, not the
        # Pallas kernel (which tests/test_optimizer_fusion.py covers)
        env = environment()
        prev = env.helper_mode
        env.helper_mode = "xla"
        try:
            sd = import_onnx(model)
            got = sd.output(feeds, ["y"])["y"]
        finally:
            env.helper_mode = prev
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

        st = sd.last_compile_stats
        reduction = st.removed / st.nodes_before
        assert reduction >= 0.15, (
            f"only {reduction:.1%} of {st.nodes_before} nodes removed; "
            f"passes: { {k: v['removed'] for k, v in st.passes.items()} }")
        # per-pass node deltas are reported, and every pass contributed
        for p in ("dce", "fold", "cse", "algebraic", "fusion"):
            assert st.passes[p]["removed"] > 0, f"pass '{p}' removed nothing"
        # the fusion-tier acceptance: ONE dot_product_attention per layer
        # (so the shape-aware flash dispatch applies to the import path)
        # and the six matmul+bias projections per layer fused — incl. the
        # decomposed-erf-gelu FF1 epilogue
        assert st.fusions["attention"] == LAYERS, st.fusions
        assert st.fusions["epilogue"] >= 6 * LAYERS, st.fusions
        plan_ops = [n.op for n in sd._jit_cache[
            ("plan", ("y",), sd._effective_passes())].nodes]
        assert plan_ops.count("dot_product_attention") == LAYERS
        assert plan_ops.count("fused_matmul_bias_act") >= 6 * LAYERS
        # the only surviving softmax is the classifier head — every
        # attention softmax was swallowed by a fused node
        assert plan_ops.count("softmax") == 1
        # algebraic still kills the Dropout/Identity no-ops; the per-layer
        # mask-expansion chains are now claimed by fusion+DCE (the fused
        # node consumes the raw mask, orphaning the penalty arithmetic),
        # so CSE's floor is the first-level dedup of the duplicated chains
        assert st.passes["algebraic"]["removed"] >= 4 * LAYERS
        assert st.passes["cse"]["removed"] >= LAYERS - 1
