"""Optimizer acceptance at scale: the BERT-base ONNX import path.

Assembles a 12-layer BERT-base (D=768, H=12, FF=3072) ModelProto at the
protobuf wire level — with the redundancy real per-module tracing exporters
emit (the attention-mask expansion chain re-inlined in every layer, Dropout
and Identity no-op nodes, per-layer scale chains computed from constants) —
imports it twice (optimizer off/on) and asserts:

  * the optimizer removes >= 15% of the imported SameDiff nodes,
  * optimized and unoptimized outputs match within 1e-5,
  * SameDiff.last_compile_stats reports per-pass node deltas.

Sequence length and batch are kept small: node count (what the optimizer
attacks) is structural, and this keeps the double compile CI-sane.
"""

import numpy as np

from tests.test_onnx_import import build_model, node_proto

from deeplearning4j_tpu.imports.onnx_import import import_onnx

B, T, D, HEADS, FF, LAYERS, VOCAB = 1, 16, 768, 12, 3072, 12, 512
HD = D // HEADS


def _bert_base_model():
    r = np.random.RandomState(0)
    nodes = []
    init = {
        "emb": (r.randn(VOCAB, D) * 0.02).astype(np.float32),
        "pos": (r.randn(T, D) * 0.02).astype(np.float32),
        "cls_w": (r.randn(D, 2) * 0.02).astype(np.float32),
        "shape_split": np.asarray([B, T, HEADS, HD], np.int64),
        "shape_merge": np.asarray([B, T, D], np.int64),
        "one": np.float32(1.0),
        "half": np.float32(0.5),
        "two": np.float32(2.0),
        "neg_big": np.float32(-10000.0),
        "hd_f": np.float32(HD),
        "eps": np.float32(1e-6),
    }

    def n(op, ins, outs, **attrs):
        nodes.append(node_proto(op, ins, outs, **attrs))
        return outs[0]

    def layer_norm(p, x):
        mu = n("ReduceMean", [x], [f"{p}_mu"], axes=[-1], keepdims=1)
        d = n("Sub", [x, mu], [f"{p}_d"])
        sq = n("Pow", [d, "two"], [f"{p}_sq"])
        var = n("ReduceMean", [sq], [f"{p}_var"], axes=[-1], keepdims=1)
        ve = n("Add", [var, "eps"], [f"{p}_ve"])
        std = n("Sqrt", [ve], [f"{p}_std"])
        norm = n("Div", [d, std], [f"{p}_norm"])
        g = n("Mul", [norm, f"{p}_g"], [f"{p}_gn"])
        return n("Add", [g, f"{p}_b"], [f"{p}_out"])

    x = n("Gather", ["emb", "ids"], ["embedded"], axis=0)
    x = n("Add", [x, "pos"], ["h0"])

    for i in range(LAYERS):
        p = f"l{i}"
        for nm, shape in [("wq", (D, D)), ("wk", (D, D)), ("wv", (D, D)),
                          ("wo", (D, D)), ("w1", (D, FF)), ("w2", (FF, D))]:
            init[f"{p}_{nm}"] = (r.randn(*shape) * 0.02).astype(np.float32)
        for nm, size in [("bq", D), ("bk", D), ("bv", D), ("bo", D),
                         ("b1", FF), ("b2", D)]:
            init[f"{p}_{nm}"] = np.zeros(size, np.float32)
        for ln in ("ln1", "ln2"):
            init[f"{p}_{ln}_g"] = np.ones(D, np.float32)
            init[f"{p}_{ln}_b"] = np.zeros(D, np.float32)

        # the attention-mask expansion chain, re-inlined per layer exactly
        # as per-module tracing exporters do — the CSE target
        mu = n("Unsqueeze", ["mask"], [f"{p}_mask_u"], axes=[1, 2])
        mc = n("Cast", [mu], [f"{p}_mask_c"], to=1)
        mi = n("Sub", ["one", mc], [f"{p}_mask_i"])
        pen = n("Mul", [mi, "neg_big"], [f"{p}_mask_pen"])

        heads = {}
        for t in ("q", "k", "v"):
            mm = n("MatMul", [x, f"{p}_w{t}"], [f"{p}_{t}mm"])
            a = n("Add", [mm, f"{p}_b{t}"], [f"{p}_{t}"])
            rs = n("Reshape", [a, "shape_split"], [f"{p}_{t}r"])
            heads[t] = n("Transpose", [rs], [f"{p}_{t}h"], perm=[0, 2, 1, 3])
        kt = n("Transpose", [heads["k"]], [f"{p}_kt"], perm=[0, 1, 3, 2])
        scores = n("MatMul", [heads["q"], kt], [f"{p}_scores"])
        scale = n("Sqrt", ["hd_f"], [f"{p}_scale"])  # foldable const chain
        scaled = n("Div", [scores, scale], [f"{p}_scaled"])
        masked = n("Add", [scaled, pen], [f"{p}_masked"])
        probs = n("Softmax", [masked], [f"{p}_probs"], axis=-1)
        probs = n("Dropout", [probs], [f"{p}_probs_d"])  # no-op at inference
        ctx = n("MatMul", [probs, heads["v"]], [f"{p}_ctx"])
        ctx = n("Transpose", [ctx], [f"{p}_ctxt"], perm=[0, 2, 1, 3])
        ctx = n("Reshape", [ctx, "shape_merge"], [f"{p}_ctxm"])
        proj = n("MatMul", [ctx, f"{p}_wo"], [f"{p}_projmm"])
        proj = n("Add", [proj, f"{p}_bo"], [f"{p}_proj"])
        proj = n("Dropout", [proj], [f"{p}_proj_d"])
        res = n("Add", [x, proj], [f"{p}_res1"])
        x1 = layer_norm(f"{p}_ln1", res)

        # FF with the decomposed-gelu chain exporters emit
        h1 = n("MatMul", [x1, f"{p}_w1"], [f"{p}_ffmm"])
        h1 = n("Add", [h1, f"{p}_b1"], [f"{p}_ff1"])
        s2 = n("Sqrt", ["two"], [f"{p}_sqrt2"])  # foldable const chain
        e = n("Div", [h1, s2], [f"{p}_ge_div"])
        e = n("Erf", [e], [f"{p}_ge_erf"])
        e = n("Add", [e, "one"], [f"{p}_ge_add"])
        e = n("Mul", [h1, e], [f"{p}_ge_mul"])
        g = n("Mul", [e, "half"], [f"{p}_gelu"])
        h2 = n("MatMul", [g, f"{p}_w2"], [f"{p}_ff2mm"])
        h2 = n("Add", [h2, f"{p}_b2"], [f"{p}_ff2"])
        h2 = n("Dropout", [h2], [f"{p}_ff2_d"])
        res2 = n("Add", [x1, h2], [f"{p}_res2"])
        x = layer_norm(f"{p}_ln2", res2)
        x = n("Identity", [x], [f"{p}_out"])  # exporter block boundary

    logits = n("MatMul", [x, "cls_w"], ["logits"])
    n("Softmax", [logits], ["y"], axis=-1)
    return build_model(nodes, [("ids", (B, T)), ("mask", (B, T))],
                       [("y", (B, T, 2))], init)


class TestBertBaseOnnxOptimizer:
    def test_node_reduction_and_equivalence(self):
        model = _bert_base_model()
        r = np.random.RandomState(1)
        feeds = {
            "ids": r.randint(0, VOCAB, (B, T)).astype(np.float32),
            "mask": (r.rand(B, T) > 0.1).astype(np.float32),
        }

        sd_ref = import_onnx(model, optimize=False)
        ref = sd_ref.output(feeds, ["y"])["y"]

        sd = import_onnx(model)
        got = sd.output(feeds, ["y"])["y"]
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

        st = sd.last_compile_stats
        reduction = st.removed / st.nodes_before
        assert reduction >= 0.15, (
            f"only {reduction:.1%} of {st.nodes_before} nodes removed; "
            f"passes: { {k: v['removed'] for k, v in st.passes.items()} }")
        # per-pass node deltas are reported, and every pass contributed
        for p in ("dce", "fold", "cse", "algebraic"):
            assert st.passes[p]["removed"] > 0, f"pass '{p}' removed nothing"
        # the win the instrumentation exists to prove: CSE collapsed the
        # per-layer mask chains, algebraic killed Dropout/Identity no-ops
        assert st.passes["cse"]["removed"] >= (LAYERS - 1) * 4
        assert st.passes["algebraic"]["removed"] >= 4 * LAYERS
