"""Ulysses all-to-all sequence parallelism vs dense attention — exact
equivalence on the 8-device CPU mesh (the ring_attention test pattern)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.ulysses import ulysses_attention


def _dense(q, k, v, scale, causal=False):
    s = np.einsum("bhqd,bhkd->bhqk", q * scale, k)
    if causal:
        t = s.shape[-1]
        mask = np.tril(np.ones((t, t), bool))
        s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


def _mesh(n):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(np.array(devs[:n]), ("seq",))


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        n = 4
        mesh = _mesh(n)
        b, h, t, d = 2, 8, 32, 16
        r = np.random.RandomState(0)
        q = r.randn(b, h, t, d).astype(np.float32)
        k = r.randn(b, h, t, d).astype(np.float32)
        v = r.randn(b, h, t, d).astype(np.float32)
        scale = 1.0 / np.sqrt(d)
        want = _dense(q, k, v, scale, causal)

        spec = NamedSharding(mesh, P(None, None, "seq", None))
        qj = jax.device_put(jnp.asarray(q), spec)
        kj = jax.device_put(jnp.asarray(k), spec)
        vj = jax.device_put(jnp.asarray(v), spec)
        got = np.asarray(ulysses_attention(qj, kj, vj, mesh=mesh,
                                           causal=causal))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_eight_way(self):
        n = 8
        mesh = _mesh(n)
        b, h, t, d = 1, 8, 64, 8
        r = np.random.RandomState(1)
        q = r.randn(b, h, t, d).astype(np.float32)
        k = r.randn(b, h, t, d).astype(np.float32)
        v = r.randn(b, h, t, d).astype(np.float32)
        want = _dense(q, k, v, 1.0 / np.sqrt(d))
        spec = NamedSharding(mesh, P(None, None, "seq", None))
        got = np.asarray(ulysses_attention(
            jax.device_put(jnp.asarray(q), spec),
            jax.device_put(jnp.asarray(k), spec),
            jax.device_put(jnp.asarray(v), spec), mesh=mesh))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_head_divisibility_enforced(self):
        mesh = _mesh(4)
        x = jnp.zeros((1, 6, 16, 8))  # 6 heads not divisible by 4
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(x, x, x, mesh=mesh)

    def test_matches_ring(self):
        """Both long-context strategies must agree (on merged BH layout)."""
        from deeplearning4j_tpu.parallel.ring_attention import ring_attention
        n = 4
        mesh = _mesh(n)
        b, h, t, d = 1, 4, 32, 8
        r = np.random.RandomState(2)
        q = r.randn(b, h, t, d).astype(np.float32)
        k = r.randn(b, h, t, d).astype(np.float32)
        v = r.randn(b, h, t, d).astype(np.float32)
        spec4 = NamedSharding(mesh, P(None, None, "seq", None))
        uly = np.asarray(ulysses_attention(
            jax.device_put(jnp.asarray(q), spec4),
            jax.device_put(jnp.asarray(k), spec4),
            jax.device_put(jnp.asarray(v), spec4), mesh=mesh))
        spec3 = NamedSharding(mesh, P(None, "seq", None))
        ring = np.asarray(ring_attention(
            jax.device_put(jnp.asarray(q.reshape(b * h, t, d)), spec3),
            jax.device_put(jnp.asarray(k.reshape(b * h, t, d)), spec3),
            jax.device_put(jnp.asarray(v.reshape(b * h, t, d)), spec3),
            mesh=mesh)).reshape(b, h, t, d)
        np.testing.assert_allclose(uly, ring, rtol=2e-4, atol=2e-4)
