"""Profiling, stats-UI shim, and native codec tests (SURVEY §6.1, §6.5,
§5.3 — OpProfiler/ProfilingListener/StatsListener + native-lib patterns)."""

import json
import os

import numpy as np
import pytest

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.datasets import DataSet, ListDataSetIterator
from deeplearning4j_tpu.utils.profiling import (
    OpProfiler, ChromeTraceWriter, ProfilingListener, ProfileAnalyzer,
)
from deeplearning4j_tpu.utils.stats import (
    StatsStorage, FileStatsStorage, StatsListener,
)
from deeplearning4j_tpu import native_ops


def xor():
    rng = np.random.RandomState(0)
    x = rng.rand(128, 2).astype(np.float32)
    y_id = ((x[:, 0] > 0.5) ^ (x[:, 1] > 0.5)).astype(int)
    return x, np.eye(2, dtype=np.float32)[y_id]


def small_net():
    return nn.MultiLayerNetwork(
        nn.builder().seed(1).updater(nn.Adam(learning_rate=0.02)).list()
        .layer(nn.DenseLayer(n_out=8, activation="tanh"))
        .layer(nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(nn.InputType.feed_forward(2)).build()
    ).init()


class TestProfiling:
    def test_op_profiler_counts(self):
        p = OpProfiler.instance()
        p.reset()
        p.start()
        p.record("conv2d", 0.001)
        p.record("conv2d", 0.002)
        p.record("matmul")
        p.stop()
        assert p.counts["conv2d"] == 2
        assert "conv2d" in p.stats()

    def test_chrome_trace_writer(self, tmp_path):
        w = ChromeTraceWriter()
        with w.span("step1", iteration=1):
            pass
        w.instant("epoch_end")
        path = str(tmp_path / "trace.json")
        w.write(path)
        data = json.load(open(path))
        assert len(data["traceEvents"]) == 2
        assert data["traceEvents"][0]["ph"] == "X"

    def test_profiling_listener_writes_trace(self, tmp_path):
        x, y = xor()
        net = small_net()
        path = str(tmp_path / "train_trace.json")
        net.set_listeners(ProfilingListener(path))
        net.fit(x, y, epochs=1, batch_size=32)
        data = json.load(open(path))
        steps = [e for e in data["traceEvents"] if e.get("cat") == "train_step"]
        assert len(steps) == 3  # 4 batches → 3 complete inter-iteration spans

    def test_profile_analyzer_compare(self, tmp_path):
        a, b = ChromeTraceWriter(), ChromeTraceWriter()
        with a.span("x", category="step"):
            pass
        with b.span("x", category="step"):
            pass
        pa, pb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
        a.write(pa)
        b.write(pb)
        cmp = ProfileAnalyzer.compare(pa, pb)
        assert "step" in cmp and "ratio" in cmp["step"]


class TestStatsListener:
    def test_collects_scores_and_ratios(self):
        x, y = xor()
        net = small_net()
        storage = StatsStorage()
        net.set_listeners(StatsListener(storage))
        net.fit(x, y, epochs=2, batch_size=64)
        assert len(storage.session_scores()) == 4
        latest = storage.latest()
        key = "0_W"
        assert key in latest["layers"]
        assert "update_ratio" in latest["layers"][key]  # the dead-LR chart
        assert latest["layers"][key]["update_ratio"] > 0

    def test_file_storage_round_trip(self, tmp_path):
        path = str(tmp_path / "stats.jsonl")
        s = FileStatsStorage(path)
        s.put({"score": 1.0, "iteration": 1})
        s2 = FileStatsStorage(path)
        assert s2.session_scores() == [1.0]

    def test_histograms(self):
        x, y = xor()
        net = small_net()
        storage = StatsStorage()
        net.set_listeners(StatsListener(storage, collect_histograms=True))
        net.fit(x, y, epochs=1, batch_size=128)
        assert "histogram" in storage.latest()["layers"]["0_W"]


class TestNativeCodec:
    def test_native_lib_builds(self):
        assert native_ops.native_available(), "cmake build of native codec failed"

    def test_encode_decode_round_trip(self):
        g = np.array([0.5, -0.2, 1.5, -2.0, 0.0, 0.9], np.float32)
        idx, residual = native_ops.threshold_encode(g, 1.0)
        assert list(idx) == [3, -4]
        decoded = native_ops.threshold_decode(idx, 1.0, g.size)
        np.testing.assert_allclose(decoded + residual, g, rtol=1e-6)

    def test_capacity_bound(self):
        g = np.full(100, 2.0, np.float32)
        idx, residual = native_ops.threshold_encode(g, 1.0, capacity=10)
        assert idx.size == 10
        assert residual[0] == pytest.approx(1.0)
        assert residual[50] == pytest.approx(2.0)  # untouched past capacity

    def test_bitmap_round_trip(self):
        g = np.array([0.5, -1.5, 2.5, 0.0], np.float32)
        bits, residual, nz = native_ops.bitmap_encode(g, 1.0)
        assert nz == 2
        decoded = native_ops.bitmap_decode(bits, 1.0, g.size)
        np.testing.assert_allclose(decoded + residual, g, rtol=1e-6)

    def test_compression_ratio_semantics(self):
        """Sparse gradient → few indices: the Strom-2015 bandwidth win."""
        rng = np.random.RandomState(0)
        g = np.zeros(10000, np.float32)
        hot = rng.choice(10000, 50, replace=False)
        g[hot] = rng.randn(50) * 10
        idx, _ = native_ops.threshold_encode(g, 1.0)
        assert idx.size <= 50
        assert idx.size >= 40

    def test_matches_python_fallback(self):
        from deeplearning4j_tpu.native_ops.threshold import _py_encode

        rng = np.random.RandomState(1)
        g = rng.randn(512).astype(np.float32)
        idx_n, res_n = native_ops.threshold_encode(g, 0.8)
        idx_p, res_p = _py_encode(g.copy(), 0.8, 512)
        np.testing.assert_array_equal(idx_n, idx_p)
        np.testing.assert_allclose(res_n, res_p, rtol=1e-6)


class TestNativeRecordLoader:
    """Native CSV/IDX loader (native/record_loader.cpp) — native-vs-python
    equality, the libnd4j-style two-impl check."""

    def test_csv_native_matches_python(self):
        from deeplearning4j_tpu.native_ops import record_loader as rl

        text = "h1,h2,h3\n1.5,2,3\n4,,bad\n7,8.25,9\n"
        out = rl.csv_to_float_matrix(text, 3, skip_rows=1)
        assert out.shape == (3, 3)
        np.testing.assert_allclose(out[0], [1.5, 2, 3])
        assert np.isnan(out[1, 1]) and np.isnan(out[1, 2])
        np.testing.assert_allclose(out[2], [7, 8.25, 9])
        if rl.native_loader_available():
            # force the python fallback and compare elementwise
            import deeplearning4j_tpu.native_ops.record_loader as mod

            orig = mod._loader_lib
            try:
                mod._loader_lib = lambda: None
                py = rl.csv_to_float_matrix(text, 3, skip_rows=1)
            finally:
                mod._loader_lib = orig
            np.testing.assert_array_equal(np.isnan(out), np.isnan(py))
            np.testing.assert_allclose(out[~np.isnan(out)], py[~np.isnan(py)])

    def test_csv_ragged_raises(self):
        from deeplearning4j_tpu.native_ops import record_loader as rl

        with pytest.raises(ValueError):
            rl.csv_to_float_matrix("1,2\n3\n", 2)

    def test_idx_round_trip(self):
        import struct

        from deeplearning4j_tpu.native_ops import record_loader as rl

        rng = np.random.RandomState(0)
        arr = rng.randint(0, 256, (4, 5, 6)).astype(np.uint8)
        buf = struct.pack(">BBBB", 0, 0, 0x08, 3)
        buf += struct.pack(">III", 4, 5, 6)
        buf += arr.tobytes()
        out = rl.idx_to_array(buf)
        assert out.shape == (4, 5, 6)
        np.testing.assert_allclose(out, arr.astype(np.float32) / 255.0)
        out2 = rl.idx_to_array(buf, scale=False)
        np.testing.assert_allclose(out2, arr.astype(np.float32))


class TestPixOps:
    """native/pixops.cpp kernels: normalize/standardize + murmur3
    (HashUtil role) — native and numpy fallback must agree bit-for-bit."""

    def test_u8_normalize_matches_numpy(self):
        from deeplearning4j_tpu.native_ops.pixops import u8_normalize
        r = np.random.RandomState(0)
        img = r.randint(0, 256, (4, 6, 3), np.uint8)
        out = u8_normalize(img, 1 / 255.0, 0.0)
        np.testing.assert_allclose(out, img.astype(np.float32) / 255.0,
                                   rtol=0, atol=1e-7)
        assert out.dtype == np.float32

    def test_u8_standardize_matches_numpy(self):
        from deeplearning4j_tpu.native_ops.pixops import u8_standardize
        r = np.random.RandomState(1)
        img = r.randint(0, 256, (2, 5, 5, 3), np.uint8)
        mean = np.asarray([100.0, 120.0, 140.0], np.float32)
        std = np.asarray([50.0, 60.0, 70.0], np.float32)
        out = u8_standardize(img, mean, std)
        np.testing.assert_allclose(
            out, (img.astype(np.float32) - mean) / std, rtol=1e-6, atol=1e-5)

    def test_murmur3_known_vectors(self):
        from deeplearning4j_tpu.native_ops.pixops import murmur3_32, _murmur3_py
        vectors = [(b"", 0, 0x0), (b"", 1, 0x514E28B7),
                   (b"abc", 0, 0xB3DD93FA), (b"hello", 0, 0x248BFA47)]
        for data, seed, want in vectors:
            assert murmur3_32(data, seed) == want
            assert _murmur3_py(data, seed) == want  # fallback bit-exact

    def test_murmur3_string_utf8(self):
        from deeplearning4j_tpu.native_ops.pixops import murmur3_32
        assert murmur3_32("hello") == murmur3_32(b"hello")
        # stability across calls (shard-assignment contract)
        assert murmur3_32("word", 7) == murmur3_32("word", 7)

    def test_scaler_uint8_fast_path(self):
        from deeplearning4j_tpu.datasets import (DataSet,
                                                 ImagePreProcessingScaler)
        r = np.random.RandomState(2)
        img = r.randint(0, 256, (3, 4, 4, 1), np.uint8)
        ds = DataSet(img, np.zeros((3, 2), np.float32))
        ImagePreProcessingScaler().transform(ds)
        np.testing.assert_allclose(ds.features,
                                   img.astype(np.float32) / 255.0,
                                   rtol=0, atol=1e-7)

    def test_standardize_uint8_fast_path(self):
        from deeplearning4j_tpu.datasets import DataSet, NormalizerStandardize
        r = np.random.RandomState(3)
        imgs = r.randint(0, 256, (8, 4, 4, 3), np.uint8)
        norm = NormalizerStandardize()
        norm.fit(DataSet(imgs.astype(np.float32), np.zeros((8, 1))))
        ds = DataSet(imgs, np.zeros((8, 1), np.float32))
        norm.transform(ds)
        want = (imgs.astype(np.float32) - norm.mean) / norm.std
        np.testing.assert_allclose(ds.features, want, rtol=1e-5, atol=1e-4)


class TestRequireNative:
    def test_require_native_raises_when_lib_missing(self, monkeypatch):
        """Under the gate (DL4J_TPU_REQUIRE_NATIVE=1) a missing native lib
        is a hard error, never a silent numpy fallback."""
        import pytest

        from deeplearning4j_tpu.native_ops import threshold as T

        monkeypatch.setattr(T, "_LIB", None)
        monkeypatch.setattr(T, "_TRIED", True)
        monkeypatch.setenv("DL4J_TPU_REQUIRE_NATIVE", "1")
        with pytest.raises(RuntimeError, match="REQUIRE_NATIVE"):
            T._get_lib()

    def test_missing_lib_falls_back_without_flag(self, monkeypatch):
        from deeplearning4j_tpu.native_ops import threshold as T

        monkeypatch.setattr(T, "_LIB", None)
        monkeypatch.setattr(T, "_TRIED", True)
        monkeypatch.delenv("DL4J_TPU_REQUIRE_NATIVE", raising=False)
        assert T._get_lib() is None  # caller uses the numpy path
