"""Regression tests for the round-3 advisor findings (ADVICE.md r3):
ONNX Mod fmod handling, TF resize/const-operand diagnostics, word2vec
binary truncation off-by-one, and spatial/alpha/gaussian dropout modes.
"""

import struct

import numpy as np
import pytest

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.nn.layers import build_layer
from deeplearning4j_tpu.ops.registry import registry


def make_net(*layers, input_type):
    b = nn.builder().seed(42).list()
    for l in layers:
        b.layer(l)
    return nn.MultiLayerNetwork(b.set_input_type(input_type).build()).init()


class TestOnnxModFmod:
    def _roundtrip(self, fmod):
        from deeplearning4j_tpu.imports.onnx_import import ONNX_OP_MAPPERS
        from deeplearning4j_tpu.autodiff.samediff import SameDiff

        sd = SameDiff()
        a = sd.placeholder("a", (4,))
        b = sd.placeholder("b", (4,))

        class FakeNode:
            pass

        out = ONNX_OP_MAPPERS["Mod"](sd, [a, b], {"fmod": fmod}, FakeNode())
        av = np.array([5.0, -5.0, 5.0, -5.0], np.float32)
        bv = np.array([3.0, 3.0, -3.0, -3.0], np.float32)
        return sd.output({"a": av, "b": bv}, out.name)[out.name], av, bv

    def test_fmod_1_is_trunc_mod(self):
        got, av, bv = self._roundtrip(1)
        np.testing.assert_allclose(got, np.fmod(av, bv), rtol=1e-6)

    def test_fmod_0_is_floor_mod(self):
        got, av, bv = self._roundtrip(0)
        np.testing.assert_allclose(got, np.mod(av, bv), rtol=1e-6)

    def test_trunc_and_floor_differ_on_mixed_signs(self):
        # sanity: the two conventions genuinely disagree here, so the
        # pre-fix mapping was silently wrong
        assert not np.allclose(np.fmod(-5.0, 3.0), np.mod(-5.0, 3.0))

    def test_truncatemod_in_registry(self):
        assert "truncatemod" in registry().names()


class TestTfImportDiagnostics:
    def test_dynamic_const_operand_raises_value_error(self):
        """Range with a dynamic limit must produce the _require_const
        diagnostic, not an opaque TypeError (ADVICE r3 finding 3)."""
        from deeplearning4j_tpu.imports.tf_import import TF_OP_MAPPERS
        from deeplearning4j_tpu.autodiff.samediff import SameDiff

        class FakeNode:
            op_type = "Range"
            name = "r"
            input = ["dyn_start", "dyn_limit", "delta"]

        sd = SameDiff()
        with pytest.raises(ValueError, match="must be a captured constant|dynamic"):
            TF_OP_MAPPERS["Range"](sd, [], {}, FakeNode(), const_values={})

    def test_legacy_nearest_resize_rejected(self):
        from deeplearning4j_tpu.imports.tf_import import TF_OP_MAPPERS
        from deeplearning4j_tpu.autodiff.samediff import SameDiff

        class FakeNode:
            op_type = "ResizeNearestNeighbor"
            name = "rn"
            input = ["x", "size"]

        sd = SameDiff()
        with pytest.raises(NotImplementedError, match="half_pixel_centers"):
            TF_OP_MAPPERS["ResizeNearestNeighbor"](
                sd, [], {"half_pixel_centers": False}, FakeNode(),
                const_values={"size": np.array([4, 4])})


class TestWord2vecTruncation:
    def test_truncated_by_one_byte_reports_word_index(self, tmp_path):
        from deeplearning4j_tpu.nlp.serde import read_word2vec_binary

        dim = 3
        payload = b"2 3\n"
        payload += b"cat " + struct.pack("<3f", 1.0, 2.0, 3.0)
        payload += b"dog " + struct.pack("<3f", 4.0, 5.0, 6.0)
        ok = tmp_path / "ok.bin"
        ok.write_bytes(payload)
        words, mat = read_word2vec_binary(str(ok))
        assert words == ["cat", "dog"]
        np.testing.assert_allclose(mat[1], [4.0, 5.0, 6.0])

        bad = tmp_path / "bad.bin"
        bad.write_bytes(payload[:-1])  # exactly one byte short
        with pytest.raises(ValueError, match="truncated at word 1"):
            read_word2vec_binary(str(bad))


class TestDropoutModes:
    def _train_acts(self, layer, x):
        net = make_net(layer, input_type=nn.InputType.feed_forward(x.shape[-1])
                       if x.ndim == 2 else nn.InputType.recurrent(x.shape[-1]))
        return np.asarray(net.feed_forward(x, train=True)[0])

    def test_spatial_drops_whole_feature_maps(self):
        # recurrent input (N, T, C): a dropped channel must be zero at
        # EVERY timestep (KerasSpatialDropout / conf/dropout/SpatialDropout.java)
        x = np.ones((8, 16, 32), np.float32)
        out = self._train_acts(nn.DropoutLayer(rate=0.5, mode="spatial"), x)
        per_channel = out.sum(axis=1)  # (N, C)
        zero_channels = per_channel == 0
        assert zero_channels.sum() > 0
        for n, c in zip(*np.nonzero(zero_channels)):
            assert (out[n, :, c] == 0).all()
        # surviving channels are scaled by 1/keep
        assert np.allclose(out[~np.isclose(out, 0)], 2.0)

    def test_alpha_dropout_preserves_mean_var(self):
        x = np.random.RandomState(0).randn(512, 256).astype(np.float32)
        out = self._train_acts(nn.DropoutLayer(rate=0.1, mode="alpha"), x)
        assert abs(out.mean() - x.mean()) < 0.05
        assert abs(out.std() - x.std()) < 0.1
        assert not np.allclose(out, x)  # it did something

    def test_gaussian_dropout_multiplicative(self):
        x = np.full((256, 128), 3.0, np.float32)
        out = self._train_acts(nn.DropoutLayer(rate=0.25, mode="gaussian"), x)
        assert abs(out.mean() - 3.0) < 0.1
        assert out.std() > 0.5  # noise applied
        # identity at inference
        net = make_net(nn.DropoutLayer(rate=0.25, mode="gaussian"),
                       input_type=nn.InputType.feed_forward(128))
        np.testing.assert_allclose(net.output(x), x)

    def test_keras_mappers_set_modes(self):
        from deeplearning4j_tpu.imports.keras_import import KerasLayerMapper

        for cls, mode in [("SpatialDropout1D", "spatial"),
                          ("SpatialDropout2D", "spatial"),
                          ("SpatialDropout3D", "spatial"),
                          ("AlphaDropout", "alpha"),
                          ("GaussianDropout", "gaussian")]:
            lc, _ = KerasLayerMapper.MAPPERS[cls]({"rate": 0.3}, {})
            assert lc.mode == mode, cls
            assert lc.rate == pytest.approx(0.3)
