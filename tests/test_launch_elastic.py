"""Launcher CLI + kill-and-resume recovery test — SURVEY §6.3's translation
("kill a host process in multi-process CPU tests, recover via
checkpoint-restart") and §8.2-M5's multi-process launcher.

The worker (examples/distributed_fit.py) runs a REAL ParallelWrapper.fit
over a 2-process jax.distributed cluster with periodic checkpoints; the
fault run injects a hard rank-0 death mid-fit, the launcher kills the
survivor and relaunches, and the resumed run must land on EXACTLY the same
final parameters as an uninterrupted run (deterministic data + no dropout
make equality exact, not just within tolerance)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "examples", "distributed_fit.py")


def run_launcher(tmp_path, tag, crash_at=0, restarts=0, nprocs=2, steps=12):
    out = tmp_path / f"{tag}_out.json"
    ckdir = tmp_path / f"{tag}_ck"
    argv = [sys.executable, "-m", "deeplearning4j_tpu.parallel.launch",
            "--nprocs", str(nprocs), "--restarts", str(restarts),
            "--timeout", "240", "--",
            WORKER, "--steps", str(steps), "--checkpoint-dir", str(ckdir),
            "--checkpoint-every", "4", "--out", str(out)]
    if crash_at:
        argv += ["--crash-at", str(crash_at),
                 "--crash-marker", str(tmp_path / f"{tag}_marker")]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(argv, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=500)
    return proc, out


class TestLauncherElastic:
    def test_clean_multiprocess_fit(self, tmp_path):
        proc, out = run_launcher(tmp_path, "clean")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        res = json.loads(out.read_text())
        assert res["final_iteration"] == 12
        assert res["first_step"] == 0
        assert len(res["losses"]) == 12
        # training made progress
        assert res["losses"][-1] < res["losses"][0]

    def test_kill_worker_and_resume_matches_uninterrupted(self, tmp_path):
        ref_proc, ref_out = run_launcher(tmp_path, "ref")
        assert ref_proc.returncode == 0, ref_proc.stdout + ref_proc.stderr
        ref = json.loads(ref_out.read_text())

        # crash rank 0 at step 10 (after the step-8 checkpoint); one restart
        proc, out = run_launcher(tmp_path, "fault", crash_at=10, restarts=1)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "injected crash" in proc.stdout
        assert "relaunching" in proc.stdout
        res = json.loads(out.read_text())
        # the final attempt resumed from the step-8 checkpoint, not step 0
        assert res["first_step"] == 8
        assert res["final_iteration"] == 12
        # resumed loss curve matches the uninterrupted run's tail
        for a, b in zip(res["losses"], ref["losses"][8:]):
            assert abs(a - b) < 1e-6, (res["losses"], ref["losses"])
        # and the final parameters are IDENTICAL
        assert res["param_sha256"] == ref["param_sha256"]

    def test_launcher_reports_failure_when_no_restarts(self, tmp_path):
        proc, _ = run_launcher(tmp_path, "nofix", crash_at=6, restarts=0)
        assert proc.returncode == 1


class TestCheckpointRngStream:
    def test_rng_key_round_trips(self, tmp_path):
        """Exact resume includes the training RNG stream — a restored net
        must continue the dropout-mask sequence, not replay it from step 0."""
        import jax
        import numpy as np

        from deeplearning4j_tpu import nn
        from deeplearning4j_tpu.parallel.checkpoint import TrainingCheckpointer

        def build():
            return nn.MultiLayerNetwork(
                nn.builder().seed(3).list()
                .layer(nn.DenseLayer(n_out=4, activation="tanh", dropout=0.5))
                .layer(nn.OutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(nn.InputType.feed_forward(3)).build()).init()

        net = build()
        x = np.random.RandomState(0).randn(8, 3).astype(np.float32)
        y = np.eye(2)[np.random.RandomState(1).randint(0, 2, 8)]
        for _ in range(5):
            net.fit(x, y)  # advances net._key
        ck = TrainingCheckpointer(str(tmp_path), use_orbax=False)
        ck.save(5, net)

        fresh = build()
        before = np.asarray(jax.random.key_data(fresh._key))
        assert ck.restore(fresh) == 5
        after = np.asarray(jax.random.key_data(fresh._key))
        want = np.asarray(jax.random.key_data(net._key))
        assert not np.array_equal(after, before)  # actually restored
        np.testing.assert_array_equal(after, want)
