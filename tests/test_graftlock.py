"""graftlock: per-rule fixture tests (positive + negative per rule),
justified-suppression mechanics, shrink-only baseline behavior over the
new tier, the repo-wide static lock-order graph, the runtime shadow-lock
cross-validation, and regression tests for the real findings the tier
convicted (frontend deferred completions, cluster death counters, the
checkpoint writer restart).

The whole-repo gate run lives in test_graftlint.py (GL011-GL014 ride the
same registry, so ``test_repo_has_no_new_findings`` already covers the
new tier); this file owns everything graftlock-specific.
"""

import os
import tempfile
import threading
import types

import numpy as np
import pytest

from deeplearning4j_tpu.lint import lint_source, write_baseline, Finding
from deeplearning4j_tpu.lint.rules_concurrency import (
    LockGraph, static_lock_order,
)
from deeplearning4j_tpu.testing.locktrace import (
    LockTracer, ShadowLock, instrument_condition, instrument_lock,
)

import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(src, rules=None):
    return lint_source(textwrap.dedent(src), path="fixture.py", rules=rules)


def _rules_hit(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# GL011 — lock-order inversion
# ---------------------------------------------------------------------------


class TestGL011LockOrder:
    def test_true_positive_nested_with(self):
        fs = _lint("""
            import threading

            class A:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._b:
                        with self._a:
                            pass
        """, rules={"GL011"})
        assert _rules_hit(fs) == {"GL011"}
        # the finding names both acquisition paths
        assert "one" in fs[0].message and "two" in fs[0].message

    def test_true_positive_call_graph_propagated(self):
        fs = _lint("""
            import threading

            class A:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        self.takes_b()

                def takes_b(self):
                    with self._b:
                        pass

                def two(self):
                    with self._b:
                        self.takes_a()

                def takes_a(self):
                    with self._a:
                        pass
        """, rules={"GL011"})
        assert _rules_hit(fs) == {"GL011"}

    def test_true_negative_consistent_order(self):
        fs = _lint("""
            import threading

            class A:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._a:
                        self.takes_b()

                def takes_b(self):
                    with self._b:
                        pass
        """, rules={"GL011"})
        assert fs == []


# ---------------------------------------------------------------------------
# GL012 — inconsistently-guarded shared state
# ---------------------------------------------------------------------------

_GUARDED_BASE = """
    import threading

    class W:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0

        def bump(self):
            with self._lock:
                self._count += 1

        def read(self):
            with self._lock:
                return self._count
"""


class TestGL012GuardedState:
    def test_true_positive_unguarded_on_thread_path(self):
        fs = _lint(_GUARDED_BASE + """
        def start(self):
            threading.Thread(target=self._run).start()

        def _run(self):
            self._count = 5
        """, rules={"GL012"})
        assert _rules_hit(fs) == {"GL012"}
        assert "_count" in fs[0].message

    def test_true_positive_public_counter_augassign(self):
        fs = _lint("""
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.deaths = 0

                def _run(self):
                    self.deaths += 1
        """, rules={"GL012"})
        assert _rules_hit(fs) == {"GL012"}
        assert "deaths" in fs[0].message

    def test_true_negative_consistently_guarded(self):
        fs = _lint(_GUARDED_BASE + """
        def start(self):
            threading.Thread(target=self._run).start()

        def _run(self):
            with self._lock:
                self._count = 5
        """, rules={"GL012"})
        assert fs == []

    def test_true_negative_init_excluded(self):
        # __init__ writes are construction, not a race
        fs = _lint(_GUARDED_BASE, rules={"GL012"})
        assert fs == []

    def test_true_negative_locked_only_helper(self):
        # a helper only ever called with the lock held counts as guarded
        # (the _health_check-from-_routable convention)
        fs = _lint(_GUARDED_BASE + """
        def _run(self):
            with self._lock:
                self._peek_locked()

        def _peek_locked(self):
            return self._count
        """, rules={"GL012"})
        assert fs == []

    def test_property_access_counts_as_guarded_site_inference(self):
        # property bodies participate in the guarded/unguarded tally:
        # an unguarded read inside a property of a class whose attr is
        # mostly guarded is visible to the inference once the property
        # is on an entry-reachable path
        fs = _lint(_GUARDED_BASE + """
        def start(self):
            threading.Thread(target=self._run).start()

        def _run(self):
            return self.snapshot

        @property
        def snapshot(self):
            return self._count
        """, rules={"GL012"})
        # the property read is unguarded and the class's guarded methods
        # are the majority — whether the property itself is flagged
        # depends on attribute-access (not call) reachability, which the
        # analyzer does not track; it must at minimum not crash and not
        # flag the GUARDED accesses
        assert all("bump" not in f.message and "read" not in f.message
                   for f in fs)


# ---------------------------------------------------------------------------
# GL013 — blocking call while holding a lock
# ---------------------------------------------------------------------------


class TestGL013BlockingUnderLock:
    def test_true_positive_sleep(self):
        fs = _lint("""
            import threading
            import time

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def poke(self):
                    with self._lock:
                        time.sleep(0.1)
        """, rules={"GL013"})
        assert _rules_hit(fs) == {"GL013"}

    def test_true_positive_queue_get_no_timeout(self):
        fs = _lint("""
            import queue
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = queue.Queue()

                def drain(self):
                    with self._lock:
                        return self._q.get()
        """, rules={"GL013"})
        assert _rules_hit(fs) == {"GL013"}

    def test_true_negative_queue_get_with_timeout(self):
        fs = _lint("""
            import queue
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = queue.Queue()

                def drain(self):
                    with self._lock:
                        return self._q.get(timeout=1.0)
        """, rules={"GL013"})
        assert fs == []

    def test_true_negative_condition_wait_is_the_cv_pattern(self):
        # Condition.wait on the HELD lock releases it — that IS the
        # pattern, not a deadlock
        fs = _lint("""
            import threading

            class W:
                def __init__(self):
                    self._cv = threading.Condition()
                    self._ready = False

                def wait_ready(self):
                    with self._cv:
                        while not self._ready:
                            self._cv.wait()
        """, rules={"GL013"})
        assert fs == []

    def test_true_negative_closure_body_not_under_lock(self):
        fs = _lint("""
            import threading
            import time

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cbs = []

                def defer(self):
                    with self._lock:
                        self._cbs.append(lambda: time.sleep(1.0))
        """, rules={"GL013"})
        assert fs == []


# ---------------------------------------------------------------------------
# GL014 — external callback under a held lock
# ---------------------------------------------------------------------------


class TestGL014CallbackUnderLock:
    def test_true_positive_set_result(self):
        fs = _lint("""
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def finish(self, fut):
                    with self._lock:
                        fut.set_result(1)
        """, rules={"GL014"})
        assert _rules_hit(fs) == {"GL014"}

    def test_true_positive_listener(self):
        fs = _lint("""
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.on_done = None

                def finish(self, x):
                    with self._lock:
                        self.on_done(x)
        """, rules={"GL014"})
        assert _rules_hit(fs) == {"GL014"}

    def test_true_negative_completion_after_release(self):
        fs = _lint("""
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def finish(self, fut):
                    with self._lock:
                        self._n += 1
                    fut.set_result(self._n)
        """, rules={"GL014"})
        assert fs == []

    def test_true_negative_deferred_lambda(self):
        # the frontend fix pattern: build the completion under the lock,
        # run it after release
        fs = _lint("""
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def finish(self, fut):
                    deferred = []
                    with self._lock:
                        deferred.append(lambda: fut.set_result(1))
                    for fn in deferred:
                        fn()
        """, rules={"GL014"})
        assert fs == []


# ---------------------------------------------------------------------------
# inline justification + baseline mechanics
# ---------------------------------------------------------------------------

_SLEEPER = """
    import threading
    import time

    class W:
        def __init__(self):
            self._lock = threading.Lock()

        def poke(self):
            with self._lock:
                time.sleep(0.1){trailer}
"""


class TestJustified:
    def test_same_line_with_reason_suppresses(self):
        fs = _lint(_SLEEPER.format(
            trailer="  # graftlock: justified(GL013): bounded 100ms pause"),
            rules={"GL013"})
        assert fs == []

    def test_reason_is_mandatory(self):
        fs = _lint(_SLEEPER.format(
            trailer="  # graftlock: justified(GL013):"),
            rules={"GL013"})
        assert _rules_hit(fs) == {"GL013"}

    def test_wrong_rule_id_does_not_suppress(self):
        fs = _lint(_SLEEPER.format(
            trailer="  # graftlock: justified(GL014): wrong rule"),
            rules={"GL013"})
        assert _rules_hit(fs) == {"GL013"}

    def test_comment_above_suppresses(self):
        fs = _lint("""
            import threading
            import time

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def poke(self):
                    with self._lock:
                        # graftlock: justified(GL013): bounded 100ms pause
                        time.sleep(0.1)
        """, rules={"GL013"})
        assert fs == []


class TestBaselineShrinkOnly:
    def test_graftlock_findings_ride_the_shrink_only_contract(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        old = Finding("GL013", "a.py", 3, "error", "sleep under W._lock")
        new = Finding("GL012", "b.py", 9, "error", "unguarded W._count")
        assert write_baseline(path, [old]) == {}       # fresh file: all in
        refused = write_baseline(path, [old, new])     # growth refused
        assert refused == {new.key: 1}
        assert write_baseline(path, [old, new], allow_growth=True) == {}


# ---------------------------------------------------------------------------
# the repo-wide static lock-order graph
# ---------------------------------------------------------------------------


class TestStaticLockOrder:
    def test_repo_graph_is_acyclic(self):
        g = static_lock_order(REPO)
        assert g.cycle() is None, (
            f"lock-order cycle in the repo: {g.cycle()} — a potential "
            f"deadlock; fix the acquisition order, do not baseline")

    def test_known_hierarchy_edges_present(self):
        # the canonical hierarchy (docs/ROBUSTNESS.md § Lock discipline):
        # frontend above scheduler; checkpoint io lock above the stack
        g = static_lock_order(REPO)
        assert ("SLOFrontend._lock", "SlotScheduler._plock") in g.edges
        assert "TrainingCheckpointer._io_lock" in g.nodes
        assert "_AsyncWriter._cv" in g.nodes

    def test_closure_contains_composed_edges(self):
        g = LockGraph()
        g.add("A.x", "B.y", "s1")
        g.add("B.y", "C.z", "s2")
        assert ("A.x", "C.z") in g.closure()
        assert g.cycle() is None


# ---------------------------------------------------------------------------
# runtime shadow-lock tracer
# ---------------------------------------------------------------------------


class TestLockTracer:
    def test_shadow_records_nesting_order(self):
        tr = LockTracer()
        a = ShadowLock(threading.Lock(), "A.x", tr)
        b = ShadowLock(threading.Lock(), "B.y", tr)
        with a:
            with b:
                pass
        assert tr.edges() == {("A.x", "B.y")}

    def test_reentrant_acquire_is_not_an_edge(self):
        tr = LockTracer()
        a = ShadowLock(threading.RLock(), "A.x", tr)
        with a:
            with a:
                pass
        assert tr.edges() == set()

    def test_check_flags_edge_outside_static_closure(self):
        tr = LockTracer()
        a = ShadowLock(threading.Lock(), "A.x", tr)
        b = ShadowLock(threading.Lock(), "B.y", tr)
        with b:
            with a:  # observed B->A; static only knows A->B
                pass
        static = LockGraph()
        static.add("A.x", "B.y", "s")
        report = tr.check(static)
        assert not report["ok"]
        assert report["unknown_edges"][0]["edge"] == ["B.y", "A.x"]
        # and the union would deadlock
        assert report["combined_cycle"] is not None

    def test_check_accepts_composed_edge_via_closure(self):
        tr = LockTracer()
        a = ShadowLock(threading.Lock(), "A.x", tr)
        c = ShadowLock(threading.Lock(), "C.z", tr)
        with a:
            with c:  # observed A->C; static has A->B->C
                pass
        static = LockGraph()
        static.add("A.x", "B.y", "s1")
        static.add("B.y", "C.z", "s2")
        report = tr.check(static)
        assert report["ok"]

    def test_instrumented_condition_traces_through_wait(self):
        tr = LockTracer()
        holder = types.SimpleNamespace(cv=threading.Condition())
        outer = ShadowLock(threading.Lock(), "Outer.lock", tr)
        instrument_condition(holder, "cv", "Inner.cv", tr)
        ready = []

        def worker():
            with holder.cv:
                while not ready:
                    holder.cv.wait(timeout=1.0)

        t = threading.Thread(target=worker)
        t.start()
        with outer:
            with holder.cv:
                ready.append(1)
                holder.cv.notify_all()
        t.join(timeout=5)
        assert not t.is_alive()
        assert ("Outer.lock", "Inner.cv") in tr.edges()


@pytest.mark.slow
class TestLockTraceConsistency:
    """The runtime leg of the acceptance criterion: observed acquisition
    order over a real threaded workload ⊆ the static graph's closure.
    (The gate's locktrace stage runs the fuller tools/locktrace.py
    harness; this is the in-suite sanity slice over the cluster.)"""

    def test_cluster_workload_is_consistent_with_static_graph(self):
        from deeplearning4j_tpu.models.gpt import GptConfig, GptModel
        from deeplearning4j_tpu.serving import ClusterRouter, GenerativeEngine

        cfg = GptConfig.tiny()
        model = GptModel(cfg, seed=1)
        tracer = LockTracer()
        engines = [GenerativeEngine(model, max_slots=2, page_size=8,
                                    max_pages_per_seq=6, max_prompt=16,
                                    seed=3, restart_backoff_s=0.0)
                   for _ in range(2)]
        for e in engines:
            instrument_lock(e, "_lifecycle",
                            "GenerativeEngine._lifecycle", tracer)
            instrument_lock(e.scheduler, "_plock",
                            "SlotScheduler._plock", tracer)
        router = ClusterRouter(engines)
        instrument_lock(router, "_lock", "ClusterRouter._lock", tracer)
        router.start()
        prompts = [np.array([3, 5, 7], np.int32),
                   np.array([11, 2], np.int32)]
        futs = [router.submit(p, max_new_tokens=3, eos_token=-1)
                for p in prompts]
        for f in futs:
            f.result(timeout=300)
        router.stop()
        report = tracer.check(repo_root=REPO)
        assert report["ok"], report


# ---------------------------------------------------------------------------
# regression tests for the convicted findings
# ---------------------------------------------------------------------------


class _StubEngine:
    """Device-free engine surface for frontend tests (mirrors
    tests/test_frontend.py)."""

    def __init__(self, max_slots: int = 2):
        from deeplearning4j_tpu.serving.scheduler import SlotScheduler
        self.scheduler = SlotScheduler(max_slots)
        self.restarts = 0
        self.cfg = types.SimpleNamespace(eos_token=-1, vocab_size=64)
        self.default_deadline_s = None

    def validate_request(self, req):
        pass

    def submit_request(self, req):
        return self.scheduler.submit(req)


class TestFrontendDeferredCompletion:
    """GL014 regression: _deny/_shed_victim used to complete caller
    futures INSIDE the frontend lock, running done-callbacks (foreign
    code) in the critical section — a callback that synchronized with
    another thread needing the lock deadlocked the frontend."""

    PROMPT = np.array([3, 5, 7], np.int32)

    def test_displacement_callback_runs_with_lock_released(self):
        from deeplearning4j_tpu.serving import SLOFrontend

        fe = SLOFrontend(_StubEngine(), max_queue_total=2)
        victim = fe.submit(self.PROMPT, slo_class="batch")
        fe.submit(self.PROMPT, slo_class="standard")

        lock_free: list = []

        def cb(fut):
            # coordinate with a thread that needs fe._lock; under the
            # old code (completion under the lock) this times out
            done = threading.Event()
            t = threading.Thread(
                target=lambda: (fe.snapshot(), done.set()))
            t.start()
            lock_free.append(done.wait(timeout=5.0))
            t.join(timeout=5.0)

        victim.add_done_callback(cb)
        # the interactive arrival displaces the batch victim, firing cb
        fe.submit(self.PROMPT, slo_class="interactive")
        assert victim.done()
        assert victim.result(timeout=0).finish_reason == "shed"
        assert lock_free == [True]

    def test_denied_future_still_terminal_and_counted(self):
        from deeplearning4j_tpu.serving import ClassPolicy, SLOFrontend

        classes = {"batch": ClassPolicy("batch", priority=2,
                                        max_queued=1)}
        fe = SLOFrontend(_StubEngine(), classes=classes)
        fe.submit(self.PROMPT, slo_class="batch")
        fut = fe.submit(self.PROMPT, slo_class="batch")
        res = fut.result(timeout=1.0)  # deferred completion still lands
        assert res.finish_reason == "shed"
        assert res.slo_class == "batch"


class TestClusterDeathCounters:
    """GL012 regression: deaths/migrations were read-modify-written
    OUTSIDE the router lock on dying worker threads — two engines dying
    concurrently could lose an increment."""

    def test_concurrent_deaths_count_exactly(self):
        from deeplearning4j_tpu.models.gpt import GptConfig, GptModel
        from deeplearning4j_tpu.serving import ClusterRouter, GenerativeEngine

        cfg = GptConfig.tiny()
        model = GptModel(cfg, seed=1)
        engines = [GenerativeEngine(model, max_slots=2, page_size=8,
                                    max_pages_per_seq=6, max_prompt=16,
                                    seed=3, restart_backoff_s=0.0)
                   for _ in range(2)]
        router = ClusterRouter(engines)
        barrier = threading.Barrier(2)

        def die(e):
            barrier.wait(timeout=10)
            router._on_engine_death(e, RuntimeError("boom"))

        threads = [threading.Thread(target=die, args=(e,))
                   for e in engines]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert router.deaths == 2
        assert router.migrations == 0  # nothing was queued
        assert {e.engine_id for e in engines} <= router._dead

    def test_old_pattern_is_a_finding(self):
        # the exact shape that was fixed: counter bumped after the
        # de-dup critical section, on the dying worker's thread path
        fs = _lint("""
            import threading

            class Router:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._dead = set()
                    self.deaths = 0

                def attach(self, eng):
                    eng.on_death = lambda exc: self._on_death(eng, exc)

                def _on_death(self, eng, exc):
                    with self._lock:
                        if eng in self._dead:
                            return
                        self._dead.add(eng)
                    self.deaths += 1
        """, rules={"GL012"})
        assert _rules_hit(fs) == {"GL012"}


class TestCheckpointWriterLocking:
    """GL012 regression: _ensure_thread wrote _stop outside _cv (racy
    against a concurrent stop()); the fixed version must still restart
    transparently after close()."""

    @staticmethod
    def _fake_net(value: float):
        net = types.SimpleNamespace()
        net.params = {"W": np.full((4, 4), value, np.float32)}
        net.opt_state = {"W": np.zeros((4, 4), np.float32)}
        net.net_state = {}
        net.iteration_count = int(value)
        net.epoch_count = 0
        return net

    def test_writer_restarts_after_close(self):
        from deeplearning4j_tpu.parallel.checkpoint import (
            TrainingCheckpointer)

        with tempfile.TemporaryDirectory() as d:
            ck = TrainingCheckpointer(d, keep_last=2, use_orbax=False)
            ck.save_async(0, self._fake_net(0.0))
            ck.close()
            # a post-close submit must restart the writer (the _stop
            # reset now happens under _cv) and drain cleanly
            ck.save_async(1, self._fake_net(1.0))
            assert ck.wait_until_finished(timeout=60)
            assert ck.drain_failures() == []
            ck.close()
