"""The OpValidation ratchet (SURVEY §5.2): every registered declarable op
must have at least one validation case, and every case must pass.

Mirrors ND4J's OpValidationSuite "coverage is asserted" pattern: the first
test FAILS THE BUILD if an op is registered without a case, so the catalog
cannot grow unvalidated.
"""

import numpy as np
import pytest

import deeplearning4j_tpu  # noqa: F401 — populates the registry
from deeplearning4j_tpu.ops import validation
from deeplearning4j_tpu.ops.registry import registry

# Ops predating the ratchet whose coverage lives in dedicated test files
# (tests/test_ops.py, test_nn_layers.py, test_pallas_attention.py, …).
# Do NOT add new ops here — new registrations must ship validation cases.
_LEGACY_COVERED = {
    "avgpool2d", "batchnorm", "clip_by_norm", "clip_by_value", "conv1d",
    "conv2d", "conv3d", "decode_bitmap", "decode_threshold", "deconv2d",
    "depthwise_conv2d", "dot_product_attention", "dropout", "encode_bitmap", "encode_threshold",
    "embedding_lookup", "gather", "global_avg_pool", "global_max_pool",
    "gru_cell", "im2col", "layer_norm", "log_softmax_op", "lrn", "lstm_cell",
    "matmul", "maxpool2d", "multi_head_dot_product_attention", "one_hot",
    "pnormpool2d", "random_bernoulli", "random_exponential", "random_gamma",
    "random_normal", "random_truncated_normal", "random_uniform", "sconv2d",
    "simple_rnn_cell", "softmax_op", "standardize", "upsampling2d",
    "xw_plus_b",
}


def test_catalog_size():
    """Breadth ratchet: the catalog must not shrink below its high-water
    mark (round-3 target: >=150 named declarable ops vs the reference's
    ~270; round 2 sat at 42)."""
    n = len(registry().names())
    assert n >= 150, f"op catalog regressed: {n} < 150"


def test_every_op_has_validation_case():
    uncovered = [n for n in validation.uncovered_ops()
                 if n not in _LEGACY_COVERED]
    assert not uncovered, (
        f"{len(uncovered)} registered ops lack validation cases: "
        f"{sorted(uncovered)} — add a numpy-oracle case via "
        "ops.validation.add_case when registering an op")


_ALL_CASES = [(name, i, fn)
              for name, fns in sorted(validation.cases().items())
              for i, fn in enumerate(fns)]


@pytest.mark.parametrize("name,i,fn", _ALL_CASES,
                         ids=[f"{n}[{i}]" for n, i, _ in _ALL_CASES])
def test_validation_case(name, i, fn):
    fn()


def test_shape_function_agrees_with_execution():
    """calculate_output_shape (DeclarableOp shape-fn analog) must match the
    executed shape for a sample of multi-shape ops."""
    import jax
    import jax.numpy as jnp

    reg = registry()
    samples = [
        ("reduce_sum", (jnp.ones((3, 4, 5)),), {"axis": 1}),
        ("top_k", (jnp.ones((2, 9)),), {"k": 3}),
        ("space_to_depth", (jnp.ones((1, 4, 4, 2)),), {"block_size": 2}),
        ("cholesky", (jnp.eye(4),), {}),
    ]
    for name, args, kwargs in samples:
        want = reg.exec(name, *args, **kwargs)
        got = reg.calculate_output_shape(name, *args, **kwargs)
        flat_w = jax.tree.leaves(want)
        flat_g = jax.tree.leaves(got)
        assert [w.shape for w in flat_w] == [g.shape for g in flat_g], name


def test_op_catalog_doc_up_to_date():
    """docs/OP_CATALOG.md must track the live registry (the codegen-role
    artifact; tools/gen_op_catalog.py regenerates it)."""
    import os
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "tools/gen_op_catalog.py", "--check"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
