"""graftshape — the static jit-signature & recompile-discipline tier
(docs/LINT.md § graftshape).

Per-rule fixtures for GS001-GS005 (positives AND the linkage negatives
the dataflow model exists for: direct registration, registrar helpers,
wrapper objects, producer methods, IfExp selection), the justified-
marker contract, the shrink-only baseline ride-along, the repo-wide
zero-unbaselined acceptance assertion, the CompileEvent.callsite
plumbing, and a slow live slice of the shapetrace cross-validation
(the gate's ``shapetrace`` stage runs the fuller tools/shapetrace.py
harness)."""

import os
import textwrap

import pytest

from deeplearning4j_tpu.lint import Finding, lint_paths, lint_source, \
    write_baseline
from deeplearning4j_tpu.lint.rules_shape import (
    GS_RULES, static_shape_inventory)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(src, rules=GS_RULES):
    return lint_source(textwrap.dedent(src), path="fixture.py",
                       rules=rules)


def _rules_hit(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# GS001 — unledgered jit
# ---------------------------------------------------------------------------


class TestGS001Unledgered:
    def test_true_positive_bare_assignment(self):
        fs = _lint("""
            import jax

            def build(f):
                step = jax.jit(f)
                return step
        """, rules={"GS001"})
        assert _rules_hit(fs) == {"GS001"}

    def test_true_positive_decorator(self):
        fs = _lint("""
            import jax

            @jax.jit
            def step(x):
                return x * 2
        """, rules={"GS001"})
        assert _rules_hit(fs) == {"GS001"}

    def test_true_positive_inline_call(self):
        fs = _lint("""
            import jax

            def run(f, x):
                return jax.jit(f)(x)
        """, rules={"GS001"})
        assert _rules_hit(fs) == {"GS001"}

    def test_true_negative_direct_registration(self):
        fs = _lint("""
            import jax
            from deeplearning4j_tpu import observe

            def build(f, x):
                step = jax.jit(f)
                observe.note_jit_signature(
                    step, graph="g", key="k",
                    signature=observe.signature_of(x=x))
                return step
        """, rules={"GS001"})
        assert fs == []

    def test_true_negative_registrar_helper(self):
        # samediff pattern: the jit flows through a parameter into a
        # helper that does the note — the dataflow must follow it
        fs = _lint("""
            import jax
            from deeplearning4j_tpu import observe

            class G:
                def _note(self, fn, x):
                    observe.note_jit_signature(
                        fn, graph="g", key="k",
                        signature=observe.signature_of(x=x))

                def build(self, f, x):
                    step = jax.jit(f)
                    self._note(step, x)
                    return step
        """, rules={"GS001"})
        assert fs == []

    def test_true_negative_producer_method(self):
        # engine pattern: self._fn built by a producer method, noted at
        # the dispatch site
        fs = _lint("""
            import jax
            from deeplearning4j_tpu import observe

            class E:
                def _build(self):
                    return jax.jit(lambda p, x: x)

                def step(self, x):
                    if self._fn is None:
                        self._fn = self._build()
                    observe.note_jit_signature(
                        self._fn, graph="g", key="k",
                        signature=observe.signature_of(x=x))
                    return self._fn(None, x)
        """, rules={"GS001"})
        assert fs == []

    def test_true_negative_ifexp_selection(self):
        # multilayer pattern: the registered fn is chosen between two
        # producers with a conditional expression
        fs = _lint("""
            import jax
            from deeplearning4j_tpu import observe

            class M:
                def _a(self):
                    return jax.jit(lambda x: x)

                def _b(self):
                    return jax.jit(lambda x: -x)

                def fit(self, tbptt, x):
                    step = (self._a() if tbptt else self._b())
                    observe.note_jit_signature(
                        step, graph="g", key="k",
                        signature=observe.signature_of(x=x))
                    return step(x)
        """, rules={"GS001"})
        assert fs == []

    def test_true_negative_wrapper_object(self):
        # CompiledGraph pattern: the jit is swallowed by a wrapper whose
        # constructor call is itself registered
        fs = _lint("""
            import jax
            from deeplearning4j_tpu import observe

            class Wrapped:
                def __init__(self, fn):
                    self.fn = fn

            def build(run, x):
                g = Wrapped(jax.jit(run))
                observe.note_jit_signature(
                    g, graph="g", key="k",
                    signature=observe.signature_of(x=x))
                return g
        """, rules={"GS001"})
        assert fs == []

    def test_true_negative_export_sink(self):
        # autodiff/export.py pattern: the jit flows into jax.export —
        # the restore side (restore_callable) registers every restored
        # executable on the ledger, so the export site is ledgered
        fs = _lint("""
            import jax
            from jax import export as jexport

            def export_it(f, specs):
                jitted = jax.jit(f)
                return jexport.export(jitted)(*specs)
        """, rules={"GS001"})
        assert fs == []

    def test_true_negative_export_sink_dotted(self):
        fs = _lint("""
            import jax

            def export_it(f, specs):
                jitted = jax.jit(f)
                return jax.export.export(jitted)(*specs)
        """, rules={"GS001"})
        assert fs == []

    def test_true_positive_foreign_export_is_not_a_sink(self):
        # only jax.export/jexport spellings are the AOT sink — another
        # module's .export() swallowing a jit must still be flagged
        fs = _lint("""
            import jax
            import mymod

            def export_it(f, specs):
                jitted = jax.jit(f)
                return mymod.export(jitted)(*specs)
        """, rules={"GS001"})
        assert _rules_hit(fs) == {"GS001"}

    def test_tools_and_examples_are_out_of_scope(self):
        src = """
            import jax

            def bench(f, x):
                return jax.jit(f)(x)
        """
        assert lint_source(textwrap.dedent(src), path="tools/bench_x.py",
                           rules={"GS001"}) == []
        assert lint_source(textwrap.dedent(src), path="examples/demo.py",
                           rules={"GS001"}) == []
        assert lint_source(textwrap.dedent(src),
                           path="deeplearning4j_tpu/x.py",
                           rules={"GS001"}) != []


# ---------------------------------------------------------------------------
# GS002 — request-shaped jit signature
# ---------------------------------------------------------------------------


class TestGS002RequestShaped:
    def test_true_positive_len_sized_buffer(self):
        fs = _lint("""
            import jax
            import numpy as np

            class S:
                def __init__(self, run):
                    self._fn = jax.jit(run)

                def admit(self, prompt):
                    n = len(prompt)
                    ids = np.zeros((1, n), np.int32)
                    return self._fn(ids)
        """, rules={"GS002"})
        assert _rules_hit(fs) == {"GS002"}

    def test_true_positive_shape_sliced_arg(self):
        fs = _lint("""
            import jax

            class S:
                def __init__(self, run):
                    self._fn = jax.jit(run)

                def admit(self, prompt, table):
                    n = prompt.shape[0]
                    return self._fn(table[:n])
        """, rules={"GS002"})
        assert _rules_hit(fs) == {"GS002"}

    def test_true_negative_bucketed(self):
        # routing the raw length through a bucketing helper launders the
        # taint — that is the fix the rule demands
        fs = _lint("""
            import jax
            import numpy as np

            def pad_bucket(n):
                return 1 << max(4, n.bit_length())

            class S:
                def __init__(self, run):
                    self._fn = jax.jit(run)

                def admit(self, prompt):
                    n = pad_bucket(len(prompt))
                    ids = np.zeros((1, n), np.int32)
                    return self._fn(ids)
        """, rules={"GS002"})
        assert fs == []

    def test_true_negative_fixed_shape(self):
        fs = _lint("""
            import jax
            import numpy as np

            class S:
                def __init__(self, run):
                    self._fn = jax.jit(run)

                def admit(self, prompt):
                    ids = np.zeros((1, 128), np.int32)
                    return self._fn(ids)
        """, rules={"GS002"})
        assert fs == []


# ---------------------------------------------------------------------------
# GS003 — traced-value leak
# ---------------------------------------------------------------------------


class TestGS003TracedLeak:
    def test_true_positive_branch_on_traced(self):
        fs = _lint("""
            import jax

            @jax.jit
            def step(x):
                if x > 0:
                    return x
                return -x
        """, rules={"GS003"})
        assert _rules_hit(fs) == {"GS003"}

    def test_true_positive_python_cast(self):
        fs = _lint("""
            import jax

            @jax.jit
            def step(x):
                return float(x)
        """, rules={"GS003"})
        assert _rules_hit(fs) == {"GS003"}

    def test_true_negative_static_argname(self):
        fs = _lint("""
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("mode",))
            def step(x, mode):
                if mode == "fast":
                    return x
                return -x
        """, rules={"GS003"})
        assert fs == []

    def test_true_negative_shape_access_is_static(self):
        # .shape/.ndim/.dtype of a tracer are trace-time constants
        fs = _lint("""
            import jax

            @jax.jit
            def step(x):
                if x.ndim == 2:
                    return x.reshape(x.shape[0], -1)
                return x
        """, rules={"GS003"})
        assert fs == []

    def test_true_negative_none_guard(self):
        fs = _lint("""
            import jax

            @jax.jit
            def step(x, mask=None):
                if mask is None:
                    return x
                return x * mask
        """, rules={"GS003"})
        assert fs == []


# ---------------------------------------------------------------------------
# GS004 — weak-type churn
# ---------------------------------------------------------------------------


class TestGS004WeakType:
    def test_true_positive_scalar_and_array(self):
        fs = _lint("""
            import jax
            import jax.numpy as jnp

            def run(f, x):
                step = jax.jit(f)
                step(x, 0.5)
                step(x, jnp.asarray(0.5, jnp.float32))
        """, rules={"GS004"})
        assert _rules_hit(fs) == {"GS004"}

    def test_true_negative_consistent_arrays(self):
        fs = _lint("""
            import jax
            import jax.numpy as jnp

            def run(f, x):
                step = jax.jit(f)
                step(x, jnp.asarray(0.5, jnp.float32))
                step(x, jnp.asarray(0.9, jnp.float32))
        """, rules={"GS004"})
        assert fs == []


# ---------------------------------------------------------------------------
# GS005 — static-arg hazard
# ---------------------------------------------------------------------------


class TestGS005StaticArgHazard:
    def test_true_positive_mutated_attr_as_static(self):
        fs = _lint("""
            import jax

            class T:
                def __init__(self):
                    self.k = 4

                def tune(self, k):
                    self.k = k

                def build(self, x):
                    fn = jax.jit(self._step, static_argnames=("k",))
                    return fn(x, k=self.k)
        """, rules={"GS005"})
        assert _rules_hit(fs) == {"GS005"}

    def test_true_negative_init_only_config(self):
        fs = _lint("""
            import jax

            class T:
                def __init__(self, k):
                    self.k = k

                def build(self, x):
                    fn = jax.jit(self._step, static_argnames=("k",))
                    return fn(x, k=self.k)
        """, rules={"GS005"})
        assert fs == []


# ---------------------------------------------------------------------------
# justified-marker contract
# ---------------------------------------------------------------------------


_JITTER = """
    import jax

    def build(f):
        step = jax.jit(f){trailer}
        return step
"""


class TestJustified:
    def test_same_line_with_reason_suppresses(self):
        fs = _lint(_JITTER.format(
            trailer="  # graftshape: justified(GS001): bench-local throwaway"),
            rules={"GS001"})
        assert fs == []

    def test_line_above_suppresses(self):
        fs = _lint("""
            import jax

            def build(f):
                # graftshape: justified(GS001): bench-local throwaway
                step = jax.jit(f)
                return step
        """, rules={"GS001"})
        assert fs == []

    def test_reason_is_mandatory(self):
        fs = _lint(_JITTER.format(
            trailer="  # graftshape: justified(GS001):"),
            rules={"GS001"})
        assert _rules_hit(fs) == {"GS001"}

    def test_wrong_rule_id_does_not_suppress(self):
        fs = _lint(_JITTER.format(
            trailer="  # graftshape: justified(GS003): wrong rule"),
            rules={"GS001"})
        assert _rules_hit(fs) == {"GS001"}


# ---------------------------------------------------------------------------
# baseline ride-along + repo-wide acceptance
# ---------------------------------------------------------------------------


class TestBaselineAndRepo:
    def test_gs_findings_ride_the_shrink_only_contract(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        old = Finding("GS001", "a.py", 3, "error", "unledgered jit 'step'")
        new = Finding("GS002", "b.py", 9, "error", "request-shaped arg")
        assert write_baseline(path, [old]) == {}
        refused = write_baseline(path, [old, new])
        assert refused == {new.key: 1}
        assert write_baseline(path, [old, new], allow_growth=True) == {}

    def test_repo_is_clean_of_unbaselined_gs_findings(self):
        # the PR's acceptance criterion: every repo jit is ledgered,
        # justified, or analyzer-visible-clean — nothing grandfathered
        fs = lint_paths(("deeplearning4j_tpu", "tools", "examples"),
                        REPO, rules=GS_RULES)
        assert fs == [], "\n".join(f.render() for f in fs)


# ---------------------------------------------------------------------------
# the static jit-boundary inventory (shapetrace's static half)
# ---------------------------------------------------------------------------


class TestShapeInventory:
    def test_inventory_covers_the_serving_engine(self):
        inv = static_shape_inventory(REPO)
        assert len(inv.jit_sites) > 20
        eng = "deeplearning4j_tpu/serving/engine.py"
        assert eng in inv.registration_spans
        # every engine jit site is ledgered or justified — the repo-wide
        # GS001 cleanliness, restated through the inventory
        for site in inv.jit_sites:
            assert site["ledgered"] or site["justified"], site

    def test_attributes_callsite_uses_line_ranges(self):
        inv = static_shape_inventory(REPO)
        eng = "deeplearning4j_tpu/serving/engine.py"
        lo, hi = inv.registration_spans[eng][0]
        assert inv.attributes_callsite(f"{eng}:{lo}")
        assert inv.attributes_callsite(f"{eng}:{hi}")
        assert not inv.attributes_callsite(f"{eng}:999999")
        assert not inv.attributes_callsite("nonexistent.py:1")
        assert not inv.attributes_callsite("garbage")

    def test_justified_hazards_stay_in_the_hazard_map(self):
        # word2vec's ragged-tail GS002 is justified in source — runtime
        # may legally observe a new_shape there, so the inventory must
        # keep it as a (tagged) hazard, not erase it
        inv = static_shape_inventory(REPO)
        w2v = "deeplearning4j_tpu/nlp/word2vec.py"
        assert inv.hazard_module(w2v)
        assert any(h["rule"] == "GS002" and h["justified"]
                   for h in inv.hazards[w2v])


# ---------------------------------------------------------------------------
# CompileEvent.callsite plumbing
# ---------------------------------------------------------------------------


class TestLedgerCallsite:
    def test_note_jit_signature_attributes_this_file(self):
        from deeplearning4j_tpu import observe

        def fn(x):
            return x

        before = len(observe.ledger().events())
        observe.note_jit_signature(fn, graph="t", key="cs_unit",
                                   signature="f32[1]")
        ev = observe.ledger().events()[before]
        assert ev.callsite is not None
        assert ev.callsite.split(":")[0].endswith(
            "tests/test_graftshape.py")
        assert int(ev.callsite.rpartition(":")[2]) > 0
        assert ev.to_dict()["callsite"] == ev.callsite

    def test_explicit_callsite_wins_over_stack_walk(self):
        from deeplearning4j_tpu import observe

        def fn(x):
            return x

        before = len(observe.ledger().events())
        observe.note_jit_signature(fn, graph="t", key="cs_explicit",
                                   signature="f32[2]",
                                   callsite="somewhere/else.py:7")
        ev = observe.ledger().events()[before]
        assert ev.callsite == "somewhere/else.py:7"

    def test_cache_hit_records_nothing(self):
        from deeplearning4j_tpu import observe

        def fn(x):
            return x

        observe.note_jit_signature(fn, graph="t", key="cs_hit",
                                   signature="f32[3]")
        before = len(observe.ledger().events())
        assert observe.note_jit_signature(
            fn, graph="t", key="cs_hit", signature="f32[3]") is None
        assert len(observe.ledger().events()) == before

    def test_summary_carries_by_callsite(self):
        from deeplearning4j_tpu import observe

        def fn(x):
            return x

        observe.note_jit_signature(fn, graph="t", key="cs_sum",
                                   signature="f32[4]",
                                   callsite="x/y.py:12")
        by_cs = observe.ledger().summary()["by_callsite"]
        assert by_cs.get("x/y.py:12", 0) >= 1


# ---------------------------------------------------------------------------
# live shapetrace slice (the gate runs the fuller tools/shapetrace.py)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestShapeTraceConsistency:
    """The runtime leg of the acceptance criterion: every recompile
    event recorded under a live shape-diverse serving workload
    attributes to a statically known registration span, and no
    new_shape escapes the static hazard map."""

    def test_randomized_replay_is_consistent_with_inventory(self):
        from deeplearning4j_tpu.serving.replay import run_randomized_replay
        from deeplearning4j_tpu.testing.shapetrace import ShapeTracer

        tracer = ShapeTracer()
        out = run_randomized_replay(n_requests=8)
        assert out["all_terminal"]
        assert out["new_shape_events"] == 0
        report = tracer.check(REPO)
        assert report["events"] > 0
        assert report["ok"], report
