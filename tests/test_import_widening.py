"""Golden tests for the round-3 dialect widening: TF 86→106 ops (incl.
multi-output slot addressing), ONNX 60→86 ops."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from tests.test_tf_import import freeze
from tests.test_onnx_import import build_model, node_proto
from deeplearning4j_tpu.imports import (TensorflowImporter, import_onnx)


def _run_tf(fn, specs, feeds_np, rtol=1e-5, atol=1e-6):
    gd, ins, outs = freeze(fn, *specs)
    golden = [np.asarray(t) for t in
              (fn(*[tf.constant(v) for v in feeds_np]),)]
    sd = TensorflowImporter().run_import(gd)
    got = sd.output(dict(zip(ins, feeds_np)), outs[0])[outs[0]]
    np.testing.assert_allclose(got, golden[0], rtol=rtol, atol=atol)


class TestTfWidening:
    def test_split_multi_output_slots(self):
        r = np.random.RandomState(0)
        x = r.randn(2, 6).astype(np.float32)

        def model(t):
            a, b, c = tf.split(t, 3, axis=1)
            return a + 2.0 * b - c  # consumes slots :0 :1 :2

        _run_tf(model, [tf.TensorSpec([None, 6], tf.float32)], [x])

    def test_topk_values_and_indices(self):
        r = np.random.RandomState(1)
        x = r.randn(3, 8).astype(np.float32)

        def model(t):
            vals, idx = tf.math.top_k(t, k=3)
            return vals + tf.cast(idx, tf.float32)

        _run_tf(model, [tf.TensorSpec([None, 8], tf.float32)], [x])

    def test_trig_and_floor_ops(self):
        r = np.random.RandomState(2)
        x = (r.rand(4, 5).astype(np.float32) - 0.5)

        def model(t):
            return (tf.atan(t) + tf.asin(t) + tf.acos(t) + tf.sinh(t)
                    + tf.cosh(t) + tf.atan2(t, t + 2.0))

        _run_tf(model, [tf.TensorSpec([None, 5], tf.float32)], [x],
                rtol=1e-4, atol=1e-5)

    def test_floordiv_mod(self):
        x = np.asarray([[7.0, -7.0, 5.0]], np.float32)

        def model(t):
            return tf.math.floordiv(t, 2.0) + tf.math.floormod(t, 3.0)

        _run_tf(model, [tf.TensorSpec([None, 3], tf.float32)], [x])

    def test_slice_fill_range_broadcast(self):
        x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)

        def model(t):
            s = tf.slice(t, [0, 1, 0], [2, 2, 4])
            f = tf.fill([2, 2, 4], 0.5)
            rng = tf.range(4.0)
            return s + f + tf.broadcast_to(rng, [2, 2, 4])

        _run_tf(model, [tf.TensorSpec([2, 3, 4], tf.float32)], [x])

    def test_one_hot(self):
        ids = np.asarray([[0, 2, 1]], np.int32)

        def model(t):
            return tf.one_hot(t, 4)

        _run_tf(model, [tf.TensorSpec([None, 3], tf.int32)], [ids])

    def test_space_depth_round_trip(self):
        x = np.random.RandomState(3).rand(1, 4, 4, 2).astype(np.float32)

        def model(t):
            return tf.nn.depth_to_space(tf.nn.space_to_depth(t, 2), 2)

        _run_tf(model, [tf.TensorSpec([1, 4, 4, 2], tf.float32)], [x])

    def test_resize_bilinear(self):
        x = np.random.RandomState(4).rand(1, 4, 4, 3).astype(np.float32)

        def model(t):
            # TF2 resize (half-pixel centers — the convention our
            # resize_bilinear op implements)
            return tf.image.resize(t, [8, 8], method="bilinear")

        _run_tf(model, [tf.TensorSpec([1, 4, 4, 3], tf.float32)], [x],
                rtol=1e-4, atol=1e-4)


class TestOnnxWidening:
    def _run(self, nodes, inputs, outputs, inits, feeds, out_name):
        model = build_model(nodes, inputs, outputs, inits)
        sd = import_onnx(bytes(model))
        return sd.output(feeds, out_name)[out_name]

    def test_split_multi_output(self):
        r = np.random.RandomState(0)
        x = r.randn(2, 6).astype(np.float32)
        nodes = [node_proto("Split", ["x"], ["a", "b", "c"], axis=1),
                 node_proto("Sub", ["a", "c"], ["y"])]
        got = self._run(nodes, [("x", (2, 6))], [("y", (2, 2))], {},
                        {"x": x}, "y")
        np.testing.assert_allclose(got, x[:, 0:2] - x[:, 4:6], rtol=1e-6)

    def test_topk(self):
        x = np.asarray([[3.0, 1.0, 4.0, 1.5]], np.float32)
        nodes = [node_proto("TopK", ["x"], ["v", "i"], k=2)]
        got = self._run(nodes, [("x", (1, 4))], [("v", (1, 2))], {},
                        {"x": x}, "v")
        np.testing.assert_allclose(got, [[4.0, 3.0]])

    def test_comparison_where(self):
        a = np.asarray([1.0, 5.0, 3.0], np.float32)
        b = np.asarray([2.0, 2.0, 3.0], np.float32)
        nodes = [node_proto("Greater", ["a", "b"], ["m"]),
                 node_proto("Where", ["m", "a", "b"], ["y"])]
        got = self._run(nodes, [("a", (3,)), ("b", (3,))], [("y", (3,))],
                        {}, {"a": a, "b": b}, "y")
        np.testing.assert_allclose(got, np.maximum(a, b))

    def test_expand_tile(self):
        x = np.asarray([[1.0], [2.0]], np.float32)
        nodes = [node_proto("Tile", ["x", "reps"], ["y"])]
        got = self._run(nodes, [("x", (2, 1))], [("y", (2, 3))],
                        {"reps": np.asarray([1, 3], np.int64)},
                        {"x": x}, "y")
        np.testing.assert_allclose(got, np.tile(x, (1, 3)))

    def test_slice_with_axes(self):
        x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        nodes = [node_proto("Slice", ["x", "st", "en", "ax"], ["y"])]
        got = self._run(nodes, [("x", (2, 3, 4))], [("y", (2, 2, 4))],
                        {"st": np.asarray([1], np.int64),
                         "en": np.asarray([3], np.int64),
                         "ax": np.asarray([1], np.int64)},
                        {"x": x}, "y")
        np.testing.assert_allclose(got, x[:, 1:3, :])

    def test_argmax_keepdims(self):
        x = np.asarray([[1.0, 9.0, 2.0], [5.0, 0.0, 3.0]], np.float32)
        nodes = [node_proto("ArgMax", ["x"], ["y"], axis=1, keepdims=1)]
        got = self._run(nodes, [("x", (2, 3))], [("y", (2, 1))], {},
                        {"x": x}, "y")
        np.testing.assert_array_equal(np.asarray(got).reshape(-1), [1, 0])

    def test_instance_normalization(self):
        r = np.random.RandomState(5)
        x = r.randn(2, 3, 4, 4).astype(np.float32)
        scale = r.rand(3).astype(np.float32) + 0.5
        bias = r.randn(3).astype(np.float32)
        nodes = [node_proto("InstanceNormalization",
                            ["x", "scale", "bias"], ["y"], epsilon=1e-5)]
        got = self._run(nodes, [("x", (2, 3, 4, 4))], [("y", (2, 3, 4, 4))],
                        {"scale": scale, "bias": bias}, {"x": x}, "y")
        mean = x.mean(axis=(2, 3), keepdims=True)
        var = x.var(axis=(2, 3), keepdims=True)
        want = ((x - mean) / np.sqrt(var + 1e-5)
                * scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_space_to_depth_nchw(self):
        x = np.random.RandomState(6).rand(1, 2, 4, 4).astype(np.float32)
        nodes = [node_proto("SpaceToDepth", ["x"], ["y"], blocksize=2)]
        got = self._run(nodes, [("x", (1, 2, 4, 4))], [("y", (1, 8, 2, 2))],
                        {}, {"x": x}, "y")
        assert got.shape == (1, 8, 2, 2)


class TestReviewFixes:
    """Regressions for the widening-review findings."""

    def test_conv_transpose_channels(self):
        # C_in != C_out exercises the kernel-layout fix
        r = np.random.RandomState(0)
        w = r.randn(3, 5, 2, 2).astype(np.float32)  # (C_in, C_out, kH, kW)
        x = r.randn(1, 3, 4, 4).astype(np.float32)
        nodes = [node_proto("ConvTranspose", ["x", "w"], ["y"],
                            strides=[2, 2])]
        model = build_model(nodes, [("x", (1, 3, 4, 4))],
                            [("y", (1, 5, 8, 8))], {"w": w})
        from deeplearning4j_tpu.imports import import_onnx
        sd = import_onnx(bytes(model))
        got = sd.output({"x": x}, "y")["y"]
        assert got.shape == (1, 5, 8, 8)
        # oracle: scatter each input pixel through the kernel
        want = np.zeros((1, 5, 8, 8), np.float32)
        for i in range(4):
            for j in range(4):
                for ci in range(3):
                    want[0, :, 2*i:2*i+2, 2*j:2*j+2] += (
                        x[0, ci, i, j] * w[ci])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_onehot_on_off_values(self):
        ids = np.asarray([[0, 2]], np.int32)

        def model(t):
            return tf.one_hot(t, 3, on_value=0.9, off_value=0.05)

        _run_tf(model, [tf.TensorSpec([None, 2], tf.int32)], [ids])

    def test_fill_feeds_reshape(self):
        """Fill/Range outputs const-fold through Concat into Reshape's
        shape operand (the shape-chain class the review flagged)."""
        x = np.arange(6, dtype=np.float32).reshape(1, 6)

        def model(t):
            shape = tf.fill([1], 6)
            return tf.reshape(t, tf.concat([tf.fill([1], 1), shape], 0)) * 2.0

        _run_tf(model, [tf.TensorSpec([1, 6], tf.float32)], [x])

    def test_onnx_topk_largest0_raises(self):
        x = np.zeros((1, 4), np.float32)
        nodes = [node_proto("TopK", ["x"], ["v", "i"], k=2, largest=0)]
        model = build_model(nodes, [("x", (1, 4))], [("v", (1, 2))], {})
        from deeplearning4j_tpu.imports import import_onnx
        with pytest.raises(NotImplementedError, match="largest"):
            import_onnx(bytes(model))

    def test_onnx_expand_bidirectional(self):
        x = np.random.RandomState(1).randn(2, 3).astype(np.float32)
        nodes = [node_proto("Expand", ["x", "shape"], ["y"])]
        model = build_model(nodes, [("x", (2, 3))], [("y", (2, 3))],
                            {"shape": np.asarray([2, 1], np.int64)})
        from deeplearning4j_tpu.imports import import_onnx
        sd = import_onnx(bytes(model))
        got = sd.output({"x": x}, "y")["y"]
        np.testing.assert_allclose(got, x)  # dim 1 keeps the input dim

    def test_unresolved_slot_raises_clearly(self):
        from deeplearning4j_tpu.imports.ir import IRGraph, IRImporter, IRNode

        def one_out(sd, ins, attrs, node):
            return sd.constant(node.name + "_c", np.zeros(2, np.float32))

        def binop(sd, ins, attrs, node):
            return sd._record("add", ins)

        ir = IRGraph(
            nodes=[IRNode("p", "Producer", [], ["p"], {}),
                   IRNode("c", "Add", ["p", "p:1"], ["c"], {})],
            initializers={}, inputs=[], outputs=["c"], name="test")
        imp = IRImporter({"Producer": one_out, "Add": binop})
        with pytest.raises(ValueError, match="unresolved input"):
            imp.run_import(ir)


class TestStridedSliceMasks:
    """begin/end/shrink masks — what python slicing compiles to."""

    def test_python_slicing_patterns(self):
        x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        for fn in [lambda t: t[:, :2] * 1.0,
                   lambda t: t[0] + 0.0,
                   lambda t: t[:, -1] * 2.0,
                   lambda t: t[1:, :2, 1:3] + 1.0,
                   lambda t: t[:, ::-1] * 1.0]:
            _run_tf(fn, [tf.TensorSpec([2, 3, 4], tf.float32)], [x])

    def test_scalar_select_then_dense(self):
        r = np.random.RandomState(0)
        w = tf.Variable(r.randn(4, 3).astype(np.float32))

        def model(t):
            first = t[0]          # shrink axis 0: (B,4) -> (4,)
            return tf.linalg.matvec(w, first, transpose_a=True)

        x = r.randn(2, 4).astype(np.float32)
        _run_tf(model, [tf.TensorSpec([2, 4], tf.float32)], [x])

    def test_ellipsis_new_axis_now_import(self):
        """Round 4 made ellipsis/new_axis masks real (t[..., None]) — the
        old raise is gone; verify golden parity instead."""
        def model(t):
            return t[..., None] * 1.0

        gd, ins, outs = freeze(model, tf.TensorSpec([2, 3], tf.float32))
        x = np.random.RandomState(0).rand(2, 3).astype(np.float32)
        sd = TensorflowImporter().run_import(gd)
        got = sd.output({ins[0]: x}, outs[0])[outs[0]]
        np.testing.assert_allclose(got, x[..., None], rtol=1e-6)
