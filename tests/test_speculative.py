"""Speculative decoding tests (docs/SERVING.md § Speculative decoding).

The one property everything else hangs off: speculation is LOSSLESS —
greedy engine output with a draft model (ANY draft model, however wrong)
is token-for-token identical to ``reference_generate``'s full-attention
oracle and to the spec-off engine. Covered across:

  * accept-all (draft == target) and reject-at-every-position (a
    zeroed draft proposing a constant token the target never emits);
  * mid-flight admits/evicts with more requests than slots, mixed with
    sampling (temperature > 0) slots that must fall back to the plain
    decode path;
  * page-boundary rollbacks on SHARED (prefix-cache-mapped) pages — a
    rejection rewind must never corrupt a page the radix tree still
    serves;
  * supervisor restarts mid-speculation (``decode_step_error`` inside
    the verify step): retries re-prefill and stay lossless, the draft KV
    drops with the restart, zero ``new_shape`` ledger events;
  * the compile-once contract: exactly one ``first_compile`` for each of
    draft_prefill / draft_decode / verify, zero ``new_shape`` across
    admits/evicts/rejections/restarts;
  * per-committed-token inter-token accounting (a 4-token step reads as
    4 gaps of step/4, keeping spec-on percentiles comparable);
  * the frontend's ``ClassPolicy.disable_spec`` degraded-mode knob and
    the zoo's draft/target config pairing.
"""

import numpy as np
import pytest

import jax

from deeplearning4j_tpu import faults, models, observe
from deeplearning4j_tpu.models.gpt import (
    GptConfig, GptModel, draft_config_for, reference_generate,
)
from deeplearning4j_tpu.serving import (
    ClassPolicy, GenerativeEngine, SLOFrontend, default_classes,
    perturbed_draft,
)

CFG = GptConfig.tiny()
MODEL = GptModel(CFG, seed=1)
#: all-zero params: LN(0) = 0 through every block, logits = 0, argmax =
#: token 0 — a draft that CONSTANTLY proposes token 0, for deterministic
#: reject-at-every-position runs (prompts/targets below avoid token 0)
ZDRAFT = GptModel(CFG, params=jax.tree.map(lambda a: a * 0.0, MODEL.params))

PROMPTS = [np.array([3, 5, 7, 9], np.int32),
           np.array([11, 2], np.int32),
           np.array([42, 43, 44, 45, 46, 47], np.int32),
           np.array([8, 8, 8], np.int32),
           np.array([17, 23, 31], np.int32)]


def make_engine(**kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_pages_per_seq", 6)
    kw.setdefault("max_prompt", 16)
    kw.setdefault("seed", 3)
    return GenerativeEngine(MODEL, **kw)


def oracle(prompt, n):
    return reference_generate(MODEL.params, CFG, prompt, n).tolist()


def serving_new_shape():
    return sum(1 for e in observe.ledger().events()
               if e.graph == "serving" and e.cause == "new_shape")


# ---------------------------------------------------------------------------
# draft half — the dense-cache propose path
# ---------------------------------------------------------------------------


class TestDraftDecoder:
    def test_propose_matches_draft_oracle(self):
        """The dense-cache draft loop IS greedy decoding of the draft
        model: proposals after a prefilled prompt must equal the draft's
        own full-attention greedy continuation."""
        from deeplearning4j_tpu.serving.speculative import SpeculativeDecoder

        spec = SpeculativeDecoder(MODEL, k=4, max_slots=2, max_ctx=48,
                                  max_prompt=16)
        prompt = PROMPTS[0]
        spec.prefill(0, prompt)
        want = reference_generate(MODEL.params, CFG, prompt, 5)
        # feed the draft's own first greedy token, as the engine feeds
        # the target's (identical here: same model)
        pend = np.zeros((2,), np.int32)
        pend[0] = want[0]
        props = spec.propose(pend, np.array([1, 0], np.int32))
        assert props[0].tolist() == want[1:].tolist()
        # the inactive slot's row was never touched
        assert spec.lens[1] == 0

    def test_commit_rewind_and_reset(self):
        from deeplearning4j_tpu.serving.speculative import SpeculativeDecoder

        spec = SpeculativeDecoder(MODEL, k=2, max_slots=1, max_ctx=32,
                                  max_prompt=16)
        spec.prefill(0, PROMPTS[0])
        assert spec.lens[0] == 4
        spec.commit(0, 3)
        assert spec.lens[0] == 7
        spec.free(0)
        assert spec.lens[0] == 0
        spec.prefill(0, PROMPTS[1])
        spec.reset()
        assert spec.lens[0] == 0

    def test_validation(self):
        from deeplearning4j_tpu.serving.speculative import SpeculativeDecoder

        with pytest.raises(ValueError, match="spec_k"):
            SpeculativeDecoder(MODEL, k=0, max_slots=1, max_ctx=32,
                               max_prompt=16)
        small = GptModel(GptConfig.tiny(max_position=8), seed=0)
        with pytest.raises(ValueError, match="max_position"):
            SpeculativeDecoder(small, k=2, max_slots=1, max_ctx=32,
                               max_prompt=16)

    def test_engine_requires_matching_draft(self):
        with pytest.raises(ValueError, match="draft_model"):
            make_engine(spec_k=2)
        bad = GptModel(GptConfig.tiny(vocab_size=128), seed=0)
        with pytest.raises(ValueError, match="vocab"):
            make_engine(spec_k=2, draft_model=bad)


# ---------------------------------------------------------------------------
# losslessness — the whole point
# ---------------------------------------------------------------------------


class TestLossless:
    def test_accept_all_matches_oracle(self):
        """draft == target: every proposal accepted, outputs still exact
        (the bonus-token arithmetic and budget truncation must not leak
        an extra or missing token)."""
        eng = make_engine(spec_k=4, draft_model=MODEL)
        res = eng.generate(PROMPTS, max_new_tokens=12, eos_token=-1)
        for r, p in zip(res, PROMPTS):
            assert r.tokens.tolist() == oracle(p, 12)
            assert r.spec_proposed_tokens > 0
            assert r.spec_accepted_tokens > 0
        eng.check_invariants()

    def test_reject_at_every_position_matches_oracle(self):
        """The zeroed draft proposes token 0 forever; target trajectories
        here never emit 0, so EVERY verify rejects at position 0 and
        commits exactly one correction token — the degenerate case that
        must equal plain decoding step-for-step."""
        for p in PROMPTS:
            assert 0 not in oracle(p, 10)  # precondition for determinism
        eng = make_engine(spec_k=3, draft_model=ZDRAFT)
        res = eng.generate(PROMPTS, max_new_tokens=10, eos_token=-1)
        for r, p in zip(res, PROMPTS):
            assert r.tokens.tolist() == oracle(p, 10)
            assert r.spec_accepted_tokens == 0
            assert r.spec_proposed_tokens > 0
        eng.check_invariants()

    def test_partial_acceptance_matches_oracle(self):
        """A perturbed draft agrees often but not always — accepts,
        rejections, and corrections all interleave and the stream stays
        exact (the replay/gate measurement model)."""
        draft = perturbed_draft(MODEL, scale=2e-3, seed=5)
        eng = make_engine(spec_k=4, draft_model=draft)
        res = eng.generate(PROMPTS, max_new_tokens=14, eos_token=-1)
        for r, p in zip(res, PROMPTS):
            assert r.tokens.tolist() == oracle(p, 14)
        eng.check_invariants()

    def test_eos_inside_committed_window(self):
        """An eos landing mid-window must cut the commit exactly there —
        same tokens and finish reason as the spec-off engine."""
        p = PROMPTS[0]
        eos_tok = oracle(p, 8)[3]
        for draft in (MODEL, ZDRAFT):
            on = make_engine(spec_k=4, draft_model=draft).generate(
                [p], max_new_tokens=8, eos_token=eos_tok)[0]
            off = make_engine().generate(
                [p], max_new_tokens=8, eos_token=eos_tok)[0]
            assert on.finish_reason == off.finish_reason == "eos"
            assert on.tokens.tolist() == off.tokens.tolist()

    def test_max_new_tokens_budget_never_overshoots(self):
        """Multi-token commits must truncate at the budget, including
        budgets smaller than the verify window."""
        p = PROMPTS[2]
        for budget in (1, 2, 5):
            r = make_engine(spec_k=4, draft_model=MODEL).generate(
                [p], max_new_tokens=budget, eos_token=-1)[0]
            assert r.tokens.tolist() == oracle(p, budget)
            assert r.finish_reason == "length"


# ---------------------------------------------------------------------------
# scheduler integration — admits/evicts, sampling fallback, accounting
# ---------------------------------------------------------------------------


class TestSchedulerIntegration:
    def test_midflight_admits_and_evicts(self):
        """More requests than slots: retire/admit churn between verify
        windows, every greedy output exact, zero new_shape."""
        before = serving_new_shape()
        eng = make_engine(spec_k=3, draft_model=perturbed_draft(
            MODEL, scale=2e-3, seed=9), max_slots=2)
        lens = [5, 11, 3, 8, 14]
        futs = []
        eng.start()
        try:
            for p, n in zip(PROMPTS, lens):
                futs.append(eng.submit(p, max_new_tokens=n, eos_token=-1))
            res = [f.result(timeout=120) for f in futs]
        finally:
            eng.stop()
        for r, p, n in zip(res, PROMPTS, lens):
            assert r.finish_reason == "length"
            assert r.tokens.tolist() == oracle(p, n)
        assert serving_new_shape() == before
        eng.check_invariants()

    def test_sampling_slots_fall_back_to_plain_decode(self):
        """temperature > 0 slots never speculate — they ride the plain
        decode dispatch next to speculating greedy neighbours."""
        eng = make_engine(spec_k=3, draft_model=MODEL)
        eng.start()
        try:
            f_greedy = eng.submit(PROMPTS[0], max_new_tokens=8,
                                  eos_token=-1)
            f_sample = eng.submit(PROMPTS[1], max_new_tokens=8,
                                  temperature=0.9, top_k=12, eos_token=-1)
            rg, rs = f_greedy.result(120), f_sample.result(120)
        finally:
            eng.stop()
        assert rg.tokens.tolist() == oracle(PROMPTS[0], 8)
        assert rg.spec_proposed_tokens > 0
        assert rs.spec_proposed_tokens == 0 and len(rs.tokens) == 8
        eng.check_invariants()

    def test_near_context_limit_degrades_to_plain(self):
        """A sequence whose verify window no longer fits its page-table
        row finishes NON-speculatively instead of overflowing — and the
        tokens stay exact across the switchover."""
        # context = 2 pages * 8 = 16; prompt 6 + 10 tokens hits the edge
        eng = make_engine(spec_k=4, draft_model=MODEL, max_pages_per_seq=2,
                          max_prompt=8)
        p = PROMPTS[2]
        r = eng.generate([p], max_new_tokens=9, eos_token=-1)[0]
        assert r.tokens.tolist() == oracle(p, 9)
        assert r.finish_reason == "length"
        eng.check_invariants()

    def test_intertoken_accounting_per_committed_token(self):
        """Multi-token steps record one inter-token gap per COMMITTED
        token (step/m), so a T-token result always carries T-1 gaps and
        the histograms stay comparable to spec-off."""
        m = observe.metrics()
        itl = m.histogram("dl4j_tpu_serving_intertoken_seconds")
        dec = m.histogram("dl4j_tpu_serving_decode_step_seconds")
        itl_before, dec_before = itl.count, dec.count
        eng = make_engine(spec_k=4, draft_model=MODEL)
        res = eng.generate([PROMPTS[0]], max_new_tokens=12, eos_token=-1)[0]
        assert len(res.tokens) == 12
        assert len(res.intertoken_s) == 11
        # 11 decode-committed tokens -> 11 observations in BOTH
        # histograms (the first token is prefill, not decode)
        assert itl.count - itl_before == 11
        assert dec.count - dec_before == 11
        # accept-all with k=4: steps commit up to 5 tokens, so the
        # per-token gaps inside one step are equal by construction
        assert res.spec_accepted_tokens > 0

    def test_spec_off_by_default(self):
        eng = make_engine()
        assert eng.spec is None
        r = eng.generate([PROMPTS[0]], max_new_tokens=6, eos_token=-1)[0]
        assert r.spec_proposed_tokens == 0
        assert r.tokens.tolist() == oracle(PROMPTS[0], 6)


# ---------------------------------------------------------------------------
# rollback vs the radix prefix cache — shared pages stay sound
# ---------------------------------------------------------------------------


class TestRollbackOnSharedPages:
    def test_page_boundary_rollback_on_shared_pages(self):
        """Prefix-hit admissions map SHARED pages into the slot; the
        verify writes (and rollback rewinds) past the prompt must never
        touch them. The donor prompt must keep serving exact hits after
        a neighbour's rejection-heavy speculative run."""
        before = serving_new_shape()
        eng = make_engine(spec_k=3, draft_model=ZDRAFT, prefix_pages=8,
                          suffix_bucket=8)
        sysp = np.array([42, 43, 44, 45, 46, 47, 48, 49, 50, 51, 52],
                        np.int32)  # 11 tokens: one full page + mid-page tail
        hits = []
        for tail in ([7], [9], [7], [11]):
            p = np.concatenate([sysp, np.array(tail, np.int32)])
            r = eng.generate([p], max_new_tokens=12, eos_token=-1)[0]
            assert r.tokens.tolist() == oracle(p, 12)
            assert r.spec_accepted_tokens == 0  # every position rejected
            hits.append(r.prefix_hit_tokens)
        assert hits[0] == 0 and all(h > 0 for h in hits[1:])
        assert serving_new_shape() == before
        eng.check_invariants()  # exact refcounts + draft/target lengths

    def test_concurrent_shared_prefix_spec_slots(self):
        """Two slots speculating over the SAME mapped prefix pages at
        once: rollbacks in both must not corrupt each other or the
        tree."""
        eng = make_engine(spec_k=3,
                          draft_model=perturbed_draft(MODEL, scale=2e-3,
                                                      seed=3),
                          prefix_pages=8, suffix_bucket=8)
        sysp = np.array([42, 43, 44, 45, 46, 47, 48, 49], np.int32)
        warm = np.concatenate([sysp, np.array([3], np.int32)])
        eng.generate([warm], max_new_tokens=2, eos_token=-1)
        p1 = np.concatenate([sysp, np.array([7], np.int32)])
        p2 = np.concatenate([sysp, np.array([9, 5], np.int32)])
        eng.start()
        try:
            f1 = eng.submit(p1, max_new_tokens=10, eos_token=-1)
            f2 = eng.submit(p2, max_new_tokens=10, eos_token=-1)
            r1, r2 = f1.result(timeout=120), f2.result(timeout=120)
        finally:
            eng.stop()
        assert r1.tokens.tolist() == oracle(p1, 10)
        assert r2.tokens.tolist() == oracle(p2, 10)
        eng.check_invariants()


# ---------------------------------------------------------------------------
# supervision — crashes inside the verify step
# ---------------------------------------------------------------------------


class TestSupervisedSpeculation:
    def test_restart_mid_speculation_stays_lossless(self):
        """A decode_step_error fired inside the speculative step kills
        the worker mid-verify; the supervisor drops the draft KV, retries
        from the prompt, and the final stream is still oracle-exact with
        zero new_shape."""
        before = serving_new_shape()
        eng = make_engine(spec_k=3, draft_model=MODEL,
                          max_restarts=4, restart_backoff_s=0.01)
        eng.generate([PROMPTS[1]], max_new_tokens=2, eos_token=-1)  # warm
        faults.arm("decode_step_error", prob=1.0, after_n=1, max_fires=1)
        try:
            eng.start()
            fut = eng.submit(PROMPTS[0], max_new_tokens=10, eos_token=-1,
                             max_retries=2)
            res = fut.result(timeout=120)
        finally:
            eng.stop()
            faults.reset()
        assert eng.restarts == 1
        assert res.finish_reason == "length"
        assert res.tokens.tolist() == oracle(PROMPTS[0], 10)
        assert serving_new_shape() == before
        eng.check_invariants()

    def test_chaos_leg_all_terminal_invariants_hold(self):
        """The chaos contract under probabilistic verify crashes: every
        request terminal, restarts within cap, allocator + draft/target
        invariants intact, zero new_shape."""
        before = serving_new_shape()
        eng = make_engine(spec_k=3,
                          draft_model=perturbed_draft(MODEL, scale=2e-3,
                                                      seed=2),
                          max_restarts=8, restart_backoff_s=0.01)
        eng.generate([PROMPTS[1]], max_new_tokens=2, eos_token=-1)  # warm
        faults.arm("decode_step_error", prob=0.5, seed=4, max_fires=5)
        try:
            eng.start()
            futs = [eng.submit(p, max_new_tokens=8, eos_token=-1,
                               max_retries=6) for p in PROMPTS]
            res = [f.result(timeout=300) for f in futs]
        finally:
            eng.stop()
            faults.reset()
        assert all(f.done() for f in futs)
        for r, p in zip(res, PROMPTS):
            if r.finish_reason in ("eos", "length"):
                assert r.tokens.tolist() == oracle(p, 8)
        assert eng.restarts <= 8
        assert serving_new_shape() == before
        eng.check_invariants()


# ---------------------------------------------------------------------------
# frontend knob, zoo pairing, replay harness
# ---------------------------------------------------------------------------


class TestDisableSpecKnob:
    def _frontend(self, eng, **kw):
        classes = default_classes()
        classes["batch"] = ClassPolicy("batch", priority=2,
                                       disable_spec=True,
                                       reject_in_shedding=False)
        return SLOFrontend(eng, classes=classes, **kw)

    def test_shedding_disables_spec_for_marked_class(self):
        eng = make_engine(spec_k=3, draft_model=MODEL).start()
        try:
            fe = self._frontend(eng)
            # force the ladder into shedding (the frontend tests' idiom)
            fe._signals = lambda: (10 ** 6, None)
            fut = fe.submit(PROMPTS[0], slo_class="batch",
                            max_new_tokens=6, eos_token=-1)
            res = fut.result(timeout=120)
        finally:
            eng.stop()
        assert fe.state == "shedding"
        assert res.spec_disabled
        assert res.spec_proposed_tokens == 0       # decoded plain
        assert res.tokens.tolist() == oracle(PROMPTS[0], 6)

    def test_ok_state_keeps_speculating(self):
        eng = make_engine(spec_k=3, draft_model=MODEL).start()
        try:
            fe = self._frontend(eng)
            fut = fe.submit(PROMPTS[0], slo_class="batch",
                            max_new_tokens=6, eos_token=-1)
            res = fut.result(timeout=120)
        finally:
            eng.stop()
        assert not res.spec_disabled
        assert res.spec_proposed_tokens > 0


class TestZooPairing:
    def test_draft_config_shares_token_space(self):
        cfg = GptConfig.base()
        d = draft_config_for(cfg)
        assert d.vocab_size == cfg.vocab_size
        assert d.eos_token == cfg.eos_token
        assert d.max_position == cfg.max_position
        assert d.hidden < cfg.hidden and d.layers < cfg.layers
        assert draft_config_for(cfg, layers=1).layers == 1

    def test_zoo_init_draft_serves(self):
        zm = models.GPT("tiny", vocab_size=256)
        target = zm.init()
        draft = zm.init_draft(layers=1)
        eng = GenerativeEngine(target, max_slots=1, page_size=8,
                               max_pages_per_seq=4, max_prompt=8,
                               spec_k=2, draft_model=draft)
        p = np.array([4, 6], np.int32)
        r = eng.generate([p], max_new_tokens=5, eos_token=-1)[0]
        want = reference_generate(target.params, target.cfg, p, 5)
        assert r.tokens.tolist() == want.tolist()


class TestReplayHarness:
    def test_replay_identical_outputs_and_acceptance(self):
        from deeplearning4j_tpu.serving.replay import run_spec_replay

        kw = dict(n_requests=3, gen_tokens=8, spec_k=3, warm_rounds=1,
                  slow_decode=False, seed=0)
        on = run_spec_replay(spec_on=True, **kw)
        off = run_spec_replay(spec_on=False, **kw)
        assert on["outputs"] == off["outputs"]
        assert on["all_terminal"] and off["all_terminal"]
        assert on["accepted_tokens"] > 0
        assert on["new_shape_events"] == off["new_shape_events"] == 0
        assert on["first_compile_keys"] == ["draft_decode", "draft_prefill",
                                            "prefill", "verify",
                                            "write_prompt"]
        assert off["first_compile_keys"] == ["decode", "prefill",
                                             "write_prompt"]


# ---------------------------------------------------------------------------
# compile-once — the ledger contract
# ---------------------------------------------------------------------------


class TestSpecJitStability:
    def test_one_compile_per_fn_zero_new_shape(self):
        led = observe.ledger()
        before = len(led.events())
        eng = make_engine(spec_k=3, draft_model=perturbed_draft(
            MODEL, scale=2e-3, seed=8))
        for n in (3, 9, 5):  # varied budgets, admits, evicts
            eng.generate([p for p in PROMPTS[:3]], max_new_tokens=n,
                         eos_token=-1)
        evs = [e for e in led.events()[before:] if e.graph == "serving"]
        by_key = {}
        for e in evs:
            by_key.setdefault(e.key, []).append(e.cause)
        assert by_key["draft_prefill"] == ["first_compile"]
        assert by_key["draft_decode"] == ["first_compile"]
        assert by_key["verify"] == ["first_compile"]
        assert all(c == "first_compile" for cs in by_key.values()
                   for c in cs), by_key
