"""Round-5 advisor findings, regression-tested.

* ONNX NonMaxSuppression honors center_point_box=1 (torchvision export
  form: boxes as [x_center, y_center, w, h]).
* Keras CuDNNLSTM bias heuristic: a fused (4H,) bias passes through
  unchanged even when 4H is divisible by 8 (even H); only an exact (8H,)
  stack splits.
* nn.MoELayer: a token whose every top-k assignment is dropped at capacity
  passes through as identity, never as zeros.
"""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.nn import conf as C


class TestOnnxNmsCenterPointBox:
    def _nms(self):
        import deeplearning4j_tpu.imports.onnx_import  # registers onnx_nms
        from deeplearning4j_tpu.autodiff.samediff import resolve_graph_op
        return resolve_graph_op("onnx_nms")

    def test_center_format_matches_corner_format(self):
        nms = self._nms()
        # three boxes: two heavily overlapping, one far away
        corners = np.array([[[0., 0., 2., 2.],
                             [0., 0.5, 2., 2.5],
                             [3., 3., 5., 5.]]], np.float32)  # [y1,x1,y2,x2]
        centers = np.array([[[1., 1., 2., 2.],
                             [1.5, 1., 2., 2.],
                             [4., 4., 2., 2.]]], np.float32)  # [xc,yc,w,h]
        scores = np.array([[[0.9, 0.8, 0.7]]], np.float32)
        kw = dict(max_out=3, iou_threshold=0.5, score_threshold=0.0)
        r_corner = np.asarray(nms(jnp.asarray(corners), jnp.asarray(scores),
                                  **kw))
        r_center = np.asarray(nms(jnp.asarray(centers), jnp.asarray(scores),
                                  center_point_box=1, **kw))
        np.testing.assert_array_equal(r_corner, r_center)
        # and the suppression is real: box 1 suppressed, boxes 0/2 kept
        kept = {int(r) for r in r_corner[:, 2] if r >= 0}
        assert kept == {0, 2}

    def test_mapper_rejects_unknown_center_point_box(self):
        from deeplearning4j_tpu.imports.onnx_import import ONNX_OP_MAPPERS

        class _Node:
            name = "nms"
            inputs = ["b", "s", "mo"]

        try:
            ONNX_OP_MAPPERS["NonMaxSuppression"](
                None, ["b", "s"], {"center_point_box": 2}, _Node(),
                const_values={"mo": np.asarray(5)})
        except NotImplementedError as e:
            assert "center_point_box" in str(e)
        else:
            raise AssertionError("center_point_box=2 must be rejected")


class TestCuDNNLSTMBiasHeuristic:
    def _weights(self, i, h, r):
        k = (r.randn(i, 4 * h) * 0.2).astype(np.float32)
        rec = (r.randn(h, 4 * h) * 0.2).astype(np.float32)
        b = (r.randn(4 * h) * 0.1).astype(np.float32)
        return k, rec, b

    def test_fused_bias_even_units_passes_through(self):
        """H=4 -> 4H=16 is divisible by 8: the old size%8 heuristic split
        and summed it into a wrong (2H,) bias."""
        from deeplearning4j_tpu.imports.keras_import import _assemble_sequential
        r = np.random.RandomState(0)
        i, h = 3, 4
        k, rec, b = self._weights(i, h, r)
        cfg = {"units": h, "name": "l", "return_sequences": True}
        net_lstm = _assemble_sequential(
            [("LSTM", dict(cfg, activation="tanh",
                           recurrent_activation="sigmoid"), [k, rec, b])],
            nn.InputType.recurrent(i))
        net_cudnn = _assemble_sequential(
            [("CuDNNLSTM", dict(cfg), [k, rec, b])],
            nn.InputType.recurrent(i))
        x = r.randn(2, 5, i).astype(np.float32)
        np.testing.assert_allclose(net_cudnn.output(x), net_lstm.output(x),
                                   atol=1e-5)

    def test_stacked_8h_bias_still_splits(self):
        from deeplearning4j_tpu.imports.keras_import import _assemble_sequential
        r = np.random.RandomState(1)
        i, h = 3, 4
        k, rec, b = self._weights(i, h, r)
        b_cudnn = np.concatenate([b * 0.25, b * 0.75])  # (8H,) input+recurrent
        cfg = {"units": h, "name": "l", "return_sequences": True}
        net_lstm = _assemble_sequential(
            [("LSTM", dict(cfg, activation="tanh",
                           recurrent_activation="sigmoid"), [k, rec, b])],
            nn.InputType.recurrent(i))
        net_cudnn = _assemble_sequential(
            [("CuDNNLSTM", dict(cfg), [k, rec, b_cudnn])],
            nn.InputType.recurrent(i))
        x = r.randn(2, 5, i).astype(np.float32)
        np.testing.assert_allclose(net_cudnn.output(x), net_lstm.output(x),
                                   atol=1e-5)


class TestMoEDroppedTokenPassthrough:
    def _moe_layer(self, **kw):
        b = nn.builder().seed(0).list()
        b.layer(C.MoELayer(n_in=8, d_hidden=16, n_experts=2,
                           activation="relu", **kw))
        b.layer(nn.OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        conf = b.set_input_type(nn.InputType.feed_forward(8)).build()
        net = nn.MultiLayerNetwork(conf).init()
        return net.layers[0], net.params[0]

    def test_fully_dropped_tokens_are_identity_not_zero(self):
        layer, params = self._moe_layer(top_k=1, capacity_factor=1e-9)
        x = np.random.RandomState(0).randn(32, 8).astype(np.float32)
        y, state, _ = layer.apply(params, jnp.asarray(x), layer.init_state(),
                                  train=False, rng=jax.random.key(0))
        y = np.asarray(y)
        assert float(state["_dropped_frac"]) > 0.9   # capacity 1/expert
        # dropped tokens: identity; NO all-zero output rows anywhere
        identical = np.isclose(y, x, atol=1e-6).all(axis=1)
        assert identical.sum() >= 30        # all but <=1 token per expert
        assert not (np.abs(y) < 1e-12).all(axis=1).any()

    def test_surviving_tokens_unaffected_by_passthrough(self):
        """With capacity for everyone, nothing is dropped and the expert
        output must NOT have the input added onto it."""
        layer, params = self._moe_layer(top_k=1, capacity_factor=64.0)
        x = np.random.RandomState(1).randn(16, 8).astype(np.float32)
        y, state, _ = layer.apply(params, jnp.asarray(x), layer.init_state(),
                                  train=False, rng=jax.random.key(0))
        assert float(state["_dropped_frac"]) == 0.0
        # relu expert FFN of a random projection almost surely != x
        assert not np.allclose(np.asarray(y), x, atol=1e-4)
