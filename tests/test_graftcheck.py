"""graftcheck (analysis/ — docs/ANALYSIS.md): per-code seeded fixtures,
symbolic-dim soundness, the constant env, importer/validate wiring,
check_network, and the CLI/baseline contract."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from deeplearning4j_tpu.analysis import (
    AVal, Dim, GC_CODES, GraphCheckError, check_network, check_samediff)
from deeplearning4j_tpu.analysis import fixtures
from deeplearning4j_tpu.analysis.broadcast import (
    BroadcastError, broadcast_shapes, promotion_surprise)
from deeplearning4j_tpu.autodiff.samediff import SameDiff, _Node

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# the six GC codes: seeded true positives with provenance
# ---------------------------------------------------------------------------


class TestSeededCodes:
    @pytest.mark.parametrize(
        "code,name,graph",
        fixtures.seeded_error_fixtures(),
        ids=[c for c, _n, _g in fixtures.seeded_error_fixtures()])
    def test_seeded_fixture_flags_its_code(self, code, name, graph):
        report = check_samediff(graph, graph_name=name)
        hit = [f for f in report.findings if f.rule == code]
        assert hit, (f"{code} not flagged on {name}; got "
                     f"{[f.render() for f in report.findings]}")
        # provenance: op + node name in the message, graph name as path,
        # node position as line
        f = hit[0]
        assert f.path == name
        assert "op " in f.message and "node '" in f.message
        assert f.line >= 1
        # severity matches the catalog
        assert f.severity == GC_CODES[code][0]

    def test_error_codes_raise_warnings_do_not(self):
        for code, name, graph in fixtures.seeded_error_fixtures():
            report = check_samediff(graph, graph_name=name)
            if GC_CODES[code][0] == "error":
                with pytest.raises(GraphCheckError):
                    report.raise_on_errors()
            else:
                report.raise_on_errors()  # warnings never raise


class TestCleanFixtures:
    @pytest.mark.parametrize(
        "name,graph", fixtures.clean_fixtures(),
        ids=[n for n, _g in fixtures.clean_fixtures()])
    def test_zero_findings(self, name, graph):
        if isinstance(graph, SameDiff):
            report = check_samediff(graph, graph_name=name)
        else:
            report = check_network(graph, graph_name=name)
        assert report.findings == [], "\n".join(
            f.render() for f in report.findings)


# ---------------------------------------------------------------------------
# symbolic dims + broadcasting soundness
# ---------------------------------------------------------------------------


class TestSymbolicDims:
    def test_named_batch_dim_flows_through(self):
        sd = SameDiff()
        x = sd.placeholder("x", (None, 128))
        w = sd.var("w", np.zeros((128, 64), np.float32))
        y = sd.nn.relu(x @ w)
        report = check_samediff(sd)
        assert report.findings == []
        aval = report.avals[y.name]
        assert aval.shape == (Dim("x.0"), 64)
        assert aval.dtype == np.dtype(np.float32)

    def test_same_symbol_unifies_across_operands(self):
        # (N, 128) + (N, 128) from the SAME placeholder-rooted chain: the
        # named dim survives (not degraded to unknown)
        sd = SameDiff()
        x = sd.placeholder("x", (None, 16))
        y = sd.math.tanh(x) + sd.math.exp(x)
        report = check_samediff(sd)
        assert report.findings == []
        assert report.avals[y.name].shape == (Dim("x.0"), 16)

    def test_symbolic_vs_concrete_never_errors(self):
        # a symbolic dim against concrete 4 is not provably wrong
        sd = SameDiff()
        a = sd.placeholder("a", (None, 8))
        b = sd.var("b", np.zeros((4, 8), np.float32))
        out = sd._record("add", [a, b])
        report = check_samediff(sd)
        assert report.findings == []
        assert report.avals[out.name].shape == (4, 8)

    def test_broadcast_shapes_symbolic(self):
        n = Dim("n")
        assert broadcast_shapes([(n, 128), (128,)]) == (n, 128)
        assert broadcast_shapes([(n, 1), (1, 5)]) == (n, 5)
        with pytest.raises(BroadcastError):
            broadcast_shapes([(2, 3), (4, 5)])

    def test_promotion_surprise_predicate(self):
        f32, i32 = np.dtype(np.float32), np.dtype(np.int32)
        assert promotion_surprise([f32, f32]) is None
        assert promotion_surprise([f32, i32]) is None  # ordinary promotion
        assert promotion_surprise([i32, np.dtype(np.uint32)])  # widens
        import jax.numpy as jnp
        assert promotion_surprise([np.dtype(jnp.bfloat16), f32])  # mixed


# ---------------------------------------------------------------------------
# constant env + eval_shape fallback
# ---------------------------------------------------------------------------


class TestConstEnv:
    def test_shape_chain_stays_concrete(self):
        sd = fixtures.shape_chain()
        report = check_samediff(sd)
        assert report.findings == []
        assert report.avals["y"].shape == (4, 6)  # reshape_dynamic resolved

    def test_bad_dynamic_reshape_flagged(self):
        sd = SameDiff()
        x = sd.var("x", np.ones((6, 4), np.float32))
        tgt = sd.constant("tgt", np.asarray([5, 5], np.int64))
        sd.op("reshape_dynamic", x, tgt)
        report = check_samediff(sd)
        assert [f.rule for f in report.findings] == ["GC005"]

    def test_const_eval_matches_jax_promotion(self):
        # const-eval must run under JAX semantics: np.int32/np.int32
        # promotes to float64 on host but float32 under jax x32 — the
        # divergence made the optimizer's invariance checker raise a
        # phantom dtype change on valid graphs (review regression)
        sd = SameDiff()
        a = sd.constant("a", np.asarray([4, 6], np.int32))
        b = sd.constant("b", np.asarray([2, 3], np.int32))
        out = a / b
        out.rename("out")
        report = check_samediff(sd)
        assert report.avals["out"].dtype == np.dtype(np.float32)
        # end-to-end: fold + invariance checker agree (no PassInvariantError)
        np.testing.assert_allclose(sd.output({}, ["out"])["out"],
                                   [2.0, 2.0])

    def test_eval_shape_fallback_exact_on_concrete(self):
        sd = SameDiff()
        x = sd.placeholder("x", (5, 8))
        vals, idx = sd.op("top_k", x, k=3, n_out=2)
        report = check_samediff(sd)
        assert report.findings == []
        assert report.avals[vals.name].shape == (5, 3)
        assert report.avals[idx.name].dtype == np.dtype(np.int32)

    def test_control_flow_opaque_but_silent(self):
        import jax.numpy as jnp

        sd = SameDiff()
        xs = sd.placeholder("xs", (4, 3))
        y = sd.scan(lambda c, x: (c + x, c), jnp.zeros(3), xs)
        report = check_samediff(sd)
        assert report.findings == []  # local ops: unknown, no GC006
        assert report.avals[y.name].shape is None


# ---------------------------------------------------------------------------
# SameDiff surface: check() / validate=True
# ---------------------------------------------------------------------------


class TestFusedOpRules:
    """First-class rules for the optimizer's fusion-target registry ops
    (docs/OPTIMIZER.md § Fusion tier): symbolic-batch graphs must infer
    exact output shapes WITHOUT the jax.eval_shape probe (which cannot run
    over symbolic dims), and provable mismatches must flag GC codes."""

    def test_dot_product_attention_symbolic_batch(self):
        sd = SameDiff()
        q = sd.placeholder("q", (None, 4, 32, 16))
        k = sd.placeholder("k", (None, 4, 32, 16))
        v = sd.placeholder("v", (None, 4, 32, 16))
        m = sd.placeholder("m", (None, 1, 1, 32))
        sd.op("dot_product_attention", q, k, v, m, scaled=True).rename("o")
        report = check_samediff(sd)
        assert not report.findings
        aval = report.avals["o"]
        # a concrete trailing shape proves the RULE ran: the eval_shape
        # probe cannot produce one over a symbolic batch dim
        assert isinstance(aval.shape[0], Dim)
        assert aval.shape[1:] == (4, 32, 16)
        assert aval.dtype == np.dtype(np.float32)

    def test_dot_product_attention_causal_kwarg(self):
        sd = SameDiff()
        q = sd.placeholder("q", (None, 4, 32, 16))
        k = sd.placeholder("k", (None, 4, 32, 16))
        v = sd.placeholder("v", (None, 4, 32, 16))
        sd.op("dot_product_attention", q, k, v, causal=True).rename("o")
        report = check_samediff(sd)
        assert not report.findings
        assert report.avals["o"].shape[1:] == (4, 32, 16)

    def test_dot_product_attention_head_dim_mismatch(self):
        sd = SameDiff()
        q = sd.placeholder("q", (2, 4, 32, 16))
        k = sd.placeholder("k", (2, 4, 32, 24))  # dk mismatch
        v = sd.placeholder("v", (2, 4, 32, 16))
        sd.op("dot_product_attention", q, k, v)
        report = check_samediff(sd)
        assert any(f.rule == "GC002" and "head dims" in f.message
                   for f in report.findings)

    def test_dot_product_attention_zero_d_mask_flagged_not_crashed(self):
        sd = SameDiff()
        q = sd.placeholder("q", (2, 4, 32, 16))
        k = sd.placeholder("k", (2, 4, 32, 16))
        v = sd.placeholder("v", (2, 4, 32, 16))
        m = sd.placeholder("m", ())  # 0-d mask: finding, not IndexError
        sd.op("dot_product_attention", q, k, v, m)
        report = check_samediff(sd)
        assert any(f.rule == "GC001" and "0-d" in f.message
                   for f in report.findings)

    def test_dot_product_attention_kv_length_mismatch(self):
        sd = SameDiff()
        q = sd.placeholder("q", (2, 4, 32, 16))
        k = sd.placeholder("k", (2, 4, 32, 16))
        v = sd.placeholder("v", (2, 4, 48, 16))  # Lk mismatch
        sd.op("dot_product_attention", q, k, v)
        report = check_samediff(sd)
        assert any(f.rule == "GC002" and "sequence lengths" in f.message
                   for f in report.findings)

    def test_paged_decode_attention_symbolic_slots(self):
        sd = SameDiff()
        q = sd.placeholder("q", (None, 4, 16))
        kp = sd.var("kp", np.zeros((6, 8, 4, 16), np.float32))
        vp = sd.var("vp", np.zeros((6, 8, 4, 16), np.float32))
        pt = sd.placeholder("pt", (None, 3), dtype=np.int32)
        sl = sd.placeholder("sl", (None,), dtype=np.int32)
        sd.op("paged_decode_attention", q, kp, vp, pt, sl).rename("o")
        report = check_samediff(sd)
        assert not report.findings
        aval = report.avals["o"]
        assert isinstance(aval.shape[0], Dim) and aval.shape[1:] == (4, 16)

    def test_paged_decode_attention_rank_and_dtype_findings(self):
        sd = SameDiff()
        q = sd.placeholder("q", (2, 4, 16))
        kp = sd.var("kp", np.zeros((6, 8, 4, 16), np.float32))
        vp = sd.var("vp", np.zeros((6, 8, 4, 16), np.float32))
        pt = sd.placeholder("pt", (2, 3, 1), dtype=np.int32)  # rank 3
        sl = sd.placeholder("sl", (2,), dtype=np.int32)
        sd.op("paged_decode_attention", q, kp, vp, pt, sl)
        report = check_samediff(sd)
        assert any(f.rule == "GC001" and "page_table" in f.message
                   for f in report.findings)

        sd = SameDiff()
        pt_f = sd.placeholder("pt", (2, 3))  # float page table
        sl = sd.placeholder("sl", (2,), dtype=np.int32)
        q = sd.placeholder("q", (2, 4, 16))
        kp = sd.var("kp", np.zeros((6, 8, 4, 16), np.float32))
        vp = sd.var("vp", np.zeros((6, 8, 4, 16), np.float32))
        sd.op("paged_decode_attention", q, kp, vp, pt_f, sl)
        report = check_samediff(sd)
        assert any(f.rule == "GC003" and "not integral" in f.message
                   for f in report.findings)

    def test_fused_matmul_bias_act_symbolic_batch(self):
        sd = SameDiff()
        x = sd.placeholder("x", (None, 32))
        w = sd.var("w", np.zeros((32, 8), np.float32))
        b = sd.var("b", np.zeros(8, np.float32))
        sd.op("fused_matmul_bias_act", x, w, b,
              activation="gelu_exact").rename("o")
        report = check_samediff(sd)
        assert not report.findings
        aval = report.avals["o"]
        assert isinstance(aval.shape[0], Dim) and aval.shape[1] == 8

    def test_fused_matmul_bias_act_findings(self):
        sd = SameDiff()
        x = sd.placeholder("x", (4, 32))
        w = sd.var("w", np.zeros((16, 8), np.float32))  # contraction
        sd.op("fused_matmul_bias_act", x, w)
        report = check_samediff(sd)
        assert any(f.rule == "GC002" for f in report.findings)

        sd = SameDiff()
        x = sd.placeholder("x", (4, 32))
        w = sd.var("w", np.zeros((32, 8), np.float32))
        b = sd.var("b", np.zeros((3,), np.float32))  # bias won't broadcast
        sd.op("fused_matmul_bias_act", x, w, b)
        report = check_samediff(sd)
        assert any(f.rule == "GC002" and "bias" in f.message
                   for f in report.findings)

        sd = SameDiff()
        x = sd.placeholder("x", (4, 32))
        w = sd.var("w", np.zeros((32, 8), np.float32))
        sd.op("fused_matmul_bias_act", x, w, activation="swish")
        report = check_samediff(sd)
        assert any(f.rule == "GC001" and "activation" in f.message
                   for f in report.findings)

    def test_zero_probe_fallbacks_on_fused_fixture(self):
        # the acceptance criterion: the fused-graph fixture verifies with
        # no GC006 opacity findings — i.e. every fused op resolved through
        # a first-class rule, never the eval_shape probe (which is
        # impossible here: the fixture's batch dims are symbolic)
        report = check_samediff(fixtures.fused_graph_sym_batch(),
                                graph_name="zoo/fused_graph_sym_batch")
        assert not report.findings
        for out in ("att", "causal_att", "h", "decoded"):
            assert report.avals[out].shape is not None


class TestTunedKernelRules:
    """First-class rules for the PR-9 kernel set (fused_layer_norm,
    fused_updater_step, quantize/dequantize_int8, matmul_int8): the
    symbolic-batch fixture must infer exact shapes with ZERO eval_shape
    probe fallbacks, and provable mismatches must flag GC codes."""

    def test_rules_registered(self):
        from deeplearning4j_tpu.analysis.rules import RULES

        for op in ("fused_layer_norm", "fused_updater_step",
                   "quantize_int8", "dequantize_int8", "matmul_int8"):
            assert op in RULES, op

    def test_zero_probe_fallbacks_on_tuned_fixture(self):
        report = check_samediff(fixtures.tuned_kernels_sym_batch(),
                                graph_name="zoo/tuned_kernels_sym_batch")
        assert not report.findings
        y = report.avals["y"]
        assert isinstance(y.shape[0], Dim)  # rule ran: probe cannot do this
        assert y.shape[1] == 128
        assert report.avals["new_p"].shape == (128,)

    def test_fused_layer_norm_gain_mismatch(self):
        sd = SameDiff()
        x = sd.placeholder("x", (None, 128))
        g = sd.var("g", np.ones(64, np.float32))
        sd.op("fused_layer_norm", x, g, activation="gelu")
        report = check_samediff(sd)
        assert any(f.rule == "GC002" and "gain" in f.message
                   for f in report.findings)

    def test_fused_layer_norm_bad_activation(self):
        sd = SameDiff()
        x = sd.placeholder("x", (4, 128))
        g = sd.var("g", np.ones(128, np.float32))
        sd.op("fused_layer_norm", x, g, activation="swish")
        report = check_samediff(sd)
        assert any(f.rule == "GC001" and "activation" in f.message
                   for f in report.findings)

    def test_fused_updater_step_state_shape_mismatch(self):
        sd = SameDiff()
        p = sd.var("p", np.zeros(8, np.float32))
        g = sd.var("g", np.zeros(8, np.float32))
        m = sd.var("m", np.zeros(4, np.float32))  # wrong leaf shape
        lr = sd.constant(np.float32(1e-3))
        step = sd.constant(np.float32(0.0))
        sd.op("fused_updater_step", p, g, lr, step, m, kind="Nesterovs",
              n_out=2)
        report = check_samediff(sd)
        assert any(f.rule == "GC002" and "state[0]" in f.message
                   for f in report.findings)

    def test_matmul_int8_non_int8_weights(self):
        sd = SameDiff()
        x = sd.placeholder("x", (None, 128))
        w = sd.var("w", np.zeros((128, 64), np.float32))
        ws = sd.var("ws", np.ones(64, np.float32))
        sd.op("matmul_int8", x, w, ws)
        report = check_samediff(sd)
        assert any(f.rule == "GC003" and "int8" in f.message
                   for f in report.findings)

    def test_quantize_int8_axis_out_of_range(self):
        sd = SameDiff()
        x = sd.placeholder("x", (4, 8))
        sd.op("quantize_int8", x, axis=5, n_out=2)
        report = check_samediff(sd)
        assert any(f.rule == "GC001" and "axis" in f.message
                   for f in report.findings)

    def test_quantize_int8_tuple_axis_checks_clean(self):
        # the impl accepts jnp.max-style axis tuples; the rule must not
        # crash on them and derives the keepdims scale shape
        sd = SameDiff()
        x = sd.placeholder("x", (4, 8))
        q, s = sd.op("quantize_int8", x, axis=(0, 1), n_out=2)
        q.rename("q")
        s.rename("s")
        report = check_samediff(sd)
        assert not report.findings
        assert report.avals["s"].shape == (1, 1)

    def test_fused_updater_step_kind_and_arity_flagged(self):
        # unknown kind and wrong state count both raise at trace time —
        # the rule must flag them pre-trace
        def graph(kind, n_state):
            sd = SameDiff()
            p = sd.var("p", np.zeros(8, np.float32))
            g = sd.var("g", np.zeros(8, np.float32))
            lr = sd.constant(np.float32(1e-3))
            step = sd.constant(np.float32(0.0))
            st = [sd.var(f"s{i}", np.zeros(8, np.float32))
                  for i in range(n_state)]
            sd.op("fused_updater_step", p, g, lr, step, *st, kind=kind,
                  n_out=1 + n_state)
            return sd

        report = check_samediff(graph("Adm", 0))
        assert any(f.rule == "GC001" and "unknown updater kind"
                   in f.message for f in report.findings)
        report = check_samediff(graph("Adam", 1))
        assert any(f.rule == "GC001" and "expected 2 state" in f.message
                   for f in report.findings)
        assert not check_samediff(graph("Adam", 2)).findings

    def test_fused_updater_step_rank_mismatch_flagged(self):
        # zip() truncation must not hide a rank mismatch
        sd = SameDiff()
        p = sd.var("p", np.zeros(4, np.float32))
        g = sd.var("g", np.zeros((4, 5), np.float32))
        lr = sd.constant(np.float32(1e-3))
        step = sd.constant(np.float32(0.0))
        sd.op("fused_updater_step", p, g, lr, step, kind="Sgd")
        report = check_samediff(sd)
        assert any(f.rule == "GC002" and "grad" in f.message
                   for f in report.findings)

    def test_fused_layer_norm_non_trailing_axis_flagged(self):
        sd = SameDiff()
        x = sd.placeholder("x", (4, 128))
        g = sd.var("g", np.ones(128, np.float32))
        sd.op("fused_layer_norm", x, g, axis=0)
        report = check_samediff(sd)
        assert any(f.rule == "GC001" and "trailing" in f.message
                   for f in report.findings)


class TestSameDiffWiring:
    def test_check_populates_last_report(self):
        sd = SameDiff()
        x = sd.placeholder("x", (2, 3))
        sd.math.tanh(x)
        assert sd.last_check_report is None
        report = sd.check(name="wiring")
        assert sd.last_check_report is report
        assert report.ok

    def test_validate_raises_before_trace(self):
        sd = SameDiff(validate=True)
        a = sd.placeholder("a", (2, 3))
        b = sd.placeholder("b", (4, 5))
        out = a + b
        with pytest.raises(GraphCheckError) as ei:
            sd.output({"a": np.ones((2, 3), np.float32),
                       "b": np.ones((4, 5), np.float32)}, [out.name])
        assert "GC002" in str(ei.value)

    def test_validate_checks_only_requested_subgraph(self):
        # the broken branch is NOT an ancestor of the requested output —
        # validate must not block execution (mirrors trace semantics)
        sd = SameDiff(validate=True)
        x = sd.placeholder("x", (2, 3))
        good = (x * 2.0).sum()
        good.rename("ok")
        x.reshape(999)  # dead and impossible
        res = sd.output({"x": np.ones((2, 3), np.float32)}, ["ok"])
        assert float(res["ok"]) == 12.0

    def test_validate_off_by_default(self):
        sd = SameDiff()
        a = sd.placeholder("a", (3,))
        (a + a).rename("y")
        res = sd.output({"a": np.ones(3, np.float32)}, ["y"])
        np.testing.assert_allclose(res["y"], 2 * np.ones(3))


# ---------------------------------------------------------------------------
# importer wiring
# ---------------------------------------------------------------------------


class TestImporterWiring:
    def _bad_ir(self):
        from deeplearning4j_tpu.imports.ir import IRGraph, IRNode

        init = {"w": np.zeros((7, 3), np.float32)}  # wrong contraction dim
        nodes = [IRNode("mm", "MatMul", ["x", "w"], ["y"])]
        return IRGraph(nodes=nodes, initializers=init,
                       inputs=[("x", (2, 8))], outputs=["y"], name="onnx")

    def test_onnx_importer_raises_with_provenance(self):
        from deeplearning4j_tpu.imports.onnx_import import OnnxImporter

        with pytest.raises(GraphCheckError) as ei:
            OnnxImporter().run_import(self._bad_ir())
        msg = str(ei.value)
        assert "GC002" in msg and "'y'" in msg  # source node name surfaces

    def test_validate_false_opts_out(self):
        from deeplearning4j_tpu.imports.onnx_import import OnnxImporter

        sd = OnnxImporter(validate=False).run_import(self._bad_ir())
        assert sd.last_check_report is None

    def test_clean_import_attaches_report(self):
        sd = fixtures.onnx_mini_import()
        assert sd.last_check_report is not None
        assert sd.last_check_report.ok


# ---------------------------------------------------------------------------
# check_network (the Keras-import surface)
# ---------------------------------------------------------------------------


class TestCheckNetwork:
    def test_clean_sequential(self):
        from deeplearning4j_tpu import nn

        conf = (nn.builder().seed(0)
                .layer(nn.DenseLayer(n_out=8, activation="relu"))
                .layer(nn.OutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(nn.InputType.feed_forward(4))
                .build())
        report = check_network(conf, graph_name="net/clean")
        assert report.findings == []

    def test_n_in_contradiction_flagged(self):
        from deeplearning4j_tpu import nn

        conf = (nn.builder().seed(0)
                .layer(nn.DenseLayer(n_in=4, n_out=8, activation="relu"))
                .layer(nn.DenseLayer(n_in=9, n_out=2))  # 8 flows in
                .set_input_type(nn.InputType.feed_forward(4))
                .build())
        report = check_network(conf, graph_name="net/bad")
        assert any(f.rule == "GC002" and "n_in=9" in f.message
                   for f in report.findings), [
            f.render() for f in report.findings]

    def test_keras_import_runs_check(self):
        # the sequential Keras path attaches last_check_report
        from deeplearning4j_tpu.imports.keras_import import (
            import_keras_sequential_config)

        config = {"class_name": "Sequential", "config": {"layers": [
            {"class_name": "Dense",
             "config": {"name": "dense", "units": 8, "activation": "relu",
                        "use_bias": True, "batch_input_shape": [None, 4]}},
            {"class_name": "Dense",
             "config": {"name": "dense_1", "units": 2,
                        "activation": "softmax", "use_bias": True}},
        ]}}
        r = np.random.RandomState(0)
        weights = {"dense": [r.randn(4, 8).astype(np.float32),
                             np.zeros(8, np.float32)],
                   "dense_1": [r.randn(8, 2).astype(np.float32),
                               np.zeros(2, np.float32)]}
        net = import_keras_sequential_config(config, weights)
        assert net.last_check_report is not None
        assert net.last_check_report.ok


# ---------------------------------------------------------------------------
# CLI + baseline contract
# ---------------------------------------------------------------------------


class TestCliAndBaseline:
    def test_cli_json_contract(self):
        proc = subprocess.run(
            [sys.executable, "tools/graftcheck.py", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert proc.returncode == 0, proc.stdout + proc.stderr
        lines = [l for l in proc.stdout.splitlines() if l.strip()]
        assert len(lines) == 1
        rec = json.loads(lines[0])
        assert rec["tool"] == "graftcheck" and rec["new"] == 0

    def test_committed_baseline_is_empty(self):
        # the fixture zoo carries NO grandfathered debt; a finding there
        # is a regression, never baseline material
        with open(os.path.join(REPO, "check_baseline.json")) as fh:
            data = json.load(fh)
        assert data["findings"] == {}

    def test_write_baseline_refuses_growth(self, tmp_path):
        from deeplearning4j_tpu.lint.core import (
            Finding, load_baseline, write_baseline)

        path = str(tmp_path / "check_baseline.json")
        write_baseline(path, [], comment="test")
        bad = Finding(path="zoo/mlp_sym_batch", line=1, rule="GC002",
                      severity="error", message="seeded")
        refused = write_baseline(path, [bad], comment="test")
        assert refused == {bad.key: 1}
        assert load_baseline(path) == {}

    def test_all_codes_documented(self):
        """Every GC code has an entry in docs/ANALYSIS.md (the lint-suite
        doc ratchet, applied to graftcheck)."""
        doc = open(os.path.join(REPO, "docs", "ANALYSIS.md")).read()
        for code in GC_CODES:
            assert code in doc, f"{code} missing from docs/ANALYSIS.md"
