"""DataVec transform DSL + EarlyStopping + TransferLearning tests
(reference datavec-api transform tests, EarlyStoppingTrainer tests,
TransferLearning tests — SURVEY §3.3/§3.4)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datavec import (
    Schema, TransformProcess, CSVRecordReader, ColumnCondition, Reducer,
    LocalTransformExecutor, records_to_dataset,
)
from deeplearning4j_tpu import nn
from deeplearning4j_tpu.datasets import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.earlystopping import (
    EarlyStoppingConfiguration, EarlyStoppingTrainer,
    MaxEpochsTerminationCondition, ScoreImprovementEpochTerminationCondition,
    MaxScoreIterationTerminationCondition, InMemoryModelSaver, LocalFileModelSaver,
)
from deeplearning4j_tpu.nn.transfer import (
    TransferLearning, FineTuneConfiguration, TransferLearningHelper,
)


CSV = """alice,25,engineer,50000
bob,31,doctor,90000
carol,17,student,0
dave,45,engineer,80000
"""


def schema():
    return (Schema.builder()
            .add_column_string("name")
            .add_column_integer("age")
            .add_column_categorical("job", "engineer", "doctor", "student")
            .add_column_double("salary")
            .build())


class TestTransformDSL:
    def test_csv_read_typed(self):
        records = CSVRecordReader(schema=schema()).read(CSV)
        assert records[0] == ["alice", 25, "engineer", 50000.0]

    def test_remove_rename_math(self):
        tp = (TransformProcess.builder(schema())
              .remove_columns("name")
              .rename_column("salary", "pay")
              .math_op("pay", "Divide", 1000.0)
              .build())
        recs = tp.execute(CSVRecordReader(schema=schema()).read(CSV))
        assert tp.final_schema().names == ["age", "job", "pay"]
        assert recs[1] == [31, "doctor", 90.0]

    def test_categorical_to_one_hot(self):
        tp = (TransformProcess.builder(schema())
              .remove_columns("name")
              .categorical_to_one_hot("job")
              .build())
        recs = tp.execute(CSVRecordReader(schema=schema()).read(CSV))
        fs = tp.final_schema()
        assert "job[engineer]" in fs.names
        assert recs[0][fs.index_of("job[engineer]")] == 1
        assert recs[1][fs.index_of("job[doctor]")] == 1

    def test_filter_condition(self):
        tp = (TransformProcess.builder(schema())
              .filter(ColumnCondition("age", "LessThan", 18))
              .build())
        recs = tp.execute(CSVRecordReader(schema=schema()).read(CSV))
        assert len(recs) == 3
        assert all(r[1] >= 18 for r in recs)

    def test_conditional_replace_and_boolean_conditions(self):
        cond = (ColumnCondition("salary", "Equal", 0.0)
                | ColumnCondition("age", "LessThan", 18))
        tp = (TransformProcess.builder(schema())
              .conditional_replace_value_transform("salary", 1000.0, cond)
              .build())
        recs = tp.execute(CSVRecordReader(schema=schema()).read(CSV))
        assert recs[2][3] == 1000.0  # carol replaced

    def test_math_function_and_string_ops(self):
        tp = (TransformProcess.builder(schema())
              .string_to_upper("name")
              .math_function("salary", "SQRT")
              .build())
        recs = tp.execute(CSVRecordReader(schema=schema()).read(CSV))
        assert recs[0][0] == "ALICE"
        assert recs[1][3] == pytest.approx(300.0)

    def test_reducer_group_by(self):
        records = CSVRecordReader(schema=schema()).read(CSV)
        red = Reducer(["job"], {"salary": "MEAN", "age": "COUNT"})
        out, out_schema = red.reduce(records, schema())
        by_job = {r[0]: r for r in out}
        assert by_job["engineer"][1] == pytest.approx(65000.0)
        assert by_job["engineer"][2] == 2

    def test_records_to_dataset_and_train(self):
        tp = (TransformProcess.builder(schema())
              .remove_columns("name")
              .categorical_to_integer("job")
              .build())
        recs = LocalTransformExecutor.execute(
            CSVRecordReader(schema=schema()).read(CSV), tp)
        ds = records_to_dataset(recs, tp.final_schema(), "job", num_classes=3)
        assert ds.features.shape == (4, 2)
        assert ds.labels.shape == (4, 3)


def make_net(seed=1, lr=0.05):
    return nn.MultiLayerNetwork(
        nn.builder().seed(seed).updater(nn.Adam(learning_rate=lr)).list()
        .layer(nn.DenseLayer(n_out=16, activation="tanh"))
        .layer(nn.DenseLayer(n_out=8, activation="tanh"))
        .layer(nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(nn.InputType.feed_forward(2)).build()
    ).init()


def xor():
    rng = np.random.RandomState(0)
    x = rng.rand(256, 2).astype(np.float32)
    y_id = ((x[:, 0] > 0.5) ^ (x[:, 1] > 0.5)).astype(int)
    return x, np.eye(2, dtype=np.float32)[y_id], y_id


class TestEarlyStopping:
    def test_max_epochs_stops(self):
        x, y, _ = xor()
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[MaxEpochsTerminationCondition(3)])
        trainer = EarlyStoppingTrainer(
            cfg, make_net(), ListDataSetIterator(DataSet(x, y), batch_size=128),
            ListDataSetIterator(DataSet(x, y), batch_size=128))
        result = trainer.fit()
        assert result.total_epochs == 3
        assert result.termination_reason == "EpochTerminationCondition"
        assert result.best_epoch >= 0

    def test_score_improvement_patience(self):
        x, y, _ = xor()
        # lr=0 → score never improves → patience triggers quickly
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[
                ScoreImprovementEpochTerminationCondition(2),
                MaxEpochsTerminationCondition(50)])
        trainer = EarlyStoppingTrainer(
            cfg, make_net(lr=0.0), ListDataSetIterator(DataSet(x, y), batch_size=256),
            ListDataSetIterator(DataSet(x, y), batch_size=256))
        result = trainer.fit()
        assert result.total_epochs < 50

    def test_divergence_guard(self):
        x, y, _ = xor()
        cfg = EarlyStoppingConfiguration(
            iteration_termination_conditions=[MaxScoreIterationTerminationCondition(1e-9)],
            epoch_termination_conditions=[MaxEpochsTerminationCondition(10)])
        trainer = EarlyStoppingTrainer(
            cfg, make_net(), ListDataSetIterator(DataSet(x, y), batch_size=256))
        result = trainer.fit()
        assert result.termination_reason == "IterationTerminationCondition"

    def test_best_model_restored(self, tmp_path):
        x, y, _ = xor()
        saver = LocalFileModelSaver(str(tmp_path))
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[MaxEpochsTerminationCondition(4)],
            model_saver=saver)
        trainer = EarlyStoppingTrainer(
            cfg, make_net(), ListDataSetIterator(DataSet(x, y), batch_size=128),
            ListDataSetIterator(DataSet(x, y), batch_size=128))
        result = trainer.fit()
        assert result.best_model is not None
        assert (tmp_path / "bestModel.zip").exists()


class TestTransferLearning:
    def test_freeze_keeps_params_fixed(self):
        x, y, _ = xor()
        base = make_net()
        base.fit(x, y, epochs=3, batch_size=128)
        tl = (TransferLearning.builder(base)
              .set_feature_extractor(0)  # freeze layer 0
              .build())
        frozen_before = np.asarray(tl.params[0]["W"]).copy()
        head_before = np.asarray(tl.params[2]["W"]).copy()
        tl.fit(x, y, epochs=3, batch_size=128)
        np.testing.assert_allclose(np.asarray(tl.params[0]["W"]), frozen_before)
        assert not np.allclose(np.asarray(tl.params[2]["W"]), head_before)

    def test_replace_output_layer(self):
        base = make_net()
        tl = (TransferLearning.builder(base)
              .fine_tune_configuration(FineTuneConfiguration(updater=nn.Sgd(learning_rate=0.1)))
              .remove_output_layer()
              .add_layer(nn.OutputLayer(n_out=5, activation="softmax", loss="mcxent"))
              .build())
        out = tl.output(np.zeros((2, 2), np.float32))
        assert out.shape == (2, 5)
        # kept layers share the source weights
        np.testing.assert_allclose(np.asarray(tl.params[0]["W"]),
                                   np.asarray(base.params[0]["W"]))

    def test_n_out_replace(self):
        base = make_net()
        tl = (TransferLearning.builder(base)
              .n_out_replace(1, 12)
              .build())
        assert tl.conf.layers[1].n_out == 12
        assert tl.conf.layers[2].n_in == 12
        assert tl.output(np.zeros((1, 2), np.float32)).shape == (1, 2)

    def test_helper_featurize_train_head(self):
        x, y, y_id = xor()
        base = make_net()
        helper = TransferLearningHelper(base, frozen_until=1)
        feat = helper.featurize(DataSet(x, y))
        assert feat.features.shape == (256, 8)
        helper.fit_featurized(feat, epochs=30, batch_size=128)
        # head trained; full net output reflects it
        acc = (base.output(x).argmax(-1) == y_id).mean()
        assert np.isfinite(acc)


class TestDataVecJoinsSequencesQuality:
    """Round-3 datavec fill: Join types, sequence conversion, quality
    analysis (datavec-api transform/join, transform/sequence, analysis)."""

    def _schemas(self):
        from deeplearning4j_tpu.datavec import Schema

        left = (Schema.Builder().add_column_integer("id")
                .add_column_string("name").build())
        right = (Schema.Builder().add_column_integer("id")
                 .add_column_double("score").build())
        return left, right

    def test_inner_and_left_outer_join(self):
        from deeplearning4j_tpu.datavec import Join

        left_s, right_s = self._schemas()
        left = [[1, "a"], [2, "b"], [3, "c"]]
        right = [[1, 0.5], [1, 0.7], [3, 0.9], [4, 1.1]]
        inner = Join(Join.INNER, left_s, right_s, ["id"])
        rows = inner.execute(left, right)
        assert sorted(rows) == [[1, "a", 0.5], [1, "a", 0.7], [3, "c", 0.9]]
        assert inner.output_schema().names == ["id", "name", "score"]

        lo = Join(Join.LEFT_OUTER, left_s, right_s, ["id"]).execute(left, right)
        assert [2, "b", None] in lo

        fo = Join(Join.FULL_OUTER, left_s, right_s, ["id"]).execute(left, right)
        assert [4, None, 1.1] in fo and [2, "b", None] in fo

    def test_sequence_conversion_and_dataset(self):
        from deeplearning4j_tpu.datavec import (
            Schema, convert_from_sequence, convert_to_sequence,
            sequence_to_dataset)

        schema = (Schema.Builder().add_column_integer("key")
                  .add_column_integer("t").add_column_double("x")
                  .add_column_integer("label").build())
        records = [[1, 2, 0.3, 1], [0, 0, 0.1, 0], [1, 1, 0.2, 0],
                   [0, 1, 0.4, 1]]
        seqs = convert_to_sequence(records, schema, "key", order_column="t")
        assert len(seqs) == 2
        assert [r[1] for r in seqs[0]] == sorted(r[1] for r in seqs[0])
        flat = convert_from_sequence(seqs)
        assert sorted(map(tuple, flat)) == sorted(map(tuple, records))

        ds = sequence_to_dataset(seqs, schema, ["x"], "label", num_classes=2)
        assert ds.features.shape == (2, 2, 1)
        assert ds.labels.shape == (2, 2, 2)

    def test_quality_and_analysis(self):
        from deeplearning4j_tpu.datavec import (
            Schema, analyze, analyze_quality)

        schema = (Schema.Builder().add_column_integer("a")
                  .add_column_double("b").build())
        records = [[1, 2.0], [None, 3.0], ["oops", float("nan")], [4, 5.0]]
        q = analyze_quality(records, schema)
        assert q.quality_of("a").count_missing == 1
        assert q.quality_of("a").count_invalid == 1
        assert q.quality_of("b").count_invalid == 1
        an = analyze(records, schema)
        assert an.min_of("b") == 2.0 and an.max_of("b") == 5.0
        np.testing.assert_allclose(an.mean_of("a"), (1 + 4) / 2)


class TestCSVNativeFastPath:
    def test_read_matrix(self):
        from deeplearning4j_tpu.datavec import CSVRecordReader

        rr = CSVRecordReader(skip_lines=1)
        m = rr.read_matrix("a,b\n1,2\n3.5,4\n", 2)
        np.testing.assert_allclose(m, [[1, 2], [3.5, 4]])
