"""CPU-vs-TPU consistency gate (SURVEY §5.2 — the ValidateCuDNN analog).

The unit suite pins the CPU backend (conftest), so the cross-backend run
happens in a SUBPROCESS with a clean environment where the ambient TPU
plugin loads; skipped when no TPU is reachable."""

import os
import subprocess
import sys

import pytest


def _tpu_available() -> bool:
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=120, env=env)
        return r.stdout.strip().endswith("tpu")
    except Exception:
        return False


@pytest.mark.skipif(not _tpu_available(), reason="no TPU device reachable")
def test_cpu_vs_tpu_consistency():
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    r = subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu.testing.consistency"],
        capture_output=True, text=True, timeout=1800, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert r.returncode == 0, (
        f"consistency suite failed:\n{r.stdout[-3000:]}\n{r.stderr[-3000:]}")
