"""graftlint: per-rule fixture tests (one true positive + one true negative
each), baseline mechanics, and the whole-repo gate run.

The repo run IS the suite-time lint the round-5 verdict asked for: it fails
this test file — and therefore tier-1 — on any finding not grandfathered in
lint_baseline.json.
"""

import json
import os
import subprocess
import sys
import textwrap

from deeplearning4j_tpu.lint import (
    AST_RULES, Finding, diff_baseline, lint_paths, lint_source,
    load_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "lint_baseline.json")


def _lint(src, rules=None):
    return lint_source(textwrap.dedent(src), path="fixture.py", rules=rules)


def _rules_hit(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# GL001 — host sync under jit
# ---------------------------------------------------------------------------


class TestGL001HostSync:
    def test_true_positive_decorated(self):
        fs = _lint("""
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                y = np.asarray(x)
                return y.item()
        """, rules={"GL001"})
        assert len(fs) == 2
        assert all(f.rule == "GL001" for f in fs)
        assert "np.asarray" in fs[0].message

    def test_true_positive_jit_wrapped(self):
        fs = _lint("""
            import jax
            import numpy as np

            def g(x):
                return np.array(x) + 1

            h = jax.jit(g)
        """, rules={"GL001"})
        assert len(fs) == 1 and fs[0].severity == "error"

    def test_true_positive_float_cast(self):
        fs = _lint("""
            import jax

            @jax.jit
            def f(x):
                return float(x) * 2
        """, rules={"GL001"})
        assert len(fs) == 1 and fs[0].severity == "warning"

    def test_true_negative(self):
        fs = _lint("""
            import jax
            import jax.numpy as jnp
            import numpy as np

            @jax.jit
            def f(x):
                return jnp.asarray(x) + 1

            def host_side(x):     # not jitted: np here is fine
                return np.asarray(x).item()
        """, rules={"GL001"})
        assert fs == []


# ---------------------------------------------------------------------------
# GL002 — unguarded backend probes
# ---------------------------------------------------------------------------


class TestGL002BackendProbe:
    def test_true_positive_import_time(self):
        fs = _lint("""
            import jax

            DEVICES = jax.devices()
        """, rules={"GL002"})
        assert len(fs) == 1 and fs[0].severity == "error"
        assert "import time" in fs[0].message

    def test_true_positive_unguarded_function(self):
        fs = _lint("""
            import jax

            def mesh_size():
                return len(jax.local_devices())
        """, rules={"GL002"})
        assert len(fs) == 1 and fs[0].severity == "warning"

    def test_true_negative_subprocess_guard(self):
        fs = _lint("""
            import subprocess
            import sys

            def has_tpu():
                probe = "import jax; print(jax.devices())"
                out = subprocess.run([sys.executable, "-c", probe],
                                     capture_output=True, timeout=180)
                return b"tpu" in out.stdout
        """, rules={"GL002"})
        assert fs == []

    def test_true_negative_timeout_guard(self):
        fs = _lint("""
            import jax

            def probe(pool):
                fut = pool.submit(jax.devices)
                return fut.result(timeout=30)
        """, rules={"GL002"})
        assert fs == []


# ---------------------------------------------------------------------------
# GL003 — side effects under jit
# ---------------------------------------------------------------------------


class TestGL003SideEffects:
    def test_true_positive(self):
        fs = _lint("""
            import jax

            _CALLS = 0

            @jax.jit
            def f(x):
                global _CALLS
                _CALLS += 1
                print("tracing", x)
                return x * 2
        """, rules={"GL003"})
        assert len(fs) == 2
        sev = {f.severity for f in fs}
        assert sev == {"error", "warning"}   # global=error, print=warning

    def test_true_negative_debug_print(self):
        fs = _lint("""
            import jax

            @jax.jit
            def f(x):
                jax.debug.print("x = {}", x)
                return x * 2

            def host():
                print("not traced")
        """, rules={"GL003"})
        assert fs == []


# ---------------------------------------------------------------------------
# GL004 — PRNG key reuse
# ---------------------------------------------------------------------------


class TestGL004KeyReuse:
    def test_true_positive(self):
        fs = _lint("""
            import jax

            def f(key):
                a = jax.random.normal(key, (2,))
                b = jax.random.uniform(key, (2,))
                return a + b
        """, rules={"GL004"})
        assert len(fs) == 1 and "consumed again" in fs[0].message

    def test_true_negative_split(self):
        fs = _lint("""
            import jax

            def f(key):
                k1, k2 = jax.random.split(key)
                a = jax.random.normal(k1, (2,))
                b = jax.random.uniform(k2, (2,))
                return a + b
        """, rules={"GL004"})
        assert fs == []

    def test_true_negative_exclusive_branches(self):
        # the weight-init dispatch pattern: one consumption per CALL
        fs = _lint("""
            import jax

            def init(key, scheme):
                if scheme == "normal":
                    return jax.random.normal(key, (2,))
                if scheme == "uniform":
                    return jax.random.uniform(key, (2,))
                return jax.random.bernoulli(key, 0.5, (2,))
        """, rules={"GL004"})
        assert fs == []

    def test_true_negative_stdlib_random(self):
        fs = _lint("""
            import random

            def f(xs):
                a = random.choice(xs)
                b = random.choice(xs)
                return a, b
        """, rules={"GL004"})
        assert fs == []

    def test_true_positive_fold_in_then_double_use(self):
        fs = _lint("""
            import jax

            def f(key, i):
                k = jax.random.fold_in(key, i)
                a = jax.random.normal(k, (2,))
                b = jax.random.normal(k, (2,))
                return a + b
        """, rules={"GL004"})
        assert len(fs) == 1


# ---------------------------------------------------------------------------
# GL005 — mutable defaults
# ---------------------------------------------------------------------------


class TestGL005MutableDefaults:
    def test_true_positive(self):
        fs = _lint("""
            def fit(x, callbacks=[], options={}):
                return x
        """, rules={"GL005"})
        assert len(fs) == 2

    def test_true_negative(self):
        fs = _lint("""
            def fit(x, callbacks=None, option=()):
                callbacks = callbacks or []
                return x

            def _internal(x, scratch=[]):   # private: not the public surface
                return x
        """, rules={"GL005"})
        assert fs == []


# ---------------------------------------------------------------------------
# GL007 — bare/swallowed except
# ---------------------------------------------------------------------------


class TestGL007BareExcept:
    def test_true_positive(self):
        fs = _lint("""
            def f():
                try:
                    risky()
                except:
                    return None

            def g():
                try:
                    risky()
                except Exception:
                    pass
        """, rules={"GL007"})
        assert len(fs) == 2
        assert {f.severity for f in fs} == {"error", "warning"}

    def test_true_negative(self):
        fs = _lint("""
            def f():
                try:
                    risky()
                except ValueError:
                    pass
                except Exception as e:
                    log(e)
        """, rules={"GL007"})
        assert fs == []


# ---------------------------------------------------------------------------
# GL006 — registry shadowing (consistency rule, live registries)
# ---------------------------------------------------------------------------


class TestGL009NumpyInOpImpl:
    def test_true_positive_dict_literal(self):
        findings = _lint("""
            import numpy as np
            GRAPH_OPS = {
                "my_op": lambda a: np.asarray(a).sum(),
            }
        """)
        assert "GL009" in _rules_hit(findings)

    def test_true_positive_annotated_dict_literal(self):
        # the REAL table is `GRAPH_OPS: Dict[...] = {...}` (AnnAssign) —
        # the rule must scan it too (review regression)
        findings = _lint("""
            import numpy as np
            GRAPH_OPS: Dict[str, Callable] = {
                "bad_op": lambda a: np.asarray(a).sum(),
            }
        """)
        assert "GL009" in _rules_hit(findings)

    def test_true_positive_subscript_assign(self):
        findings = _lint("""
            import numpy as np
            def _impl(a):
                return np.stack([a, a])
            _sdmod.GRAPH_OPS["patched_op"] = _impl
        """)
        assert "GL009" in _rules_hit(findings)

    def test_true_positive_registry_decorator(self):
        findings = _lint("""
            import numpy as np
            @_op("my_reduce")
            def my_reduce(x):
                return np.sum(x)
        """)
        assert "GL009" in _rules_hit(findings)

    def test_true_positive_register_call(self):
        findings = _lint("""
            import numpy as np
            def fancy(x):
                return np.asarray(x)
            _REG.register("fancy", fancy)
        """)
        assert "GL009" in _rules_hit(findings)

    def test_true_negative_whitelisted_numpy_static(self):
        # shape_of/stack/unstack are DOCUMENTED numpy-static (their host
        # behavior is the contract) — never flagged
        findings = _lint("""
            import numpy as np
            @_op("stack")
            def stack(*xs, axis=0):
                return np.stack([np.asarray(x) for x in xs], axis=axis)

            @_op("shape_of")
            def shape_of(x):
                return np.asarray(x.shape, np.int32)
        """)
        assert "GL009" not in _rules_hit(findings)

    def test_true_negative_jnp_and_helpers(self):
        # jnp inside an impl and np inside a NON-op helper are both fine
        findings = _lint("""
            import numpy as np
            import jax.numpy as jnp

            GRAPH_OPS = {"ok_op": lambda a: jnp.asarray(a).sum()}

            def plain_helper(x):
                return np.asarray(x)   # not a graph-op impl
        """)
        assert "GL009" not in _rules_hit(findings)

    def test_repo_op_impl_numpy_is_whitelisted_or_justified(self):
        """The live ops/ tree carries no un-justified np in op impls —
        every hit is either whitelisted (shape_of/stack/unstack) or has an
        inline disable with a written justification."""
        findings = lint_paths(["deeplearning4j_tpu/ops"], REPO,
                              rules=["GL009"])
        assert findings == [], "\n".join(f.render() for f in findings)


class TestGL010WalltimeDuration:
    def test_true_positive_direct_subtraction(self):
        fs = _lint("""
            import time

            def run(job):
                t0 = time.time()
                job()
                return time.time() - t0
        """, rules={"GL010"})
        assert len(fs) == 1
        assert fs[0].rule == "GL010" and fs[0].severity == "error"
        assert "perf_counter" in fs[0].message

    def test_true_positive_attribute_anchor_across_methods(self):
        # the repo's own listener pattern: anchor stashed in __init__,
        # subtracted in a later callback
        fs = _lint("""
            import time

            class L:
                def __init__(self):
                    self._t0 = time.time()

                def done(self):
                    return (time.time() - self._t0) * 1000.0
        """, rules={"GL010"})
        assert len(fs) == 1 and fs[0].rule == "GL010"

    def test_true_positive_from_import_alias(self):
        fs = _lint("""
            from time import time as now

            def f():
                a = now()
                return now() - a
        """, rules={"GL010"})
        assert len(fs) == 1 and fs[0].rule == "GL010"

    def test_true_negative_timestamps_and_epoch_arithmetic(self):
        # timestamps (stored, compared, shifted by a constant) are
        # whitelisted: only a two-wall-operand subtraction is a duration
        fs = _lint("""
            import time

            def record(store, timeout):
                store["timestamp"] = time.time()
                yesterday = time.time() - 86400
                deadline = time.time() + timeout
                return time.time() > deadline, yesterday
        """, rules={"GL010"})
        assert fs == []

    def test_true_negative_perf_counter(self):
        fs = _lint("""
            import time

            def run(job):
                t0 = time.perf_counter()
                job()
                return time.perf_counter() - t0
        """, rules={"GL010"})
        assert fs == []

    def test_repo_durations_are_monotonic(self):
        """The package itself carries no wall-clock durations (the
        observability PR swept listeners/arbiter/earlystopping)."""
        findings = lint_paths(["deeplearning4j_tpu"], REPO, rules=["GL010"])
        assert findings == [], "\n".join(f.render() for f in findings)


class TestGL006RegistryShadowing:
    def test_repo_whitelist_is_exact(self):
        from deeplearning4j_tpu.lint.rules_consistency import (
            rule_registry_shadowing)
        assert rule_registry_shadowing(REPO) == []

    def test_unlisted_shadow_is_flagged(self, monkeypatch):
        from deeplearning4j_tpu.autodiff import samediff
        from deeplearning4j_tpu.lint.rules_consistency import (
            rule_registry_shadowing)
        from deeplearning4j_tpu.ops.registry import registry
        victim = next(n for n in registry().names()
                      if n not in samediff.GRAPH_OPS)
        monkeypatch.setitem(samediff.GRAPH_OPS, victim, lambda *a: a)
        fs = rule_registry_shadowing(REPO)
        assert len(fs) == 1 and victim in fs[0].message
        assert fs[0].rule == "GL006" and fs[0].severity == "error"

    def test_stale_whitelist_entry_is_flagged(self, monkeypatch):
        from deeplearning4j_tpu.autodiff import samediff
        from deeplearning4j_tpu.lint.rules_consistency import (
            rule_registry_shadowing)
        monkeypatch.setattr(
            samediff, "REGISTRY_SHADOW_WHITELIST",
            samediff.REGISTRY_SHADOW_WHITELIST | {"not_a_real_op_name"})
        fs = rule_registry_shadowing(REPO)
        assert len(fs) == 1 and "stale" in fs[0].message


# ---------------------------------------------------------------------------
# GL008 — README surface counts (consistency rule)
# ---------------------------------------------------------------------------


class TestGL008ReadmeCounts:
    def test_repo_readme_matches_live_registries(self):
        from deeplearning4j_tpu.lint.rules_consistency import (
            rule_readme_counts)
        assert rule_readme_counts(REPO) == []

    def test_drifted_claim_is_flagged(self, tmp_path):
        from deeplearning4j_tpu.lint.rules_consistency import (
            rule_readme_counts)
        (tmp_path / "README.md").write_text(
            "a 99999-entry named declarable-op registry of things\n")
        fs = rule_readme_counts(str(tmp_path))
        assert len(fs) == 1 and fs[0].rule == "GL008"
        assert "99999" in fs[0].message


# ---------------------------------------------------------------------------
# suppression + baseline mechanics
# ---------------------------------------------------------------------------


class TestSuppressionAndBaseline:
    def test_inline_disable_comment(self):
        fs = _lint("""
            def fit(x, callbacks=[]):  # graftlint: disable=GL005
                return x
        """, rules={"GL005"})
        assert fs == []

    def test_disable_is_rule_scoped(self):
        fs = _lint("""
            def fit(x, callbacks=[]):  # graftlint: disable=GL001
                return x
        """, rules={"GL005"})
        assert len(fs) == 1   # disabling GL001 does not silence GL005

    def test_skip_file_marker(self):
        fs = _lint("""\
            # graftlint: skip-file
            def fit(x, callbacks=[]):
                return x
        """)
        assert fs == []

    def test_diff_baseline_new_and_fixed(self):
        f1 = Finding(path="a.py", line=3, rule="GL005", severity="warning",
                     message="m1")
        f2 = Finding(path="a.py", line=9, rule="GL005", severity="warning",
                     message="m1")   # same key, second occurrence
        new, fixed = diff_baseline([f1, f2], {f1.key: 1})
        assert new == [f2]           # one grandfathered, one new
        new, fixed = diff_baseline([], {f1.key: 1})
        assert new == [] and fixed == [f1.key]   # fixed: baseline can shrink
        new, fixed = diff_baseline([f1], {f1.key: 1})
        assert new == [] and fixed == []

    def test_write_baseline_refuses_growth(self, tmp_path):
        """Regenerating the baseline can never silently grandfather a
        regression: new keys are refused unless allow_growth is explicit."""
        from deeplearning4j_tpu.lint import write_baseline
        path = str(tmp_path / "baseline.json")
        old = Finding(path="a.py", line=1, rule="GL007", severity="warning",
                      message="old debt")
        assert write_baseline(path, [old]) == {}         # fresh file: all in
        new = Finding(path="b.py", line=2, rule="GL002", severity="warning",
                      message="new regression")
        refused = write_baseline(path, [old, new])
        assert refused == {new.key: 1}
        assert load_baseline(path) == {old.key: 1}       # regression NOT blessed
        assert write_baseline(path, [old, new], allow_growth=True) == {}
        assert load_baseline(path) == {old.key: 1, new.key: 1}

    def test_write_baseline_subset_paths_refused_by_cli(self, capsys):
        """A subset scan must not clobber the repo-wide baseline."""
        from deeplearning4j_tpu.lint.cli import run
        try:
            run(["deeplearning4j_tpu/nn", "--write-baseline",
                 "--no-consistency"])
        except SystemExit as e:
            assert e.code == 2
        else:
            raise AssertionError("subset --write-baseline must be refused")
        assert "subset" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# the gate run: whole repo vs the committed baseline
# ---------------------------------------------------------------------------


class TestRepoIsClean:
    def test_rule_catalog_documented(self):
        """Every registered rule has an entry in docs/LINT.md."""
        from deeplearning4j_tpu.lint.rules_consistency import (
            CONSISTENCY_RULES)
        doc = open(os.path.join(REPO, "docs", "LINT.md")).read()
        for rule_id in set(AST_RULES) | set(CONSISTENCY_RULES):
            assert rule_id in doc, f"{rule_id} missing from docs/LINT.md"

    def test_repo_has_no_new_findings(self):
        """THE suite-time lint: deeplearning4j_tpu/, tools/, examples/
        against lint_baseline.json. A new footgun fails tier-1 here."""
        from deeplearning4j_tpu.lint.rules_consistency import run_consistency
        findings = lint_paths(["deeplearning4j_tpu", "tools", "examples"],
                              REPO)
        findings.extend(run_consistency(REPO))
        baseline = load_baseline(BASELINE)
        new, _fixed = diff_baseline(sorted(findings), baseline)
        assert new == [], "new lint findings:\n" + "\n".join(
            f.render() for f in new)

    def test_baseline_entries_all_still_real(self):
        """The baseline is debt, not decoration: every grandfathered entry
        must still correspond to a live finding (no stale padding)."""
        from deeplearning4j_tpu.lint.rules_consistency import run_consistency
        findings = lint_paths(["deeplearning4j_tpu", "tools", "examples"],
                              REPO)
        findings.extend(run_consistency(REPO))
        baseline = load_baseline(BASELINE)
        _new, fixed = diff_baseline(sorted(findings), baseline)
        assert fixed == [], (
            "baseline entries now fixed — shrink lint_baseline.json via "
            "`make lint-baseline`: " + ", ".join(fixed))

    def test_seeded_violation_fails_the_gate(self, tmp_path):
        """Acceptance criterion: a seeded footgun in a scratch fixture is
        caught as a NEW finding against the committed baseline."""
        bad = tmp_path / "scratch_violation.py"
        bad.write_text(textwrap.dedent("""
            import jax
            import numpy as np

            @jax.jit
            def step(x):
                return np.asarray(x).sum()
        """))
        findings = lint_source(bad.read_text(),
                               path=str(bad.relative_to(tmp_path)))
        baseline = load_baseline(BASELINE)
        new, _ = diff_baseline(findings, baseline)
        assert any(f.rule == "GL001" for f in new), \
            "seeded GL001 violation must surface as a new finding"

    def test_cli_json_contract(self):
        """tools/graftlint.py --json emits exactly one parsable JSON line
        and exits 0 on the clean repo — the gate/driver artifact contract."""
        proc = subprocess.run(
            [sys.executable, "tools/graftlint.py", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert proc.returncode == 0, proc.stdout + proc.stderr
        lines = [l for l in proc.stdout.splitlines() if l.strip()]
        assert len(lines) == 1
        rec = json.loads(lines[0])
        assert rec["tool"] == "graftlint" and rec["new"] == 0
