"""Round-5 TF dialect widening goldens: segment/scatter/linalg/image/math
tails (181 mappers total), each frozen from in-env TF and compared
elementwise — the reference's samediff-import-tensorflow test pattern
(SURVEY §5.4)."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.imports.tf_import import TensorflowImporter

from tests.test_tf_import import freeze


def check(model, spec_or_specs, feeds):
    specs = (spec_or_specs if isinstance(spec_or_specs, (list, tuple))
             else [spec_or_specs])
    gd, ins, outs = freeze(model, *specs)
    golden = model(*[tf.constant(f) for f in feeds])
    sd = TensorflowImporter().run_import(gd)
    got = sd.output(dict(zip(ins, feeds)), outs[0])[outs[0]]
    np.testing.assert_allclose(np.asarray(got), golden.numpy(),
                               rtol=1e-4, atol=1e-5)


R = np.random.RandomState(0)
X34 = R.randn(3, 4).astype(np.float32)
X44 = R.randn(4, 4).astype(np.float32)
SPEC34 = tf.TensorSpec([3, 4], tf.float32)
SPEC44 = tf.TensorSpec([4, 4], tf.float32)


class TestRound5TfOps:
    def test_segment_sum(self):
        check(lambda a: tf.math.segment_sum(a, tf.constant([0, 0, 1])),
              SPEC34, [X34])

    def test_segment_mean(self):
        check(lambda a: tf.math.segment_mean(a, tf.constant([0, 1, 1])),
              SPEC34, [X34])

    def test_unsorted_segment_sum(self):
        check(lambda a: tf.math.unsorted_segment_sum(
            a, tf.constant([1, 0, 1]), 2), SPEC34, [X34])

    def test_scatter_nd(self):
        check(lambda a: tf.scatter_nd(tf.constant([[0], [2]]), a[:2],
                                      tf.constant([5, 4])), SPEC34, [X34])

    def test_tensor_scatter_update(self):
        check(lambda a: tf.tensor_scatter_nd_update(
            a, tf.constant([[0, 1]]), tf.constant([9.0])), SPEC34, [X34])

    def test_tensor_scatter_add(self):
        check(lambda a: tf.tensor_scatter_nd_add(
            a, tf.constant([[1, 2]]), tf.constant([3.0])), SPEC34, [X34])

    def test_reverse_roll(self):
        check(lambda a: tf.roll(tf.reverse(a, axis=[1]), shift=[1], axis=[0]),
              SPEC34, [X34])

    def test_matrix_band_part_inverse(self):
        check(lambda a: tf.linalg.inv(
            tf.linalg.band_part(a @ tf.transpose(a), 4, 4)
            + 3.0 * tf.eye(4)), SPEC44, [X44])

    def test_matrix_diag_and_set_diag(self):
        check(lambda a: tf.linalg.set_diag(
            a, tf.zeros(4)) + tf.linalg.diag(tf.ones(4)), SPEC44, [X44])

    def test_special_functions(self):
        check(lambda a: tf.math.lgamma(tf.abs(a) + 1.0)
              + tf.math.digamma(tf.abs(a) + 2.0), SPEC34, [X34])

    def test_betainc_igamma(self):
        b = np.abs(X34) + 0.5
        check(lambda a: tf.math.betainc(
            tf.constant(b), tf.constant(b), tf.clip_by_value(tf.abs(a), 0.1, 0.9)),
            SPEC34, [X34])

    def test_histogram_fixed_width(self):
        check(lambda a: tf.histogram_fixed_width(a, [-3.0, 3.0], nbins=5),
              SPEC34, [X34])

    def test_extract_image_patches(self):
        x = R.randn(1, 4, 4, 2).astype(np.float32)
        check(lambda a: tf.image.extract_patches(
            a, sizes=[1, 2, 2, 1], strides=[1, 2, 2, 1],
            rates=[1, 1, 1, 1], padding="VALID"),
            tf.TensorSpec([1, 4, 4, 2], tf.float32), [x])

    def test_in_top_k(self):
        check(lambda a: tf.cast(tf.math.in_top_k(
            tf.constant([1, 0, 2]), a, 2), tf.float32), SPEC34, [X34])

    def test_bincount_raw(self):
        check(lambda a: tf.raw_ops.Bincount(
            arr=tf.constant([0, 1, 1, 3]), size=tf.constant(5),
            weights=tf.constant([], tf.int32))
            + tf.cast(tf.reduce_sum(a) * 0, tf.int32), SPEC34, [X34])

    def test_crop_and_resize(self):
        x = R.rand(1, 6, 6, 2).astype(np.float32)
        check(lambda a: tf.image.crop_and_resize(
            a, tf.constant([[0.0, 0.0, 0.5, 0.5]]), tf.constant([0]),
            tf.constant([3, 3])), tf.TensorSpec([1, 6, 6, 2], tf.float32),
            [x])

    def test_qr_multi_output(self):
        # Qr emits two outputs (q, r); reconstruct to compare one tensor
        def model(a):
            q, r_ = tf.linalg.qr(a)
            return q @ r_
        check(model, SPEC44, [X44])

    def test_mapper_count_ratchet(self):
        from deeplearning4j_tpu.imports.tf_import import TF_OP_MAPPERS
        assert len(TF_OP_MAPPERS) >= 180


class TestRound5MapperEdgeCases:
    """Regression tests for the review-found mapper bugs."""

    def test_listdiff_preserves_order_and_duplicates(self):
        check(lambda a: tf.raw_ops.ListDiff(
            x=tf.constant([3, 1, 2, 3]), y=tf.constant([2]))[0]
            + tf.cast(tf.reduce_sum(a) * 0, tf.int32), SPEC34, [X34])

    def test_tf1_reverse_bool_mask(self):
        check(lambda a: tf.raw_ops.Reverse(
            tensor=a, dims=tf.constant([True, False])), SPEC34, [X34])

    def test_matrix_diag_padded_shape(self):
        check(lambda a: tf.linalg.diag(tf.ones(3), num_rows=3, num_cols=6)
              + tf.cast(tf.reduce_sum(a) * 0, tf.float32), SPEC34, [X34])

    def test_bincount_binary_output(self):
        check(lambda a: tf.raw_ops.DenseBincount(
            input=tf.constant([0, 1, 1, 3]), size=tf.constant(5),
            weights=tf.constant([], tf.int32), binary_output=True)
            + tf.cast(tf.reduce_sum(a) * 0, tf.int32), SPEC34, [X34])

    def test_weighted_bincount_rejected(self):
        def model(a):
            return tf.raw_ops.Bincount(
                arr=tf.constant([0, 1]), size=tf.constant(3),
                weights=tf.cast(a[0, :2], tf.float32))
        gd, ins, outs = freeze(model, SPEC34)
        with pytest.raises(NotImplementedError, match="weighted bincount"):
            TensorflowImporter().run_import(gd)


class TestRound5LinalgConv3dRandom:
    def test_svd_reconstruction(self):
        def model(a):
            s, u, v = tf.linalg.svd(a)
            return u @ tf.linalg.diag(s) @ tf.transpose(v)
        # reconstruction is unique even though (u, v) signs are not
        check(model, SPEC44, [X44])

    def test_triangular_solve(self):
        def model(a):
            lower = tf.linalg.band_part(a, -1, 0) + 4.0 * tf.eye(4)
            return tf.linalg.triangular_solve(lower, a, lower=True)
        check(model, SPEC44, [X44])

    def test_cross(self):
        x3 = R.randn(4, 3).astype(np.float32)
        check(lambda a: tf.linalg.cross(a, a[::-1]),
              tf.TensorSpec([4, 3], tf.float32), [x3])

    def test_conv3d(self):
        x = R.randn(1, 4, 6, 6, 2).astype(np.float32)
        w = R.randn(2, 3, 3, 2, 4).astype(np.float32) * 0.2
        check(lambda a: tf.nn.conv3d(a, tf.constant(w),
                                     strides=[1, 1, 2, 2, 1], padding="SAME"),
              tf.TensorSpec([1, 4, 6, 6, 2], tf.float32), [x])

    def test_eigh(self):
        def model(a):
            sym = a @ tf.transpose(a)
            e, v = tf.linalg.eigh(sym)
            return v @ tf.linalg.diag(e) @ tf.transpose(v)  # reconstruction
        check(model, SPEC44, [X44])

    def test_random_shapes_and_determinism(self):
        # stateful TF randoms import as a FIXED seeded stream (documented
        # static-graph semantics) — assert shape and run-to-run determinism
        def model(a):
            return a + tf.random.normal([3, 4], seed=7)
        gd, ins, outs = freeze(model, SPEC34)
        sd = TensorflowImporter().run_import(gd)
        o1 = np.asarray(sd.output({ins[0]: X34}, outs[0])[outs[0]])
        o2 = np.asarray(sd.output({ins[0]: X34}, outs[0])[outs[0]])
        assert o1.shape == (3, 4)
        np.testing.assert_array_equal(o1, o2)

    def test_distinct_seeds_give_distinct_streams(self):
        # review regression: seed/seed2 must COMBINE (tf puts the per-op
        # seed in seed2; first-nonzero collapsed all ops to one stream)
        def model(a):
            return (a + tf.random.normal([3, 4], seed=7)
                    - tf.random.normal([3, 4], seed=8))
        gd, ins, outs = freeze(model, SPEC34)
        sd = TensorflowImporter().run_import(gd)
        out = np.asarray(sd.output({ins[0]: X34}, outs[0])[outs[0]])
        # if both streams collapsed, out == X34 exactly
        assert np.abs(out - X34).max() > 1e-3

    def test_lu_pivots_tf_convention(self):
        def model(a):
            lu_, p = tf.linalg.lu(a @ tf.transpose(a) + 4.0 * tf.eye(4))
            return tf.cast(p, tf.float32) + tf.reduce_sum(lu_) * 0.0
        check(model, SPEC44, [X44])


class TestSoftmaxXent:
    # the raw ops are what frozen training graphs carry (the python
    # wrappers add dynamic Rank/Slice scaffolding that freezes poorly)
    def test_softmax_xent_loss(self):
        labels = np.eye(4)[[0, 2, 1]].astype(np.float32)
        check(lambda a: tf.raw_ops.SoftmaxCrossEntropyWithLogits(
            features=a, labels=tf.constant(labels))[0], SPEC34, [X34])

    def test_sparse_softmax_xent_loss(self):
        check(lambda a: tf.raw_ops.SparseSoftmaxCrossEntropyWithLogits(
            features=a, labels=tf.constant([0, 2, 1], tf.int32))[0],
            SPEC34, [X34])

    def test_xent_backprop_output(self):
        # output :1 is the gradient training-graph freezes consume
        labels = np.eye(4)[[0, 2, 1]].astype(np.float32)
        check(lambda a: tf.raw_ops.SoftmaxCrossEntropyWithLogits(
            features=a, labels=tf.constant(labels))[1], SPEC34, [X34])


class TestImageAndDynamicOps:
    def test_adjust_and_hsv_chain(self):
        x = R.rand(1, 4, 4, 3).astype(np.float32)
        check(lambda a: tf.image.hsv_to_rgb(tf.image.rgb_to_hsv(
            tf.image.adjust_saturation(tf.image.adjust_contrast(a, 1.3),
                                       0.8))),
            tf.TensorSpec([1, 4, 4, 3], tf.float32), [x])

    def test_resize_bicubic(self):
        x = R.rand(1, 4, 4, 2).astype(np.float32)
        check(lambda a: tf.image.resize(a, [8, 8], method="bicubic"),
              tf.TensorSpec([1, 4, 4, 2], tf.float32), [x])

    def test_sparse_stitch_rejected(self):
        def model(a):
            return tf.dynamic_stitch(
                [tf.constant([0, 3])], [a[:2]])  # sparse: skips 1, 2
        gd, ins, outs = freeze(model, SPEC34)
        with pytest.raises(NotImplementedError, match="dense permutation"):
            TensorflowImporter().run_import(gd)

    def test_dynamic_stitch(self):
        # interleave two row sets by explicit index lists (the static-shape
        # form; DynamicPartition itself is a documented reject)
        def model(a):
            return tf.dynamic_stitch(
                [tf.constant([0, 2]), tf.constant([1])], [a[:2], a[2:]])
        check(model, SPEC34, [X34])

    def test_dynamic_partition_rejected(self):
        def model(a):
            return tf.dynamic_partition(a, tf.constant([0, 1, 0]), 2)[0]
        gd, ins, outs = freeze(model, SPEC34)
        with pytest.raises(NotImplementedError, match="DynamicPartition"):
            TensorflowImporter().run_import(gd)
