"""Pipeline (pp) and expert (ep) parallelism tests on the virtual 8-device
CPU mesh — the remaining axes of the tp/pp/dp/sp/ep multichip contract.
Both compare against dense single-device oracles."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from deeplearning4j_tpu.parallel.pipeline import (
    PipelineParallelTrainer, pipeline_forward, stack_stage_params)
from deeplearning4j_tpu.parallel.moe import (init_moe_params, moe_forward)


def _mesh(n, axis):
    devs = jax.devices()[:n]
    return Mesh(np.array(devs), (axis,))


def stage_fn(p, x):
    return jnp.tanh(x @ p["W"] + p["b"])


class TestPipelineParallel:
    def _params(self, n_stages, d, seed=0):
        r = np.random.RandomState(seed)
        return [{"W": jnp.asarray(r.randn(d, d).astype(np.float32) * 0.5),
                 "b": jnp.asarray(r.randn(d).astype(np.float32) * 0.1)}
                for _ in range(n_stages)]

    def test_forward_matches_sequential(self):
        n_stages, d, batch = 4, 8, 16
        mesh = _mesh(n_stages, "pipe")
        per_stage = self._params(n_stages, d)
        stacked = stack_stage_params(per_stage)
        r = np.random.RandomState(1)
        x = jnp.asarray(r.randn(batch, d).astype(np.float32))

        fwd = pipeline_forward(stage_fn, mesh, num_microbatches=4)
        got = np.asarray(jax.jit(fwd)(stacked, x))

        want = x
        for p in per_stage:
            want = stage_fn(p, want)
        np.testing.assert_allclose(got, np.asarray(want), rtol=1e-5,
                                   atol=1e-5)

    def test_microbatch_count_invariance(self):
        n_stages, d, batch = 2, 6, 12
        mesh = _mesh(n_stages, "pipe")
        stacked = stack_stage_params(self._params(n_stages, d, seed=2))
        x = jnp.asarray(np.random.RandomState(3)
                        .randn(batch, d).astype(np.float32))
        outs = []
        for m in (2, 3, 6):
            fwd = pipeline_forward(stage_fn, mesh, num_microbatches=m)
            outs.append(np.asarray(fwd(stacked, x)))
        np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(outs[0], outs[2], rtol=1e-5, atol=1e-6)

    def test_training_through_the_pipeline(self):
        n_stages, d, batch = 4, 8, 16
        mesh = _mesh(n_stages, "pipe")
        stacked = stack_stage_params(self._params(n_stages, d, seed=4))
        r = np.random.RandomState(5)
        head = {"Wo": jnp.asarray(r.randn(d, 1).astype(np.float32) * 0.3)}
        x = jnp.asarray(r.randn(batch, d).astype(np.float32))
        y = jnp.asarray(r.randn(batch, 1).astype(np.float32))

        def head_fn(hp, feats, yy):
            return jnp.mean((feats @ hp["Wo"] - yy) ** 2)

        tr = PipelineParallelTrainer(stage_fn, head_fn, mesh,
                                     num_microbatches=4)
        tr.init_params(stacked, head)
        step = tr.make_train_step(lr=0.05)
        opt = tr.opt_state
        losses = []
        for i in range(15):
            stacked, head, opt, loss = step(
                stacked, head, opt, jnp.asarray(i, jnp.int32), x, y)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7, losses


class TestExpertParallel:
    def test_moe_matches_dense_oracle(self):
        ep, d, h = 4, 8, 16
        n_experts = 8
        tokens = 64  # 16 per device
        mesh = _mesh(ep, "expert")
        params = init_moe_params(jax.random.key(0), n_experts, d, h)
        r = np.random.RandomState(0)
        x = jnp.asarray(r.randn(tokens, d).astype(np.float32))

        # capacity_factor huge -> no drops -> dense oracle applies exactly
        fwd = moe_forward(mesh, n_experts=n_experts, capacity_factor=64.0)
        y, aux = jax.jit(fwd)(params, x)
        y = np.asarray(y)

        xn = np.asarray(x)
        router = np.asarray(params["router"])
        logits = xn @ router
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        eidx = probs.argmax(-1)
        gate = probs[np.arange(tokens), eidx]
        W1, W2 = np.asarray(params["W1"]), np.asarray(params["W2"])
        want = np.stack([
            gate[t] * (np.maximum(xn[t] @ W1[eidx[t]], 0) @ W2[eidx[t]])
            for t in range(tokens)])
        np.testing.assert_allclose(y, want, rtol=1e-4, atol=1e-4)
        assert float(aux) > 0.9  # ~1 at uniform routing

    def test_capacity_drops_pass_through(self):
        ep, d, h = 4, 4, 8
        n_experts = 4
        tokens = 32
        mesh = _mesh(ep, "expert")
        params = init_moe_params(jax.random.key(1), n_experts, d, h)
        # force ALL tokens to expert 0: biased router column
        params = dict(params)
        router = np.zeros((d, n_experts), np.float32)
        router[:, 0] = 10.0
        params["router"] = jnp.asarray(router)
        x = jnp.asarray(np.random.RandomState(1)
                        .rand(tokens, d).astype(np.float32))
        fwd = moe_forward(mesh, n_experts=n_experts, capacity_factor=1.0)
        y, aux = fwd(params, x)
        y = np.asarray(y)
        # capacity per device = ceil(1.0 * 8 / 4) = 2 -> 2 of 8 local
        # tokens routed per device, the rest pass through unchanged
        xn = np.asarray(x)
        passed_through = np.isclose(y, xn, atol=1e-6).all(axis=1).sum()
        assert passed_through >= tokens // 2, passed_through
        assert float(aux) > 1.0  # heavy imbalance -> big aux loss

    def test_gradients_flow(self):
        ep, d, h = 2, 6, 8
        mesh = _mesh(ep, "expert")
        params = init_moe_params(jax.random.key(2), 2, d, h)
        x = jnp.asarray(np.random.RandomState(2)
                        .randn(16, d).astype(np.float32))
        fwd = moe_forward(mesh, n_experts=2, capacity_factor=8.0)

        def loss(p):
            y, aux = fwd(p, x)
            return jnp.mean(y ** 2) + 0.01 * aux

        g = jax.grad(loss)(params)
        for k, v in g.items():
            assert np.isfinite(np.asarray(v)).all(), k
            assert np.abs(np.asarray(v)).max() > 0, k
