"""Arbiter-role hyperparameter search tests (SURVEY §2 Arbiter module):
parameter spaces, grid/random generators, the local runner with
termination conditions, and an end-to-end search that actually separates
good from bad learning rates on a toy problem."""

import numpy as np
import pytest

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.arbiter import (
    ContinuousParameterSpace, DiscreteParameterSpace,
    GridSearchCandidateGenerator, IntegerParameterSpace,
    LocalOptimizationRunner, RandomSearchGenerator, evaluation_score)
from deeplearning4j_tpu.arbiter import test_set_loss_score as loss_score_fn
from deeplearning4j_tpu.datasets.dataset import DataSet


class TestParameterSpaces:
    def test_continuous_bounds_and_log(self):
        r = np.random.RandomState(0)
        sp = ContinuousParameterSpace(0.1, 10.0, log=True)
        vals = [sp.sample(r) for _ in range(200)]
        assert all(0.1 <= v <= 10.0 for v in vals)
        # log sampling: ~half the draws under the geometric mean 1.0
        frac = sum(v < 1.0 for v in vals) / len(vals)
        assert 0.3 < frac < 0.7
        g = sp.grid(3)
        np.testing.assert_allclose(g, [0.1, 1.0, 10.0], rtol=1e-6)

    def test_integer_and_discrete(self):
        r = np.random.RandomState(1)
        isp = IntegerParameterSpace(2, 5)
        assert set(isp.sample(r) for _ in range(100)) == {2, 3, 4, 5}
        assert isp.grid(4) == [2, 3, 4, 5]
        dsp = DiscreteParameterSpace("adam", "sgd")
        assert set(dsp.grid(7)) == {"adam", "sgd"}


class TestGenerators:
    def test_grid_cartesian_product(self):
        gen = GridSearchCandidateGenerator(
            {"lr": ContinuousParameterSpace(0.1, 0.3),
             "width": DiscreteParameterSpace(4, 8),
             "fixed": "relu"}, discretization=3)
        combos = list(gen)
        assert len(combos) == 3 * 2
        assert all(c["fixed"] == "relu" for c in combos)
        assert {c["width"] for c in combos} == {4, 8}

    def test_random_respects_bounds(self):
        gen = iter(RandomSearchGenerator(
            {"lr": ContinuousParameterSpace(1e-4, 1e-1, log=True),
             "n": IntegerParameterSpace(1, 3)}, seed=7))
        for _ in range(20):
            c = next(gen)
            assert 1e-4 <= c["lr"] <= 1e-1
            assert c["n"] in (1, 2, 3)


def _toy_data(seed=0, n=128):
    r = np.random.RandomState(seed)
    x = r.randn(n, 4).astype(np.float32)
    w = np.array([[1.0, -1.0], [2.0, 0.5], [-1.5, 1.0], [0.5, -0.5]],
                 np.float32)
    y = (x @ w).argmax(axis=1)
    return [DataSet(x, np.eye(2, dtype=np.float32)[y])]


def _builder(params):
    return nn.MultiLayerNetwork(
        nn.builder().seed(3)
        .updater(nn.Sgd(learning_rate=params["lr"])).list()
        .layer(nn.DenseLayer(n_out=params.get("width", 8), activation="tanh"))
        .layer(nn.OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(nn.InputType.feed_forward(4)).build()).init()


class TestRunner:
    def test_search_separates_learning_rates(self):
        train = _toy_data(0)
        heldout = _toy_data(1)
        runner = LocalOptimizationRunner(
            _builder,
            GridSearchCandidateGenerator(
                {"lr": DiscreteParameterSpace(1e-5, 0.3), "width": 8}),
            train_data=train, score_data=heldout,
            score_fn=loss_score_fn, epochs=30, max_candidates=4)
        best = runner.execute()
        assert len(runner.results) == 2
        assert best.parameters["lr"] == pytest.approx(0.3)
        worst = max(runner.results, key=lambda r: r.score)
        assert best.score < worst.score * 0.9  # a REAL separation

    def test_max_candidates_condition(self):
        runner = LocalOptimizationRunner(
            _builder,
            RandomSearchGenerator({"lr": ContinuousParameterSpace(0.01, 0.1),
                                   "width": 4}, seed=0),
            train_data=_toy_data(), epochs=1, max_candidates=3)
        runner.execute()
        assert len(runner.results) == 3

    def test_evaluation_score_function(self):
        train = _toy_data(0)
        runner = LocalOptimizationRunner(
            _builder,
            GridSearchCandidateGenerator(
                {"lr": DiscreteParameterSpace(0.2), "width": 8}),
            train_data=train, score_fn=evaluation_score("accuracy"),
            epochs=30, max_candidates=1)
        best = runner.execute()
        assert -1.0 <= best.score <= -0.8  # negated accuracy, near 1.0
