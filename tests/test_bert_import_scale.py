"""Round-5 verdict item 7: full-scale importer stress test.

Generates a 12-layer BERT-base-SIZED SavedModel in-env (D=768, 12 heads,
FF=3072, random weights, vocab trimmed to keep the file reasonable),
imports it through the public SavedModel path, runs one fine-tune step,
exports StableHLO, and asserts the whole thing stays under a CI-sane wall
budget. This proves the import machinery at the scale BASELINE config[3]
names, not the D=32 toy of TestBertSavedModelFinetune (which verifies
numerics; this one verifies SCALE: 12-deep function inlining, ~100M-param
variable restore, compile-time behavior)."""

import time

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

D, HEADS, FF, LAYERS, T, VOCAB = 768, 12, 3072, 12, 64, 4096
BUDGET_S = 300.0  # <5 min on the CPU mesh (verdict's sane-budget gate)


def _build_bert_base():
    class Block(tf.Module):
        def __init__(self, r, i):
            super().__init__()

            def g(name, *s):
                return tf.Variable(r.randn(*s).astype(np.float32) * 0.02,
                                   name=f"l{i}_{name}")

            self.wq, self.wk = g("wq", D, D), g("wk", D, D)
            self.wv, self.wo = g("wv", D, D), g("wo", D, D)
            self.ln1_g = tf.Variable(np.ones(D, np.float32), name=f"l{i}_ln1g")
            self.ln1_b = tf.Variable(np.zeros(D, np.float32), name=f"l{i}_ln1b")
            self.w1 = g("w1", D, FF)
            self.b1 = tf.Variable(np.zeros(FF, np.float32), name=f"l{i}_b1")
            self.w2 = g("w2", FF, D)
            self.b2 = tf.Variable(np.zeros(D, np.float32), name=f"l{i}_b2")
            self.ln2_g = tf.Variable(np.ones(D, np.float32), name=f"l{i}_ln2g")
            self.ln2_b = tf.Variable(np.zeros(D, np.float32), name=f"l{i}_ln2b")

    class BertBase(tf.Module):
        def __init__(self):
            super().__init__()
            r = np.random.RandomState(0)
            self.emb = tf.Variable(r.randn(VOCAB, D).astype(np.float32) * 0.02,
                                   name="emb")
            self.pos = tf.Variable(r.randn(T, D).astype(np.float32) * 0.02,
                                   name="pos")
            self.blocks = [Block(r, i) for i in range(LAYERS)]
            self.cls_w = tf.Variable(r.randn(D, 2).astype(np.float32) * 0.02,
                                     name="cls_w")
            self.cls_b = tf.Variable(np.zeros(2, np.float32), name="cls_b")

        @staticmethod
        def ln(x, gv, bv):
            m = tf.reduce_mean(x, axis=-1, keepdims=True)
            v = tf.reduce_mean(tf.square(x - m), axis=-1, keepdims=True)
            return (x - m) * tf.math.rsqrt(v + 1e-6) * gv + bv

        @tf.function(input_signature=[tf.TensorSpec([None, T], tf.int32)])
        def __call__(self, ids):
            x = tf.gather(self.emb, ids) + self.pos
            hd = D // HEADS
            for blk in self.blocks:
                def split(t):
                    s = tf.shape(t)
                    return tf.transpose(
                        tf.reshape(t, [s[0], T, HEADS, hd]), [0, 2, 1, 3])

                q = split(x @ blk.wq)
                k = split(x @ blk.wk)
                v = split(x @ blk.wv)
                scores = tf.einsum("bhqd,bhkd->bhqk", q, k) / \
                    np.sqrt(hd).astype(np.float32)
                att = tf.einsum("bhqk,bhkd->bhqd",
                                tf.nn.softmax(scores, axis=-1), v)
                att = tf.reshape(tf.transpose(att, [0, 2, 1, 3]),
                                 [tf.shape(x)[0], T, D])
                x = BertBase.ln(x + att @ blk.wo, blk.ln1_g, blk.ln1_b)
                h = tf.nn.gelu(x @ blk.w1 + blk.b1)
                x = BertBase.ln(x + h @ blk.w2 + blk.b2,
                                blk.ln2_g, blk.ln2_b)
            return tf.nn.softmax(x[:, 0] @ self.cls_w + self.cls_b)

    return BertBase()


class TestBertBaseScaleImport:
    def test_import_finetune_export_under_budget(self, tmp_path):
        from deeplearning4j_tpu import nn
        from deeplearning4j_tpu.autodiff.samediff import TrainingConfig
        from deeplearning4j_tpu.datasets.dataset import (
            DataSet, ListDataSetIterator)
        from deeplearning4j_tpu.imports.tf_import import import_saved_model

        t_start = time.perf_counter()
        m = _build_bert_base()
        path = str(tmp_path / "bert_base")
        tf.saved_model.save(m, path)
        t_saved = time.perf_counter()

        sd = import_saved_model(path)
        t_import = time.perf_counter()
        # ~85M transformer params restored (12 deep x (4D^2 + 2*D*FF) + emb)
        n_params = sum(int(np.asarray(v).size)
                       for v in sd._arrays.values())
        assert n_params > 60e6, f"only {n_params/1e6:.1f}M params restored"

        rng = np.random.RandomState(1)
        ids = rng.randint(0, VOCAB, (2, T)).astype(np.int32)
        golden = m(tf.constant(ids)).numpy()
        got = sd.output({sd.graph_inputs[0]: ids},
                        sd.graph_outputs[0])[sd.graph_outputs[0]]
        np.testing.assert_allclose(got, golden, rtol=5e-2, atol=2e-3)
        t_forward = time.perf_counter()

        # one fine-tune step through the standard TrainingConfig path
        labels = sd.placeholder("labels", shape=(None, 2))
        out_var = sd._vars[sd.graph_outputs[0]]
        sd.loss.mean_squared_error(out_var, labels).rename("ft_loss")
        sd.set_training_config(TrainingConfig(
            updater=nn.Adam(learning_rate=1e-4),
            data_set_feature_mapping=[sd.graph_inputs[0]],
            data_set_label_mapping=["labels"],
            loss_variables=["ft_loss"]))
        ys = np.eye(2, dtype=np.float32)[ids[:, 0] % 2]
        hist = sd.fit(ListDataSetIterator(DataSet(ids, ys), batch_size=2),
                      epochs=1)
        assert np.isfinite(hist[-1])
        t_step = time.perf_counter()

        hlo = sd.as_stablehlo({sd.graph_inputs[0]: ids},
                              [sd.graph_outputs[0]])
        assert "stablehlo" in hlo or "func.func" in hlo
        t_end = time.perf_counter()

        total = t_end - t_start
        print(f"\nbert-base-scale import: save {t_saved - t_start:.1f}s, "
              f"import {t_import - t_saved:.1f}s, "
              f"fwd+compile {t_forward - t_import:.1f}s, "
              f"train step {t_step - t_forward:.1f}s, "
              f"stablehlo {t_end - t_step:.1f}s, total {total:.1f}s")
        assert total < BUDGET_S, f"{total:.1f}s exceeds the {BUDGET_S:.0f}s budget"
