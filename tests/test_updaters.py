"""Updater math vs numpy oracles — the reference's UpdaterTest.java analog
(nd4j tests assert exact update values per updater)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nn import updater as U


def run_updater(upd, grads, param_shape=(4,)):
    """Apply a sequence of gradients, return list of updates."""
    import jax.numpy as jnp

    p = jnp.zeros(param_shape)
    s = upd.init_state(p)
    outs = []
    for t, g in enumerate(grads):
        lr = upd.lr(t)
        u, s = upd.apply(jnp.asarray(g), s, lr, t)
        outs.append(np.asarray(u))
    return outs


class TestUpdaterMath:
    def test_sgd(self):
        g = np.array([1.0, -2.0, 3.0, 0.0], np.float32)
        (u,) = run_updater(U.Sgd(learning_rate=0.5), [g])
        np.testing.assert_allclose(u, 0.5 * g)

    def test_adam_first_step(self):
        g = np.array([1.0, 2.0, -1.0, 0.5], np.float32)
        upd = U.Adam(learning_rate=1e-3, beta1=0.9, beta2=0.999, epsilon=1e-8)
        (u,) = run_updater(upd, [g])
        m = 0.1 * g
        v = 0.001 * g * g
        alpha = 1e-3 * np.sqrt(1 - 0.999) / (1 - 0.9)
        np.testing.assert_allclose(u, alpha * m / (np.sqrt(v) + 1e-8), rtol=1e-5)

    def test_adam_two_steps_against_oracle(self):
        rng = np.random.RandomState(0)
        gs = [rng.randn(4).astype(np.float32) for _ in range(3)]
        upd = U.Adam(learning_rate=0.01)
        outs = run_updater(upd, gs)
        m = np.zeros(4)
        v = np.zeros(4)
        for t, (g, u) in enumerate(zip(gs, outs), start=1):
            m = 0.9 * m + 0.1 * g
            v = 0.999 * v + 0.001 * g * g
            alpha = 0.01 * np.sqrt(1 - 0.999**t) / (1 - 0.9**t)
            np.testing.assert_allclose(u, alpha * m / (np.sqrt(v) + 1e-8), rtol=1e-4)

    def test_nesterovs(self):
        g = np.array([1.0, 1.0], np.float32)
        upd = U.Nesterovs(learning_rate=0.1, momentum=0.9)
        outs = run_updater(upd, [g, g], param_shape=(2,))
        # t0: vPrev=0, v=-0.1g; update = -(0 - 1.9*(-0.1g)) = -0.19g → params -= -0.19g?
        # reference: update = mu*vPrev - (1+mu)*v = 0 - 1.9*(-0.1) = 0.19 (params += 0.19·(-g)… )
        # our convention: params -= update, so update must be +0.19*g-direction *down*:
        np.testing.assert_allclose(outs[0], 0.19 * g, rtol=1e-5)

    def test_rmsprop(self):
        g = np.array([2.0], np.float32)
        upd = U.RmsProp(learning_rate=0.1, rms_decay=0.95, epsilon=1e-8)
        (u,) = run_updater(upd, [g], param_shape=(1,))
        g2 = 0.95 * 1e-8 + 0.05 * 4.0
        np.testing.assert_allclose(u, 0.1 * 2.0 / np.sqrt(g2 + 1e-8), rtol=1e-5)

    def test_adagrad(self):
        g = np.array([3.0], np.float32)
        upd = U.AdaGrad(learning_rate=0.1, epsilon=1e-6)
        (u,) = run_updater(upd, [g], param_shape=(1,))
        h = 1e-6 + 9.0
        np.testing.assert_allclose(u, 0.1 * 3.0 / (np.sqrt(h) + 1e-6), rtol=1e-5)

    def test_adadelta_lr_free(self):
        g = np.array([1.0], np.float32)
        upd = U.AdaDelta(rho=0.95, epsilon=1e-6)
        (u,) = run_updater(upd, [g], param_shape=(1,))
        msg = 0.05
        np.testing.assert_allclose(
            u, np.sqrt(1e-6) / np.sqrt(msg + 1e-6) * 1.0, rtol=1e-4)

    def test_amsgrad_monotone_vhat(self):
        gs = [np.array([3.0], np.float32), np.array([0.1], np.float32)]
        outs = run_updater(U.AmsGrad(learning_rate=0.1), gs, param_shape=(1,))
        assert np.isfinite(outs).all()

    def test_all_updaters_run(self):
        g = np.random.RandomState(1).randn(5).astype(np.float32)
        for name, cls in U.UPDATERS.items():
            outs = run_updater(cls(), [g, g], param_shape=(5,))
            assert np.isfinite(outs).all(), name


class TestSchedules:
    def test_step(self):
        s = U.StepSchedule(value=1.0, decay_rate=0.5, step=10)
        assert float(s(0)) == 1.0
        assert float(s(10)) == 0.5
        assert float(s(25)) == 0.25

    def test_exponential(self):
        s = U.ExponentialSchedule(value=2.0, gamma=0.9)
        assert float(s(0)) == pytest.approx(2.0)
        assert float(s(2)) == pytest.approx(2.0 * 0.81)

    def test_poly(self):
        s = U.PolySchedule(value=1.0, power=2.0, max_iter=100)
        assert float(s(0)) == pytest.approx(1.0)
        assert float(s(50)) == pytest.approx(0.25)
        assert float(s(100)) == pytest.approx(0.0)

    def test_inverse(self):
        s = U.InverseSchedule(value=1.0, gamma=1.0, power=1.0)
        assert float(s(1)) == pytest.approx(0.5)

    def test_map(self):
        s = U.MapSchedule(value=1.0, values=((10, 0.1), (20, 0.01)))
        assert float(s(5)) == pytest.approx(1.0)
        assert float(s(15)) == pytest.approx(0.1)
        assert float(s(30)) == pytest.approx(0.01)

    def test_sigmoid(self):
        s = U.SigmoidSchedule(value=1.0, gamma=0.01, step_size=100)
        assert float(s(100)) == pytest.approx(0.5)

    def test_schedule_json(self):
        for s in [U.StepSchedule(), U.ExponentialSchedule(), U.InverseSchedule(),
                  U.PolySchedule(), U.SigmoidSchedule(), U.CycleSchedule(),
                  U.MapSchedule(values=((5, 0.5),))]:
            s2 = U.Schedule.from_dict(s.to_dict())
            assert float(s2(7)) == pytest.approx(float(s(7)), rel=1e-6)
