"""FusedBottleneck (nn/fused_blocks.py) must equal the composed-layer
bottleneck graph: forward (train & eval), gradients (one fit step), and
running-stat updates. On the CPU mesh the fused layer runs the reference
(non-Pallas) chain — the Pallas path itself is pinned against the same
reference in test_perf_levers.py, so equality here covers both."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.nn.graph import ComputationGraph, ElementWiseVertex, graph_builder

from tests._helpers import _rng


def _composed(c_in, filters, stride, project, h, w, updater, dtype="float32"):
    s = (stride, stride)
    b = (graph_builder().seed(3).updater(updater).weight_init("relu")
         .dtype(dtype).add_inputs("input")
         .set_input_types(input=nn.InputType.convolutional(h, w, c_in)))
    b.add_layer("c1", nn.ConvolutionLayer(
        n_out=filters, kernel=(1, 1), stride=s, convolution_mode="same",
        activation="identity", has_bias=False), "input")
    b.add_layer("bn1", nn.BatchNormalization(activation="relu"), "c1")
    b.add_layer("c2", nn.ConvolutionLayer(
        n_out=filters, kernel=(3, 3), convolution_mode="same",
        activation="identity", has_bias=False), "bn1")
    b.add_layer("bn2", nn.BatchNormalization(activation="relu"), "c2")
    b.add_layer("c3", nn.ConvolutionLayer(
        n_out=4 * filters, kernel=(1, 1), convolution_mode="same",
        activation="identity", has_bias=False), "bn2")
    b.add_layer("bn3", nn.BatchNormalization(activation="identity"), "c3")
    if project:
        b.add_layer("sc", nn.ConvolutionLayer(
            n_out=4 * filters, kernel=(1, 1), stride=s, convolution_mode="same",
            activation="identity", has_bias=False), "input")
        b.add_layer("scbn", nn.BatchNormalization(activation="identity"), "sc")
        shortcut = "scbn"
    else:
        shortcut = "input"
    b.add_vertex("add", ElementWiseVertex(op="add"), "bn3", shortcut)
    b.add_layer("out", nn.ActivationLayer(activation="relu"), "add")
    b.add_layer("gap", nn.GlobalPoolingLayer(pooling_type="avg"), "out")
    b.add_layer("fc", nn.OutputLayer(n_out=3, activation="softmax",
                                     loss="mcxent"), "gap")
    b.set_outputs("fc")
    return ComputationGraph(b.build()).init()


def _fused(c_in, filters, stride, project, h, w, updater, dtype="float32"):
    b = (graph_builder().seed(3).updater(updater).weight_init("relu")
         .dtype(dtype).add_inputs("input")
         .set_input_types(input=nn.InputType.convolutional(h, w, c_in)))
    b.add_layer("block", nn.FusedBottleneck(
        filters=filters, stride=stride, project=project), "input")
    b.add_layer("gap", nn.GlobalPoolingLayer(pooling_type="avg"), "block")
    b.add_layer("fc", nn.OutputLayer(n_out=3, activation="softmax",
                                     loss="mcxent"), "gap")
    b.set_outputs("fc")
    return ComputationGraph(b.build()).init()


def _copy_weights(comp, fus, project):
    """Map composed-layer params into the fused layer's param dict."""
    p = {
        "W1": comp.params["c1"]["W"], "g1": comp.params["bn1"]["gamma"],
        "b1": comp.params["bn1"]["beta"],
        "W2": comp.params["c2"]["W"], "g2": comp.params["bn2"]["gamma"],
        "b2": comp.params["bn2"]["beta"],
        "W3": comp.params["c3"]["W"], "g3": comp.params["bn3"]["gamma"],
        "b3": comp.params["bn3"]["beta"],
    }
    if project:
        p["Wsc"] = comp.params["sc"]["W"]
        p["gsc"] = comp.params["scbn"]["gamma"]
        p["bsc"] = comp.params["scbn"]["beta"]
    fus.params = dict(fus.params)
    fus.params["block"] = jax.tree.map(jnp.array, p)
    fus.params["fc"] = jax.tree.map(jnp.array, comp.params["fc"])


CASES = [
    dict(c_in=8, filters=4, stride=1, project=True),
    dict(c_in=16, filters=4, stride=1, project=False),
    dict(c_in=8, filters=4, stride=2, project=True),
]


class TestFusedBottleneckEquality:
    @pytest.mark.parametrize("case", CASES)
    def test_train_forward_and_step(self, case):
        h = w = 8
        upd = nn.Sgd(learning_rate=0.05)
        comp = _composed(h=h, w=w, updater=upd, **case)
        fus = _fused(h=h, w=w, updater=upd, **case)
        _copy_weights(comp, fus, case["project"])
        r = _rng(0)
        x = r.randn(4, h, w, case["c_in"]).astype(np.float32)
        y = np.eye(3)[r.randint(0, 3, 4)].astype(np.float32)

        oc = comp.output(x)
        of = fus.output(x)
        np.testing.assert_allclose(of, oc, atol=2e-5)

        comp.fit(x, y)
        fus.fit(x, y)
        # post-step weights equal ⇒ gradients equal (incl. the BN stats term)
        for fk, (ln, pk) in {"W1": ("c1", "W"), "g1": ("bn1", "gamma"),
                             "b1": ("bn1", "beta"), "W2": ("c2", "W"),
                             "g3": ("bn3", "gamma"), "W3": ("c3", "W")}.items():
            np.testing.assert_allclose(
                np.asarray(fus.params["block"][fk]),
                np.asarray(comp.params[ln][pk]), atol=5e-5,
                err_msg=f"param {fk} diverged after one step")
        # running stats updated identically
        np.testing.assert_allclose(
            np.asarray(fus.net_state["block"]["m1"]),
            np.asarray(comp.net_state["bn1"]["mean"]), atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(fus.net_state["block"]["v2"]),
            np.asarray(comp.net_state["bn2"]["var"]), atol=1e-5)

        # eval-mode forward (uses running stats) must also agree
        oc2 = comp.output(x)
        of2 = fus.output(x)
        np.testing.assert_allclose(of2, oc2, atol=2e-5)

    def test_resnet50_fused_builds_and_runs(self):
        from deeplearning4j_tpu import models
        net = models.ResNet50(num_classes=5, input_shape=(32, 32, 3),
                              updater=nn.Sgd(learning_rate=0.01),
                              dtype="mixed", fused_blocks=True).init()
        assert any(isinstance(l.lc, nn.FusedBottleneck)
                   for l in net.layers.values())
        r = _rng(1)
        x = r.randn(2, 32, 32, 3).astype(np.float32)
        y = np.eye(5)[r.randint(0, 5, 2)].astype(np.float32)
        losses = net.fit_scanned(jnp.asarray(x), jnp.asarray(y), steps=3)
        assert np.all(np.isfinite(np.asarray(losses)))

    def test_json_roundtrip(self):
        from deeplearning4j_tpu.nn import conf as C
        lc = nn.FusedBottleneck(n_in=8, filters=4, stride=2, project=True)
        back = C.LayerConf.from_dict(lc.to_dict())
        assert back == lc
