"""GRAPH_OPS vs declarable-registry resolution order (round-5 verdict
item 4), pinned by regression tests.

The documented order is local -> GRAPH_OPS -> registry. Two collisions bit
the build historically:

* ``where``  — GRAPH_OPS jnp.where(cond, x, y) must win over the registry's
  legacy signature;
* ``shape_of``/``stack`` — must be ABSENT from GRAPH_OPS so their registry
  impls win, because those deliberately stay in NUMPY for un-traced shape
  chains (tf.shape -> Pack -> Reshape imports).
"""

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.autodiff.samediff import (
    GRAPH_OPS, REGISTRY_SHADOW_WHITELIST, resolve_graph_op)
from deeplearning4j_tpu.ops.registry import registry


class TestResolutionOrder:
    def test_local_ops_beat_graph_ops(self):
        sentinel = object()
        assert resolve_graph_op("where", {"where": sentinel}) is sentinel

    def test_where_resolves_to_graph_ops_jnp_where(self):
        """`where` IS a whitelisted shadow: jnp.where wins over the
        registry impl, with 3-arg broadcast semantics."""
        assert "where" in GRAPH_OPS and "where" in registry()
        assert "where" in REGISTRY_SHADOW_WHITELIST
        fn = resolve_graph_op("where")
        assert fn is GRAPH_OPS["where"]
        out = fn(jnp.asarray([True, False]), jnp.asarray([1.0, 2.0]),
                 jnp.asarray([9.0, 9.0]))
        np.testing.assert_array_equal(np.asarray(out), [1.0, 9.0])

    def test_shape_of_resolves_to_registry_numpy_impl(self):
        """`shape_of` must NOT be in GRAPH_OPS: the registry impl returns
        numpy so shape arithmetic stays trace-time concrete."""
        assert "shape_of" not in GRAPH_OPS
        fn = resolve_graph_op("shape_of")
        assert fn is registry().get("shape_of").fn
        out = fn(jnp.ones((2, 3)))
        assert isinstance(out, np.ndarray)
        np.testing.assert_array_equal(out, [2, 3])

    def test_stack_resolves_to_registry_numpy_preserving_impl(self):
        """`stack` must NOT be in GRAPH_OPS: the registry impl keeps host
        scalars in numpy for un-traced shape chains."""
        assert "stack" not in GRAPH_OPS
        fn = resolve_graph_op("stack")
        assert fn is registry().get("stack").fn
        out = fn(np.int32(2), np.int32(3), axis=0)
        assert isinstance(out, np.ndarray)
        np.testing.assert_array_equal(out, [2, 3])

    def test_unknown_op_raises_keyerror(self):
        try:
            resolve_graph_op("definitely_not_an_op")
        except KeyError as e:
            assert "definitely_not_an_op" in str(e)
        else:
            raise AssertionError("expected KeyError")


class TestWhitelistIsExact:
    """The graftlint GL006 invariant, also pinned here so a whitelist
    regression fails even when only this file runs."""

    def _shadowed(self):
        # importers mutate GRAPH_OPS at import time; settle the surface
        import deeplearning4j_tpu.imports.keras_import  # noqa: F401
        import deeplearning4j_tpu.imports.onnx_import   # noqa: F401
        import deeplearning4j_tpu.imports.tf_import     # noqa: F401
        return set(GRAPH_OPS) & set(registry().names())

    def test_every_shadow_is_whitelisted(self):
        unlisted = self._shadowed() - REGISTRY_SHADOW_WHITELIST
        assert unlisted == set(), (
            f"GRAPH_OPS keys silently shadowing registry ops: "
            f"{sorted(unlisted)} — whitelist with a justification or "
            f"delete the duplicate")

    def test_whitelist_has_no_stale_entries(self):
        stale = REGISTRY_SHADOW_WHITELIST - self._shadowed()
        assert stale == set(), (
            f"stale whitelist entries (no longer shadowed): {sorted(stale)}")
