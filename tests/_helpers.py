"""Shared test helpers (single definition for the layer/breadth suites)."""

import numpy as np

from deeplearning4j_tpu import nn


def _rng(seed=0):
    return np.random.RandomState(seed)


def _mln(layers, itype):
    b = nn.builder().seed(7).updater(nn.Sgd(learning_rate=0.1)).list()
    for lc in layers:
        b.layer(lc)
    return nn.MultiLayerNetwork(b.set_input_type(itype).build()).init()
