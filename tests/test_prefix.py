"""Radix prefix cache tests (docs/SERVING.md § Radix prefix cache).

Covers the properties the subsystem is built around:
  * refcounted allocator soundness — free XOR rc>=1 partition, exact
    slot+tree accounting, release-exactly-once under sharing (incl. a
    randomized alloc/share/free property test);
  * tree mechanics — per-page trie insert/match, partial tails, LRU leaf
    eviction under a budget, pool-pressure reclaim, pinning;
  * engine integration — greedy generation WITH prefix reuse is
    token-for-token identical to the cache-off oracle across mid-flight
    admits, evictions, copy-on-write divergence, and a supervisor
    restart (tree dropped cleanly, pin intents survive), with ZERO
    ``new_shape`` ledger events;
  * chaos — injected ``page_oom`` through the prefix admission path
    leaves every request terminal and the invariants intact;
  * frontend — ``ClassPolicy.shared_prefix`` pre-warms + pins.
"""

import numpy as np
import pytest

from deeplearning4j_tpu import faults, observe
from deeplearning4j_tpu.models.gpt import (
    GptConfig, GptModel, reference_generate,
)
from deeplearning4j_tpu.serving import (
    GenerativeEngine, PagedKVCache, RadixPrefixCache,
)

CFG = GptConfig.tiny()
MODEL = GptModel(CFG, seed=1)

SYS = np.arange(1, 12, dtype=np.int32)  # 11 tokens: 1 full page + 3 tail
                                        # at page_size=8


def make_engine(**kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_pages_per_seq", 6)
    kw.setdefault("max_prompt", 16)
    kw.setdefault("seed", 3)
    kw.setdefault("prefix_pages", 12)
    kw.setdefault("suffix_bucket", 8)
    return GenerativeEngine(MODEL, **kw)


def assert_oracle(prompt, res, n=None):
    n = len(res.tokens) if n is None else n
    np.testing.assert_array_equal(
        res.tokens, reference_generate(MODEL.params, CFG, prompt, n))


# ---------------------------------------------------------------------------
# refcounted allocator (satellite: check_invariants in the refcount era)
# ---------------------------------------------------------------------------


class TestRefcountAllocator:
    def make_cache(self, **kw):
        kw.setdefault("layers", 2)
        kw.setdefault("heads", 2)
        kw.setdefault("head_dim", 8)
        kw.setdefault("page_size", 4)
        kw.setdefault("num_pages", 8)
        kw.setdefault("max_slots", 3)
        kw.setdefault("max_pages_per_seq", 4)
        return PagedKVCache(**kw)

    def test_share_release_exactly_once(self):
        """Two slots share a page run; each free_slot releases once; the
        pages enter the free list exactly once (the satellite-6 double-
        free regression, pinned on the free-list counters)."""
        c = self.make_cache()
        assert c.ensure_capacity(0, 8) == "ok"  # 2 private pages
        run = list(c.owned[0])
        for p in run:
            c.map_shared(1, p)  # slot 1 shares slot 0's run
        c.check_invariants()
        assert c.refcount[run[0]] == 2
        free_before = c.free_pages
        c.free_slot(0)
        assert c.free_pages == free_before  # slot 1 still holds them
        c.check_invariants()
        c.free_slot(0)  # idempotent: nothing left to release
        assert c.free_pages == free_before
        c.free_slot(1)
        assert c.free_pages == c.num_pages
        for p in run:
            assert c.free.count(p) == 1, "page entered the free list twice"
        c.check_invariants()

    def test_retain_release_guards(self):
        c = self.make_cache()
        page = c.alloc_page()
        c.release(page)
        with pytest.raises(AssertionError, match="double free"):
            c.release(page)
        with pytest.raises(AssertionError, match="free list"):
            c.retain(page)

    def test_tree_refs_exact_accounting(self):
        c = self.make_cache()
        assert c.ensure_capacity(0, 4) == "ok"
        page = c.owned[0][0]
        c.retain(page)  # a "tree" reference
        c.check_invariants(tree_refs={page: 1})
        with pytest.raises(AssertionError, match="tree refs"):
            c.check_invariants(tree_refs={})  # rc 2 but only 1 slot holder
        c.free_slot(0)
        c.check_invariants(tree_refs={page: 1})
        c.release(page)
        c.check_invariants(tree_refs={})
        assert c.free_pages == c.num_pages

    def test_cow_page_copies_device_state(self):
        import jax.numpy as jnp

        c = self.make_cache()
        src = c.alloc_page()
        c.owned[0].append(src)
        c.page_table[0, 0] = src
        c.kv = c.kv.at[:, :, src].set(7.0)
        dst = c.cow_page(1, src)
        assert dst is not None and dst != src
        np.testing.assert_array_equal(np.asarray(c.kv[:, :, dst]),
                                      np.asarray(c.kv[:, :, src]))
        assert c.page_table[1, 0] == dst and c.owned[1] == [dst]
        c.check_invariants()
        c.kv = c.kv.at[:, :, dst].set(9.0)  # writes never alias the source
        assert float(jnp.max(jnp.abs(c.kv[:, :, src] - 7.0))) == 0.0

    def test_cow_page_pool_exhausted(self):
        c = self.make_cache(num_pages=1)
        src = c.alloc_page()
        assert c.cow_page(0, src) is None
        c.release(src)
        c.check_invariants()

    def test_randomized_alloc_share_free_property(self):
        """Satellite 1: random interleavings of grow/share/free/tree-
        retain/tree-release never break the partition or the exact
        refcount accounting."""
        r = np.random.RandomState(0)
        c = self.make_cache(num_pages=12, max_slots=4, max_pages_per_seq=5)
        tree: dict = {}  # page -> refs (the model "tree")
        for step in range(400):
            op = r.randint(5)
            slot = int(r.randint(c.max_slots))
            if op == 0:  # grow
                c.ensure_capacity(slot, int(r.randint(1, 21)))
            elif op == 1:  # free
                c.free_slot(slot)
            elif op == 2:  # share a live page into a slot with row room
                live = [p for o in c.owned for p in o] + list(tree)
                if live and len(c.owned[slot]) < c.max_pages_per_seq:
                    c.map_shared(slot, live[int(r.randint(len(live)))])
            elif op == 3:  # tree retains a live page
                live = [p for o in c.owned for p in o] + list(tree)
                if live:
                    p = live[int(r.randint(len(live)))]
                    c.retain(p)
                    tree[p] = tree.get(p, 0) + 1
            else:  # tree releases
                if tree:
                    p = list(tree)[int(r.randint(len(tree)))]
                    c.release(p)
                    tree[p] -= 1
                    if not tree[p]:
                        del tree[p]
            c.check_invariants(tree_refs=tree)
        for slot in range(c.max_slots):
            c.free_slot(slot)
        for p in list(tree):
            for _ in range(tree.pop(p)):
                c.release(p)
        c.check_invariants(tree_refs={})
        assert c.free_pages == c.num_pages


# ---------------------------------------------------------------------------
# radix tree mechanics (no engine)
# ---------------------------------------------------------------------------


class TestRadixTree:
    def setup_tree(self, max_pages=8, num_pages=24):
        cache = PagedKVCache(layers=1, heads=1, head_dim=8, page_size=4,
                             num_pages=num_pages, max_slots=2,
                             max_pages_per_seq=6)
        return cache, RadixPrefixCache(cache, max_pages=max_pages)

    def grab(self, cache, n):
        return [cache.alloc_page() for _ in range(n)]

    def release_run(self, cache, pages):
        for p in pages:
            cache.release(p)

    def test_insert_match_full_and_tail(self):
        cache, tree = self.setup_tree()
        toks = np.arange(10, dtype=np.int32)  # 2 full pages + 2-token tail
        pages = self.grab(cache, 3)
        assert tree.insert(toks, pages) == 3
        self.release_run(cache, pages)  # the "slot" lets go; tree holds
        cache.check_invariants(tree_refs=tree.page_refs())
        m = tree.match(np.arange(12, dtype=np.int32))
        assert m is not None and m.matched == 10
        assert m.pages == pages
        # identical prompt: capped at len-1 so one token re-prefills
        m = tree.match(toks)
        assert m.matched == 9 and m.pages == pages
        # mid-page divergence against a FULL page: CoW-able tail match
        div = np.asarray([0, 1, 2, 3, 4, 5, 99, 98], np.int32)
        m = tree.match(div)
        assert m.matched == 6 and m.pages == pages[:2]
        tree.check_invariants()

    def test_min_match_gate(self):
        cache, tree = self.setup_tree()
        pages = self.grab(cache, 1)
        tree.insert(np.arange(4, dtype=np.int32), pages)
        self.release_run(cache, pages)
        assert tree.match(np.asarray([0, 1, 9, 9, 9], np.int32)) is None
        assert tree.match(np.arange(6, dtype=np.int32)).matched == 4

    def test_dedup_insert_refreshes_not_duplicates(self):
        cache, tree = self.setup_tree()
        toks = np.arange(8, dtype=np.int32)
        pages = self.grab(cache, 2)
        assert tree.insert(toks, pages) == 2
        self.release_run(cache, pages)
        dup = self.grab(cache, 2)
        assert tree.insert(toks, dup) == 0  # deduplicated
        self.release_run(cache, dup)  # slot's copies free entirely
        assert tree.tree_pages == 2
        cache.check_invariants(tree_refs=tree.page_refs())

    def test_lru_leaf_eviction_under_budget(self):
        cache, tree = self.setup_tree(max_pages=3)
        a = self.grab(cache, 2)
        tree.insert(np.arange(8, dtype=np.int32), a)       # path A: 2 nodes
        self.release_run(cache, a)
        b = self.grab(cache, 2)
        tree.insert(np.arange(50, 58, dtype=np.int32), b)  # path B: 2 nodes
        self.release_run(cache, b)
        # budget 3: the LRU leaf (path A's deepest node) evicted first
        assert tree.tree_pages == 3
        m = tree.match(np.arange(10, dtype=np.int32))
        assert m is not None and m.matched == 4  # A's first page survives
        assert tree.match(np.arange(50, 60, dtype=np.int32)).matched == 8
        cache.check_invariants(tree_refs=tree.page_refs())

    def test_evict_to_free_and_reclaimable(self):
        cache, tree = self.setup_tree(max_pages=8, num_pages=4)
        pages = self.grab(cache, 4)
        tree.insert(np.arange(16, dtype=np.int32), pages)
        self.release_run(cache, pages)
        assert cache.free_pages == 0
        assert tree.reclaimable_pages() == 4
        freed = tree.evict_to_free(2)
        assert freed == 2 and cache.free_pages == 2
        assert tree.tree_pages == 2
        cache.check_invariants(tree_refs=tree.page_refs())

    def test_slot_shared_pages_are_not_reclaimable(self):
        """A tree page an active slot still maps frees NOTHING when
        evicted — it must not count as reclaimable supply (the admission
        precheck would turn a backpressure wait into a spurious terminal
        oom). evict_to_free still evicts such a leaf as a FALLBACK to
        unblock freeable ancestors behind it, and reports only what
        actually reached the free list."""
        cache, tree = self.setup_tree(max_pages=8, num_pages=4)
        pages = self.grab(cache, 4)
        tree.insert(np.arange(16, dtype=np.int32), pages)
        self.release_run(cache, pages)
        cache.map_shared(0, pages[-1])  # a "mid-flight hit" holds the two
        cache.map_shared(0, pages[-2])  # deepest nodes of the chain
        assert tree.reclaimable_pages() == 2  # only the slot-free pair
        # the slot-held leaves get evicted as fallbacks (freeing nothing
        # now, releasing the tree refs) to reach the freeable ancestors
        assert tree.evict_to_free(4) == 2
        assert cache.free_pages == 2
        cache.check_invariants(tree_refs=tree.page_refs())
        free_before = cache.free_pages
        cache.free_slot(0)  # slot retires: the fallback-evicted pages free
        assert cache.free_pages == free_before + 2

    def test_unusable_match_does_not_refresh_lru(self):
        """A path whose uncached tail exceeds max_suffix can never serve
        a hit — matching it must not refresh its LRU stamps, or
        never-usable entries crowd serving ones out of the budget."""
        cache, tree = self.setup_tree(max_pages=8)
        a = self.grab(cache, 1)
        tree.insert(np.arange(4, dtype=np.int32), a)
        self.release_run(cache, a)
        b = self.grab(cache, 1)
        tree.insert(np.arange(50, 54, dtype=np.int32), b)
        self.release_run(cache, b)
        stamp = {n.tokens: n.last_used for n in tree._all_nodes()}
        long_tail = np.concatenate([np.arange(4), np.arange(90, 110)]) \
            .astype(np.int32)
        assert tree.match(long_tail, max_suffix=2) is None
        assert {n.tokens: n.last_used
                for n in tree._all_nodes()} == stamp  # untouched
        assert tree.match(np.arange(6, dtype=np.int32),
                          max_suffix=2).matched == 4  # usable: refreshes

    def test_pinned_never_evicted_and_intents_survive_clear(self):
        cache, tree = self.setup_tree(max_pages=2, num_pages=24)
        toks = np.arange(8, dtype=np.int32)
        pages = self.grab(cache, 2)
        tree.insert(toks, pages)
        self.release_run(cache, pages)
        assert tree.pin(toks) == 2
        assert tree.reclaimable_pages() == 0
        assert tree.evict_to_free(1) == 0  # nothing evictable
        # budget pressure cannot displace the pinned path either
        other = self.grab(cache, 2)
        tree.insert(np.arange(50, 58, dtype=np.int32), other)
        self.release_run(cache, other)
        assert tree.match(np.arange(9, dtype=np.int32)).matched == 8
        # clear drops pages but keeps the pin INTENT: re-insert re-pins
        tree.clear()
        assert tree.tree_pages == 0 and tree.pinned_pages == 0
        cache.check_invariants(tree_refs={})
        again = self.grab(cache, 2)
        tree.insert(toks, again)
        self.release_run(cache, again)
        assert tree.pinned_pages == 2
        tree.check_invariants()

    def test_pin_intent_covers_rebuilt_divergence_tails(self):
        """Regression: after clear(), traffic re-inserts the pinned
        system prompt's mid-page remainder only EMBEDDED in its own
        divergence tails (rem + traffic tokens, never rem exactly). The
        intent must pin one covering tail — page-aligned-only coverage
        would silently leave the mid-page KV evictable."""
        cache, tree = self.setup_tree(max_pages=8, num_pages=24)
        sysp = np.arange(6, dtype=np.int32)  # 1 full page + 2-token rem
        pages = self.grab(cache, 2)
        tree.insert(sysp, pages)
        self.release_run(cache, pages)
        tree.pin(sysp)
        assert tree.pinned_pages == 2
        tree.clear()
        # traffic rebuild: sysp + a request-specific token — the partial
        # tail key is (4, 5, 9), not the intent's (4, 5)
        rebuilt = self.grab(cache, 2)
        tree.insert(np.concatenate([sysp, np.asarray([9], np.int32)]),
                    rebuilt)
        self.release_run(cache, rebuilt)
        assert tree.pinned_pages == 2  # full page AND a covering tail
        assert tree.reclaimable_pages() == 0
        assert tree.evict_to_free(2) == 0  # the mid-page KV is protected
        assert tree.match(np.concatenate(
            [sysp, np.asarray([9, 9], np.int32)])).matched >= 6
        # a second traffic tail must NOT grow the pin set without bound
        more = self.grab(cache, 2)
        tree.insert(np.concatenate([sysp, np.asarray([7], np.int32)]),
                    more)
        self.release_run(cache, more)
        assert tree.pinned_pages == 2
        tree.check_invariants()

    def test_zero_budget_rejected(self):
        cache, _ = self.setup_tree()
        with pytest.raises(ValueError, match="max_pages"):
            RadixPrefixCache(cache, max_pages=0)


# ---------------------------------------------------------------------------
# engine integration: oracle equality with reuse (satellite: test coverage)
# ---------------------------------------------------------------------------


class TestPrefixEngine:
    def test_hits_are_oracle_identical_with_zero_new_shape(self):
        observe.reset()
        eng = make_engine()
        p1 = np.concatenate([SYS, np.asarray([50, 51], np.int32)])
        p2 = np.concatenate([SYS, np.asarray([60], np.int32)])
        hits = []
        for p in (p1, p2, p1, p2):
            res = eng.generate([p], max_new_tokens=5, eos_token=-1)[0]
            assert res.finish_reason == "length"
            assert_oracle(p, res)
            hits.append(res.prefix_hit_tokens)
        assert hits[0] == 0              # cold: full prefill, inserted
        assert all(h >= 8 for h in hits[1:])  # warm: shared-prefix hits
        assert hits[2] == p1.size - 1    # exact repeat: all but one token
        eng.check_invariants()
        serving = [e for e in observe.ledger().events()
                   if e.graph == "serving"]
        assert not any(e.cause == "new_shape" for e in serving)
        keys = {e.key for e in serving}
        assert "suffix_prefill" in keys and "copy_page" in keys
        m = observe.metrics()
        assert m.counter("dl4j_tpu_prefix_hits_total").value == 3
        assert m.counter("dl4j_tpu_prefix_hit_tokens_total").value \
            == sum(hits)

    def test_hit_tokens_ride_the_result(self):
        eng = make_engine()
        p = np.concatenate([SYS, np.asarray([50], np.int32)])
        eng.generate([p], max_new_tokens=2, eos_token=-1)
        res = eng.generate([np.concatenate(
            [SYS, np.asarray([77], np.int32)])],
            max_new_tokens=2, eos_token=-1)[0]
        assert res.prefix_hit_tokens == SYS.size  # full pages + CoW tail

    def test_cow_divergence_does_not_corrupt_donor(self):
        """Two prompts diverge MID-PAGE: the second CoWs the tail page;
        both must match the oracle, and replaying the first afterwards
        must still match (its cached page was never written)."""
        observe.reset()
        eng = make_engine()
        a = np.concatenate([SYS, np.asarray([50, 51], np.int32)])
        b = np.concatenate([SYS[:9], np.asarray([70, 71, 72], np.int32)])
        assert_oracle(a, eng.generate([a], max_new_tokens=4,
                                      eos_token=-1)[0])
        res_b = eng.generate([b], max_new_tokens=4, eos_token=-1)[0]
        assert res_b.prefix_hit_tokens == 9  # 8 full + 1 shared tail token
        assert_oracle(b, res_b)
        res_a2 = eng.generate([a], max_new_tokens=4, eos_token=-1)[0]
        assert res_a2.prefix_hit_tokens >= 11
        assert_oracle(a, res_a2)
        assert observe.metrics().counter(
            "dl4j_tpu_prefix_cow_copies_total").value >= 2
        eng.check_invariants()

    def test_midflight_admits_with_shared_prefix(self):
        """Several same-prefix requests through 2 slots with different
        budgets: mid-flight turnover, shared pages across LIVE slots,
        every output oracle-exact, every page accounted for."""
        eng = make_engine()
        warm = np.concatenate([SYS, np.asarray([40], np.int32)])
        eng.generate([warm], max_new_tokens=2, eos_token=-1)
        prompts = [np.concatenate([SYS, np.asarray([50 + i], np.int32)])
                   for i in range(5)]
        budgets = [3, 8, 2, 6, 4]
        futs = [eng.submit(p, max_new_tokens=b, eos_token=-1)
                for p, b in zip(prompts, budgets)]
        while eng.scheduler.has_work():
            eng.step()
        for p, b, f in zip(prompts, budgets, futs):
            res = f.result(timeout=0)
            assert res.finish_reason == "length"
            assert res.prefix_hit_tokens >= 8
            np.testing.assert_array_equal(
                res.tokens, reference_generate(MODEL.params, CFG, p, b))
        eng.check_invariants()
        # every non-tree page came home
        assert eng.cache.free_pages == \
            eng.cache.num_pages - eng.prefix.tree_pages

    def test_suffix_over_bucket_falls_back_to_full_prefill(self):
        eng = make_engine(suffix_bucket=2)
        warm = np.concatenate([SYS, np.asarray([40], np.int32)])
        eng.generate([warm], max_new_tokens=2, eos_token=-1)
        p = np.concatenate([SYS, np.asarray([50, 51, 52], np.int32)])
        res = eng.generate([p], max_new_tokens=3, eos_token=-1)[0]
        assert res.prefix_hit_tokens == 0  # suffix 3 > bucket 2
        assert_oracle(p, res)

    def test_eviction_pressure_keeps_serving_correctly(self):
        """A tiny tree budget under many distinct prompts: evictions
        churn, correctness and invariants hold, pages never leak."""
        observe.reset()
        eng = make_engine(prefix_pages=4)
        r = np.random.RandomState(5)
        for _ in range(8):
            p = r.randint(1, CFG.vocab_size, size=int(r.randint(9, 15))) \
                .astype(np.int32)
            assert_oracle(p, eng.generate([p], max_new_tokens=3,
                                          eos_token=-1)[0])
            eng.check_invariants()
        assert observe.metrics().counter(
            "dl4j_tpu_prefix_evicted_pages_total").value > 0
        assert eng.prefix.tree_pages <= 4

    def test_supervisor_restart_drops_tree_cleanly(self):
        """A mid-generation crash: the tree is dropped (its device KV
        died with reset_kv), the retried request still matches the
        oracle, zero new_shape across the recovery, and the tree rebuilds
        from the retire-insert."""
        observe.reset()
        eng = make_engine(restart_backoff_s=0.0)
        p = np.concatenate([SYS, np.asarray([50, 51], np.int32)])
        eng.generate([p], max_new_tokens=3, eos_token=-1)
        assert eng.prefix.tree_pages > 0
        faults.arm("decode_step_error", prob=1.0, after_n=1, max_fires=1)
        try:
            res = eng.generate([p], max_new_tokens=5, eos_token=-1)[0]
        finally:
            faults.reset()
        assert eng.restarts == 1
        assert_oracle(p, res, 5)
        eng.check_invariants()
        assert eng.prefix.tree_pages > 0  # rebuilt at retire
        serving = [e for e in observe.ledger().events()
                   if e.graph == "serving"]
        assert not any(e.cause == "new_shape" for e in serving)

    def test_page_oom_mid_match_is_terminal_and_sound(self):
        """Satellite 2 (unit leg): injected pool pressure firing through
        the PREFIX admission path — after shared pages are mapped —
        unwinds the slot, retires the request terminally as oom, and
        leaves exact refcount accounting intact."""
        eng = make_engine(max_slots=1)
        p = np.concatenate([SYS, np.asarray([50], np.int32)])
        eng.generate([p], max_new_tokens=2, eos_token=-1)
        faults.arm("page_oom", prob=1.0, max_fires=1)
        try:
            res = eng.generate([p], max_new_tokens=2, eos_token=-1)[0]
        finally:
            faults.reset()
        assert res.finish_reason == "oom"
        eng.check_invariants()
        res = eng.generate([p], max_new_tokens=2, eos_token=-1)[0]
        assert res.finish_reason == "length"  # pressure gone: serves again
        assert_oracle(p, res)

    def test_pool_pressure_waits_instead_of_spurious_oom(self):
        """Regression: when the only 'reclaimable' tree pages are the
        matched prefix's OWN pages (about to be consumed, not freed),
        the admission precheck must take the backpressure WAIT path —
        not admit, fail to reclaim, and retire the request terminally as
        oom one step before a blocker would have freed real pages."""
        eng = make_engine(max_slots=2, num_pages=4, prefix_pages=3)
        warm = np.concatenate([SYS, np.asarray([40], np.int32)])
        eng.generate([warm], max_new_tokens=1, eos_token=-1)
        assert eng.prefix.tree_pages == 2  # sysp full page + partial tail
        blocker = eng.submit(np.arange(100, 108, dtype=np.int32),
                             max_new_tokens=5, eos_token=-1)
        eng.step()  # blocker admits: free list now empty
        assert eng.cache.free_pages == 0
        victim = eng.submit(np.concatenate(
            [SYS, np.asarray([50], np.int32)]),
            max_new_tokens=3, eos_token=-1)
        while eng.scheduler.has_work():
            eng.step()
        assert blocker.result(timeout=0).finish_reason == "length"
        res = victim.result(timeout=0)
        assert res.finish_reason == "length", res.finish_reason  # not oom
        assert res.prefix_hit_tokens >= 8  # and the match survived
        assert_oracle(np.concatenate([SYS, np.asarray([50], np.int32)]),
                      res)
        eng.check_invariants()

    def test_disabled_by_default(self):
        eng = GenerativeEngine(MODEL, max_slots=2, page_size=8,
                               max_pages_per_seq=6, max_prompt=16)
        assert eng.prefix is None
        p = np.asarray([3, 5, 7, 9], np.int32)
        res = eng.generate([p], max_new_tokens=3)[0]
        assert res.prefix_hit_tokens == 0
        assert eng.cache.free_pages == eng.cache.num_pages


# ---------------------------------------------------------------------------
# frontend pre-warm + pinning (ClassPolicy.shared_prefix)
# ---------------------------------------------------------------------------


class TestFrontendSharedPrefix:
    def test_prewarm_pins_and_first_request_hits(self):
        from deeplearning4j_tpu.serving import ClassPolicy, SLOFrontend

        observe.reset()
        eng = make_engine()
        classes = {
            "interactive": ClassPolicy("interactive", priority=0,
                                       degradable=False,
                                       shared_prefix=SYS.tolist()),
            "batch": ClassPolicy("batch", priority=2),
        }
        fe = SLOFrontend(eng, classes=classes)
        assert eng.prefix.pinned_pages > 0
        eng.start()
        try:
            fut = fe.submit(np.concatenate(
                [SYS, np.asarray([90], np.int32)]),
                slo_class="interactive", max_new_tokens=3, eos_token=-1)
            res = fut.result(timeout=120)
        finally:
            eng.stop()
        assert res.finish_reason == "length"
        assert res.prefix_hit_tokens >= 8  # hit from the FIRST request
        assert_oracle(np.concatenate([SYS, np.asarray([90], np.int32)]),
                      res)

    def test_prewarm_skipped_when_prefix_disabled(self):
        from deeplearning4j_tpu.serving import ClassPolicy, SLOFrontend

        eng = GenerativeEngine(MODEL, max_slots=2, page_size=8,
                               max_pages_per_seq=6, max_prompt=16)
        classes = {"standard": ClassPolicy("standard", priority=1,
                                           shared_prefix=[1, 2, 3])}
        fe = SLOFrontend(eng, classes=classes)  # must not raise
        assert eng.prefix is None
        assert fe.classes["standard"].shared_prefix == [1, 2, 3]


# ---------------------------------------------------------------------------
# replay harness (the bench/gate substrate)
# ---------------------------------------------------------------------------


class TestReplayHarness:
    def test_replay_identical_outputs_and_hits(self):
        from deeplearning4j_tpu.serving.replay import run_prefix_replay

        kw = dict(n_requests=4, n_prefixes=2, sys_len=11, tail_max=3,
                  gen_tokens=3, max_prompt=16, page_size=8,
                  suffix_bucket=8, warm_rounds=2, model=MODEL)
        on = run_prefix_replay(prefix_on=True, **kw)
        off = run_prefix_replay(prefix_on=False, **kw)
        assert on["prompts"] == off["prompts"]  # identical plan
        assert on["outputs"] == off["outputs"]  # bit-identical greedy
        assert on["prefix_hit_tokens"] > 0
        assert off["prefix_hit_tokens"] == 0
        assert on["all_terminal"] and off["all_terminal"]
        assert on["new_shape_events"] == 0
        # and the cache-on leg equals the REAL oracle, not just the twin
        for prompt, out in zip(on["prompts"], on["outputs"]):
            np.testing.assert_array_equal(
                out, reference_generate(
                    MODEL.params, CFG, np.asarray(prompt, np.int32),
                    len(out)))
