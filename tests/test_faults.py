"""Fault-injection registry + durable-checkpoint tests
(deeplearning4j_tpu/faults/, parallel/checkpoint.py — docs/ROBUSTNESS.md).

Covers the injection machinery itself (arming, schedules, env parsing,
determinism, the metric/counting contract) and the checkpoint durability
guarantees (atomic publish, checksum verification, newest-intact
fallback) the ``checkpoint_torn_write`` point exists to exercise. The
engine-supervisor behaviors live in tests/test_robustness.py.
"""

import json
import logging
import os
import types

import numpy as np
import pytest

from deeplearning4j_tpu import faults, observe
from deeplearning4j_tpu.faults import FaultSpec, InjectedFault
from deeplearning4j_tpu.parallel.checkpoint import TrainingCheckpointer


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv(faults.FAULTS_ENV, raising=False)
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# injection registry
# ---------------------------------------------------------------------------


class TestFaultRegistry:
    def test_unarmed_never_fires(self):
        assert not faults.active()
        for point in faults.FAULT_POINTS:
            assert not faults.should_fire(point)

    def test_unknown_point_rejected(self):
        with pytest.raises(ValueError, match="unknown fault point"):
            faults.arm("not_a_point")

    def test_arm_prob_one_always_fires(self):
        faults.arm("page_oom", prob=1.0)
        assert all(faults.should_fire("page_oom") for _ in range(5))
        assert faults.fire_counts() == {"page_oom": 5}

    def test_prob_zero_never_fires(self):
        faults.arm("page_oom", prob=0.0)
        assert not any(faults.should_fire("page_oom") for _ in range(20))

    def test_after_n_skips_first_calls(self):
        faults.arm("decode_step_error", prob=1.0, after_n=3)
        fired = [faults.should_fire("decode_step_error") for _ in range(5)]
        assert fired == [False, False, False, True, True]

    def test_max_fires_caps_schedule(self):
        faults.arm("worker_death", prob=1.0, max_fires=2)
        fired = [faults.should_fire("worker_death") for _ in range(5)]
        assert fired == [True, True, False, False, False]

    def test_seeded_schedule_is_deterministic(self):
        faults.arm("page_oom", prob=0.5, seed=7)
        a = [faults.should_fire("page_oom") for _ in range(32)]
        faults.reset()
        faults.arm("page_oom", prob=0.5, seed=7)
        b = [faults.should_fire("page_oom") for _ in range(32)]
        assert a == b and any(a) and not all(a)

    def test_disarm_and_reset(self):
        faults.arm("page_oom")
        faults.arm("slow_decode")
        faults.disarm("page_oom")
        assert not faults.should_fire("page_oom")
        assert faults.should_fire("slow_decode")
        faults.reset()
        assert not faults.should_fire("slow_decode")

    def test_maybe_fail_raises_injected_fault(self):
        faults.arm("decode_step_error")
        with pytest.raises(InjectedFault, match="decode_step_error") as ei:
            faults.maybe_fail("decode_step_error")
        assert ei.value.point == "decode_step_error"
        # unarmed points pass through silently
        faults.maybe_fail("page_oom")

    def test_fires_counted_in_metric_family(self):
        observe.reset()
        faults.arm("page_oom", max_fires=3)
        for _ in range(5):
            faults.should_fire("page_oom")
        m = observe.metrics()
        assert m.counter("dl4j_tpu_faults_injected_total",
                         point="page_oom").value == 3
        assert m.family_total("dl4j_tpu_faults_injected_total") == 3

    def test_engine_death_point_registered(self):
        """The 9th catalog entry (serving/cluster.py's failure domain):
        armable, schedulable, counted like every other point."""
        assert "engine_death" in faults.FAULT_POINTS
        faults.arm("engine_death", prob=1.0, max_fires=1)
        assert faults.should_fire("engine_death")
        assert not faults.should_fire("engine_death")
        with pytest.raises(InjectedFault, match="engine_death") as ei:
            faults.arm("engine_death", prob=1.0)
            faults.maybe_fail("engine_death")
        assert ei.value.point == "engine_death"
        m = observe.metrics()
        assert m.counter("dl4j_tpu_faults_injected_total",
                         point="engine_death").value >= 2

    def test_invalid_spec_rejected(self):
        with pytest.raises(ValueError, match="prob"):
            FaultSpec(point="page_oom", prob=1.5)
        with pytest.raises(ValueError, match="after_n"):
            FaultSpec(point="page_oom", after_n=-1)


class TestEnvSchedule:
    def test_env_syntax_point_prob_after(self, monkeypatch):
        monkeypatch.setenv(faults.FAULTS_ENV, "page_oom:1:2,slow_decode:0")
        assert faults.active()
        fired = [faults.should_fire("page_oom") for _ in range(4)]
        assert fired == [False, False, True, True]
        assert not faults.should_fire("slow_decode")  # prob 0

    def test_env_point_alone_means_always(self, monkeypatch):
        monkeypatch.setenv(faults.FAULTS_ENV, "decode_step_error")
        assert faults.should_fire("decode_step_error")

    def test_malformed_env_entry_ignored(self, monkeypatch, caplog):
        monkeypatch.setenv(faults.FAULTS_ENV,
                           "bogus_point:1,page_oom:notafloat,slow_decode:1")
        with caplog.at_level(logging.WARNING):
            assert faults.should_fire("slow_decode")
        assert not faults.should_fire("page_oom")

    def test_programmatic_arm_wins_over_env(self, monkeypatch):
        monkeypatch.setenv(faults.FAULTS_ENV, "page_oom:1")
        faults.arm("page_oom", prob=0.0)
        assert not faults.should_fire("page_oom")

    def test_env_unset_is_inactive(self):
        assert not faults.active()


# ---------------------------------------------------------------------------
# durable checkpoints
# ---------------------------------------------------------------------------


def _fake_net(value: float):
    net = types.SimpleNamespace()
    net.params = {"W": np.full((4, 4), float(value), np.float32)}
    net.opt_state = {"W": np.zeros((4, 4), np.float32)}
    net.net_state = {}
    net.iteration_count = int(value)
    net.epoch_count = 0
    return net


class TestDurableCheckpoints:
    def make(self, tmp_path, **kw):
        kw.setdefault("use_orbax", False)
        return TrainingCheckpointer(str(tmp_path / "ckpt"), **kw)

    def test_atomic_save_no_temp_residue(self, tmp_path):
        ck = self.make(tmp_path)
        path = ck.save(1, _fake_net(1.0))
        assert os.path.exists(path)
        assert not any(f.endswith(".tmp")
                       for f in os.listdir(os.path.dirname(path)))

    def test_marker_carries_checksum(self, tmp_path):
        ck = self.make(tmp_path)
        ck.save(1, _fake_net(1.0))
        with open(os.path.join(ck.dir, "latest.json")) as f:
            d = json.load(f)
        (step, path, checksum), = d["saved"]
        assert step == 1 and len(checksum) == 64  # sha256 hex

    def test_restore_falls_back_past_torn_file(self, tmp_path):
        observe.reset()
        ck = self.make(tmp_path)
        ck.save(1, _fake_net(1.0))
        ck.save(2, _fake_net(2.0))
        p3 = ck.save(3, _fake_net(3.0))
        with open(p3, "r+b") as f:  # torn write after publish
            f.truncate(os.path.getsize(p3) // 2)
        net = _fake_net(0.0)
        assert ck.restore(net) == 2
        assert net.params["W"][0, 0] == 2.0
        m = observe.metrics()
        assert m.counter("dl4j_tpu_checkpoint_corrupt_total").value >= 1
        assert m.counter("dl4j_tpu_checkpoint_fallback_total").value == 1

    def test_torn_write_fault_point(self, tmp_path):
        """The chaos arm: checkpoint_torn_write corrupts the published
        file; the checksum recorded pre-corruption exposes it."""
        ck = self.make(tmp_path)
        ck.save(1, _fake_net(1.0))
        faults.arm("checkpoint_torn_write", max_fires=1)
        ck.save(2, _fake_net(2.0))
        net = _fake_net(0.0)
        assert ck.restore(net) == 1
        assert net.params["W"][0, 0] == 1.0

    def test_all_corrupt_returns_none(self, tmp_path, caplog):
        ck = self.make(tmp_path)
        for s in (1, 2):
            p = ck.save(s, _fake_net(s))
            with open(p, "r+b") as f:
                f.truncate(4)
        with caplog.at_level(logging.WARNING):
            assert ck.restore(_fake_net(0.0)) is None
        assert "no intact checkpoint" in caplog.text

    def test_explicit_corrupt_step_raises(self, tmp_path):
        ck = self.make(tmp_path)
        p1 = ck.save(1, _fake_net(1.0))
        ck.save(2, _fake_net(2.0))
        with open(p1, "r+b") as f:
            f.truncate(4)
        with pytest.raises(IOError, match="integrity"):
            ck.restore(_fake_net(0.0), step=1)

    def test_old_two_entry_marker_still_loads(self, tmp_path):
        """Pre-robustness markers ([step, path] pairs, no checksum) keep
        working — checksum None skips the verify."""
        ck = self.make(tmp_path)
        ck.save(1, _fake_net(1.0))
        ck.save(2, _fake_net(2.0))
        marker = os.path.join(ck.dir, "latest.json")
        with open(marker) as f:
            d = json.load(f)
        d["saved"] = [[s, p] for s, p, _c in d["saved"]]
        with open(marker, "w") as f:
            json.dump(d, f)
        ck2 = TrainingCheckpointer(ck.dir, use_orbax=False)
        net = _fake_net(0.0)
        assert ck2.restore(net) == 2
        assert net.params["W"][0, 0] == 2.0

    def test_unreadable_load_falls_back_not_raises(self, tmp_path):
        """A checkpoint that passes no checksum but fails np.load (the
        checksum-less legacy case) still falls back instead of raising
        mid-fit."""
        ck = self.make(tmp_path)
        ck.save(1, _fake_net(1.0))
        p2 = ck.save(2, _fake_net(2.0))
        # legacy marker (no checksums), then corrupt the newest file
        marker = os.path.join(ck.dir, "latest.json")
        with open(marker) as f:
            d = json.load(f)
        d["saved"] = [[s, p] for s, p, _c in d["saved"]]
        with open(marker, "w") as f:
            json.dump(d, f)
        with open(p2, "r+b") as f:
            f.truncate(4)
        ck2 = TrainingCheckpointer(ck.dir, use_orbax=False)
        net = _fake_net(0.0)
        assert ck2.restore(net) == 1
        assert net.params["W"][0, 0] == 1.0


# ---------------------------------------------------------------------------
# JSONL event-log hardening (observe/registry.py — satellite)
# ---------------------------------------------------------------------------


class TestObsLogHardening:
    def test_unwritable_path_warns_once_and_disables(self, tmp_path,
                                                     monkeypatch, caplog):
        observe.reset_log_state()
        monkeypatch.setenv(observe.OBS_LOG_ENV, str(tmp_path))  # a DIRECTORY
        with caplog.at_level(logging.WARNING,
                             logger="deeplearning4j_tpu.observe.registry"):
            observe.log_event("train_epoch", steps=1)  # must not raise
            observe.log_event("train_epoch", steps=2)
            observe.log_event("train_epoch", steps=3)
        warnings = [r for r in caplog.records
                    if "event logging DISABLED" in r.getMessage()]
        assert len(warnings) == 1
        observe.reset_log_state()

    def test_fresh_path_reenables_after_failure(self, tmp_path, monkeypatch):
        observe.reset_log_state()
        monkeypatch.setenv(observe.OBS_LOG_ENV, str(tmp_path))  # fails
        observe.log_event("train_epoch", steps=1)
        good = tmp_path / "events.jsonl"
        monkeypatch.setenv(observe.OBS_LOG_ENV, str(good))
        observe.log_event("train_epoch", steps=2)  # different path: works
        lines = good.read_text().strip().splitlines()
        assert len(lines) == 1 and json.loads(lines[0])["steps"] == 2
        observe.reset_log_state()

    def test_reset_log_state_clears_disable(self, tmp_path, monkeypatch):
        observe.reset_log_state()
        bad_then_good = tmp_path / "log.jsonl"
        monkeypatch.setenv(observe.OBS_LOG_ENV, str(tmp_path))
        observe.log_event("x")           # disables the directory path
        monkeypatch.setenv(observe.OBS_LOG_ENV, str(bad_then_good))
        observe.reset_log_state()
        observe.log_event("recovered", n=1)
        assert "recovered" in bad_then_good.read_text()
        observe.reset_log_state()
