"""ONNX import golden tests — the samediff-import-onnx golden pattern
(SURVEY §3.2): assemble an ONNX ModelProto, import to SameDiff, and compare
outputs elementwise against an independent oracle (numpy / torch).

No ONNX producer exists in this environment (no onnx package; torch's
exporter requires it), so models are assembled at the protobuf byte level
with the same wire codec the importer uses for decoding — the round trip
plus the independent-oracle forward checks both codec directions AND the
mapping rules.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.imports import protowire as pw
from deeplearning4j_tpu.imports.onnx_import import (
    OnnxImporter, import_onnx, parse_model,
)


# ---------------------------------------------------------------------------
# ModelProto assembly helpers — canonical home is
# deeplearning4j_tpu/testing/onnx_builder.py (bench.py builds the
# BENCH_MODEL=bert_import model with the same codec); re-exported here for
# the golden-test files that import them from this module.
# ---------------------------------------------------------------------------

from deeplearning4j_tpu.testing.onnx_builder import (  # noqa: F401,E402
    attr_proto, build_model, node_proto, tensor_proto, value_info)


def _run(sd, feeds, out):
    return sd.output(feeds, out)[out]


class TestOnnxParser:
    def test_tensor_round_trip(self):
        arr = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        model = build_model([], [("x", (1,))], [("x", (1,))], {"w": arr})
        ir = parse_model(model)
        np.testing.assert_array_equal(ir.initializers["w"], arr)

    def test_int64_tensor(self):
        arr = np.asarray([2, -1, 12], np.int64)
        model = build_model([], [("x", (1,))], [("x", (1,))], {"s": arr})
        ir = parse_model(model)
        np.testing.assert_array_equal(ir.initializers["s"], arr)

    def test_node_attrs(self):
        n = node_proto("Softmax", ["x"], ["y"], axis=-1)
        model = build_model([n], [("x", (2, 3))], [("y", (2, 3))], {})
        ir = parse_model(model)
        assert ir.nodes[0].op_type == "Softmax"
        assert ir.nodes[0].attrs["axis"] == -1
        assert ir.inputs == [("x", (2, 3))]
        assert ir.outputs == ["y"]


class TestOnnxImport:
    def test_mlp_golden(self):
        r = np.random.RandomState(0)
        w0 = r.randn(8, 4).astype(np.float32)  # Gemm transB: (out, in)
        b0 = r.randn(8).astype(np.float32)
        w1 = r.randn(3, 8).astype(np.float32)
        b1 = r.randn(3).astype(np.float32)
        nodes = [
            node_proto("Gemm", ["x", "w0", "b0"], ["h0"], transB=1),
            node_proto("Relu", ["h0"], ["h1"]),
            node_proto("Gemm", ["h1", "w1", "b1"], ["h2"], transB=1),
            node_proto("Softmax", ["h2"], ["y"], axis=-1),
        ]
        model = build_model(nodes, [("x", (5, 4))], [("y", (5, 3))],
                            {"w0": w0, "b0": b0, "w1": w1, "b1": b1})
        x = r.randn(5, 4).astype(np.float32)
        # independent numpy oracle
        h = np.maximum(x @ w0.T + b0, 0) @ w1.T + b1
        e = np.exp(h - h.max(axis=-1, keepdims=True))
        want = e / e.sum(axis=-1, keepdims=True)

        sd = import_onnx(model)
        got = _run(sd, {"x": x}, "y")
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_cnn_golden_vs_torch(self):
        torch = pytest.importorskip("torch")
        import torch.nn.functional as F

        r = np.random.RandomState(1)
        x = r.randn(2, 3, 8, 8).astype(np.float32)
        w = (r.randn(4, 3, 3, 3) * 0.5).astype(np.float32)
        b = r.randn(4).astype(np.float32)
        gamma = (np.abs(r.randn(4)) + 0.5).astype(np.float32)
        beta = r.randn(4).astype(np.float32)
        mean = r.randn(4).astype(np.float32)
        var = (np.abs(r.randn(4)) + 0.5).astype(np.float32)
        wf = r.randn(5, 4).astype(np.float32)
        bf = r.randn(5).astype(np.float32)

        nodes = [
            node_proto("Conv", ["x", "w", "b"], ["c1"],
                       kernel_shape=[3, 3], strides=[1, 1],
                       pads=[1, 1, 1, 1]),
            node_proto("BatchNormalization",
                       ["c1", "gamma", "beta", "mean", "var"], ["bn"],
                       epsilon=1e-5),
            node_proto("Relu", ["bn"], ["r1"]),
            node_proto("MaxPool", ["r1"], ["p1"], kernel_shape=[2, 2],
                       strides=[2, 2]),
            node_proto("GlobalAveragePool", ["p1"], ["g1"]),
            node_proto("Flatten", ["g1"], ["f1"], axis=1),
            node_proto("Gemm", ["f1", "wf", "bf"], ["y"], transB=1),
        ]
        model = build_model(
            nodes, [("x", (2, 3, 8, 8))], [("y", (2, 5))],
            {"w": w, "b": b, "gamma": gamma, "beta": beta, "mean": mean,
             "var": var, "wf": wf, "bf": bf})

        with torch.no_grad():
            t = torch.from_numpy(x)
            t = F.conv2d(t, torch.from_numpy(w), torch.from_numpy(b),
                         padding=1)
            t = F.batch_norm(t, torch.from_numpy(mean), torch.from_numpy(var),
                             torch.from_numpy(gamma), torch.from_numpy(beta),
                             training=False, eps=1e-5)
            t = F.relu(t)
            t = F.max_pool2d(t, 2, 2)
            t = F.adaptive_avg_pool2d(t, 1).flatten(1)
            want = (t @ torch.from_numpy(wf).T + torch.from_numpy(bf)).numpy()

        sd = import_onnx(model)
        got = _run(sd, {"x": x}, "y")
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_depthwise_conv_golden_vs_torch(self):
        torch = pytest.importorskip("torch")
        import torch.nn.functional as F

        r = np.random.RandomState(2)
        x = r.randn(1, 4, 6, 6).astype(np.float32)
        w = r.randn(4, 1, 3, 3).astype(np.float32)
        nodes = [node_proto("Conv", ["x", "w"], ["y"], kernel_shape=[3, 3],
                            strides=[1, 1], pads=[0, 0, 0, 0], group=4)]
        model = build_model(nodes, [("x", (1, 4, 6, 6))], [("y", (1, 4, 4, 4))],
                            {"w": w})
        with torch.no_grad():
            want = F.conv2d(torch.from_numpy(x), torch.from_numpy(w),
                            groups=4).numpy()
        got = _run(import_onnx(model), {"x": x}, "y")
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_elementwise_reduce_chain(self):
        r = np.random.RandomState(3)
        x = r.randn(3, 6).astype(np.float32)
        c = r.randn(6).astype(np.float32)
        nodes = [
            node_proto("Add", ["x", "c"], ["a"]),
            node_proto("Clip", ["a"], ["cl"], min=-1.0, max=1.0),
            node_proto("Mul", ["cl", "cl"], ["m"]),
            node_proto("ReduceMean", ["m"], ["rm"], axes=[1], keepdims=0),
            node_proto("Sqrt", ["rm"], ["y"]),
        ]
        model = build_model(nodes, [("x", (3, 6))], [("y", (3,))], {"c": c})
        want = np.sqrt(np.mean(np.clip(x + c, -1, 1) ** 2, axis=1))
        got = _run(import_onnx(model), {"x": x}, "y")
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_shape_ops_chain(self):
        r = np.random.RandomState(4)
        x = r.randn(2, 3, 4).astype(np.float32)
        shape = np.asarray([2, 12], np.int64)
        nodes = [
            node_proto("Transpose", ["x"], ["t"], perm=[0, 2, 1]),
            node_proto("Reshape", ["t", "shape"], ["rs"]),
            node_proto("Concat", ["rs", "rs"], ["cc"], axis=0),
            node_proto("Pad", ["cc"], ["y"], pads=[0, 1, 0, 1], value=0.5),
        ]
        model = build_model(nodes, [("x", (2, 3, 4))], [("y", (4, 14))],
                            {"shape": shape})
        t = x.transpose(0, 2, 1).reshape(2, 12)
        cc = np.concatenate([t, t], axis=0)
        want = np.pad(cc, [(0, 0), (1, 1)], constant_values=0.5)
        got = _run(import_onnx(model), {"x": x}, "y")
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_unsupported_op_message(self):
        nodes = [node_proto("NonexistentOp", ["x"], ["y"])]
        model = build_model(nodes, [("x", (1,))], [("y", (1,))], {})
        with pytest.raises(NotImplementedError, match="NonexistentOp"):
            import_onnx(model)

    def test_supported_ops_listing(self):
        ops = OnnxImporter().supported_ops()
        assert len(ops) >= 45
        assert "Conv" in ops and "Gemm" in ops and "BatchNormalization" in ops

    def test_avgpool_pads_excludes_padding(self):
        torch = pytest.importorskip("torch")
        import torch.nn.functional as F

        r = np.random.RandomState(5)
        x = r.randn(1, 2, 6, 6).astype(np.float32)
        nodes = [node_proto("AveragePool", ["x"], ["y"], kernel_shape=[3, 3],
                            strides=[1, 1], pads=[1, 1, 1, 1])]
        model = build_model(nodes, [("x", (1, 2, 6, 6))], [("y", (1, 2, 6, 6))], {})
        with torch.no_grad():
            want = F.avg_pool2d(torch.from_numpy(x), 3, 1, padding=1,
                                count_include_pad=False).numpy()
        got = _run(import_onnx(model), {"x": x}, "y")
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_unsqueeze_multiple_axes(self):
        x = np.random.RandomState(6).randn(3, 4).astype(np.float32)
        ax = np.asarray([0, 3], np.int64)
        nodes = [node_proto("Unsqueeze", ["x", "ax"], ["y"])]
        model = build_model(nodes, [("x", (3, 4))], [("y", (1, 3, 4, 1))],
                            {"ax": ax})
        got = _run(import_onnx(model), {"x": x}, "y")
        assert got.shape == (1, 3, 4, 1)
        np.testing.assert_array_equal(got[0, :, :, 0], x)

    def test_grouped_conv_rejected(self):
        w = np.random.RandomState(7).randn(4, 2, 3, 3).astype(np.float32)
        nodes = [node_proto("Conv", ["x", "w"], ["y"], kernel_shape=[3, 3],
                            group=2)]
        model = build_model(nodes, [("x", (1, 4, 6, 6))], [("y", (1, 4, 4, 4))],
                            {"w": w})
        with pytest.raises(NotImplementedError, match="group"):
            import_onnx(model)


class TestOnnxRecurrentAndResize:
    """Round-4 widening: LSTM/GRU sequence ops + Resize, numpy oracles
    implementing the ONNX operator spec."""

    @staticmethod
    def _sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    def test_lstm_forward(self):
        r = np.random.RandomState(0)
        t, n, i, h = 4, 2, 3, 5
        x = r.randn(t, n, i).astype(np.float32)
        W = r.randn(1, 4 * h, i).astype(np.float32)   # gates i,o,f,c
        R = r.randn(1, 4 * h, h).astype(np.float32)
        B = r.randn(1, 8 * h).astype(np.float32)
        nodes = [node_proto("LSTM", ["x", "W", "R", "B"],
                            ["Y", "Y_h", "Y_c"], hidden_size=h)]
        model = build_model(nodes, [("x", (t, n, i))],
                            [("Y", (t, 1, n, h)), ("Y_h", (1, n, h)),
                             ("Y_c", (1, n, h))],
                            {"W": W, "R": R, "B": B})
        from deeplearning4j_tpu.imports import import_onnx

        sd = import_onnx(bytes(model))
        res = sd.output({"x": x}, ["Y", "Y_h", "Y_c"])

        # ONNX LSTM oracle (spec equations, gates i,o,f,c)
        Wi, Wo, Wf, Wc = np.split(W[0], 4)
        Ri, Ro, Rf, Rc = np.split(R[0], 4)
        Wb, Rb = np.split(B[0], 2)
        bi, bo, bf, bc = np.split(Wb, 4)
        rbi, rbo, rbf, rbc = np.split(Rb, 4)
        hh = np.zeros((n, h), np.float32)
        cc = np.zeros((n, h), np.float32)
        Y = np.zeros((t, 1, n, h), np.float32)
        for s in range(t):
            xi = x[s]
            it = self._sig(xi @ Wi.T + hh @ Ri.T + bi + rbi)
            ot = self._sig(xi @ Wo.T + hh @ Ro.T + bo + rbo)
            ft = self._sig(xi @ Wf.T + hh @ Rf.T + bf + rbf)
            ct = np.tanh(xi @ Wc.T + hh @ Rc.T + bc + rbc)
            cc = ft * cc + it * ct
            hh = ot * np.tanh(cc)
            Y[s, 0] = hh
        np.testing.assert_allclose(res["Y"], Y, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(res["Y_h"][0], hh, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(res["Y_c"][0], cc, rtol=1e-4, atol=1e-5)

    def test_gru_forward_both_lbr(self):
        r = np.random.RandomState(1)
        t, n, i, h = 3, 2, 4, 3
        x = r.randn(t, n, i).astype(np.float32)
        W = r.randn(1, 3 * h, i).astype(np.float32)   # gates z,r,h
        R = r.randn(1, 3 * h, h).astype(np.float32)
        B = r.randn(1, 6 * h).astype(np.float32)
        from deeplearning4j_tpu.imports import import_onnx

        for lbr in (0, 1):
            nodes = [node_proto("GRU", ["x", "W", "R", "B"], ["Y", "Y_h"],
                                hidden_size=h, linear_before_reset=lbr)]
            model = build_model(nodes, [("x", (t, n, i))],
                                [("Y", (t, 1, n, h)), ("Y_h", (1, n, h))],
                                {"W": W, "R": R, "B": B})
            sd = import_onnx(bytes(model))
            res = sd.output({"x": x}, ["Y", "Y_h"])

            Wz, Wr, Wh = np.split(W[0], 3)
            Rz, Rr, Rh = np.split(R[0], 3)
            Wb, Rb = np.split(B[0], 2)
            bz, br, bh = np.split(Wb, 3)
            rbz, rbr, rbh = np.split(Rb, 3)
            hh = np.zeros((n, h), np.float32)
            Y = np.zeros((t, 1, n, h), np.float32)
            for s in range(t):
                xi = x[s]
                zt = self._sig(xi @ Wz.T + hh @ Rz.T + bz + rbz)
                rt = self._sig(xi @ Wr.T + hh @ Rr.T + br + rbr)
                if lbr:
                    ht = np.tanh(xi @ Wh.T + rt * (hh @ Rh.T + rbh) + bh)
                else:
                    ht = np.tanh(xi @ Wh.T + (rt * hh) @ Rh.T + bh + rbh)
                hh = (1.0 - zt) * ht + zt * hh
                Y[s, 0] = hh
            np.testing.assert_allclose(res["Y"], Y, rtol=1e-4, atol=1e-5,
                                       err_msg=f"lbr={lbr}")
            np.testing.assert_allclose(res["Y_h"][0], hh, rtol=1e-4,
                                       atol=1e-5)

    def test_resize_bilinear_half_pixel(self):
        r = np.random.RandomState(2)
        x = r.rand(1, 2, 4, 4).astype(np.float32)  # NCHW
        sizes = np.asarray([1, 2, 8, 8], np.int64)
        nodes = [node_proto("Resize", ["x", "", "", "sizes"], ["y"],
                            mode="linear",
                            coordinate_transformation_mode="half_pixel")]
        model = build_model(nodes, [("x", (1, 2, 4, 4))],
                            [("y", (1, 2, 8, 8))], {"sizes": sizes})
        from deeplearning4j_tpu.imports import import_onnx

        sd = import_onnx(bytes(model))
        got = sd.output({"x": x}, "y")["y"]
        assert got.shape == (1, 2, 8, 8)

        # half-pixel bilinear oracle
        def bilinear(img, oh, ow):
            ih, iw = img.shape
            out = np.zeros((oh, ow), np.float32)
            for a in range(oh):
                for b in range(ow):
                    sy = (a + 0.5) * ih / oh - 0.5
                    sx = (b + 0.5) * iw / ow - 0.5
                    y0 = int(np.floor(sy)); x0 = int(np.floor(sx))
                    dy = sy - y0; dx = sx - x0
                    y0c = np.clip([y0, y0 + 1], 0, ih - 1)
                    x0c = np.clip([x0, x0 + 1], 0, iw - 1)
                    out[a, b] = (
                        img[y0c[0], x0c[0]] * (1 - dy) * (1 - dx)
                        + img[y0c[0], x0c[1]] * (1 - dy) * dx
                        + img[y0c[1], x0c[0]] * dy * (1 - dx)
                        + img[y0c[1], x0c[1]] * dy * dx)
            return out

        want = np.stack([bilinear(x[0, c], 8, 8) for c in range(2)])[None]
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)

    def test_resize_rejects_other_transform(self):
        import pytest

        sizes = np.asarray([1, 1, 2, 2], np.int64)
        nodes = [node_proto("Resize", ["x", "", "", "sizes"], ["y"],
                            mode="linear",
                            coordinate_transformation_mode="align_corners")]
        model = build_model(nodes, [("x", (1, 1, 4, 4))],
                            [("y", (1, 1, 2, 2))], {"sizes": sizes})
        from deeplearning4j_tpu.imports import import_onnx

        with pytest.raises(NotImplementedError, match="align_corners"):
            import_onnx(bytes(model))

    def test_lstm_weights_are_trainable(self):
        """The gate re-packing is recorded in-graph, so gradients flow to
        the ORIGINAL imported W/R/B variables (fine-tune contract)."""
        r = np.random.RandomState(3)
        t, n, i, h = 3, 2, 3, 4
        x = r.randn(t, n, i).astype(np.float32)
        W = r.randn(1, 4 * h, i).astype(np.float32)
        R = r.randn(1, 4 * h, h).astype(np.float32)
        B = r.randn(1, 8 * h).astype(np.float32)
        nodes = [node_proto("LSTM", ["x", "W", "R", "B"],
                            ["Y", "Y_h", "Y_c"], hidden_size=h)]
        model = build_model(nodes, [("x", (t, n, i))],
                            [("Y", (t, 1, n, h))], {"W": W, "R": R, "B": B})
        from deeplearning4j_tpu.imports import import_onnx

        sd = import_onnx(bytes(model))
        assert sd._vars["W"].vtype == "VARIABLE"
        loss = sd._record("reduce_mean", [sd._vars["Y"]],
                          {"axes": None, "keepdims": False}).rename("l2loss")
        grads = sd.calculate_gradients({"x": x}, "l2loss", wrt=["W", "R", "B"])
        for k in ("W", "R", "B"):
            assert np.isfinite(grads[k]).all()
            assert np.abs(grads[k]).max() > 0, f"zero grad for {k}"

    def test_lstm_rejects_initial_state_and_seqlens(self):
        import pytest

        r = np.random.RandomState(4)
        h = 3
        W = r.randn(1, 4 * h, 2).astype(np.float32)
        R = r.randn(1, 4 * h, h).astype(np.float32)
        h0 = np.zeros((1, 2, h), np.float32)
        from deeplearning4j_tpu.imports import import_onnx

        # initial_h on slot 5 with EMPTY B/seq_lens slots — the guard must
        # check wire slots, not the compacted ins list
        nodes = [node_proto("LSTM", ["x", "W", "R", "", "", "h0"], ["Y"],
                            hidden_size=h)]
        model = build_model(nodes, [("x", (2, 2, 2))], [("Y", (2, 1, 2, h))],
                            {"W": W, "R": R, "h0": h0})
        with pytest.raises(NotImplementedError, match="initial_h"):
            import_onnx(bytes(model))

    def test_resize_from_scales(self):
        r = np.random.RandomState(5)
        x = r.rand(1, 2, 4, 4).astype(np.float32)
        scales = np.asarray([1.0, 1.0, 2.0, 2.0], np.float32)
        nodes = [node_proto("Resize", ["x", "", "scales"], ["y"],
                            mode="nearest",
                            coordinate_transformation_mode="half_pixel")]
        model = build_model(nodes, [("x", (1, 2, 4, 4))],
                            [("y", (1, 2, 8, 8))], {"scales": scales})
        from deeplearning4j_tpu.imports import import_onnx

        sd = import_onnx(bytes(model))
        got = sd.output({"x": x}, "y")["y"]
        assert got.shape == (1, 2, 8, 8)
        np.testing.assert_allclose(got[0, 0, ::2, ::2], x[0, 0], atol=1e-6)


class TestOnnxRound4Breadth:
    def test_einsum_gathernd_cumsum(self):
        r = np.random.RandomState(0)
        a = r.randn(2, 3, 4).astype(np.float32)
        b = r.randn(2, 4, 5).astype(np.float32)
        idx = np.asarray([[0, 1], [1, 2]], np.int64)
        nodes = [
            node_proto("Einsum", ["a", "b"], ["e"], equation="bij,bjk->bik"),
            node_proto("GatherND", ["a", "idx"], ["g"]),
            node_proto("CumSum", ["a", "ax"], ["c"]),
        ]
        model = build_model(nodes, [("a", (2, 3, 4)), ("b", (2, 4, 5))],
                            [("e", (2, 3, 5)), ("g", (2, 4)),
                             ("c", (2, 3, 4))],
                            {"idx": idx, "ax": np.asarray(1, np.int64)})
        from deeplearning4j_tpu.imports import import_onnx

        sd = import_onnx(bytes(model))
        res = sd.output({"a": a, "b": b}, ["e", "g", "c"])
        np.testing.assert_allclose(res["e"], np.einsum("bij,bjk->bik", a, b),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(res["g"], a[[0, 1], [1, 2]], rtol=1e-6)
        np.testing.assert_allclose(res["c"], np.cumsum(a, axis=1),
                                   rtol=1e-5, atol=1e-5)

    def test_trilu_not_isnan_hardmax(self):
        r = np.random.RandomState(1)
        x = r.randn(4, 4).astype(np.float32)
        nodes = [
            node_proto("Trilu", ["x"], ["u"], upper=1),
            node_proto("Hardmax", ["x"], ["h"]),
            node_proto("IsNaN", ["x"], ["n"]),
            node_proto("Not", ["n"], ["nn"]),
        ]
        model = build_model(nodes, [("x", (4, 4))],
                            [("u", (4, 4)), ("h", (4, 4)), ("nn", (4, 4))],
                            {})
        from deeplearning4j_tpu.imports import import_onnx

        sd = import_onnx(bytes(model))
        res = sd.output({"x": x}, ["u", "h", "nn"])
        np.testing.assert_allclose(res["u"], np.triu(x), rtol=1e-6)
        want_h = (x == x.max(axis=-1, keepdims=True)).astype(np.float32)
        np.testing.assert_allclose(res["h"], want_h)
        assert res["nn"].all()  # nothing is NaN

    def test_lp_norm_and_mvn(self):
        r = np.random.RandomState(2)
        x = r.randn(3, 6).astype(np.float32)
        xc = r.randn(2, 3, 4, 4).astype(np.float32)
        nodes = [node_proto("LpNormalization", ["x"], ["l"], p=2, axis=-1),
                 node_proto("MeanVarianceNormalization", ["xc"], ["m"])]
        model = build_model(nodes, [("x", (3, 6)), ("xc", (2, 3, 4, 4))],
                            [("l", (3, 6)), ("m", (2, 3, 4, 4))], {})
        from deeplearning4j_tpu.imports import import_onnx

        sd = import_onnx(bytes(model))
        res = sd.output({"x": x, "xc": xc}, ["l", "m"])
        np.testing.assert_allclose(
            res["l"], x / np.linalg.norm(x, axis=-1, keepdims=True),
            rtol=1e-4, atol=1e-5)
        mean = xc.mean(axis=(0, 2, 3), keepdims=True)
        var = ((xc - mean) ** 2).mean(axis=(0, 2, 3), keepdims=True)
        np.testing.assert_allclose(res["m"], (xc - mean) / np.sqrt(var + 1e-9),
                                   rtol=1e-3, atol=1e-4)
