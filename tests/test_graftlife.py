"""graftlife: per-rule fixture tests (positive + negative per rule,
including ownership-transfer-via-call and raise-path negatives), the
justified-suppression contract, shrink-only baseline mechanics over the
new tier, the repo-wide zero-unbaselined assertion, the static ownership
inventory in span units, the live lifetrace-vs-inventory consistency
check, and regression tests for the real findings the tier convicted
(the engine-step admission unwind, the hub's torn manifest, the UI
server's unjoined worker, the prefetch iterator's worker, the async
checkpoint writer's orphaned tmps).

The whole-repo gate run lives in test_graftlint.py (GR001-GR005 ride the
same registry, so ``test_repo_has_no_new_findings`` already covers the
new tier); this file owns everything graftlife-specific.
"""

import glob
import os
import tempfile
import textwrap
import threading
import time
import types

import numpy as np
import pytest

from deeplearning4j_tpu.lint import Finding, lint_paths, lint_source, \
    write_baseline
from deeplearning4j_tpu.lint.rules_lifecycle import (
    GR_RULES, OwnershipInventory, static_ownership_inventory,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(src, rules=None):
    return lint_source(textwrap.dedent(src), path="fixture.py", rules=rules)


def _rules_hit(findings):
    return {f.rule for f in findings}


def _fake_net(value: float):
    r = np.random.RandomState(0)
    net = types.SimpleNamespace()
    net.params = {"W": (r.randn(4, 4) * 0 + value).astype(np.float32)}
    net.opt_state = {"W": np.zeros((4, 4), np.float32)}
    net.net_state = {}
    net.iteration_count = int(value)
    net.epoch_count = 0
    return net


# ---------------------------------------------------------------------------
# GR001 — unbalanced page ownership
# ---------------------------------------------------------------------------


class TestGR001PageOwnership:
    def test_true_positive_leak_via_early_return(self):
        fs = _lint("""
            class Pool:
                def grab(self):
                    p = self.cache.alloc_page()
                    if self.full:
                        return None
                    self.cache.release(p)
                    return True
        """, rules=["GR001"])
        assert _rules_hit(fs) == {"GR001"}
        assert "'p'" in fs[0].message and "return" in fs[0].message

    def test_true_positive_leak_via_raise(self):
        fs = _lint("""
            def grab(cache, check):
                p = cache.alloc_page()
                if check():
                    raise RuntimeError("bad state")
                cache.release(p)
        """, rules=["GR001"])
        assert _rules_hit(fs) == {"GR001"}
        assert "raise" in fs[0].message

    def test_negative_released_on_every_path(self):
        fs = _lint("""
            def grab(cache):
                p = cache.alloc_page()
                if p is None:
                    return "oom"
                cache.release(p)
                return "ok"
        """, rules=["GR001"])
        assert fs == []

    def test_negative_none_guard_raise_path(self):
        # the allocator's None-on-exhaustion contract: the failure branch
        # holds nothing, so raising there is not a leak
        fs = _lint("""
            def grab(cache):
                p = cache.alloc_page()
                if p is None:
                    raise RuntimeError("pool exhausted")
                cache.release(p)
        """, rules=["GR001"])
        assert fs == []

    def test_negative_try_finally_discharges(self):
        fs = _lint("""
            def grab(cache, work):
                p = cache.alloc_page()
                try:
                    work(slot=3)
                finally:
                    cache.release(p)
        """, rules=["GR001"])
        assert fs == []

    def test_negative_free_slot_discharges_everything(self):
        fs = _lint("""
            def grow(cache, slot, check):
                p = cache.alloc_page()
                q = cache.alloc_page()
                if check():
                    cache.free_slot(slot)
                    raise RuntimeError("unwound")
                cache.release(p)
                cache.release(q)
        """, rules=["GR001"])
        assert fs == []

    def test_negative_handoff_to_radix_tree(self):
        # tree.insert retains what it keeps — the documented handoff
        fs = _lint("""
            def publish(cache, tree, key):
                p = cache.alloc_page()
                q = cache.alloc_page()
                tree.insert(key, [p, q])
                return key
        """, rules=["GR001"])
        assert fs == []

    def test_negative_ownership_transfer_via_call(self):
        # passing the held ref to ANY callee transfers ownership — the
        # intra-module helper that releases its parameter now owns it
        fs = _lint("""
            class Pool:
                def _give_back(self, page):
                    self.cache.release(page)

                def grab(self, check):
                    p = self.cache.alloc_page()
                    if check():
                        self._give_back(p)
                        raise RuntimeError("unwound")
                    self._give_back(p)
        """, rules=["GR001"])
        assert fs == []

    def test_negative_ownership_transfer_via_return(self):
        fs = _lint("""
            def grab(cache):
                p = cache.alloc_page()
                return p
        """, rules=["GR001"])
        assert fs == []

    def test_negative_stored_into_container(self):
        fs = _lint("""
            def grab(cache, owned, slot):
                p = cache.cow_page(slot, 0)
                owned[slot] = p
        """, rules=["GR001"])
        assert fs == []

    def test_call_graph_arm_positive(self):
        # the engine-step shape: the prefill path has raise-unwind
        # protection, the acquiring admission call does not
        fs = _lint("""
            class Engine:
                def _admit(self, slot):
                    self.cache.map_shared(slot, 0, 1)

                def step(self, slot):
                    self._admit(slot)
                    try:
                        self._prefill(slot)
                    except Exception:
                        self.cache.free_slot(slot)
                        raise
        """, rules=["GR001"])
        assert _rules_hit(fs) == {"GR001"}
        assert "_admit" in fs[0].message and "outside" in fs[0].message

    def test_call_graph_arm_negative_protected(self):
        fs = _lint("""
            class Engine:
                def _admit(self, slot):
                    self.cache.map_shared(slot, 0, 1)

                def step(self, slot):
                    try:
                        self._admit(slot)
                        self._prefill(slot)
                    except Exception:
                        self.cache.free_slot(slot)
                        raise
        """, rules=["GR001"])
        assert fs == []

    def test_not_applied_to_tools(self):
        src = textwrap.dedent("""
            def grab(cache, check):
                p = cache.alloc_page()
                if check():
                    raise RuntimeError("bad")
                cache.release(p)
        """)
        assert lint_source(src, path="tools/bench.py",
                           rules=["GR001"]) == []


# ---------------------------------------------------------------------------
# GR002 — double-release hazard
# ---------------------------------------------------------------------------


class TestGR002DoubleRelease:
    def test_true_positive_second_release(self):
        fs = _lint("""
            def unwind(cache):
                p = cache.alloc_page()
                cache.release(p)
                cache.release(p)
        """, rules=["GR002"])
        assert _rules_hit(fs) == {"GR002"}
        assert "released twice" in fs[0].message

    def test_true_positive_two_loops_same_list(self):
        fs = _lint("""
            def drain(cache, pages):
                for p in pages:
                    cache.release(p)
                for p in pages:
                    cache.release(p)
        """, rules=["GR002"])
        assert _rules_hit(fs) == {"GR002"}
        assert "two separate loops" in fs[0].message

    def test_negative_single_release(self):
        fs = _lint("""
            def unwind(cache):
                p = cache.alloc_page()
                cache.release(p)
        """, rules=["GR002"])
        assert fs == []

    def test_negative_release_on_disjoint_branches(self):
        fs = _lint("""
            def unwind(cache, fast):
                p = cache.alloc_page()
                if fast:
                    cache.release(p)
                else:
                    cache.release(p)
        """, rules=["GR002"])
        assert fs == []

    def test_negative_two_loops_different_lists(self):
        fs = _lint("""
            def drain(cache, owned, shared):
                for p in owned:
                    cache.release(p)
                for p in shared:
                    cache.release(p)
        """, rules=["GR002"])
        assert fs == []

    def test_negative_reacquired_then_released(self):
        fs = _lint("""
            def churn(cache):
                p = cache.alloc_page()
                cache.release(p)
                p = cache.alloc_page()
                cache.release(p)
        """, rules=["GR002"])
        assert fs == []


# ---------------------------------------------------------------------------
# GR003 — terminal-taxonomy exactly-once
# ---------------------------------------------------------------------------


class TestGR003TerminalExactlyOnce:
    def test_true_positive_completer_without_funnel(self):
        fs = _lint("""
            def finish(fut, result):
                fut.set_result(result)
        """, rules=["GR003"])
        assert _rules_hit(fs) == {"GR003"}
        assert "count_terminal" in fs[0].message

    def test_true_positive_deferred_lambda_completer(self):
        fs = _lint("""
            def finish_later(fut, pool):
                pool.defer(lambda: fut.set_exception(RuntimeError("x")))
        """, rules=["GR003"])
        assert _rules_hit(fs) == {"GR003"}

    def test_true_positive_double_count_straight_line(self):
        fs = _lint("""
            def retire(fut, count_terminal):
                fut.set_result(1)
                count_terminal("done")
                count_terminal("done")
        """, rules=["GR003"])
        assert _rules_hit(fs) == {"GR003"}
        assert "twice" in fs[0].message

    def test_negative_completer_with_funnel(self):
        fs = _lint("""
            def finish(fut, result, count_terminal):
                fut.set_result(result)
                count_terminal("done")
        """, rules=["GR003"])
        assert fs == []

    def test_negative_module_local_funnel_helper(self):
        # counting() fixpoint: _note reaches count_terminal, so calling
        # _note IS routing through the funnel
        fs = _lint("""
            def _note(reason):
                count_terminal(reason)

            def finish(fut, result):
                fut.set_result(result)
                _note("done")
        """, rules=["GR003"])
        assert fs == []

    def test_negative_known_funnel_helpers(self):
        fs = _lint("""
            class Engine:
                def crash(self, req, fut):
                    self._finish_unslotted(req, fut, "oom")
                    fut.set_exception(RuntimeError("oom"))
        """, rules=["GR003"])
        assert fs == []

    def test_negative_counts_on_separate_branches(self):
        fs = _lint("""
            def retire(fut, ok, count_terminal):
                fut.set_result(1)
                if ok:
                    count_terminal("done")
                else:
                    count_terminal("error")
        """, rules=["GR003"])
        assert fs == []


# ---------------------------------------------------------------------------
# GR004 — unstoppable thread
# ---------------------------------------------------------------------------


class TestGR004UnstoppableThread:
    def test_true_positive_local_never_joined(self):
        fs = _lint("""
            import threading

            def run(work):
                t = threading.Thread(target=work)
                t.start()
        """, rules=["GR004"])
        assert _rules_hit(fs) == {"GR004"}

    def test_true_positive_anonymous_start(self):
        fs = _lint("""
            import threading

            def run(work):
                threading.Thread(target=work).start()
        """, rules=["GR004"])
        assert _rules_hit(fs) == {"GR004"}
        assert "never be joined" in fs[0].message

    def test_true_positive_daemon_does_not_exempt(self):
        fs = _lint("""
            import threading

            def run(work):
                threading.Thread(target=work, daemon=True).start()
        """, rules=["GR004"])
        assert _rules_hit(fs) == {"GR004"}
        assert "daemon=True needs a written justification" in fs[0].message

    def test_true_positive_self_stored_in_non_joining_class(self):
        fs = _lint("""
            import threading

            class Worker:
                def start(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()

                def _run(self):
                    pass
        """, rules=["GR004"])
        assert _rules_hit(fs) == {"GR004"}

    def test_negative_local_joined_in_function(self):
        fs = _lint("""
            import threading

            def run(work):
                t = threading.Thread(target=work)
                t.start()
                t.join(timeout=5.0)
        """, rules=["GR004"])
        assert fs == []

    def test_negative_self_stored_with_joining_stop(self):
        fs = _lint("""
            import threading

            class Worker:
                def start(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()

                def stop(self):
                    self._t.join(timeout=5.0)

                def _run(self):
                    pass
        """, rules=["GR004"])
        assert fs == []


# ---------------------------------------------------------------------------
# GR005 — non-atomic durable write
# ---------------------------------------------------------------------------


class TestGR005AtomicDurableWrite:
    def test_true_positive_open_w(self):
        fs = _lint("""
            import json

            def save(path, obj):
                with open(path, "w") as f:
                    json.dump(obj, f)
        """, rules=["GR005"])
        assert _rules_hit(fs) == {"GR005"}
        assert "os.replace" in fs[0].message

    def test_true_positive_mode_kwarg(self):
        fs = _lint("""
            def save(path, text):
                with open(path, mode="w") as f:
                    f.write(text)
        """, rules=["GR005"])
        assert _rules_hit(fs) == {"GR005"}

    def test_true_positive_np_save_direct_path(self):
        fs = _lint("""
            import numpy as np

            def save(path, arr):
                np.save(path + ".npy", arr)
        """, rules=["GR005"])
        assert _rules_hit(fs) == {"GR005"}

    def test_negative_tmp_plus_replace(self):
        fs = _lint("""
            import json
            import os

            def save(path, obj):
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(obj, f)
                os.replace(tmp, path)
        """, rules=["GR005"])
        assert fs == []

    def test_negative_read_mode(self):
        fs = _lint("""
            import json

            def load(path):
                with open(path, "r") as f:
                    return json.load(f)
        """, rules=["GR005"])
        assert fs == []

    def test_negative_np_savez_into_handle(self):
        # np.savez(f) into an open()-produced handle is the open's
        # business — only direct-path saves are the durable write
        fs = _lint("""
            import numpy as np

            def save(f, arr):
                np.savez(f, W=arr)
        """, rules=["GR005"])
        assert fs == []


# ---------------------------------------------------------------------------
# justified-suppression mechanics
# ---------------------------------------------------------------------------

_GR005_BAIT = """
    def save(path, text):  {marker}
        with open(path, "w") as f:  {inline}
            f.write(text)
"""


class TestJustified:
    def test_same_line_with_reason_suppresses(self):
        fs = _lint("""
            def save(path, text):
                with open(path, "w") as f:  # graftlife: justified(GR005): caller-owned scratch file
                    f.write(text)
        """, rules=["GR005"])
        assert fs == []

    def test_reason_is_mandatory(self):
        fs = _lint("""
            def save(path, text):
                with open(path, "w") as f:  # graftlife: justified(GR005):
                    f.write(text)
        """, rules=["GR005"])
        assert _rules_hit(fs) == {"GR005"}

    def test_wrong_rule_id_does_not_suppress(self):
        fs = _lint("""
            def save(path, text):
                with open(path, "w") as f:  # graftlife: justified(GR001): wrong rule
                    f.write(text)
        """, rules=["GR005"])
        assert _rules_hit(fs) == {"GR005"}

    def test_comment_block_above_suppresses(self):
        # real reasons run to multiple comment lines — the marker may sit
        # anywhere in the contiguous block directly above the finding
        fs = _lint("""
            def save(path, text):
                # caller-owned export path, not repo durable state —
                # graftlife: justified(GR005): a torn export is visibly
                # truncated and simply re-exported
                with open(path, "w") as f:
                    f.write(text)
        """, rules=["GR005"])
        assert fs == []

    def test_detached_comment_does_not_suppress(self):
        fs = _lint("""
            def save(path, text):
                # graftlife: justified(GR005): too far away

                with open(path, "w") as f:
                    f.write(text)
        """, rules=["GR005"])
        assert _rules_hit(fs) == {"GR005"}


# ---------------------------------------------------------------------------
# shrink-only baseline over the new tier
# ---------------------------------------------------------------------------


class TestBaselineShrinkOnly:
    def test_fresh_write_then_growth_refused(self):
        f1 = Finding("a.py", 3, "GR001", "error", "leak one")
        f2 = Finding("b.py", 9, "GR004", "error", "unstoppable")
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "baseline.json")
            # fresh write: everything grandfathered, nothing refused
            assert write_baseline(path, [f1]) == {}
            # atomic write (the GR005 fix in core.py): no tmp left behind
            assert glob.glob(os.path.join(d, "*.tmp")) == []
            # regenerating with MORE findings refuses the growth
            refused = write_baseline(path, [f1, f2])
            assert refused == {f2.key: 1}
            # the explicit escape hatch admits the new rule's findings
            assert write_baseline(path, [f1, f2], allow_growth=True) == {}


# ---------------------------------------------------------------------------
# the repo itself is clean under the new tier
# ---------------------------------------------------------------------------


class TestRepoWideClean:
    def test_zero_unbaselined_gr_findings(self):
        # acceptance bar: the first repo-wide run's real findings are
        # FIXED (not baselined) and the justified sites carry reasons,
        # so the GR tier contributes zero findings and zero baseline debt
        findings = lint_paths(["deeplearning4j_tpu", "tools", "examples"],
                              REPO, rules=list(GR_RULES))
        assert findings == [], [f"{f.path}:{f.line} {f.rule} {f.message}"
                                for f in findings]


# ---------------------------------------------------------------------------
# the static ownership inventory (span units)
# ---------------------------------------------------------------------------


class TestOwnershipInventory:
    def test_spans_and_callsite_attribution(self):
        with tempfile.TemporaryDirectory() as d:
            os.makedirs(os.path.join(d, "pkg"))
            src = textwrap.dedent("""
                class Pool:
                    def grab(self):
                        p = self.cache.alloc_page()
                        self.cache.release(p)
                        return True

                def unrelated():
                    return 1
            """)
            with open(os.path.join(d, "pkg", "mod.py"), "w") as f:
                f.write(src)
            inv = static_ownership_inventory(d, roots=("pkg",))
            assert [s["qualname"] for s in inv.spans] == ["grab"]
            assert inv.op_count() == 2
            span = inv.spans[0]
            assert span["path"] == os.path.join("pkg", "mod.py")
            # a callsite inside grab() attributes; one in unrelated()
            # (or outside any span) does not
            assert inv.attributes_callsite(span["path"], span["start"] + 1)
            assert not inv.attributes_callsite(span["path"], span["end"] + 3)
            assert not inv.attributes_callsite("pkg/other.py",
                                               span["start"] + 1)
            assert inv.as_dict()["ops"] == 2

    def test_lock_free_helpers_excluded(self):
        # release() without an argument is a lock idiom, not the page
        # vocabulary — it must not mint an inventory span
        with tempfile.TemporaryDirectory() as d:
            os.makedirs(os.path.join(d, "pkg"))
            with open(os.path.join(d, "pkg", "mod.py"), "w") as f:
                f.write("def f(lock):\n    lock.release()\n")
            inv = static_ownership_inventory(d, roots=("pkg",))
            assert inv.spans == []

    def test_repo_inventory_covers_the_allocator(self):
        inv = static_ownership_inventory(REPO)
        assert inv.op_count() > 0
        paths = {s["path"] for s in inv.spans}
        assert any(p.endswith(os.path.join("serving", "cache.py"))
                   for p in paths), sorted(paths)
        assert any(p.endswith(os.path.join("serving", "engine.py"))
                   for p in paths), sorted(paths)


# ---------------------------------------------------------------------------
# regression: the engine-step admission unwind (the GR001 conviction)
# ---------------------------------------------------------------------------


class TestAdmissionUnwindRegression:
    def test_step_crash_mid_admission_releases_and_requeues(self):
        from deeplearning4j_tpu.models.gpt import GptConfig, GptModel
        from deeplearning4j_tpu.serving import GenerativeEngine
        from deeplearning4j_tpu.serving.scheduler import GenerationRequest

        cfg = GptConfig.tiny(vocab_size=64)
        eng = GenerativeEngine(GptModel(cfg, seed=0), max_slots=2,
                               page_size=4, max_pages_per_seq=4,
                               max_prompt=12, seed=0)
        prompt = np.arange(1, 6, dtype=np.int32)
        fut = eng.submit_request(GenerationRequest(
            prompt=prompt, max_new_tokens=3, eos_token=-1))

        orig = eng._admit_pages
        state = {"armed": True}

        def bomb(slot, req, match):
            # run the REAL admission (pages get mapped to the slot), then
            # die — the exact window the step() unwind must cover
            out = orig(slot, req, match)
            if state["armed"]:
                state["armed"] = False
                raise RuntimeError("injected admission crash")
            return out

        eng._admit_pages = bomb
        with pytest.raises(RuntimeError, match="injected admission crash"):
            eng.step()
        # the unwind: every page the admission mapped is back in the
        # pool, the allocator invariants hold, and the request is
        # re-queued (not stranded) with its future still open
        assert eng.cache.free_pages == eng.cache.num_pages
        eng.cache.check_invariants(
            eng.prefix.page_refs() if eng.prefix is not None else None)
        assert not fut.done()
        assert eng.scheduler.has_work()
        # the retry path completes the request normally
        while eng.scheduler.has_work():
            eng.step()
        res = fut.result(timeout=10)
        assert res.finish_reason == "length"
        assert len(res.tokens) == 3


# ---------------------------------------------------------------------------
# regression: the hub's torn manifest (the GR005 conviction)
# ---------------------------------------------------------------------------


class TestHubAtomicManifest:
    def _net(self):
        from deeplearning4j_tpu import nn
        conf = (nn.builder().seed(3).updater(nn.Sgd(learning_rate=0.1))
                .list()
                .layer(nn.DenseLayer(n_out=4, activation="tanh"))
                .layer(nn.OutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(nn.InputType.feed_forward(3)).build())
        return nn.MultiLayerNetwork(conf).init()

    def test_publish_leaves_no_tmp(self, tmp_path):
        from deeplearning4j_tpu.models.hub import ModelHub
        hub = ModelHub(root=str(tmp_path))
        hub.publish("m", self._net(), metadata={"v": 1})
        assert glob.glob(str(tmp_path / "m" / "*.tmp")) == []

    def test_crash_mid_manifest_write_keeps_old_entry(self, tmp_path,
                                                      monkeypatch):
        # load() checksum-verifies against the manifest, so the old code
        # (open(manifest, "w") in place) truncated the entry the moment a
        # re-publish crashed mid-dump — the whole model bricked. The
        # atomic tmp + os.replace publish must keep v1 loadable.
        import json as json_mod
        from deeplearning4j_tpu.models import hub as hub_mod
        hub = hub_mod.ModelHub(root=str(tmp_path))
        hub.publish("m", self._net(), metadata={"v": 1})

        real_dump = json_mod.dump

        def torn_dump(obj, fh, **kw):
            fh.write('{"torn":')  # a few bytes land, then the crash
            raise IOError("disk full")

        monkeypatch.setattr(hub_mod.json, "dump", torn_dump)
        with pytest.raises(IOError, match="disk full"):
            hub.publish("m", self._net(), metadata={"v": 2})
        monkeypatch.setattr(hub_mod.json, "dump", real_dump)
        # the published entry is untouched: manifest intact, model loads
        assert hub.manifest("m")["metadata"] == {"v": 1}
        hub.load("m")


# ---------------------------------------------------------------------------
# regression: joinable workers (the GR004 convictions)
# ---------------------------------------------------------------------------


class TestWorkerThreadsJoin:
    def test_ui_server_stop_joins_its_thread(self):
        from deeplearning4j_tpu.ui.server import UIServer
        srv = UIServer(port=0).start()
        t = srv._thread
        assert t is not None and t.is_alive()
        srv.stop()
        assert not t.is_alive()
        assert srv._thread is None

    def test_async_iterator_worker_exits_with_the_epoch(self):
        from deeplearning4j_tpu.datasets.dataset import AsyncDataSetIterator

        class _ListIter:
            batch_size = 2

            def __init__(self, items):
                self._items = items

            def __iter__(self):
                return iter(self._items)

            def reset(self):
                pass

        before = {id(t) for t in threading.enumerate()}
        it = AsyncDataSetIterator(_ListIter(list(range(7))), prefetch=2)
        assert list(it) == list(range(7))
        leaked = [t for t in threading.enumerate()
                  if id(t) not in before and t.is_alive()]
        assert leaked == []


# ---------------------------------------------------------------------------
# regression: the async checkpoint writer's orphaned tmps (satellite)
# ---------------------------------------------------------------------------


class TestCheckpointOrphanTmps:
    def test_restart_sweeps_preexisting_orphans(self):
        from deeplearning4j_tpu.parallel.checkpoint import \
            TrainingCheckpointer
        with tempfile.TemporaryDirectory() as d:
            orphan = os.path.join(d, "step_7.npz.tmp")
            with open(orphan, "w") as f:
                f.write("half a checkpoint")
            ck = TrainingCheckpointer(d, use_orbax=False)
            try:
                assert not os.path.exists(orphan)
            finally:
                ck.close()

    def test_writer_death_mid_write_is_swept_and_surfaced(self):
        from deeplearning4j_tpu import faults
        from deeplearning4j_tpu.parallel.checkpoint import \
            TrainingCheckpointer
        with tempfile.TemporaryDirectory() as d:
            ck = TrainingCheckpointer(d, keep_last=None, use_orbax=False,
                                      max_queue=2, overflow="block")
            # the 2nd async write dies between fsync and the publishing
            # rename — exactly the orphaned-tmp window
            faults.arm("worker_death", prob=1.0, after_n=1, max_fires=1)
            try:
                for step in range(3):
                    ck.save_async(step, _fake_net(float(step)))
                assert ck.wait_until_finished(timeout=60)
            finally:
                faults.reset()
            # the failure surfaces, the orphan does not survive the drain
            assert len(ck.drain_failures()) == 1
            assert glob.glob(os.path.join(d, "step_*.npz.tmp")) == []
            # durability restored by a compensating sync save
            ck.save(3, _fake_net(3.0))
            assert ck.restore(_fake_net(-1.0)) == 3
            ck.close()


# ---------------------------------------------------------------------------
# live lifetrace-vs-inventory consistency (the cross-validation, small)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestLifetraceConsistency:
    def test_live_workload_matches_static_inventory(self):
        """Run a real 2-engine cluster workload under the tracer and hold
        it to the full contract: rc-clean pages, exactly-once terminals,
        no leaked threads, and every observed acquire/release callsite
        inside the static ownership inventory."""
        from deeplearning4j_tpu.models.gpt import GptConfig, GptModel
        from deeplearning4j_tpu.serving import ClusterRouter, \
            GenerativeEngine
        from deeplearning4j_tpu.testing.lifetrace import ResourceTracer

        cfg = GptConfig.tiny()
        model = GptModel(cfg, seed=0)
        engines = [GenerativeEngine(model, max_slots=2, page_size=8,
                                    max_pages_per_seq=6, max_prompt=16,
                                    seed=3, restart_backoff_s=0.0)
                   for _ in range(2)]
        tracer = ResourceTracer()
        for i, e in enumerate(engines):
            tracer.attach_engine(e, name=f"engine{i}")
        router = ClusterRouter(engines)
        router.start()
        try:
            r = np.random.RandomState(0)
            futs = [router.submit(
                r.randint(1, cfg.vocab_size, size=5).astype(np.int32),
                max_new_tokens=4, eos_token=-1) for _ in range(4)]
            for f in futs:
                f.result(timeout=300)
        finally:
            router.stop()
        report = tracer.check(repo_root=REPO)
        assert report["ok"], report
        assert report["terminals"]["tracked"] >= 4
        assert report["callsites"]["observed"] > 0
        assert report["callsites"]["validated"]
        assert report["callsites"]["unknown"] == []
