"""Optimizer fusion tier (autodiff/optimize.py, docs/OPTIMIZER.md):
attention-chain → dot_product_attention, matmul+bias(+act) →
fused_matmul_bias_act, and the opt-in bf16 autocast pass.

Positive matches assert the rewritten plan AND numeric equivalence against
the unfused graph; negative fixtures (scale on the wrong side, non-softmax
normalizer, mask dtype mismatch, shared intermediates) assert the matcher
leaves the graph untouched; the Pallas flash/epilogue kernels are compared
under forced helper modes on CPU (interpret mode — no TPU in CI).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.autodiff.optimize import (
    OPTIONAL_PASSES, PASS_ORDER, default_passes)
from deeplearning4j_tpu.autodiff.samediff import SameDiff
from deeplearning4j_tpu.environment import environment

B, H, T, HD = 2, 2, 8, 8


def _plan(sd, outputs=("out",)):
    # cache keys carry the EFFECTIVE pass tuple (env-resolved), so an env
    # toggle between calls can never serve a stale plan
    return sd._jit_cache[("plan", tuple(outputs), sd._effective_passes())]


def _plan_ops(sd, outputs=("out",)):
    return [n.op for n in _plan(sd, outputs).nodes]


def _attention_graph(scale_variant="div_scores", normalizer="softmax",
                     mask="float", share_probs=False, transpose_b=False,
                     optimize=True):
    """The ONNX/TF-importer-shaped attention chain, recorded directly."""
    r = np.random.RandomState(0)
    sd = SameDiff(optimize=optimize)
    q = sd.placeholder("q", (B, H, T, HD))
    k = sd.placeholder("k", (B, H, T, HD))
    v = sd.placeholder("v", (B, H, T, HD))
    m = sd.placeholder("m", (B, 1, 1, T),
                       dtype=jnp.int32 if mask == "int" else jnp.float32)
    one = sd.constant("one", np.float32(1.0))
    neg = sd.constant("neg", np.float32(-10000.0))
    scale = sd.constant("scale", np.float32(np.sqrt(HD)))
    inv_scale = sd.constant("inv_scale", np.float32(1.0 / np.sqrt(HD)))

    if transpose_b:
        scores = sd._record("mmul", [q, k], {"transpose_b": True})
    else:
        kt = sd._record("transpose", [k], {"axes": (0, 1, 3, 2)})
        scores = sd._record("mmul", [q, kt])
    if scale_variant == "div_scores":
        scaled = scores / scale
    elif scale_variant == "mul_scores":
        scaled = scores * inv_scale
    elif scale_variant == "wrong_side":
        scaled = scores * scale          # multiplies by sqrt(d): not 1/sqrt
    elif scale_variant == "none":
        scaled = scores
    else:
        raise AssertionError(scale_variant)
    if mask != "off":
        pen = (one - m) * neg
        scaled = scaled + pen
    if normalizer == "softmax":
        probs = sd.nn.softmax(scaled, axis=-1)
    else:
        probs = sd.nn.sigmoid(scaled)    # non-softmax normalizer
    if share_probs:
        sd._record("reduce_sum", [probs]).rename("probs_sum")
    sd._record("mmul", [probs, v]).rename("out")

    feeds = {"q": r.randn(B, H, T, HD).astype(np.float32),
             "k": r.randn(B, H, T, HD).astype(np.float32),
             "v": r.randn(B, H, T, HD).astype(np.float32),
             "m": (r.rand(B, 1, 1, T) > 0.2).astype(
                 np.int32 if mask == "int" else np.float32)}
    return sd, feeds


def _ref(sd_kwargs, feeds_outputs=("out",)):
    sd, feeds = _attention_graph(optimize=False, **sd_kwargs)
    return sd.output(feeds, list(feeds_outputs)), feeds


class TestAttentionFusion:
    @pytest.mark.parametrize("variant", ["div_scores", "mul_scores", "none"])
    def test_fused_matches_unfused(self, variant):
        ref, feeds = _ref({"scale_variant": variant})
        sd, _ = _attention_graph(scale_variant=variant)
        got = sd.output(feeds, ["out"])
        np.testing.assert_allclose(got["out"], ref["out"],
                                   rtol=1e-5, atol=1e-5)
        assert sd.last_compile_stats.fusions.get("attention") == 1
        ops = _plan_ops(sd)
        assert "dot_product_attention" in ops
        assert "softmax" not in ops

    def test_transpose_b_variant(self):
        ref, feeds = _ref({"transpose_b": True})
        sd, _ = _attention_graph(transpose_b=True)
        got = sd.output(feeds, ["out"])
        np.testing.assert_allclose(got["out"], ref["out"],
                                   rtol=1e-5, atol=1e-5)
        assert sd.last_compile_stats.fusions.get("attention") == 1

    def test_no_mask_variant(self):
        ref, feeds = _ref({"mask": "off"})
        sd, _ = _attention_graph(mask="off")
        got = sd.output(feeds, ["out"])
        np.testing.assert_allclose(got["out"], ref["out"],
                                   rtol=1e-5, atol=1e-5)
        assert sd.last_compile_stats.fusions.get("attention") == 1

    def test_causal_const_mask_fuses_to_causal_kwarg(self):
        r = np.random.RandomState(3)
        tri = np.where(np.tril(np.ones((T, T), bool)), 0.0, -1e9) \
            .astype(np.float32)

        def build(optimize):
            sd = SameDiff(optimize=optimize)
            q = sd.placeholder("q", (B, H, T, HD))
            k = sd.placeholder("k", (B, H, T, HD))
            v = sd.placeholder("v", (B, H, T, HD))
            c = sd.constant("tri", tri)
            scale = sd.constant("scale", np.float32(np.sqrt(HD)))
            kt = sd._record("transpose", [k], {"axes": (0, 1, 3, 2)})
            scores = sd._record("mmul", [q, kt]) / scale
            probs = sd.nn.softmax(scores + c, axis=-1)
            sd._record("mmul", [probs, v]).rename("out")
            return sd

        feeds = {n: r.randn(B, H, T, HD).astype(np.float32)
                 for n in ("q", "k", "v")}
        ref = build(False).output(feeds, ["out"])["out"]
        sd = build(True)
        got = sd.output(feeds, ["out"])["out"]
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
        assert sd.last_compile_stats.fusions.get("attention") == 1
        plan = _plan(sd)
        fused = [n for n in plan.nodes if n.op == "dot_product_attention"]
        assert fused and fused[0].kwargs.get("causal") is True
        assert len(fused[0].inputs) == 3  # no mask operand

    def test_flash_helper_path_on_cpu_interpret(self, monkeypatch):
        # forced pallas + a floor-zero dispatch threshold: the fused node
        # must route through the flash kernel (interpret mode off-TPU) and
        # agree with the unfused graph at kernel tolerances (1e-2/1e-5)
        monkeypatch.setenv("DL4J_TPU_FLASH_MIN_T", "1")
        ref, feeds = _ref({})
        sd, _ = _attention_graph()
        env = environment()
        prev = env.helper_mode
        env.helper_mode = "pallas"
        try:
            got = sd.output(feeds, ["out"])
        finally:
            env.helper_mode = prev
        assert sd.last_compile_stats.fusions.get("attention") == 1
        np.testing.assert_allclose(got["out"], ref["out"],
                                   rtol=1e-2, atol=1e-5)

    def test_feed_violating_declared_head_dim_keeps_original_scale(self):
        # declared placeholder shapes are NOT enforced at feed time; the
        # rewrite re-applies the graph's original scale constant to q, so
        # a feed with a different head dim still divides by sqrt(DECLARED
        # dk) exactly like the unfused graph — never sqrt(actual dk)
        ref_sd, _ = _attention_graph(optimize=False)
        sd, _ = _attention_graph()
        r = np.random.RandomState(9)
        odd = {"q": r.randn(B, H, T, 4).astype(np.float32),
               "k": r.randn(B, H, T, 4).astype(np.float32),
               "v": r.randn(B, H, T, 4).astype(np.float32),
               "m": (r.rand(B, 1, 1, T) > 0.2).astype(np.float32)}
        ref = ref_sd.output(odd, ["out"])["out"]
        got = sd.output(odd, ["out"])["out"]
        assert sd.last_compile_stats.fusions.get("attention") == 1
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    def test_gradients_flow_through_fused_node(self):
        _, feeds = _attention_graph(optimize=False)
        w = np.random.RandomState(7).randn(HD, HD).astype(np.float32) * 0.1

        # gradient equivalence: loss over the fused vs unfused graph
        def build(optimize):
            sd, _ = _attention_graph(optimize=optimize)
            out = sd.get_variable("out")
            wv = sd.var("w", w)
            (out @ wv).sum().rename("loss")
            return sd

        g_ref = build(False).calculate_gradients(feeds, "loss")
        sd = build(True)
        g_opt = sd.calculate_gradients(feeds, "loss")
        assert sd.last_compile_stats.fusions.get("attention") == 1
        assert set(g_ref) == set(g_opt)
        for k in g_ref:
            np.testing.assert_allclose(g_opt[k], g_ref[k],
                                       rtol=1e-5, atol=1e-5)


class TestAttentionPatternMisses:
    """The negative fixtures: each must leave the graph UNFUSED (and the
    outputs still correct)."""

    def _assert_untouched(self, sd, feeds, ref, outputs=("out",)):
        got = sd.output(feeds, list(outputs))
        for o in outputs:
            np.testing.assert_allclose(got[o], ref[o], rtol=1e-5, atol=1e-5)
        assert sd.last_compile_stats.fusions.get("attention", 0) == 0
        assert "dot_product_attention" not in _plan_ops(sd, outputs)

    def test_scale_on_wrong_side(self):
        ref, feeds = _ref({"scale_variant": "wrong_side"})
        sd, _ = _attention_graph(scale_variant="wrong_side")
        self._assert_untouched(sd, feeds, ref)

    def test_non_softmax_normalizer(self):
        ref, feeds = _ref({"normalizer": "sigmoid"})
        sd, _ = _attention_graph(normalizer="sigmoid")
        self._assert_untouched(sd, feeds, ref)

    def test_mask_dtype_mismatch(self):
        ref, feeds = _ref({"mask": "int"})
        sd, _ = _attention_graph(mask="int")
        self._assert_untouched(sd, feeds, ref)

    def test_shared_intermediate_consumed_elsewhere(self):
        ref, feeds = _ref({"share_probs": True},
                          feeds_outputs=("out", "probs_sum"))
        sd, _ = _attention_graph(share_probs=True)
        self._assert_untouched(sd, feeds, ref, outputs=("out", "probs_sum"))

    def test_non_binary_constant_mask_not_fused(self):
        # the mask contract is BINARY 0/1; a provably fractional CONSTANT
        # mask (where additive -5000 != where-masking) must stay verbatim
        def build(optimize):
            r = np.random.RandomState(11)
            sd = SameDiff(optimize=optimize)
            q = sd.placeholder("q", (B, H, T, HD))
            k = sd.placeholder("k", (B, H, T, HD))
            v = sd.placeholder("v", (B, H, T, HD))
            m = sd.constant("m", np.full((B, 1, 1, T), 0.5, np.float32))
            one = sd.constant("one", np.float32(1.0))
            neg = sd.constant("neg", np.float32(-10000.0))
            scale = sd.constant("scale", np.float32(np.sqrt(HD)))
            kt = sd._record("transpose", [k], {"axes": (0, 1, 3, 2)})
            scores = sd._record("mmul", [q, kt]) / scale
            probs = sd.nn.softmax(scores + (one - m) * neg, axis=-1)
            sd._record("mmul", [probs, v]).rename("out")
            return sd

        r = np.random.RandomState(12)
        feeds = {n: r.randn(B, H, T, HD).astype(np.float32)
                 for n in ("q", "k", "v")}
        ref = build(False).output(feeds, ["out"])["out"]
        sd = build(True)
        got = sd.output(feeds, ["out"])["out"]
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
        assert sd.last_compile_stats.fusions.get("attention", 0) == 0

    def test_env_opt_out(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_FUSION", "0")
        assert "fusion" not in default_passes()
        ref, feeds = _ref({})
        sd, _ = _attention_graph()
        self._assert_untouched(sd, feeds, ref)

    def test_env_toggle_after_compile_rebuilds_plan(self, monkeypatch):
        # cache keys carry the env-RESOLVED pass tuple: flipping
        # DL4J_TPU_FUSION between calls must not serve the stale plan
        ref, feeds = _ref({})
        sd, _ = _attention_graph()
        got = sd.output(feeds, ["out"])
        assert "dot_product_attention" in _plan_ops(sd)
        monkeypatch.setenv("DL4J_TPU_FUSION", "0")
        got_off = sd.output(feeds, ["out"])
        assert "dot_product_attention" not in _plan_ops(sd)
        np.testing.assert_allclose(got_off["out"], got["out"],
                                   rtol=1e-5, atol=1e-5)
        monkeypatch.delenv("DL4J_TPU_FUSION")
        sd.output(feeds, ["out"])
        assert "dot_product_attention" in _plan_ops(sd)


def _epilogue_graph(act="none", optimize=True, share_mm=False, m=4, k=16,
                    n=8):
    r = np.random.RandomState(1)
    sd = SameDiff(optimize=optimize)
    x = sd.placeholder("x", (m, k))
    w = sd.var("w", (r.randn(k, n) * 0.2).astype(np.float32))
    b = sd.var("b", (r.randn(n) * 0.1).astype(np.float32))
    h = x @ w + b
    if share_mm:
        # the matmul output feeds a second consumer: must NOT fuse
        mm_name = sd._nodes[0].outputs[0]
        sd._record("reduce_sum", [sd.get_variable(mm_name)]) \
            .rename("mm_sum")
    if act in ("relu", "tanh", "gelu"):
        h = sd._record(act, [h])
    h.rename("out")
    feeds = {"x": r.randn(m, k).astype(np.float32)}
    return sd, feeds


class TestEpilogueFusion:
    @pytest.mark.parametrize("act", ["none", "relu", "tanh", "gelu"])
    def test_fused_matches_unfused_forced_xla(self, act):
        # the acceptance contract: helper_mode="xla"-forced CPU
        # equivalence for fused_matmul_bias_act (no-TPU container)
        sd_ref, feeds = _epilogue_graph(act=act, optimize=False)
        ref = sd_ref.output(feeds, ["out"])["out"]
        sd, _ = _epilogue_graph(act=act)
        env = environment()
        prev = env.helper_mode
        env.helper_mode = "xla"
        try:
            got = sd.output(feeds, ["out"])["out"]
        finally:
            env.helper_mode = prev
        np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)
        assert sd.last_compile_stats.fusions.get("epilogue") == 1
        plan = _plan(sd)
        fused = [n for n in plan.nodes if n.op == "fused_matmul_bias_act"]
        assert fused and fused[0].kwargs["activation"] == act

    def test_erf_gelu_chain_fuses_exact(self):
        def build(optimize):
            r = np.random.RandomState(1)
            sd = SameDiff(optimize=optimize)
            x = sd.placeholder("x", (4, 16))
            w = sd.var("w", (r.randn(16, 8) * 0.2).astype(np.float32))
            b = sd.var("b", (r.randn(8) * 0.1).astype(np.float32))
            s2 = sd.constant("s2", np.float32(np.sqrt(np.float32(2.0))))
            one = sd.constant("one", np.float32(1.0))
            half = sd.constant("half", np.float32(0.5))
            h = x @ w + b
            e = sd.math.erf(h / s2)
            g = (h * (e + one)) * half
            g.rename("out")
            return sd

        r = np.random.RandomState(2)
        feeds = {"x": r.randn(4, 16).astype(np.float32)}
        ref = build(False).output(feeds, ["out"])["out"]
        sd = build(True)
        got = sd.output(feeds, ["out"])["out"]
        np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)
        assert sd.last_compile_stats.fusions.get("epilogue") == 1
        plan = _plan(sd)
        fused = [n for n in plan.nodes if n.op == "fused_matmul_bias_act"]
        assert fused and fused[0].kwargs["activation"] == "gelu_exact"
        assert "erf" not in [n.op for n in plan.nodes]

    def test_integer_matmul_bias_relu_fuses_with_integer_dtype(self):
        # relu is dtype-preserving: fusing an int chain must neither trip
        # the pass invariant checker (rule would claim float) nor change
        # the result dtype (review-round regression)
        def build(optimize):
            sd = SameDiff(optimize=optimize)
            x = sd.placeholder("x", (4, 8), dtype=jnp.int32)
            w = sd.var("w", np.arange(32, dtype=np.int32).reshape(8, 4) - 16)
            b = sd.var("b", np.ones(4, np.int32))
            sd._record("relu", [x @ w + b]).rename("out")
            return sd

        feeds = {"x": np.arange(32, dtype=np.int32).reshape(4, 8)}
        ref = build(False).output(feeds, ["out"])["out"]
        sd = build(True)
        got = sd.output(feeds, ["out"])["out"]
        assert got.dtype == ref.dtype == np.int32
        np.testing.assert_array_equal(got, ref)
        assert sd.last_compile_stats.fusions.get("epilogue") == 1

    def test_shared_matmul_not_fused(self):
        sd_ref, feeds = _epilogue_graph(optimize=False, share_mm=True)
        ref = sd_ref.output(feeds, ["out", "mm_sum"])
        sd, _ = _epilogue_graph(share_mm=True)
        got = sd.output(feeds, ["out", "mm_sum"])
        for o in ("out", "mm_sum"):
            np.testing.assert_allclose(got[o], ref[o], rtol=1e-6, atol=1e-6)
        assert sd.last_compile_stats.fusions.get("epilogue", 0) == 0

    def test_gradients_match_through_fused_epilogue(self):
        def build(optimize):
            sd, feeds = _epilogue_graph(act="gelu", optimize=optimize)
            out = sd.get_variable("out")
            (out * out).mean().rename("loss")
            return sd, feeds

        sd_ref, feeds = build(False)
        g_ref = sd_ref.calculate_gradients(feeds, "loss")
        sd, _ = build(True)
        g_opt = sd.calculate_gradients(feeds, "loss")
        assert set(g_ref) == set(g_opt)
        for kk in g_ref:
            np.testing.assert_allclose(g_opt[kk], g_ref[kk],
                                       rtol=1e-5, atol=1e-6)


class TestAutocast:
    def _mlp(self, optimize=True, passes=None):
        r = np.random.RandomState(4)
        sd = SameDiff(optimize=optimize, optimize_passes=passes)
        x = sd.placeholder("x", (8, 32))
        w1 = sd.var("w1", (r.randn(32, 32) * 0.2).astype(np.float32))
        w2 = sd.var("w2", (r.randn(32, 4) * 0.2).astype(np.float32))
        h = sd.math.tanh(x @ w1)
        sd.nn.softmax(h @ w2, axis=-1).rename("out")
        feeds = {"x": r.randn(8, 32).astype(np.float32)}
        return sd, feeds

    def test_off_by_default(self):
        sd, feeds = self._mlp()
        sd.output(feeds, ["out"])
        assert "autocast" not in default_passes()
        assert "autocast" not in sd.last_compile_stats.passes
        assert sd.last_compile_stats.fusions.get("autocast_casts", 0) == 0

    def test_env_opt_in_bf16_tolerance(self, monkeypatch):
        ref_sd, feeds = self._mlp(optimize=False)
        ref = ref_sd.output(feeds, ["out"])["out"]
        monkeypatch.setenv("DL4J_TPU_AUTOCAST", "bf16")
        assert "autocast" in default_passes()
        sd, _ = self._mlp()
        got = sd.output(feeds, ["out"])["out"]
        st = sd.last_compile_stats
        assert st.fusions.get("autocast_casts", 0) >= 2
        assert "autocast" in st.passes
        # bf16 matmul math, f32 interface: dtype preserved, values within
        # bf16 tolerance but NOT bit-identical
        assert got.dtype == np.float32
        np.testing.assert_allclose(got, ref, rtol=2e-2, atol=1e-2)

    def test_explicit_pass_list(self):
        ref_sd, feeds = self._mlp(optimize=False)
        ref = ref_sd.output(feeds, ["out"])["out"]
        sd, _ = self._mlp(passes=PASS_ORDER + OPTIONAL_PASSES)
        got = sd.output(feeds, ["out"])["out"]
        assert sd.last_compile_stats.fusions.get("autocast_casts", 0) >= 2
        np.testing.assert_allclose(got, ref, rtol=2e-2, atol=1e-2)

    def test_softmax_inputs_stay_f32(self, monkeypatch):
        # the dtype policy: matmuls go bf16, the normalizer consumes the
        # f32 cast-back — no softmax may read a bf16-producing node
        monkeypatch.setenv("DL4J_TPU_AUTOCAST", "bf16")
        sd, feeds = self._mlp()
        sd.output(feeds, ["out"])
        plan = _plan(sd)
        producer = {o: n for n in plan.nodes for o in n.outputs}
        softmaxes = [n for n in plan.nodes if n.op == "softmax"]
        assert softmaxes
        for n in softmaxes:
            p = producer.get(n.inputs[0])
            assert p is not None and p.op == "cast" \
                and p.kwargs["dtype"] == "float32"

    def test_invariant_checker_accepts_autocast(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_AUTOCAST", "bf16")
        sd, feeds = self._mlp()
        sd.output(feeds, ["out"])  # would raise PassInvariantError on a
        assert sd.last_compile_stats.invariant_checks > 0  # dtype break


class TestStatsAndObserve:
    def test_fusions_in_to_dict_and_counter_family(self):
        from deeplearning4j_tpu import observe

        before = observe.metrics().counter(
            "dl4j_tpu_graph_fusions_total", kind="attention").value
        sd, feeds = _attention_graph()
        sd.output(feeds, ["out"])
        d = sd.last_compile_stats.to_dict()
        assert d["fusions"].get("attention") == 1
        after = observe.metrics().counter(
            "dl4j_tpu_graph_fusions_total", kind="attention").value
        assert after == before + 1

    def test_compile_event_carries_fusions(self):
        from deeplearning4j_tpu import observe

        sd, feeds = _attention_graph()
        sd.output(feeds, ["out"])
        evs = [ev for ev in observe.ledger().events()
               if ev.stats is sd.last_compile_stats]
        assert evs
        assert evs[-1].to_dict()["fusions"].get("attention") == 1

    def test_fusion_pass_idempotent_at_fixpoint(self):
        # the fixpoint loop re-runs fusion on its own output: node count
        # and fusion hit counts must be stable (each chain fused once)
        sd, feeds = _attention_graph()
        sd.output(feeds, ["out"])
        st = sd.last_compile_stats
        assert st.fusions.get("attention") == 1
        assert _plan_ops(sd).count("dot_product_attention") == 1
