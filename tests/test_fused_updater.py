"""Fused updater step (ops/pallas_updater.py + nn/updater.py wiring).

The generic registry op must be BIT-identical to the unfused
``Updater.apply`` chain (it calls it); the Pallas interpret kernel must
match at f32 1e-5 or better; the MLN / SameDiff train steps route through
``apply_fused`` without changing trajectories."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deeplearning4j_tpu.ops  # noqa: F401 - registers catalog + helpers
from deeplearning4j_tpu.nn.updater import UPDATERS, Adam, Nesterovs, Sgd
from deeplearning4j_tpu.ops.pallas_updater import (
    fused_updater_helper, fused_updater_step)
from deeplearning4j_tpu.ops.registry import registry


def _leaf(kind, n=67, seed=0):
    r = np.random.RandomState(seed)
    upd = UPDATERS[kind]()
    p = jnp.asarray(r.randn(n).astype(np.float32))
    g = jnp.asarray((r.randn(n) * 0.01).astype(np.float32))
    state = upd.init_state(p)
    # a non-trivial state point: zeros hide asymmetric-state bugs
    state = {k: jnp.asarray(np.abs(r.randn(n)).astype(np.float32)) * 0.1
             for k in state}
    return upd, p, g, state


class TestAllKindsEquivalence:
    @pytest.mark.parametrize("kind", sorted(UPDATERS))
    def test_generic_matches_apply_exactly(self, kind):
        upd, p, g, state = _leaf(kind)
        keys = sorted(state)
        lr, step = jnp.float32(1e-2), jnp.float32(3.0)
        u, new = upd.apply(g, state, lr, step)
        got = fused_updater_step.fn(p, g, lr, step,
                                    *(state[k] for k in keys), kind=kind)
        np.testing.assert_array_equal(np.asarray(got[0]),
                                      np.asarray(p - u))
        for k, a in zip(keys, got[1:]):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(new[k]))

    @pytest.mark.parametrize("kind", sorted(UPDATERS))
    def test_pallas_interpret_matches_generic(self, kind):
        upd, p, g, state = _leaf(kind, seed=1)
        keys = sorted(state)
        lr, step = jnp.float32(1e-2), jnp.float32(3.0)
        want = fused_updater_step.fn(p, g, lr, step,
                                     *(state[k] for k in keys), kind=kind)
        got = fused_updater_helper(p, g, lr, step,
                                   *(state[k] for k in keys), kind=kind,
                                   block_rows=8, interpret=True)
        for w, a in zip(want, got):
            np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                       rtol=1e-5, atol=1e-6)

    def test_bf16_leaf_through_pallas_kernel(self):
        """bf16 params/states must not crash the kernel (the f32 lr/step
        promote the chain; stores cast back to the ref dtype)."""
        r = np.random.RandomState(9)
        p = jnp.asarray(r.randn(64).astype(np.float32)).astype(jnp.bfloat16)
        g = jnp.asarray((r.randn(64) * 0.01).astype(np.float32)) \
            .astype(jnp.bfloat16)
        z = jnp.zeros((64,), jnp.bfloat16)
        lr, step = jnp.float32(1e-2), jnp.float32(0.0)
        got = fused_updater_helper(p, g, lr, step, z, z, kind="Adam",
                                   block_rows=8, interpret=True)
        want = fused_updater_step.fn(p, g, lr, step, z, z, kind="Adam")
        assert got[0].dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(got[0], np.float32),
            np.asarray(want[0], np.float32), rtol=1e-2, atol=1e-3)

    def test_hyperparams_thread_through(self):
        _, p, g, state = _leaf("Adam", seed=2)
        lr, step = jnp.float32(1e-3), jnp.float32(7.0)
        upd = Adam(beta1=0.5, beta2=0.9, epsilon=1e-6)
        u, _ = upd.apply(g, state, lr, step)
        got = fused_updater_step.fn(p, g, lr, step, state["m"], state["v"],
                                    kind="Adam", beta1=0.5, beta2=0.9,
                                    epsilon=1e-6)
        np.testing.assert_array_equal(np.asarray(got[0]),
                                      np.asarray(p - u))

    def test_grad_flows_through_generic(self):
        """The op is differentiable wrt grad (the train step never needs
        it, but the graph surface must not be a grad sink)."""
        upd, p, g, state = _leaf("Adam", seed=3)
        lr, step = jnp.float32(1e-2), jnp.float32(0.0)

        def via_op(g_):
            return jnp.sum(fused_updater_step.fn(
                p, g_, lr, step, state["m"], state["v"], kind="Adam")[0])

        def via_apply(g_):
            u, _ = Adam().apply(g_, state, lr, step)
            return jnp.sum(p - u)

        np.testing.assert_allclose(np.asarray(jax.grad(via_op)(g)),
                                   np.asarray(jax.grad(via_apply)(g)),
                                   rtol=1e-5, atol=1e-6)

    def test_unknown_kind_and_bad_state_count(self):
        p = jnp.zeros((8,), jnp.float32)
        lr = jnp.float32(1e-2)
        with pytest.raises(ValueError, match="unknown updater kind"):
            fused_updater_step.fn(p, p, lr, lr, kind="Adamish")
        with pytest.raises(ValueError, match="expected 2 state"):
            fused_updater_step.fn(p, p, lr, lr, p, kind="Adam")


class TestApplyFusedWiring:
    def test_apply_fused_matches_apply(self):
        upd, p, g, state = _leaf("RmsProp", seed=4)
        lr, step = jnp.float32(5e-3), jnp.float32(2.0)
        u, new = upd.apply(g, state, lr, step)
        np_, ns = upd.apply_fused(p, g, state, lr, step)
        np.testing.assert_array_equal(np.asarray(np_), np.asarray(p - u))
        for k in new:
            np.testing.assert_array_equal(np.asarray(ns[k]),
                                          np.asarray(new[k]))

    def test_opt_out_env(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_FUSED_UPDATER", "0")
        calls = []
        orig = registry().get("fused_updater_step").__call__

        upd, p, g, state = _leaf("Sgd", seed=5)
        lr, step = jnp.float32(0.1), jnp.float32(0.0)
        # with the opt-out the registry op must not be involved at all
        desc = registry().get("fused_updater_step")
        monkeypatch.setattr(
            type(desc), "__call__",
            lambda self, *a, **k: calls.append(1) or orig(self, *a, **k))
        np_, _ = upd.apply_fused(p, g, state, lr, step)
        assert not calls
        u, _ = upd.apply(g, state, lr, step)
        np.testing.assert_array_equal(np.asarray(np_), np.asarray(p - u))

    def test_subclass_keeps_override(self):
        class Doubler(Sgd):
            def apply(self, grad, state, lr, step):
                return 2 * lr * grad, state

        upd = Doubler(learning_rate=0.1)
        assert not upd._fusable()
        p = jnp.ones((8,), jnp.float32)
        g = jnp.ones((8,), jnp.float32)
        np_, _ = upd.apply_fused(p, g, {}, jnp.float32(0.1),
                                 jnp.float32(0.0))
        np.testing.assert_allclose(np.asarray(np_), 0.8, rtol=1e-6)

    def test_fused_hyper_excludes_lr(self):
        assert "learning_rate" not in Nesterovs(momentum=0.8).fused_hyper()
        assert Nesterovs(momentum=0.8).fused_hyper()["momentum"] == 0.8


class TestTrainStepTrajectories:
    def _fit_mln(self):
        from deeplearning4j_tpu import nn

        rng = np.random.RandomState(7)
        net = nn.MultiLayerNetwork(
            nn.builder().seed(12345).updater(nn.Adam(learning_rate=1e-2))
            .list()
            .layer(nn.DenseLayer(n_out=16, activation="tanh"))
            .layer(nn.OutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(8)).build()
        ).init()
        x = rng.randn(32, 8).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 32)]
        net.fit(x, y, epochs=2, batch_size=32)
        return [np.asarray(l) for l in jax.tree.leaves(net.params)]

    def test_mln_trajectory_identical_fused_vs_not(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_FUSED_UPDATER", "1")
        fused = self._fit_mln()
        monkeypatch.setenv("DL4J_TPU_FUSED_UPDATER", "0")
        unfused = self._fit_mln()
        assert len(fused) == len(unfused)
        for a, b in zip(fused, unfused):
            np.testing.assert_array_equal(a, b)

    def test_samediff_fit_runs_fused(self, monkeypatch):
        from deeplearning4j_tpu.autodiff.samediff import (
            SameDiff, TrainingConfig)
        from deeplearning4j_tpu.datasets import (
            DataSet, ListDataSetIterator)

        monkeypatch.setenv("DL4J_TPU_FUSED_UPDATER", "1")
        r = np.random.RandomState(11)
        sd = SameDiff()
        x = sd.placeholder("x", shape=(None, 4))
        labels = sd.placeholder("labels", shape=(None, 2))
        w = sd.var("w", (r.randn(4, 2) * 0.1).astype(np.float32))
        logits = x.mmul(w)
        sd.loss.softmax_cross_entropy(logits, labels).rename("loss")
        sd.set_training_config(TrainingConfig(
            updater=Adam(learning_rate=5e-2),
            data_set_feature_mapping=["x"],
            data_set_label_mapping=["labels"],
            loss_variables=["loss"]))
        xs = r.randn(64, 4).astype(np.float32)
        yl = (xs[:, 0] > 0).astype(int)
        ys = np.eye(2, dtype=np.float32)[yl]
        hist = sd.fit(ListDataSetIterator(DataSet(xs, ys), batch_size=64),
                      epochs=10)
        assert hist[-1] < hist[0]
