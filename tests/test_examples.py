"""Smoke-run the examples (dl4j-examples role): each must execute
end-to-end on the CPU harness within example-scale budgets."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")


def _run(name, timeout=900):  # CPU compile of conv stacks dominates
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["EXAMPLE_MAX_BATCHES"] = "5"  # smoke scale; users run full scale
    proc = subprocess.run([sys.executable, os.path.join(EXAMPLES, name)],
                          cwd=REPO, env=env, capture_output=True, text=True,
                          timeout=timeout)
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-2000:]
    return proc.stdout


class TestExamples:
    def test_transfer_learning(self):
        out = _run("transfer_learning.py")
        assert "frozen backbone unchanged: True" in out

    def test_rnn_timeseries(self):
        out = _run("rnn_timeseries.py")
        assert "streamed 6 steps" in out

    def test_distributed_data_parallel(self):
        out = _run("distributed_data_parallel.py")
        assert "trained over 8 devices" in out

    def test_samediff_training(self):
        out = _run("samediff_training.py")
        assert "loss first -> last" in out

    def test_bert_finetune(self):
        out = _run("bert_finetune.py")
        assert "MLM loss" in out

    def test_model_import(self):
        pytest.importorskip("tensorflow")
        out = _run("model_import.py")
        assert "GraphRunner outputs" in out

    def test_lenet_mnist_runs(self):
        out = _run("lenet_mnist.py", timeout=560)
        assert "Accuracy" in out or "accuracy" in out

    def test_long_context_attention(self):
        out = _run("long_context_attention.py")
        assert "strategies agree" in out

    def test_hyperparameter_search(self):
        out = _run("hyperparameter_search.py")
        assert "grid refinement best" in out
        assert "search ok" in out

    def test_saved_model_finetune(self):
        pytest.importorskip("tensorflow")
        out = _run("saved_model_finetune.py")
        assert "imported outputs match TF: True" in out
        assert "weights moved from the pretrained point: True" in out

    def test_moe_pipeline_parallel(self):
        out = _run("moe_pipeline_parallel.py")
        assert "MoE dp×ep" in out and "pipeline dp×pp" in out
