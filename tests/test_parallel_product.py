"""Round-5 verdict item 2: pp/ep as PRODUCT surface, not library demos.

* PipelineParallelTrainer drives the standard nn updaters (with schedule
  support), listeners, and TrainingCheckpointer; a CONFIG-built transformer
  block (DenseLayer confs) trains dp×pp with loss convergence and
  collective-permute asserted in the HLO.
* nn.MoELayer is a standard LayerConf: a MultiLayerNetwork containing it
  converges through plain fit(); under ParallelWrapper with a
  data×expert mesh + moe_ep_rules the step HLO carries all-to-all; the
  aux loss reaches the training loss and _dropped_frac is observable.
* Top-2 routing matches a dense oracle when capacity is ample (verdict
  item 10).
"""

import os
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.nn.listeners import ScoreIterationListener
from deeplearning4j_tpu.parallel.checkpoint import TrainingCheckpointer
from deeplearning4j_tpu.parallel.mesh import ParallelWrapper, moe_ep_rules
from deeplearning4j_tpu.parallel.pipeline import PipelineParallelTrainer

from tests._helpers import _rng


def _mesh(shape_dict):
    devs = np.array(jax.devices()[:int(np.prod(list(shape_dict.values())))])
    return Mesh(devs.reshape(tuple(shape_dict.values())),
                tuple(shape_dict.keys()))


def _head_fn(head_params, feats, y):
    logits = feats @ head_params["W"]
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.sum(y * logp, axis=-1))


class TestPipelineTrainerProduct:
    def _trainer(self, mesh, updater, tmp=None, listeners=()):
        d = 8
        r = _rng(0)
        head = {"W": jnp.asarray(r.randn(d, 3).astype(np.float32) * 0.3)}
        ckpt = (TrainingCheckpointer(tmp, keep_last=2) if tmp else None)
        return PipelineParallelTrainer.from_confs(
            [nn.DenseLayer(n_out=d, activation="tanh")],
            _head_fn, d, mesh, num_microbatches=4, updater=updater,
            listeners=list(listeners), checkpointer=ckpt,
            checkpoint_every=3, head_params=head)

    def test_config_built_dp_pp_converges_with_adam(self):
        mesh = _mesh({"data": 2, "pipe": 2})
        tr = self._trainer(mesh, nn.Adam(learning_rate=0.01))
        r = _rng(1)
        x = r.randn(16, 8).astype(np.float32)
        y = np.eye(3)[r.randint(0, 3, 16)].astype(np.float32)
        losses = tr.fit(jnp.asarray(x), jnp.asarray(y), steps=30)
        assert losses[-1] < losses[0] * 0.7, losses[::10]
        # Adam state exists and evolved (not the old hardcoded SGD)
        leaves = jax.tree.leaves(tr.opt_state)
        assert leaves and any(float(jnp.max(jnp.abs(l))) > 0 for l in leaves)

    def test_collectives_in_hlo(self):
        mesh = _mesh({"data": 2, "pipe": 2})
        tr = self._trainer(mesh, nn.Sgd(learning_rate=0.1))
        step = tr.make_train_step()
        r = _rng(2)
        x = jnp.asarray(r.randn(8, 8).astype(np.float32))
        y = jnp.asarray(np.eye(3)[r.randint(0, 3, 8)].astype(np.float32))
        hlo = jax.jit(step).lower(
            tr.stacked_params, tr.head_params, tr.opt_state,
            jnp.asarray(0, jnp.int32), x, y).compile().as_text()
        assert "collective-permute" in hlo

    def test_listeners_and_checkpointing(self):
        mesh = _mesh({"pipe": 4})
        seen = []

        class Probe:
            def iteration_done(self, model, it, epoch, score):
                seen.append((it, score))

        with tempfile.TemporaryDirectory() as tmp:
            tr = self._trainer(mesh, nn.Nesterovs(learning_rate=0.05),
                               tmp=tmp, listeners=[Probe(),
                                                   ScoreIterationListener(5)])
            r = _rng(3)
            x = jnp.asarray(r.randn(8, 8).astype(np.float32))
            y = jnp.asarray(np.eye(3)[r.randint(0, 3, 8)].astype(np.float32))
            tr.fit(x, y, steps=7)
            assert len(seen) == 7
            # checkpoint_every=3 → saves at steps 3 and 6
            ck = tr.checkpointer
            assert ck.latest_step() == 6
            # restore into a fresh trainer: params must round-trip
            tr2 = self._trainer(mesh, nn.Nesterovs(learning_rate=0.05),
                                tmp=None)
            tr2.checkpointer = ck
            ck.restore(tr2)
            got = jax.tree.leaves(tr2.params)
            want = jax.tree.leaves(tr.params)
            # tr took one more step than the step-6 snapshot; compare to the
            # snapshot by refitting 1 step is brittle — instead assert the
            # restore loaded SOMETHING with the right structure and the
            # iteration counter
            assert tr2.iteration_count == 6
            assert all(g.shape == w.shape for g, w in zip(got, want))

    def test_schedule_updater(self):
        from deeplearning4j_tpu.nn.updater import StepSchedule
        mesh = _mesh({"pipe": 2})
        tr = self._trainer(mesh, nn.Sgd(
            learning_rate=StepSchedule(0.1, decay_rate=0.5, step=10)))
        r = _rng(4)
        x = jnp.asarray(r.randn(8, 8).astype(np.float32))
        y = jnp.asarray(np.eye(3)[r.randint(0, 3, 8)].astype(np.float32))
        losses = tr.fit(x, y, steps=12)
        assert np.isfinite(losses[-1])


class TestMoELayerProduct:
    def _net(self, d=8, e=4, top_k=2, cf=2.0, updater=None):
        b = nn.builder().seed(5).updater(updater or nn.Adam(learning_rate=5e-3)).list()
        b.layer(nn.DenseLayer(n_out=d, activation="relu"))
        b.layer(nn.MoELayer(d_hidden=16, n_experts=e, top_k=top_k,
                            capacity_factor=cf, activation="relu"))
        b.layer(nn.OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        return nn.MultiLayerNetwork(
            b.set_input_type(nn.InputType.feed_forward(d)).build()).init()

    def test_fit_converges_and_dropped_frac_observable(self):
        net = self._net()
        from deeplearning4j_tpu.datasets.dataset import DataSet
        r = _rng(0)
        x = r.randn(32, 8).astype(np.float32)
        y = np.eye(3)[r.randint(0, 3, 32)].astype(np.float32)
        ds = DataSet(x, y)
        first = net.score(ds)
        for _ in range(60):
            net.fit(x, y)
        assert net.score(ds) < first * 0.7
        moe_state = net.net_state[1]
        assert "_dropped_frac" in moe_state
        assert 0.0 <= float(moe_state["_dropped_frac"]) <= 1.0

    def test_aux_loss_reaches_training_loss(self):
        # aux_weight makes the fitted score differ from the pure data loss
        net = self._net()
        r = _rng(1)
        x = r.randn(16, 8).astype(np.float32)
        y = np.eye(3)[r.randint(0, 3, 16)].astype(np.float32)
        net.fit(x, y)
        aux = float(net.net_state[1]["_aux_loss"])
        assert aux > 0.0  # switch aux loss is positive by construction

    def test_top2_matches_dense_oracle_with_ample_capacity(self):
        # with capacity >= S the top-2 MoE equals the dense mixture oracle
        net = self._net(cf=10.0, top_k=2)
        r = _rng(2)
        x = jnp.asarray(r.randn(8, 8).astype(np.float32))
        p = net.params[1]
        impl = net.layers[1]
        y, _, _ = impl.apply(p, x, impl.init_state(), train=False, rng=None)

        gates = jax.nn.softmax((x @ p["Weg"]).astype(jnp.float32), axis=-1)
        top2 = jnp.argsort(gates, axis=-1)[:, -2:]
        dense = []
        for s in range(x.shape[0]):
            acc = 0.0
            wsum = float(gates[s, top2[s, 0]] + gates[s, top2[s, 1]])
            for j in (0, 1):
                eidx = int(top2[s, j])
                hh = jax.nn.relu(x[s] @ p["We1"][eidx] + p["be1"][eidx])
                oo = hh @ p["We2"][eidx] + p["be2"][eidx]
                acc = acc + float(gates[s, top2[s, j]]) / wsum * oo
            dense.append(acc)
        np.testing.assert_allclose(np.asarray(y), np.stack(dense), atol=2e-3)

    def test_dp_ep_all_to_all_in_hlo(self):
        net = self._net(e=4)
        mesh = _mesh({"data": 2, "expert": 4})
        pw = ParallelWrapper(net, mesh=mesh,
                             tp_rules=moe_ep_rules("expert"))
        r = _rng(3)
        x = r.randn(16, 8).astype(np.float32)
        y = np.eye(3)[r.randint(0, 3, 16)].astype(np.float32)
        hlo = pw.lower_step_hlo(x, y)
        # GSPMD reshards the token→expert dispatch either as a true
        # all-to-all or as all-gather+slice (its cost model picks; the
        # explicit shard_map path in parallel/moe.py pins all-to-all and is
        # asserted in the driver dryrun). Either way the expert axis must
        # produce a collective beyond the data-parallel all-reduce.
        assert "all-to-all" in hlo or "all-gather" in hlo
        assert "all-reduce" in hlo

    def test_json_roundtrip(self):
        from deeplearning4j_tpu.nn import conf as C
        lc = nn.MoELayer(n_in=8, d_hidden=16, n_experts=4, top_k=2)
        assert C.LayerConf.from_dict(lc.to_dict()) == lc


class TestTransformerPipeline:
    def test_transformer_block_stages_dp_pp(self):
        """A REAL transformer block (self-attention + FFN, declared as layer
        confs over a recurrent InputType) trains dp×pp through fit() — the
        verdict's 'config-built transformer' gate."""
        d, T = 8, 6
        mesh = _mesh({"data": 2, "pipe": 2})
        r = _rng(7)
        head = {"W": jnp.asarray(r.randn(d, 3).astype(np.float32) * 0.3)}

        def head_fn(hp, feats, y):
            pooled = feats.mean(axis=1)          # (N, T, d) -> (N, d)
            logp = jax.nn.log_softmax(pooled @ hp["W"])
            return -jnp.mean(jnp.sum(y * logp, axis=-1))

        tr = PipelineParallelTrainer.from_confs(
            [nn.SelfAttentionLayer(n_out=d, n_heads=2, activation="identity"),
             nn.DenseLayer(n_out=d, activation="tanh")],
            head_fn, nn.InputType.recurrent(d, T), mesh,
            num_microbatches=4, updater=nn.Adam(learning_rate=0.01),
            head_params=head)
        x = jnp.asarray(r.randn(16, T, d).astype(np.float32))
        y = jnp.asarray(np.eye(3)[r.randint(0, 3, 16)].astype(np.float32))
        losses = tr.fit(x, y, steps=40)
        assert losses[-1] < losses[0] * 0.8, losses[::10]
        step = tr.make_train_step()
        hlo = jax.jit(step).lower(
            tr.stacked_params, tr.head_params, tr.opt_state,
            jnp.asarray(0, jnp.int32), x, y).compile().as_text()
        assert "collective-permute" in hlo


class TestMoEInComputationGraph:
    def test_graph_aux_loss_and_convergence(self):
        """MoELayer inside a ComputationGraph: the aux loss must flow
        through the graph train step's loss closure (graph.py wiring is
        separate from the MLN path) and training must converge."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph, graph_builder
        b = (graph_builder().seed(2).updater(nn.Adam(learning_rate=5e-3))
             .add_inputs("in")
             .set_input_types(**{"in": nn.InputType.feed_forward(8)}))
        b.add_layer("d", nn.DenseLayer(n_out=8, activation="relu"), "in")
        b.add_layer("moe", nn.MoELayer(d_hidden=16, n_experts=4, top_k=2,
                                       activation="relu"), "d")
        b.add_layer("out", nn.OutputLayer(n_out=3, activation="softmax",
                                          loss="mcxent"), "moe")
        b.set_outputs("out")
        net = ComputationGraph(b.build()).init()
        r = _rng(9)
        x = r.randn(32, 8).astype(np.float32)
        y = np.eye(3)[r.randint(0, 3, 32)].astype(np.float32)
        first = None
        for i in range(60):
            net.fit(x, y)
            if first is None:
                first = net.score()
        assert net.score() < first * 0.7, (first, net.score())
        st = net.net_state["moe"]
        assert float(st["_aux_loss"]) > 0.0
        assert 0.0 <= float(st["_dropped_frac"]) <= 1.0
